"""AST-level repo hazard lints (the sub-second half of the verifier).

Four lint families, each targeting a bug class this repo has actually
shipped or nearly shipped:

JIT01 jit-cache-key: a jit-compiled callable is stored in a cache dict
    (`self._fns[key] = jax.jit(...)` / `= (fn, consts)`) but the
    closure/partial it wraps depends on an enclosing-function local that
    is NOT derivable from the cache key — so two call sites that differ
    in that value silently share (or miss) a compiled program. This is
    the PR 3 bug class (digit extraction caching per exact width while
    warmup compiled another). Derivability is tracked through simple
    local assignments (`plain = boundary == "plain"` makes `plain`
    key-derived when `boundary` is in the key); `self` and module
    globals are allowed (per-instance caches are keyed by identity,
    globals are latched configuration).

PROM01/PROM02 dtype promotion: arithmetic in a kernel module mixing a
    bare Python float literal into (potentially traced) expressions —
    jnp promotes uint32 arrays to f32 silently — and any float64
    reference in kernel modules (the limb pipeline is 32-bit end to
    end).

LOCK01/LOCK02 lock discipline (service/ + store/): a self attribute of
    a class that owns a threading lock is mutated both inside and
    outside `with self._lock` scopes (LOCK01), or mutated outside the
    lock while another method READS it under the lock (LOCK02) —
    outside __init__ in both cases. Helper methods whose intra-class
    call sites are ALL lock-held count as lock-held themselves
    (fixpoint), so `_delete_locked`-style internals don't
    false-positive.

OBS01 metric glossary (service/ + runtime/ + store/ + obs/): a metric
    name recorded via a string-literal `.inc("name")` / `.observe("name")`
    must be documented in service/metrics.py's module docstring — the
    glossary is the operator's only map from a /metrics line to what
    the code actually counted, and undocumented names rot into
    write-only telemetry. Documented = the name (or a `family_*`
    wildcard covering it) appears on one of the docstring's indented
    glossary lines; names published through a scoped registry
    (Metrics.scoped) also pass when their store_-prefixed form is
    documented. F-string/derived names are out of scope (they are
    families; document the wildcard).

LOG01 structured-log subsystem glossary (same dirs as OBS01): the
    `subsystem` literal of every structured-log emission
    (`obs.log.emit("dispatcher", ...)` / `LogBuffer.emit(...)`) must be
    documented in obs/log.py's module docstring glossary — the
    subsystem field is how an operator slices the fleet's JSONL logs,
    and an undocumented (or typo'd) subsystem silently forks the
    vocabulary. Derived/variable subsystems are out of scope.

LOCK03 lock-acquisition order (same scope as LOCK01): a directed graph
    over (class, lock) nodes with an edge A -> B wherever code may
    acquire B while holding A — a nested `with self.<B>` inside
    `with self.<A>`, a multi-item `with self.A, self.B`, or a call made
    under A to a method (of this or any other linted class, matched by
    method name) that acquires B. Any cycle in that may-hold-while-
    acquiring relation is a deadlock two threads can reach by taking
    the locks in opposite orders; a self-edge on a plain Lock (not
    RLock) is the single-thread re-entry deadlock. `Condition(lock)`
    aliases the wrapped lock. Cross-class edges are name-matched (no
    type inference), so a shared method name can over-approximate — a
    pragma on any edge of a reported cycle breaks the cycle.

ENV01 knob glossary (whole package): every string literal naming a
    `DPT_*` environment knob must appear in the knob glossary held in
    constants.py's module docstring (same indented name-column format
    as the OBS01 metric glossary; a `DPT_FAMILY_*` token documents a
    family). The glossary is the single source of truth operators get
    for the ~100 knobs accreted across PRs; an undocumented knob is
    configuration surface nobody can discover. Derived names
    (`"DPT_TTL_%s_S" % cls`) are out of scope — document the wildcard.

TAG01 wire-tag conformance (repo-wide): every tag in
    runtime/protocol.py's TAG_NAMES table must be referenced by at
    least one encode/decode/dispatch site in the package outside
    protocol.py, AND by at least one test under tests/ (the old-peer
    ERR-degradation/back-compat reference) — a new JOIN/LEAVE/
    AGGREGATE-style tag that lands without a test for how old peers
    degrade is exactly how a fleet rolls into a protocol split. The
    tag table is read by AST, so the lint never imports the native
    codec module.

Suppression: append `# analysis: ok(<reason>)` to the flagged line (or
the line above) — deliberate exceptions stay visible and reasoned at
the site. Pragmas are honored by every lint (for LOCK03, on any edge
of the cycle; for TAG01, on the tag's assignment line in protocol.py).
"""

import ast
import os
import re

PRAGMA_RE = re.compile(r"#\s*analysis:\s*ok\(([^)]*)\)")

_REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), "..", ".."))
_PKG = os.path.join(_REPO, "distributed_plonk_tpu")

# modules whose code is (or stages) traced kernels: the promotion and
# jit-cache lints run here
KERNEL_DIRS = ("backend", "parallel", "runtime")
# modules with cross-thread shared state: the lock lints run here
# (runtime/ added with the fleet fault domain: LivenessTracker state,
# WorkerState task tables, peer-connection caches are all cross-thread;
# obs/ added with the fleet observability plane: the log ring and the
# scraper's latest-snapshot state are cross-thread too; prover.py /
# circuits/ / aggregate.py added with ISSUE 19 — PipelinedProver and
# the aggregation plane run under the pool's threads and had never
# been linted. Entries ending in ".py" are single top-level modules.)
LOCK_DIRS = ("service", "store", "runtime", "obs", "circuits",
             "prover.py", "aggregate.py")
# modules that record metrics into the shared registry: the OBS01
# glossary lint runs here; LOG01 (structured-log subsystem glossary)
# shares the same scope
OBS_DIRS = ("service", "store", "runtime", "obs", "circuits",
            "prover.py", "aggregate.py")

# mutating container-method names treated as writes by LOCK01 (calls on
# self.<attr>.<name>(...)); read-only or thread-safe APIs (queue.put,
# event.set) are deliberately absent
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popitem",
             "clear", "update", "setdefault", "move_to_end", "sort",
             "add", "discard"}


class Finding:
    def __init__(self, path, line, code, message):
        self.path = path
        self.line = line
        self.code = code
        self.message = message

    def __str__(self):
        rel = os.path.relpath(self.path, _REPO)
        return f"{rel}:{self.line}: {self.code}: {self.message}"


def _pragma_lines(src):
    """Line numbers (1-based) carrying an `# analysis: ok(...)` pragma."""
    out = set()
    for i, line in enumerate(src.splitlines(), start=1):
        if PRAGMA_RE.search(line):
            out.add(i)
    return out


def _suppressed(pragmas, line):
    return line in pragmas or (line - 1) in pragmas


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _self_attr(node):
    """'self.x' -> 'x' (walking through subscripts: self.x[k] -> 'x')."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


# --- JIT01: jit cache keys ----------------------------------------------------

def _is_jit_call(node):
    """`jax.jit(...)` / `jit(...)` call expression."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return ((isinstance(f, ast.Attribute) and f.attr == "jit")
            or (isinstance(f, ast.Name) and f.id == "jit"))


def _has_jit_decorator(fdef):
    for d in fdef.decorator_list:
        if (isinstance(d, ast.Attribute) and d.attr == "jit") \
                or (isinstance(d, ast.Name) and d.id == "jit") \
                or (isinstance(d, ast.Call) and _is_jit_call(d)):
            return True
    return False


def _local_deps(fn):
    """name -> set(names it was computed from), for simple assignments
    directly in `fn`'s body (no control-flow sensitivity — enough to
    track `plain = boundary == "plain"` style derivations)."""
    deps = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            deps[node.targets[0].id] = _names_in(node.value)
    return deps


def _transitive(names, deps, limit=32):
    out = set(names)
    for _ in range(limit):
        grew = False
        for n in list(out):
            for d in deps.get(n, ()):
                if d not in out:
                    out.add(d)
                    grew = True
        if not grew:
            break
    return out


def _closure_free_names(value, fn, jit_defs):
    """Names the cached value's compiled behavior depends on: names in
    jit(...) call arguments, plus — when the value references a local
    function that carries @jit — that function's body free names."""
    names = set()
    for node in ast.walk(value):
        if _is_jit_call(node):
            for arg in node.args + [kw.value for kw in node.keywords]:
                names |= _names_in(arg)
        elif isinstance(node, ast.Name) and node.id in jit_defs:
            names |= jit_defs[node.id]
    return names


def _jit_def_free_names(fdef):
    """Free names of a nested @jit function: names read in its body that
    are not its own params/locals."""
    bound = {a.arg for a in (fdef.args.args + fdef.args.kwonlyargs
                             + fdef.args.posonlyargs)}
    if fdef.args.vararg:
        bound.add(fdef.args.vararg.arg)
    if fdef.args.kwarg:
        bound.add(fdef.args.kwarg.arg)
    for node in ast.walk(fdef):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    bound.add(t.id)
    free = set()
    for node in ast.walk(fdef):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id not in bound:
            free.add(node.id)
    return free


def _lint_jit_cache(tree, path, src, module_names, findings):
    pragmas = _pragma_lines(src)
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
                  + fn.args.posonlyargs}
        deps = _local_deps(fn)
        jit_defs = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.FunctionDef) and node is not fn \
                    and _has_jit_decorator(node):
                jit_defs[node.name] = _jit_def_free_names(node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _is_jit_call(node.value):
                jit_defs[node.targets[0].id] = _names_in(node.value)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)):
                continue
            target = node.targets[0]
            # only cache DICTS survive across calls: self.<x>[key] = ...
            if _self_attr(target) is None:
                continue
            closure = _closure_free_names(node.value, fn, jit_defs)
            if not closure:
                continue  # not a jit-carrying cache write
            # a closure name is key-derived when every ORIGIN of its
            # assignment chain (a name with no recorded local
            # derivation) is the key itself, `self`, or module scope;
            # an origin that is a function PARAMETER outside the key is
            # exactly the hazard: the trace varies with it, the cache
            # key does not
            key_closure = _transitive(_names_in(target.slice), deps)
            hazards = set()
            for n in sorted(closure):
                if n == "self" or n in module_names or n in key_closure:
                    continue
                chain = _transitive({n}, deps)
                origins = {r for r in chain if r not in deps} or {n}
                hazards |= {r for r in origins
                            if r in params and r not in key_closure
                            and r != "self" and r not in module_names}
            hazards = sorted(hazards)
            if hazards and not _suppressed(pragmas, node.lineno):
                findings.append(Finding(
                    path, node.lineno, "JIT01",
                    f"jit cache write keyed on {sorted(_names_in(target.slice))} "
                    f"but the cached trace also depends on {hazards} — a "
                    "call differing only there reuses the wrong compiled "
                    "program (add them to the key or derive them from it)"))


# --- PROM: dtype promotion ----------------------------------------------------

def _lint_promotion(tree, path, src, findings):
    pragmas = _pragma_lines(src)
    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp):
            for side in (node.left, node.right):
                if isinstance(side, ast.Constant) \
                        and isinstance(side.value, float):
                    other = node.right if side is node.left else node.left
                    if isinstance(other, ast.Constant):
                        continue  # constant folding, no array involved
                    if _suppressed(pragmas, node.lineno):
                        continue
                    findings.append(Finding(
                        path, node.lineno, "PROM01",
                        f"float literal {side.value!r} in kernel-module "
                        "arithmetic: jnp silently promotes uint32 "
                        "operands to f32 (use an int, or mark the "
                        "host-only expression with # analysis: ok(...))"))
                    break
        elif isinstance(node, ast.Attribute) and node.attr == "float64":
            if not _suppressed(pragmas, node.lineno):
                findings.append(Finding(
                    path, node.lineno, "PROM02",
                    "float64 reference in a kernel module (the limb "
                    "pipeline is 32-bit end to end)"))


# --- LOCK01: lock discipline --------------------------------------------------

def _lock_attrs(cls):
    """Attrs assigned threading.Lock()/RLock() anywhere in the class."""
    out = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            f = node.value.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if name in ("Lock", "RLock"):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        out.add(attr)
    return out


def _with_lock_ranges(method, locks):
    """(start, end) line ranges of `with self.<lock>` bodies."""
    ranges = []
    for node in ast.walk(method):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in locks:
                end = max(getattr(n, "end_lineno", n.lineno)
                          for n in node.body)
                ranges.append((node.body[0].lineno
                               if node.body else node.lineno, end))
                break
    return ranges


def _flat_targets(targets):
    """Assignment targets with tuple/list unpacking flattened."""
    out = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            out.extend(_flat_targets(t.elts))
        else:
            out.append(t)
    return out


def _writes_in(method):
    """[(attr, line)] of self-attribute mutations in a method."""
    out = []
    for node in ast.walk(method):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in _flat_targets(targets):
                attr = _self_attr(t)
                if attr:
                    out.append((attr, node.lineno,
                                isinstance(t, ast.Subscript)))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for t in _flat_targets([node.target]):
                attr = _self_attr(t)
                if attr:
                    out.append((attr, node.lineno, False))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr:
                    out.append((attr, node.lineno, True))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
            if attr:
                out.append((attr, node.lineno, True))
    return out


def _reads_in(method):
    """[(attr, line)] of self-attribute loads in a method."""
    out = []
    for node in ast.walk(method):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load):
            attr = _self_attr(node)
            if attr:
                out.append((attr, node.lineno))
    return out


def _method_calls(method):
    """Names of self.<m>(...) calls made by a method, with lines."""
    out = []
    for node in ast.walk(method):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            out.append((node.func.attr, node.lineno))
    return out


def _lint_locks(tree, path, src, findings):
    pragmas = _pragma_lines(src)
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        methods = {m.name: m for m in cls.body
                   if isinstance(m, ast.FunctionDef)}
        ranges = {name: _with_lock_ranges(m, locks)
                  for name, m in methods.items()}

        def _in_lock(name, line):
            return any(a <= line <= b for a, b in ranges.get(name, ()))

        # fixpoint: a method is lock-held if every intra-class call site
        # is inside a lock scope or in a lock-held method (__init__ and
        # the lock-holding frames count as held: single-threaded
        # construction / already-serialized)
        held = {"__init__"}
        callers = {}  # method -> [(caller, line)]
        for name, m in methods.items():
            for callee, line in _method_calls(m):
                callers.setdefault(callee, []).append((name, line))
        changed = True
        while changed:
            changed = False
            for name in methods:
                if name in held or name not in callers:
                    continue
                if all(caller in held or _in_lock(caller, line)
                       for caller, line in callers[name]):
                    held.add(name)
                    changed = True

        locked_writers = {}    # attr -> first locked write line
        locked_readers = {}    # attr -> first locked read line
        unlocked_writers = {}  # attr -> [(method, line)]
        for name, m in methods.items():
            if name == "__init__":
                continue
            for attr, line, _sub in _writes_in(m):
                if attr in locks:
                    continue
                if name in held or _in_lock(name, line):
                    locked_writers.setdefault(attr, line)
                else:
                    unlocked_writers.setdefault(attr, []).append(
                        (name, line))
            for attr, line in _reads_in(m):
                if attr not in locks \
                        and (name in held or _in_lock(name, line)):
                    locked_readers.setdefault(attr, line)

        for attr, sites in unlocked_writers.items():
            if attr in locked_writers:
                code, other = "LOCK01", ("written under `with self.<lock>`"
                                         f" at line {locked_writers[attr]}")
            elif attr in locked_readers:
                code, other = "LOCK02", ("read under `with self.<lock>` at"
                                         f" line {locked_readers[attr]}")
            else:
                continue
            for method, line in sites:
                if _suppressed(pragmas, line):
                    continue
                findings.append(Finding(
                    path, line, code,
                    f"{cls.name}.{attr} is {other} but mutated without "
                    f"the lock in {method}()"))


# --- LOCK03: lock-acquisition-order graph -------------------------------------

# lock-object methods: calls on these never descend into user code, so a
# held call to them is not an acquisition edge
_LOCK_OBJ_METHODS = {"acquire", "release", "locked", "notify", "notify_all",
                     "wait", "wait_for"}

# method names that collide with builtin container/string/IO protocols:
# excluded from cross-class NAME matching (a held `d.get(k)` on a plain
# dict must not edge into every class exposing a locked `get`). A held
# call through one of these names onto a real linted object is the
# lint's known blind spot — such APIs get reviewed manually.
_GENERIC_METHODS = {"get", "put", "pop", "popitem", "keys", "values",
                    "items", "update", "setdefault", "clear", "copy",
                    "append", "extend", "insert", "remove", "sort",
                    "index", "count", "add", "discard", "split", "join",
                    "strip", "format", "encode", "decode", "read",
                    "write", "close", "flush", "readline", "seek",
                    "load", "loads", "dump", "dumps", "send", "recv"}


def _lock_kinds(cls):
    """({attr: 'Lock'|'RLock'|'Condition'}, {alias_attr: lock_attr}) for
    a class: attrs assigned threading.Lock()/RLock()/Condition() anywhere
    in the class body. `Condition(self._lock)` does not mint a new lock —
    acquiring the condition IS acquiring the wrapped lock, so it is
    recorded as an alias."""
    kinds, aliases = {}, {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            f = node.value.func
            name = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if name not in ("Lock", "RLock", "Condition"):
                continue
            wrapped = _self_attr(node.value.args[0]) \
                if name == "Condition" and node.value.args else None
            for t in node.targets:
                attr = _self_attr(t)
                if not attr:
                    continue
                if wrapped is not None:
                    aliases[attr] = wrapped
                else:
                    kinds[attr] = name
    # an alias of an unknown lock (Condition over a parameter) counts as
    # its own plain lock
    for a, w in list(aliases.items()):
        if w not in kinds:
            del aliases[a]
            kinds[a] = "Condition"
    return kinds, aliases


def _collect_lock_graph(tree, path, src):
    """Per-class acquisition records for LOCK03 from one module. The
    graph itself is assembled globally (cross-file, cross-class) by
    _lock_graph_findings once every module in scope is collected."""
    pragmas = _pragma_lines(src)
    out = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        kinds, aliases = _lock_kinds(cls)
        if not kinds:
            continue
        rec = {"name": cls.name, "path": path, "pragmas": pragmas,
               "kinds": kinds, "methods": {}}
        for m in cls.body:
            if not isinstance(m, ast.FunctionDef):
                continue

            def canon(expr_attr):
                return aliases.get(expr_attr, expr_attr)

            ranges = {}  # lock attr -> [(body start, body end)]
            for node in ast.walk(m):
                if not isinstance(node, ast.With) or not node.body:
                    continue
                end = max(getattr(n, "end_lineno", n.lineno)
                          for n in node.body)
                for item in node.items:
                    attr = canon(_self_attr(item.context_expr))
                    if attr in kinds:
                        ranges.setdefault(attr, []).append(
                            (node.body[0].lineno, end))

            def held(line):
                return {a for a, rs in ranges.items()
                        if any(s <= line <= e for s, e in rs)}

            with_edges, held_calls, self_calls, attr_calls = [], [], [], []
            for node in ast.walk(m):
                if isinstance(node, ast.With):
                    h, here = held(node.lineno), []
                    for item in node.items:
                        attr = canon(_self_attr(item.context_expr))
                        if attr not in kinds:
                            continue
                        for prev in sorted(h) + here:
                            with_edges.append((prev, attr, node.lineno))
                        here.append(attr)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr not in _LOCK_OBJ_METHODS:
                    is_self = isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self"
                    # cross-class candidates are SIMPLE chains only —
                    # `obj.m()` / `self.attr.m()`; a subscripted chain
                    # (`self._table[k].get(...)`) is container traffic,
                    # and name-matching dict/list protocol calls against
                    # class APIs would flood the graph with false edges
                    simple = isinstance(node.func.value,
                                        (ast.Name, ast.Attribute))
                    if is_self:
                        self_calls.append((node.func.attr, node.lineno))
                    elif simple:
                        attr_calls.append((node.func.attr, node.lineno))
                    h = held(node.lineno)
                    if h and (is_self or simple):
                        held_calls.append((node.func.attr, is_self,
                                           frozenset(h), node.lineno))
            rec["methods"][m.name] = {
                "direct": set(ranges), "with_edges": with_edges,
                "held_calls": held_calls, "self_calls": self_calls,
                "attr_calls": attr_calls}
        out.append(rec)
    return out


def _lock_graph_findings(class_infos):
    """Assemble the global may-hold-while-acquiring graph and report one
    LOCK03 finding per cycle (strongly connected component, or self-edge
    on a non-reentrant lock)."""
    # per-class transitive acquires: locks a method may take through its
    # intra-class self-call closure (fixpoint); the same closure carries
    # the method names it calls on OTHER objects, so a helper invoked
    # under a lock still contributes its outbound cross-class calls
    for rec in class_infos:
        methods = rec["methods"]
        trans = {n: set(m["direct"]) for n, m in methods.items()}
        ext = {n: {c for c, _l in m["attr_calls"]}
               for n, m in methods.items()}
        changed = True
        while changed:
            changed = False
            for n, m in methods.items():
                for callee, _line in m["self_calls"]:
                    extra = trans.get(callee, set()) - trans[n]
                    extra_ext = ext.get(callee, set()) - ext[n]
                    if extra or extra_ext:
                        trans[n] |= extra
                        ext[n] |= extra_ext
                        changed = True
        rec["trans"] = trans
        rec["ext"] = ext

    # method-name index for cross-class edges (no type inference: a held
    # call `obj.submit(...)` edges into every linted class whose `submit`
    # may acquire a lock)
    by_method = {}
    for rec in class_infos:
        for mname, acquired in rec["trans"].items():
            if acquired:
                by_method.setdefault(mname, []).append((rec, acquired))

    def name_targets(callee):
        if callee in _GENERIC_METHODS:
            return []
        return [(rec2, lock) for rec2, locks in by_method.get(callee, ())
                for lock in locks]

    edges = {}  # (src, dst) -> (path, line, suppressed)

    def add_edge(src_rec, src_attr, dst_node, line, path, pragmas):
        src = (src_rec["name"], src_attr)
        if src == dst_node \
                and src_rec["kinds"].get(src_attr) == "RLock":
            return  # re-entrant re-acquisition is fine
        key = (src, dst_node)
        if key not in edges:
            edges[key] = (path, line, _suppressed(pragmas, line))

    for rec in class_infos:
        for m in rec["methods"].values():
            for a, b, line in m["with_edges"]:
                add_edge(rec, a, (rec["name"], b), line,
                         rec["path"], rec["pragmas"])
            for callee, is_self, held, line in m["held_calls"]:
                # name matches back into the SAME class are dropped: the
                # receiver is not self (a helper object whose method name
                # collides with the class API — Histogram.snapshot vs
                # Metrics.snapshot), and intra-class edges are already
                # covered precisely by the self./trans path
                if is_self:
                    # everything the callee may acquire: its own class's
                    # locks plus its outbound calls' name matches
                    targets = [(rec["name"], lock)
                               for lock in rec["trans"].get(callee, ())]
                    for name in rec["ext"].get(callee, ()):
                        targets += [(r2["name"], lock)
                                    for r2, lock in name_targets(name)
                                    if r2 is not rec]
                else:
                    targets = [(r2["name"], lock)
                               for r2, lock in name_targets(callee)
                               if r2 is not rec]
                for h in held:
                    for dst in targets:
                        add_edge(rec, h, dst, line,
                                 rec["path"], rec["pragmas"])

    graph = {}
    for (src, dst) in edges:
        graph.setdefault(src, set()).add(dst)
        graph.setdefault(dst, set())

    # Tarjan SCC (graphs here are tiny; recursion depth is bounded by
    # the node count)
    index_of, low, stack, on_stack, sccs = {}, {}, [], set(), []

    def strongconnect(v, counter=[0]):
        index_of[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in graph.get(v, ()):
            if w not in index_of:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index_of[w])
        if low[v] == index_of[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            sccs.append(comp)

    for v in graph:
        if v not in index_of:
            strongconnect(v)

    findings = []
    for comp in sccs:
        comp_set = set(comp)
        if len(comp) == 1:
            v = comp[0]
            if (v, v) not in edges:
                continue
            cycle = [v, v]
        else:
            # shortest representative cycle from one node back to itself
            # through the component
            start = min(comp_set)
            prev, frontier, seen = {}, [start], {start}
            cycle = None
            while frontier and cycle is None:
                nxt = []
                for u in frontier:
                    for w in graph.get(u, ()):
                        if w == start:
                            cycle = [start]
                            node = u
                            while node != start:
                                cycle.append(node)
                                node = prev[node]
                            cycle.append(start)
                            cycle.reverse()
                            break
                        if w in comp_set and w not in seen:
                            seen.add(w)
                            prev[w] = u
                            nxt.append(w)
                    if cycle:
                        break
                frontier = nxt
            if cycle is None:
                continue  # unreachable for a true SCC
        sites = [edges[(cycle[i], cycle[i + 1])]
                 for i in range(len(cycle) - 1)]
        if any(sup for _p, _l, sup in sites):
            continue  # a pragma on any edge breaks the cycle
        names = " -> ".join(f"{c}.{a}" for c, a in cycle)
        where = "; ".join(f"{os.path.relpath(p, _REPO)}:{line}"
                          for p, line, _s in sites)
        path, line, _s = sites[0]
        if len(cycle) == 2 and cycle[0] == cycle[1]:
            msg = (f"non-reentrant lock {names.split(' -> ')[0]} may be "
                   f"re-acquired while already held (self-deadlock); "
                   f"acquisition sites: {where}")
        else:
            msg = (f"lock-order cycle {names}: two threads taking these "
                   f"locks in opposite orders deadlock; acquisition "
                   f"sites: {where}")
        findings.append(Finding(path, line, "LOCK03", msg))
    return findings


# --- OBS01: metric-name glossary ----------------------------------------------

_GLOSSARY_PATH = os.path.join(_PKG, "service", "metrics.py")
_GLOSSARY_TOKEN_RE = re.compile(r"[a-z][a-z0-9_/]*(?:\*)?")


def parse_glossary(doc):
    """(exact names, wildcard prefixes) from a glossary docstring. Only
    the NAME COLUMN of indented entry lines is read — the entry format
    is `    name [/ name...]  description`, names separated from the
    description by >= 2 spaces — so prose (descriptions, paragraphs)
    can't accidentally document a metric; a token `family_*` (or
    `family/*`) documents every name under that prefix."""
    exact, prefixes = set(), []
    for line in doc.splitlines():
        if not line.startswith("    ") or not line.strip():
            continue
        name_col = re.split(r"\s{2,}", line.strip(), maxsplit=1)[0]
        for tok in _GLOSSARY_TOKEN_RE.findall(name_col):
            if tok.endswith("*"):
                prefixes.append(tok[:-1])
            else:
                exact.add(tok)
    return exact, tuple(prefixes)


def _load_glossary():
    with open(_GLOSSARY_PATH) as f:
        tree = ast.parse(f.read(), filename=_GLOSSARY_PATH)
    return parse_glossary(ast.get_docstring(tree) or "")


def _documented(name, glossary):
    exact, prefixes = glossary
    for n in (name, "store_" + name):  # scoped-registry publication
        if n in exact or any(n.startswith(p) for p in prefixes):
            return True
    return False


def _lint_obs(tree, path, src, findings, glossary):
    pragmas = _pragma_lines(src)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("inc", "observe")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        if _documented(name, glossary) or _suppressed(pragmas, node.lineno):
            continue
        findings.append(Finding(
            path, node.lineno, "OBS01",
            f"metric {name!r} is recorded here but absent from the "
            "service/metrics.py glossary — document it (or a matching "
            "`family_*` wildcard) so the /metrics line stays legible"))


# --- LOG01: structured-log subsystem glossary ---------------------------------

_LOG_GLOSSARY_PATH = os.path.join(_PKG, "obs", "log.py")


def parse_log_glossary(doc):
    """Documented subsystem names from a glossary docstring — delegates
    to obs/log.py's canonical parser (stdlib-only import), so the
    vocabulary this lint enforces and log.documented_subsystems() are
    the product of ONE parser."""
    from ..obs.log import parse_subsystem_glossary
    return parse_subsystem_glossary(doc)


def _load_log_glossary():
    with open(_LOG_GLOSSARY_PATH) as f:
        tree = ast.parse(f.read(), filename=_LOG_GLOSSARY_PATH)
    return parse_log_glossary(ast.get_docstring(tree) or "")


def _lint_log_subsystems(tree, path, src, findings, subsystems):
    pragmas = _pragma_lines(src)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            (f.id if isinstance(f, ast.Name) else None)
        if name != "emit":
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        sub = node.args[0].value
        if sub in subsystems or _suppressed(pragmas, node.lineno):
            continue
        findings.append(Finding(
            path, node.lineno, "LOG01",
            f"log subsystem {sub!r} is emitted here but absent from the "
            "obs/log.py subsystem glossary — document it so the fleet's "
            "structured logs keep one vocabulary"))


# --- ENV01: DPT_* knob glossary -----------------------------------------------

_KNOB_GLOSSARY_PATH = os.path.join(_PKG, "constants.py")
_KNOB_RE = re.compile(r"DPT_[A-Z0-9_]+")
_KNOB_TOKEN_RE = re.compile(r"DPT_[A-Z0-9_]*\*?")


def parse_knob_glossary(doc):
    """(exact names, wildcard prefixes) from the knob glossary held in a
    module docstring — same shape as the OBS01 metric glossary: only the
    NAME COLUMN of indented lines is read (name separated from the
    description by >= 2 spaces), and a `DPT_FAMILY_*` token documents
    every knob under that prefix."""
    exact, prefixes = set(), []
    for line in doc.splitlines():
        if not line.startswith("    ") or not line.strip():
            continue
        name_col = re.split(r"\s{2,}", line.strip(), maxsplit=1)[0]
        for tok in _KNOB_TOKEN_RE.findall(name_col):
            if tok.endswith("*"):
                prefixes.append(tok[:-1])
            else:
                exact.add(tok)
    return exact, tuple(prefixes)


def _load_knob_glossary():
    with open(_KNOB_GLOSSARY_PATH) as f:
        tree = ast.parse(f.read(), filename=_KNOB_GLOSSARY_PATH)
    return parse_knob_glossary(ast.get_docstring(tree) or "")


def _knob_documented(name, glossary):
    exact, prefixes = glossary
    return name in exact or any(name.startswith(p) for p in prefixes)


def _lint_env_knobs(tree, path, src, findings, glossary):
    """Every standalone string literal naming a DPT_* knob (env reads,
    helper-wrapped reads, registry patch targets) must be documented.
    Only whole-literal matches count, so prose mentioning a knob inside
    a docstring or message never false-passes OR false-fails."""
    pragmas = _pragma_lines(src)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _KNOB_RE.fullmatch(node.value)):
            continue
        if _knob_documented(node.value, glossary) \
                or _suppressed(pragmas, node.lineno):
            continue
        findings.append(Finding(
            path, node.lineno, "ENV01",
            f"knob {node.value!r} is read here but absent from the "
            "constants.py knob glossary — document it (or a matching "
            "`DPT_FAMILY_*` wildcard) so operators can discover it"))


# --- TAG01: wire-tag conformance ----------------------------------------------

_PROTOCOL_PATH = os.path.join(_PKG, "runtime", "protocol.py")
_TESTS_DIR = os.path.join(_REPO, "tests")
# mirrors protocol.py's TAG_NAMES comprehension (non-tag uppercase ints)
_NON_TAG_CONSTS = ("FR_BYTES", "FQ_BYTES", "POINT_BYTES")


def _protocol_tags():
    """{tag name: assignment line}, replicated from protocol.TAG_NAMES'
    comprehension by AST so the lint never imports the native codec."""
    with open(_PROTOCOL_PATH) as f:
        tree = ast.parse(f.read(), filename=_PROTOCOL_PATH)
    consts = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.isupper() \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int):
            consts[node.targets[0].id] = (node.value.value, node.lineno)
    err = consts.get("ERR", (101, 0))[0]
    return {name: line for name, (value, line) in consts.items()
            if 0 < value <= err and name not in _NON_TAG_CONSTS}


def _tag_refs_in(tree, tags):
    """Tag names referenced by this module (protocol.NAME attribute
    access or a bare NAME from-import use)."""
    refs = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in tags:
            refs.add(node.attr)
        elif isinstance(node, ast.Name) and node.id in tags:
            refs.add(node.id)
    return refs


def _tag_findings(tags, code_refs):
    """TAG01 findings: tags with no package encode/decode site or no
    test reference. `code_refs` = tag names seen in package code outside
    protocol.py."""
    with open(_PROTOCOL_PATH) as f:
        src = f.read()
    pragmas = _pragma_lines(src)
    test_blob = []
    if os.path.isdir(_TESTS_DIR):
        for fname in sorted(os.listdir(_TESTS_DIR)):
            if fname.endswith(".py"):
                with open(os.path.join(_TESTS_DIR, fname)) as f:
                    test_blob.append(f.read())
    test_blob = "\n".join(test_blob)
    findings = []
    for name, line in sorted(tags.items(), key=lambda kv: kv[1]):
        if _suppressed(pragmas, line):
            continue
        missing = []
        if name not in code_refs:
            missing.append("encode/decode site in the package")
        if not re.search(rf"\b{name}\b", test_blob):
            missing.append("back-compat test reference under tests/")
        if missing:
            findings.append(Finding(
                _PROTOCOL_PATH, line, "TAG01",
                f"wire tag {name} has no {' and no '.join(missing)} — "
                "every protocol tag needs a live codec site and an "
                "old-peer degradation test before it ships"))
    return findings


# --- driver -------------------------------------------------------------------

def _module_globals(tree):
    names = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, (ast.FunctionDef, ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                names.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                names.add(a.asname or a.name)
    return names


def _iter_py(root, subdirs):
    """Yield .py files under each subdir; an entry ending in ".py" is a
    single top-level module (prover.py / aggregate.py)."""
    for sub in subdirs:
        d = os.path.join(root, sub)
        if sub.endswith(".py"):
            if os.path.isfile(d):
                yield d
            continue
        if not os.path.isdir(d):
            continue
        for fname in sorted(os.listdir(d)):
            if fname.endswith(".py"):
                yield os.path.join(d, fname)


def _iter_py_all(root):
    """Every .py file in the package (the ENV01/TAG01 scope)."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def run_lints(pkg_root=_PKG):
    """All lints over their target scopes. Returns [Finding]."""
    findings = []
    glossary = _load_glossary()
    log_glossary = _load_log_glossary()
    knob_glossary = _load_knob_glossary()
    tags = _protocol_tags()
    scoped = set(_iter_py(pkg_root, KERNEL_DIRS + LOCK_DIRS + OBS_DIRS))
    lock_classes, tag_refs = [], set()
    for path in _iter_py_all(pkg_root):
        with open(path) as f:
            src = f.read()
        tree = ast.parse(src, filename=path)
        # package-wide scopes: knob glossary + tag reference collection
        _lint_env_knobs(tree, path, src, findings, knob_glossary)
        if os.path.normpath(path) != os.path.normpath(_PROTOCOL_PATH):
            tag_refs |= _tag_refs_in(tree, tags)
        if path not in scoped:
            continue
        rel = os.path.relpath(path, pkg_root)
        top = rel.split(os.sep)[0]
        if top in KERNEL_DIRS:
            _lint_jit_cache(tree, path, src, _module_globals(tree),
                            findings)
            _lint_promotion(tree, path, src, findings)
        if top in LOCK_DIRS:
            _lint_locks(tree, path, src, findings)
            lock_classes += _collect_lock_graph(tree, path, src)
        if top in OBS_DIRS:
            _lint_obs(tree, path, src, findings, glossary)
            _lint_log_subsystems(tree, path, src, findings, log_glossary)
    findings += _lock_graph_findings(lock_classes)
    findings += _tag_findings(tags, tag_refs)
    return findings


def lint_source(src, path="<string>", kinds=("jit", "prom", "lock"),
                glossary_doc=None, log_glossary_doc=None,
                knob_glossary_doc=None):
    """Lint one source string (unit tests / editor integration).
    glossary_doc: docstring text for the "obs" kind (defaults to the
    real service/metrics.py glossary); log_glossary_doc likewise for
    the "log" kind (defaults to the real obs/log.py glossary);
    knob_glossary_doc likewise for the "env" kind (defaults to the real
    constants.py knob glossary). The "lock" kind runs LOCK01/LOCK02 and
    the LOCK03 order graph over the classes in this one source string."""
    findings = []
    tree = ast.parse(src, filename=path)
    if "jit" in kinds:
        _lint_jit_cache(tree, path, src, _module_globals(tree), findings)
    if "prom" in kinds:
        _lint_promotion(tree, path, src, findings)
    if "lock" in kinds:
        _lint_locks(tree, path, src, findings)
        findings += _lock_graph_findings(
            _collect_lock_graph(tree, path, src))
    if "obs" in kinds:
        glossary = parse_glossary(glossary_doc) \
            if glossary_doc is not None else _load_glossary()
        _lint_obs(tree, path, src, findings, glossary)
    if "log" in kinds:
        subsystems = parse_log_glossary(log_glossary_doc) \
            if log_glossary_doc is not None else _load_log_glossary()
        _lint_log_subsystems(tree, path, src, findings, subsystems)
    if "env" in kinds:
        knobs = parse_knob_glossary(knob_glossary_doc) \
            if knob_glossary_doc is not None else _load_knob_glossary()
        _lint_env_knobs(tree, path, src, findings, knobs)
    return findings
