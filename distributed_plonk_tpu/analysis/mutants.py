"""Seeded known-bad kernel variants: the analyzer's self-test corpus.

A verifier that has never rejected anything is indistinguishable from
one that checks nothing.  This module builds registry Entries around
deliberately broken variants of the production kernels — each mirrors
the REAL kernel body (same helpers, same shapes, same constants) with
exactly ONE seeded defect — plus lint sources seeding the concurrency
and config bug classes.  `check_mutants()` asserts every mutant is
rejected by the pass that owns its bug class under `--strict`, and that
the value-class mutants are INVISIBLE to the interval pass alone:
those are precisely the bugs a bounds analysis cannot see, which is
why the value pass exists.

Bug classes (one Mutant each; `caught_by` names the owning pass):

  dropped-carry-lane    f32 mont_mul assembles the high half without
                        c_t (the t-mod-R carry into column L).  Every
                        limb still fits 16 bits -> bounds-CLEAN; the
                        product value is wrong whenever a*b's low half
                        overflows R.                caught_by: value
  skipped-carry-sweep   u32 mont_mul feeds raw uncarried product
                        columns (< 2^30) into the next column product:
                        u32 overflow.               caught_by: bounds
  off-by-one-limb-shift high half taken from mp_cols[l-1 : 2l-1]
                        instead of [l : 2l].  Sweeps still emit 16-bit
                        limbs -> bounds-clean; the value is shifted
                        garbage.                    caught_by: value
  wrong-modulus         Fr mont_mul built from a FieldSpec whose
                        modulus is p + 2^16 (with its own consistent
                        Montgomery inverse): a perfectly well-formed
                        reduction — for the wrong field.  Same limb
                        ranges -> bounds-clean.     caught_by: value
  swapped-twiddle       the n=32 NTT with its power table rotated one
                        lane: every gathered stage twiddle is stale.
                        Table entries are still canonical limbs ->
                        bounds-clean; the transform no longer matches
                        the poly oracle.            caught_by: value

Lint-side mutants (module constants, checked via lint.lint_source):
LOCK03_MUTANT (a two-class lock-order cycle -> deadlock) and
ENV01_MUTANT (a DPT_* knob read that is not in the constants.py
glossary).  tests/test_analysis.py drives all of this in tier-1.
"""

import numpy as np

from . import registry as R
from .bounds import limb_rows

U16 = (1 << 16) - 1


class Mutant:
    """One seeded defect: a registry Entry plus the pass that owns it.

    caught_by "value": Entry.check() (bounds) must be CLEAN and
    Entry.check_values() must reject.  caught_by "bounds":
    Entry.check() must reject."""

    def __init__(self, entry, caught_by, bug):
        self.entry = entry
        self.caught_by = caught_by
        self.bug = bug

    @property
    def name(self):
        return self.entry.name


def _mont_mul_f32_mutant(spec, a, b, drop_carry=False, off_by_one=False):
    """field_jax.mont_mul's f32/MXU branch, re-assembled from the real
    helpers, with one switchable defect.  With all switches off this IS
    the production body (kept that way so a mutant failure can't be an
    artifact of the harness drifting from the kernel)."""
    from ..backend import field_jax as FJ
    l = spec.n_limbs
    t_cols = FJ._mul_columns_f32(a, b, 2 * l)
    t_lo, c_t = FJ._carry_sweep(t_cols[:l])
    m_cols = FJ._mul_columns_const(spec.ninv_toeplitz, t_lo, l)
    m, _ = FJ._carry_sweep(m_cols)
    mp_cols = FJ._mul_columns_const(spec.mod_toeplitz, m, 2 * l)
    _, c_lo = FJ._carry_sweep(mp_cols[:l] + t_lo)
    hi_mp = mp_cols[l - 1:2 * l - 1] if off_by_one else mp_cols[l:]
    carry_in = c_lo if drop_carry else c_t + c_lo
    hi = (hi_mp + t_cols[l:]).at[0].add(carry_in)
    return FJ._cond_sub_mod(spec, hi)


def _wrong_modulus_spec():
    """An internally consistent FieldSpec for the WRONG prime: Fr's
    modulus nudged up one limb unit, with the matching -p^-1 mod R so
    the Montgomery algebra is flawless — only the field is wrong."""
    from ..backend import field_jax as FJ
    p_bad = FJ.FR.mod + (1 << 16)
    R = 1 << (16 * FJ.FR.n_limbs)
    inv_bad = pow((-p_bad) % R, -1, R)
    return FJ.FieldSpec("FrBad", p_bad, FJ.FR.n_limbs,
                        FJ.FR.mod, inv_bad)  # r2 unused by mont_mul


def _mont_mul_u32_skip_sweep(spec, a, b):
    """field_jax.mont_mul's u32 branch with the t-mod-R carry sweep
    skipped: raw product columns (< 2^30) flow into the m = t*(-p^-1)
    column product, whose u32 partial products then overflow."""
    from ..backend import field_jax as FJ
    l = spec.n_limbs
    t_cols = FJ._mul_columns_u32(a, b, 2 * l)
    t_lo = t_cols[:l]  # MUTANT: _carry_sweep skipped
    ninv = FJ._bcast_const(spec.ninv_limbs, a.ndim)
    m, _ = FJ._carry_sweep(FJ._mul_columns_u32(t_lo, ninv, l))
    p = FJ._bcast_const(spec.mod_limbs, a.ndim)
    mp_cols = FJ._mul_columns_u32(m, p, 2 * l)
    _, c_lo = FJ._carry_sweep(mp_cols[:l] + t_lo)
    hi = (mp_cols[l:] + t_cols[l:]).at[0].add(c_lo)
    return FJ._cond_sub_mod(spec, hi)


def _field_mutants():
    from ..backend import field_jax as FJ
    spec = FJ.FR
    l = spec.n_limbs
    pair = (limb_rows(l, 8), limb_rows(l, 8))
    limbs_out = [(0, U16)]

    def entry(name, fn, value=True):
        val = R._field_value(spec, "mont_mul", 2) if value else None
        return R.Entry(name, fn, pair, limbs_out, value=val)

    return [
        Mutant(entry("field/mutant_dropped_carry_lane_f32",
                     lambda a, b: _mont_mul_f32_mutant(
                         spec, a, b, drop_carry=True)),
               "value", "dropped-carry-lane"),
        Mutant(entry("field/mutant_skipped_carry_sweep_u32",
                     lambda a, b: _mont_mul_u32_skip_sweep(spec, a, b)),
               "bounds", "skipped-carry-sweep"),
        Mutant(entry("field/mutant_off_by_one_limb_shift_f32",
                     lambda a, b: _mont_mul_f32_mutant(
                         spec, a, b, off_by_one=True)),
               "value", "off-by-one-limb-shift"),
        Mutant(entry("field/mutant_wrong_modulus_f32",
                     lambda a, b, bad=_wrong_modulus_spec():
                     _mont_mul_f32_mutant(bad, a, b)),
               "value", "wrong-modulus"),
    ]


def _ntt_mutant():
    from ..backend import ntt_jax as NTT
    # fresh NttPlan, not get_plan: the mutated consts must not poison
    # the shared plan's memo
    plan = NTT.NttPlan(32)
    fn, consts = plan.traced_kernel(False, False, boundary="mont",
                                    radix=4, kernel="xla")
    bad = {k: np.asarray(v) for k, v in consts.items()}
    bad["pow"] = np.roll(bad["pow"], 1, axis=1)  # MUTANT: stale twiddles
    entry = R.Entry("ntt/mutant_swapped_twiddle_n32", fn,
                    (limb_rows(16, 32), bad), [(0, U16)],
                    value=R._ntt_value(32, False, False, bad))
    return Mutant(entry, "value", "swapped-twiddle")


def build_mutants():
    """All seeded kernel mutants (list of Mutant)."""
    return _field_mutants() + [_ntt_mutant()]


def check_mutants(progress=None):
    """Run every mutant through both passes under --strict semantics and
    return a list of error strings — NON-EMPTY means the analyzer lost
    a bug class it is contractually able to catch (or a value-class
    mutant stopped being bounds-clean, i.e. the harness no longer
    demonstrates the interval pass's blind spot).  [] == the analyzer
    still rejects every seeded defect for the right reason."""
    errors = []
    for m in build_mutants():
        bounds_v = m.entry.check(strict=True)
        value_v = m.entry.check_values(strict=True)
        if m.caught_by == "bounds":
            if not bounds_v:
                errors.append(f"{m.name} ({m.bug}): bounds pass no "
                              f"longer rejects this mutant")
        else:
            if bounds_v:
                errors.append(
                    f"{m.name} ({m.bug}): expected bounds-clean (the "
                    f"interval pass cannot see this bug class) but got: "
                    f"{bounds_v[0]}")
            if not value_v:
                errors.append(f"{m.name} ({m.bug}): value pass no "
                              f"longer rejects this mutant")
        if progress is not None:
            progress(m, bounds_v, value_v)
    return errors


# -- lint-side mutants ---------------------------------------------------------

# Two classes, each calling into the other under its own lock: the
# classic AB/BA lock-order cycle LOCK03's graph closure must find.
LOCK03_MUTANT = '''
import threading


class Scheduler:
    def __init__(self, ledger):
        self._lock = threading.Lock()
        self.ledger = ledger
        self.active = 0

    def promote(self, job):
        with self._lock:
            self.active += 1
            self.ledger.record(job)   # MUTANT: held call into Ledger

    def drain(self):
        with self._lock:
            self.active = 0


class Ledger:
    def __init__(self, sched):
        self._lock = threading.Lock()
        self.sched = sched
        self.rows = 0

    def record(self, job):
        with self._lock:
            self.rows += 1

    def audit(self):
        with self._lock:
            self.sched.drain()        # back edge -> AB/BA cycle
'''

# Same classes with the back edge moved outside the lock: the cycle is
# broken, so LOCK03 must stay silent.
LOCK03_FIXED = LOCK03_MUTANT.replace(
    "        with self._lock:\n"
    "            self.sched.drain()        # back edge -> AB/BA cycle",
    "        with self._lock:\n"
    "            rows = self.rows\n"
    "        self.sched.drain()\n"
    "        return rows")

# A non-reentrant lock re-acquired through a held self-call: the
# single-class LOCK03 self-deadlock form.
LOCK03_SELF_MUTANT = '''
import threading


class Journal:
    def __init__(self):
        self._lock = threading.Lock()
        self.entries = 0

    def compact(self):
        with self._lock:
            self.truncate()           # MUTANT: re-acquires self._lock

    def truncate(self):
        with self._lock:
            self.entries = 0
'''

# A DPT_* knob read the constants.py glossary does not document.
ENV01_MUTANT = '''
import os


def fanout():
    return int(os.environ.get("DPT_MUTANT_UNDOCUMENTED_KNOB", "4"))
'''

# Glossary text that documents the knob: ENV01 must accept it (shape
# mirrors the real constants.py knob table: name column + >= 2 spaces).
ENV01_GLOSSARY = """Knobs:

    DPT_MUTANT_UNDOCUMENTED_KNOB  fan-out width (default 4).
"""
