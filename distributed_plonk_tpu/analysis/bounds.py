"""Jaxpr abstract interpretation: per-value integer magnitude intervals.

The kernel half of the static verifier (`python -m
distributed_plonk_tpu.analysis`). Every hot kernel in this prover is
correct only under hand-reasoned magnitude bounds — 16x16-bit limb
products fit a uint32, byte-column sums stay exact in f32, carry sweeps
receive values that fit their limb count. This module re-derives those
bounds mechanically: it traces a kernel with `jax.make_jaxpr` at
representative shapes and pushes an interval `[lo, hi]` per traced value
through the primitive vocabulary the kernels use, reporting a violation
wherever

  (a) an integer op's true-math result can leave its dtype's range
      (silent modular wraparound — the overflow class a dropped carry
      sweep or widened shift introduces),
  (b) a float value can stop being an exactly-represented integer
      (f32 values must stay < 2^24, bf16 operands < 2^8, and float
      inputs must be integer-valued — the exactness contract the
      MXU/byte-product multiplier path rests on), or
  (c) a forbidden dtype appears (f64/x64: nothing in the limb pipeline
      may silently promote), or a declared output bound is exceeded.

Control flow: `lax.scan` / `lax.while_loop` bodies are interpreted to a
carry fixpoint (join-until-stable, bounded iterations) — a carry whose
bound keeps growing is itself reported (`scan carry bounds do not
stabilize`). `pjit` / custom-call wrappers are entered transparently.

Pallas kernels: a `pallas_call` eqn is entered too — the kernel IS a
jaxpr. Every input/output/scratch ref becomes one interval cell
(_RefCell: full-coverage writes replace, partial writes join,
read-before-any-write is the full dtype range), `pl.when` branches run
from a shared entry state and join their exits, `program_id` is bounded
by the enclosing grid, and the grid itself is a join-until-stable
fixpoint (VMEM scratch persists across grid steps exactly like a scan
carry). Outputs take their cells' stabilized bounds.

Precision notes (sound, documented weakenings):
- Intervals collapse array extent: one `[lo, hi]` per value, with exact
  intervals for concrete constants (twiddle/exponent tables).
- The one-hot bucket gather (`sum(where(dg == iota, plane, 0), axis)`)
  is recognized structurally — eq-against-iota yields a mask with at
  most one hit per reduced lane, so the masked sum's bound is the
  plane's bound, not plane * buckets.
- `scatter-add` assumes each output element receives at most one
  update (true for the kernels' `.at[idx].add` uses: unique indices).

What intervals cannot prove — that a *value spread across limb columns*
fits its limb count (the zero-carry-out claims of `_carry_sweep`
callers rest on modular number theory: `v < 2p <= R`, `(t + m*p)/R <
2p`) — is promoted instead into `field_jax.CARRY_CONTRACTS`, explicit
inequalities over the actual field constants that `check_contracts`
evaluates for every spec. Together: intervals prove no op overflows for
ANY input the declared bounds admit; the contracts prove the documented
zero-carry side conditions hold for these moduli.
"""

import math

import numpy as np

import jax
import jax.numpy as jnp


# dtypes whose appearance anywhere in a kernel trace is a violation:
# the limb pipeline is 32-bit; an x64 or double promotion is always an
# accident (jax x64 is globally off, but a trace-level check catches a
# kernel that flips it or a numpy f64 constant leaking in)
_FORBIDDEN_DTYPES = {"float64", "int64", "uint64", "complex64", "complex128"}

# largest integer magnitude each float dtype represents EXACTLY
# (2^mantissa_bits); values at or under this bound round-trip, so
# integer arithmetic staged through these dtypes stays exact as long as
# every intermediate (including dot_general accumulations) fits
_FLOAT_EXACT_MAX = {
    "float32": 1 << 24,
    "bfloat16": 1 << 8,
    "float16": 1 << 11,
}


def _dtype_range(dtype):
    d = np.dtype(dtype)
    if d.kind == "b":
        return 0, 1
    if d.kind in "ui":
        info = np.iinfo(d)
        return int(info.min), int(info.max)
    return -math.inf, math.inf


class AbsVal:
    """Abstract value: dtype + magnitude interval + exactness/shape tags.

    lo/hi are Python ints (or +-inf / floats for float dtypes) bounding
    every element. `exact` means "provably an exactly-represented
    integer" (always true for int/bool dtypes; tracked for floats).
    `bcast_axes` are axes along which the value is known constant;
    `iota_axis` marks a broadcasted_iota; `onehot_axes` are axes along
    which at most one element is nonzero (everything else exactly 0).
    `zero` marks a provably all-zero value.
    """

    __slots__ = ("dtype", "shape", "lo", "hi", "exact",
                 "bcast_axes", "iota_axis", "onehot_axes", "pow2",
                 "anchor", "anchor_kind")

    def __init__(self, dtype, shape, lo, hi, exact=True,
                 bcast_axes=frozenset(), iota_axis=None,
                 onehot_axes=frozenset(), pow2=0):
        self.dtype = np.dtype(dtype)
        self.shape = tuple(shape)
        self.lo = lo
        self.hi = hi
        self.exact = exact
        self.bcast_axes = frozenset(bcast_axes)
        self.iota_axis = iota_axis
        self.onehot_axes = frozenset(onehot_axes)
        # pow2 < 0: the value is m * 2^pow2 with m an exactly-represented
        # f32 integer — an exponent-only rescale of an exact value (the
        # lazy-carry kernels' cols * 2^-8). anchor/anchor_kind track the
        # x -> x*2^-k -> floor -> *2^k -> x - that remainder chain (the
        # lazy local rounds' base-2^k digit split, which plain interval
        # arithmetic cannot bound below 2^k): "scaled" = x * 2^-k,
        # "floordiv" = floor(x * 2^-k), "floormul" = floor(x * 2^-k)*2^k,
        # each anchored to id(x). Every rule that constructs a fresh
        # AbsVal drops the tags (conservative, sound).
        self.pow2 = pow2
        self.anchor = None
        self.anchor_kind = None

    @property
    def zero(self):
        return self.lo == 0 and self.hi == 0

    def __repr__(self):
        return (f"AbsVal({self.dtype}, {self.shape}, "
                f"[{self.lo}, {self.hi}], exact={self.exact})")


def from_concrete(x):
    """AbsVal of a concrete numpy array / scalar (exact interval)."""
    a = np.asarray(x)
    if a.size == 0:
        lo, hi = 0, 0
    elif a.dtype.kind == "b":
        lo, hi = int(a.min()), int(a.max())
    elif a.dtype.kind in "ui":
        lo, hi = int(a.min()), int(a.max())
    else:
        lo, hi = float(a.min()), float(a.max())
    exact = True
    if a.dtype.kind == "f" and a.size:
        exact = bool(np.all(a == np.floor(a)))
    return AbsVal(a.dtype, a.shape, lo, hi, exact=exact)


class Bound:
    """Declared input interval for a traced argument: shape + dtype +
    [lo, hi] over every element (the kernel's documented precondition,
    e.g. '16-bit limb rows' = Bound(shape, uint32, 0, 2**16 - 1))."""

    def __init__(self, shape, dtype, lo, hi):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.lo = lo
        self.hi = hi

    def absval(self):
        return AbsVal(self.dtype, self.shape, self.lo, self.hi)

    def spec(self):
        return jax.ShapeDtypeStruct(self.shape, self.dtype)


def limb_rows(*shape):
    """16-bit limb array bound (the standard kernel input contract)."""
    return Bound(shape, jnp.uint32, 0, (1 << 16) - 1)


class _RefCell:
    """Abstract state of one Pallas ref (input block / output block /
    VMEM scratch): a single interval covering every element the ref has
    ever held, or BOTTOM (None) before the first write. A full-coverage
    write replaces the interval (strong update); a partial write joins
    (the untouched region keeps its old bound); a partial write to
    BOTTOM widens to the full dtype range — sound for kernels that may
    read what they never wrote."""

    __slots__ = ("dtype", "shape", "val")

    def __init__(self, dtype, shape, val=None):
        self.dtype = np.dtype(dtype)
        self.shape = tuple(shape)
        self.val = val  # AbsVal or None (= bottom / uninitialized)

    def read(self, dtype, shape):
        if self.val is None:
            lo, hi = _dtype_range(dtype)
            return AbsVal(dtype, shape, lo, hi,
                          exact=np.dtype(dtype).kind != "f")
        return AbsVal(dtype, shape, self.val.lo, self.val.hi,
                      exact=self.val.exact)

    def write(self, val, full):
        norm = AbsVal(self.dtype, self.shape, val.lo, val.hi,
                      exact=val.exact)
        if full:
            self.val = norm
        elif self.val is None:
            lo, hi = _dtype_range(self.dtype)
            self.val = AbsVal(self.dtype, self.shape, lo, hi,
                              exact=self.dtype.kind != "f")
        else:
            self.val = _join(self.val, norm)


class Violation:
    def __init__(self, kernel, prim, message, where=""):
        self.kernel = kernel
        self.prim = prim
        self.message = message
        self.where = where

    def __str__(self):
        loc = f" @ {self.where}" if self.where else ""
        return f"[{self.kernel}] {self.prim}: {self.message}{loc}"


def _source_of(eqn):
    try:
        from jax._src import source_info_util
        return source_info_util.summarize(eqn.source_info)
    except Exception:  # pragma: no cover - jax internals moved
        return ""


def _join(a, b):
    """Least upper bound of two AbsVals of one variable (same aval)."""
    return AbsVal(a.dtype, a.shape, min(a.lo, b.lo), max(a.hi, b.hi),
                  exact=a.exact and b.exact,
                  bcast_axes=a.bcast_axes & b.bcast_axes,
                  iota_axis=a.iota_axis if a.iota_axis == b.iota_axis
                  else None,
                  onehot_axes=a.onehot_axes & b.onehot_axes,
                  pow2=a.pow2 if a.pow2 == b.pow2 else 0)


def _pow2_exponent(v):
    """k if v is a single-valued positive power-of-two constant 2^k,
    else None (the exact-rescale side condition of the mul rule)."""
    if v.lo != v.hi or not v.lo > 0:
        return None
    m, e = math.frexp(float(v.lo))
    return e - 1 if m == 0.5 else None


def _stable(prev, new):
    return new.lo >= prev.lo and new.hi <= prev.hi


# primitives that only move data (intervals and exactness pass through
# unchanged; structural tags are dropped conservatively)
_SHAPE_ONLY = {
    "reshape", "transpose", "squeeze", "expand_dims", "rev", "slice",
    "dynamic_slice", "copy", "stop_gradient", "gather", "real",
    "reduce_max", "reduce_min", "device_put", "sharding_constraint",
    "optimization_barrier", "reduce_precision", "dynamic_update_slice",
    "sort", "pad", "concatenate",
}

# calls to enter transparently (sub-jaxpr under params['jaxpr'] or
# params['call_jaxpr'])
_CALL_PRIMS = {"pjit", "closed_call", "core_call", "custom_jvp_call",
               "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
               "checkpoint", "xla_call", "named_call"}

_MAX_FIXPOINT_ITERS = 8


class Interpreter:
    def __init__(self, kernel_name, strict=True):
        self.kernel = kernel_name
        self.strict = strict
        self.violations = []
        self.warnings = []
        self._check = True  # False while searching for a loop fixpoint
        self._grids = []    # enclosing pallas_call grids (program_id bound)

    # -- reporting ------------------------------------------------------------

    def _flag(self, eqn, msg):
        if self._check:
            self.violations.append(
                Violation(self.kernel, eqn.primitive.name, msg,
                          _source_of(eqn)))

    def _warn(self, eqn, msg):
        if self._check:
            self.warnings.append(
                Violation(self.kernel, eqn.primitive.name, msg,
                          _source_of(eqn)))

    # -- environment ----------------------------------------------------------

    def _read(self, env, var):
        if isinstance(var, jax.core.Literal):
            return from_concrete(var.val)
        return env[var]

    def _out(self, eqn, i=0):
        aval = eqn.outvars[i].aval
        return aval.dtype, tuple(aval.shape)

    def _mk(self, eqn, lo, hi, exact=True, i=0, **tags):
        dtype, shape = self._out(eqn, i)
        return AbsVal(dtype, shape, lo, hi, exact=exact, **tags)

    # -- dtype / overflow checks ----------------------------------------------

    def _check_dtype(self, eqn, v):
        if v.dtype.name in _FORBIDDEN_DTYPES:
            self._flag(eqn, f"forbidden dtype {v.dtype.name} "
                            "(x64/double promotion in an integer kernel)")

    def _arith_result(self, eqn, lo, hi, exact_in=True, i=0):
        """Bound-check an arithmetic result against its dtype and return
        the (possibly clamped) AbsVal."""
        dtype, shape = self._out(eqn, i)
        d = np.dtype(dtype)
        self._check_dtype(eqn, AbsVal(dtype, shape, lo, hi))
        if d.kind in "uib":
            dlo, dhi = _dtype_range(d)
            if hi > dhi or lo < dlo:
                self._flag(eqn, f"{d.name} range exceeded: result in "
                                f"[{lo}, {hi}] vs dtype [{dlo}, {dhi}] "
                                "(silent modular wraparound)")
                return AbsVal(dtype, shape, max(lo, dlo),
                              min(hi, dhi))
            return AbsVal(dtype, shape, lo, hi)
        # float result: must remain an exactly-representable integer
        exact_max = _FLOAT_EXACT_MAX.get(d.name)
        exact = exact_in
        if not exact_in:
            self._flag(eqn, f"{d.name} value is not provably integer-"
                            "valued (float contamination in an integer "
                            "kernel)")
        elif exact_max is not None and max(abs(lo), abs(hi)) > exact_max:
            self._flag(eqn, f"{d.name} exactness lost: |result| can reach "
                            f"{max(abs(lo), abs(hi))} > {exact_max} "
                            f"(2^{exact_max.bit_length() - 1} integer "
                            "round-trip bound)")
            exact = False
        return AbsVal(dtype, shape, lo, hi, exact=exact)

    # -- the interpreter ------------------------------------------------------

    def run(self, closed_jaxpr, in_vals):
        """Interpret a ClosedJaxpr given AbsVals for its invars; returns
        AbsVals for its outvars."""
        jaxpr = closed_jaxpr.jaxpr
        env = {}
        for var, const in zip(jaxpr.constvars, closed_jaxpr.consts):
            env[var] = from_concrete(const)
        assert len(jaxpr.invars) == len(in_vals), \
            (len(jaxpr.invars), len(in_vals))
        for var, val in zip(jaxpr.invars, in_vals):
            env[var] = val
        self._run_eqns(jaxpr.eqns, env)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _run_eqns(self, eqns, env):
        for eqn in eqns:
            ins = [self._read(env, v) for v in eqn.invars]
            outs = self._eqn(eqn, ins, env)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            for var, val in zip(eqn.outvars, outs):
                if isinstance(val, AbsVal):
                    self._check_dtype(eqn, val)
                env[var] = val

    def _subjaxpr(self, eqn):
        p = eqn.params
        sub = p.get("jaxpr") or p.get("call_jaxpr") or p.get("fun_jaxpr")
        if sub is None and "branches" in p:
            return None
        if sub is not None and not hasattr(sub, "consts"):
            sub = jax.core.ClosedJaxpr(sub, ())
        return sub

    def _eqn(self, eqn, ins, env):
        name = eqn.primitive.name

        if name in _CALL_PRIMS:
            sub = self._subjaxpr(eqn)
            if sub is None:
                return self._fallback(eqn, ins)
            n = len(sub.jaxpr.invars)
            return self.run(sub, ins[len(ins) - n:])

        if name == "scan":
            return self._scan(eqn, ins)
        if name == "while":
            return self._while(eqn, ins)
        if name == "cond":
            return self._cond(eqn, ins)

        handler = getattr(self, "_p_" + name.replace("-", "_"), None)
        if handler is not None:
            return handler(eqn, ins)
        if name in _SHAPE_ONLY:
            return self._shape_only(eqn, ins)
        return self._fallback(eqn, ins)

    def _fallback(self, eqn, ins):
        """Unknown primitive: full dtype range (sound), and in strict
        mode a violation — silent imprecision would let a kernel rewrite
        smuggle an unvetted op past the verifier."""
        msg = (f"unhandled primitive '{eqn.primitive.name}' "
               "(add a transfer rule to analysis/bounds.py)")
        if self.strict:
            self._flag(eqn, msg)
        else:
            self._warn(eqn, msg)
        outs = []
        for i in range(len(eqn.outvars)):
            dtype, shape = self._out(eqn, i)
            lo, hi = _dtype_range(dtype)
            outs.append(AbsVal(dtype, shape, lo, hi,
                               exact=np.dtype(dtype).kind != "f"))
        return outs

    def _shape_only(self, eqn, ins):
        v = ins[0]
        dtype, shape = self._out(eqn)
        lo, hi = v.lo, v.hi
        exact = v.exact
        if eqn.primitive.name in ("pad", "concatenate",
                                  "dynamic_update_slice", "sort"):
            for o in ins[1:]:
                lo, hi = min(lo, o.lo), max(hi, o.hi)
                exact = exact and o.exact
        outs = [AbsVal(dtype, shape, lo, hi, exact=exact)]
        # extra outputs (e.g. argsort's index operand through `sort`)
        # need not share the data interval: full dtype range, sound
        for i in range(1, len(eqn.outvars)):
            d, s = self._out(eqn, i)
            dlo, dhi = _dtype_range(d)
            outs.append(AbsVal(d, s, dlo, dhi,
                               exact=np.dtype(d).kind != "f"))
        return outs

    # -- elementwise arithmetic -----------------------------------------------

    def _p_add(self, eqn, ins):
        a, b = ins
        return self._arith_result(eqn, a.lo + b.lo, a.hi + b.hi,
                                  a.exact and b.exact)

    def _p_sub(self, eqn, ins):
        a, b = ins
        if (b.anchor_kind == "floormul" and b.anchor == id(a)
                and a.lo >= 0 and a.exact):
            # x - floor(x * 2^-k) * 2^k for x >= 0: the base-2^k
            # remainder, in [0, 2^k) (the lazy-carry local rounds'
            # digit split; every op in the chain was proved exact)
            return self._arith_result(eqn, 0, (1 << (-b.pow2)) - 1)
        return self._arith_result(eqn, a.lo - b.hi, a.hi - b.lo,
                                  a.exact and b.exact)

    def _p_mul(self, eqn, ins):
        a, b = ins
        prods = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
        d = np.dtype(self._out(eqn)[0])
        if d.kind == "f":
            for x, y in ((a, b), (b, a)):
                k = _pow2_exponent(y)
                if k is None:
                    continue
                if (k < 0 and x.exact and x.pow2 == 0 and x.lo >= 0
                        and np.dtype(x.dtype).kind == "f"):
                    # exact power-of-two down-scaling (the lazy-carry
                    # local rounds' cols * 2^-8): exponent-only, the
                    # mantissa — already proved f32-exact via x.exact —
                    # is untouched, so the value is exactly m * 2^k even
                    # though no longer integer-valued. Tag for the floor
                    # rule instead of flagging here.
                    out = AbsVal(d, self._out(eqn)[1], min(prods),
                                 max(prods), exact=False, pow2=k)
                    out.anchor = id(x)
                    out.anchor_kind = "scaled"
                    return out
                if (k > 0 and x.anchor_kind == "floordiv"
                        and x.pow2 == -k and x.exact):
                    # floor(x * 2^-k) * 2^k: restore the anchor so the
                    # subtraction rule can recognize the remainder
                    out = self._arith_result(eqn, min(prods), max(prods))
                    out.anchor = x.anchor
                    out.anchor_kind = "floormul"
                    out.pow2 = x.pow2
                    return out
        return self._arith_result(eqn, min(prods), max(prods),
                                  a.exact and b.exact)

    def _p_neg(self, eqn, ins):
        (a,) = ins
        return self._arith_result(eqn, -a.hi, -a.lo, a.exact)

    def _p_abs(self, eqn, ins):
        (a,) = ins
        lo = 0 if a.lo <= 0 <= a.hi else min(abs(a.lo), abs(a.hi))
        return self._arith_result(eqn, lo, max(abs(a.lo), abs(a.hi)),
                                  a.exact)

    def _p_max(self, eqn, ins):
        a, b = ins
        return self._arith_result(eqn, max(a.lo, b.lo), max(a.hi, b.hi),
                                  a.exact and b.exact)

    def _p_min(self, eqn, ins):
        a, b = ins
        return self._arith_result(eqn, min(a.lo, b.lo), min(a.hi, b.hi),
                                  a.exact and b.exact)

    def _p_clamp(self, eqn, ins):
        lo_v, x, hi_v = ins
        return self._arith_result(eqn, max(x.lo, lo_v.lo),
                                  min(x.hi, hi_v.hi), x.exact)

    def _p_sign(self, eqn, ins):
        return self._mk(eqn, -1, 1)

    def _p_floor(self, eqn, ins):
        # floor of an exact value (or of a pow2-tagged exact rescale) is
        # an exact integer; _arith_result re-checks the f32 magnitude
        # bound. floor of anything else is integer-valued but its
        # pre-round error is unknowable — flag like any inexact float.
        # Used by field_pallas' lazy-carry local rounds.
        (a,) = ins
        lo, hi = int(math.floor(a.lo)), int(math.floor(a.hi))
        out = self._arith_result(eqn, lo, hi,
                                 exact_in=a.exact or a.pow2 < 0)
        if a.anchor_kind == "scaled" and a.pow2 < 0:
            out.anchor = a.anchor
            out.anchor_kind = "floordiv"
            out.pow2 = a.pow2
        return out

    def _p_round(self, eqn, ins):
        (a,) = ins
        return self._arith_result(eqn, int(math.floor(a.lo)),
                                  int(math.ceil(a.hi)),
                                  exact_in=a.exact or a.pow2 < 0)

    def _p_integer_pow(self, eqn, ins):
        (a,) = ins
        y = eqn.params["y"]
        vals = [a.lo ** y, a.hi ** y] + ([0] if a.lo <= 0 <= a.hi else [])
        return self._arith_result(eqn, min(vals), max(vals), a.exact)

    def _p_rem(self, eqn, ins):
        a, b = ins
        if b.lo >= 1:
            # C-style rem with positive divisors: sign follows the
            # dividend, |result| < divisor and |result| <= |dividend|
            m = b.hi - 1
            lo = 0 if a.lo >= 0 else max(-m, a.lo)
            hi = 0 if a.hi <= 0 else min(m, a.hi)
            return self._arith_result(eqn, lo, hi, a.exact)
        return self._fallback(eqn, ins)

    def _p_div(self, eqn, ins):
        a, b = ins
        d = np.dtype(self._out(eqn)[0])
        if d.kind in "ui" and b.lo == b.hi and b.lo > 0:
            n = b.lo

            def q(v):  # lax.div truncates toward ZERO (not floor)
                return -((-v) // n) if v < 0 else v // n

            return self._arith_result(eqn, q(a.lo), q(a.hi), True)
        # float division: exactness is not preserved in general
        lo, hi = _dtype_range(d)
        return self._arith_result(eqn, lo, hi, exact_in=False)

    # -- bitwise / shifts ------------------------------------------------------

    def _bits_hi(self, hi):
        return (1 << int(hi).bit_length()) - 1 if hi > 0 else 0

    def _p_and(self, eqn, ins):
        a, b = ins
        if a.lo < 0 or b.lo < 0:
            dlo, dhi = _dtype_range(self._out(eqn)[0])
            return self._mk(eqn, dlo, dhi)
        return self._mk(eqn, 0, min(a.hi, b.hi))

    def _p_or(self, eqn, ins):
        a, b = ins
        if a.lo < 0 or b.lo < 0:
            dlo, dhi = _dtype_range(self._out(eqn)[0])
            return self._mk(eqn, dlo, dhi)
        return self._mk(eqn, max(a.lo, b.lo),
                        max(self._bits_hi(a.hi), self._bits_hi(b.hi)))

    def _p_xor(self, eqn, ins):
        a, b = ins
        if a.lo < 0 or b.lo < 0:
            dlo, dhi = _dtype_range(self._out(eqn)[0])
            return self._mk(eqn, dlo, dhi)
        return self._mk(eqn, 0,
                        max(self._bits_hi(a.hi), self._bits_hi(b.hi)))

    def _p_not(self, eqn, ins):
        d = np.dtype(self._out(eqn)[0])
        if d.kind == "b":
            return self._mk(eqn, 0, 1)
        dlo, dhi = _dtype_range(d)
        return self._mk(eqn, dlo, dhi)

    def _p_shift_left(self, eqn, ins):
        a, s = ins
        if s.lo < 0:
            return self._fallback(eqn, ins)
        # true-math bound: wraparound past the dtype is the violation a
        # widened shift introduces
        lo = a.lo << s.lo if a.lo >= 0 else a.lo << s.hi
        hi = a.hi << s.hi if a.hi >= 0 else a.hi << s.lo
        return self._arith_result(eqn, lo, hi, a.exact)

    def _p_shift_right_logical(self, eqn, ins):
        a, s = ins
        if a.lo < 0:
            dlo, dhi = _dtype_range(self._out(eqn)[0])
            return self._mk(eqn, 0, dhi)
        return self._mk(eqn, a.lo >> s.hi, a.hi >> s.lo)

    def _p_shift_right_arithmetic(self, eqn, ins):
        a, s = ins
        return self._mk(eqn, min(a.lo >> s.lo, a.lo >> s.hi),
                        max(a.hi >> s.lo, a.hi >> s.hi))

    # -- comparisons / select --------------------------------------------------

    def _cmp(self, eqn, ins):
        a, b = ins
        onehot = frozenset()
        # eq against a broadcasted_iota along axis k, where the other
        # operand is constant along k (size-1 axis or broadcast): at
        # most one index matches per lane => one-hot mask along k
        if eqn.primitive.name == "eq":
            for x, y in ((a, b), (b, a)):
                k = x.iota_axis
                if k is None:
                    continue
                const_along_k = (k in y.bcast_axes
                                 or (k < len(y.shape) and y.shape[k] == 1)
                                 or y.lo == y.hi)
                if const_along_k:
                    onehot = onehot | {k}
        return self._mk(eqn, 0, 1, onehot_axes=onehot)

    _p_eq = _cmp
    _p_ne = _cmp
    _p_ge = _cmp
    _p_gt = _cmp
    _p_le = _cmp
    _p_lt = _cmp

    def _p_select_n(self, eqn, ins):
        pred, *cases = ins
        lo = min(c.lo for c in cases)
        hi = max(c.hi for c in cases)
        exact = all(c.exact for c in cases)
        onehot = frozenset()
        # where(mask, v, 0): if the mask is one-hot along k and the
        # mostly-selected FALSE case (index 0) is exactly zero, the
        # result is zero outside one slot along k — a later sum over k
        # needs no axis multiplier
        if len(cases) == 2 and pred.onehot_axes and cases[0].zero:
            onehot = pred.onehot_axes
        return self._mk(eqn, lo, hi, exact=exact, onehot_axes=onehot)

    # -- structure -------------------------------------------------------------

    def _p_broadcast_in_dim(self, eqn, ins):
        (a,) = ins
        dims = eqn.params["broadcast_dimensions"]
        dtype, shape = self._out(eqn)
        bcast = set(range(len(shape))) - set(dims)
        for i, d in enumerate(dims):
            if a.shape[i] == 1 and shape[d] != 1:
                bcast.add(d)
        for ax in a.bcast_axes:
            if ax < len(dims):
                bcast.add(dims[ax])
        iota_axis = None
        if a.iota_axis is not None and a.iota_axis < len(dims):
            d = dims[a.iota_axis]
            if shape[d] == a.shape[a.iota_axis]:
                iota_axis = d
        onehot = frozenset(dims[ax] for ax in a.onehot_axes
                           if ax < len(dims)
                           and shape[dims[ax]] == a.shape[ax])
        return AbsVal(dtype, shape, a.lo, a.hi, exact=a.exact,
                      bcast_axes=frozenset(bcast), iota_axis=iota_axis,
                      onehot_axes=onehot)

    def _p_iota(self, eqn, ins):
        dim = eqn.params["dimension"]
        dtype, shape = self._out(eqn)
        bcast = frozenset(i for i in range(len(shape)) if i != dim)
        return AbsVal(dtype, shape, 0, max(shape[dim] - 1, 0),
                      bcast_axes=bcast, iota_axis=dim)

    def _p_convert_element_type(self, eqn, ins):
        (a,) = ins
        dtype, shape = self._out(eqn)
        d = np.dtype(dtype)
        if d.kind in "uib":
            if np.dtype(a.dtype).kind == "f" and not a.exact:
                self._flag(eqn, "float -> int conversion of a value that "
                                "is not provably integer-valued")
            dlo, dhi = _dtype_range(d)
            lo = dlo if a.lo == -math.inf else int(math.floor(a.lo))
            hi = dhi if a.hi == math.inf else int(math.ceil(a.hi))
            return self._arith_result(eqn, lo, hi, True)
        return self._arith_result(eqn, a.lo, a.hi, a.exact)

    # -- reductions ------------------------------------------------------------

    def _reduce_count(self, eqn, v):
        """Number of summed elements per output lane, discounting axes
        where at most one element is nonzero (one-hot gather)."""
        n = 1
        for ax in eqn.params["axes"]:
            if ax in v.onehot_axes:
                continue
            n *= v.shape[ax]
        return max(n, 1)

    def _p_reduce_sum(self, eqn, ins):
        (a,) = ins
        n = self._reduce_count(eqn, a)
        full = 1
        for ax in eqn.params["axes"]:
            full *= a.shape[ax]
        if n != full:  # one-hot axes: elements off the hot slot are 0
            lo = min(0, a.lo) * n
            hi = max(0, a.hi) * n
        else:
            lo, hi = a.lo * n, a.hi * n
        return self._arith_result(eqn, lo, hi, a.exact)

    def _p_cumsum(self, eqn, ins):
        (a,) = ins
        n = a.shape[eqn.params["axis"]]
        return self._arith_result(eqn, min(a.lo, a.lo * n),
                                  max(a.hi, a.hi * n), a.exact)

    def _p_cumprod(self, eqn, ins):
        (a,) = ins
        n = a.shape[eqn.params["axis"]]
        vals = [a.lo ** n, a.hi ** n, a.lo, a.hi] \
            + ([0] if a.lo <= 0 <= a.hi else [])
        return self._arith_result(eqn, min(vals), max(vals), a.exact)

    def _p_reduce_and(self, eqn, ins):
        return self._mk(eqn, 0, 1)

    def _p_reduce_or(self, eqn, ins):
        return self._mk(eqn, 0, 1)

    def _p_argmax(self, eqn, ins):
        dtype, shape = self._out(eqn)
        (a,) = ins
        size = 1
        for ax in eqn.params["axes"]:
            size *= a.shape[ax]
        return AbsVal(dtype, shape, 0, max(size - 1, 0))

    _p_argmin = _p_argmax

    def _p_dot_general(self, eqn, ins):
        a, b = ins
        ((lc, rc), _) = eqn.params["dimension_numbers"]
        k = 1
        for ax in lc:
            k *= a.shape[ax]
        prods = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
        lo, hi = min(prods) * k, max(prods) * k
        # operand exactness: each input must already be exact in ITS
        # dtype (checked where it was produced); the accumulation is
        # checked against the OUTPUT dtype here
        return self._arith_result(eqn, lo, hi, a.exact and b.exact)

    # -- scatter ---------------------------------------------------------------

    def _p_scatter(self, eqn, ins):
        op, idx, upd = ins
        dtype, shape = self._out(eqn)
        return AbsVal(dtype, shape, min(op.lo, upd.lo),
                      max(op.hi, upd.hi), exact=op.exact and upd.exact)

    def _p_scatter_add(self, eqn, ins):
        op, idx, upd = ins
        # assumes unique scatter indices (every kernel use is
        # .at[const].add or put_along_axis with distinct rows)
        return self._arith_result(eqn, op.lo + min(upd.lo, 0),
                                  op.hi + max(upd.hi, 0),
                                  op.exact and upd.exact)

    # -- control flow ----------------------------------------------------------

    def _scan(self, eqn, ins):
        p = eqn.params
        sub = p["jaxpr"]
        if not hasattr(sub, "consts"):
            sub = jax.core.ClosedJaxpr(sub, ())
        nc, nk = p["num_consts"], p["num_carry"]
        consts = ins[:nc]
        carry = list(ins[nc:nc + nk])
        xs = []
        for x in ins[nc + nk:]:
            xs.append(AbsVal(x.dtype, x.shape[1:], x.lo, x.hi,
                             exact=x.exact))
        carry, ys = self._loop_fixpoint(eqn, sub, consts, carry, xs)
        outs = list(carry)
        length = p["length"]
        for y in ys:
            outs.append(AbsVal(y.dtype, (length,) + y.shape, y.lo, y.hi,
                               exact=y.exact))
        return outs

    def _while(self, eqn, ins):
        p = eqn.params
        body = p["body_jaxpr"]
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        cond_consts = ins[:cn]
        body_consts = ins[cn:cn + bn]
        carry = list(ins[cn + bn:])
        carry, _ = self._loop_fixpoint(eqn, body, body_consts, carry, [])
        # the cond body's ops obey the same rules — checked AT THE
        # STABILIZED carry bounds (which include the initial ones), so a
        # condition that overflows on a late iteration is still caught
        self.run(p["cond_jaxpr"], cond_consts + carry)
        return carry

    def _cond(self, eqn, ins):
        branches = eqn.params["branches"]
        pred, ops = ins[0], ins[1:]
        # Pallas kernels pass VMEM refs into cond branches (pl.when):
        # run every branch from the same entry cell state and join the
        # exit states. A branch that never writes a cell contributes
        # BOTTOM, which joins as identity — i.e. the analysis assumes a
        # cell read after the cond was initialized by SOME branch or an
        # earlier grid pass (the when(step==0) init idiom); a kernel
        # that truly reads never-written scratch is its own bug.
        cells = [o for o in ops if isinstance(o, _RefCell)]
        snap = [c.val for c in cells]
        exits = [None] * len(cells)
        outs = None
        for br in branches:
            for c, v in zip(cells, snap):
                c.val = v
            res = self.run(br, list(ops))
            for i, c in enumerate(cells):
                if exits[i] is None:
                    exits[i] = c.val
                elif c.val is not None:
                    exits[i] = _join(exits[i], c.val)
            outs = res if outs is None else [
                _join(a, b) for a, b in zip(outs, res)]
        for c, v in zip(cells, exits):
            c.val = v
        return outs

    # -- pallas kernels --------------------------------------------------------

    def _p_program_id(self, eqn, ins):
        axis = eqn.params.get("axis", 0)
        hi = (1 << 31) - 1
        if self._grids and axis < len(self._grids[-1]):
            g = self._grids[-1][axis]
            if isinstance(g, int):
                hi = max(g - 1, 0)
        return self._mk(eqn, 0, hi)

    def _p_num_programs(self, eqn, ins):
        axis = eqn.params.get("axis", 0)
        if self._grids and axis < len(self._grids[-1]) \
                and isinstance(self._grids[-1][axis], int):
            g = self._grids[-1][axis]
            return self._mk(eqn, g, g)
        return self._mk(eqn, 1, (1 << 31) - 1)

    def _p_get(self, eqn, ins):
        if not isinstance(ins[0], _RefCell):
            return self._fallback(eqn, ins)
        dtype, shape = self._out(eqn)
        return ins[0].read(dtype, shape)

    def _p_swap(self, eqn, ins):
        if not isinstance(ins[0], _RefCell):
            return self._fallback(eqn, ins)
        cell, val = ins[0], ins[1]
        dtype, shape = self._out(eqn)
        old = cell.read(dtype, shape)
        # a slice whose element count equals the ref's covers the whole
        # ref (slice extents can never exceed an axis), so the write is
        # strong; anything smaller joins with the region it left intact
        numel = 1
        for d in shape:
            numel *= d
        ref_numel = 1
        for d in cell.shape:
            ref_numel *= d
        cell.write(val, full=(numel == ref_numel))
        return old

    def _p_addupdate(self, eqn, ins):
        if not isinstance(ins[0], _RefCell):
            return self._fallback(eqn, ins)
        cell, val = ins[0], ins[1]
        old = cell.read(cell.dtype, cell.shape)
        acc = AbsVal(cell.dtype, cell.shape, old.lo + val.lo,
                     old.hi + val.hi, exact=old.exact and val.exact)
        self._check_dtype(eqn, acc)
        d = np.dtype(cell.dtype)
        if d.kind in "uib":
            dlo, dhi = _dtype_range(d)
            if acc.hi > dhi or acc.lo < dlo:
                self._flag(eqn, f"{d.name} range exceeded in ref "
                                f"accumulate: [{acc.lo}, {acc.hi}]")
        else:
            exact_max = _FLOAT_EXACT_MAX.get(d.name)
            if exact_max is not None and \
                    max(abs(acc.lo), abs(acc.hi)) > exact_max:
                self._flag(eqn, f"{d.name} exactness lost in ref "
                                f"accumulate: |result| can reach "
                                f"{max(abs(acc.lo), abs(acc.hi))}")
        cell.write(acc, full=False)
        return []

    def _p_pallas_call(self, eqn, ins):
        """Interpret the kernel jaxpr (it IS a jaxpr) under the same
        interval rules, with one _RefCell per input/output/scratch ref
        and the grid modeled as a join-until-stable fixpoint — VMEM
        scratch persists across grid steps, so cells carry over exactly
        like scan carries. Outputs take their cells' stabilized bounds.
        """
        p = eqn.params
        sub = p.get("jaxpr")
        gm = p.get("grid_mapping")
        if sub is None or gm is None or \
                getattr(gm, "num_index_operands", 0):
            return self._fallback(eqn, ins)
        if not hasattr(sub, "consts"):
            sub = jax.core.ClosedJaxpr(sub, ())
        n_in = gm.num_inputs
        grid = tuple(gm.grid or ())
        invars = sub.jaxpr.invars
        ops_in = ins[len(ins) - n_in:] if n_in else []
        cells = []
        for i, var in enumerate(invars):
            inner = getattr(var.aval, "inner_aval", var.aval)
            cell = _RefCell(inner.dtype, inner.shape)
            if i < n_in:
                v = ops_in[i]
                cell.val = AbsVal(inner.dtype, inner.shape, v.lo, v.hi,
                                  exact=v.exact)
            cells.append(cell)
        prev_check = self._check
        self._grids.append(grid)
        try:
            for _ in range(_MAX_FIXPOINT_ITERS):
                self._check = False
                before = [c.val for c in cells]
                self.run(sub, list(cells))
                stable = True
                for c, b in zip(cells, before):
                    if c.val is None:
                        continue
                    if b is None or not _stable(b, c.val):
                        stable = False
                        c.val = c.val if b is None else _join(b, c.val)
                if stable:
                    break
            else:
                self._check = prev_check
                self._flag(eqn, "pallas grid fixpoint: ref bounds do "
                                "not stabilize after "
                                f"{_MAX_FIXPOINT_ITERS} widening "
                                "iterations (unbounded accumulation "
                                "across grid steps)")
                for c in cells:
                    lo, hi = _dtype_range(c.dtype)
                    c.val = AbsVal(c.dtype, c.shape, lo, hi,
                                   exact=c.dtype.kind != "f")
            self._check = prev_check
            self.run(sub, list(cells))
        finally:
            self._grids.pop()
            self._check = prev_check
        outs = []
        for i in range(len(eqn.outvars)):
            dtype, shape = self._out(eqn, i)
            outs.append(cells[n_in + i].read(dtype, shape))
        return outs

    def _loop_fixpoint(self, eqn, body, consts, carry, xs):
        """Interpret a loop body until the carry intervals stop growing
        (violations are only collected on the final, stable pass)."""
        prev_check = self._check
        ys = []
        for it in range(_MAX_FIXPOINT_ITERS):
            self._check = False
            outs = self.run(body, list(consts) + list(carry) + list(xs))
            new_carry = outs[:len(carry)]
            ys = outs[len(carry):]
            if all(_stable(c, n) for c, n in zip(carry, new_carry)):
                break
            carry = [_join(c, n) for c, n in zip(carry, new_carry)]
        else:
            self._check = prev_check
            self._flag(eqn, "loop carry bounds do not stabilize after "
                            f"{_MAX_FIXPOINT_ITERS} widening iterations "
                            "(a carried value's magnitude grows every "
                            "step — unbounded accumulation)")
            # widen to dtype range for the reporting pass
            carry = [AbsVal(c.dtype, c.shape, *_dtype_range(c.dtype),
                            exact=np.dtype(c.dtype).kind != "f")
                     for c in carry]
        self._check = prev_check
        outs = self.run(body, list(consts) + list(carry) + list(xs))
        return outs[:len(carry)], outs[len(carry):]


def check_fn(name, fn, args, out_bounds=None, strict=True):
    """Trace `fn` at the declared argument bounds and interval-check the
    whole jaxpr. `args` is a pytree of Bound / concrete numpy arrays
    (concrete values get exact intervals — constant tables). Returns a
    list of Violations (empty = proven clean at these shapes).
    `out_bounds`: optional list of (lo, hi) per flattened output, the
    kernel's declared POSTcondition."""
    flat, treedef = jax.tree_util.tree_flatten(args)
    specs = []
    in_vals = []
    for leaf in flat:
        if isinstance(leaf, Bound):
            specs.append(leaf.spec())
            in_vals.append(leaf.absval())
        else:
            arr = np.asarray(leaf)
            specs.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
            in_vals.append(from_concrete(arr))
    spec_tree = jax.tree_util.tree_unflatten(treedef, specs)
    closed = jax.make_jaxpr(
        lambda *a: fn(*a))(*spec_tree)
    interp = Interpreter(name, strict=strict)
    outs = interp.run(closed, in_vals)
    if out_bounds is not None:
        # fail closed: a postcondition list that doesn't cover every
        # output would silently leave the extras unchecked
        assert len(out_bounds) == len(outs), \
            (name, len(out_bounds), len(outs))
        for i, ((lo, hi), v) in enumerate(zip(out_bounds, outs)):
            if v.lo < lo or v.hi > hi:
                interp.violations.append(Violation(
                    name, "output",
                    f"output {i} bound [{v.lo}, {v.hi}] exceeds the "
                    f"declared contract [{lo}, {hi}]"))
    return interp.violations


def check_contracts(specs=None):
    """Evaluate field_jax.CARRY_CONTRACTS — the promoted zero-carry /
    exactness side conditions — against the actual field constants.
    Returns a list of Violations (empty = every contract holds)."""
    from ..backend import field_jax as FJ

    if specs is None:
        specs = (FJ.FR, FJ.FQ)
    out = []
    for spec in specs:
        for c in FJ.CARRY_CONTRACTS:
            try:
                ok = bool(c["holds"](spec))
            except Exception as e:  # pragma: no cover - malformed contract
                ok = False
                out.append(Violation(f"contract/{c['name']}", spec.name,
                                     f"contract raised: {e!r}"))
                continue
            if not ok:
                out.append(Violation(
                    f"contract/{c['name']}", spec.name,
                    f"DOES NOT HOLD for {spec.name}: {c['claim']}"))
    return out
