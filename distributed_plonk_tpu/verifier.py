"""TurboPlonk verifier (host-side, pairing-based).

Plays the role of the stock jf-plonk verifier the reference checks its proofs
against (/root/reference/src/dispatcher2.rs:1290-1293). Challenges are
re-derived through the same byte-exact transcript as the prover; the
linearization commitment D is reconstructed homomorphically from the vk, and
two KZG openings (zeta and omega*zeta) are checked in one multi-pairing.

The expected evaluation of the linearization polynomial at zeta is derived
from the quotient identity:
    lin(zeta) = alpha^2 L1(zeta) - PI(zeta)
              + alpha * perm_next_eval * (w4 + gamma)
                * prod_{i<4} (w_i + beta sigma_i(zeta) + gamma)
"""

import random

from .constants import R_MOD
from .fields import fr_inv, batch_inverse
from . import curve as C
from . import poly as P
from .circuit import (
    NUM_WIRE_TYPES,
    Q_LC,
    Q_MUL,
    Q_HASH,
    Q_O,
    Q_C,
    Q_ECC,
)
from .transcript import StandardTranscript


def _replay_challenges(vk, pub_input, proof):
    t = StandardTranscript()
    t.append_vk_and_pub_input(vk, pub_input)
    t.append_commitments(b"witness_poly_comms", proof.wires_poly_comms)
    beta = t.get_and_append_challenge(b"beta")
    gamma = t.get_and_append_challenge(b"gamma")
    t.append_commitment(b"perm_poly_comms", proof.prod_perm_poly_comm)
    alpha = t.get_and_append_challenge(b"alpha")
    t.append_commitments(b"quot_poly_comms", proof.split_quot_poly_comms)
    zeta = t.get_and_append_challenge(b"zeta")
    t.append_proof_evaluations(
        proof.wires_evals, proof.wire_sigma_evals, proof.perm_next_eval)
    v = t.get_and_append_challenge(b"v")
    return beta, gamma, alpha, zeta, v


def _g1_in_subgroup(p):
    """On-curve + order-r check (G1 has cofactor > 1; reject small-subgroup
    points, as jf-plonk's deserialization-time validation does)."""
    if p is None:
        return True
    if not C.g1_is_on_curve(p):
        return False
    acc = C.g1_to_jac(p)
    t = (1, 1, 0)
    k = R_MOD
    while k > 0:  # unreduced scalar mul by r
        if k & 1:
            t = C.g1_jac_add(t, acc)
        acc = C.g1_jac_double(acc)
        k >>= 1
    return t[2] == 0


def _validate_proof_shape(proof):
    if len(proof.wires_poly_comms) != NUM_WIRE_TYPES:
        return False
    if len(proof.split_quot_poly_comms) != NUM_WIRE_TYPES:
        return False
    if len(proof.wires_evals) != NUM_WIRE_TYPES:
        return False
    if len(proof.wire_sigma_evals) != NUM_WIRE_TYPES - 1:
        return False
    points = (proof.wires_poly_comms + proof.split_quot_poly_comms
              + [proof.prod_perm_poly_comm, proof.opening_proof,
                 proof.shifted_opening_proof])
    if not all(_g1_in_subgroup(p) for p in points):
        return False
    scalars = list(proof.wires_evals) + list(proof.wire_sigma_evals) + [proof.perm_next_eval]
    return all(isinstance(s, int) and 0 <= s < R_MOD for s in scalars)


def opening_terms(vk, pub_input, proof, u, domain=None):
    """The verifier's final pairing equation, held open as MSM terms.

    Returns (lhs_points, lhs_scalars, rhs_points, rhs_scalars) such that
    the proof verifies iff

        e(MSM(lhs), g2) * e(-MSM(rhs), tau_g2) == 1

    with `u` the opening-fold challenge (verify() draws it from its rng;
    verify_aggregate derives per-member u_j from the aggregation
    transcript). Returns None when the proof fails any of the structural
    validations (malformed shape, non-subgroup point, bad public input,
    zeta landing in the domain) — callers must treat None as REJECT.
    Keeping the terms un-evaluated is what makes batch aggregation a
    one-liner: scale every member's scalars by r_j, concatenate, and the
    N-proof check is still two MSMs and ONE 2-pair pairing_check.
    """
    n = vk.domain_size
    domain = domain or P.Domain(n)

    if not _validate_proof_shape(proof):
        return None
    # Reject length mismatches: extra "public inputs" would land on non-IO
    # rows via L_i(zeta) and let a prover bind arbitrary claimed values.
    if len(pub_input) != vk.num_inputs:
        return None
    if not all(isinstance(x, int) and 0 <= x < R_MOD for x in pub_input):
        return None

    beta, gamma, alpha, zeta, vch = _replay_challenges(vk, pub_input, proof)

    vanish_eval = (pow(zeta, n, R_MOD) - 1) % R_MOD
    if vanish_eval == 0:
        return None  # zeta landed in the domain; reject (prob ~ n/r)
    zeta_minus_1_inv = fr_inv((zeta - 1) % R_MOD)
    n_inv = fr_inv(n % R_MOD)
    lagrange_1_eval = vanish_eval * n_inv % R_MOD * zeta_minus_1_inv % R_MOD

    # PI(zeta) = sum_i pub_i * L_i(zeta), L_i(zeta) = w^i/n * Z_H(zeta)/(zeta-w^i)
    w_pows = []
    w_pow = 1
    for _ in pub_input:
        w_pows.append(w_pow)
        w_pow = w_pow * domain.group_gen % R_MOD
    denom_invs = batch_inverse([(zeta - wp) % R_MOD for wp in w_pows], R_MOD)
    pi_eval = 0
    for x, wp, dinv in zip(pub_input, w_pows, denom_invs):
        li = wp * n_inv % R_MOD * vanish_eval % R_MOD * dinv % R_MOD
        pi_eval = (pi_eval + x * li) % R_MOD

    a, b, c, d, e = proof.wires_evals
    ab = a * b % R_MOD
    cd = c * d % R_MOD

    # expected lin(zeta) from the quotient identity
    sigma_prod = 1
    for w_eval, s_eval in zip(proof.wires_evals[:NUM_WIRE_TYPES - 1],
                              proof.wire_sigma_evals):
        sigma_prod = sigma_prod * ((w_eval + beta * s_eval + gamma) % R_MOD) % R_MOD
    lin_eval = (
        alpha * alpha % R_MOD * lagrange_1_eval
        - pi_eval
        + alpha * proof.perm_next_eval % R_MOD * ((e + gamma) % R_MOD) % R_MOD * sigma_prod
    ) % R_MOD

    # homomorphic linearization commitment D
    scalars = []
    points = []
    gate_terms = [
        (Q_LC, a), (Q_LC + 1, b), (Q_LC + 2, c), (Q_LC + 3, d),
        (Q_MUL, ab), (Q_MUL + 1, cd),
        (Q_HASH, pow(a, 5, R_MOD)), (Q_HASH + 1, pow(b, 5, R_MOD)),
        (Q_HASH + 2, pow(c, 5, R_MOD)), (Q_HASH + 3, pow(d, 5, R_MOD)),
        (Q_O, (-e) % R_MOD), (Q_C, 1),
        (Q_ECC, ab * cd % R_MOD * e % R_MOD),
    ]
    for sel_idx, coeff in gate_terms:
        scalars.append(coeff)
        points.append(vk.selector_comms[sel_idx])

    coeff_z = alpha
    for w_eval, ki in zip(proof.wires_evals, vk.k):
        coeff_z = coeff_z * ((w_eval + beta * ki % R_MOD * zeta + gamma) % R_MOD) % R_MOD
    coeff_z = (coeff_z + alpha * alpha % R_MOD * lagrange_1_eval) % R_MOD
    scalars.append(coeff_z)
    points.append(proof.prod_perm_poly_comm)

    coeff_sigma = alpha * beta % R_MOD * proof.perm_next_eval % R_MOD * sigma_prod % R_MOD
    scalars.append((-coeff_sigma) % R_MOD)
    points.append(vk.sigma_comms[NUM_WIRE_TYPES - 1])

    zeta_np2 = (vanish_eval + 1) * zeta % R_MOD * zeta % R_MOD
    coeff = (-vanish_eval) % R_MOD
    for t_comm in proof.split_quot_poly_comms:
        scalars.append(coeff)
        points.append(t_comm)
        coeff = coeff * zeta_np2 % R_MOD

    # batch commitment and batch evaluation (powers of v)
    batch_eval = lin_eval
    vpow = vch
    for comm, ev in zip(proof.wires_poly_comms, proof.wires_evals):
        scalars.append(vpow)
        points.append(comm)
        batch_eval = (batch_eval + vpow * ev) % R_MOD
        vpow = vpow * vch % R_MOD
    for comm, ev in zip(vk.sigma_comms[:NUM_WIRE_TYPES - 1], proof.wire_sigma_evals):
        scalars.append(vpow)
        points.append(comm)
        batch_eval = (batch_eval + vpow * ev) % R_MOD
        vpow = vpow * vch % R_MOD

    # fold the shifted opening in with the challenge u:
    #   e(C_batch - [batch_eval] + zeta W1
    #     + u (z_comm - [perm_next_eval] + omega zeta W2), g2)
    #   == e(W1 + u W2, tau g2)
    omega_zeta = domain.group_gen * zeta % R_MOD

    scalars.append((-batch_eval - u * proof.perm_next_eval) % R_MOD)
    points.append(vk.g1)
    scalars.append(zeta)
    points.append(proof.opening_proof)
    scalars.append(u)
    points.append(proof.prod_perm_poly_comm)
    scalars.append(u * omega_zeta % R_MOD)
    points.append(proof.shifted_opening_proof)

    rhs_points = [proof.opening_proof, proof.shifted_opening_proof]
    rhs_scalars = [1, u]
    return points, scalars, rhs_points, rhs_scalars


def verify(vk, pub_input, proof, domain=None, rng=None):
    rng = rng or random.Random()
    u = rng.randrange(1, R_MOD)
    terms = opening_terms(vk, pub_input, proof, u, domain=domain)
    if terms is None:
        return False
    points, scalars, rhs_points, rhs_scalars = terms
    lhs = C.g1_msm(points, scalars)
    rhs_w = C.g1_msm(rhs_points, rhs_scalars)
    return C.pairing_check([
        (lhs, vk.g2),
        (C.g1_neg(rhs_w), vk.tau_g2),
    ])


def verify_aggregate(members, domains=None):
    """Batched verification: N proofs, ONE 2-pair pairing check.

    members: [(vk, pub_input, proof, u, r)] where (u, r) are the
    per-member opening-fold and linear-combination challenges (derived by
    aggregate.derive_challenges from the aggregation transcript — never
    chosen by the prover). Folds every member's pairing equation by the
    random r_j:

        e(sum_j r_j lhs_j, g2) * e(-sum_j r_j (W1_j + u_j W2_j), tau_g2)

    which is 1 iff (w.h.p. over the r_j) EVERY constituent equation
    holds — a single member failing makes the fold nonzero except with
    probability ~1/r. All members must share the same SRS tail (g2,
    tau_g2): distinct-tau members would pair against different tau_g2
    and cannot be folded, so that is a structural REJECT, not an assert.
    """
    if not members:
        return False
    g2, tau_g2 = members[0][0].g2, members[0][0].tau_g2
    lhs_points, lhs_scalars = [], []
    rhs_points, rhs_scalars = [], []
    for vk, pub_input, proof, u, r in members:
        if vk.g2 != g2 or vk.tau_g2 != tau_g2:
            return False
        domain = (domains or {}).get(vk.domain_size)
        terms = opening_terms(vk, pub_input, proof, u, domain=domain)
        if terms is None:
            return False
        points, scalars, rpoints, rscalars = terms
        lhs_points += points
        lhs_scalars += [r * s % R_MOD for s in scalars]
        rhs_points += rpoints
        rhs_scalars += [r * s % R_MOD for s in rscalars]
    lhs = C.g1_msm(lhs_points, lhs_scalars)
    rhs_w = C.g1_msm(rhs_points, rhs_scalars)
    return C.pairing_check([
        (lhs, g2),
        (C.g1_neg(rhs_w), tau_g2),
    ])
