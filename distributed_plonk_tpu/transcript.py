"""Fiat-Shamir transcript: Keccak-f[1600] + STROBE-128 + merlin clone.

The reference drives Fiat-Shamir through `merlin::Transcript` 3.0 wrapped in
`FakeStandardTranscript` (/root/reference/src/dispatcher2.rs:44-154), which
byte-for-byte reproduces jf-plonk's `StandardTranscript`. For proofs to be
byte-identical with the reference, this module re-implements that stack from
the public specifications:

  * Keccak-f[1600] permutation (FIPS 202) - self-tested against hashlib's
    SHA3 by tests/test_transcript.py.
  * STROBE-128 lite (exactly the subset merlin implements: AD / META-AD /
    PRF over keccak-f[1600], rate 166).
  * merlin's framing: protocol label "Merlin v1.0", dom-sep on new(),
    append_message/challenge_bytes with u32-LE length meta-AD.
  * jf-plonk's StandardTranscript message schedule (labels and arkworks
    CanonicalSerialize byte layouts).
"""

MASK64 = (1 << 64) - 1

_KECCAK_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rho rotation offsets, indexed [x + 5*y]
_KECCAK_ROT = [
    0, 1, 62, 28, 27,
    36, 44, 6, 55, 20,
    3, 10, 43, 25, 39,
    41, 45, 15, 21, 8,
    18, 2, 61, 56, 14,
]


def _rol64(v, n):
    n %= 64
    return ((v << n) | (v >> (64 - n))) & MASK64


def keccak_f1600(lanes):
    """In-place-style permutation over 25 64-bit lanes (A[x + 5y])."""
    A = list(lanes)
    for rnd in range(24):
        # theta
        C = [A[x] ^ A[x + 5] ^ A[x + 10] ^ A[x + 15] ^ A[x + 20] for x in range(5)]
        D = [C[(x - 1) % 5] ^ _rol64(C[(x + 1) % 5], 1) for x in range(5)]
        A = [A[i] ^ D[i % 5] for i in range(25)]
        # rho + pi: B[y + 5*((2x+3y)%5)] = rol(A[x + 5y], rot[x + 5y])
        B = [0] * 25
        for x in range(5):
            for y in range(5):
                B[y + 5 * ((2 * x + 3 * y) % 5)] = _rol64(A[x + 5 * y], _KECCAK_ROT[x + 5 * y])
        # chi
        A = [B[x + 5 * y] ^ ((~B[(x + 1) % 5 + 5 * y] & MASK64) & B[(x + 2) % 5 + 5 * y])
             for y in range(5) for x in range(5)]
        # iota
        A[0] ^= _KECCAK_RC[rnd]
    return A


def keccak_f1600_bytes(state):
    """Permute a 200-byte state (little-endian lanes)."""
    lanes = [int.from_bytes(state[8 * i:8 * i + 8], "little") for i in range(25)]
    lanes = keccak_f1600(lanes)
    out = bytearray(200)
    for i, lane in enumerate(lanes):
        out[8 * i:8 * i + 8] = lane.to_bytes(8, "little")
    return out


# --- STROBE-128 (the merlin-internal subset) ---------------------------------

STROBE_R = 166

FLAG_I = 1
FLAG_A = 1 << 1
FLAG_C = 1 << 2
FLAG_T = 1 << 3
FLAG_M = 1 << 4
FLAG_K = 1 << 5


class Strobe128:
    def __init__(self, protocol_label):
        st = bytearray(200)
        st[0:6] = bytes([1, STROBE_R + 2, 1, 0, 1, 96])
        st[6:18] = b"STROBEv1.0.2"
        self.state = keccak_f1600_bytes(st)
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol_label, False)

    def _run_f(self):
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[STROBE_R + 1] ^= 0x80
        self.state = keccak_f1600_bytes(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data):
        for byte in data:
            self.state[self.pos] ^= byte
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()

    def _squeeze(self, n):
        out = bytearray(n)
        for i in range(n):
            out[i] = self.state[self.pos]
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags, more):
        if more:
            assert flags == self.cur_flags, "flag mismatch on continued op"
            return
        assert flags & FLAG_T == 0, "transport flags unsupported"
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        force_f = flags & (FLAG_C | FLAG_K) != 0
        if force_f and self.pos != 0:
            self._run_f()

    def meta_ad(self, data, more):
        self._begin_op(FLAG_M | FLAG_A, more)
        self._absorb(data)

    def ad(self, data, more):
        self._begin_op(FLAG_A, more)
        self._absorb(data)

    def prf(self, n, more=False):
        self._begin_op(FLAG_I | FLAG_A | FLAG_C, more)
        return self._squeeze(n)


# --- merlin Transcript -------------------------------------------------------

MERLIN_PROTOCOL_LABEL = b"Merlin v1.0"


class MerlinTranscript:
    def __init__(self, label):
        self.strobe = Strobe128(MERLIN_PROTOCOL_LABEL)
        self.append_message(b"dom-sep", label)

    def append_message(self, label, message):
        data_len = len(message).to_bytes(4, "little")
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(data_len, True)
        self.strobe.ad(message, False)

    def challenge_bytes(self, label, n):
        data_len = n.to_bytes(4, "little")
        self.strobe.meta_ad(label, False)
        self.strobe.meta_ad(data_len, True)
        return self.strobe.prf(n)


# --- arkworks-style serialization (for transcript + proofs) ------------------

from .constants import R_MOD, Q_MOD  # noqa: E402


def fr_to_bytes(x):
    """ark CanonicalSerialize of Fr: 32 bytes LE of the canonical integer."""
    return (x % R_MOD).to_bytes(32, "little")


def fr_from_le_bytes_mod_order(b):
    return int.from_bytes(b, "little") % R_MOD


def g1_to_bytes_compressed(p):
    """ark 0.3 compressed G1: 48 bytes LE x, flags in the top byte.

    bit 6 of byte[47]: infinity; bit 7: y is the lexicographically
    larger root ("positive", i.e. y > q - y).
    """
    if p is None:
        b = bytearray(48)
        b[47] |= 1 << 6
        return bytes(b)
    x, y = p
    b = bytearray(x.to_bytes(48, "little"))
    if y > Q_MOD - y:
        b[47] |= 1 << 7
    return bytes(b)


def g2_to_bytes_compressed(p):
    """ark 0.3 compressed G2: 96 bytes (c0 then c1 of x, LE), flags in top byte."""
    if p is None:
        b = bytearray(96)
        b[95] |= 1 << 6
        return bytes(b)
    (x0, x1), (y0, y1) = p
    b = bytearray(x0.to_bytes(48, "little") + x1.to_bytes(48, "little"))
    # y sign: lexicographic comparison (c1, then c0) against its negation
    ny0, ny1 = (Q_MOD - y0) % Q_MOD, (Q_MOD - y1) % Q_MOD
    if (y1, y0) > (ny1, ny0):
        b[95] |= 1 << 7
    return bytes(b)


# --- jf-plonk StandardTranscript schedule ------------------------------------

class StandardTranscript:
    """Byte-compatible clone of jf-plonk's StandardTranscript.

    Message schedule mirrors FakeStandardTranscript
    (/root/reference/src/dispatcher2.rs:44-154).
    """

    def __init__(self):
        self.t = MerlinTranscript(b"PlonkProof")

    def append_vk_and_pub_input(self, vk, pub_input):
        self.t.append_message(b"field size in bits", (255).to_bytes(8, "little"))
        self.t.append_message(b"domain size", vk.domain_size.to_bytes(8, "little"))
        self.t.append_message(b"input size", vk.num_inputs.to_bytes(8, "little"))
        for ki in vk.k:
            self.t.append_message(b"wire subsets separators", fr_to_bytes(ki))
        for comm in vk.selector_comms:
            self.t.append_message(b"selector commitments", g1_to_bytes_compressed(comm))
        for comm in vk.sigma_comms:
            self.t.append_message(b"sigma commitments", g1_to_bytes_compressed(comm))
        for x in pub_input:
            self.t.append_message(b"public input", fr_to_bytes(x))

    def append_commitment(self, label, comm):
        self.t.append_message(label, g1_to_bytes_compressed(comm))

    def append_commitments(self, label, comms):
        for c in comms:
            self.append_commitment(label, c)

    def append_proof_evaluations(self, wires_evals, wire_sigma_evals, perm_next_eval):
        for w in wires_evals:
            self.t.append_message(b"wire_evals", fr_to_bytes(w))
        for s in wire_sigma_evals:
            self.t.append_message(b"wire_sigma_evals", fr_to_bytes(s))
        self.t.append_message(b"perm_next_eval", fr_to_bytes(perm_next_eval))

    def get_and_append_challenge(self, label):
        buf = self.t.challenge_bytes(label, 64)
        challenge = fr_from_le_bytes_mod_order(buf)
        self.t.append_message(label, fr_to_bytes(challenge))
        return challenge
