"""Result-integrity plane: detect and ATTRIBUTE silent wrong answers.

The fault-tolerance layers so far (breaker/replan PR 6, journal PR 7,
supervision/membership PR 12) all assume a worker either answers
correctly or fails loudly. The dangerous production failure in an
accelerator fleet is the quiet one — a flipped limb from a bad chip,
stale device state, a buggy kernel path — which returns a WELL-FORMED
wrong answer that sails under every CRC/SHA layer (those protect bytes
in flight and at rest, not the computation that produced them). This
module holds the math and policy for catching that class at the phase
boundary, with enough structure to name the lying worker:

  Sharded FFT / iNTT (Schwartz-Zippel): both directions of the 4-step
    transform are linear maps whose output power sum at a random point t
    has a CLOSED FORM over the input. With w the n-th root of unity,
    g the coset generator, u and s per mode
        forward:  u = t,       s = w,      pre_i = x_i * g^i,  post = 1
        inverse:  u = t / g,   s = w^-1,   pre_j = x_j,        post = 1/n
    and z_i = u * s^i, the served output y must satisfy
        sum_v y_v t^v  ==  post * sum_i pre_i * (u^n - 1) / (z_i - 1)
    (z_i == 1 contributes pre_i * n). A wrong output differs as a
    polynomial of degree < n, so it passes at a random t with
    probability <= (n-1)/|Fr| ~ 2^-230 — soundness error is negligible.
    ATTRIBUTION uses the same identity restricted to one worker's output
    panel: worker i owns flat indices {k1 + r*k2 : k1 in [cs_i, ce_i)},
    and the panel's true power sum is
        post * sum_j pre_j * geo(z_j; cs, ce) * geo(z_j^r; c)
    with geo the finite geometric sums — O(n) host muls per panel, paid
    only on a failed total. The mismatched panel names the liar.

  Distributed MSM (duplicate execution + group law): G1 partials are
    checked on-curve and (optionally) in the order-r subgroup before the
    fold — a flipped coordinate limb almost never lands back on the
    curve. A wrong-but-on-curve partial (stale bases: the PR 12 bug
    class) is caught by probabilistic duplicate execution: with rate
    DPT_INTEGRITY_MSM_DUP a range is recomputed by a second worker on
    FRESHLY pushed bases and the partials compared; a mismatch is
    attributed by a third worker's vote (or the host oracle for small
    ranges) and the liar quarantined.

  Distributed round-4 evaluation (duplicate execution + host referee):
    partial Horner sums are scalars, so the host referee is always
    affordable — attribution on mismatch is exact.

Detection feeds the quarantine machinery in runtime/dispatcher.py:
the attributed worker is marked SUSPECT (runtime/health.py — sticky:
probes do NOT re-admit it), LEAVEd through the membership registry so
the supervisor replaces the process, and re-admission happens only via
a fresh JOIN that passes a known-answer challenge (Dispatcher.
run_challenge). `DPT_INTEGRITY=0` disables the whole plane — zero added
wire bytes, zero added host math, zero new counters.

Knobs (env, read by from_env):
    DPT_INTEGRITY           master switch (1)
    DPT_INTEGRITY_MSM_DUP   duplicate-execution sampling rate (0.05)
    DPT_INTEGRITY_SUBGROUP  full order-r subgroup check on partials (1;
                            on-curve is always checked)
    DPT_INTEGRITY_REFEREE_MAX  largest MSM range the host oracle will
                            referee when no third worker exists (2048)
"""

import os
import random
import threading

from .. import curve as C
from ..constants import R_MOD, FR_GENERATOR
from ..fields import batch_inverse, fr_inv, fr_root_of_unity
from ..poly import poly_eval


class IntegrityError(RuntimeError):
    """An algebraic phase check failed: the served data is wrong. The
    suspects (fleet indices) have already been quarantined by the caller
    when attribution succeeded; the phase must recompute on survivors."""

    def __init__(self, msg, suspects=()):
        super().__init__(msg)
        self.suspects = tuple(suspects)


# --- power sums --------------------------------------------------------------

# sum_v values[v] * t^v mod r — exactly dense-poly Horner evaluation
power_sum = poly_eval


def rows_power_sum(values, t, rs, re, c_dim):
    """Power sum of the stage-1 row slice [rs, re): worker i's INPUT in
    the 4-step FFT is rows j2 in [rs, re), row j2 = values[j2::c_dim]
    (flat index j1*c_dim + j2)."""
    if re <= rs:
        return 0
    n = len(values)
    r_dim = n // c_dim
    tc = pow(t, c_dim, R_MOD)
    tot = 0
    tk = pow(t, rs, R_MOD)
    for j2 in range(rs, re):
        acc = 0
        for j1 in reversed(range(r_dim)):
            acc = (acc * tc + values[j1 * c_dim + j2]) % R_MOD
        tot = (tot + acc * tk) % R_MOD
        tk = tk * t % R_MOD
    return tot


def cols_power_sum(values, t, cs, ce, r_dim):
    """Power sum of the stage-2 column slice [cs, ce): worker i's OUTPUT
    covers flat indices {k1 + r_dim*k2 : k1 in [cs, ce)}."""
    if ce <= cs:
        return 0
    c_dim = len(values) // r_dim
    tr = pow(t, r_dim, R_MOD)
    tot = 0
    tk = pow(t, cs, R_MOD)
    for k1 in range(cs, ce):
        acc = 0
        for k2 in reversed(range(c_dim)):
            acc = (acc * tr + values[k1 + r_dim * k2]) % R_MOD
        tot = (tot + acc * tk) % R_MOD
        tk = tk * t % R_MOD
    return tot


# --- transform identities ----------------------------------------------------

def _mode_walk(x, t, inverse, coset):
    """(pre, post, u, step): the per-mode reindexing that makes every
    FFT/iNTT variant the same identity (module docstring). pre is the
    weighted input vector, z_i = u * step^i."""
    n = len(x)
    w = fr_root_of_unity(n)
    g = FR_GENERATOR if coset else 1
    if not inverse:
        u = t % R_MOD
        step = w
        if coset:
            pre = []
            gp = 1
            for v in x:
                pre.append(v * gp % R_MOD)
                gp = gp * g % R_MOD
        else:
            pre = [v % R_MOD for v in x]
        post = 1
    else:
        u = t * fr_inv(g) % R_MOD if coset else t % R_MOD
        step = fr_inv(w)
        pre = [v % R_MOD for v in x]
        post = fr_inv(n % R_MOD)
    return pre, post, u, step


def _safe_batch_inverse(dens):
    """batch_inverse tolerating zeros: zero denominators (z == 1, prob
    ~ n/2^255 at a random t, but the math must not crash) come back as
    None so the caller can substitute the limit form."""
    nz = [d if d else 1 for d in dens]
    invs = batch_inverse(nz, R_MOD)
    return [inv if d else None for d, inv in zip(dens, invs)]


def expected_output_eval(x, t, inverse, coset):
    """The closed-form value sum_v y_v t^v MUST take when y is the true
    (i)(coset)FFT of x — O(n) host muls + one batch inversion."""
    n = len(x)
    pre, post, u, step = _mode_walk(x, t, inverse, coset)
    un1 = (pow(u, n, R_MOD) - 1) % R_MOD
    zs = []
    z = u
    for _ in range(n):
        zs.append(z)
        z = z * step % R_MOD
    invs = _safe_batch_inverse([(z - 1) % R_MOD for z in zs])
    tot = 0
    for p, z, inv in zip(pre, zs, invs):
        geo = n % R_MOD if inv is None else un1 * inv % R_MOD
        tot = (tot + p * geo) % R_MOD
    return tot * post % R_MOD


def expected_panel_eval(x, t, cs, ce, r_dim, c_dim, inverse, coset):
    """The closed-form power sum of the TRUE output restricted to one
    worker's column panel {k1 + r_dim*k2 : k1 in [cs, ce)} — the
    bisection probe that attributes a failed total to a panel. O(n)
    host muls; only ever run after a failed check."""
    n = len(x)
    assert r_dim * c_dim == n
    if ce <= cs:
        return 0
    pre, post, u, step = _mode_walk(x, t, inverse, coset)
    return _panel_eval(pre, post, u, step, cs, ce, r_dim, c_dim, n)


def _panel_eval(pre, post, u, step, cs, ce, r_dim, c_dim, n):
    """Core of expected_panel_eval on a pre-walked mode: three parallel
    geometric walks give z_i^cs, z_i^ce, z_i^r for z_i = u*step^i with
    O(1) muls per i; z_i^n == u^n for every i (step^n == 1)."""
    un1 = (pow(u, n, R_MOD) - 1) % R_MOD
    za = pow(u, cs, R_MOD)
    sa = pow(step, cs, R_MOD)
    zb = pow(u, ce, R_MOD)
    sb = pow(step, ce, R_MOD)
    zr = pow(u, r_dim, R_MOD)
    sr = pow(step, r_dim, R_MOD)
    zs, zcs, zce, zrs = [], [], [], []
    z = u
    for _ in range(n):
        zs.append(z)
        zcs.append(za)
        zce.append(zb)
        zrs.append(zr)
        z = z * step % R_MOD
        za = za * sa % R_MOD
        zb = zb * sb % R_MOD
        zr = zr * sr % R_MOD
    inv1 = _safe_batch_inverse([(z - 1) % R_MOD for z in zs])
    invr = _safe_batch_inverse([(zr - 1) % R_MOD for zr in zrs])
    tot = 0
    for p, zc, zE, zr, i1, ir in zip(pre, zcs, zce, zrs, inv1, invr):
        # geo_range(z; cs, ce) = (z^ce - z^cs)/(z-1), limit ce-cs at z=1
        ga = (ce - cs) % R_MOD if i1 is None else (zE - zc) * i1 % R_MOD
        # geo over k2: sum (z^r)^k2 = (z^n - 1)/(z^r - 1), limit c_dim
        gb = c_dim % R_MOD if ir is None else un1 * ir % R_MOD
        tot = (tot + p * ga % R_MOD * gb) % R_MOD
    return tot * post % R_MOD


# --- G1 partial sanity -------------------------------------------------------

def g1_on_curve(p):
    return C.g1_is_on_curve(p)


def g1_in_subgroup(p):
    """Order-r check (G1 cofactor > 1): on-curve AND [r]P == infinity.
    ~255 Jacobian double/adds of host big-int math — milliseconds per
    point, run only on the k per-MSM partials, never on the data
    plane."""
    if p is None:
        return True
    return C.g1_is_on_curve(p) and _r_mul_is_infinity(p)


def _r_mul_is_infinity(p):
    """[r]P == infinity for an on-curve affine P (the scalar-mul half of
    g1_in_subgroup, so point_sane need not re-check on-curve)."""
    acc = C.g1_to_jac(p)
    t = (1, 1, 0)
    k = R_MOD
    while k > 0:
        if k & 1:
            t = C.g1_jac_add(t, acc)
        acc = C.g1_jac_double(acc)
        k >>= 1
    return t[2] == 0


# --- policy object -----------------------------------------------------------

class FleetIntegrity:
    """Config + sampling state for the dispatcher's integrity plane.

    Thread-safety: the sampling rng is guarded by its own lock (MSM
    ranges are checked from executor threads); everything else is
    immutable after construction."""

    def __init__(self, metrics=None, rng=None, msm_dup_rate=None,
                 subgroup_check=None, referee_max=None,
                 ntt_check_rate=None):
        from .health import NullMetrics
        self.metrics = metrics or NullMetrics()
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self.msm_dup_rate = float(
            os.environ.get("DPT_INTEGRITY_MSM_DUP", "0.05")
            if msm_dup_rate is None else msm_dup_rate)
        self.subgroup_check = bool(int(
            os.environ.get("DPT_INTEGRITY_SUBGROUP", "1")
            if subgroup_check is None else subgroup_check))
        self.referee_max = int(
            os.environ.get("DPT_INTEGRITY_REFEREE_MAX", "2048")
            if referee_max is None else referee_max)
        # sampling rate for the per-offload NTT Schwartz-Zippel check:
        # unlike the sharded-FFT check (once per fft_dist) the whole-poly
        # path runs per offloaded transform, and the O(n) host big-int
        # cost adds up at production n — operators bound dispatcher CPU
        # by sampling (detection probability across a prove's dozens of
        # NTTs stays high). Default 1.0: check everything.
        self.ntt_check_rate = float(
            os.environ.get("DPT_INTEGRITY_NTT_RATE", "1.0")
            if ntt_check_rate is None else ntt_check_rate)

    @classmethod
    def from_env(cls, metrics=None):
        """None when DPT_INTEGRITY=0 — the whole plane compiles out:
        legacy wire bytes, no extra host math, no new counters."""
        if os.environ.get("DPT_INTEGRITY", "1").strip() in ("0", "off"):
            return None
        return cls(metrics=metrics)

    def draw_point(self):
        """A random Fr check point (never 0/1: t=0 checks only the
        constant term, t=1 only the plain sum)."""
        with self._lock:
            return self._rng.randrange(2, R_MOD)

    def sample_msm_dup(self):
        with self._lock:
            return self._rng.random() < self.msm_dup_rate  # analysis: ok(host-only sampling)

    def sample_ntt_check(self):
        if self.ntt_check_rate >= 1.0:
            return True
        with self._lock:
            return self._rng.random() < self.ntt_check_rate  # analysis: ok(host-only sampling)

    def point_sane(self, p):
        """On-curve (always) + subgroup (knob) for one G1 partial."""
        if not g1_on_curve(p):
            return False
        if self.subgroup_check and p is not None \
                and not _r_mul_is_infinity(p):
            return False
        return True

    # -- check implementations (detection cheap, attribution on failure) ---

    def check_transform(self, x, y, t, inverse, coset):
        """True iff y is the (i)(coset)FFT of x at random point t."""
        self.metrics.inc("integrity_checks")
        if power_sum(y, t) == expected_output_eval(x, t, inverse, coset):
            return True
        self.metrics.inc("integrity_failures")
        return False

    def attribute_fft(self, x, y, t, col_ranges, r_dim, c_dim, inverse,
                      coset, claimed=None, row_bounds=None):
        """After a failed total: name the worker(s) whose output panel
        disagrees with the closed-form per-panel expectation, plus any
        worker whose claimed input/output partials are inconsistent
        (SDC in its retained stage-1 input, or claim != served data).
        Returns a sorted fleet-index list (never empty when the total
        failed and the panels partition the output)."""
        suspects = set()
        claimed = claimed or {}
        for i, (cs, ce) in enumerate(col_ranges):
            if ce <= cs:
                continue
            got = cols_power_sum(y, t, cs, ce, r_dim)
            want = expected_panel_eval(x, t, cs, ce, r_dim, c_dim,
                                       inverse, coset)
            if got != want:
                suspects.add(i)
            b = claimed.get(i, (None, None))[1]
            if b is not None and b != got:
                # the worker's own claim disagrees with the panel it
                # served: inconsistent either way
                suspects.add(i)
        if row_bounds:
            for i, (rs, re) in row_bounds.items():
                a = claimed.get(i, (None, None))[0]
                if a is not None and \
                        a != rows_power_sum(x, t, rs, re, c_dim):
                    suspects.add(i)
        return sorted(suspects)
