"""Worker daemon: serves MSM/NTT over the native framed transport.

The analog of the reference's worker binary (/root/reference/src/worker.rs:
441-536): holds device-resident SRS state across requests (State,
worker.rs:42-59), executes kernels per RPC. Threading model: one thread per
connection, state guarded by a lock — replacing the reference's
single-thread-plus-unsafe-aliasing design (worker.rs:135 etc.) with an
actually sound one.

Also serves the cross-worker sharded 4-step FFT (the reference's signature
protocol): FFT_INIT allocates a task (worker.rs:187-233), FFT1 runs the
stage-1 row kernels (worker.rs:235-278 -> 66-94), FFT2_PREPARE pushes each
peer its column slices over direct worker<->worker connections
(worker.rs:280-345 sender, 412-438 receiver), FFT2 runs the stage-2 column
kernels and returns the result shard (worker.rs:347-381 -> 96-115). Unlike
the reference there is no second listener plane: peer exchange frames
arrive on the same port, distinguished by tag (netconfig.py documents the
single-plane choice).

Run: python -m distributed_plonk_tpu.runtime.worker <index> [config.json]
    [--backend python|jax] [--store DIR]

--store serves the given artifact store over the STORE_FETCH tag (a
replacement worker on a fresh host pulls SRS/pk/checkpoint blobs from a
peer instead of rebuilding — store/remote.py is the client side).
"""

import os
import struct
import sys
import threading
import time
from collections import OrderedDict

import numpy as np

from . import native, protocol
from .faults import FaultInjector
from .netconfig import NetworkConfig
from ..constants import R_MOD, FR_GENERATOR
from ..fields import fr_inv, fr_root_of_unity
from ..obs import log as olog
from ..obs import profiling
from ..poly import Domain, poly_eval
from ..service.metrics import Metrics
from ..trace import NULL_TRACER, Tracer, msm_flops, ntt_flops

# resident per-trace span buffers: the dispatcher fetches-and-forgets
# them via TRACE_DUMP, but a dispatcher that dies mid-prove must not
# leak its trace buffers forever — LRU cap, oldest trace dropped
_TRACE_CAP = int(os.environ.get("DPT_WORKER_TRACE_CAP", "32"))


def _make_backend(name):
    if name == "jax":
        from ..backend.jax_backend import JaxBackend
        return JaxBackend()
    from ..backend.python_backend import PythonBackend
    return PythonBackend()


class FftTask:
    """In-flight sharded FFT state (the reference's FftTask,
    /root/reference/src/worker.rs:50-54): stage-1 results for our rows,
    stage-2 input columns filled in by peer exchanges.

    Data plane is numpy limb matrices end to end (exchange panels land with
    one slice assignment); `created` supports age-based GC, fixing the
    reference's task leak on dispatcher abort (worker.rs:378)."""

    def __init__(self, inverse, coset, n, r, c, rs, re, col_ranges, me,
                 keep_raw=False):
        self.inverse = inverse
        self.coset = coset
        self.n, self.r, self.c = n, r, c
        self.rs, self.re = rs, re          # our stage-1 rows (j2 indices)
        self.col_ranges = col_ranges       # every worker's stage-2 range (k1)
        self.cs, self.ce = col_ranges[me]
        self.rows = [None] * (re - rs)     # [local j2] -> length-r row (ints)
        self.rows_mat = None               # (16, re-rs, r) panel (jax path)
        self.rows_filled = np.zeros(re - rs, dtype=bool)
        # RAW stage-1 input panels as received (first_row -> limbs): the
        # integrity plane's input-side partial is a power sum of what
        # this worker actually holds, so the dispatcher can tell "your
        # input rotted" from "your stage-2 math lied" (keyed by
        # first_row, so a retried FFT1 resend overwrites idempotently).
        # Retained only when FFT_INIT announced an armed integrity plane
        # (keep_raw) — a plane-off fleet keeps legacy panel memory.
        self.keep_raw = keep_raw
        self.raw_panels = {}
        # [16, local k1, j2] stage-2 input columns; fill_mask tracks exchange
        # completeness per (column, row) cell — a REGION mask, not a counter,
        # so a retried FFT2_PREPARE (same panels re-pushed after a dispatcher
        # reconnect) stays idempotent
        self.cols = np.zeros((16, self.ce - self.cs, c), dtype=np.uint32)
        self.fill_mask = np.zeros((self.ce - self.cs, c), dtype=bool)
        self.cols_lock = threading.Lock()
        self.created = time.monotonic()
        # FFT2 caches its reply here instead of deleting the task, so a
        # dispatcher retry (reconnect after timeout) gets the same bytes
        # back — FFT2 is idempotent like every other request; completed
        # tasks are GC'd by age at the next FFT_INIT
        self.result = None
        self.done_at = None


class WorkerState:
    def __init__(self, backend, config=None, me=0, store=None, epoch=0):
        self.backend = backend
        self.config = config
        self.me = me
        self.store = store  # optional ArtifactStore served via STORE_FETCH
        # membership-roster version this worker last adopted (0 = static
        # fleet / never joined): FFT_INIT frames planned against an older
        # epoch are rejected as stale, and ROSTER pushes advance it
        self.epoch = epoch
        # worker-side chaos: the `corrupt:at=data` plane perturbs OUR
        # computed results before framing (SDC model — runtime/faults.py);
        # None when DPT_FAULTS is unset, zero-overhead fast path
        self.faults = FaultInjector.from_env()
        self.sdc_injected = 0
        self.warm = None  # warm-rejoin stats (store/remote.warm_sync)
        # full observability registry (served counters, kernel latency
        # histograms, live gflops/MFU gauges) served over METRICS_FETCH —
        # the structured upgrade of the raw {tag: count} STATS dict,
        # which stays for wire back-compat. The structured-log ring
        # (obs/log.py) publishes its counters here too.
        self.metrics = Metrics()
        olog.set_metrics(self.metrics)
        self.started = time.monotonic()
        self.base_sets = {}  # set_id -> bases (a worker can adopt ranges)
        self.lock = threading.Lock()
        self.domains = {}
        self.fft_tasks = {}
        self.peers = {}
        self.peer_lock = threading.Lock()
        self.counters = {}
        # trace_id -> Tracer holding this worker's spans for that trace
        # (shipped back + forgotten on TRACE_DUMP; LRU-capped)
        self.traces = OrderedDict()
        # jax workers run whole FFT1/FFT2 frames as single batched device
        # launches over limb panels (no per-row dispatch, no host ints)
        if getattr(backend, "name", "") == "jax":
            from .jax_stages import StageKernels
            self.stages = StageKernels()
        else:
            self.stages = None

    def domain(self, n):
        if n not in self.domains:
            self.domains[n] = Domain(n)
        return self.domains[n]

    def count(self, tag):
        with self.lock:
            self.counters[tag] = self.counters.get(tag, 0) + 1
        # served_<tag> counter family in the structured registry: what
        # the fleet scraper aggregates into dpt_fleet_served_* series
        self.metrics.inc("served_" + protocol.tag_name(tag).lower())

    def observe_kernel(self, stage, dur_s, flops=0, data_bytes=0):
        """Fold one kernel execution into the live per-stage surfaces:
        a latency histogram plus — when the flops model applies — the
        same kernel_<stage>_gflops / mfu_<stage>_pct gauges the service
        pool derives from trace spans, so a fleet worker's device
        utilization is scrapeable without a trace being armed."""
        self.metrics.observe(f"worker_{stage}_s", dur_s)
        if flops:
            self.metrics.observe_kernels(
                [{"span": stage, "flops": flops, "dur_s": dur_s,
                  "data_bytes": data_bytes}])

    def tracer_for(self, ctx):
        """The per-trace Tracer an incoming traced frame records under
        (created on first sight of the trace id, LRU past _TRACE_CAP)."""
        tid = ctx.get("trace_id") if isinstance(ctx, dict) else None
        if not tid:
            return NULL_TRACER
        with self.lock:
            tr = self.traces.get(tid)
            if tr is None:
                tr = self.traces[tid] = Tracer(
                    trace_id=tid, proc=f"worker/{self.me}")
                while len(self.traces) > _TRACE_CAP:
                    self.traces.popitem(last=False)
            else:
                self.traces.move_to_end(tid)
            return tr

    def pop_trace(self, trace_id):
        with self.lock:
            return self.traces.pop(trace_id, None)

    def peer(self, p):
        """Lazy worker->worker connection (the reference opens peer
        connections per exchange, worker.rs:297-338; here they are cached).
        Includes the self-loop via TCP, as the reference does."""
        with self.peer_lock:
            if p not in self.peers:
                host, port = self.config.workers[p]
                conn = native.connect(host, port)
                self.peers[p] = (conn, threading.Lock())
            return self.peers[p]

    def drop_peer(self, p):
        """Forget a cached peer connection (it broke mid-exchange — the
        peer died or restarted; the next peer() dials fresh)."""
        with self.peer_lock:
            entry = self.peers.pop(p, None)
        if entry is not None:
            try:
                entry[0].close()
            except OSError:  # pragma: no cover - already dead
                pass

    def peer_call(self, p, tag, payload):
        """One request/reply to peer p, retrying ONCE on a fresh
        connection: a cached stream goes stale when the peer restarts
        (cross-host re-admission), and the exchange payload is idempotent
        at the receiver (region-mask overwrite), so a blind resend is
        safe. Raises on the second failure — the dispatcher's fleet probe
        then attributes the death correctly."""
        for attempt in (0, 1):
            pconn, plock = self.peer(p)
            with plock:
                try:
                    pconn.send(tag, payload)
                    return pconn.recv()
                except (ConnectionError, OSError):
                    self.drop_peer(p)
                    if attempt:
                        raise


def _sdc_due(state, tag):
    """True when the worker-side data-plane chaos should corrupt the
    result just computed for `tag` (see runtime/faults.py, at=data)."""
    if state.faults is None:
        return False
    if not state.faults.on_data(state.me, tag):
        return False
    with state.lock:
        state.sdc_injected += 1
    olog.emit("worker", "sdc_injected", level="warn", worker=state.me,
              tag=protocol.tag_name(tag))
    return True


# traced kernel tags that earn a per-request structured log event (the
# control/bulk tags — PING, FFT1 panels, exchanges — would only be noise)
_LOGGED_TAGS = frozenset((protocol.MSM, protocol.NTT, protocol.FFT2,
                          protocol.EVAL, protocol.FFT_INIT))

# sum_j row[j] * base^j — exactly dense-poly Horner evaluation
_horner = poly_eval


def _fft2_partials(task, point):
    """The integrity piggyback (runtime/integrity.py): (input-side,
    output-side) partial power sums at the dispatcher's random point.
    Input side walks the RAW stage-1 rows as received (flat index
    j1*c + j2 -> row j2 Horner in base t^c, scaled t^j2); output side
    walks the computed result panel (flat index k1 + r*k2 -> row k1
    Horner in base t^r, scaled t^k1). Both are computed from the SAME
    buffers the data plane serves, so an SDC in either shows up in the
    partials exactly as it does in the data. O(n/k) host muls."""
    a = 0
    tc = pow(point, task.c, R_MOD)
    for first_row, panel in sorted(task.raw_panels.items()):
        count, row_len = panel.shape[1], panel.shape[2]
        ints = protocol.matrix_to_ints(panel.reshape(16, count * row_len))
        tk = pow(point, first_row, R_MOD)
        for off in range(count):
            row = ints[off * row_len:(off + 1) * row_len]
            a = (a + _horner(row, tc) * tk) % R_MOD
            tk = tk * point % R_MOD
    b = 0
    vals = protocol.decode_scalars(task.result)
    c = task.c
    tr = pow(point, task.r, R_MOD)
    tk = pow(point, task.cs, R_MOD)
    for k1 in range(task.ce - task.cs):
        b = (b + _horner(vals[k1 * c:(k1 + 1) * c], tr) * tk) % R_MOD
        tk = tk * point % R_MOD
    return a, b


def _stage1_row(backend, domain_r, task, j2, row):
    """Stage-1 kernel for one global row j2 (fft1_helper,
    /root/reference/src/worker.rs:66-94): optional forward-coset pre-scale
    g^(j2 + c*j1), r-point (i)FFT, mid twiddle w^(+-j2*k1) — twiddles built
    incrementally, not per-element pow (improving on worker.rs:77-79)."""
    n, r, c = task.n, task.r, task.c
    if task.coset and not task.inverse:
        gc = pow(FR_GENERATOR, c, R_MOD)
        t = pow(FR_GENERATOR, j2, R_MOD)
        scaled = []
        for v in row:
            scaled.append(v * t % R_MOD)
            t = t * gc % R_MOD
        row = scaled
    out = backend.ifft(domain_r, row) if task.inverse else backend.fft(domain_r, row)
    w = fr_root_of_unity(n)
    base = pow(fr_inv(w) if task.inverse else w, j2, R_MOD)
    t = 1
    tw = []
    for v in out:
        tw.append(v * t % R_MOD)
        t = t * base % R_MOD
    return tw


def _stage2_row(backend, domain_c, task, k1, row):
    """Stage-2 kernel for one global column row k1 (fft2_helper,
    /root/reference/src/worker.rs:96-115): c-point (i)FFT + inverse-coset
    post-scale g^-(k1 + r*k2); the 1/n factor comes from the two stage
    iFFTs (1/r * 1/c), as in the reference."""
    out = backend.ifft(domain_c, row) if task.inverse else backend.fft(domain_c, row)
    if task.inverse and task.coset:
        g_inv = fr_inv(FR_GENERATOR)
        step = pow(g_inv, task.r, R_MOD)
        t = pow(g_inv, k1, R_MOD)
        scaled = []
        for v in out:
            scaled.append(v * t % R_MOD)
            t = t * step % R_MOD
        return scaled
    return out


def handle(conn, state):
    """Serve one connection until EOF/shutdown. Returns False to stop the
    whole daemon."""
    while True:
        try:
            tag, payload = conn.recv()
        except ConnectionError:
            return True
        try:
            # trace-context framing: a TRACED frame carries the caller's
            # {trace_id, parent_id}; the request is served under a span in
            # that trace's buffer (shipped back via TRACE_DUMP). Untraced
            # frames take the identical path with the null tracer.
            tag, ctx, payload = protocol.strip_context(tag, payload)
            tracer = state.tracer_for(ctx) if ctx is not None else NULL_TRACER
            parent = ctx.get("parent_id") if ctx else None
            with tracer.span("serve/" + protocol.tag_name(tag).lower(),
                             parent=parent, req_bytes=len(payload)):
                cont = _dispatch(conn, state, tag, payload, tracer=tracer)
            if ctx is not None and tag in _LOGGED_TAGS:
                # trace-correlated structured event per traced KERNEL
                # frame (debug level; the ring cap bounds it): the
                # worker's leg of the incident timeline — LOG_FETCH
                # filtered by this trace_id returns exactly these
                olog.emit("worker", "served", level="debug",
                          worker=state.me, trace_id=tracer.trace_id,
                          tag=protocol.tag_name(tag))
        except Exception as e:  # malformed payload / backend failure
            # counted so the fleet scrape's serve-error aggregate
            # (dpt_fleet_serve_errors_total) reflects real error replies
            state.metrics.inc("serve_errors")
            try:
                conn.send(protocol.ERR, repr(e).encode())
            except ConnectionError:
                return True
            continue
        if cont is False:
            return False


# abandoned FFT tasks (dispatcher died mid-protocol) are purged when older
# than this; COMPLETED tasks (kept only so FFT2 retries can re-read their
# reply) are purged much sooner; both checked on every FFT_INIT
_FFT_TASK_TTL_S = float(os.environ.get("DPT_FFT_TASK_TTL", "600"))
_FFT_DONE_TTL_S = float(os.environ.get("DPT_FFT_DONE_TTL", "60"))
# hard cap on resident tasks (the FFT2 replay cache grew per task_id with
# no bound between FFT_INITs — a fast dispatcher loop could OOM a worker
# inside one TTL window): LRU eviction, completed tasks first (their reply
# cache is the cheap thing to lose — a retry after eviction recomputes),
# then oldest in-flight (those are abandoned replans by construction when
# the cap is hit)
_FFT_TASK_CAP = int(os.environ.get("DPT_FFT_TASK_CAP", "64"))


def _evict_fft_tasks(tasks, cap, now):
    """TTL purge + LRU cap for the task table (state.lock held). Keeps at
    most `cap` - 1 entries so the task the caller is about to insert fits."""
    stale = [tid for tid, t in tasks.items()
             if (now - t.created > _FFT_TASK_TTL_S
                 or (t.done_at is not None
                     and now - t.done_at > _FFT_DONE_TTL_S))]
    for tid in stale:
        del tasks[tid]
    room = max(cap - 1, 0)
    if len(tasks) <= room:
        return
    done = sorted((tid for tid, t in tasks.items() if t.done_at is not None),
                  key=lambda tid: tasks[tid].done_at)
    live = sorted((tid for tid, t in tasks.items() if t.done_at is None),
                  key=lambda tid: tasks[tid].created)
    for tid in done + live:
        if len(tasks) <= room:
            break
        del tasks[tid]


def _dispatch(conn, state, tag, payload, tracer=NULL_TRACER):
    """Handle one request frame. Returns False to stop the daemon, anything
    else to keep serving.

    Locking: state.lock guards only STATE lookups/mutations (bases ref,
    domain/task tables); kernel execution happens OUTSIDE it, so one worker
    can overlap compute for concurrent connections (round-2 weakness #9
    serialized the whole MSM under the lock)."""
    state.count(tag)
    if tag == protocol.PING:
        conn.send(protocol.OK)
    elif tag == protocol.INIT_BASES:
        set_id, bases = protocol.decode_init_bases(payload)
        with state.lock:
            state.base_sets[set_id] = bases
        conn.send(protocol.OK)
    elif tag == protocol.MSM:
        set_id, scalars = protocol.decode_msm_request(payload)
        with state.lock:
            bases = state.base_sets.get(set_id)
        if bases is None:
            conn.send(protocol.ERR, b"no bases for set %d" % set_id)
            return None
        # kernel span attrs carry the bench.py flops/bytes model so the
        # merged timeline (and the MFU gauges fed from it) can attribute
        # where device time went, not just that it went
        t0 = time.perf_counter()
        with tracer.span("msm", n=len(scalars),
                         flops=msm_flops(len(scalars)),
                         data_bytes=len(scalars) * protocol.FR_BYTES):
            result = state.backend.msm(bases, scalars)
        state.observe_kernel("msm", time.perf_counter() - t0,
                             flops=msm_flops(len(scalars)),
                             data_bytes=len(scalars) * protocol.FR_BYTES)
        if _sdc_due(state, protocol.MSM):
            # a WELL-FORMED wrong answer (on-curve, in-subgroup): only
            # value-level checks (duplicate execution) can catch it
            from .. import curve as _C
            result = _C.g1_add_affine(result, _C.G1_GEN)
        conn.send(protocol.OK, protocol.encode_point(result))
    elif tag == protocol.NTT:
        values, inverse, coset = protocol.decode_ntt_request(payload)
        with state.lock:
            domain = state.domain(len(values))
        t0 = time.perf_counter()
        with tracer.span("ntt", n=len(values), inverse=inverse, coset=coset,
                         flops=ntt_flops(len(values)),
                         data_bytes=len(values) * protocol.FR_BYTES):
            if inverse and coset:
                out = state.backend.coset_ifft(domain, values)
            elif inverse:
                out = state.backend.ifft(domain, values)
            elif coset:
                out = state.backend.coset_fft(domain, values)
            else:
                out = state.backend.fft(domain, values)
        state.observe_kernel("ntt", time.perf_counter() - t0,
                             flops=ntt_flops(len(values)),
                             data_bytes=len(values) * protocol.FR_BYTES)
        if _sdc_due(state, protocol.NTT):
            out = list(out)
            out[0] = (out[0] + 1) % R_MOD  # one flipped field element
        conn.send(protocol.OK,
                  protocol.encode_scalar_matrix(protocol.ints_to_matrix(out)))
    elif tag == protocol.FFT_INIT:
        (task_id, inverse, coset, n, r, c, rs, re,
         col_ranges, epoch, keep_raw) = protocol.decode_fft_init(payload)
        now = time.monotonic()
        with state.lock:
            if epoch and state.epoch and epoch != state.epoch:
                # roster mismatch in EITHER direction is unservable: an
                # older plan's col_ranges no longer match the fleet, and
                # a NEWER plan references peers this worker's table does
                # not know yet (it missed a roster push) — rejecting
                # loudly beats an IndexError mid-exchange, and the
                # dispatcher re-pushes the roster on the replan path so
                # the lagging side converges (epoch 0 on either side =
                # no membership plane, always accepted)
                state.counters["stale_epoch"] = \
                    state.counters.get("stale_epoch", 0) + 1
                conn.send(protocol.ERR,
                          b"stale epoch: frame %d, roster %d"
                          % (epoch, state.epoch))
                return None
            _evict_fft_tasks(state.fft_tasks, _FFT_TASK_CAP, now)
            state.fft_tasks[task_id] = FftTask(
                inverse, coset, n, r, c, rs, re, col_ranges, state.me,
                keep_raw=keep_raw)
        conn.send(protocol.OK)
    elif tag == protocol.FFT1:
        task_id, first_row, panel = protocol.decode_fft1_matrix(payload)
        with state.lock:
            task = state.fft_tasks[task_id]
        count = panel.shape[1]
        if task.keep_raw:
            # retain the raw input panel: the FFT2 integrity piggyback's
            # input-side partial is computed over exactly what we received
            task.raw_panels[first_row] = panel
        t0 = time.perf_counter()
        with tracer.span("fft1_rows", rows=count, r=task.r,
                         flops=ntt_flops(task.r, count),
                         data_bytes=count * task.r * protocol.FR_BYTES):
            if state.stages is not None:
                staged = state.stages.stage1_panel(task, first_row, panel)
                lo = first_row - task.rs
                with task.cols_lock:
                    if task.rows_mat is None:
                        task.rows_mat = np.zeros(
                            (16, task.re - task.rs, task.r), dtype=np.uint32)
                    task.rows_mat[:, lo:lo + count, :] = staged
                    task.rows_filled[lo:lo + count] = True
            else:
                with state.lock:
                    domain_r = state.domain(task.r)
                ints = protocol.matrix_to_ints(
                    panel.reshape(16, count * panel.shape[2]))
                row_len = panel.shape[2]
                for off in range(count):
                    j2 = first_row + off
                    task.rows[j2 - task.rs] = _stage1_row(
                        state.backend, domain_r, task, j2,
                        ints[off * row_len:(off + 1) * row_len])
        state.observe_kernel("fft1", time.perf_counter() - t0,
                             flops=ntt_flops(task.r, count))
        conn.send(protocol.OK)
    elif tag == protocol.FFT2_PREPARE:
        (task_id,) = struct.unpack_from("<Q", payload, 0)
        with state.lock:
            task = state.fft_tasks[task_id]
        # push every peer its column slice of our rows (the all-to-all,
        # worker.rs:280-345); each send waits for the peer's ACK, so our OK
        # to the dispatcher implies all our data has landed. Rows go out as
        # ONE contiguous limb panel per peer (bulk codec, no per-row lists).
        if task.re > task.rs:
            if task.rows_mat is not None:
                # loud failure if any row range never saw an FFT1 frame —
                # the zero-initialized panel must not ship silently (the
                # int path raised on a None row here)
                assert task.rows_filled.all(), \
                    f"fft2_prepare before stage 1 complete " \
                    f"({task.rows_filled.sum()}/{task.rows_filled.size})"
                rows_np = task.rows_mat
            else:
                flat = [v for j2 in range(task.rs, task.re)
                        for v in task.rows[j2 - task.rs]]
                rows_np = protocol.ints_to_matrix(flat).reshape(
                    16, task.re - task.rs, task.r)
            # the all-to-all is worker->worker: re-inject our trace
            # context into each peer frame so the receiving workers'
            # exchange spans land in the SAME trace (peer legs would
            # otherwise be invisible to the merged timeline)
            with tracer.span("fft_exchange_push") as push_sid:
                for p, (ps, pe) in enumerate(task.col_ranges):
                    if pe == ps:
                        continue
                    panel = np.ascontiguousarray(rows_np[:, :, ps:pe])
                    xtag, xpayload = protocol.FFT_EXCHANGE, \
                        protocol.encode_fft_exchange(
                            task_id, ps, pe - ps, task.rs, panel)
                    if push_sid is not None:
                        xtag, xpayload = protocol.wrap_traced(
                            xtag, xpayload, {"trace_id": tracer.trace_id,
                                             "parent_id": push_sid})
                    # peer_call retries once on a fresh stream: a peer that
                    # restarted since the last FFT invalidates the cached
                    # conn
                    rtag, rpayload = state.peer_call(p, xtag, xpayload)
                    if rtag != protocol.OK:
                        raise RuntimeError(
                            f"peer {p} exchange failed: {rpayload!r}")
        conn.send(protocol.OK)
    elif tag == protocol.FFT_EXCHANGE:
        task_id, col_start, col_count, row_start, panel = \
            protocol.decode_fft_exchange(payload)
        with state.lock:
            task = state.fft_tasks[task_id]
        lo = col_start - task.cs
        with task.cols_lock:
            task.cols[:, lo:lo + col_count,
                      row_start:row_start + panel.shape[1]] = \
                panel.transpose(0, 2, 1)
            task.fill_mask[lo:lo + col_count,
                           row_start:row_start + panel.shape[1]] = True
        conn.send(protocol.OK)
    elif tag == protocol.FFT2:
        task_id, check_point = protocol.decode_fft2_request(payload)
        with state.lock:
            task = state.fft_tasks[task_id]
            domain_c = state.domain(task.c)
        if task.result is None:
            assert task.fill_mask.all(), \
                f"fft2 before exchange complete ({task.fill_mask.sum()}" \
                f"/{task.fill_mask.size})"
            t0 = time.perf_counter()
            with tracer.span("fft2_cols", cols=task.ce - task.cs, c=task.c,
                             flops=ntt_flops(task.c, task.ce - task.cs)):
                if state.stages is not None and task.ce > task.cs:
                    staged = state.stages.stage2_panel(task, task.cols)
                    task.result = protocol.encode_scalar_matrix(
                        staged.reshape(16,
                                       staged.shape[1] * staged.shape[2]))
                else:
                    out = []
                    for local, k1 in enumerate(range(task.cs, task.ce)):
                        row = protocol.matrix_to_ints(task.cols[:, local, :])
                        out.extend(_stage2_row(state.backend, domain_c,
                                               task, k1, row))
                    # reply rides the bulk codec (wire-identical path)
                    task.result = protocol.encode_scalar_matrix(
                        protocol.ints_to_matrix(out))
            state.observe_kernel("fft2", time.perf_counter() - t0,
                                 flops=ntt_flops(task.c,
                                                 task.ce - task.cs))
            if task.result and _sdc_due(state, protocol.FFT2):
                # SDC in the computed panel: one element perturbed IN the
                # cached buffer — retries and the integrity partials all
                # see the same corrupted result, like a real bad chip
                v = (protocol.decode_scalar(task.result) + 1) % R_MOD
                task.result = protocol.encode_scalar(v) \
                    + task.result[protocol.FR_BYTES:]
            task.done_at = time.monotonic()
        if check_point is not None and task.result \
                and (task.keep_raw or task.re <= task.rs):
            # integrity piggyback: (input-side, output-side) partial
            # power sums at the dispatcher's random point, computed from
            # the very buffers the data plane serves (O(n/k) host muls).
            # A task whose FFT_INIT did not announce the plane (mixed-
            # version fleet) answers plain — a zero input-side claim
            # over rows we dropped would read as a false SDC verdict.
            a, b = _fft2_partials(task, check_point)
            conn.send(protocol.OK,
                      protocol.encode_fft2_partials(a, b, task.result))
        else:
            conn.send(protocol.OK, task.result)
    elif tag == protocol.EVAL:
        # distributed partial evaluation (round 4 of the fleet prove):
        # sum_i c_i * point^i over the shipped coefficient chunk — the
        # dispatcher scales by point^start and folds across workers;
        # duplicate-executed chunks cross-check workers for SDC
        point, chunk = protocol.decode_eval_request(payload)
        with tracer.span("eval", n=len(chunk)):
            val = state.backend.eval_h(state.backend.lift(chunk), point)
        if _sdc_due(state, protocol.EVAL):
            val = (val + 1) % R_MOD
        conn.send(protocol.OK, protocol.encode_scalar(val))
    elif tag == protocol.STATS:
        import json as _json
        with state.lock:
            snap = dict(state.counters)
        conn.send(protocol.OK, _json.dumps(snap).encode())
    elif tag == protocol.HEALTH:
        # the liveness/re-admission probe (runtime/health.py): cheap,
        # lock-scoped snapshot — MUST stay fast even mid-FFT, a probe
        # that queues behind a kernel defeats the breaker's fast-fail
        import json as _json
        with state.lock:
            snap = {
                "uptime_s": round(time.monotonic() - state.started, 3),
                "served": sum(state.counters.values()),
                "fft_tasks": len(state.fft_tasks),
                "base_sets": sorted(state.base_sets),
                "backend": getattr(state.backend, "name", "?"),
                # wall-clock sample: the dispatcher brackets the probe
                # with its own clock and estimates this worker's offset
                # as now - (t_send + t_recv)/2, NTP-style — how merged
                # trace timestamps get onto one timeline
                "now": time.time(),
                "traces": len(state.traces),
                "epoch": state.epoch,
                # result-integrity chaos visibility: how many computed
                # results this worker's data plane has corrupted (always
                # 0 outside DPT_FAULTS soaks)
                "sdc_injected": state.sdc_injected,
                # warm-rejoin stats (set once after a --join worker
                # finishes its peer sync): the supervisor/operator's
                # evidence that a respawn came up warm
                "warm": state.warm,
            }
        conn.send(protocol.OK, _json.dumps(snap).encode())
    elif tag == protocol.ROSTER:
        # membership push: adopt the epoch table iff it is NEWER (an
        # out-of-order push is a no-op — epochs only move forward), and
        # drop every cached peer stream: indices are stable but a rejoin
        # means the old socket to that index is dead
        import json as _json
        req = protocol.decode_json(payload)
        new_epoch = int(req.get("epoch", 0))
        adopted = False
        with state.lock:
            if new_epoch > state.epoch:
                state.epoch = new_epoch
                state.config = NetworkConfig(req.get("workers", []))
                adopted = True
        if adopted:
            with state.peer_lock:
                stale = list(state.peers)
            for p in stale:
                state.drop_peer(p)
        conn.send(protocol.OK,
                  _json.dumps({"epoch": state.epoch,
                               "adopted": adopted}).encode())
    elif tag == protocol.STORE_LIST:
        from ..store import remote as store_remote
        store_remote.serve_list(
            state.store, payload, conn,
            no_store_reason="no store on this worker (--store)")
    elif tag == protocol.METRICS_FETCH:
        # the fleet-scrape surface (obs/fleet.py): this worker's FULL
        # structured registry — served counters, kernel latency
        # histograms, live gflops/MFU gauges — plus identity fields, one
        # JSON blob. Old dispatchers never send this; old workers answer
        # ERR "unknown tag" and the scraper degrades to snapshot=None.
        import json as _json
        snap = state.metrics.snapshot()
        with state.lock:
            snap.update({
                "index": state.me,
                "epoch": state.epoch,
                "backend": getattr(state.backend, "name", "?"),
                "uptime_s": round(time.monotonic() - state.started, 3),
                "sdc_injected": state.sdc_injected,
                "fft_tasks": len(state.fft_tasks),
                "base_sets": len(state.base_sets),
                "traces": len(state.traces),
                "log_seq": olog.buffer().seq,
            })
        conn.send(protocol.OK, _json.dumps(snap).encode())
    elif tag == protocol.LOG_FETCH:
        # structured-log ring fetch (obs/log.py): optionally filtered to
        # one trace id (the dispatcher's collect_trace merge) or tailed
        # via since_seq (the console). Reads never clear the ring.
        import json as _json
        req = protocol.decode_json(payload)
        out = olog.fetch(trace_id=req.get("trace_id"),
                         since_seq=int(req.get("since_seq") or 0),
                         limit=req.get("limit"))
        conn.send(protocol.OK, _json.dumps(out).encode())
    elif tag == protocol.PROFILE:
        # on-demand capture (obs/profiling.py): jax.profiler xplane on
        # jax backends, all-thread Python stack sampler otherwise. The
        # capture blocks only THIS connection thread for the window —
        # kernel serving on other connections continues (and is exactly
        # what the sampler sees). Reply is header+blob like STORE_FETCH.
        req = protocol.decode_json(payload)
        meta, blob = profiling.capture(
            duration_ms=req.get("duration_ms"),
            kind=req.get("kind", "auto"),
            backend_name=getattr(state.backend, "name", None))
        meta["worker"] = state.me
        state.metrics.inc("profiles_captured")
        olog.emit("worker", "profile_captured", worker=state.me,
                  format=meta.get("format"), bytes=len(blob))
        conn.send(protocol.OK, protocol.encode_result(meta, blob))
    elif tag == protocol.TRACE_DUMP:
        # fetch-and-forget one trace's worker-side spans: the dispatcher
        # stitches them (offset-corrected) into the merged per-job
        # timeline; an unknown id answers {} (the worker may have been
        # restarted, or LRU-dropped an abandoned trace)
        import json as _json
        req = protocol.decode_json(payload)
        tr = state.pop_trace(req.get("trace_id"))
        conn.send(protocol.OK,
                  _json.dumps(tr.dump() if tr is not None else {}).encode())
    elif tag == protocol.STORE_FETCH:
        # peer-serving plane: a replacement worker on a fresh host pulls
        # SRS/pk/checkpoint blobs from us instead of rebuilding them
        from ..store import remote as store_remote
        store_remote.serve_fetch(
            state.store, payload, conn,
            no_store_reason="no store on this worker (--store)")
    elif tag == protocol.SHUTDOWN:
        conn.send(protocol.OK)
        return False
    else:
        conn.send(protocol.ERR, b"unknown tag")
    return None


def _make_store(store_dir):
    if store_dir is None:
        return None
    from ..store import ArtifactStore, set_jax_cache_env
    # synced/persisted compiled executables live under the store: point
    # a not-yet-imported jax backend's persistent compile cache there so
    # warm-rejoined cache entries actually get hit
    set_jax_cache_env(store_dir)
    return ArtifactStore(store_dir)


def _load_calibration(store, mode=None):
    """Adopt the store's kernel-calibration plan for this machine
    (store/calibration.py, DPT_AUTOTUNE=load|run|off; default load).
    Import-light and jax-free on the load path, so python-backend
    workers pay nothing. Never fatal: a worker without a plan (or with a
    broken one) serves with the built-in kernel defaults. `mode`
    overrides DPT_AUTOTUNE (serve_joined pins its pre-sync probe to
    'load'). Returns the pickup report (or None)."""
    if store is None:
        return None
    from ..store import calibration
    try:
        return calibration.load_or_run(store, mode=mode)
    except Exception:  # noqa: BLE001 - calibration is an accelerator,
        # never a startup gate
        return None


def _run_server(listener, state, ready_event=None):
    """Accept loop until a SHUTDOWN frame lands."""
    if ready_event is not None:
        ready_event.set()
    stop = threading.Event()

    def run_conn(conn):
        if not handle(conn, state):
            stop.set()
        conn.close()

    def accept_loop():
        while True:
            conn = listener.accept()
            if conn.fd < 0:
                return
            threading.Thread(target=run_conn, args=(conn,), daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()
    stop.wait()  # SHUTDOWN flips this; daemon threads die with the process
    listener.close()


def serve(index, config, backend_name="python", ready_event=None,
          store_dir=None):
    """Static-fleet daemon: index + config fixed at startup (epoch 0)."""
    host, port = config.workers[index]
    listener = native.Listener(host, port)
    # store BEFORE backend: _make_store points the jax compile cache
    # under the store via env that field_jax reads at import — building
    # the backend first would configure the cache elsewhere and leave
    # this worker with zero jaxcache:* entries to serve warm-rejoiners
    store = _make_store(store_dir)
    _load_calibration(store)
    olog.configure_from_env(proc=f"worker/{index}")
    state = WorkerState(_make_backend(backend_name), config=config, me=index,
                        store=store)
    olog.emit("worker", "serving", worker=index, backend=backend_name,
              port=port, store=store_dir is not None)
    _run_server(listener, state, ready_event=ready_event)


def serve_joined(join_addr, listen_addr=("127.0.0.1", 0),
                 backend_name="python", store_dir=None, ready_event=None):
    """Dynamic-membership daemon (`--join host:port`): bind first (port 0
    = ephemeral), announce to the membership server, adopt the returned
    index + epoch + roster, serve — then warm-rejoin in the background:
    pull bucket-key artifacts and jax persistent-compile-cache entries
    from the roster's store-serving peers (STORE_FETCH/STORE_LIST), so a
    replacement worker reaches first-kernel-launch without rebuilding
    keys or recompiling stages. The worker is schedulable from the JOIN
    ack; the sync only ACCELERATES first touches, it gates nothing."""
    from . import membership
    host, port = listen_addr
    listener = native.Listener(host, port)
    port = port or native.listener_port(listener)
    reply = membership.join_fleet(join_addr[0], join_addr[1], host, port,
                                  store=store_dir is not None)
    # adopt a locally present plan immediately — but LOAD only: under
    # DPT_AUTOTUNE=run a fresh joiner must not burn its startup on a
    # full local measure pass when the warm sync below may pull this
    # fingerprint's plan from a roster peer for free (the post-sync
    # pickup keeps the configured mode, so a genuinely plan-less fleet
    # still calibrates)
    store = _make_store(store_dir)
    _load_calibration(store, mode="load")
    olog.configure_from_env(proc=f"worker/{reply['index']}")
    state = WorkerState(_make_backend(backend_name),
                        config=NetworkConfig(reply["workers"]),
                        me=int(reply["index"]), store=store,
                        epoch=int(reply["epoch"]))
    olog.emit("worker", "joined", worker=state.me, backend=backend_name,
              port=port, epoch=state.epoch)

    def warm_sync():
        from ..store import remote as store_remote
        me = f"{host}:{port}"
        peers = [tuple(a.rsplit(":", 1)) for a in reply.get("stores", [])
                 if a != me]
        stats = {"warm_rejoin_s": 0.0, "artifacts": 0, "jax_cache_files": 0,
                 "peers": 0}
        if store is not None and peers:
            stats = store_remote.warm_sync(
                store, [(h, int(p)) for h, p in peers])
            # the sync may have just pulled this fingerprint's autotune:
            # plan from a roster peer (WARM_SYNC_PREFIXES) — adopt it so
            # a replacement worker dispatches the calibrated kernels
            # without ever measuring locally
            from ..backend import autotune as _autotune
            if _autotune.active_plan() is None:
                _load_calibration(store)
        state.warm = stats
        olog.emit("worker", "warm_rejoin", worker=state.me, **{
            k: v for k, v in stats.items()
            if isinstance(v, (int, float, str, bool))})
        if store is not None:
            # storeless joiners have nothing to sync: reporting ready
            # would count a zero-length "warm rejoin" and fill the
            # warm_rejoin_s histogram with meaningless 0.0 samples
            membership.report_ready(join_addr[0], join_addr[1], host,
                                    port, stats)

    threading.Thread(target=warm_sync, daemon=True).start()
    _run_server(listener, state, ready_event=ready_event)


def _parse_hostport(s):
    h, _, p = s.rpartition(":")
    return h or "127.0.0.1", int(p)


def main(argv):
    backend = "python"
    if "--backend" in argv:
        backend = argv[argv.index("--backend") + 1]
    store_dir = None
    if "--store" in argv:
        store_dir = argv[argv.index("--store") + 1]
    if "--join" in argv:
        join_addr = _parse_hostport(argv[argv.index("--join") + 1])
        listen_addr = ("127.0.0.1", 0)
        if "--listen" in argv:
            listen_addr = _parse_hostport(argv[argv.index("--listen") + 1])
        serve_joined(join_addr, listen_addr, backend, store_dir=store_dir)
        return
    index = int(argv[0])
    cfg_path = argv[1] if len(argv) > 1 else "config/network.json"
    serve(index, NetworkConfig.load(cfg_path), backend, store_dir=store_dir)


if __name__ == "__main__":
    main(sys.argv[1:])
