"""Worker daemon: serves MSM/NTT over the native framed transport.

The analog of the reference's worker binary (/root/reference/src/worker.rs:
441-536): holds device-resident SRS state across requests (State,
worker.rs:42-59), executes kernels per RPC. Threading model: one thread per
dispatcher connection, state guarded by a lock — replacing the reference's
single-thread-plus-unsafe-aliasing design (worker.rs:135 etc.) with an
actually sound one.

Run: python -m distributed_plonk_tpu.runtime.worker <index> [config.json]
    [--backend python|jax]
"""

import sys
import threading

from . import native, protocol
from .netconfig import NetworkConfig
from ..poly import Domain


def _make_backend(name):
    if name == "jax":
        from ..backend.jax_backend import JaxBackend
        return JaxBackend()
    from ..backend.python_backend import PythonBackend
    return PythonBackend()


class WorkerState:
    def __init__(self, backend):
        self.backend = backend
        self.bases = None
        self.lock = threading.Lock()
        self.domains = {}

    def domain(self, n):
        if n not in self.domains:
            self.domains[n] = Domain(n)
        return self.domains[n]


def handle(conn, state):
    """Serve one connection until EOF/shutdown. Returns False to stop the
    whole daemon."""
    while True:
        try:
            tag, payload = conn.recv()
        except ConnectionError:
            return True
        try:
            cont = _dispatch(conn, state, tag, payload)
        except Exception as e:  # malformed payload / backend failure
            try:
                conn.send(protocol.ERR, repr(e).encode())
            except ConnectionError:
                return True
            continue
        if cont is False:
            return False


def _dispatch(conn, state, tag, payload):
    """Handle one request frame. Returns False to stop the daemon, anything
    else to keep serving."""
    if tag == protocol.PING:
        conn.send(protocol.OK)
    elif tag == protocol.INIT_BASES:
        with state.lock:
            state.bases = protocol.decode_points(payload)
        conn.send(protocol.OK)
    elif tag == protocol.MSM:
        scalars = protocol.decode_scalars(payload)
        with state.lock:
            if state.bases is None:
                conn.send(protocol.ERR, b"no bases")
                return None
            result = state.backend.msm(state.bases, scalars)
        conn.send(protocol.OK, protocol.encode_point(result))
    elif tag == protocol.NTT:
        values, inverse, coset = protocol.decode_ntt_request(payload)
        with state.lock:
            domain = state.domain(len(values))
            if inverse and coset:
                out = state.backend.coset_ifft(domain, values)
            elif inverse:
                out = state.backend.ifft(domain, values)
            elif coset:
                out = state.backend.coset_fft(domain, values)
            else:
                out = state.backend.fft(domain, values)
        conn.send(protocol.OK, protocol.encode_scalars(out))
    elif tag == protocol.SHUTDOWN:
        conn.send(protocol.OK)
        return False
    else:
        conn.send(protocol.ERR, b"unknown tag")
    return None


def serve(index, config, backend_name="python", ready_event=None):
    host, port = config.workers[index]
    listener = native.Listener(host, port)
    state = WorkerState(_make_backend(backend_name))
    if ready_event is not None:
        ready_event.set()
    stop = threading.Event()

    def run_conn(conn):
        if not handle(conn, state):
            stop.set()
        conn.close()

    def accept_loop():
        while True:
            conn = listener.accept()
            if conn.fd < 0:
                return
            threading.Thread(target=run_conn, args=(conn,), daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()
    stop.wait()  # SHUTDOWN flips this; daemon threads die with the process
    listener.close()


def main(argv):
    index = int(argv[0])
    cfg_path = argv[1] if len(argv) > 1 else "config/network.json"
    backend = "python"
    if "--backend" in argv:
        backend = argv[argv.index("--backend") + 1]
    serve(index, NetworkConfig.load(cfg_path), backend)


if __name__ == "__main__":
    main(sys.argv[1:])
