"""Worker daemon: serves MSM/NTT over the native framed transport.

The analog of the reference's worker binary (/root/reference/src/worker.rs:
441-536): holds device-resident SRS state across requests (State,
worker.rs:42-59), executes kernels per RPC. Threading model: one thread per
connection, state guarded by a lock — replacing the reference's
single-thread-plus-unsafe-aliasing design (worker.rs:135 etc.) with an
actually sound one.

Also serves the cross-worker sharded 4-step FFT (the reference's signature
protocol): FFT_INIT allocates a task (worker.rs:187-233), FFT1 runs the
stage-1 row kernels (worker.rs:235-278 -> 66-94), FFT2_PREPARE pushes each
peer its column slices over direct worker<->worker connections
(worker.rs:280-345 sender, 412-438 receiver), FFT2 runs the stage-2 column
kernels and returns the result shard (worker.rs:347-381 -> 96-115). Unlike
the reference there is no second listener plane: peer exchange frames
arrive on the same port, distinguished by tag (netconfig.py documents the
single-plane choice).

Run: python -m distributed_plonk_tpu.runtime.worker <index> [config.json]
    [--backend python|jax]
"""

import struct
import sys
import threading

from . import native, protocol
from .netconfig import NetworkConfig
from ..constants import R_MOD, FR_GENERATOR
from ..fields import fr_inv, fr_root_of_unity
from ..poly import Domain


def _make_backend(name):
    if name == "jax":
        from ..backend.jax_backend import JaxBackend
        return JaxBackend()
    from ..backend.python_backend import PythonBackend
    return PythonBackend()


class FftTask:
    """In-flight sharded FFT state (the reference's FftTask,
    /root/reference/src/worker.rs:50-54): stage-1 results for our rows,
    stage-2 input columns filled in by peer exchanges."""

    def __init__(self, inverse, coset, n, r, c, rs, re, col_ranges, me):
        self.inverse = inverse
        self.coset = coset
        self.n, self.r, self.c = n, r, c
        self.rs, self.re = rs, re          # our stage-1 rows (j2 indices)
        self.col_ranges = col_ranges       # every worker's stage-2 range (k1)
        self.cs, self.ce = col_ranges[me]
        self.rows = [None] * (re - rs)     # [local j2] -> length-r row
        self.cols = [[None] * c for _ in range(self.ce - self.cs)]  # [local k1][j2]


class WorkerState:
    def __init__(self, backend, config=None, me=0):
        self.backend = backend
        self.config = config
        self.me = me
        self.bases = None
        self.lock = threading.Lock()
        self.domains = {}
        self.fft_tasks = {}
        self.peers = {}
        self.peer_lock = threading.Lock()
        self.counters = {}

    def domain(self, n):
        if n not in self.domains:
            self.domains[n] = Domain(n)
        return self.domains[n]

    def count(self, tag):
        with self.lock:
            self.counters[tag] = self.counters.get(tag, 0) + 1

    def peer(self, p):
        """Lazy worker->worker connection (the reference opens peer
        connections per exchange, worker.rs:297-338; here they are cached).
        Includes the self-loop via TCP, as the reference does."""
        with self.peer_lock:
            if p not in self.peers:
                host, port = self.config.workers[p]
                conn = native.connect(host, port)
                self.peers[p] = (conn, threading.Lock())
            return self.peers[p]


def _stage1_row(backend, domain_r, task, j2, row):
    """Stage-1 kernel for one global row j2 (fft1_helper,
    /root/reference/src/worker.rs:66-94): optional forward-coset pre-scale
    g^(j2 + c*j1), r-point (i)FFT, mid twiddle w^(+-j2*k1) — twiddles built
    incrementally, not per-element pow (improving on worker.rs:77-79)."""
    n, r, c = task.n, task.r, task.c
    if task.coset and not task.inverse:
        gc = pow(FR_GENERATOR, c, R_MOD)
        t = pow(FR_GENERATOR, j2, R_MOD)
        scaled = []
        for v in row:
            scaled.append(v * t % R_MOD)
            t = t * gc % R_MOD
        row = scaled
    out = backend.ifft(domain_r, row) if task.inverse else backend.fft(domain_r, row)
    w = fr_root_of_unity(n)
    base = pow(fr_inv(w) if task.inverse else w, j2, R_MOD)
    t = 1
    tw = []
    for v in out:
        tw.append(v * t % R_MOD)
        t = t * base % R_MOD
    return tw


def _stage2_row(backend, domain_c, task, k1, row):
    """Stage-2 kernel for one global column row k1 (fft2_helper,
    /root/reference/src/worker.rs:96-115): c-point (i)FFT + inverse-coset
    post-scale g^-(k1 + r*k2); the 1/n factor comes from the two stage
    iFFTs (1/r * 1/c), as in the reference."""
    out = backend.ifft(domain_c, row) if task.inverse else backend.fft(domain_c, row)
    if task.inverse and task.coset:
        g_inv = fr_inv(FR_GENERATOR)
        step = pow(g_inv, task.r, R_MOD)
        t = pow(g_inv, k1, R_MOD)
        scaled = []
        for v in out:
            scaled.append(v * t % R_MOD)
            t = t * step % R_MOD
        return scaled
    return out


def handle(conn, state):
    """Serve one connection until EOF/shutdown. Returns False to stop the
    whole daemon."""
    while True:
        try:
            tag, payload = conn.recv()
        except ConnectionError:
            return True
        try:
            cont = _dispatch(conn, state, tag, payload)
        except Exception as e:  # malformed payload / backend failure
            try:
                conn.send(protocol.ERR, repr(e).encode())
            except ConnectionError:
                return True
            continue
        if cont is False:
            return False


def _dispatch(conn, state, tag, payload):
    """Handle one request frame. Returns False to stop the daemon, anything
    else to keep serving."""
    state.count(tag)
    if tag == protocol.PING:
        conn.send(protocol.OK)
    elif tag == protocol.INIT_BASES:
        with state.lock:
            state.bases = protocol.decode_points(payload)
        conn.send(protocol.OK)
    elif tag == protocol.MSM:
        scalars = protocol.decode_scalars(payload)
        with state.lock:
            if state.bases is None:
                conn.send(protocol.ERR, b"no bases")
                return None
            result = state.backend.msm(state.bases, scalars)
        conn.send(protocol.OK, protocol.encode_point(result))
    elif tag == protocol.NTT:
        values, inverse, coset = protocol.decode_ntt_request(payload)
        with state.lock:
            domain = state.domain(len(values))
            if inverse and coset:
                out = state.backend.coset_ifft(domain, values)
            elif inverse:
                out = state.backend.ifft(domain, values)
            elif coset:
                out = state.backend.coset_fft(domain, values)
            else:
                out = state.backend.fft(domain, values)
        conn.send(protocol.OK, protocol.encode_scalars(out))
    elif tag == protocol.FFT_INIT:
        (task_id, inverse, coset, n, r, c, rs, re,
         col_ranges) = protocol.decode_fft_init(payload)
        with state.lock:
            state.fft_tasks[task_id] = FftTask(
                inverse, coset, n, r, c, rs, re, col_ranges, state.me)
        conn.send(protocol.OK)
    elif tag == protocol.FFT1:
        task_id, first_row, rows = protocol.decode_fft1(payload)
        with state.lock:
            task = state.fft_tasks[task_id]
        domain_r = state.domain(task.r)
        for off, row in enumerate(rows):
            j2 = first_row + off
            task.rows[j2 - task.rs] = _stage1_row(
                state.backend, domain_r, task, j2, row)
        conn.send(protocol.OK)
    elif tag == protocol.FFT2_PREPARE:
        (task_id,) = struct.unpack_from("<Q", payload, 0)
        with state.lock:
            task = state.fft_tasks[task_id]
        # push every peer its column slice of our rows (the all-to-all,
        # worker.rs:280-345); each send waits for the peer's ACK, so our OK
        # to the dispatcher implies all our data has landed
        for p, (ps, pe) in enumerate(task.col_ranges):
            if pe == ps or task.re == task.rs:
                continue
            entries = [(j2, task.rows[j2 - task.rs][ps:pe])
                       for j2 in range(task.rs, task.re)]
            pconn, plock = state.peer(p)
            with plock:
                pconn.send(protocol.FFT_EXCHANGE, protocol.encode_fft_exchange(
                    task_id, ps, pe - ps, entries))
                rtag, rpayload = pconn.recv()
            if rtag != protocol.OK:
                raise RuntimeError(f"peer {p} exchange failed: {rpayload!r}")
        conn.send(protocol.OK)
    elif tag == protocol.FFT_EXCHANGE:
        task_id, col_start, col_count, entries = \
            protocol.decode_fft_exchange(payload)
        with state.lock:
            task = state.fft_tasks[task_id]
        for j2, vals in entries:
            for i in range(col_count):
                task.cols[col_start + i - task.cs][j2] = vals[i]
        conn.send(protocol.OK)
    elif tag == protocol.FFT2:
        (task_id,) = struct.unpack_from("<Q", payload, 0)
        with state.lock:
            task = state.fft_tasks[task_id]
        domain_c = state.domain(task.c)
        out = []
        for local, k1 in enumerate(range(task.cs, task.ce)):
            row = task.cols[local]
            assert None not in row, f"fft2 before exchange complete (k1={k1})"
            out.extend(_stage2_row(state.backend, domain_c, task, k1, row))
        with state.lock:
            del state.fft_tasks[task_id]  # GC (the reference leaks on abort
            # too, worker.rs:378; dispatcher failure mid-task leaves the
            # entry until process restart)
        conn.send(protocol.OK, protocol.encode_scalars(out))
    elif tag == protocol.STATS:
        import json as _json
        with state.lock:
            snap = dict(state.counters)
        conn.send(protocol.OK, _json.dumps(snap).encode())
    elif tag == protocol.SHUTDOWN:
        conn.send(protocol.OK)
        return False
    else:
        conn.send(protocol.ERR, b"unknown tag")
    return None


def serve(index, config, backend_name="python", ready_event=None):
    host, port = config.workers[index]
    listener = native.Listener(host, port)
    state = WorkerState(_make_backend(backend_name), config=config, me=index)
    if ready_event is not None:
        ready_event.set()
    stop = threading.Event()

    def run_conn(conn):
        if not handle(conn, state):
            stop.set()
        conn.close()

    def accept_loop():
        while True:
            conn = listener.accept()
            if conn.fd < 0:
                return
            threading.Thread(target=run_conn, args=(conn,), daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()
    stop.wait()  # SHUTDOWN flips this; daemon threads die with the process
    listener.close()


def main(argv):
    index = int(argv[0])
    cfg_path = argv[1] if len(argv) > 1 else "config/network.json"
    backend = "python"
    if "--backend" in argv:
        backend = argv[argv.index("--backend") + 1]
    serve(index, NetworkConfig.load(cfg_path), backend)


if __name__ == "__main__":
    main(sys.argv[1:])
