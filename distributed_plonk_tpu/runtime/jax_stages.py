"""Batched device stage kernels for a jax-backend fleet worker.

The generic worker path runs the 4-step FFT stage kernels row by row
through the int-list backend API (fine for the python oracle backend, but
a jax worker would pay one device dispatch per row — hundreds of tunnel
round-trips per FFT1 frame). This module runs a whole FFT1/FFT2 frame as
ONE jitted launch over the (16, rows, len) limb panel, with the coset /
mid / inverse-coset twiddle scalings folded in as precomputed Montgomery
tables — and no host int conversion anywhere (wire bytes <-> limb panels
only).

Stage math matches worker._stage1_row/_stage2_row (the reference's
fft1/fft2 helpers, /root/reference/src/worker.rs:66-115) bit for bit.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..backend import autotune
from ..backend import ntt_jax
from ..backend import field_jax as FJ
from ..backend.field_jax import FR
from ..constants import R_MOD, FR_GENERATOR
from ..fields import fr_inv, fr_root_of_unity


class StageKernels:
    """Per-worker cache of twiddle tables + jitted panel kernels."""

    _TABLE_CAP = 8  # (n, mode, range) table sets kept resident

    def __init__(self):
        self._tables = {}

    @staticmethod
    @jax.jit
    def _panel_fn(v, pre, mid, post, core):
        """(16, B, L) canonical panel -> staged canonical panel. pre/mid/
        post are optional Montgomery scale tables (None-ness is static per
        trace); core is a shared stage-core table set
        (ntt_jax.NttPlan.core_consts), so the fleet panels run the same
        radix-selected butterflies as the single-device kernels."""
        v = FJ.to_mont(FR, v)
        if pre is not None:
            v = FJ.mont_mul(FR, v, pre)
        v = ntt_jax.run_stages(v, core)
        if mid is not None:
            v = FJ.mont_mul(FR, v, mid)
        if post is not None:
            v = FJ.mont_mul(FR, v, post)
        return FJ.from_mont(FR, v)

    def _plan_consts(self, size, inverse):
        # keyed on the active radix AND kernel (DPT_NTT_KERNEL): pallas
        # table sets carry the fused-stage twiddle blocks alongside the
        # XLA tables, so the fleet panels follow the same dispatch knob
        # as the single-device and mesh paths
        key = autotune.cache_key(
            "plan", size, inverse, ntt_jax._active_radix(n=size),
            ntt_jax._active_kernel(n=size))
        if key not in self._tables:
            plan = ntt_jax.get_plan(size)
            self._tables[key] = {
                k: jnp.asarray(a)
                for k, a in plan.core_consts(inverse).items()}
        return self._tables[key]

    def _cache_put(self, key, value):
        """Tables are stored as DEVICE arrays: numpy here would re-pay a
        host->device transfer of up to tens of MB per FFT frame."""
        if len(self._tables) >= self._TABLE_CAP:
            self._tables.pop(next(iter(self._tables)))
        value = jax.tree_util.tree_map(jnp.asarray, value)
        self._tables[key] = value
        return value

    def _stage1_tables(self, task, rs, re):
        """(pre, mid) Montgomery tables for global rows j2 in [rs, re)."""
        key = ("s1", task.n, task.inverse, task.coset, rs, re)
        if key in self._tables:
            return self._tables[key]
        n, r, c = task.n, task.r, task.c
        pre = None
        if task.coset and not task.inverse:
            vals = []
            gc = pow(FR_GENERATOR, c, R_MOD)
            for j2 in range(rs, re):
                vals.extend(ntt_jax._powers(
                    gc, r, start=pow(FR_GENERATOR, j2, R_MOD)))
            pre = ntt_jax._mont_table(vals).reshape(16, re - rs, r)
        w = fr_root_of_unity(n)
        base = fr_inv(w) if task.inverse else w
        # the stage core (run_stages) omits the 1/size factor of an iNTT: fold the
        # stage-1 1/r into the mid twiddles (the int path's backend.ifft
        # applies it internally)
        start0 = fr_inv(r % R_MOD) if task.inverse else 1
        vals = []
        for j2 in range(rs, re):
            vals.extend(ntt_jax._powers(pow(base, j2, R_MOD), r, start=start0))
        mid = ntt_jax._mont_table(vals).reshape(16, re - rs, r)
        return self._cache_put(key, (pre, mid))

    def _stage2_tables(self, task, cs, ce):
        """post Montgomery table for global columns k1 in [cs, ce):
        inverse-coset scales g^-(k1 + r*k2) plus the stage-2 1/c factor
        (the 1/n of a full iNTT = the 1/r folded into stage 1's mids times
        this 1/c, as in the reference's two stage iFFTs)."""
        key = ("s2", task.n, task.inverse, task.coset, cs, ce)
        if key in self._tables:
            return self._tables[key]
        post = None
        if task.inverse:
            c_inv = fr_inv(task.c % R_MOD)
            if task.coset:
                g_inv = fr_inv(FR_GENERATOR)
                step = pow(g_inv, task.r, R_MOD)
                vals = []
                for k1 in range(cs, ce):
                    vals.extend(ntt_jax._powers(
                        step, task.c,
                        start=c_inv * pow(g_inv, k1, R_MOD) % R_MOD))
                post = ntt_jax._mont_table(vals).reshape(16, ce - cs, task.c)
            else:
                post = ntt_jax._mont_table([c_inv]).reshape(16, 1, 1)
        return self._cache_put(key, post)

    def stage1_panel(self, task, first_row, panel):
        """(16, B, r) canonical limb panel for rows [first_row, ...) ->
        staged panel (numpy)."""
        b = panel.shape[1]
        pre, mid = self._stage1_tables(task, first_row, first_row + b)
        core = self._plan_consts(task.r, task.inverse)
        out = self._panel_fn(panel, pre, mid, None, core)
        return np.asarray(out)

    def stage2_panel(self, task, cols_panel):
        """(16, locals, c) canonical columns panel -> staged output panel
        (numpy), ready for the wire."""
        post = self._stage2_tables(task, task.cs, task.ce)
        core = self._plan_consts(task.c, task.inverse)
        out = self._panel_fn(cols_panel, None, None, post, core)
        return np.asarray(out)
