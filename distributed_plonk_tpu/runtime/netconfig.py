"""Cluster topology config.

Equivalent of the reference's `config/network.json` + src/config.rs:5-9,
with one plane instead of two: the reference needed a second peer-to-peer
plane for the FFT all-to-all (src/worker.rs:503-532); here that exchange is
an XLA collective over ICI inside the pod, so only the dispatcher<->worker
control/data plane remains.
"""

import json


class NetworkConfig:
    def __init__(self, workers):
        # workers: list of "host:port"
        self.workers = []
        for w in workers:
            host, port = w.rsplit(":", 1)
            self.workers.append((host, int(port)))

    @classmethod
    def load(cls, path):
        with open(path) as f:
            data = json.load(f)
        return cls(data["workers"])

    def save(self, path):
        with open(path, "w") as f:
            json.dump({"workers": [f"{h}:{p}" for h, p in self.workers]}, f)

    @property
    def n_workers(self):
        return len(self.workers)
