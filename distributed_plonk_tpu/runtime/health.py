"""Fleet liveness tracking: per-worker circuit breaker + probe backoff.

The dispatcher-side health model the reference never had (every worker RPC
there is an `.unwrap()`, /root/reference/src/worker.rs:303 — one crash
panics the prove). Here each worker carries a tiny state machine:

    CLOSED   healthy: requests route to it normally.
    OPEN     dead: `breaker_k` CONSECUTIVE call failures opened the
             breaker; requests fast-fail (`usable()` is False) so callers
             adopt its ranges instead of burning reconnect timeouts.
    half-open (implicit): once `next_probe` passes, exactly ONE caller per
             window gets `probe_due()` True and sends a cheap HEALTH/PING
             on a fresh connection; success re-admits (CLOSED), failure
             pushes `next_probe` out exponentially (with jitter).
    SUSPECT  quarantined (runtime/integrity.py attributed a WRONG answer
             to it): breaker open AND sticky — a suspect worker answers
             probes perfectly well (it is alive; its answers are wrong),
             so `record_ok` does NOT re-admit it. Only an explicit
             `clear_suspect` (the membership JOIN path, after the fresh
             process passes a known-answer challenge) closes the breaker
             again.

All mutable state lives in per-worker dicts guarded by `self._lock`
(LOCK01/02 discipline — analysis/lint.py runs over runtime/ too). The
tracker never talks to the network itself: callers report outcomes via
`record_ok`/`record_failure` and run the probes it schedules, so it stays
backend- and transport-agnostic (and trivially testable).

Knobs (env, read at construction):
    DPT_BREAKER_K        consecutive failures to open the breaker (3)
    DPT_PROBE_BASE_MS    first re-admission probe delay (200)
    DPT_PROBE_MAX_MS     probe backoff ceiling (5000)
"""

import os
import random
import threading
import time


class NullMetrics:
    """No-op stand-in for the duck-typed service.metrics.Metrics shape —
    the one shared null object for every layer that takes an optional
    registry (tracker, dispatcher, artifact store)."""

    def inc(self, name, by=1):
        pass

    def gauge(self, name, value):
        pass

    def observe(self, name, seconds):
        pass


class LivenessTracker:
    """Per-worker consecutive-failure circuit breaker with probe backoff."""

    def __init__(self, n_workers, breaker_k=None, probe_base_s=None,
                 probe_max_s=None, metrics=None, rng=None):
        self.breaker_k = breaker_k if breaker_k is not None else int(
            os.environ.get("DPT_BREAKER_K", "3"))
        self.probe_base_s = probe_base_s if probe_base_s is not None else \
            float(os.environ.get("DPT_PROBE_BASE_MS", "200")) / 1000.0  # analysis: ok(host-only ms->s)
        self.probe_max_s = probe_max_s if probe_max_s is not None else \
            float(os.environ.get("DPT_PROBE_MAX_MS", "5000")) / 1000.0  # analysis: ok(host-only ms->s)
        self.metrics = metrics or NullMetrics()
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._state = [self._fresh() for _ in range(n_workers)]

    @staticmethod
    def _fresh():
        return {"open": False, "failures": 0, "next_probe": 0.0,
                "probe_backoff": 0.0, "opens": 0, "suspect": False}

    def add_worker(self):
        """Grow the table by one (dynamic membership: a JOIN appends a
        worker; indices are stable, so growth is append-only). Returns
        the new worker's index."""
        with self._lock:
            self._state.append(self._fresh())
            return len(self._state) - 1

    def _jitter(self, base):
        """base + up to 50% random jitter: fleet-wide probes/retries must
        not synchronize into thundering herds."""
        return base * (1.0 + 0.5 * self._rng.random())  # analysis: ok(host-only jitter)

    # -- outcome reporting ----------------------------------------------------

    def record_ok(self, i):
        """A successful call: reset failures; re-admit if OPEN (the call
        doubled as a successful probe). A SUSPECT worker is NOT
        re-admitted: it is alive and answering — its answers are wrong
        (the whole point of quarantine); only clear_suspect revives it."""
        with self._lock:
            s = self._state[i]
            if s["suspect"]:
                return False
            readmitted = s["open"]
            s["open"] = False
            s["failures"] = 0
            s["probe_backoff"] = 0.0
        if readmitted:
            self.metrics.inc("fleet_readmissions")
        return readmitted

    def mark_suspect(self, i):
        """Quarantine verdict from the integrity plane: breaker opened
        and made STICKY. Returns True when this call flipped it."""
        now = time.monotonic()
        with self._lock:
            s = self._state[i]
            flipped = not s["suspect"]
            s["suspect"] = True
            opened = not s["open"]
            s["open"] = True
            s["failures"] = max(s["failures"], self.breaker_k)
            if opened:
                s["opens"] += 1
                s["probe_backoff"] = self.probe_base_s
                s["next_probe"] = now + self._jitter(s["probe_backoff"])
        if flipped:
            self.metrics.inc("workers_quarantined")
        return flipped

    def clear_suspect(self, i):
        """Absolution (a fresh JOIN passed the known-answer challenge):
        drop the sticky flag and close the breaker."""
        with self._lock:
            s = self._state[i]
            s["suspect"] = False
            s["open"] = False
            s["failures"] = 0
            s["probe_backoff"] = 0.0

    def is_suspect(self, i):
        with self._lock:
            return self._state[i]["suspect"]

    def record_failure(self, i):
        """A failed call (reconnect retries exhausted). Returns True when
        this failure OPENED the breaker."""
        now = time.monotonic()
        with self._lock:
            s = self._state[i]
            s["failures"] += 1
            opened = not s["open"] and s["failures"] >= self.breaker_k
            if opened:
                s["open"] = True
                s["opens"] += 1
            if s["open"]:
                # failure while open (probe failed): back off the next probe
                s["probe_backoff"] = min(
                    self.probe_max_s,
                    (s["probe_backoff"] * 2) or self.probe_base_s)
                s["next_probe"] = now + self._jitter(s["probe_backoff"])
        if opened:
            self.metrics.inc("fleet_breaker_opens")
        return opened

    def mark_dead(self, i):
        """Authoritative death report (a direct probe just failed): open
        the breaker immediately, regardless of the consecutive count."""
        now = time.monotonic()
        with self._lock:
            s = self._state[i]
            opened = not s["open"]
            s["open"] = True
            s["failures"] = max(s["failures"], self.breaker_k)
            if opened:
                s["opens"] += 1
                s["probe_backoff"] = self.probe_base_s
                s["next_probe"] = now + self._jitter(s["probe_backoff"])
        if opened:
            self.metrics.inc("fleet_breaker_opens")
        return opened

    # -- routing decisions ----------------------------------------------------

    def usable(self, i):
        with self._lock:
            return not self._state[i]["open"]

    def usable_set(self):
        with self._lock:
            return [i for i, s in enumerate(self._state) if not s["open"]]

    def probe_due(self, i):
        """True at most once per probe window: the caller that gets True
        owns the half-open probe; the window is immediately pushed out —
        by the CURRENT backoff, since record_failure owns the exponential
        advance (granting must not double, or a failed probe cycle
        advances x4) — so concurrent callers don't dogpile a
        maybe-recovering worker."""
        now = time.monotonic()
        with self._lock:
            s = self._state[i]
            # suspects never get half-open probes: they answer probes
            # fine (alive, wrong), so probing can only waste a window
            if not s["open"] or s["suspect"] or now < s["next_probe"]:
                return False
            s["next_probe"] = now + self._jitter(
                s["probe_backoff"] or self.probe_base_s)
            return True

    def due_probes(self):
        return [i for i in range(len(self._state)) if self.probe_due(i)]

    def force_probe(self, i=None):
        """Make the next probe_due() True immediately (tests, an operator
        'I restarted it, re-admit now' path)."""
        with self._lock:
            for s in (self._state if i is None else [self._state[i]]):
                s["next_probe"] = 0.0

    def snapshot(self):
        with self._lock:
            return [dict(s) for s in self._state]
