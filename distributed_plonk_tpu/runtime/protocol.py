"""Wire protocol: frame tags + payload codecs.

The explicit replacement for the reference's capnp schema
(/root/reference/src/hello_world.capnp): the implemented subset maps to the
reference's live RPCs (init/varMsm/fft*); the 12 methods the reference
declared but never implemented (hello_world.capnp:26-44) are deliberately
absent — device-resident rounds make them unnecessary.

All integers little-endian. Field elements are 32-byte LE; G1 affine points
are x(48B LE) || y(48B LE) || inf(u8).
"""

import struct

import numpy as np

from ..constants import R_MOD
from . import native

# tags
PING = 1
INIT_BASES = 2     # u64 set_id, u64 n, then n * 97B points -> reply OK
                   # (workers hold MULTIPLE base sets keyed by id, so a
                   # healthy worker can adopt a dead worker's range)
MSM = 3            # u64 set_id, u64 count, count * 32B scalars
                   #                                   -> reply 97B point
NTT = 4            # u8 flags (1=inverse, 2=coset), u64 n, n * 32B elements
                   #                                   -> reply n * 32B
SHUTDOWN = 5
# --- cross-worker sharded 4-step FFT (the reference's distributed-FFT
# protocol, src/hello_world.capnp:19-23,48 / src/worker.rs:187-438, carried
# over the host fleet's TCP plane) ---
FFT_INIT = 6       # u64 id, u8 flags, u64 n/r/c, u64 rs/re/cs/ce -> OK
FFT1 = 7           # u64 id, u64 first_row, u64 count, count*r*32B -> OK
FFT2_PREPARE = 8   # u64 id -> OK once all peer exchanges are acknowledged
FFT_EXCHANGE = 9   # worker->worker: u64 id, u64 col_start, u64 col_count,
                   # u64 row_start, u64 row_count, then a contiguous
                   # (row_count x col_count) panel of 32B scalars -> OK
FFT2 = 10          # u64 id -> reply (ce-cs)*c_len*32B stage-2 rows + task GC
STATS = 11         # -> reply JSON {tag: count} served-request counters
HEALTH = 12        # -> reply JSON {uptime_s, served, fft_tasks, base_sets}:
                   # the liveness/re-admission probe (runtime/health.py) —
                   # cheaper than STATS to interpret, richer than PING
# --- proof service control plane (service/server.py) -------------------------
# Rides the exact same framed transport; payloads are JSON (control plane is
# cold — the hot data plane above keeps its binary codecs).
SUBMIT = 20        # JSON job spec -> OK + JSON {job_id, ...} | ERR + JSON
                   # {reason} (admission control rejects loudly, never queues
                   # past the configured depth)
STATUS = 21        # JSON {job_id} -> OK + JSON job status snapshot
RESULT = 22        # JSON {job_id} -> OK + [u32 hdr_len][hdr JSON][proof
                   # bytes] once DONE; ERR + JSON {reason, state} otherwise
METRICS = 23       # -> OK + JSON metrics snapshot (queue depth, wait/run
                   # histograms, per-round latency, throughput)
KILL_WORKER = 24   # fault injection (serve --chaos only): JSON {job_id |
                   # worker, at_round?} -> OK + JSON {worker}
WARMUP = 25        # JSON job spec (+ optional "aot": true) -> OK + JSON
                   # {shape_key, source: memory|disk|built, domain_size,
                   # warm_s, aot?}: pre-resolve a shape bucket's keys
                   # through the store tiers and (aot) precompile its
                   # prover stages, so later SUBMITs of the shape are warm
STORE_FETCH = 26   # JSON {key} -> OK + [u32 hdr][hdr JSON {key, digest,
                   # meta}][blob]: serve one artifact-store blob (bucket
                   # keys, prover checkpoint, SRS) to a peer/replacement
                   # host — cross-host warm start and resume become a
                   # network copy instead of a rebuild (store/remote.py
                   # re-verifies the digest client-side). Served by the
                   # proof service and by runtime workers given --store.
TRACE_DUMP = 27    # JSON {trace_id} -> OK + JSON tracer dump ({} when
                   # the worker holds no spans for that id): fetch-and-
                   # forget one trace's worker-side spans so the
                   # dispatcher can stitch them into the merged per-job
                   # timeline (trace.merge_traces, offset-corrected
                   # against the HEALTH clock sample)
# --- dynamic membership plane (runtime/membership.py) ------------------------
# Served by the dispatcher's MembershipServer (JOIN/LEAVE/ROSTER as
# queries) and by workers (ROSTER as a push). Control plane: JSON payloads.
JOIN = 28          # JSON {host, port, store?, phase?, stats?} -> OK + JSON
                   # {index, epoch, workers: ["h:p"...], stores: ["h:p"...]}
                   # — a starting worker announces itself and receives its
                   # fleet index + the epoch-numbered roster. A known
                   # (host, port) re-JOINs IN PLACE (same index: the
                   # supervisor-respawn path, re-admitted through the PR 6
                   # breaker machinery). phase="ready" is an idempotent
                   # update carrying warm-rejoin stats — no epoch bump.
LEAVE = 29         # JSON {index | host+port} -> OK + JSON {epoch}: declare
                   # a member permanently gone (supervisor flap cap, an
                   # operator decommission) — breaker opened, epoch bumped
ROSTER = 30        # to the membership server, empty payload: -> OK + JSON
                   # {epoch, workers, stores} (query);
                   # to a worker, JSON {epoch, workers}: adopt the pushed
                   # table iff epoch is newer -> OK + JSON {epoch} — how
                   # FFT2_PREPARE peer routing follows membership changes
STORE_LIST = 31    # JSON {prefix?} -> OK + JSON {keys}: enumerate store
                   # keys (manifest artifacts plus jaxcache:<relpath>
                   # pseudo-keys for persistent-compile-cache files) so a
                   # joining worker knows what to STORE_FETCH for its warm
                   # rejoin
# --- result-integrity plane (runtime/integrity.py) ---------------------------
EVAL = 32          # 32B point, u64 count, count * 32B coeffs -> reply 32B
                   # partial Horner evaluation sum_i c_i * point^i — the
                   # distributed round-4 evaluation chunk (the dispatcher
                   # scales by point^start and folds; duplicate-executed
                   # chunks cross-check workers against each other)
# --- fleet observability plane (obs/) ----------------------------------------
# Flag-safe, back-compatible like TRACE_DUMP: an old worker answers any of
# these with ERR "unknown tag" and the connection stays usable — scrapers
# degrade to an empty result, a prove is never harmed.
METRICS_FETCH = 33  # empty payload -> OK + JSON: the worker's FULL
                    # service.metrics.Metrics snapshot (counters/gauges/
                    # histograms incl. per-kernel gflops/MFU gauges) plus
                    # identity fields (index, epoch, backend, uptime_s,
                    # sdc_injected) — what the dispatcher/service fleet
                    # scraper aggregates into dpt_fleet_* series
LOG_FETCH = 34      # JSON {trace_id?, since_seq?, limit?} -> OK + JSON
                    # {events: [...], seq}: the worker's structured-log
                    # ring buffer (obs/log.py), optionally filtered to one
                    # trace id — how quarantines/replans/respawns become
                    # queryable events on the merged per-job timeline.
                    # Reads do NOT clear the ring (idempotent; the cap
                    # bounds memory), so since_seq gives tail -f semantics.
PROFILE = 35        # JSON {duration_ms?, kind?} -> OK + [u32 hdr][hdr JSON
                    # {format, ...}][blob]: arm an on-demand device/host
                    # profile capture on the worker for the window — the
                    # jax.profiler xplane capture (format "xplane-targz")
                    # on jax backends, an all-thread Python stack sampler
                    # (format "pystacks-json") otherwise. The caller stores
                    # the blob as a content-addressed profile:<id> artifact
                    # served at /profile/<id>.
# --- proof aggregation plane (aggregate.py, ISSUE 17) ------------------------
AGGREGATE = 36      # JSON {job_ids: [...]} -> OK + JSON {agg_id, members,
                    # kinds, store_key?, digest?, build_s}: fold N DONE
                    # jobs' proofs into one batch-KZG aggregate artifact
                    # (aggregate:<agg_id>, journaled like DONE) whose
                    # verification is ONE 2-pair pairing check regardless
                    # of N. ERR + JSON {reason, job_id?} when any named
                    # job is unknown or not DONE — an aggregate over a
                    # partial batch would silently weaken the client's
                    # "everything I submitted verified" claim.
AGG_FETCH = 37      # JSON {agg_id} -> OK + [u32 hdr][hdr JSON {agg_id,
                    # members, digest}][aggregate JSON blob]: serve a
                    # built aggregate artifact (from the store when the
                    # service has one, from the in-memory table
                    # otherwise; journal recovery restores both paths)
OK = 100
ERR = 101

# TRACED is a tag FLAG, not a tag: a sender that wants its trace context
# to ride a frame ORs it into the tag and prefixes the payload with
# [u16 ctx_len][ctx JSON {trace_id, parent_id?}] (wrap_traced). Receivers
# call strip_context() first, which passes flag-less frames through
# untouched — an old client's frames parse exactly as before, and a
# traced frame to an old receiver fails loudly (unknown tag), never
# silently misparses. Kept clear of the chaos injector's corruption bit
# (runtime/faults.py XORs 0x40000000).
TRACED = 0x10000

FR_BYTES = 32
FQ_BYTES = 48
POINT_BYTES = 2 * FQ_BYTES + 1

# tag value -> name, for span labels and diagnostics (flag bits and
# non-tag constants excluded: tags live in [1, 101])
TAG_NAMES = {value: name for name, value in list(globals().items())
             if name.isupper() and isinstance(value, int)
             and 0 < value <= ERR
             and name not in ("FR_BYTES", "FQ_BYTES", "POINT_BYTES")}


def tag_name(tag):
    return TAG_NAMES.get(tag & ~TRACED, str(tag))


# --- trace-context framing ---------------------------------------------------

def wrap_traced(tag, payload, ctx):
    """(tag | TRACED, context-prefixed payload) — attach a trace context
    (trace.Tracer.context() dict) to one frame. No-op when ctx is None."""
    if not ctx:
        return tag, payload
    raw = encode_json(ctx)
    return tag | TRACED, struct.pack("<H", len(raw)) + raw + payload


def strip_context(tag, payload):
    """(base_tag, ctx | None, payload) — inverse of wrap_traced. Frames
    without the TRACED flag (every pre-trace client) pass through
    untouched, so the framing stays back-compatible."""
    if not tag & TRACED:
        return tag, None, payload
    (clen,) = struct.unpack_from("<H", payload, 0)
    return tag & ~TRACED, decode_json(payload[2:2 + clen]), payload[2 + clen:]


def encode_scalars(scalars):
    return b"".join(int(s % R_MOD).to_bytes(FR_BYTES, "little") for s in scalars)


def decode_scalars(raw):
    n = len(raw) // FR_BYTES
    return [int.from_bytes(raw[i * FR_BYTES:(i + 1) * FR_BYTES], "little")
            for i in range(n)]


# --- bulk limb-matrix codecs (hot data plane) --------------------------------
# Same wire bytes as encode_scalars/decode_scalars (concatenated 32B LE
# elements), but host-side data stays a (16, n) uint32 limb matrix converted
# by the native C++ codec in ONE call — no per-int Python serialization
# (round-2 weakness #8: the pure-Python plane was the 2^18 bottleneck; the
# reference's analog is its zero-copy transmute, src/utils.rs:27-43).

def encode_scalar_matrix(limbs):
    """(16, n) uint32 16-bit-limb matrix -> wire bytes."""
    return native.limbs_to_bytes(np.ascontiguousarray(limbs))


def decode_scalar_matrix(raw):
    """Wire bytes -> (16, n) uint32 limb matrix."""
    n = len(raw) // FR_BYTES
    return native.bytes_to_limbs(raw, n, FR_BYTES)


def ints_to_matrix(scalars):
    """Host int list -> (16, n) limb matrix (one C-level pass)."""
    from ..backend.limbs import ints_to_limbs
    return ints_to_limbs([s % R_MOD for s in scalars], FR_BYTES // 2)


def matrix_to_ints(limbs):
    """(16, n) limb matrix -> host int list (one C-level pass)."""
    from ..backend.limbs import limbs_to_ints
    return limbs_to_ints(limbs)


def encode_point(p):
    if p is None:
        return bytes(POINT_BYTES - 1) + b"\x01"
    return (p[0].to_bytes(FQ_BYTES, "little")
            + p[1].to_bytes(FQ_BYTES, "little") + b"\x00")


def decode_point(raw):
    assert len(raw) == POINT_BYTES
    if raw[-1]:
        return None
    return (int.from_bytes(raw[:FQ_BYTES], "little"),
            int.from_bytes(raw[FQ_BYTES:2 * FQ_BYTES], "little"))


def encode_points(points):
    return struct.pack("<Q", len(points)) + b"".join(
        encode_point(p) for p in points)


def decode_points(raw, off=0):
    (n,) = struct.unpack_from("<Q", raw, off)
    out = []
    off += 8
    for _ in range(n):
        out.append(decode_point(raw[off:off + POINT_BYTES]))
        off += POINT_BYTES
    return out


def encode_init_bases(set_id, points):
    return struct.pack("<Q", set_id) + encode_points(points)


def decode_init_bases(raw):
    (set_id,) = struct.unpack_from("<Q", raw, 0)
    return set_id, decode_points(raw, off=8)


def encode_msm_request(set_id, scalars):
    return struct.pack("<QQ", set_id, len(scalars)) + encode_scalars(scalars)


def decode_msm_request(raw):
    set_id, n = struct.unpack_from("<QQ", raw, 0)
    return set_id, decode_scalars(raw[16:16 + n * FR_BYTES])


def encode_fft_init(task_id, inverse, coset, n, r, c, rs, re, col_ranges,
                    epoch=0, integrity=False):
    """col_ranges: every worker's stage-2 row range [(cs, ce)] — each worker
    needs the full table to route its peer exchange. `epoch` is the
    sender's membership-roster version (0 = no membership plane): a worker
    whose roster moved past it rejects the frame as stale, forcing the
    dispatcher to replan on the CURRENT fleet width. `integrity` announces
    that the dispatcher's integrity plane is armed: the worker then
    retains its raw FFT1 input panels so the FFT2 check point can get an
    input-side partial (a plane-off dispatcher keeps the legacy zero
    extra memory)."""
    flags = (1 if inverse else 0) | (2 if coset else 0)
    head = struct.pack("<QBQQQQQQ", task_id, flags, n, r, c, rs, re,
                       len(col_ranges))
    body = b"".join(struct.pack("<QQ", cs, ce) for cs, ce in col_ranges)
    return head + body + struct.pack("<QB", epoch, 1 if integrity else 0)


def decode_fft_init(raw):
    task_id, flags, n, r, c, rs, re, k = struct.unpack_from("<QBQQQQQQ", raw, 0)
    off = struct.calcsize("<QBQQQQQQ")
    col_ranges = [struct.unpack_from("<QQ", raw, off + 16 * i) for i in range(k)]
    off += 16 * k
    # trailing epoch + integrity flag are optional on the wire: frames
    # from older senders decode as epoch 0 / integrity off
    epoch = struct.unpack_from("<Q", raw, off)[0] if len(raw) >= off + 8 else 0
    integrity = raw[off + 8] != 0 if len(raw) >= off + 9 else False
    return (task_id, bool(flags & 1), bool(flags & 2), n, r, c, rs, re,
            col_ranges, epoch, integrity)


def encode_fft1_matrix(task_id, first_row, panel):
    """panel: (16, count, row_len) limb array; wire format: u64 id, u64
    first_row, u64 count, then count rows of row_len 32B LE scalars."""
    count = panel.shape[1]
    return (struct.pack("<QQQ", task_id, first_row, count)
            + encode_scalar_matrix(panel.reshape(16, count * panel.shape[2])))


def decode_fft1_matrix(raw):
    """-> (task_id, first_row, (16, count, row_len) limbs)"""
    task_id, first_row, count = struct.unpack_from("<QQQ", raw, 0)
    m = decode_scalar_matrix(raw[24:])
    row_len = m.shape[1] // count if count else 0
    return task_id, first_row, m.reshape(16, count, row_len)


def encode_fft_exchange(task_id, col_start, col_count, row_start, panel):
    """panel: (16, row_count, col_count) uint32 limb array — the sender's
    CONTIGUOUS stage-1 row block sliced to one peer's column range, shipped
    as one limb-matrix codec call (the per-row int-list format of round 2
    was the fleet's serialization bottleneck)."""
    row_count = panel.shape[1]
    head = struct.pack("<QQQQQ", task_id, col_start, col_count, row_start,
                       row_count)
    return head + encode_scalar_matrix(panel.reshape(16, row_count * col_count))


def decode_fft_exchange(raw):
    """-> (task_id, col_start, col_count, row_start, (16, rows, cols) limbs)"""
    task_id, col_start, col_count, row_start, row_count = \
        struct.unpack_from("<QQQQQ", raw, 0)
    m = decode_scalar_matrix(raw[40:])
    return (task_id, col_start, col_count, row_start,
            m.reshape(16, row_count, col_count))


# --- result-integrity codecs (runtime/integrity.py) --------------------------

def encode_eval_request(point, values):
    """EVAL: evaluate sum_i values[i] * point^i on the worker."""
    return (int(point % R_MOD).to_bytes(FR_BYTES, "little")
            + struct.pack("<Q", len(values)) + encode_scalars(values))


def decode_eval_request(raw):
    point = int.from_bytes(raw[:FR_BYTES], "little")
    (n,) = struct.unpack_from("<Q", raw, FR_BYTES)
    off = FR_BYTES + 8
    return point, decode_scalars(raw[off:off + n * FR_BYTES])


def encode_scalar(v):
    return int(v % R_MOD).to_bytes(FR_BYTES, "little")


def decode_scalar(raw):
    return int.from_bytes(raw[:FR_BYTES], "little")


def encode_fft2_request(task_id, point=None):
    """FFT2 fetch, optionally carrying the integrity check point: when
    `point` rides the frame the worker piggybacks its (input-side,
    output-side) partial power sums at that point on the reply. Workers
    that predate the integrity plane ignore the trailing bytes (the
    decoder unpacks only the leading u64), so the request stays
    back-compatible."""
    head = struct.pack("<Q", task_id)
    if point is None:
        return head
    return head + encode_scalar(point)


def decode_fft2_request(raw):
    (task_id,) = struct.unpack_from("<Q", raw, 0)
    point = None
    if len(raw) >= 8 + FR_BYTES:
        point = decode_scalar(raw[8:8 + FR_BYTES])
    return task_id, point


_FFT2_PARTIAL_FLAG = b"\x01"


def encode_fft2_partials(a, b, panel_bytes):
    """Reply = flag byte + 32B input-side partial + 32B output-side
    partial + the panel. The panel alone is a multiple of 32 bytes, so
    receivers distinguish the two layouts by `len % 32 == 1` — a reply
    from an integrity-unaware worker (panel only) still parses."""
    return _FFT2_PARTIAL_FLAG + encode_scalar(a) + encode_scalar(b) \
        + panel_bytes


def split_fft2_reply(raw):
    """((input_partial, output_partial) | None, panel_bytes)."""
    if len(raw) % FR_BYTES == 1 and raw[:1] == _FFT2_PARTIAL_FLAG:
        a = decode_scalar(raw[1:1 + FR_BYTES])
        b = decode_scalar(raw[1 + FR_BYTES:1 + 2 * FR_BYTES])
        return (a, b), raw[1 + 2 * FR_BYTES:]
    return None, raw


# --- proof service codecs ----------------------------------------------------

def encode_json(obj):
    import json
    return json.dumps(obj, separators=(",", ":")).encode()


def decode_json(raw):
    import json
    return json.loads(raw.decode()) if raw else {}


def encode_result(header, blob):
    """RESULT reply: [u32 header_len][header JSON][opaque proof bytes]."""
    h = encode_json(header)
    return struct.pack("<I", len(h)) + h + blob


def decode_result(raw):
    (hlen,) = struct.unpack_from("<I", raw, 0)
    return decode_json(raw[4:4 + hlen]), raw[4 + hlen:]


def encode_ntt_request(values, inverse, coset):
    flags = (1 if inverse else 0) | (2 if coset else 0)
    return (struct.pack("<BQ", flags, len(values))
            + encode_scalars(values))


def decode_ntt_request(raw):
    flags, n = struct.unpack_from("<BQ", raw, 0)
    values = decode_scalars(raw[9:9 + n * FR_BYTES])
    return values, bool(flags & 1), bool(flags & 2)
