"""Wire protocol: frame tags + payload codecs.

The explicit replacement for the reference's capnp schema
(/root/reference/src/hello_world.capnp): the implemented subset maps to the
reference's live RPCs (init/varMsm/fft*); the 12 methods the reference
declared but never implemented (hello_world.capnp:26-44) are deliberately
absent — device-resident rounds make them unnecessary.

All integers little-endian. Field elements are 32-byte LE; G1 affine points
are x(48B LE) || y(48B LE) || inf(u8).
"""

import struct

from ..constants import R_MOD

# tags
PING = 1
INIT_BASES = 2     # u64 n, then n * 97B points       -> reply OK
MSM = 3            # u64 count, count * 32B scalars    -> reply 97B point
NTT = 4            # u8 flags (1=inverse, 2=coset), u64 n, n * 32B elements
                   #                                   -> reply n * 32B
SHUTDOWN = 5
OK = 100
ERR = 101

FR_BYTES = 32
FQ_BYTES = 48
POINT_BYTES = 2 * FQ_BYTES + 1


def encode_scalars(scalars):
    return b"".join(int(s % R_MOD).to_bytes(FR_BYTES, "little") for s in scalars)


def decode_scalars(raw):
    n = len(raw) // FR_BYTES
    return [int.from_bytes(raw[i * FR_BYTES:(i + 1) * FR_BYTES], "little")
            for i in range(n)]


def encode_point(p):
    if p is None:
        return bytes(POINT_BYTES - 1) + b"\x01"
    return (p[0].to_bytes(FQ_BYTES, "little")
            + p[1].to_bytes(FQ_BYTES, "little") + b"\x00")


def decode_point(raw):
    assert len(raw) == POINT_BYTES
    if raw[-1]:
        return None
    return (int.from_bytes(raw[:FQ_BYTES], "little"),
            int.from_bytes(raw[FQ_BYTES:2 * FQ_BYTES], "little"))


def encode_points(points):
    return struct.pack("<Q", len(points)) + b"".join(
        encode_point(p) for p in points)


def decode_points(raw):
    (n,) = struct.unpack_from("<Q", raw, 0)
    out = []
    off = 8
    for _ in range(n):
        out.append(decode_point(raw[off:off + POINT_BYTES]))
        off += POINT_BYTES
    return out


def encode_ntt_request(values, inverse, coset):
    flags = (1 if inverse else 0) | (2 if coset else 0)
    return (struct.pack("<BQ", flags, len(values))
            + encode_scalars(values))


def decode_ntt_request(raw):
    flags, n = struct.unpack_from("<BQ", raw, 0)
    values = decode_scalars(raw[9:9 + n * FR_BYTES])
    return values, bool(flags & 1), bool(flags & 2)
