"""Wire protocol: frame tags + payload codecs.

The explicit replacement for the reference's capnp schema
(/root/reference/src/hello_world.capnp): the implemented subset maps to the
reference's live RPCs (init/varMsm/fft*); the 12 methods the reference
declared but never implemented (hello_world.capnp:26-44) are deliberately
absent — device-resident rounds make them unnecessary.

All integers little-endian. Field elements are 32-byte LE; G1 affine points
are x(48B LE) || y(48B LE) || inf(u8).
"""

import struct

from ..constants import R_MOD

# tags
PING = 1
INIT_BASES = 2     # u64 n, then n * 97B points       -> reply OK
MSM = 3            # u64 count, count * 32B scalars    -> reply 97B point
NTT = 4            # u8 flags (1=inverse, 2=coset), u64 n, n * 32B elements
                   #                                   -> reply n * 32B
SHUTDOWN = 5
# --- cross-worker sharded 4-step FFT (the reference's distributed-FFT
# protocol, src/hello_world.capnp:19-23,48 / src/worker.rs:187-438, carried
# over the host fleet's TCP plane) ---
FFT_INIT = 6       # u64 id, u8 flags, u64 n/r/c, u64 rs/re/cs/ce -> OK
FFT1 = 7           # u64 id, u64 first_row, u64 count, count*r*32B -> OK
FFT2_PREPARE = 8   # u64 id -> OK once all peer exchanges are acknowledged
FFT_EXCHANGE = 9   # worker->worker: u64 id, u64 col_start, u64 col_count,
                   # u64 n_rows, then per row: u64 j2, col_count*32B -> OK
FFT2 = 10          # u64 id -> reply (ce-cs)*c_len*32B stage-2 rows + task GC
STATS = 11         # -> reply JSON {tag: count} served-request counters
OK = 100
ERR = 101

FR_BYTES = 32
FQ_BYTES = 48
POINT_BYTES = 2 * FQ_BYTES + 1


def encode_scalars(scalars):
    return b"".join(int(s % R_MOD).to_bytes(FR_BYTES, "little") for s in scalars)


def decode_scalars(raw):
    n = len(raw) // FR_BYTES
    return [int.from_bytes(raw[i * FR_BYTES:(i + 1) * FR_BYTES], "little")
            for i in range(n)]


def encode_point(p):
    if p is None:
        return bytes(POINT_BYTES - 1) + b"\x01"
    return (p[0].to_bytes(FQ_BYTES, "little")
            + p[1].to_bytes(FQ_BYTES, "little") + b"\x00")


def decode_point(raw):
    assert len(raw) == POINT_BYTES
    if raw[-1]:
        return None
    return (int.from_bytes(raw[:FQ_BYTES], "little"),
            int.from_bytes(raw[FQ_BYTES:2 * FQ_BYTES], "little"))


def encode_points(points):
    return struct.pack("<Q", len(points)) + b"".join(
        encode_point(p) for p in points)


def decode_points(raw):
    (n,) = struct.unpack_from("<Q", raw, 0)
    out = []
    off = 8
    for _ in range(n):
        out.append(decode_point(raw[off:off + POINT_BYTES]))
        off += POINT_BYTES
    return out


def encode_fft_init(task_id, inverse, coset, n, r, c, rs, re, col_ranges):
    """col_ranges: every worker's stage-2 row range [(cs, ce)] — each worker
    needs the full table to route its peer exchange."""
    flags = (1 if inverse else 0) | (2 if coset else 0)
    head = struct.pack("<QBQQQQQQ", task_id, flags, n, r, c, rs, re,
                       len(col_ranges))
    return head + b"".join(struct.pack("<QQ", cs, ce) for cs, ce in col_ranges)


def decode_fft_init(raw):
    task_id, flags, n, r, c, rs, re, k = struct.unpack_from("<QBQQQQQQ", raw, 0)
    off = struct.calcsize("<QBQQQQQQ")
    col_ranges = [struct.unpack_from("<QQ", raw, off + 16 * i) for i in range(k)]
    return (task_id, bool(flags & 1), bool(flags & 2), n, r, c, rs, re,
            col_ranges)


def encode_fft1(task_id, first_row, rows):
    return (struct.pack("<QQQ", task_id, first_row, len(rows))
            + b"".join(encode_scalars(r) for r in rows))


def decode_fft1(raw):
    task_id, first_row, count = struct.unpack_from("<QQQ", raw, 0)
    body = raw[24:]
    row_len = len(body) // count // FR_BYTES if count else 0
    rows = [decode_scalars(body[i * row_len * FR_BYTES:(i + 1) * row_len * FR_BYTES])
            for i in range(count)]
    return task_id, first_row, rows


def encode_fft_exchange(task_id, col_start, col_count, entries):
    """entries: [(j2, values[col_count])]"""
    head = struct.pack("<QQQQ", task_id, col_start, col_count, len(entries))
    body = b"".join(struct.pack("<Q", j2) + encode_scalars(vals)
                    for j2, vals in entries)
    return head + body


def decode_fft_exchange(raw):
    task_id, col_start, col_count, n_rows = struct.unpack_from("<QQQQ", raw, 0)
    off = 32
    stride = 8 + col_count * FR_BYTES
    entries = []
    for _ in range(n_rows):
        (j2,) = struct.unpack_from("<Q", raw, off)
        vals = decode_scalars(raw[off + 8:off + stride])
        entries.append((j2, vals))
        off += stride
    return task_id, col_start, col_count, entries


def encode_ntt_request(values, inverse, coset):
    flags = (1 if inverse else 0) | (2 if coset else 0)
    return (struct.pack("<BQ", flags, len(values))
            + encode_scalars(values))


def decode_ntt_request(raw):
    flags, n = struct.unpack_from("<BQ", raw, 0)
    values = decode_scalars(raw[9:9 + n * FR_BYTES])
    return values, bool(flags & 1), bool(flags & 2)
