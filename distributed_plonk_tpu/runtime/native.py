"""ctypes binding + on-demand build of the native library.

Replaces the reference's build.rs capnp codegen step
(/root/reference/build.rs:1-2): the native component is compiled once per
source hash into .native_build/ and memoized.
"""

import ctypes
import hashlib
import os
import subprocess

import numpy as np

_REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), "..", ".."))
_SRC = os.path.join(_REPO, "native", "dpt_native.cpp")
_BUILD_DIR = os.path.join(_REPO, ".native_build")

_lib = None


def build_native():
    """Compile (if needed) and return the path to the shared library."""
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_BUILD_DIR, f"dpt_native_{digest}.so")
    if not os.path.exists(so_path):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        # pid-unique tmp + atomic rename: a fleet of worker subprocesses
        # all hitting a fresh source hash build concurrently; a SHARED
        # tmp path lets one racer rename the file out from under another
        tmp = f"{so_path}.tmp.{os.getpid()}"
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True)
        os.replace(tmp, so_path)
    return so_path


def lib():
    global _lib
    if _lib is None:
        _lib = ctypes.CDLL(build_native())
        L = _lib
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        L.le_bytes_to_limbs.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64, u32p]
        L.limbs_to_le_bytes.argtypes = [u32p, ctypes.c_uint64, ctypes.c_uint64, u8p]
        L.limbs_to_le_bytes.restype = ctypes.c_int
        L.transpose_u32.argtypes = [u32p, ctypes.c_uint64, ctypes.c_uint64, u32p]
        L.dpt_listen.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        L.dpt_accept.argtypes = [ctypes.c_int]
        L.dpt_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_int]
        L.dpt_send.argtypes = [ctypes.c_int, ctypes.c_uint32, u8p, ctypes.c_uint64]
        L.dpt_recv_header.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32)]
        L.dpt_recv_payload.argtypes = [ctypes.c_int, u8p, ctypes.c_uint64]
        L.dpt_set_timeout.argtypes = [ctypes.c_int, ctypes.c_int]
        L.dpt_close.argtypes = [ctypes.c_int]
    return _lib


def _u8(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _u32(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


# --- data plane --------------------------------------------------------------

def bytes_to_limbs(raw, n, elem_bytes):
    """Concatenated LE elements -> (elem_bytes/2, n) uint32 limb matrix."""
    inp = np.frombuffer(raw, dtype=np.uint8)
    assert inp.size == n * elem_bytes
    out = np.empty((elem_bytes // 2, n), dtype=np.uint32)
    lib().le_bytes_to_limbs(_u8(inp), n, elem_bytes, _u32(out))
    return out


def limbs_to_bytes(limbs):
    """(n_limbs, n) uint32 limb matrix -> concatenated LE elements."""
    limbs = np.ascontiguousarray(limbs, dtype=np.uint32)
    n_limbs, n = limbs.shape
    out = np.empty(n * n_limbs * 2, dtype=np.uint8)
    rc = lib().limbs_to_le_bytes(_u32(limbs), n, n_limbs * 2, _u8(out))
    if rc != 0:
        raise ValueError("unreduced limb at native boundary")
    return out.tobytes()


def transpose(arr):
    """Blocked transpose of a 2-D uint32 array."""
    arr = np.ascontiguousarray(arr, dtype=np.uint32)
    rows, cols = arr.shape
    out = np.empty((cols, rows), dtype=np.uint32)
    lib().transpose_u32(_u32(arr), rows, cols, _u32(out))
    return out


# --- transport ---------------------------------------------------------------

class Conn:
    """One framed TCP connection ([u64 len][u32 tag][payload])."""

    def __init__(self, fd):
        assert fd >= 0
        self.fd = fd

    def send(self, tag, payload=b""):
        buf = np.frombuffer(payload, dtype=np.uint8) if payload else \
            np.empty(0, dtype=np.uint8)
        rc = lib().dpt_send(self.fd, tag, _u8(buf), len(payload))
        if rc != 0:
            raise ConnectionError("send failed")

    def recv(self):
        length = ctypes.c_uint64()
        tag = ctypes.c_uint32()
        if lib().dpt_recv_header(self.fd, ctypes.byref(length),
                                 ctypes.byref(tag)) != 0:
            raise ConnectionError("recv header failed")
        buf = np.empty(length.value, dtype=np.uint8)
        if length.value and lib().dpt_recv_payload(self.fd, _u8(buf),
                                                   length.value) != 0:
            raise ConnectionError("recv payload failed")
        return tag.value, buf.tobytes()

    def set_timeout(self, ms):
        """Socket send/recv timeout. A timeout mid-frame desynchronizes the
        stream, so callers must reconnect after one fires (WorkerHandle
        does)."""
        if lib().dpt_set_timeout(self.fd, int(ms)) != 0:
            raise OSError("set_timeout failed")

    def close(self):
        if self.fd >= 0:
            lib().dpt_close(self.fd)
            self.fd = -1


class Listener:
    def __init__(self, host, port, backlog=16):
        self.fd = lib().dpt_listen(host.encode(), port, backlog)
        if self.fd < 0:
            raise OSError(f"cannot listen on {host}:{port}")

    def accept(self):
        return Conn(lib().dpt_accept(self.fd))

    def close(self):
        if self.fd >= 0:
            lib().dpt_close(self.fd)
            self.fd = -1


def listener_port(listener):
    """Actual bound port of a Listener (needed when it bound port 0 for
    an ephemeral port — the membership JOIN flow announces it)."""
    import os
    import socket
    s = socket.socket(fileno=os.dup(listener.fd))
    try:
        return s.getsockname()[1]
    finally:
        s.close()


def connect(host, port, timeout_ms=0):
    """timeout_ms bounds the CONNECT itself (0 = blocking); I/O timeouts
    are set separately via Conn.set_timeout after the dial succeeds."""
    fd = lib().dpt_connect(host.encode(), port, timeout_ms)
    if fd < 0:
        raise ConnectionError(f"cannot connect to {host}:{port}")
    return Conn(fd)
