"""Host runtime: native data plane + multi-host control plane.

The TPU-native replacement for the reference's worker/dispatcher runtime
(SURVEY.md L1-L2): a C++ data-plane/transport library (native/dpt_native.cpp)
loaded via ctypes, a network config, a worker daemon, and a dispatcher
client. Intra-pod compute never touches this path (XLA collectives over
ICI); this layer carries the host-level control plane and DCN bulk data,
like the reference's capnp plane did for everything.
"""
