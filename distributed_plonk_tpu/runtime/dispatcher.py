"""Dispatcher client: drives a worker fleet over the native transport.

The analog of the reference's dispatcher client library
(/root/reference/src/dispatcher.rs:29-175) + the v2 distributed compute
entry points (`Prover::fft` dispatcher2.rs:731-787, `commit_polynomial`
dispatcher2.rs:834-893), with the sharding convention fixed: every worker
receives exactly the base chunk its scalar range covers (the reference
mixed v1 full-broadcast with v2 chunking and indexed out of bounds —
SURVEY.md §2.3.1).
"""

import concurrent.futures as futures
import os
import random
import struct
import threading

import numpy as np

from . import native, protocol
from .. import curve as C
from ..backend.python_backend import PythonBackend


def _split_rc(n):
    """n = r*c with r = 2^floor(log2(n)/2) (the reference's domain split,
    /root/reference/src/worker.rs:142-155)."""
    log_n = n.bit_length() - 1
    r = 1 << (log_n // 2)
    return r, n // r


class _Failure:
    def __init__(self, err):
        self.err = err


def _try(fn, arg):
    """Capture a worker failure as a value so a pool.map survives it."""
    try:
        return fn(arg)
    except Exception as e:
        return _Failure(e)


class WorkerHandle:
    """One framed connection to a worker, with a per-call timeout and one
    reconnect-retry — the failure handling the reference never had (every
    RPC there is .unwrap(), SURVEY.md §5: a worker crash hangs the prove).

    A timeout mid-frame desynchronizes the stream, so recovery is always
    reconnect-then-retry, never resend on the same socket. Retried requests
    are idempotent at the worker (MSM/NTT are pure; FFT1/FFT_EXCHANGE
    overwrite the same slots; FFT2 replays its cached reply instead of
    deleting the task — completed tasks are GC'd by age)."""

    # 0 = block forever; FFT2 on a python-backend worker can take minutes
    TIMEOUT_MS = int(os.environ.get("DPT_CALL_TIMEOUT_MS", "600000"))

    def __init__(self, host, port):
        self.host, self.port = host, port
        self.conn = self._connect()
        # one in-flight request per connection: frames are not interleavable
        self._lock = threading.Lock()

    def _connect(self):
        conn = native.connect(self.host, self.port)
        if self.TIMEOUT_MS:
            conn.set_timeout(self.TIMEOUT_MS)
        return conn

    def call(self, tag, payload=b""):
        with self._lock:
            try:
                self.conn.send(tag, payload)
                rtag, rpayload = self.conn.recv()
            except (ConnectionError, OSError):
                self.conn.close()
                self.conn = self._connect()  # one retry on a fresh stream
                self.conn.send(tag, payload)
                rtag, rpayload = self.conn.recv()
        if rtag != protocol.OK:
            raise RuntimeError(f"worker error: {rpayload!r}")
        return rpayload

    def close(self):
        self.conn.close()


class Dispatcher:
    """Connections to every worker + distributed MSM / NTT offload."""

    def __init__(self, config):
        self.workers = [WorkerHandle(h, p) for h, p in config.workers]
        self.pool = futures.ThreadPoolExecutor(max_workers=len(self.workers))
        self._ranges = None
        self._adopted = {}  # base-range i -> worker j that adopted it

    def ping(self):
        for w in self.workers:
            w.call(protocol.PING)

    def init_bases(self, bases):
        """Range-shard the SRS: worker i holds bases[start_i:end_i]
        (contiguous split, like MsmWorkload ranges) under set id i. The
        full base list is retained host-side so a dead worker's range can
        be re-provisioned onto a healthy worker mid-prove."""
        n = len(bases)
        k = len(self.workers)
        bounds = [n * i // k for i in range(k + 1)]
        self._ranges = list(zip(bounds[:-1], bounds[1:]))
        self._bases = bases
        self._adopted = {}
        # a worker that is dead at provisioning time is tolerated: its
        # range stays unowned and the first msm() adopts it onto a healthy
        # worker through the same lazy-recovery path as a mid-prove death
        results = self.pool.map(
            lambda iw: _try(
                lambda iw: iw[1].call(protocol.INIT_BASES,
                                      protocol.encode_init_bases(
                                          iw[0],
                                          bases[self._ranges[iw[0]][0]:
                                                self._ranges[iw[0]][1]])),
                iw),
            enumerate(self.workers))
        if all(isinstance(r, _Failure) for r in results):
            raise RuntimeError("no worker accepted its base range")

    def msm(self, scalars):
        """Distributed MSM with elastic recovery: scatter scalar ranges,
        fold partial G1 sums on the host (reference dispatcher2.rs:888-890
        — where every worker failure is an unwrap panic, src/worker.rs:303;
        here a dead worker's range is re-provisioned onto a healthy worker
        and recomputed)."""
        assert self._ranges is not None, "init_bases first"

        def part(i):
            start, end = self._ranges[i]
            chunk = scalars[start:end]
            if not chunk:
                return None
            # an adopted range routes straight to its new owner — no
            # re-dialing the dead worker, no re-upload
            w = self.workers[self._adopted.get(i, i)]
            raw = w.call(protocol.MSM,
                         protocol.encode_msm_request(i, chunk))
            return protocol.decode_point(raw)

        total = None
        failed = []
        for i, res in enumerate(self.pool.map(
                lambda i: _try(part, i), range(len(self.workers)))):
            if isinstance(res, _Failure):
                failed.append(i)
            else:
                total = C.g1_add_affine(total, res)
        if failed:
            # recoveries run concurrently; _recover_msm spreads adoptions
            # across the fleet starting at dead_i + 1
            for p in self.pool.map(
                    lambda i: self._recover_msm(i, scalars), failed):
                total = C.g1_add_affine(total, p)
        return total

    def _recover_msm(self, dead_i, scalars):
        """Re-provision range dead_i's bases onto a healthy worker (set id
        unchanged — ids are ranges, not workers), recompute its part, and
        REMEMBER the adoption so later msm() calls route directly."""
        start, end = self._ranges[dead_i]
        chunk = scalars[start:end]
        if not chunk:
            return None
        k = len(self.workers)
        failed_owner = self._adopted.get(dead_i, dead_i)
        last_err = None
        for off in range(1, k + 1):
            j = (dead_i + off) % k
            if j == failed_owner:
                continue
            w = self.workers[j]
            try:
                w.call(protocol.INIT_BASES, protocol.encode_init_bases(
                    dead_i, self._bases[start:end]))
                raw = w.call(protocol.MSM,
                             protocol.encode_msm_request(dead_i, chunk))
                self._adopted[dead_i] = j
                return protocol.decode_point(raw)
            except Exception as e:  # try the next healthy worker
                last_err = e
        raise RuntimeError(
            f"no healthy worker could adopt MSM range {dead_i}") from last_err

    def ntt(self, values, inverse=False, coset=False, worker=0):
        """Offload one whole NTT to a worker (per-polynomial task
        parallelism, reference §2.3.3). NTTs are stateless, so a dead
        worker is simply routed around: every other worker is tried before
        giving up."""
        k = len(self.workers)
        payload = protocol.encode_ntt_request(values, inverse, coset)
        last_err = None
        for off in range(k):
            try:
                raw = self.workers[(worker + off) % k].call(
                    protocol.NTT, payload)
                return protocol.decode_scalars(raw)
            except Exception as e:
                last_err = e
        raise RuntimeError("no worker could serve the NTT") from last_err

    def ntt_many(self, jobs):
        """Round-robin a batch of NTT jobs [(values, inverse, coset), ...]
        across the fleet concurrently (the join_all pattern,
        reference dispatcher2.rs:294-321)."""
        return list(self.pool.map(
            lambda ij: self.ntt(ij[1][0], ij[1][1], ij[1][2], worker=ij[0]),
            enumerate(jobs)))

    def fft_dist(self, values, inverse=False, coset=False):
        """ONE cross-worker sharded 4-step (i)(coset)FFT — the reference's
        hot protocol (Prover::fft, dispatcher2.rs:731-787): stage-1 rows
        scattered block-wise, direct worker<->worker all-to-all, stage-2
        columns gathered. len(values) must be a power of two.

        Host data plane is a (16, n) numpy limb matrix end to end: the
        row/column restrides are numpy views and every wire payload is one
        bulk codec call (the per-int Python path was round-2 weakness #8;
        the reference's analog is ip_transpose around scatter/gather,
        src/dispatcher.rs:305,332)."""
        n = len(values)
        assert n >= 4 and n & (n - 1) == 0, n
        r, c = _split_rc(n)
        k = len(self.workers)
        task_id = random.getrandbits(63)
        row_bounds = [c * i // k for i in range(k + 1)]
        col_ranges = [(r * i // k, r * (i + 1) // k) for i in range(k)]

        # (16, c, r): axis 1 = row index j2 (stride c in the flat poly)
        vm = protocol.ints_to_matrix(values).reshape(16, r, c)
        rows_mat = vm.transpose(0, 2, 1)  # [16, j2, position-in-row]

        list(self.pool.map(
            lambda i: self.workers[i].call(
                protocol.FFT_INIT, protocol.encode_fft_init(
                    task_id, inverse, coset, n, r, c,
                    row_bounds[i], row_bounds[i + 1], col_ranges)),
            range(k)))

        def scatter(i):
            rs, re = row_bounds[i], row_bounds[i + 1]
            if re == rs:
                return
            panel = np.ascontiguousarray(rows_mat[:, rs:re, :])
            self.workers[i].call(
                protocol.FFT1, protocol.encode_fft1_matrix(task_id, rs, panel))

        list(self.pool.map(scatter, range(k)))

        # trigger the all-to-all; each worker's OK implies its slices landed
        list(self.pool.map(
            lambda i: self.workers[i].call(
                protocol.FFT2_PREPARE, struct.pack("<Q", task_id)),
            range(k)))

        def gather(i):
            return protocol.decode_scalar_matrix(self.workers[i].call(
                protocol.FFT2, struct.pack("<Q", task_id)))

        out = np.empty((16, r, c), dtype=np.uint32)  # [16, k1, k2]
        for i, flat in enumerate(self.pool.map(gather, range(k))):
            cs, ce = col_ranges[i]
            if ce > cs:
                out[:, cs:ce, :] = flat.reshape(16, ce - cs, c)
        # result index is k1 + r*k2 -> transpose to [k2, k1] before flatten
        return protocol.matrix_to_ints(
            np.ascontiguousarray(out.transpose(0, 2, 1)).reshape(16, n))

    def stats(self):
        """Per-worker served-request counters {tag: count}."""
        import json
        return [json.loads(w.call(protocol.STATS).decode())
                for w in self.workers]

    def shutdown(self):
        for w in self.workers:
            try:
                w.call(protocol.SHUTDOWN)
            except Exception:
                pass
            w.close()


class RemoteBackend(PythonBackend):
    """Prover backend that routes every FFT/MSM through the worker fleet —
    the v2 fully-distributed prove path (reference dispatcher2.rs:192-713).
    The poly-handle protocol (round math) is inherited from the host
    oracle: like the reference's dispatcher, the sequential round logic
    stays local while the throughput kernels go to the fleet."""

    name = "remote"

    def __init__(self, dispatcher, dist_fft_min=None):
        """dist_fft_min: domain size at or above which a single NTT is run
        as the cross-worker sharded 4-step FFT (fft_dist) instead of being
        shipped whole to one worker; None = never (per-poly parallelism
        only)."""
        self.d = dispatcher
        self._inited = None
        self._rr = 0  # round-robin cursor for single NTTs
        self.dist_fft_min = dist_fft_min

    def _ensure_bases(self, bases):
        if self._inited is not bases:
            self.d.init_bases(bases)
            self._inited = bases

    def fft(self, domain, values):
        return self._ntt(domain, values, False, False)

    def ifft(self, domain, values):
        return self._ntt(domain, values, True, False)

    def coset_fft(self, domain, values):
        return self._ntt(domain, values, False, True)

    def coset_ifft(self, domain, values):
        return self._ntt(domain, values, True, True)

    def _ntt(self, domain, values, inverse, coset):
        padded = list(values) + [0] * (domain.size - len(values))
        if self.dist_fft_min is not None and domain.size >= self.dist_fft_min:
            return self.d.fft_dist(padded, inverse, coset)
        self._rr += 1
        return self.d.ntt(padded, inverse, coset, worker=self._rr)

    def _many(self, domain, handles, inverse, coset):
        padded = [list(h) + [0] * (domain.size - len(h)) for h in handles]
        if self.dist_fft_min is not None and domain.size >= self.dist_fft_min:
            # each FFT is itself sharded across the whole fleet
            return [self.d.fft_dist(v, inverse, coset) for v in padded]
        return self.d.ntt_many([(v, inverse, coset) for v in padded])

    def ifft_many(self, domain, handles):
        """Concurrent multi-worker batch (join_all across the fleet,
        reference dispatcher2.rs:294-321)."""
        return self._many(domain, handles, True, False)

    def coset_fft_many(self, domain, handles):
        return self._many(domain, handles, False, True)

    def msm(self, bases, scalars):
        self._ensure_bases(bases)
        padded = list(scalars) + [0] * (len(bases) - len(scalars))
        return self.d.msm(padded)

    def commit(self, ck, coeffs):
        return self.msm(ck, coeffs)
