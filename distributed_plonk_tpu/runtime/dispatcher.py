"""Dispatcher client: drives a worker fleet over the native transport.

The analog of the reference's dispatcher client library
(/root/reference/src/dispatcher.rs:29-175) + the v2 distributed compute
entry points (`Prover::fft` dispatcher2.rs:731-787, `commit_polynomial`
dispatcher2.rs:834-893), with the sharding convention fixed: every worker
receives exactly the base chunk its scalar range covers (the reference
mixed v1 full-broadcast with v2 chunking and indexed out of bounds —
SURVEY.md §2.3.1).

Fault domain (the reference treats every worker failure as an unwrap
panic, src/worker.rs:303): every dispatcher->worker call runs behind a
reconnect loop with exponential backoff + jitter, a per-worker circuit
breaker (runtime/health.py) fast-fails calls to a worker that has died so
its ranges get adopted instead of timing out, half-open probes re-admit a
worker that comes back, the sharded 4-step FFT re-plans around deaths at
ANY protocol phase (mirroring `_recover_msm`), and a deterministic fault
injector (runtime/faults.py) can be threaded through every frame for
chaos testing. Failure counters land in the duck-typed `metrics` registry
(service.metrics.Metrics shape): fleet_reconnects, fleet_backoff_waits,
fleet_breaker_opens, fleet_range_adoptions, fleet_readmissions,
fleet_fft_replans, fleet_fft_degraded.
"""

import concurrent.futures as futures
import os
import random
import struct
import threading
import time

import numpy as np

from contextlib import nullcontext

from . import native, protocol
from .faults import FaultInjector
from .health import LivenessTracker, NullMetrics
from .integrity import FleetIntegrity, IntegrityError, power_sum
from .. import curve as C
from ..backend.python_backend import PythonBackend
from ..constants import R_MOD
from ..obs import log as olog
from ..trace import merge_traces

# worker-side base-set id reserved for known-answer challenges: range ids
# are fleet positions (small ints), so a huge constant can never collide
CHALLENGE_SET_ID = 1 << 62


def _split_rc(n):
    """n = r*c with r = 2^floor(log2(n)/2) (the reference's domain split,
    /root/reference/src/worker.rs:142-155)."""
    log_n = n.bit_length() - 1
    r = 1 << (log_n // 2)
    return r, n // r


class _Failure:
    def __init__(self, err):
        self.err = err


def _try(fn, arg):
    """Capture a worker failure as a value so a pool.map survives it."""
    try:
        return fn(arg)
    except Exception as e:
        return _Failure(e)


class WorkerUnavailable(ConnectionError):
    """Fast-fail for a breaker-open worker: no dial, no timeout burned."""


class FleetError(RuntimeError):
    """A distributed protocol attempt lost at least one worker."""


class WorkerHandle:
    """One framed connection to a worker, with a per-call timeout and a
    bounded reconnect loop (exponential backoff + jitter) — replacing the
    single reconnect-retry of earlier rounds; the reference has neither
    (every RPC there is .unwrap(), SURVEY.md §5: a worker crash hangs the
    prove).

    A timeout mid-frame desynchronizes the stream, so recovery is always
    reconnect-then-retry, never resend on the same socket. Retried requests
    are idempotent at the worker (MSM/NTT are pure; FFT1/FFT_EXCHANGE
    overwrite the same slots; FFT2 replays its cached reply instead of
    deleting the task — completed tasks are GC'd by age + LRU cap).

    The connection is LAZY: constructing a handle to a not-yet-alive
    worker is fine; the first call dials."""

    # 0 = block forever; FFT2 on a python-backend worker can take minutes
    TIMEOUT_MS = int(os.environ.get("DPT_CALL_TIMEOUT_MS", "600000"))
    RECONNECT_TRIES = int(os.environ.get("DPT_RECONNECT_TRIES", "3"))
    # analysis: ok(host-only ms->s conversion, no traced arithmetic)
    BACKOFF_BASE_S = float(os.environ.get("DPT_BACKOFF_BASE_MS", "50")) / 1e3
    # analysis: ok(host-only ms->s conversion, no traced arithmetic)
    BACKOFF_MAX_S = float(os.environ.get("DPT_BACKOFF_MAX_MS", "2000")) / 1e3

    def __init__(self, host, port, index=0, tracker=None, metrics=None,
                 faults=None, tracer=None):
        self.host, self.port = host, port
        self.index = index
        self.tracker = tracker
        self.metrics = metrics or NullMetrics()
        self.faults = faults
        # tracer: when set, every call records an rpc span and injects
        # its {trace_id, parent_id} into the frame (protocol.TRACED), so
        # the worker's serve/kernel spans land in the same trace
        self.tracer = tracer
        self.conn = None
        # one in-flight request per connection: frames are not interleavable
        self._lock = threading.Lock()

    def _connect(self):
        # bound the dial by the call timeout too: a partitioned worker
        # (dropped SYNs) must cost one timeout, not the OS connect
        # default of minutes
        conn = native.connect(self.host, self.port,
                              timeout_ms=self.TIMEOUT_MS)
        if self.TIMEOUT_MS:
            conn.set_timeout(self.TIMEOUT_MS)
        return conn

    def _drop_conn_locked(self):
        """self._lock held (the reconnect loop's own drop)."""
        if self.conn is not None:
            self.conn.close()
            self.conn = None

    def drop_conn(self):
        """Discard the cached stream so the next call dials fresh (the
        dispatcher's probe/readmit paths know it is or may be stale).
        Takes the call lock: never closes a socket mid-request."""
        with self._lock:
            self._drop_conn_locked()

    def call(self, tag, payload=b"", traced=True, parent=None):
        """Send one request; reconnect with backoff on transport failure.
        Raises WorkerUnavailable without dialing when the breaker is open
        (callers adopt the range / replan instead of burning a timeout),
        ConnectionError when every reconnect try failed, RuntimeError on
        an ERR reply (the worker is ALIVE — protocol errors don't count
        against the breaker). With a tracer armed, the call is recorded
        as an rpc span and its context rides the frame (traced=False
        opts a control call out, e.g. TRACE_DUMP itself); `parent` links
        the span explicitly when the call runs on an executor thread
        that cannot see the caller's span stack (the fleet fan-outs)."""
        if self.tracker is not None and not self.tracker.usable(self.index):
            raise WorkerUnavailable(f"worker {self.index} breaker open")
        span = nullcontext() if self.tracer is None or not traced else \
            self.tracer.span(f"rpc/{protocol.tag_name(tag).lower()}",
                             parent=parent, worker=self.index,
                             req_bytes=len(payload))
        try:
            with span as span_sid, self._lock:
                if span_sid is not None:
                    # context computed once, outside the retry loop: a
                    # reconnect resends the identical (idempotent) frame
                    _, payload = protocol.wrap_traced(
                        tag, payload, {"trace_id": self.tracer.trace_id,
                                       "parent_id": span_sid})
                rtag, rpayload = self._call_locked(
                    tag, payload, traced=span_sid is not None)
        except (ConnectionError, OSError):
            if self.tracker is not None:
                self.tracker.record_failure(self.index)
            raise
        if self.tracker is not None:
            self.tracker.record_ok(self.index)
        if rtag != protocol.OK:
            raise RuntimeError(f"worker error: {rpayload!r}")
        return rpayload

    def _call_locked(self, tag, payload, traced=False):
        delay = self.BACKOFF_BASE_S
        for attempt in range(self.RECONNECT_TRIES):
            try:
                if self.conn is None:
                    self.conn = self._connect()
                wire_tag = tag
                if self.faults is not None:
                    # may sleep (delay), raise InjectedDrop (drop), scramble
                    # the tag (corrupt), or kill the worker process (kill).
                    # Rules match on the BASE tag; the TRACED flag rides on
                    # whatever tag the injector returns.
                    wire_tag = self.faults.on_send(self.index, tag, payload)
                if traced:
                    wire_tag |= protocol.TRACED
                self.conn.send(wire_tag, payload)
                return self.conn.recv()
            except (ConnectionError, OSError):
                self._drop_conn_locked()
                if attempt + 1 >= self.RECONNECT_TRIES:
                    raise
                # exponential backoff with jitter: a fleet of callers
                # retrying a flapping worker must not stampede it
                sleep_s = min(self.BACKOFF_MAX_S, delay) \
                    * (1.0 + 0.5 * random.random())  # analysis: ok(host-only jitter)
                delay *= 2
                self.metrics.inc("fleet_reconnects")
                self.metrics.inc("fleet_backoff_waits")
                self.metrics.observe("fleet_backoff", sleep_s)
                time.sleep(sleep_s)
        raise ConnectionError("unreachable")  # pragma: no cover

    def probe(self, timeout_ms=5000):
        """Liveness check on a FRESH short-timeout connection (half-open
        breaker probe): never touches the cached stream, so a probe racing
        a real call cannot desynchronize it. Returns the HEALTH snapshot
        dict, or None when the worker is unreachable."""
        import json
        try:
            # timeout covers the dial as well: probes are the breaker's
            # fast-fail plane and must never block on a partitioned host
            conn = native.connect(self.host, self.port,
                                  timeout_ms=timeout_ms)
        except (ConnectionError, OSError):
            return None
        try:
            conn.set_timeout(timeout_ms)
            conn.send(protocol.HEALTH)
            rtag, rpayload = conn.recv()
            if rtag != protocol.OK:
                return None
            return json.loads(rpayload.decode() or "{}")
        except (ConnectionError, OSError, ValueError):
            return None
        finally:
            conn.close()

    def close(self):
        self.drop_conn()


class Dispatcher:
    """Connections to every worker + distributed MSM / NTT offload, with
    liveness tracking, breaker-gated routing, and re-admission probes."""

    FFT_QUORUM = int(os.environ.get("DPT_FFT_QUORUM", "2"))

    def __init__(self, config, metrics=None, faults=None, tracer=None,
                 integrity=None):
        self.metrics = metrics or NullMetrics()
        # result-integrity plane (runtime/integrity.py): algebraic phase
        # checks on every sharded FFT / NTT offload, duplicate-execution
        # sampling + group-law sanity on MSM partials, dup-checked
        # distributed round-4 evaluation, and quarantine of attributed
        # liars. DPT_INTEGRITY=0 (or integrity=False) turns the whole
        # plane off — legacy wire bytes, zero extra host math.
        if integrity is None:
            integrity = FleetIntegrity.from_env(metrics=self.metrics)
        self.integrity = integrity or None
        if faults is None:
            # env-driven chaos (DPT_FAULTS="drop:tag=NTT;delay:tag=MSM:ms=50")
            # for soaks against a live deployment; None when unset, so the
            # hot path stays injection-free
            faults = FaultInjector.from_env(metrics=self.metrics)
        self.faults = faults
        # tracer: arms the distributed trace plane — every worker call
        # becomes an rpc span carrying context over the wire, and
        # collect_trace() stitches the workers' spans back into one
        # offset-corrected timeline. None keeps the hot path span-free.
        self.tracer = tracer
        self.tracker = LivenessTracker(len(config.workers),
                                       metrics=self.metrics)
        self.workers = [
            WorkerHandle(h, p, index=i, tracker=self.tracker,
                         metrics=self.metrics, faults=faults, tracer=tracer)
            for i, (h, p) in enumerate(config.workers)]
        # headroom past the initial width: dynamic membership can grow the
        # fleet mid-life (an undersized executor only costs parallelism,
        # never correctness, but joins should not serialize the fan-outs)
        self.pool = futures.ThreadPoolExecutor(
            max_workers=max(8, 2 * len(self.workers)))
        self._ranges = None
        self._bases = None
        self._adopted = {}  # base-range i -> worker j that adopted it
        # ranges whose INIT_BASES push failed at the last provisioning:
        # their nominal owner may hold a STALE same-id set from an
        # earlier init_bases, so routing there would succeed with wrong
        # bases — these ranges go straight to the adoption path instead
        self._unprovisioned = set()
        # dynamic membership (runtime/membership.py): enable_membership()
        # arms it; a fleet without it behaves exactly as before (epoch 0
        # frames, fixed width)
        self.membership = None
        self._member_server = None

    @property
    def epoch(self):
        """Current membership-roster version (0 = static fleet)."""
        return self.membership.epoch if self.membership is not None else 0

    def enable_membership(self, host="127.0.0.1", port=0):
        """Own a membership registry + serve it (JOIN/LEAVE/ROSTER) on
        `host:port` (0 = ephemeral). Returns the MembershipServer (its
        `.port` is what workers pass to --join)."""
        from .membership import MembershipRegistry, MembershipServer
        if self.membership is None:
            self.membership = MembershipRegistry(
                self, metrics=self.metrics, tracer=self.tracer)
        if self._member_server is None:
            self._member_server = MembershipServer(
                self.membership, host=host, port=port)
        return self._member_server

    def adopt_worker(self, host, port):
        """Append one worker to the fleet (membership JOIN path); returns
        its index. Indices are stable forever — the sharded FFT's
        col_ranges and the MSM range table keep indexing by fleet
        position. The new worker is schedulable immediately: the next
        fft_dist attempt plans over the wider usable set, and the next
        init_bases() range-shards across the full width; until then it
        serves NTTs and adopts dead MSM ranges like any survivor."""
        i = self.tracker.add_worker()
        self.workers.append(
            WorkerHandle(host, port, index=i, tracker=self.tracker,
                         metrics=self.metrics, faults=self.faults,
                         tracer=self.tracer))
        return i

    def _log(self, event, level="info", **fields):
        """One structured log event (obs/log.py) under the dispatcher
        subsystem, trace-correlated when a tracer is armed — every
        quarantine/adoption/replan becomes a queryable line on the same
        timeline as the spans."""
        olog.emit("dispatcher", event, level=level,
                  trace_id=self.tracer.trace_id
                  if self.tracer is not None else None, **fields)

    def ping(self):
        for w in self.workers:
            w.call(protocol.PING)

    def health(self):
        """Fresh-probe HEALTH snapshot per worker (None = unreachable),
        annotated with the dispatcher-side quarantine verdict."""
        snaps = [w.probe() for w in self.workers]
        for i, s in enumerate(snaps):
            if s is not None:
                s["suspect"] = self.tracker.is_suspect(i)
        return snaps

    # -- liveness maintenance -------------------------------------------------

    def _probe_fleet(self):
        """Find out who is ACTUALLY dead after a distributed attempt
        failed: a worker often reports a peer's death as its own error
        (FFT2_PREPARE push to a dead peer), so failure attribution needs a
        direct probe of everyone. Probes run concurrently; dead workers
        get the breaker opened immediately (authoritative evidence)."""
        def one(iw):
            i, w = iw
            if self._left(i):
                return  # decommissioned: stays dead regardless of probes
            if w.probe() is None:
                self.tracker.mark_dead(i)
                w.drop_conn()
            else:
                self.tracker.record_ok(i)
        list(self.pool.map(one, enumerate(self.workers)))

    def _left(self, i):
        """True for a member declared permanently gone via LEAVE: the
        re-admission planes must not probe or revive it (a
        decommissioned address may still answer) — only an explicit
        JOIN brings it back."""
        return self.membership is not None and self.membership.is_left(i)

    def _maybe_readmit(self):
        """Half-open probes for breaker-open workers whose backoff window
        elapsed; a worker that answers is re-admitted and (if bases are
        provisioned) gets its original MSM range re-uploaded so routing
        rebalances instead of leaning on the adopter forever."""
        for i in self.tracker.due_probes():
            if self._left(i):
                continue
            w = self.workers[i]
            if w.probe() is None:
                self.tracker.record_failure(i)
                continue
            w.drop_conn()  # stale pre-death stream, if any
            self.tracker.record_ok(i)  # counts fleet_readmissions
            self._log("readmitted", worker=i)
            self._reprovision(i)

    def _reprovision(self, i):
        """Best effort: push range i's bases back to a re-admitted worker
        i and drop the adoption redirect. A failure here is harmless —
        the lazy recovery path re-adopts at the next msm()."""
        if self._ranges is None or i >= len(self._ranges):
            return
        start, end = self._ranges[i]
        if end <= start:
            return
        try:
            self.workers[i].call(
                protocol.INIT_BASES,
                protocol.encode_init_bases(i, self._bases[start:end]))
            self._adopted.pop(i, None)
            self._unprovisioned.discard(i)
        except Exception:
            pass

    # -- MSM ------------------------------------------------------------------

    def init_bases(self, bases):
        """Range-shard the SRS: worker i holds bases[start_i:end_i]
        (contiguous split, like MsmWorkload ranges) under set id i. The
        full base list is retained host-side so a dead worker's range can
        be re-provisioned onto a healthy worker mid-prove."""
        n = len(bases)
        k = len(self.workers)
        bounds = [n * i // k for i in range(k + 1)]
        self._ranges = list(zip(bounds[:-1], bounds[1:]))
        self._bases = bases
        self._adopted = {}
        # a worker that is dead at provisioning time is tolerated: its
        # range stays unowned and the first msm() adopts it onto a healthy
        # worker through the same lazy-recovery path as a mid-prove death.
        # The map MUST be materialized with list(): Executor.map's result
        # generator CANCELS still-pending futures when it is closed
        # early, so a short-circuiting consumer (the old `all(...)`)
        # could silently skip a worker's INIT_BASES under load — leaving
        # a STALE same-id base set from an earlier provisioning on an
        # alive worker, which then serves later MSMs with wrong bases
        # (caught live as an intermittent wrong-proof in the fleet-TCP
        # tests). Failed pushes are remembered in _unprovisioned so
        # msm() routes those ranges through recovery instead of trusting
        # the nominal owner.
        with self._span("fleet/init_bases", n=n) as prov_sid:
            results = list(self.pool.map(
                lambda iw: _try(
                    lambda iw: iw[1].call(protocol.INIT_BASES,
                                          protocol.encode_init_bases(
                                              iw[0],
                                              bases[self._ranges[iw[0]][0]:
                                                    self._ranges[iw[0]][1]]),
                                          parent=prov_sid),
                    iw),
                enumerate(self.workers)))
            self._unprovisioned = {
                i for i, r in enumerate(results) if isinstance(r, _Failure)}
            if results and len(self._unprovisioned) == len(results):
                raise RuntimeError("no worker accepted its base range")

    def msm(self, scalars):
        """Distributed MSM with elastic recovery: scatter scalar ranges,
        fold partial G1 sums on the host (reference dispatcher2.rs:888-890
        — where every worker failure is an unwrap panic, src/worker.rs:303;
        here a dead worker's range is re-provisioned onto a healthy worker
        and recomputed)."""
        assert self._ranges is not None, "init_bases first"
        self._maybe_readmit()

        # the fan-out runs on executor threads that cannot see this
        # thread's span stack, so the fleet span's sid is threaded down
        # explicitly — rpc spans stay children of fleet/msm in the tree
        with self._span("fleet/msm", n=len(scalars)) as fleet_sid:
            return self._msm_inner(scalars, fleet_sid)

    def _msm_inner(self, scalars, fleet_sid=None):
        def part(i):
            start, end = self._ranges[i]
            chunk = scalars[start:end]
            if not chunk:
                return None
            # a range whose provisioning push failed must NOT be served
            # by its nominal owner: an alive worker can hold a stale
            # same-id set from an earlier init_bases and would answer
            # with the wrong partial — force the adoption path, which
            # re-pushes the bases before computing
            if i in self._unprovisioned and i not in self._adopted:
                raise ConnectionError(f"range {i} never provisioned")
            # an adopted range routes straight to its new owner — no
            # re-dialing the dead worker, no re-upload
            server = self._adopted.get(i, i)
            raw = self.workers[server].call(
                protocol.MSM, protocol.encode_msm_request(i, chunk),
                parent=fleet_sid)
            return protocol.decode_point(raw), server

        # per-range (partial point, serving worker) — kept apart until
        # the integrity pass has inspected EVERY partial (primary AND
        # recovery-path adopted — the PR 12 stale-base class must be
        # caught on the recovery path too), only then folded
        results = [None] * len(self._ranges)
        failed = []
        # ranges, not workers: a member that joined after init_bases()
        # holds no range yet (it becomes an adopter/full member at the
        # next provisioning)
        for i, res in enumerate(self.pool.map(
                lambda i: _try(part, i), range(len(self._ranges)))):
            if isinstance(res, _Failure):
                failed.append(i)
            else:
                results[i] = res
        if failed:
            # recoveries run concurrently; _recover_msm spreads adoptions
            # across the fleet starting at dead_i + 1
            for i, rec in zip(failed, self.pool.map(
                    lambda i: self._recover_msm(i, scalars, fleet_sid),
                    failed)):
                results[i] = rec
        if self.integrity is not None:
            results = list(self.pool.map(
                lambda ir: self._msm_check_range(ir[0], ir[1], scalars,
                                                 fleet_sid),
                enumerate(results)))
        total = None
        for rec in results:
            if rec is not None:
                total = C.g1_add_affine(total, rec[0])
        return total

    def _msm_check_range(self, i, rec, scalars, fleet_sid=None):
        """Integrity pass for one served MSM partial: group-law sanity
        (on-curve + subgroup) always, duplicate execution at the sampled
        rate (DPT_INTEGRITY_MSM_DUP). A worker caught serving a wrong
        partial is quarantined and the range recomputed on a healthy
        adopter (whose result is sanity-checked in turn). Returns the
        (partial, server) record to fold — possibly replaced."""
        if rec is None:
            return None
        integ = self.integrity
        point, server = rec
        integ.metrics.inc("integrity_checks")
        if not integ.point_sane(point):
            # a flipped coordinate limb: not even on the curve (or not
            # in the order-r subgroup) — attribution is immediate
            integ.metrics.inc("integrity_failures")
            self.quarantine(server, f"msm range {i}: partial fails the "
                                    "group-law sanity check")
            return self._msm_requarantine_recompute(i, scalars, fleet_sid)
        if not integ.sample_msm_dup():
            return rec
        integ.metrics.inc("integrity_msm_dups")
        verdict = self._msm_dup_check(i, point, server, scalars, fleet_sid)
        if verdict is None:
            return rec  # agreed (or no second worker to ask)
        liar, good = verdict
        integ.metrics.inc("integrity_failures")
        self.quarantine(liar, f"msm range {i}: duplicate execution "
                              "mismatch")
        if liar != server:
            return rec  # the verifier lied; the served partial stands
        if good is not None:
            return good
        return self._msm_requarantine_recompute(i, scalars, fleet_sid)

    def _msm_requarantine_recompute(self, i, scalars, fleet_sid):
        """Recompute range i after its server was quarantined: the
        normal adoption path (fresh bases pushed to a healthy worker),
        with the new partial re-checked — group-law sanity AND one
        duplicate execution (the adopter may be lying too; found live in
        the sdc soak, where the unchecked recompute was the one path a
        wrong partial could ride into the fold — self-verify caught it,
        but the phase boundary should). A second failure means the fleet
        cannot serve trustworthy data for this range — loud
        IntegrityError, never a silent wrong fold."""
        rec = self._recover_msm(i, scalars, fleet_sid)
        if rec is None:
            return None
        if not self.integrity.point_sane(rec[0]):
            self.integrity.metrics.inc("integrity_failures")
            self.quarantine(rec[1], f"msm range {i}: recomputed partial "
                                    "fails the group-law sanity check")
            raise IntegrityError(
                f"msm range {i}: no trustworthy partial", (rec[1],))
        verdict = self._msm_dup_check(i, rec[0], rec[1], scalars, fleet_sid)
        if verdict is not None:
            liar, good = verdict
            self.integrity.metrics.inc("integrity_failures")
            self.quarantine(liar, f"msm range {i}: recomputed partial "
                                  "duplicate mismatch")
            if liar != rec[1]:
                return rec
            if good is not None:
                return good
            raise IntegrityError(
                f"msm range {i}: no trustworthy partial", (liar,))
        return rec

    def _msm_dup_check(self, i, point, server, scalars, fleet_sid=None):
        """Duplicate-execute range i on a second worker with FRESHLY
        pushed bases and compare. None = partials agree (or nobody to
        ask). On a mismatch, a third worker votes (host oracle referees
        small ranges when the fleet is only 2 wide): returns
        (liar_index, (good_point, good_server) | None)."""
        start, end = self._ranges[i]
        chunk = scalars[start:end]

        def compute_on(j):
            w = self.workers[j]
            w.call(protocol.INIT_BASES,
                   protocol.encode_init_bases(i, self._bases[start:end]),
                   parent=fleet_sid)
            raw = w.call(protocol.MSM,
                         protocol.encode_msm_request(i, chunk),
                         parent=fleet_sid)
            return protocol.decode_point(raw)

        k = len(self.workers)
        candidates = [j for j in ((server + off) % k
                                  for off in range(1, k))
                      if j != server and self.tracker.usable(j)]
        verifier = dup = None
        for j in candidates:
            try:
                dup = compute_on(j)
                verifier = j
                break
            except Exception:
                continue
        if verifier is None:
            return None  # nobody to cross-check against: unsampled
        if dup == point:
            return None
        # disagreement: one of the two is lying — get a third opinion
        for j in candidates:
            if j == verifier:
                continue
            try:
                ref = compute_on(j)
            except Exception:
                continue
            if ref == dup:
                return server, (dup, verifier)
            if ref == point:
                return verifier, None
            break  # three-way disagreement: fall through to conservative
        if len(chunk) <= self.integrity.referee_max:
            ref = C.g1_msm(self._bases[start:end][:len(chunk)], chunk)
            if ref == dup:
                return server, (dup, verifier)
            if ref == point:
                return verifier, None
        # unattributable beyond doubt: the worker SERVING the data is
        # the one whose wrong answer would poison the proof — quarantine
        # it and recompute (conservative; an innocent server rejoins via
        # the challenge gate)
        return server, None

    def _recover_msm(self, dead_i, scalars, fleet_sid=None):
        """Re-provision range dead_i's bases onto a healthy worker (set id
        unchanged — ids are ranges, not workers), recompute its part, and
        REMEMBER the adoption so later msm() calls route directly. Workers
        with an open breaker are skipped up front (no timeout burned);
        only if NO usable worker can adopt are the breaker-open ones
        probed directly and re-admitted on an answer — same last-resort
        rule as ntt(): a recovered fleet whose breakers are all still
        open must serve the call, not abort the prove.

        Returns (partial point, adopting worker) — the adopter rides
        along so the integrity pass can attribute/quarantine adopted
        ranges exactly like primary ones."""
        start, end = self._ranges[dead_i]
        chunk = scalars[start:end]
        if not chunk:
            return None
        k = len(self.workers)
        failed_owner = self._adopted.get(dead_i, dead_i)
        # an UNPROVISIONED range's owner never actually failed a call —
        # msm() pre-empted it because its bases may be stale. adopt()
        # re-pushes fresh bases first, so the owner is a legitimate
        # candidate (excluding it could fail a prove with a healthy
        # worker available, e.g. k=2 with the other worker dead)
        if dead_i in self._unprovisioned and dead_i not in self._adopted:
            failed_owner = None
        last_err = None

        def adopt(j):
            w = self.workers[j]
            w.call(protocol.INIT_BASES, protocol.encode_init_bases(
                dead_i, self._bases[start:end]), parent=fleet_sid)
            raw = w.call(protocol.MSM,
                         protocol.encode_msm_request(dead_i, chunk),
                         parent=fleet_sid)
            self._adopted[dead_i] = j
            self._unprovisioned.discard(dead_i)  # freshly pushed to j
            self.metrics.inc("fleet_range_adoptions")
            self._log("range_adopted", level="warn", range=dead_i,
                      worker=j)
            return protocol.decode_point(raw), j

        rotation = [(dead_i + off) % k for off in range(1, k + 1)]
        for j in rotation:
            if j == failed_owner or not self.tracker.usable(j):
                continue
            try:
                return adopt(j)
            except Exception as e:  # try the next healthy worker
                last_err = e
        for j in self._probe_readmit(
                j for j in rotation
                if j != failed_owner and not self.tracker.usable(j)):
            try:
                return adopt(j)
            except Exception as e:
                last_err = e
        raise RuntimeError(
            f"no healthy worker could adopt MSM range {dead_i}") from last_err

    def _probe_readmit(self, candidates):
        """Last-resort plane shared by ntt() and _recover_msm(): probe
        each breaker-open candidate directly and yield the ones that
        answer (re-admitted) so the caller can route to them — a
        recovered fleet whose breakers are all still open must serve the
        call, not fast-fail it (call() alone would raise
        WorkerUnavailable without dialing)."""
        for i in candidates:
            if self._left(i) or self.tracker.is_suspect(i):
                continue  # decommissioned/quarantined: a JOIN (plus, for
                # suspects, a passed challenge) is the only way back
            if self.workers[i].probe() is None:
                continue  # actually dead: leave the breaker open
            self.tracker.record_ok(i)  # alive: re-admit, then route to it
            yield i

    # -- result-integrity quarantine ------------------------------------------

    def quarantine(self, i, reason):
        """The integrity plane attributed a WRONG answer to worker i:
        mark it SUSPECT (sticky breaker — probes do NOT re-admit it, its
        process is alive and answering; its answers are wrong), and
        LEAVE it through the membership registry so the supervisor
        replaces the process (flap-cap rules apply to repeat offenders).
        Re-admission is only via a fresh JOIN that passes the
        known-answer challenge (run_challenge)."""
        flipped = self.tracker.mark_suspect(i)
        self.workers[i].drop_conn()
        self._log("quarantine", level="warn", worker=i, reason=reason)
        if self.tracer is not None:
            self.tracer.add_event("integrity/quarantine", time.time(), 0.0,
                                  worker=i, reason=reason)
        if self.membership is not None and flipped:
            try:
                self.membership.leave(index=i, reason="integrity")
            except Exception:  # registry races a concurrent leave: fine
                pass
        return flipped

    def run_challenge(self, host, port, timeout_s=15.0):
        """Known-answer gate for (re-)admitting a worker the integrity
        plane quarantined: a fresh random NTT and a fresh random MSM,
        both compared against the host oracle. Values are drawn per call
        so a lying worker cannot replay cached answers. Retries the
        connection while the (just-respawned) worker binds."""
        from .. import poly as P
        rng = random.Random()
        xs = [rng.randrange(R_MOD) for _ in range(64)]
        want_ntt = P.fft(P.Domain(64), xs)
        bases = [C.g1_mul(C.G1_GEN, k + 2) for k in range(8)]
        sc = [rng.randrange(R_MOD) for _ in range(8)]
        want_msm = C.g1_msm(bases, sc)
        self.metrics.inc("integrity_challenges")
        h = WorkerHandle(host, port, metrics=self.metrics)
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                try:
                    got_ntt = protocol.decode_scalars(h.call(
                        protocol.NTT,
                        protocol.encode_ntt_request(xs, False, False),
                        traced=False))
                    h.call(protocol.INIT_BASES,
                           protocol.encode_init_bases(CHALLENGE_SET_ID,
                                                      bases), traced=False)
                    got_msm = protocol.decode_point(h.call(
                        protocol.MSM,
                        protocol.encode_msm_request(CHALLENGE_SET_ID, sc),
                        traced=False))
                    break
                except (ConnectionError, OSError):
                    if time.monotonic() >= deadline:
                        self.metrics.inc("integrity_challenges_failed")
                        return False
                    h.drop_conn()
                    time.sleep(0.2)
                except RuntimeError:  # worker ERR reply: that's a fail
                    self.metrics.inc("integrity_challenges_failed")
                    return False
        finally:
            h.close()
        ok = got_ntt == want_ntt and got_msm == want_msm
        if not ok:
            self.metrics.inc("integrity_challenges_failed")
        olog.emit("integrity", "challenge", level="info" if ok else "warn",
                  host=host, port=port, ok=ok)
        return ok

    # -- NTT ------------------------------------------------------------------

    def ntt(self, values, inverse=False, coset=False, worker=0):
        """Offload one whole NTT to a worker (per-polynomial task
        parallelism, reference §2.3.3). NTTs are stateless, so a dead
        worker is simply routed around: usable workers are tried first
        (rotation order); if every one of them fails, breaker-open
        workers are PROBED directly and re-admitted on an answer — a
        recovered fleet whose breakers are all still open must serve the
        call, not fast-fail it (call() alone would raise
        WorkerUnavailable without dialing)."""
        k = len(self.workers)
        payload = protocol.encode_ntt_request(values, inverse, coset)
        self._maybe_readmit()
        rotation = [(worker + off) % k for off in range(k)]
        last_err = None

        def served_by(i):
            """One attempt on worker i, integrity-checked: a wrong (but
            well-formed) result quarantines the server and raises so the
            rotation tries the next worker — attribution is trivial
            here, exactly one worker computed the answer."""
            raw = self.workers[i].call(protocol.NTT, payload)
            out = protocol.decode_scalars(raw)
            if self.integrity is not None \
                    and self.integrity.sample_ntt_check():
                t = self.integrity.draw_point()
                if not self.integrity.check_transform(values, out, t,
                                                      inverse, coset):
                    self.quarantine(i, "ntt result fails the "
                                       "Schwartz-Zippel check")
                    raise IntegrityError(
                        f"worker {i} served a wrong NTT", (i,))
            return out

        with self._span("fleet/ntt", n=len(values), inverse=inverse,
                        coset=coset):
            for i in [i for i in rotation if self.tracker.usable(i)]:
                try:
                    return served_by(i)
                except Exception as e:
                    last_err = e
            for i in self._probe_readmit(
                    i for i in rotation if not self.tracker.usable(i)):
                try:
                    return served_by(i)
                except Exception as e:
                    last_err = e
        raise RuntimeError("no worker could serve the NTT") from last_err

    def ntt_many(self, jobs):
        """Round-robin a batch of NTT jobs [(values, inverse, coset), ...]
        across the fleet concurrently (the join_all pattern,
        reference dispatcher2.rs:294-321)."""
        return list(self.pool.map(
            lambda ij: self.ntt(ij[1][0], ij[1][1], ij[1][2], worker=ij[0]),
            enumerate(jobs)))

    # -- distributed evaluation (round 4) -------------------------------------

    def eval_many(self, pairs):
        """[(coeffs, point)] -> evaluations, each polynomial's Horner
        sum range-sharded across the fleet (worker j returns
        sum_i chunk[i] * point^i; the host scales by point^start and
        folds). Exact field math — byte-identical to a host evaluation.
        ALL pairs' chunks ride ONE executor fan-out (round 4 submits 10
        polys at once; sequencing them would serialize 10 scatter/gather
        barriers onto the hot path). Integrity: chunks are duplicate-
        executed at the sampled rate and a mismatch is refereed by the
        host (a chunk evaluation is O(n/k) host muls — always
        affordable), so attribution is exact; a dead worker's chunk
        silently falls back to the host referee too."""
        usable = self.tracker.usable_set()
        k = max(len(usable), 1)
        plans = []   # (coeffs, point, bounds | None); None = host path
        for coeffs, point in pairs:
            coeffs = [int(v) % R_MOD for v in coeffs]
            point = int(point) % R_MOD
            n = len(coeffs)
            if not usable or n < 4 * k:
                plans.append((coeffs, point, None))
            else:
                plans.append((coeffs, point,
                              [n * j // k for j in range(k + 1)]))
        flat = [(pi, j) for pi, (_c, _p, b) in enumerate(plans)
                if b is not None for j in range(k)]
        out = [0] * len(pairs)
        if flat:
            total_n = sum(len(c) for c, _p, b in plans if b is not None)
            with self._span("fleet/eval", n=total_n,
                            polys=len(pairs)) as sid:
                def one(arg):
                    pi, j = arg
                    coeffs, point, bounds = plans[pi]
                    lo, hi = bounds[j], bounds[j + 1]
                    if hi <= lo:
                        return 0
                    chunk = coeffs[lo:hi]
                    server = usable[j]
                    try:
                        val = self._eval_chunk(server, chunk, point, sid)
                    except Exception:
                        # dead/unreachable worker: the host referee is
                        # the fallback — eval must never fail the prove
                        return power_sum(chunk, point) \
                            * pow(point, lo, R_MOD) % R_MOD
                    if self.integrity is not None:
                        val = self._eval_integrity(j, server, chunk,
                                                   point, val, usable,
                                                   sid)
                    return val * pow(point, lo, R_MOD) % R_MOD

                for (pi, _j), part in zip(flat, self.pool.map(one, flat)):
                    out[pi] = (out[pi] + part) % R_MOD
        for pi, (coeffs, point, b) in enumerate(plans):
            if b is None:
                out[pi] = power_sum(coeffs, point)
        return out

    def eval_poly(self, coeffs, point):
        return self.eval_many([(coeffs, point)])[0]

    def _eval_chunk(self, i, chunk, point, sid=None):
        raw = self.workers[i].call(
            protocol.EVAL, protocol.encode_eval_request(point, chunk),
            parent=sid)
        return protocol.decode_scalar(raw) % R_MOD

    def _eval_integrity(self, j, server, chunk, point, val, usable,
                        sid=None):
        """Duplicate-execution sampling for one evaluation chunk. On a
        mismatch the host referee (exact, cheap) names the liar; the
        refereed value is what gets served either way."""
        integ = self.integrity
        integ.metrics.inc("integrity_checks")
        if not integ.sample_msm_dup() or len(usable) < 2:
            return val
        integ.metrics.inc("integrity_eval_dups")
        verifier = usable[(j + 1) % len(usable)]
        try:
            dup = self._eval_chunk(verifier, chunk, point, sid)
        except Exception:
            return val  # nobody answered the cross-check: unsampled
        if dup == val:
            return val
        integ.metrics.inc("integrity_failures")
        ref = power_sum(chunk, point)
        liar = server if ref != val else verifier
        self.quarantine(liar, "eval chunk duplicate execution mismatch")
        return ref

    # -- sharded 4-step FFT ---------------------------------------------------

    def fft_dist(self, values, inverse=False, coset=False):
        """ONE cross-worker sharded 4-step (i)(coset)FFT — the reference's
        hot protocol (Prover::fft, dispatcher2.rs:731-787): stage-1 rows
        scattered block-wise, direct worker<->worker all-to-all, stage-2
        columns gathered. len(values) must be a power of two.

        Failure recovery: a worker dying at ANY phase (FFT_INIT / FFT1 /
        the EXCHANGE all-to-all / FFT2_PREPARE / FFT2) fails the attempt;
        the fleet is probed to find who actually died (a healthy worker
        reports a dead PEER's loss as its own error), the dead workers'
        panel rows and column ranges are re-provisioned onto the healthy
        subset, and the protocol re-runs under a fresh task id — the FFT
        mirror of `_recover_msm`, leaning on the worker handlers being
        idempotent and tasks being GC'd by TTL/cap. When the healthy set
        shrinks below FFT_QUORUM the call degrades gracefully to the
        whole-poly single-worker NTT path (which itself routes around
        dead workers). Byte-identical output either way — the kernels are
        deterministic and the math doesn't care where it runs."""
        n = len(values)
        assert n >= 4 and n & (n - 1) == 0, n
        k = len(self.workers)
        self._maybe_readmit()
        last_err = None
        same_set_retry = False
        with self._span("fleet/fft_dist", n=n, inverse=inverse,
                        coset=coset) as fft_sid:
            for _attempt in range(k + 1):
                active = self.tracker.usable_set()
                if len(active) < max(self.FFT_QUORUM, 1):
                    if len(active) < k:
                        # a fault shrank the fleet below quorum; a
                        # CONFIGURED sub-quorum fleet (k=1) taking this
                        # path is healthy and must not read as continuous
                        # degradation
                        self.metrics.inc("fleet_fft_degraded")
                        self._log("fft_degraded", level="warn", n=n,
                                  active=len(active), width=k)
                    return self.ntt(values, inverse, coset)
                try:
                    return self._fft_dist_attempt(values, inverse, coset,
                                                  active, fft_sid)
                except (FleetError, ConnectionError, OSError,
                        RuntimeError) as e:
                    last_err = e
                    # attribute the loss: probe everyone, open breakers on
                    # the actually-dead, then replan on the survivors
                    self._probe_fleet()
                    if self.membership is not None:
                        # the failure may be roster lag, not death: a
                        # worker that missed a push rejects plans whose
                        # epoch mismatches its table. Re-push and WAIT
                        # (bounded) so the next attempt — which re-reads
                        # self.epoch — runs against a converged fleet;
                        # the one same-set retry below then succeeds
                        # instead of burning on the identical rejection.
                        for f in self.membership.push_roster():
                            try:
                                f.result(timeout=5)
                            except Exception:
                                pass
                    if self.tracker.usable_set() == active:
                        # nobody actually died: a transient (dropped/
                        # corrupt frame, one slow call) gets ONE same-set
                        # retry; a second failure on the unchanged set is
                        # a deterministic error — surface it instead of
                        # burning k+1 identical multi-second attempts
                        if same_set_retry:
                            raise
                        same_set_retry = True
                    else:
                        same_set_retry = False
                    self.metrics.inc("fleet_fft_replans")
                    self._log("fft_replan", level="warn", n=n,
                              error=repr(last_err)[:200])
        raise RuntimeError(
            f"sharded FFT failed after {k + 1} replans") from last_err

    def _fft_dist_attempt(self, values, inverse, coset, active,
                          fft_sid=None):
        """One protocol run over the `active` worker subset. Dead workers
        keep zero-width row/column ranges, so the full-length col_ranges
        table still indexes by fleet position (peer routing is by config
        index) while all data lands on the healthy subset. The phase
        fan-outs run on executor threads, so rpc spans link to the
        fleet/fft_dist span through the explicit `fft_sid`."""
        n = len(values)
        r, c = _split_rc(n)
        k = len(self.workers)
        a = len(active)
        task_id = random.getrandbits(63)
        arow = [c * j // a for j in range(a + 1)]
        acol = [r * j // a for j in range(a + 1)]
        row_bounds = {i: (arow[j], arow[j + 1]) for j, i in enumerate(active)}
        col_ranges = [(0, 0)] * k
        for j, i in enumerate(active):
            col_ranges[i] = (acol[j], acol[j + 1])

        # (16, c, r): axis 1 = row index j2 (stride c in the flat poly)
        vm = protocol.ints_to_matrix(values).reshape(16, r, c)
        rows_mat = vm.transpose(0, 2, 1)  # [16, j2, position-in-row]

        def run_phase(fn, targets):
            failures = [res for res in self.pool.map(lambda i: _try(fn, i),
                                                     targets)
                        if isinstance(res, _Failure)]
            if failures:
                raise FleetError(
                    f"fft phase lost {len(failures)} worker(s)") \
                    from failures[0].err

        # the frame carries the membership epoch this plan was made
        # against: a worker whose roster moved on (a join/leave landed
        # mid-attempt) rejects it loudly and the outer loop replans at
        # the CURRENT width — how the fleet replans *up* at the next
        # phase boundary instead of finishing narrow
        epoch = self.epoch
        run_phase(
            lambda i: self.workers[i].call(
                protocol.FFT_INIT, protocol.encode_fft_init(
                    task_id, inverse, coset, n, r, c,
                    row_bounds[i][0], row_bounds[i][1], col_ranges,
                    epoch=epoch, integrity=self.integrity is not None),
                parent=fft_sid),
            active)

        def scatter(i):
            rs, re = row_bounds[i]
            if re == rs:
                return
            panel = np.ascontiguousarray(rows_mat[:, rs:re, :])
            self.workers[i].call(
                protocol.FFT1, protocol.encode_fft1_matrix(task_id, rs, panel),
                parent=fft_sid)

        run_phase(scatter, active)

        # trigger the all-to-all; each worker's OK implies its slices landed
        run_phase(
            lambda i: self.workers[i].call(
                protocol.FFT2_PREPARE, struct.pack("<Q", task_id),
                parent=fft_sid),
            active)

        # integrity: a random Fr check point rides every FFT2 fetch; the
        # workers piggyback (input-side, output-side) partial power sums
        # at that point on their replies (attribution evidence), and the
        # GATHERED output — the data actually served — must satisfy the
        # closed-form Schwartz-Zippel identity against the input
        check_t = self.integrity.draw_point() \
            if self.integrity is not None else None
        claimed = {}

        def gather(i):
            cs, ce = col_ranges[i]
            if ce == cs:
                return i, None
            raw = self.workers[i].call(
                protocol.FFT2,
                protocol.encode_fft2_request(task_id, check_t),
                parent=fft_sid)
            partials, panel = protocol.split_fft2_reply(raw)
            if partials is not None:
                claimed[i] = partials  # distinct keys: no lock needed
            flat = protocol.decode_scalar_matrix(panel)
            return i, flat

        out = np.empty((16, r, c), dtype=np.uint32)  # [16, k1, k2]
        failures = []
        for res in self.pool.map(lambda i: _try(gather, i), active):
            if isinstance(res, _Failure):
                failures.append(res)
                continue
            i, flat = res
            if flat is None:
                continue
            cs, ce = col_ranges[i]
            out[:, cs:ce, :] = flat.reshape(16, ce - cs, c)
        if failures:
            raise FleetError(
                f"fft gather lost {len(failures)} worker(s)") \
                from failures[0].err
        # result index is k1 + r*k2 -> transpose to [k2, k1] before flatten
        result = protocol.matrix_to_ints(
            np.ascontiguousarray(out.transpose(0, 2, 1)).reshape(16, n))
        if check_t is not None and not self.integrity.check_transform(
                values, result, check_t, inverse, coset):
            # detection is O(n); attribution (per-panel bisection against
            # the closed-form panel expectation, plus the workers' own
            # claimed partial pairs) runs only now, on the failed check
            suspects = self.integrity.attribute_fft(
                values, result, check_t, col_ranges, r, c, inverse, coset,
                claimed=claimed, row_bounds=row_bounds)
            for s in suspects:
                self.quarantine(s, "fft panel fails the Schwartz-Zippel "
                                   "check")
            raise IntegrityError(
                f"sharded fft integrity check failed "
                f"(suspect workers {suspects})", suspects)
        return result

    # -- tracing --------------------------------------------------------------

    def _span(self, name, **attrs):
        if self.tracer is None:
            return nullcontext()
        return self.tracer.span(name, **attrs)

    def estimate_offsets(self):
        """Per-worker wall-clock offset estimates (seconds each worker's
        clock runs AHEAD of ours), from the HEALTH probe round trip:
        offset = worker_now - (t_send + t_recv)/2. Error is bounded by
        half the round trip — microseconds on a LAN, far below the span
        durations being aligned. Unreachable workers estimate 0.0."""
        offsets = [0.0] * len(self.workers)
        for i, w in enumerate(self.workers):
            t0 = time.time()
            snap = w.probe()
            t1 = time.time()
            if snap is not None and isinstance(snap.get("now"), (int, float)):
                offsets[i] = snap["now"] - (t0 + t1) / 2.0  # analysis: ok(host-only clock math)
        return offsets

    def collect_trace(self, logs=True):
        """Stitch the distributed timeline for this dispatcher's trace:
        our own spans + every worker's TRACE_DUMP for the trace id,
        timestamps corrected by the per-worker clock-offset estimate.
        Returns the merged dump (trace.merge_traces shape — store it as
        a `trace:<job_id>` artifact via store.keycache.store_trace, or
        export with trace.to_chrome_trace). None when no tracer armed.
        Worker dumps are fetch-and-forget: collect once, at prove end.

        With logs=True the merged dump additionally carries a `logs`
        list: structured log events (obs/log.py) from THIS process's
        ring and every worker's LOG_FETCH, either tagged with the trace
        id or — for subsystems that cannot know it, like a supervisor
        respawn — untagged events inside the prove's time window, which
        are stamped with the trace id as they are attributed to it. The
        chrome export renders them as instant events on the timeline."""
        if self.tracer is None:
            return None
        dumps = [self.tracer.dump()]
        offsets = [0.0]
        est = self.estimate_offsets()
        req = protocol.encode_json({"trace_id": self.tracer.trace_id})
        log_sets = []  # (events, offset)
        for i, w in enumerate(self.workers):
            try:
                d = protocol.decode_json(
                    w.call(protocol.TRACE_DUMP, req, traced=False))
            except Exception:
                d = {}  # dead/restarted worker: its spans are lost
            if d.get("events"):
                dumps.append(d)
                offsets.append(est[i])
            if logs:
                try:
                    lf = protocol.decode_json(w.call(
                        protocol.LOG_FETCH, protocol.encode_json({}),
                        traced=False))
                    log_sets.append((lf.get("events") or [], est[i]))
                except Exception:
                    pass  # old worker / dead: logs degrade to absent
        merged = merge_traces(dumps, offsets=offsets)
        if logs:
            log_sets.append((olog.fetch()["events"], 0.0))
            merged["logs"] = self._trace_logs(merged, log_sets)
        return merged

    def _trace_logs(self, merged, log_sets):
        """Select + offset-correct the log events belonging to one merged
        timeline: events carrying the trace id always; untagged events
        whose (corrected) timestamp lies inside the span window too —
        stamped with the id, since merging IS the attribution."""
        tid = merged.get("trace_id")
        events = merged.get("events") or []
        lo = min((e["ts"] for e in events), default=0.0) - 2  # analysis: ok(host-only window pad)
        hi = max((e["ts"] + e.get("dur_s", 0.0) for e in events),
                 default=0.0) + 2  # analysis: ok(host-only window pad)
        out = []
        for evs, off in log_sets:
            for e in evs:
                e = dict(e)
                e["ts"] = round(float(e.get("ts", 0.0)) - off, 6)
                if e.get("trace_id") == tid:
                    out.append(e)
                elif "trace_id" not in e and lo <= e["ts"] <= hi:
                    e["trace_id"] = tid
                    out.append(e)
        out.sort(key=lambda e: e["ts"])
        return out

    # -- fleet observability (obs/fleet.py consumes these) --------------------

    def fleet_metrics(self):
        """One METRICS_FETCH scrape over the current roster — see
        obs.fleet.scrape for the entry shape (breaker/suspect-aware;
        old workers degrade to snapshot=None)."""
        from ..obs import fleet as obs_fleet
        return obs_fleet.scrape(self)

    def fetch_logs(self, worker=None, trace_id=None, since_seq=0):
        """[{worker, events, seq}] from each (or one) worker's LOG_FETCH
        ring. A worker that predates the tag, or is dead, contributes an
        empty list — never an error."""
        req = protocol.encode_json(
            {k: v for k, v in (("trace_id", trace_id),
                               ("since_seq", since_seq)) if v})
        targets = (enumerate(self.workers) if worker is None
                   else [(worker, self.workers[worker])])
        out = []
        for i, w in targets:
            entry = {"worker": i, "events": [], "seq": 0}
            try:
                lf = protocol.decode_json(
                    w.call(protocol.LOG_FETCH, req, traced=False))
                entry["events"] = lf.get("events") or []
                entry["seq"] = lf.get("seq", 0)
            except Exception:
                pass
            out.append(entry)
        return out

    def profile_worker(self, i, duration_ms=None, kind="auto"):
        """Arm one on-demand profile capture on worker i (PROFILE tag).
        Returns (meta, blob); raises on an unreachable worker, returns
        ({"format": "unsupported", ...}, b"") against an old one. With a
        tracer armed the capture lands as a span on the timeline so the
        stored profile:<id> artifact is linked from the trace.

        The capture rides a DEDICATED connection (fresh dial, closed
        after): the cached WorkerHandle stream serializes frames under
        its call lock, so a multi-second capture window there would
        stall every prove RPC to that worker — exactly the harm
        observability must never cause. Worker-side, the capture blocks
        only this dedicated connection's thread."""
        t0 = time.time()
        w = self.workers[i]
        h = WorkerHandle(w.host, w.port, index=i, metrics=self.metrics)
        try:
            raw = h.call(
                protocol.PROFILE,
                protocol.encode_json(
                    {"duration_ms": duration_ms, "kind": kind}),
                traced=False)
        except RuntimeError as e:
            # ERR reply: a worker that predates the tag — degrade, the
            # caller still gets a well-formed (meta, blob) pair
            return {"format": "unsupported", "worker": i,
                    "error": str(e)[:200]}, b""
        finally:
            h.close()
        meta, blob = protocol.decode_result(raw)
        if self.tracer is not None:
            from ..obs import profiling as obs_profiling
            self.tracer.add_event(
                "obs/profile", t0, time.time() - t0, worker=i,
                format=meta.get("format"),
                profile_id=obs_profiling.profile_id(blob)
                if blob else None)
        return meta, blob

    # -- misc -----------------------------------------------------------------

    def stats(self):
        """Per-worker served-request counters {tag: count} ({} for a
        worker that can't answer)."""
        import json

        def one(w):
            try:
                return json.loads(w.call(protocol.STATS).decode())
            except Exception:
                return {}
        return [one(w) for w in self.workers]

    def shutdown(self):
        if self._member_server is not None:
            self._member_server.close()
        for w in self.workers:
            try:
                w.call(protocol.SHUTDOWN)
            except Exception:
                pass
            w.close()


class RemoteBackend(PythonBackend):
    """Prover backend that routes every FFT/MSM through the worker fleet —
    the v2 fully-distributed prove path (reference dispatcher2.rs:192-713).
    The poly-handle protocol (round math) is inherited from the host
    oracle: like the reference's dispatcher, the sequential round logic
    stays local while the throughput kernels go to the fleet."""

    name = "remote"

    def __init__(self, dispatcher, dist_fft_min=None, dist_eval=None):
        """dist_fft_min: domain size at or above which a single NTT is run
        as the cross-worker sharded 4-step FFT (fft_dist) instead of being
        shipped whole to one worker; None = never (per-poly parallelism
        only). dist_eval: range-shard round-4 polynomial evaluations
        across the fleet (Dispatcher.eval_many — exact field math, so
        proof bytes are unchanged; duplicate-execution integrity applies);
        default on, DPT_FLEET_EVAL=0 (or dist_eval=False) keeps
        evaluations on the host."""
        self.d = dispatcher
        self._inited = None
        self._rr = 0  # round-robin cursor for single NTTs
        self.dist_fft_min = dist_fft_min
        if dist_eval is None:
            dist_eval = os.environ.get("DPT_FLEET_EVAL", "1") != "0"
        self.dist_eval = bool(dist_eval)

    def _ensure_bases(self, bases):
        if self._inited is not bases:
            self.d.init_bases(bases)
            self._inited = bases

    def fft(self, domain, values):
        return self._ntt(domain, values, False, False)

    def ifft(self, domain, values):
        return self._ntt(domain, values, True, False)

    def coset_fft(self, domain, values):
        return self._ntt(domain, values, False, True)

    def coset_ifft(self, domain, values):
        return self._ntt(domain, values, True, True)

    def _ntt(self, domain, values, inverse, coset):
        padded = list(values) + [0] * (domain.size - len(values))
        if self.dist_fft_min is not None and domain.size >= self.dist_fft_min:
            return self.d.fft_dist(padded, inverse, coset)
        self._rr += 1
        return self.d.ntt(padded, inverse, coset, worker=self._rr)

    def _many(self, domain, handles, inverse, coset):
        padded = [list(h) + [0] * (domain.size - len(h)) for h in handles]
        if self.dist_fft_min is not None and domain.size >= self.dist_fft_min:
            # each FFT is itself sharded across the whole fleet
            return [self.d.fft_dist(v, inverse, coset) for v in padded]
        return self.d.ntt_many([(v, inverse, coset) for v in padded])

    def ifft_many(self, domain, handles):
        """Concurrent multi-worker batch (join_all across the fleet,
        reference dispatcher2.rs:294-321)."""
        return self._many(domain, handles, True, False)

    def coset_fft_many(self, domain, handles):
        return self._many(domain, handles, False, True)

    def msm(self, bases, scalars):
        self._ensure_bases(bases)
        padded = list(scalars) + [0] * (len(bases) - len(scalars))
        return self.d.msm(padded)

    def commit(self, ck, coeffs):
        return self.msm(ck, coeffs)

    def eval_many_h(self, pairs):
        """Round-4 evaluations range-sharded across the fleet (exact
        field math — bytes identical to the host path), dup-checked by
        the integrity plane; DPT_FLEET_EVAL=0 restores the host path."""
        if not self.dist_eval or not self.d.workers:
            return super().eval_many_h(pairs)
        return self.d.eval_many(pairs)
