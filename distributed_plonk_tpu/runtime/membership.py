"""Dynamic fleet membership: epoch-numbered worker table + JOIN plane.

The reference hardcodes its worker set at startup and unwrap-panics on
loss (/root/reference/src/worker.rs:303); PR 6 made death survivable
(breaker + replan on survivors) but the fleet stayed permanently degraded
— a replacement host could only return by answering a half-open probe on
the exact dead address. This module makes composition DYNAMIC:

    MembershipRegistry   owned by the Dispatcher: the authoritative,
        epoch-numbered member table. Every change (join / rejoin / leave)
        bumps `epoch` and pushes the new roster to the live workers, so
        FFT2_PREPARE peer routing follows membership and frames planned
        against an older roster are rejected as stale (FFT_INIT carries
        the epoch; the dispatcher then replans at the current width).
    MembershipServer     a tiny listener serving the registry over the
        native framed transport (JOIN / LEAVE / ROSTER query): a freshly
        started `runtime/worker.py --join host:port` announces itself
        here, receives its index + epoch + peer roster, and is
        schedulable from that moment — the sharded FFT replans *up* to
        the wider fleet at its next phase boundary, and a rejoining
        worker's MSM range is re-provisioned through the PR 6
        re-admission path (no special case for respawns).

Index stability is the core invariant: a member's fleet index NEVER moves
or gets reused. Joins append; a known (host, port) re-joins IN PLACE;
leaves keep the slot (zero-width ranges, breaker open). col_ranges tables
and peer routing can therefore always index by fleet position.

Store-serving members (`--store`) are advertised in the roster's
`stores` list: joiners warm-rejoin from them (store/remote.warm_sync —
bucket keys + jax persistent-compile-cache entries over STORE_FETCH),
and a ProofService attached via `attach_membership` auto-registers them
as BucketCache peers (ROADMAP direction-2 auto-discovery).

Counters/gauges land in the duck-typed metrics registry:
membership_joins / membership_rejoins / membership_leaves /
roster_pushes / warm_rejoin_s, fleet_size / membership_epoch. With a
tracer armed, joins and leaves land as zero-duration spans on the PR 9
trace timeline (`membership/join`, `membership/leave`).
"""

import os
import threading
import time

from . import native, protocol
from .health import NullMetrics
from ..obs import log as olog


class MembershipRegistry:
    """The dispatcher's member table. All mutation runs under one lock;
    the dispatcher's own structures (workers list, tracker) only ever
    GROW, and they grow here, so concurrent proves observe either the
    old or the new width — never a torn table."""

    def __init__(self, dispatcher, metrics=None, tracer=None):
        self.d = dispatcher
        self.metrics = metrics or NullMetrics()
        self.tracer = tracer
        self._lock = threading.RLock()
        self.epoch = 1
        # index -> True for members that answer STORE_FETCH/STORE_LIST
        self.stores = {}
        # indices declared permanently gone by LEAVE: the dispatcher's
        # half-open probe loop must NOT re-admit these (a decommissioned
        # address may still answer probes), and must stop dialing them
        self.left = set()
        # (host, port) addresses LEAVEd by the result-integrity plane
        # (reason="integrity"): a fresh JOIN from one of these is only
        # SCHEDULABLE after the known-answer challenge passes
        # (Dispatcher.run_challenge) — a wrong-answer worker must not
        # re-enter service just by answering its own JOIN
        self.quarantined = set()
        self._listeners = []
        self._publish()

    # -- read side ------------------------------------------------------------

    def addresses(self):
        with self._lock:
            return [(w.host, w.port) for w in self.d.workers]

    def store_peers(self):
        """[(host, port)] of members advertising a store."""
        with self._lock:
            return [(self.d.workers[i].host, self.d.workers[i].port)
                    for i in sorted(self.stores)
                    if self.stores[i] and i < len(self.d.workers)]

    def roster(self):
        with self._lock:
            return {
                "epoch": self.epoch,
                "workers": [f"{h}:{p}" for h, p in self.addresses()],
                "stores": [f"{h}:{p}" for h, p in self.store_peers()],
            }

    def subscribe(self, fn):
        """fn(event dict) after every membership change — how a
        ProofService auto-registers store-serving joiners as bucket-cache
        peers without the registry knowing the service exists."""
        with self._lock:
            self._listeners.append(fn)

    # -- mutation -------------------------------------------------------------

    def join(self, host, port, store=False, phase=None, stats=None):
        """Admit (or re-admit) a member; returns the JOIN reply dict.

        phase="ready" is the post-warm-sync update from a worker that
        already joined: it records the reported warm-rejoin stats and
        returns the current roster WITHOUT bumping the epoch."""
        port = int(port)
        if phase == "ready":
            return self._ready(host, port, stats or {})
        with self._lock:
            index = self._find(host, port)
            rejoin = index is not None
            challenged = (host, port) in self.quarantined \
                and getattr(self.d, "integrity", None) is not None
            if rejoin:
                self.left.discard(index)  # an explicit JOIN un-leaves
                self._readmit(index, challenged=challenged)
            else:
                index = self.d.adopt_worker(host, port)
                if challenged:
                    # a quarantined ADDRESS coming back under a fresh
                    # slot is still gated (shouldn't happen — rejoins
                    # land in place — but the gate must not be evadable)
                    self.d.tracker.mark_suspect(index)
                    self.d.pool.submit(self._challenge, index, host, port)
            if store:
                self.stores[index] = True
            self.epoch += 1
            self.metrics.inc(
                "membership_rejoins" if rejoin else "membership_joins")
            self._publish()
            reply = dict(self.roster(), index=index)
            event = {"event": "join", "index": index, "host": host,
                     "port": port, "store": bool(store), "rejoin": rejoin,
                     "epoch": self.epoch}
        self._emit("membership/join", event)
        self._push_roster(exclude=index)
        return reply

    def leave(self, index=None, host=None, port=None, reason=None):
        """Declare a member permanently gone (flap cap / decommission /
        integrity quarantine): breaker opened immediately, epoch bumped,
        slot retained. reason="integrity" additionally quarantines the
        ADDRESS: its next JOIN is challenge-gated, and an attached
        supervisor kills the (alive but lying) process so it respawns
        clean."""
        with self._lock:
            if index is None:
                index = self._find(host, int(port))
            if index is None or not 0 <= index < len(self.d.workers):
                raise LookupError(f"unknown member {host}:{port}")
            w = self.d.workers[index]
            self.left.add(index)
            self.d.tracker.mark_dead(index)
            w.drop_conn()
            self.stores.pop(index, None)
            if reason == "integrity":
                self.quarantined.add((w.host, w.port))
            self.epoch += 1
            self.metrics.inc("membership_leaves")
            self._publish()
            event = {"event": "leave", "index": index, "host": w.host,
                     "port": w.port, "epoch": self.epoch,
                     "reason": reason}
        self._emit("membership/leave", event)
        self._push_roster(exclude=index)
        return {"epoch": self.epoch, "index": index}

    def is_left(self, index):
        """True for a member declared permanently gone: the dispatcher's
        re-admission planes skip it (only an explicit JOIN revives it)."""
        with self._lock:
            return index in self.left

    # -- internals ------------------------------------------------------------

    def _find(self, host, port):
        for i, w in enumerate(self.d.workers):
            if w.host == host and w.port == port:
                return i
        return None

    def _readmit(self, index, challenged=False):
        """Re-admission through the PR 6 path: fresh stream, breaker
        closed (counts fleet_readmissions when it was open), and the
        member's original MSM base range re-provisioned so routing
        rebalances off the adopter. The re-provision runs on the
        dispatcher's executor AFTER the JOIN reply goes out: the joiner
        is still blocked on that reply and not yet serving, so an inline
        INIT_BASES here would deadlock the whole membership plane until
        the call timeout (found live: the supervisor then wedge-killed
        the healthy rejoiner in a loop).

        challenged=True (the address was quarantined by the integrity
        plane): the member STAYS suspect — unschedulable — until the
        async known-answer challenge passes; same deadlock rationale,
        the challenge dials the joiner after the reply goes out."""
        w = self.d.workers[index]
        w.drop_conn()
        if challenged:
            self.d.tracker.mark_suspect(index)  # idempotent; stays dark
            self.d.pool.submit(self._challenge, index, w.host, w.port)
            return
        self.d.tracker.record_ok(index)
        self.d.pool.submit(self.d._reprovision, index)

    def _challenge(self, index, host, port):
        """Async challenge gate for a quarantined address's fresh JOIN:
        pass -> absolved (suspect cleared, schedulable, range
        re-provisioned); fail -> LEAVEd again, still quarantined — a
        worker that still serves wrong answers never re-enters service."""
        try:
            ok = self.d.run_challenge(host, port)
        except Exception:
            ok = False
        if ok:
            with self._lock:
                self.quarantined.discard((host, port))
            self.d.tracker.clear_suspect(index)
            self.d.tracker.record_ok(index)
            self.d._reprovision(index)
            self._emit("membership/challenge_passed",
                       {"event": "challenge_passed", "index": index,
                        "host": host, "port": port})
        else:
            try:
                self.leave(index=index, reason="integrity")
            except Exception:
                pass
            self._emit("membership/challenge_failed",
                       {"event": "challenge_failed", "index": index,
                        "host": host, "port": port})

    def _ready(self, host, port, stats):
        with self._lock:
            index = self._find(host, port)
            if index is None:
                raise LookupError(f"ready from non-member {host}:{port}")
            v = stats.get("warm_rejoin_s")
            if isinstance(v, (int, float)):
                self.metrics.observe("warm_rejoin_s", float(v))
                self.metrics.inc("warm_rejoins")
            event = {"event": "ready", "index": index, "stats": stats,
                     "epoch": self.epoch}
            reply = dict(self.roster(), index=index)
        self._emit("membership/ready", event)
        return reply

    def _publish(self):
        self.metrics.gauge("fleet_size", len(self.d.workers))
        self.metrics.gauge("membership_epoch", self.epoch)

    def _emit(self, span, event):
        attrs = {k: v for k, v in event.items()
                 if k != "event" and isinstance(v, (int, float, str, bool))}
        kind = event.get("event", "change")
        # every roster change is a structured log event too (obs/log.py):
        # trace-correlated when the dispatcher's tracer is armed, so the
        # merged per-job timeline shows the membership churn it survived
        olog.emit("membership", kind,
                  level="warn" if kind in ("leave", "challenge_failed")
                  else "info",
                  trace_id=self.tracer.trace_id
                  if self.tracer is not None else None, **attrs)
        if self.tracer is not None:
            self.tracer.add_event(span, time.time(), 0.0, event=kind,
                                  **attrs)
        for fn in list(self._listeners):
            try:
                fn(event)
            except Exception:  # a listener must not break membership
                pass

    def push_roster(self, exclude=None):
        """Best-effort epoch-table push to every member not LEAVEd (the
        excluded one — the joiner itself — gets the roster in its JOIN
        reply; breaker-open members are still attempted, since a
        transiently-marked-dead worker may be reachable and MUST learn
        the table before it is re-admitted). Runs on the dispatcher's
        executor. A member that still misses the push converges later:
        an epoch-mismatched FFT_INIT draws a loud error, and the
        dispatcher's replan path calls push_roster() again before the
        next attempt."""
        payload = protocol.encode_json(
            {k: v for k, v in self.roster().items()
             if k in ("epoch", "workers")})

        def push(i):
            try:
                self.d.workers[i].call(protocol.ROSTER, payload,
                                       traced=False)
                self.metrics.inc("roster_pushes")
            except Exception:
                pass  # breaker fast-fail / dead member: converges later

        with self._lock:
            targets = [i for i in range(len(self.d.workers))
                       if i != exclude and i not in self.left]
        return [self.d.pool.submit(push, i) for i in targets]

    _push_roster = push_roster


class MembershipServer:
    """Serve one registry over the framed transport (JOIN / LEAVE /
    ROSTER / PING). Lives inside the dispatcher's process — membership
    is dispatcher-owned state, the listener is just its wire face."""

    def __init__(self, registry, host="127.0.0.1", port=0):
        self.registry = registry
        self.host = host
        self._listener = native.Listener(host, port)
        self.port = port or native.listener_port(self._listener)
        self._accept = threading.Thread(target=self._accept_loop,
                                        name="membership-accept",
                                        daemon=True)
        self._accept.start()

    def address(self):
        return self.host, self.port

    def _accept_loop(self):
        while True:
            try:
                conn = self._listener.accept()
            except Exception:
                # native.Conn asserts on the -1 a failed/closed accept
                # returns. A dead accept thread would silently stop ALL
                # healing (no JOIN ever served again), so: exit cleanly
                # when the listener was closed, retry on transients
                # (EMFILE/ECONNABORTED under load)
                if self._listener.fd < 0:
                    return
                time.sleep(0.05)
                continue
            if conn.fd < 0:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                try:
                    tag, payload = conn.recv()
                except ConnectionError:
                    return
                try:
                    self._dispatch(conn, tag, payload)
                except Exception as e:
                    try:
                        conn.send(protocol.ERR, protocol.encode_json(
                            {"reason": repr(e)}))
                    except ConnectionError:
                        return
        finally:
            conn.close()

    def _dispatch(self, conn, tag, payload):
        reg = self.registry
        if tag == protocol.PING:
            conn.send(protocol.OK)
        elif tag == protocol.JOIN:
            req = protocol.decode_json(payload)
            reply = reg.join(req["host"], req["port"],
                             store=bool(req.get("store")),
                             phase=req.get("phase"),
                             stats=req.get("stats"))
            conn.send(protocol.OK, protocol.encode_json(reply))
        elif tag == protocol.LEAVE:
            req = protocol.decode_json(payload)
            reply = reg.leave(index=req.get("index"),
                              host=req.get("host"), port=req.get("port"))
            conn.send(protocol.OK, protocol.encode_json(reply))
        elif tag == protocol.ROSTER:
            conn.send(protocol.OK, protocol.encode_json(reg.roster()))
        else:
            conn.send(protocol.ERR, protocol.encode_json(
                {"reason": "unknown membership tag"}))

    def close(self):
        self._listener.close()


# -- worker-side join client ---------------------------------------------------

JOIN_RETRY_S = float(os.environ.get("DPT_JOIN_RETRY_S", "30"))
JOIN_TIMEOUT_MS = int(os.environ.get("DPT_JOIN_TIMEOUT_MS", "10000"))


def _member_call(host, port, tag, obj, timeout_ms=None):
    timeout_ms = JOIN_TIMEOUT_MS if timeout_ms is None else timeout_ms
    conn = native.connect(host, port, timeout_ms=timeout_ms)
    try:
        if timeout_ms:
            conn.set_timeout(timeout_ms)
        conn.send(tag, protocol.encode_json(obj))
        rtag, rpayload = conn.recv()
    finally:
        conn.close()
    if rtag != protocol.OK:
        raise RuntimeError(
            f"membership call failed: {protocol.decode_json(rpayload)}")
    return protocol.decode_json(rpayload)


def join_fleet(join_host, join_port, my_host, my_port, store=False,
               retry_s=None):
    """Announce one worker to the membership server, retrying while the
    server comes up (the supervisor may spawn workers before the
    dispatcher finishes binding). Returns the JOIN reply."""
    deadline = time.monotonic() + (JOIN_RETRY_S if retry_s is None
                                   else retry_s)
    last = None
    while True:
        try:
            return _member_call(join_host, join_port, protocol.JOIN,
                                {"host": my_host, "port": my_port,
                                 "store": bool(store)})
        except (ConnectionError, OSError) as e:
            last = e
            if time.monotonic() >= deadline:
                raise ConnectionError(
                    f"cannot join fleet at {join_host}:{join_port}: "
                    f"{last!r}") from last
            time.sleep(0.25)


def report_ready(join_host, join_port, my_host, my_port, stats):
    """Post-warm-sync JOIN update (phase=ready): best-effort — a lost
    update only loses the warm_rejoin_s observation, never membership."""
    try:
        return _member_call(join_host, join_port, protocol.JOIN,
                            {"host": my_host, "port": my_port,
                             "phase": "ready", "stats": stats})
    except (ConnectionError, OSError, RuntimeError):
        return None


def leave_fleet(join_host, join_port, host, port):
    """Declare (host, port) permanently gone (the supervisor's flap-cap
    path). Best-effort; returns the reply or None."""
    try:
        return _member_call(join_host, join_port, protocol.LEAVE,
                            {"host": host, "port": port})
    except (ConnectionError, OSError, RuntimeError):
        return None
