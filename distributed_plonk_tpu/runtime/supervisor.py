"""Worker supervision: spawn, liveness-watch, respawn with backoff.

The process-level half of the self-healing fleet (runtime/membership.py
is the fleet-level half): a WorkerSupervisor owns N local worker
SUBPROCESSES started with `--join`, watches each one's liveness through
the existing HEALTH probe with a consecutive-miss budget, and respawns
dead or wedged ones with jittered exponential backoff. A respawned
worker rejoins through the exact same JOIN path as a brand-new one —
same port, same fleet index, re-admitted via the PR 6 breaker machinery
and warm-rejoined from the roster's store peers; the supervisor has no
special re-entry protocol.

A crash-looping worker (bad binary, poisoned store) must not be
respawned forever: `flap_cap` respawns inside `flap_window_s` marks the
slot FAILED, stops respawning it, and (when the membership address is
known) declares it gone with a LEAVE so the fleet stops probing the
corpse. Counters land in the duck-typed metrics registry:
worker_respawns / worker_flap_capped / supervisor_probe_misses /
worker_retires, gauge supervised_workers (active slots: not failed, not
retired).

Scale-down is graceful (`retire_slot`, the autoscaler's down actuator):
drain (HEALTH's fft_tasks table empties) -> membership LEAVE -> SIGTERM,
escalating to SIGKILL only past DPT_SUP_RETIRE_TIMEOUT_S per phase. The
ordering is the no-lost-work contract: the worker finishes or
checkpoints its in-flight ranges BEFORE the fleet stops routing to it,
and is only signalled after it is out of the roster. A retired slot is
NOT a flap — it leaves supervision entirely: the watch loop skips it, it
is never respawned, and it adds nothing to the flap window
(tests/test_autoscale.py pins worker_flap_capped staying 0 across a
retire).

Startup is graced: the miss budget only ticks once a worker has answered
its FIRST probe — before that, only `startup_grace_s` elapsing counts as
wedged. A freshly spawned interpreter on a loaded host can take tens of
seconds to import and bind; probing it at the steady-state cadence would
wedge-kill healthy starting workers in a loop straight into the flap cap
(found live under tier-1 load).

Knobs (env, read at construction; constructor args override):
    DPT_SUP_PROBE_MS        liveness probe interval (500)
    DPT_SUP_PROBE_TIMEOUT_MS  per-probe budget (3000)
    DPT_SUP_MISS_BUDGET     consecutive misses before a respawn (3)
    DPT_SUP_STARTUP_GRACE_S first-answer deadline for a fresh spawn (120)
    DPT_SUP_BACKOFF_BASE_MS first respawn delay (250)
    DPT_SUP_BACKOFF_MAX_MS  respawn delay ceiling (10000)
    DPT_SUP_FLAP_CAP        respawns inside the window before giving up (5)
    DPT_SUP_FLAP_WINDOW_S   the flap-counting window (60)
    DPT_SUP_RETIRE_TIMEOUT_S  retire_slot per-phase budget: drain wait,
                            then SIGTERM wait before SIGKILL (20)
"""

import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time

from . import membership
from .dispatcher import WorkerHandle
from .health import NullMetrics
from ..obs import log as olog


def _env_ms(name, default):
    # analysis: ok(host-only ms->s conversion, no traced arithmetic)
    return float(os.environ.get(name, default)) / 1000.0


def reserve_port(host="127.0.0.1"):
    """Pick a currently-free port for a worker slot. The tiny bind race
    (another process grabbing it before the worker does) is tolerated on
    the loopback deployments this targets: the worker's bind then fails,
    the supervisor sees the death and respawns on a fresh port."""
    s = socket.socket()
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


class _Slot:
    """One supervised worker: its reserved address, live subprocess, and
    flap bookkeeping. Mutated only under the supervisor's lock."""

    def __init__(self, port, store_dir=None):
        self.port = port
        self.store_dir = store_dir
        self.proc = None
        self.misses = 0
        self.backoff = 0.0
        self.next_spawn = 0.0
        self.spawn_times = []  # monotonic stamps inside the flap window
        self.spawned_at = 0.0
        self.answered = False  # this incarnation answered >= 1 probe
        self.healthy_since = None
        self.failed = False
        self.retired = False
        self.respawns = 0


class WorkerSupervisor:
    def __init__(self, join_host, join_port, n=0, backend="python",
                 host="127.0.0.1", store_dirs=None, metrics=None,
                 probe_interval_s=None, probe_timeout_ms=None,
                 miss_budget=None, startup_grace_s=None,
                 backoff_base_s=None, backoff_max_s=None,
                 flap_cap=None, flap_window_s=None, cwd=None, rng=None,
                 spawn_cmd=None, extra_args=None):
        """spawn_cmd(slot_index, slot) -> argv overrides the worker
        command line (tests inject crash-looping commands); store_dirs:
        per-slot artifact-store dirs (workers then serve STORE_FETCH and
        warm-rejoin on respawn)."""
        self.join_host, self.join_port = join_host, join_port
        self.backend = backend
        self.host = host
        self.metrics = metrics or NullMetrics()
        self.cwd = cwd
        self.spawn_cmd = spawn_cmd
        self.extra_args = list(extra_args or [])
        self.probe_interval_s = probe_interval_s if probe_interval_s \
            is not None else _env_ms("DPT_SUP_PROBE_MS", "500")
        self.probe_timeout_ms = probe_timeout_ms if probe_timeout_ms \
            is not None else int(os.environ.get("DPT_SUP_PROBE_TIMEOUT_MS",
                                                "3000"))
        self.miss_budget = miss_budget if miss_budget is not None else \
            int(os.environ.get("DPT_SUP_MISS_BUDGET", "3"))
        self.startup_grace_s = startup_grace_s if startup_grace_s \
            is not None else float(os.environ.get("DPT_SUP_STARTUP_GRACE_S",
                                                  "120"))
        self.backoff_base_s = backoff_base_s if backoff_base_s is not None \
            else _env_ms("DPT_SUP_BACKOFF_BASE_MS", "250")
        self.backoff_max_s = backoff_max_s if backoff_max_s is not None \
            else _env_ms("DPT_SUP_BACKOFF_MAX_MS", "10000")
        self.flap_cap = flap_cap if flap_cap is not None else \
            int(os.environ.get("DPT_SUP_FLAP_CAP", "5"))
        self.flap_window_s = flap_window_s if flap_window_s is not None \
            else float(os.environ.get("DPT_SUP_FLAP_WINDOW_S", "60"))
        self.retire_timeout_s = float(
            os.environ.get("DPT_SUP_RETIRE_TIMEOUT_S", "20"))
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watcher = None
        store_dirs = list(store_dirs or [])
        self.slots = [
            _Slot(reserve_port(host),
                  store_dirs[i] if i < len(store_dirs) else None)
            for i in range(n)]

    # -- lifecycle ------------------------------------------------------------

    def start(self):
        for i in range(len(self.slots)):
            self._spawn(i)
        self._watcher = threading.Thread(target=self._watch_loop,
                                         name="worker-supervisor",
                                         daemon=True)
        self._watcher.start()
        return self

    def stop(self):
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=10)
        with self._lock:
            procs = [s.proc for s in self.slots if s.proc is not None]
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass

    def attach_registry(self, registry):
        """Close the quarantine loop (runtime/integrity.py): when the
        membership registry LEAVEs a member with reason="integrity", the
        process is ALIVE — it answers probes, its answers are wrong — so
        liveness supervision alone would never replace it. Subscribing
        here turns the quarantine verdict into a SIGKILL of the owning
        slot; the normal watch loop then respawns it (backoff + flap-cap
        rules apply to repeat offenders) and the fresh process re-JOINs
        through the challenge gate."""
        def _on_event(ev):
            if ev.get("event") != "leave" \
                    or ev.get("reason") != "integrity":
                return
            j = self.slot_for_port(ev.get("port"))
            if j is not None:
                # kill() waits on the process: never block the
                # registry's emit path behind it
                threading.Thread(target=self.kill, args=(j,),
                                 daemon=True).start()
        registry.subscribe(_on_event)
        return self

    def add_slot(self, store_dir=None):
        """Grow the supervised fleet by one slot at runtime (scale-up):
        the new worker takes the exact JOIN path of every other member.
        Returns the slot index; the worker is spawned immediately."""
        with self._lock:
            self.slots.append(_Slot(reserve_port(self.host), store_dir))
            i = len(self.slots) - 1
        self._spawn(i)
        return i

    def retire_slot(self, i, timeout_s=None):
        """Graceful scale-down of slot i: drain -> LEAVE -> SIGTERM, with
        SIGKILL escalation only past the per-phase budget
        (DPT_SUP_RETIRE_TIMEOUT_S). Order is the no-lost-work contract:
        the worker first empties its in-flight task table (HEALTH's
        fft_tasks — finished or checkpointed), is THEN declared gone
        through the membership registry so nothing new routes to it, and
        only after that receives a signal — a retiring worker is never
        killed mid-prove. Marking `retired` under the lock first takes
        the slot out of supervision atomically: the watch loop skips it,
        nothing respawns it, and the retire is not a flap. Returns True
        iff this call performed the retire (False: already retired /
        failed)."""
        budget = self.retire_timeout_s if timeout_s is None else timeout_s
        with self._lock:
            slot = self.slots[i]
            if slot.retired or slot.failed:
                return False
            slot.retired = True
            proc = slot.proc
        olog.emit("supervisor", "retire", slot=i, port=slot.port)
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            if proc is None or proc.poll() is not None:
                break  # already dead == already drained
            snap = WorkerHandle(self.host, slot.port).probe(
                timeout_ms=self.probe_timeout_ms)
            if snap is not None and not snap.get("fft_tasks"):
                break
            time.sleep(min(0.1, self.probe_interval_s))
        # LEAVE before any signal: the fleet must stop routing first
        membership.leave_fleet(self.join_host, self.join_port,
                               self.host, slot.port)
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=max(1.0, budget))
            except subprocess.TimeoutExpired:
                # SIGTERM ignored past the budget — the member already
                # LEAVEd and drained, so a hard kill cannot lose work
                proc.kill()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        self.metrics.inc("worker_retires")
        self.metrics.gauge("supervised_workers", self.active_count())
        olog.emit("supervisor", "retired", slot=i, port=slot.port)
        return True

    def active_count(self):
        """Slots still under supervision (not failed, not retired) —
        the autoscaler's worker-count sensor."""
        with self._lock:
            return sum(1 for s in self.slots
                       if not s.failed and not s.retired)

    # -- chaos / introspection -------------------------------------------------

    def slot_for_port(self, port):
        with self._lock:
            for j, s in enumerate(self.slots):
                if s.port == port:
                    return j
        return None

    def proc_killer(self, dispatcher):
        """kill_cb for the `kill:at=proc` chaos plane: the injector hands
        over a DISPATCHER worker index, which need not equal the slot
        index (join order is concurrent) — translate through the
        address, which is the stable identity on both sides."""
        def _kill(i):
            j = self.slot_for_port(dispatcher.workers[i].port)
            if j is not None:
                self.kill(j)
        return _kill

    def kill(self, i, sig=signal.SIGKILL):
        """SIGKILL slot i's subprocess — the `kill:at=proc` chaos plane's
        callback (runtime/faults.py) and the heal canary's trigger. The
        watch loop then detects the death and respawns through the
        normal path."""
        with self._lock:
            proc = self.slots[i].proc
        if proc is not None and proc.poll() is None:
            proc.send_signal(sig)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass

    def address(self, i):
        return self.host, self.slots[i].port

    def snapshot(self):
        with self._lock:
            return [{"port": s.port, "respawns": s.respawns,
                     "failed": s.failed, "retired": s.retired,
                     "alive": s.proc is not None and s.proc.poll() is None}
                    for s in self.slots]

    # -- internals ------------------------------------------------------------

    def _cmd(self, i, slot):
        if self.spawn_cmd is not None:
            return self.spawn_cmd(i, slot)
        cmd = [sys.executable, "-m", "distributed_plonk_tpu.runtime.worker",
               "--join", f"{self.join_host}:{self.join_port}",
               "--listen", f"{self.host}:{slot.port}",
               "--backend", self.backend]
        if slot.store_dir is not None:
            cmd += ["--store", slot.store_dir]
        return cmd + self.extra_args

    def _spawn(self, i):
        """Start slot i's subprocess (caller ensured backoff elapsed)."""
        with self._lock:
            slot = self.slots[i]
            if slot.failed or slot.retired or self._stop.is_set():
                return
            now = time.monotonic()
            slot.spawn_times = [t for t in slot.spawn_times
                                if now - t <= self.flap_window_s]
            slot.spawn_times.append(now)
            slot.misses = 0
            slot.healthy_since = None
            slot.spawned_at = now
            slot.answered = False
            first = slot.proc is None
            slot.proc = subprocess.Popen(self._cmd(i, slot), cwd=self.cwd)
        if not first:
            self.metrics.inc("worker_respawns")
            with self._lock:
                slot.respawns += 1
            olog.emit("supervisor", "respawn", level="warn", slot=i,
                      port=slot.port, respawns=slot.respawns)
        else:
            olog.emit("supervisor", "spawn", slot=i, port=slot.port)
        self.metrics.gauge("supervised_workers", self.active_count())

    def _schedule_respawn(self, i):
        """Slot i's process is dead/wedged: arm the next spawn time with
        jittered exponential backoff, or give up at the flap cap (stop
        respawning, declare the member gone via LEAVE)."""
        now = time.monotonic()
        gave_up = False
        with self._lock:
            slot = self.slots[i]
            if slot.failed or slot.retired:
                return
            recent = [t for t in slot.spawn_times
                      if now - t <= self.flap_window_s]
            if len(recent) >= self.flap_cap:
                slot.failed = True
                gave_up = True
            else:
                slot.backoff = min(self.backoff_max_s,
                                   (slot.backoff * 2) or self.backoff_base_s)
                jitter = 1.0 + 0.5 * self._rng.random()  # analysis: ok(host-only jitter)
                slot.next_spawn = now + slot.backoff * jitter
                slot.misses = 0
        if gave_up:
            # network call outside the lock: a slow membership server
            # must not stall supervision of the other slots
            self.metrics.inc("worker_flap_capped")
            olog.emit("supervisor", "flap_capped", level="error", slot=i,
                      port=slot.port)
            membership.leave_fleet(self.join_host, self.join_port,
                                   self.host, slot.port)

    def _watch_one(self, i):
        now = time.monotonic()
        with self._lock:
            slot = self.slots[i]
            if slot.failed or slot.retired:
                return
            proc, next_spawn = slot.proc, slot.next_spawn
        if proc is None or proc.poll() is not None:
            # process is gone: respawn once the backoff window passes
            if next_spawn == 0.0:
                self._schedule_respawn(i)
            elif now >= next_spawn:
                with self._lock:
                    slot.next_spawn = 0.0
                self._spawn(i)
            return
        # process alive: probe HEALTH (a wedged worker answers nothing)
        h, p = self.address(i)
        snap = WorkerHandle(h, p).probe(timeout_ms=self.probe_timeout_ms)
        with self._lock:
            if snap is None:
                self.metrics.inc("supervisor_probe_misses")
                if not slot.answered:
                    # STARTUP GRACE: a fresh interpreter on a loaded
                    # host takes tens of seconds to import and bind —
                    # the steady-state miss budget would wedge-kill
                    # healthy starting workers in a loop straight into
                    # the flap cap. Before the first answer, only the
                    # grace deadline counts as wedged.
                    wedged = (now - slot.spawned_at
                              >= self.startup_grace_s)
                else:
                    slot.misses += 1
                    slot.healthy_since = None
                    wedged = slot.misses >= self.miss_budget
            else:
                slot.answered = True
                slot.misses = 0
                if slot.healthy_since is None:
                    slot.healthy_since = now
                elif now - slot.healthy_since >= self.flap_window_s:
                    slot.backoff = 0.0  # stable again: forgive the past
                wedged = False
        if wedged:
            olog.emit("supervisor", "wedge_kill", level="warn", slot=i,
                      port=p)
            self.kill(i)
            self._schedule_respawn(i)

    def _watch_loop(self):
        while not self._stop.wait(self.probe_interval_s):
            for i in range(len(self.slots)):
                if self._stop.is_set():
                    return
                try:
                    self._watch_one(i)
                except Exception:  # supervision must outlive any one slot
                    pass
