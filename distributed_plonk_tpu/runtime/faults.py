"""Deterministic chaos injection for the distributed prover.

One injector object threads through both failure planes:

  wire plane (runtime/dispatcher.py): `on_send(worker, tag, payload)` runs
      just before every dispatcher->worker frame. Rules select a protocol
      tag + worker + Nth occurrence (deterministic: the chaos sweep kills a
      worker at EXACTLY one protocol phase per run) or a probability
      (loadgen chaos soak). Actions:
        kill     invoke the registered kill callback (test harness kills
                 the worker process; a real deploy could fence a pod)

  proc plane (`at=proc`): rides the SAME on_send occurrence matching as
      the wire plane, but `kill` invokes `proc_kill_cb` — registered by
      the supervisor (runtime/supervisor.py) as a real SIGKILL of the
      worker SUBPROCESS. Where the wire plane models "the frame/worker
      vanished" at the dispatcher's edge, the proc plane kills an actual
      OS process so the chaos harness exercises the supervisor's real
      detect -> respawn -> rejoin recovery path:
        DPT_FAULTS="kill:at=proc:tag=FFT1:worker=1"
        drop     raise InjectedDrop (a ConnectionError) without sending —
                 the frame "was lost"; the handle's reconnect/backoff path
                 must resend (worker handlers are idempotent)
        corrupt  scramble the frame TAG so the receiver rejects it loudly
                 (ERR "unknown tag") — modeling a framing-level corruption
                 the way the transport can actually detect it; payload
                 bit-flips below the codec's radar are modeled on the
                 checkpoint plane instead, where SHA-256 catches them
        delay    sleep `ms` (slow worker / congested link)

  checkpoint plane (service/pool.py): `on_round(round_no, checkpoint)`
      runs at every prover round boundary, after the snapshot is durable.
      Actions:
        delay         sleep `ms` (slow prover)
        corrupt_ckpt  flip a byte inside the just-written snapshot
                      artifact (checkpoint.chaos_corrupt()) — the
                      integrity layer (SHA-256 in the store, zip/manifest
                      validation on files) must detect it and restart the
                      prove cleanly rather than resume garbage

  data plane (`at=data`, runtime/worker.py): `on_data(worker, tag)` runs
      WORKER-SIDE, right after a result is computed and before it is
      framed — the silent-data-corruption model (a flipped limb from a
      bad chip, stale device state): the worker perturbs its OWN
      computed value (MSM partial += G1 generator; FFT2 panel / NTT /
      EVAL element += 1 mod r), so the corruption is a WELL-FORMED wrong
      answer under every CRC/SHA layer. `worker` matches the worker's
      own fleet index (each worker process parses DPT_FAULTS itself);
      `tag` matches the protocol tag whose result is being corrupted
      (MSM, NTT, FFT2, EVAL). Only the result-integrity plane
      (runtime/integrity.py) or the self-verify backstop can catch it:
        DPT_FAULTS="corrupt:at=data:tag=MSM:worker=1"

  proof plane (`at=proof`, service/pool.py): `on_proof(job_id)` runs in
      the service right after a finished proof is serialized and BEFORE
      the verify-before-serve gate — SDC between prove and serve. The
      pool flips a byte in the proof bytes; DPT_SELF_VERIFY must block
      it from ever reaching a journal DONE record or a client:
        DPT_FAULTS="corrupt:at=proof:rate=0.3"

  journal plane (service/journal.py): `on_journal(rtype, label, job_id)`
      runs right after each job-journal record is DURABLE (fsync'd).
      `tag` matches the record type ("SUBMIT", "START", "ROUND", "DONE",
      "SHED", "FAILED") or a round-qualified label ("ROUND2"). Actions:
        kill    invoke the kill callback — scripts/serve.py registers
                os._exit, so `DPT_FAULTS="kill:at=journal:tag=ROUND2"`
                kills the SERVICE PROCESS at exactly that journal
                occurrence (the restart-recovery test plane: the record
                is on disk, nothing after it is)
        delay   sleep `ms` (slow journal device)

Rules come from code (tests) or from the environment:

    DPT_FAULTS="kill:tag=FFT1:worker=1:nth=1;delay:tag=MSM:ms=50"
    DPT_FAULTS="kill:at=journal:tag=ROUND2"

Entries are `action[:key=value]*` separated by `;`. Keys: tag (name,
number, or — on the journal plane — a record label string), worker, nth
(1-based occurrence; default 1), rate (probability, overrides nth), ms,
max (max fires, default 1 for nth rules, unlimited for rate rules), at
(plane: wire | proc | round | journal | data | proof). Occurrence
counting is per-rule and thread-safe.
"""

import os
import random
import threading
import time

from . import protocol


class InjectedDrop(ConnectionError):
    """A frame the injector 'lost' before it hit the socket."""


# scrambling the tag keeps the frame well-formed but unroutable, so the
# receiver's reply is a deterministic ERR (unknown tag), never a silently
# wrong computation
_CORRUPT_TAG_XOR = 0x40000000

_TAG_NAMES = {name: value for name, value in vars(protocol).items()
              if name.isupper() and isinstance(value, int)}


class Rule:
    def __init__(self, action, tag=None, worker=None, nth=1, rate=None,
                 ms=0.0, max_fires=None, plane=None):
        assert action in ("kill", "drop", "corrupt", "delay", "corrupt_ckpt"), action
        self.action = action
        self.tag = tag          # protocol tag int (wire) / round no (round)
        self.worker = worker    # worker index, or None = any
        self.nth = nth          # 1-based matching-occurrence to fire on
        self.rate = rate        # probability per occurrence (overrides nth)
        self.ms = ms
        # which hook runs the rule: corrupt_ckpt only makes sense at round
        # boundaries; everything else defaults to the wire (at=round in the
        # env spec, or plane="round" in code, moves a delay to the pool)
        self.plane = plane or ("round" if action == "corrupt_ckpt" else "wire")
        if max_fires is None:
            max_fires = None if rate is not None else 1
        self.max_fires = max_fires
        self.seen = 0
        self.fired = 0

    def matches(self, tag=None, worker=None):
        if self.max_fires is not None and self.fired >= self.max_fires:
            return False
        if self.tag is not None and tag != self.tag:
            return False
        if self.worker is not None and worker is not None \
                and worker != self.worker:
            return False
        return True

    @classmethod
    def parse(cls, entry):
        """'kill:tag=FFT1:worker=1:nth=2' -> Rule. Tag resolution is
        plane-aware (after all keys are read, since `at=` may follow
        `tag=`): journal rules keep the record-label STRING — "SUBMIT"
        is both a protocol tag name and a journal record type, and a
        journal rule must match the latter."""
        parts = entry.strip().split(":")
        action, kvs = parts[0], parts[1:]
        kw = {}
        tag_raw = None
        for kv in kvs:
            k, _, v = kv.partition("=")
            k = k.strip()
            v = v.strip()
            if k == "tag":
                tag_raw = v
            elif k == "worker":
                kw["worker"] = int(v)
            elif k == "nth":
                kw["nth"] = int(v)
            elif k == "rate":
                kw["rate"] = float(v)
            elif k == "ms":
                kw["ms"] = float(v)
            elif k == "max":
                kw["max_fires"] = int(v)
            elif k == "at":
                kw["plane"] = v
            else:
                raise ValueError(f"unknown fault key {k!r} in {entry!r}")
        if tag_raw is not None:
            if kw.get("plane") == "journal":
                kw["tag"] = tag_raw                 # record label string
            elif tag_raw in _TAG_NAMES:
                kw["tag"] = _TAG_NAMES[tag_raw]     # protocol tag name
            else:
                kw["tag"] = int(tag_raw)
        return cls(action, **kw)


class FaultInjector:
    """Holds the rule set + side-effect callbacks; thread-safe.

    kill_cb(worker_index): registered by the harness that owns the worker
    processes. metrics: duck-typed inc() (service.metrics.Metrics). rng:
    rate-based decisions (seed it for reproducible soaks).
    """

    def __init__(self, rules=None, kill_cb=None, metrics=None, rng=None,
                 proc_kill_cb=None):
        self.rules = list(rules or [])
        self.kill_cb = kill_cb
        # proc-plane kill: SIGKILL the worker SUBPROCESS (the supervisor
        # registers its kill(); falls back to kill_cb when unset so a
        # harness with one process-level callback serves both planes)
        self.proc_kill_cb = proc_kill_cb
        self.metrics = metrics
        self._rng = rng or random.Random()
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, env_var="DPT_FAULTS", **kwargs):
        """Injector from the env spec; None when the variable is unset or
        empty (callers keep a zero-overhead fast path)."""
        spec = os.environ.get(env_var, "").strip()
        if not spec:
            return None
        rules = [Rule.parse(e) for e in spec.split(";") if e.strip()]
        return cls(rules, **kwargs)

    def _inc(self, name):
        if self.metrics is not None:
            self.metrics.inc(name)

    def _due(self, rule, tag=None, worker=None):
        """Occurrence bookkeeping under the lock; returns True to fire."""
        with self._lock:
            if not rule.matches(tag=tag, worker=worker):
                return False
            rule.seen += 1
            if rule.rate is not None:
                fire = self._rng.random() < rule.rate
            else:
                fire = rule.seen == rule.nth
            if fire:
                rule.fired += 1
            return fire

    # -- wire plane (dispatcher) ----------------------------------------------

    def on_send(self, worker, tag, payload):
        """Run matching wire rules; returns the (possibly corrupted) tag.
        May sleep (delay), raise InjectedDrop (drop), or kill the worker
        out from under the send (kill)."""
        for rule in self.rules:
            if rule.plane not in ("wire", "proc"):
                continue
            if not self._due(rule, tag=tag, worker=worker):
                continue
            self._inc(f"faults_injected_{rule.action}")
            if rule.action == "delay":
                time.sleep(rule.ms / 1000.0)  # analysis: ok(host-only ms->s)
            elif rule.action == "drop":
                raise InjectedDrop(
                    f"injected drop of tag {tag} to worker {worker}")
            elif rule.action == "corrupt":
                tag = tag ^ _CORRUPT_TAG_XOR
            elif rule.action == "kill":
                # proc-plane kill SIGKILLs the actual subprocess (the
                # supervisor's recovery path gets exercised for real)
                cb = (self.proc_kill_cb or self.kill_cb) \
                    if rule.plane == "proc" else self.kill_cb
                if cb is not None:
                    cb(worker)
        return tag

    # -- data plane (worker-side SDC) -----------------------------------------

    def on_data(self, worker, tag):
        """Worker-side hook, run between 'result computed' and 'result
        framed': True when a matching `corrupt:at=data` rule fires — the
        caller then perturbs the value it just computed (modeling SDC in
        the compute path itself: everything downstream, including any
        piggybacked integrity partials, sees the corrupted buffer)."""
        fired = False
        for rule in self.rules:
            if rule.plane != "data" or rule.action != "corrupt":
                continue
            if not self._due(rule, tag=tag, worker=worker):
                continue
            self._inc("faults_injected_corrupt")
            fired = True
        return fired

    # -- proof plane (service, post-serialize) --------------------------------

    def on_proof(self, job_id=None):
        """True when a `corrupt:at=proof` rule fires for this finished
        proof: the pool flips a byte in the serialized proof before the
        verify-before-serve gate sees it."""
        fired = False
        for rule in self.rules:
            if rule.plane != "proof" or rule.action != "corrupt":
                continue
            if not self._due(rule, tag=rule.tag):
                continue
            self._inc("faults_injected_corrupt")
            fired = True
        return fired

    # -- checkpoint plane (prover pool) ---------------------------------------

    def on_round(self, round_no, checkpoint=None):
        """Round-boundary hook: `tag` in rules is interpreted as the round
        number here (tag=2 -> after round 2), None = every round."""
        for rule in self.rules:
            if rule.plane != "round":
                continue
            if not self._due(rule, tag=round_no):
                continue
            self._inc(f"faults_injected_{rule.action}")
            if rule.action == "delay":
                time.sleep(rule.ms / 1000.0)  # analysis: ok(host-only ms->s)
            elif rule.action == "corrupt_ckpt" and checkpoint is not None:
                if checkpoint.chaos_corrupt():
                    self._inc("faults_ckpt_corrupted")

    # -- journal plane (proof-service job journal) ----------------------------

    def on_journal(self, rtype, label, job_id=None):
        """Post-append hook: `tag` in journal rules matches either the
        bare record type ("ROUND": any round) or the qualified label
        ("ROUND2": that round exactly). The record is already durable
        when this runs, so a kill here models a crash with this
        transition journaled and nothing after it."""
        for rule in self.rules:
            if rule.plane != "journal":
                continue
            if rule.tag is not None and rule.tag not in (rtype, label):
                continue
            # tag match done above (two aliases per occurrence); _due only
            # does the nth/rate/max bookkeeping
            if not self._due(rule, tag=rule.tag):
                continue
            self._inc(f"faults_injected_{rule.action}")
            if rule.action == "delay":
                time.sleep(rule.ms / 1000.0)  # analysis: ok(host-only ms->s)
            elif rule.action == "kill":
                if self.kill_cb is not None:
                    self.kill_cb(label)

    def counts(self):
        with self._lock:
            return {f"{r.action}@{r.tag}": {"seen": r.seen, "fired": r.fired}
                    for r in self.rules}
