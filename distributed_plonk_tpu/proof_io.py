"""Proof (de)serialization: the pinned wire layout for golden fixtures.

The reference's Proof<Bls12_381> is assembled at
/root/reference/src/dispatcher2.rs:699-710 and serialized only implicitly
through ark-serialize. This repo pins an EXPLICIT layout so full proofs
can be stored as golden fixtures (tests/test_proof_golden.py) and
compared byte-for-byte across backends and rounds — the regression floor
VERDICT r4 asked for in lieu of a jf-plonk fixture (no Rust toolchain in
this environment to record one).

Layout (fixed width, 944 bytes total; field order mirrors the reference's
Proof struct and the verifier's transcript order, verifier.py:78-79):

  offset  size  field
  ------  ----  -----------------------------------------------------
  0       5x48  wires_poly_comms      5 G1, zcash compressed (encoding.py)
  240     1x48  prod_perm_poly_comm   1 G1
  288     5x48  split_quot_poly_comms 5 G1
  528     1x48  opening_proof         1 G1
  576     1x48  shifted_opening_proof 1 G1
  624     5x32  wires_evals           5 Fr, 32-byte little-endian canonical
  784     4x32  wire_sigma_evals      4 Fr
  912     1x32  perm_next_eval        1 Fr

G1 points use the zcash/IETF compressed format (48 bytes, external golden
vectors — encoding.py), so deserialization validates curve membership AND
the r-order subgroup. Fr scalars are canonical (< r) little-endian, the
arkworks PrimeField byte order used on the transcript (transcript.py).
"""

from .constants import R_MOD
from .circuit import NUM_WIRE_TYPES
from . import encoding as E
from .prover import Proof

PROOF_BYTES = 13 * 48 + 10 * 32


def _fr_bytes(x):
    assert 0 <= x < R_MOD
    return int(x).to_bytes(32, "little")


def serialize_proof(proof):
    """Proof -> 944 fixed-layout bytes (see module docstring)."""
    out = bytearray()
    points = (list(proof.wires_poly_comms) + [proof.prod_perm_poly_comm]
              + list(proof.split_quot_poly_comms)
              + [proof.opening_proof, proof.shifted_opening_proof])
    assert len(points) == 2 * NUM_WIRE_TYPES + 3
    for p in points:
        out += E.g1_to_zcash(p)
    scalars = (list(proof.wires_evals) + list(proof.wire_sigma_evals)
               + [proof.perm_next_eval])
    assert len(scalars) == 2 * NUM_WIRE_TYPES
    for s in scalars:
        out += _fr_bytes(s)
    assert len(out) == PROOF_BYTES
    return bytes(out)


def deserialize_proof(b):
    """944 fixed-layout bytes -> Proof (validates every point, including
    the subgroup check, and every scalar's canonical range)."""
    b = bytes(b)
    if len(b) != PROOF_BYTES:
        raise ValueError(f"proof must be {PROOF_BYTES} bytes, got {len(b)}")
    w = NUM_WIRE_TYPES
    points = [E.g1_from_zcash(b[i * 48:(i + 1) * 48]) for i in range(2 * w + 3)]
    off = (2 * w + 3) * 48
    scalars = []
    for i in range(2 * w):
        x = int.from_bytes(b[off + i * 32:off + (i + 1) * 32], "little")
        if x >= R_MOD:
            raise ValueError("scalar out of canonical range")
        scalars.append(x)
    return Proof(
        wires_poly_comms=points[:w],
        prod_perm_poly_comm=points[w],
        split_quot_poly_comms=points[w + 1:2 * w + 1],
        opening_proof=points[2 * w + 1],
        shifted_opening_proof=points[2 * w + 2],
        wires_evals=scalars[:w],
        wire_sigma_evals=scalars[w:2 * w - 1],
        perm_next_eval=scalars[2 * w - 1],
    )
