"""Rescue-Prime permutation + sponge hash over Fr, native and in-circuit.

Re-provides the `jf-primitives` Rescue surface the reference's workload
generator consumes (/root/reference/src/dispatcher.rs:25-26,1076-1108 pulls
`RescueParameter`-based MerkleTree + `MerkleTreeGadget`; the crate itself is
out-of-tree, so this is a fresh Rescue-Prime instantiation, not a byte
clone). Parameters follow the published Rescue-Prime spec (Szepieniec,
Ashur, Dhooghe 2020) specialised to TurboPlonk's gate set:

  - alpha = 5: the forward S-box x^5 is exactly the q_hash gate
    (/root/reference/src/dispatcher2.rs:469-473), and the inverse S-box
    x^(1/5) is one gate run backwards (witness the root, enforce the power).
  - state width m = 4 = GATE_WIDTH: one MDS row spans one gate's four
    input wires, so a full affine layer is 4 gates.
  - capacity 1, rate 3: a 3-ary Merkle node (two siblings + child) or a
    (leaf-index, payload, domain-tag) triple absorbs in a single permutation.

Round constants and the MDS matrix are derived deterministically from
SHAKE-256 (nothing-up-my-sleeve, as in the Rescue-Prime reference code).
In-circuit cost: 12 gates/round, 144 gates/permutation - the same order as
the reference's stated 157 constraints per Merkle level
(/root/reference/src/dispatcher.rs:1068-1070).
"""

import hashlib

from .constants import R_MOD

STATE_WIDTH = 4
RATE = 3
CAPACITY = 1
ALPHA = 5
ALPHA_INV = pow(ALPHA, -1, R_MOD - 1)
NUM_ROUNDS = 12  # jf-primitives' ROUNDS for the width-4 BLS12-381 instance

_FR_BYTES = 32


def _shake_field_elements(tag, count):
    """Deterministic field elements: SHAKE-256(tag), rejection-free
    reduction of 512-bit draws (bias < 2^-257)."""
    out = []
    shake = hashlib.shake_256(tag.encode())
    stream = shake.digest(count * 2 * _FR_BYTES)
    for i in range(count):
        chunk = stream[i * 2 * _FR_BYTES:(i + 1) * 2 * _FR_BYTES]
        out.append(int.from_bytes(chunk, "little") % R_MOD)
    return out


def _derive_mds():
    """4x4 Cauchy matrix M[i][j] = 1/(x_i + y_j): MDS whenever the x_i and
    y_j are distinct and all sums nonzero (every square submatrix of a
    Cauchy matrix is invertible)."""
    attempt = 0
    while True:
        # attempt counter in the tag: every retry draws fresh elements
        # (a fixed tag would loop forever if the first draw ever failed)
        elems = _shake_field_elements(
            f"dpt-rescue-mds-v1-{attempt}", 2 * STATE_WIDTH)
        xs, ys = elems[:STATE_WIDTH], elems[STATE_WIDTH:]
        if len(set(xs)) == STATE_WIDTH and len(set(ys)) == STATE_WIDTH and all(
                (x + y) % R_MOD != 0 for x in xs for y in ys):
            break
        attempt += 1
    return [[pow((x + y) % R_MOD, -1, R_MOD) for y in ys] for x in xs]


MDS = _derive_mds()
# 2 injections per round (after each half-round) + 1 pre-round injection
ROUND_KEYS = [
    _shake_field_elements(f"dpt-rescue-rk-v1-{k}", STATE_WIDTH)
    for k in range(2 * NUM_ROUNDS + 1)
]


def _affine(state, key):
    return [
        (sum(MDS[i][j] * state[j] for j in range(STATE_WIDTH)) + key[i]) % R_MOD
        for i in range(STATE_WIDTH)
    ]


def permutation(state):
    """The Rescue-Prime permutation on a 4-element Fr state."""
    assert len(state) == STATE_WIDTH
    state = [(ROUND_KEYS[0][i] + state[i]) % R_MOD for i in range(STATE_WIDTH)]
    for r in range(NUM_ROUNDS):
        state = [pow(x, ALPHA, R_MOD) for x in state]
        state = _affine(state, ROUND_KEYS[2 * r + 1])
        state = [pow(x, ALPHA_INV, R_MOD) for x in state]
        state = _affine(state, ROUND_KEYS[2 * r + 2])
    return state


def hash3(a, b, c):
    """Fixed-length 3-to-1 sponge: absorb (a,b,c) into the rate, one
    permutation, squeeze state[0]."""
    return permutation([a % R_MOD, b % R_MOD, c % R_MOD, 0])[0]


_SPONGE_IV = 2  # capacity-element IV: domain-separates the variable-length
# sponge from hash3 (capacity 0), so sponge([a,b]) can never collide with a
# fixed-length digest like leaf/node hashes


def sponge(inputs):
    """Variable-length sponge (rate 3, 10* zero-padding to a rate multiple,
    nonzero capacity IV for domain separation from hash3)."""
    data = [x % R_MOD for x in inputs] + [1]
    while len(data) % RATE:
        data.append(0)
    state = [0] * RATE + [_SPONGE_IV]
    for off in range(0, len(data), RATE):
        for i in range(RATE):
            state[i] = (state[i] + data[off + i]) % R_MOD
        state = permutation(state)
    return state[0]


# --- in-circuit gadgets ------------------------------------------------------

def permutation_gadget(cs, state_vars):
    """In-circuit Rescue-Prime permutation: 12 gates/round.

    Forward half-round: S-box + MDS row + round key fuse into ONE
    pow5_lc_with_const gate per output element (4 gates). Inverse
    half-round: 4 root5 gates (x^(1/5) witnessed, x^5 enforced) + 4
    lc_with_const gates for the affine layer.
    """
    assert len(state_vars) == STATE_WIDTH
    state_vars = [
        cs.add_constant(state_vars[i], ROUND_KEYS[0][i])
        for i in range(STATE_WIDTH)
    ]
    for r in range(NUM_ROUNDS):
        key1 = ROUND_KEYS[2 * r + 1]
        state_vars = [
            cs.pow5_lc_with_const(state_vars, MDS[i], key1[i])
            for i in range(STATE_WIDTH)
        ]
        roots = [cs.root5(v) for v in state_vars]
        key2 = ROUND_KEYS[2 * r + 2]
        state_vars = [
            cs.lc_with_const(roots, MDS[i], key2[i])
            for i in range(STATE_WIDTH)
        ]
    return state_vars


def hash3_gadget(cs, a, b, c):
    """In-circuit fixed-length 3-to-1 hash matching hash3()."""
    out_state = permutation_gadget(cs, [a, b, c, cs.zero_var])
    return out_state[0]
