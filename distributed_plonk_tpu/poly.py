"""Host-side polynomial utilities + reference radix-2 NTT (CPU oracle).

Mirrors the semantics of `ark-poly`'s Radix2EvaluationDomain as used by the
reference (fft/ifft/coset at /root/reference/src/worker.rs:82-115 and the
4-step decomposition spec at /root/reference/src/playground.rs:21-80):

  fft(c)[i]      = sum_j c_j w^{ij}              (evals on H)
  ifft(e)[j]     = 1/n sum_i e_i w^{-ij}
  coset_fft(c)   = fft(c_j * g^j)                (evals on gH, g = 7)
  coset_ifft(e)  = ifft(e)_j * g^{-j}

Everything here is pure Python over int lists - it is the oracle the JAX/TPU
NTT kernels (backend/ntt_jax.py) are asserted bit-identical against.
"""

from .constants import R_MOD, FR_GENERATOR
from .fields import fr_inv, fr_root_of_unity


class Domain:
    """Radix-2 evaluation domain over Fr (size a power of two)."""

    def __init__(self, min_size):
        n = 1
        while n < min_size:
            n <<= 1
        self.size = n
        self.log_size = n.bit_length() - 1
        self.group_gen = fr_root_of_unity(n)
        self.group_gen_inv = fr_inv(self.group_gen) if n > 1 else 1
        self.size_inv = fr_inv(n % R_MOD)
        self.coset_gen = FR_GENERATOR

    def elements(self):
        w = self.group_gen
        cur = 1
        for _ in range(self.size):
            yield cur
            cur = cur * w % R_MOD

    def vanishing_eval(self, tau):
        """Z_H(tau) = tau^n - 1."""
        return (pow(tau, self.size, R_MOD) - 1) % R_MOD


def _bit_reverse_permute(v):
    n = len(v)
    log_n = n.bit_length() - 1
    for i in range(n):
        j = int(bin(i)[2:].zfill(log_n)[::-1], 2) if log_n > 0 else 0
        if j > i:
            v[i], v[j] = v[j], v[i]


def _ntt_in_place(v, omega):
    """Iterative Cooley-Tukey: v[i] <- sum_j v[j] omega^{ij}."""
    n = len(v)
    assert n & (n - 1) == 0
    if n == 1:
        return
    _bit_reverse_permute(v)
    m = 1
    while m < n:
        w_m = pow(omega, n // (2 * m), R_MOD)
        for k in range(0, n, 2 * m):
            w = 1
            for j in range(m):
                t = w * v[k + j + m] % R_MOD
                u = v[k + j]
                v[k + j] = (u + t) % R_MOD
                v[k + j + m] = (u - t) % R_MOD
                w = w * w_m % R_MOD
        m <<= 1


def fft(domain, coeffs):
    assert len(coeffs) <= domain.size, "input longer than domain"
    v = list(coeffs) + [0] * (domain.size - len(coeffs))
    _ntt_in_place(v, domain.group_gen)
    return v


def ifft(domain, evals):
    assert len(evals) <= domain.size, "input longer than domain"
    v = list(evals) + [0] * (domain.size - len(evals))
    _ntt_in_place(v, domain.group_gen_inv)
    s = domain.size_inv
    return [x * s % R_MOD for x in v]


def distribute_powers(coeffs, g):
    out = []
    cur = 1
    for c in coeffs:
        out.append(c * cur % R_MOD)
        cur = cur * g % R_MOD
    return out


def coset_fft(domain, coeffs):
    return fft(domain, distribute_powers(coeffs, domain.coset_gen))


def coset_ifft(domain, evals):
    return distribute_powers(ifft(domain, evals), fr_inv(domain.coset_gen))


# --- dense polynomial helpers (coefficient vectors, low degree first) --------

def poly_eval(coeffs, x):
    acc = 0
    for c in reversed(coeffs):
        acc = (acc * x + c) % R_MOD
    return acc


def poly_add(a, b):
    n = max(len(a), len(b))
    return [((a[i] if i < len(a) else 0) + (b[i] if i < len(b) else 0)) % R_MOD for i in range(n)]


def poly_sub(a, b):
    n = max(len(a), len(b))
    return [((a[i] if i < len(a) else 0) - (b[i] if i < len(b) else 0)) % R_MOD for i in range(n)]


def poly_scale(a, k):
    return [c * k % R_MOD for c in a]


def poly_mul_vanishing(a, n):
    """a(X) * (X^n - 1)."""
    out = [0] * (len(a) + n)
    for i, c in enumerate(a):
        out[i + n] = c
        out[i] = (out[i] - c) % R_MOD
    return out


def poly_degree(a):
    for i in range(len(a) - 1, -1, -1):
        if a[i] % R_MOD != 0:
            return i
    return 0


def synthetic_divide(coeffs, z):
    """Quotient of (p(X) - p(z)) / (X - z).

    Matches the reference's manual synthetic division in round 5
    (/root/reference/src/dispatcher2.rs:651-666): returns quotient only,
    the remainder (= p(z)) is discarded.
    """
    n = len(coeffs)
    if n <= 1:
        return []
    q = [0] * (n - 1)
    acc = 0
    for i in range(n - 1, 0, -1):
        acc = (acc * z + coeffs[i]) % R_MOD
        q[i - 1] = acc
    return q
