"""Benchmark workload: Merkle-membership circuit generator.

Re-expresses the reference's `generate_circuit`
(/root/reference/src/dispatcher.rs:1063-1116 and
/root/reference/src/dispatcher2.rs:1218-1271) for the new frontend: build a
3-ary Rescue Merkle tree, then a TurboPlonk circuit proving membership of
`num_proofs` elements, root(s) exposed as public input. The reference's
scales: height 32 with 1 proof (v1, ~2^13 domain) and 50 proofs (v2,
~2^18 domain); cost model `num_proofs * (157*height + 149)` constraints
(/root/reference/src/dispatcher.rs:1068-1070) — ours lands within a few
percent (permutation 148 + selection ~11 gates per level).
"""

import random

from .circuit import PlonkCircuit
from .constants import R_MOD
from . import merkle


def generate_circuit(rng=None, height=32, num_proofs=1, num_leaves=None):
    """Build (circuit, tree): `num_proofs` in-circuit membership checks
    against one tree, root public. Mirrors the reference's workload shape
    (uid = leaf index, elem = random payload)."""
    rng = rng or random.Random(0)
    if num_leaves is None:
        num_leaves = max(num_proofs, 3)
    payloads = [rng.randrange(R_MOD) for _ in range(num_leaves)]
    tree = merkle.MerkleTree(payloads, height=height)

    cs = PlonkCircuit()
    root_var = cs.create_public_variable(tree.root)
    for k in range(num_proofs):
        idx = k % num_leaves
        proof = tree.open(idx)
        assert proof.verify(tree.root)
        payload_var = cs.create_variable(proof.payload)
        computed_root = merkle.membership_gadget(cs, idx, payload_var, proof)
        cs.enforce_equal(computed_root, root_var)
    ok, bad = cs.check_satisfiability()
    assert ok, f"workload circuit unsatisfied at gate {bad}"
    return cs.finalize(), tree
