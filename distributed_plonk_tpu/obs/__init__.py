"""Fleet observability plane (ISSUE 15): one pane of glass.

Three layers, composed from the surfaces the earlier PRs created:

    obs/log.py        structured JSONL event log: level + subsystem +
                      trace/job/worker correlation, per-process ring
                      buffer served over the LOG_FETCH wire tag, optional
                      file sink (serve.py --log-dir / DPT_LOG_DIR) — every
                      quarantine, replan, respawn, and shed verdict
                      becomes a queryable event on the same timeline as
                      the trace spans.
    obs/fleet.py      fleet metrics aggregation: scrape every worker's
                      full Metrics snapshot over METRICS_FETCH
                      (membership-driven, breaker/suspect-aware), render
                      dpt_fleet_* Prometheus series with per-worker
                      labels, and build the /fleet JSON snapshot.
    obs/profiling.py  on-demand capture behind the PROFILE wire tag:
                      jax.profiler xplane capture on jax backends, an
                      all-thread Python stack sampler otherwise; captures
                      land as content-addressed profile:<id> artifacts
                      served at /profile/<id>.

The wire plane (protocol.METRICS_FETCH/LOG_FETCH/PROFILE) is flag-safe
and back-compatible like TRACE_DUMP: an old worker answers ERR and the
caller degrades to an empty result — observability never fails a prove.
"""

from . import fleet, log, profiling  # noqa: F401

__all__ = ["log", "fleet", "profiling"]
