"""On-demand profile capture (the PROFILE wire tag's engine).

Two capture formats, chosen by what the process can actually do:

    xplane-targz   jax.profiler device trace: start_trace/stop_trace
                   around the window, the resulting log dir tar-gzipped
                   into one blob (open in tensorboard/xprof — the
                   XLA-level view under the kernel spans the trace
                   timeline already shows).
    pystacks-json  all-thread Python stack sampler (jax-free workers,
                   or a platform where the profiler refuses): every
                   DPT_PROFILE_HZ (default 100) Hz tick grabs
                   sys._current_frames() and accumulates collapsed
                   stacks — a poor-man's py-spy that sees every
                   connection thread's kernel execution, not just the
                   caller's.

`capture()` never raises: a failed capture returns a degraded-but-valid
({"format": "error", ...}, b"") pair, because observability must never
kill the serving thread that armed it.

Captures are content-addressed by blob digest: `profile_id(blob)` is the
store key suffix (`profile:<id>`, store/keycache.py), so identical
captures dedupe and the /profile/<id> URL is tamper-evident.
"""

import hashlib
import io
import json
import os
import sys
import tarfile
import tempfile
import threading
import time

_DEFAULT_MS = int(os.environ.get("DPT_PROFILE_MS", "250"))
_SAMPLE_HZ = float(os.environ.get("DPT_PROFILE_HZ", "100"))
_MAX_MS = 60_000  # a scraper typo must not arm a minute-long capture


def profile_id(blob):
    """Content id for one capture blob (16 hex chars)."""
    return hashlib.sha256(blob).hexdigest()[:16]


def capture(duration_ms=None, kind="auto", backend_name=None):
    """(meta dict, blob bytes) for one profile window. kind: "auto"
    (jax when the backend is jax, else stacks), "jax", or "stacks"."""
    ms = min(int(duration_ms or _DEFAULT_MS), _MAX_MS)
    want_jax = kind == "jax" or (kind == "auto" and backend_name == "jax")
    if want_jax:
        meta, blob = _capture_jax(ms)
        if meta is not None:
            return meta, blob
        # fall through: the sampler is the universal fallback
    return _capture_stacks(ms)


def _capture_jax(ms):
    """jax.profiler window -> tar.gz of the trace dir, or (None, b"")
    when the profiler is unavailable (caller falls back to stacks)."""
    try:
        import jax
    except Exception:
        return None, b""
    tmp = tempfile.mkdtemp(prefix="dpt-profile-")
    try:
        try:
            jax.profiler.start_trace(tmp)
        except Exception:
            return None, b""
        time.sleep(ms / 1000.0)  # analysis: ok(host-only ms->s)
        try:
            jax.profiler.stop_trace()
        except Exception:
            # a failed stop may leave the session armed — one cleanup
            # retry so a later capture's start_trace doesn't hit
            # "profiler already started" and silently downgrade forever
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            return None, b""
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            tf.add(tmp, arcname="profile")
        blob = buf.getvalue()
        return {"format": "xplane-targz", "duration_ms": ms,
                "bytes": len(blob)}, blob
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)


def _capture_stacks(ms):
    """All-thread stack sampler: collapsed stacks -> JSON blob."""
    stacks = {}
    samples = 0
    me = threading.get_ident()
    interval = 1.0 / max(_SAMPLE_HZ, 1.0)
    deadline = time.perf_counter() + ms / 1000.0  # analysis: ok(host-only ms->s)
    while time.perf_counter() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # the sampler's own loop is noise
            parts = []
            depth = 0
            while frame is not None and depth < 64:
                code = frame.f_code
                parts.append(f"{os.path.basename(code.co_filename)}:"
                             f"{code.co_name}:{frame.f_lineno}")
                frame = frame.f_back
                depth += 1
            key = ";".join(reversed(parts))
            stacks[key] = stacks.get(key, 0) + 1
        samples += 1
        time.sleep(interval)
    blob = json.dumps(
        {"format": "pystacks-json", "duration_ms": ms,
         "sample_hz": _SAMPLE_HZ, "samples": samples,
         "stacks": dict(sorted(stacks.items(), key=lambda kv: -kv[1]))},
        separators=(",", ":")).encode()
    return {"format": "pystacks-json", "duration_ms": ms,
            "samples": samples, "bytes": len(blob)}, blob
