"""Fleet metrics aggregation: scrape, aggregate, render — one pane.

The dispatcher (or a ProofService with an attached fleet) scrapes every
roster member's FULL Metrics snapshot over the METRICS_FETCH wire tag and
this module turns the results into the operator surfaces:

    scrape(dispatcher)       one fan-out over the CURRENT roster,
                             breaker/suspect-aware: breaker-open and
                             LEAVEd members are reported by state without
                             burning a dial; an old worker (ERR
                             "unknown tag") degrades to snapshot=None
                             with reachable=True — never an error.
    aggregate(entries, m)    fold a scrape into dpt_fleet_* gauges on the
                             shared registry (width, reachable, suspects,
                             open breakers, fleet-total served/errors).
    render_prom(entries)     Prometheus text with per-worker labels:
                             dpt_fleet_<name>{worker="i",addr="h:p"} for
                             every numeric counter/gauge a worker
                             published — per-worker MFU/gflops, served
                             counters, sdc_injected, all on one scrape.
    FleetScraper             the interval loop (DPT_FLEET_SCRAPE_S,
                             default 5): owns the latest scrape for the
                             /fleet endpoint and appends its rendering to
                             ObsServer /metrics.
"""

import json
import os
import re
import threading
import time

_SCRAPE_S = float(os.environ.get("DPT_FLEET_SCRAPE_S", "5"))

_LABEL_SAFE = re.compile(r"[^a-zA-Z0-9_:.\-]")


def _prom_name(name):
    return "dpt_fleet_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _labels(entry):
    addr = _LABEL_SAFE.sub("_", str(entry.get("addr", "?")))
    return f'{{worker="{entry["index"]}",addr="{addr}"}}'


def scrape(dispatcher):
    """[entry] per roster slot: {index, addr, usable, suspect, left,
    reachable, snapshot|None}. Runs the fan-out on the dispatcher's
    executor (one slow worker doesn't serialize the scrape)."""
    from ..runtime import protocol

    tracker = dispatcher.tracker

    def one(iw):
        i, w = iw
        entry = {"index": i, "addr": f"{w.host}:{w.port}",
                 "usable": tracker.usable(i),
                 "suspect": tracker.is_suspect(i),
                 "left": dispatcher._left(i),
                 "reachable": False, "snapshot": None}
        if entry["left"] or not entry["usable"]:
            # breaker/suspect-aware: no dial — the state IS the datum
            return entry
        try:
            raw = w.call(protocol.METRICS_FETCH, traced=False)
            entry["snapshot"] = json.loads(raw.decode() or "{}")
            entry["reachable"] = True
        except RuntimeError:
            # ERR reply — an old worker without the tag: alive, opaque
            entry["reachable"] = True
            entry["unsupported"] = True
        except Exception:
            pass  # dead/unreachable: breaker machinery will catch up
        return entry

    return list(dispatcher.pool.map(one, enumerate(dispatcher.workers)))


def aggregate(entries, metrics):
    """Fold one scrape into fleet-level gauges on `metrics`."""
    reachable = [e for e in entries if e["reachable"]]
    with_snap = [e for e in entries if e["snapshot"]]
    metrics.inc("fleet_scrapes")
    metrics.gauge("fleet_width", len(entries))
    metrics.gauge("fleet_reachable", len(reachable))
    metrics.gauge("fleet_suspects",
                  sum(1 for e in entries if e["suspect"]))
    metrics.gauge("fleet_breakers_open",
                  sum(1 for e in entries
                      if not e["usable"] and not e["left"]))
    served = errors = 0
    for e in with_snap:
        ctr = (e["snapshot"].get("counters") or {})
        served += sum(v for k, v in ctr.items()
                      if k.startswith("served_") and isinstance(v, int))
        errors += ctr.get("serve_errors", 0)
    metrics.gauge("fleet_served_total", served)
    metrics.gauge("fleet_serve_errors_total", errors)
    return {"width": len(entries), "reachable": len(reachable),
            "scraped": len(with_snap)}


def render_prom(entries):
    """Per-worker labelled series for one scrape (Prometheus text).
    Counters become dpt_fleet_<name>_total{worker=,addr=}, numeric
    gauges dpt_fleet_<name>{...}; an up/suspect pair per slot always."""
    lines = []
    typed = set()

    def put(name, entry, value, kind):
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            return
        n = _prom_name(name) + ("_total" if kind == "counter" else "")
        if n not in typed:
            typed.add(n)
            lines.append(f"# TYPE {n} {kind}")
        lines.append(f"{n}{_labels(entry)} {value}")

    for e in entries:
        put("up", e, int(bool(e["reachable"])), "gauge")
        put("suspect", e, int(bool(e["suspect"])), "gauge")
        snap = e.get("snapshot") or {}
        for k, v in sorted((snap.get("counters") or {}).items()):
            put(k, e, v, "counter")
        gauges = dict(snap.get("gauges") or {})
        for k in ("uptime_s", "epoch", "sdc_injected"):
            if isinstance(snap.get(k), (int, float)):
                gauges[k] = snap[k]
        for k, v in sorted(gauges.items()):
            put(k, e, v, "gauge")
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot_json(dispatcher, entries, extra=None):
    """The /fleet endpoint body: roster + per-member state + the latest
    per-worker snapshots, one JSON object."""
    out = {
        "ts": round(time.time(), 3),
        "epoch": dispatcher.epoch,
        "width": len(entries),
        "members": entries,
    }
    if extra:
        out.update(extra)
    return out


class FleetScraper:
    """Interval scraper owned by whoever holds the dispatcher (the
    ProofService via attach_fleet, or a standalone operator loop). Keeps
    the latest scrape for /fleet, folds aggregates into the shared
    registry each cycle, and renders the labelled series for /metrics."""

    def __init__(self, dispatcher, metrics, interval_s=None):
        self.d = dispatcher
        self.metrics = metrics
        self.interval_s = _SCRAPE_S if interval_s is None else interval_s
        self.last = []          # latest entries
        self.last_ts = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    def scrape_once(self):
        # the WHOLE cycle is guarded: a malformed snapshot from one
        # skewed worker must neither kill the interval thread (which
        # would freeze /fleet silently) nor escape into a caller — the
        # error counter exists exactly for this
        try:
            entries = scrape(self.d)
            aggregate(entries, self.metrics)
            with self._lock:
                self.last = entries
                self.last_ts = time.time()
            return entries
        except Exception:
            self.metrics.inc("fleet_scrape_errors")
            return self.snapshot()

    def snapshot(self):
        with self._lock:
            return list(self.last)

    def render(self):
        """Labelled per-worker series for the latest scrape."""
        return render_prom(self.snapshot())

    def fleet_json(self, extra=None):
        with self._lock:
            entries, ts = list(self.last), self.last_ts
        out = snapshot_json(self.d, entries, extra=extra)
        out["scraped_at"] = round(ts, 3) if ts else None
        return out

    def start(self):
        self.scrape_once()  # the first /fleet must not race the interval
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-scraper", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.scrape_once()

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
