"""Structured logging: JSONL events, trace-correlated, ring-buffered.

The queryable upgrade of "it printed something somewhere": every
noteworthy control-plane decision (a quarantine, an FFT replan, a
supervisor respawn, a shed verdict) is recorded as ONE structured event

    {"ts": <wall s>, "seq": n, "level": "warn", "subsystem": "dispatcher",
     "event": "quarantine", "proc": "...", "pid": ...,
     "trace_id": ..., "job_id": ..., "worker": ..., <fields>}

into a bounded per-process ring buffer. Workers serve their ring over the
LOG_FETCH wire tag (reads do not clear it — the cap bounds memory, and
`since_seq` gives tail-f semantics), the dispatcher merges trace-filtered
events into the per-job `trace:<job_id>` timeline artifact
(Dispatcher.collect_trace), and a daemon that owns its process can tee
every event to a JSONL file sink (`serve.py --log-dir` / DPT_LOG_DIR).

Correlation is the point: an event recorded while a traced request is
being served carries that request's trace_id, so `grep trace_id` across
the fleet's logs — or the merged timeline's `logs` list — reconstructs
one incident end to end.

SUBSYSTEM GLOSSARY — every `subsystem=` literal the code emits must be
documented here; analysis/lint.py's LOG01 lint enforces it (same contract
as the OBS01 metric glossary). The name column ends at the first run of
two or more spaces:

    dispatcher   fleet client decisions: quarantines, MSM range
                 adoptions, FFT replans/degradations, re-admissions
    membership   roster changes: joins, rejoins, leaves, challenge
                 verdicts, roster pushes that failed
    supervisor   worker-process lifecycle: respawns, wedge kills,
                 flap-cap giveups
    integrity    result-integrity verdicts: failed phase checks,
                 duplicate-execution mismatches, challenge outcomes
    service      serving-plane verdicts: shed/rejected jobs, retries,
                 self-verify blocks, drain outcomes
    worker       worker-daemon lifecycle: serve start, warm-rejoin
                 report, profile captures, injected SDC (chaos)
    obs          the observability plane itself: scrape errors,
                 profile-capture failures, log-sink errors
    autoscale    closed-loop controller decisions (service/autoscale.py):
                 scale_up/scale_down verdicts, lease resizes, pressure
                 sheds, loop start — dry-mode recommendations included
                 (applied=False)
    aggregate    batch-KZG aggregation verdicts (service/server.py):
                 aggregates built (members, kinds, build_s), self-verify
                 rejections, recovery restores/losses

Levels: debug < info < warn < error (no filtering on record — the ring
is small and the consumer filters; the FILE sink honors DPT_LOG_LEVEL).
"""

import json
import os
import threading
import time
from collections import deque

_LEVELS = {"debug": 0, "info": 1, "warn": 2, "error": 3}

# ring capacity per process (events, not bytes); the ring is the wire-
# served surface, so the cap is also the LOG_FETCH reply bound
_CAP = int(os.environ.get("DPT_LOG_CAP", "512"))


class LogBuffer:
    """Bounded ring of structured events + optional JSONL file sink.

    Thread-safe; `seq` is a monotonically increasing per-process event
    number (fetchers use it for tail-f semantics and to detect drops:
    `seq - len(events)` events have scrolled out of the ring)."""

    def __init__(self, cap=None, proc=None):
        self.cap = cap or _CAP
        self.proc = proc or "main"
        self._lock = threading.Lock()
        self._ring = deque(maxlen=self.cap)
        self.seq = 0
        self._file = None
        self._file_level = _LEVELS["debug"]
        self.metrics = None  # duck-typed Metrics; set via set_metrics

    # -- configuration --------------------------------------------------------

    def set_metrics(self, metrics):
        """Publish log_events/log_dropped counters into a registry."""
        with self._lock:
            self.metrics = metrics

    def open_sink(self, log_dir, proc=None, level=None):
        """Tee every event (at or above `level`) to
        <log_dir>/<proc>-<pid>.jsonl — line-buffered append, one JSON
        object per line. Never raises: a broken sink only loses the file
        copy, the ring keeps serving."""
        if proc:
            self.proc = proc
        level = level or os.environ.get("DPT_LOG_LEVEL", "debug")
        try:
            os.makedirs(log_dir, exist_ok=True)
            path = os.path.join(log_dir,
                                f"{self.proc.replace('/', '_')}-"
                                f"{os.getpid()}.jsonl")
            f = open(path, "a", buffering=1)
        except OSError:
            return None
        with self._lock:
            self._file = f
            self._file_level = _LEVELS.get(level, 0)
        return path

    def close_sink(self):
        with self._lock:
            f, self._file = self._file, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    # -- record / read --------------------------------------------------------

    def emit(self, subsystem, event, level="info", trace_id=None,
             job_id=None, worker=None, **fields):
        """Record one structured event; returns its seq number."""
        ev = {"ts": round(time.time(), 6), "level": level,
              "subsystem": subsystem, "event": event, "proc": self.proc,
              "pid": os.getpid()}
        if trace_id is not None:
            ev["trace_id"] = trace_id
        if job_id is not None:
            ev["job_id"] = job_id
        if worker is not None:
            ev["worker"] = worker
        for k, v in fields.items():
            if v is not None:
                ev[k] = v
        with self._lock:
            self.seq += 1
            ev["seq"] = self.seq
            if len(self._ring) == self.cap and self.metrics is not None:
                self.metrics.inc("log_dropped")
            self._ring.append(ev)
            f = self._file if _LEVELS.get(level, 0) >= self._file_level \
                else None
            if f is not None:
                try:
                    f.write(json.dumps(ev, separators=(",", ":")) + "\n")
                except (OSError, ValueError):
                    self._file = None  # dead sink: ring keeps serving
        if self.metrics is not None:
            self.metrics.inc("log_events")
        return ev["seq"]

    def fetch(self, trace_id=None, since_seq=0, limit=None):
        """{"events": [...], "seq": latest}: the ring's current contents
        (oldest first), optionally filtered to one trace id and/or to
        events after `since_seq`. Reads never clear the ring — fetch is
        idempotent, the cap bounds memory."""
        with self._lock:
            events = list(self._ring)
            seq = self.seq
        if since_seq:
            events = [e for e in events if e["seq"] > since_seq]
        if trace_id is not None:
            events = [e for e in events if e.get("trace_id") == trace_id]
        if limit is not None:
            events = events[-int(limit):]
        return {"events": events, "seq": seq}

    def reset(self):
        """Drop everything (tests)."""
        with self._lock:
            self._ring.clear()
            self.seq = 0


# -- per-process default buffer ------------------------------------------------
# One ring per process is the model: the worker daemon, the serve.py
# frontend, and an embedded dispatcher each log into their process's
# buffer; LOG_FETCH serves the worker ones, the service/dispatcher merge
# their own directly.

_BUFFER = LogBuffer()


def buffer():
    return _BUFFER


def emit(subsystem, event, **kw):
    """Module-level shorthand: obs.log.emit("dispatcher", "quarantine",
    level="warn", worker=i, reason=...). The LOG01 lint checks the
    subsystem literal against the glossary above."""
    return _BUFFER.emit(subsystem, event, **kw)


def fetch(trace_id=None, since_seq=0, limit=None):
    return _BUFFER.fetch(trace_id=trace_id, since_seq=since_seq,
                         limit=limit)


def set_metrics(metrics):
    _BUFFER.set_metrics(metrics)


def configure(log_dir=None, proc=None, metrics=None):
    """Process-level setup (daemon entry points): name the process, open
    the file sink, attach a metrics registry. Returns the sink path (or
    None)."""
    if proc:
        _BUFFER.proc = proc
    if metrics is not None:
        _BUFFER.set_metrics(metrics)
    if log_dir:
        return _BUFFER.open_sink(log_dir, proc=proc)
    return None


def configure_from_env(proc=None):
    """Honor DPT_LOG_DIR in processes that don't parse flags (workers
    spawned by the supervisor inherit the env)."""
    d = os.environ.get("DPT_LOG_DIR")
    return configure(log_dir=d, proc=proc) if d else configure(proc=proc)


def reset():
    _BUFFER.reset()
    _BUFFER.close_sink()


def parse_subsystem_glossary(doc):
    """Documented subsystem names from a glossary docstring: the name
    column (first token, >= 2 spaces before the description) of each
    indented entry line — prose can't accidentally document one. THE
    canonical parser: the LOG01 lint (analysis/lint.py) imports this,
    so the enforced vocabulary and documented_subsystems() cannot
    diverge."""
    import re
    out = set()
    for line in (doc or "").splitlines():
        if not line.startswith("    ") or not line.strip():
            continue
        cols = re.split(r"\s{2,}", line.strip(), maxsplit=1)
        if len(cols) == 2 and re.fullmatch(r"[a-z][a-z0-9_]*", cols[0]):
            out.add(cols[0])
    return out


def documented_subsystems():
    return parse_subsystem_glossary(__doc__)
