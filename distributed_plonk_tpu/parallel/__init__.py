"""Mesh-parallel compute: the TPU-native replacement for the reference's
worker fleet + Cap'n Proto collectives (SURVEY.md §2.4).

The reference moves FFT panels between workers over TCP (fftExchange
all-to-all, /root/reference/src/worker.rs:293-344,412-438) and sum-reduces
MSM partials on the dispatcher (/root/reference/src/dispatcher2.rs:888-890).
Here the same dataflow is expressed as XLA collectives over a
jax.sharding.Mesh: `all_to_all` for the 4-step NTT transpose, `all_gather`
+ on-device fold for the MSM partial reduction — no host round-trips.
"""
