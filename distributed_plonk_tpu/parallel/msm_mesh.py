"""Sharded variable-base MSM over a device mesh.

TPU-native replacement for the reference's distributed MSM
(/root/reference/src/dispatcher2.rs:834-893 + src/worker.rs:159-185):
bases and scalars are range-sharded across the mesh (the MsmWorkload
convention, with the v1 full-coverage semantics — SURVEY.md §2.3.1),
every device runs the sort-free Pippenger bucket pipeline on its slice,
and the per-device BUCKET PLANES fold ON DEVICE via all_gather + the same
scanned fold body the group fold uses — replacing the reference's
host-side sum-reduce of partial totals (dispatcher2.rs:888-890). (G1
addition is not a ring sum, so `psum` does not apply; the all_gather+fold
is the collective equivalent.) A single finish machine then turns the
globally folded buckets into the result, so the whole mesh program
compiles the same THREE complete-projective-add bodies (RCB15; 2
stacked-lane multiplier instances each) as the single-device path — the
structure that keeps the multi-chip dry-run inside the compile budget on
a virtual CPU mesh.
"""

from functools import partial

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from ..backend import msm_jax
from .mesh import SHARD_AXIS


class MeshMsmContext:
    """Device-mesh-resident base set: every device holds its contiguous
    1/D range of the SRS (the v1 init semantics the rebuild standardizes
    on, /root/reference/src/dispatcher.rs:572-578)."""

    def __init__(self, mesh, bases_affine):
        self.mesh = mesh
        d = mesh.devices.size
        n = len(bases_affine)
        self.n = n
        # pad so every shard is non-trivially groupable
        pad = (-n) % (2 * d)
        self.padded_n = n + pad
        self.local_n = self.padded_n // d
        self.group = msm_jax._group_size(self.local_n)
        # Pippenger window size from the per-device slice (what each
        # device's bucket pipeline actually sees)
        self.c = msm_jax.window_bits(self.local_n)

        # the mesh scan keeps unsigned digits (tiny dry-run shapes use
        # c < 8 where the signed recode has no overflow margin) but rides
        # the same complete-projective bucket pipeline as the single-chip
        # path; bases stay HOST numpy so the only device traffic is the
        # sharded put
        ax, ay, ainf = msm_jax.points_to_device(bases_affine, pad)
        shard_nd = jax.sharding.NamedSharding(mesh, P(None, SHARD_AXIS))
        inf_nd = jax.sharding.NamedSharding(mesh, P(SHARD_AXIS))
        self.point = (jax.device_put(ax, shard_nd),
                      jax.device_put(ay, shard_nd),
                      jax.device_put(ainf, inf_nd))

        shard = P(None, SHARD_AXIS)

        def body(ax, ay, ainf, digits):
            # local slice: (24, local_n); digits (W, local_n)
            wb = jax.vmap(partial(msm_jax._bucket_scan, group=self.group,
                                  n_buckets=1 << self.c),
                          in_axes=(None, None, None, 0))(ax, ay, ainf, digits)
            planes = tuple(b.transpose(2, 1, 0, 3) for b in wb)
            local = msm_jax.fold_planes(*planes)  # (24, W, B) per device
            # fold bucket planes across the mesh on device (the reference
            # folds partial totals on the dispatcher host instead); the
            # fold body is identical to the group fold's -> compiled once
            gathered = tuple(lax.all_gather(b, SHARD_AXIS) for b in local)
            return msm_jax.fold_planes(*gathered)

        # check_vma=False: the all_gather+fold makes the outputs replicated
        # in value, which the varying-axes checker cannot infer statically
        self._fn = jax.jit(_shard_map(
            body, mesh=mesh,
            in_specs=(shard, shard, P(SHARD_AXIS), shard),
            out_specs=(P(None, None, None),) * 3, check_vma=False))
        # the O(windows*buckets) finish tail runs on the replicated fold
        # result OUTSIDE the mesh program: one single-device compile (shared
        # with MsmContext's pipeline via the persistent cache) instead of an
        # 8-partition one
        self._finish = jax.jit(msm_jax.finish)

    def msm(self, scalars):
        """Σ scalars_i * bases_i -> affine point (host ints) or None."""
        assert len(scalars) <= self.n
        digits = msm_jax.digits_of_scalars(scalars, self.padded_n, self.c)
        ax, ay, ainf = self.point
        buckets = self._fn(ax, ay, ainf, digits)
        # commit the replicated fold result to ONE device: otherwise the
        # finish jit inherits the 8-way replicated sharding and every
        # device redundantly executes the whole tail. Under multi-controller
        # the global array is not fully addressable, so each process pulls
        # its LOCAL replica (identical by construction) and runs the tail
        # on its own first device.
        dev = next((d for d in self.mesh.devices.ravel()
                    if d.process_index == jax.process_index()),
                   self.mesh.devices.ravel()[0])
        buckets = tuple(jax.device_put(b.addressable_data(0), dev)
                        for b in buckets)
        tx, ty, tz = self._finish(*buckets)
        return msm_jax._proj_limbs_to_affine(tx, ty, tz)
