"""Sharded variable-base MSM over a device mesh.

TPU-native replacement for the reference's distributed MSM
(/root/reference/src/dispatcher2.rs:834-893 + src/worker.rs:159-185):
bases and scalars are range-sharded across the mesh (the MsmWorkload
convention, with the v1 full-coverage semantics — SURVEY.md §2.3.1),
every device runs the sort-free Pippenger bucket pipeline on its slice,
and the partial G1 sums fold ON DEVICE via all_gather + a tiny scan —
replacing the reference's host-side sum-reduce (dispatcher2.rs:888-890).
(G1 addition is not a ring sum, so `psum` does not apply; the
all_gather+fold is the collective equivalent.)
"""

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from ..constants import FQ_MONT_R, Q_MOD, R_MOD, FR_LIMBS, FQ_LIMBS
from ..backend import curve_jax as CJ
from ..backend import msm_jax
from ..backend.limbs import ints_to_limbs
from .mesh import SHARD_AXIS


class MeshMsmContext:
    """Device-mesh-resident base set: every device holds its contiguous
    1/D range of the SRS (the v1 init semantics the rebuild standardizes
    on, /root/reference/src/dispatcher.rs:572-578)."""

    def __init__(self, mesh, bases_affine):
        self.mesh = mesh
        d = mesh.devices.size
        n = len(bases_affine)
        self.n = n
        # pad so every shard is non-trivially groupable
        pad = (-n) % (2 * d)
        self.padded_n = n + pad
        self.local_n = self.padded_n // d
        self.group = msm_jax._group_size(self.local_n)

        xs, ys, infs = [], [], []
        for p in bases_affine:
            if p is None:
                xs.append(0)
                ys.append(0)
                infs.append(True)
            else:
                xs.append(p[0] * FQ_MONT_R % Q_MOD)
                ys.append(p[1] * FQ_MONT_R % Q_MOD)
                infs.append(False)
        xs += [0] * pad
        ys += [0] * pad
        infs += [True] * pad
        shard_nd = jax.sharding.NamedSharding(mesh, P(None, SHARD_AXIS))
        x = jax.device_put(ints_to_limbs(xs, FQ_LIMBS), shard_nd)
        y = jax.device_put(ints_to_limbs(ys, FQ_LIMBS), shard_nd)
        inf = jax.device_put(np.array(infs), jax.sharding.NamedSharding(mesh, P(SHARD_AXIS)))
        self.point = jax.jit(CJ.from_affine)(x, y, inf)

        shard = P(None, SHARD_AXIS)
        digit_spec = P(None, SHARD_AXIS)

        def body(px, py, pz, digits):
            # local slice: (24, local_n); digits (32, local_n)
            wb = jax.vmap(partial(msm_jax._window_buckets, group=self.group),
                          in_axes=(None, None, None, 0))(px, py, pz, digits)
            bx, by, bz = (b.transpose(1, 0, 2) for b in wb)
            tx, ty, tz = msm_jax._finish(bx, by, bz)
            # fold the D partial sums on device (reference folds on the
            # dispatcher host instead)
            gx = lax.all_gather(tx, SHARD_AXIS)  # (D, 24)
            gy = lax.all_gather(ty, SHARD_AXIS)
            gz = lax.all_gather(tz, SHARD_AXIS)

            def red(acc, g):
                return CJ.jac_add(acc, g), None

            vz = gz.ravel()[0] & 0  # varying-zero, see msm_jax._window_buckets
            init = tuple(b + vz for b in CJ.pt_inf(()))
            total, _ = lax.scan(red, init, (gx, gy, gz))
            return total

        # check_vma=False: the all_gather+fold makes the outputs replicated
        # in value, which the varying-axes checker cannot infer statically
        self._fn = jax.jit(_shard_map(
            body, mesh=mesh,
            in_specs=(shard, shard, shard, digit_spec),
            out_specs=(P(None), P(None), P(None)), check_vma=False))

    def msm(self, scalars):
        """Σ scalars_i * bases_i -> affine point (host ints) or None."""
        assert len(scalars) <= self.n
        scalars = [s % R_MOD for s in scalars]
        scalars += [0] * (self.padded_n - len(scalars))
        limbs = ints_to_limbs(scalars, FR_LIMBS)
        digits = np.stack([limbs & 0xFF, limbs >> 8], axis=1).astype(np.uint32)
        digits = digits.reshape(msm_jax.NUM_WINDOWS, self.padded_n)
        px, py, pz = self.point
        tx, ty, tz = self._fn(px, py, pz, digits)
        return msm_jax._jac_limbs_to_affine(tx, ty, tz)
