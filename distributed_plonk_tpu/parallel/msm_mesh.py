"""Sharded variable-base MSM over a device mesh.

TPU-native replacement for the reference's distributed MSM
(/root/reference/src/dispatcher2.rs:834-893 + src/worker.rs:159-185):
bases and scalars are range-sharded across the mesh (the MsmWorkload
convention, with the v1 full-coverage semantics — SURVEY.md §2.3.1),
every device runs the sort-free Pippenger bucket pipeline on its slice,
and the per-device BUCKET PLANES fold ON DEVICE via all_gather + the same
scanned fold body the group fold uses — replacing the reference's
host-side sum-reduce of partial totals (dispatcher2.rs:888-890). (G1
addition is not a ring sum, so `psum` does not apply; the all_gather+fold
is the collective equivalent.) A single finish machine then turns the
globally folded buckets into the result.

This is the full prover commitment surface, not just a host-scalar demo:
like the single-device MsmContext, the mesh context

  - runs the SIGNED radix-256 batched pipeline (128 buckets, sign folded
    into y) whenever the per-device slice is large enough, falling back
    to the unsigned small-window scan only for tiny slices where the
    signed recode has no overflow margin;
  - accepts (16, L) MONTGOMERY poly handles and extracts digits on
    device (`msm_mont_limbs_many`), so a mesh-backed prove commits
    device-resident polynomials without a host round-trip;
  - batches B polynomials through shared scan steps and chunks the
    point range so one device execution stays under the per-call budget
    (the tunneled runtime kills ~60 s executions).

Data layout: points live as (24, D, local) arrays sharded on the device
axis — device d owns the contiguous base range [d*local, (d+1)*local) —
so chunk slices along the LOCAL axis never reshard.
"""

import os
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _raw_shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _raw_shard_map


def _shard_map(body, **kwargs):
    """shard_map with the replication-checker kwarg papered over: newest
    jax calls it check_vma, older jax check_rep, in-between versions have
    neither — passing the wrong name is a TypeError, so translate/drop
    against the installed signature instead of pinning one spelling."""
    import inspect
    try:
        params = set(inspect.signature(_raw_shard_map).parameters)
    except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
        params = set()
    if "check_vma" not in params:
        flag = kwargs.pop("check_vma", None)
        if "check_rep" in params and flag is not None:
            kwargs["check_rep"] = flag
    return _raw_shard_map(body, **kwargs)

from ..constants import FQ_LIMBS
from ..backend import msm_jax
from ..backend import curve_jax as CJ
from ..backend import field_jax as FJ
from ..backend.msm_jax import (
    SCALAR_BITS, DeviceCommitKey, window_bits, _group_size_batch,
    bucket_planes_batch, bucket_planes_batch_signed, fold_planes,
    finish_batch, digits_of_scalars, signed_digits_of_scalars,
    digits_from_mont, signed_digits_from_mont, points_to_device,
    _proj_limbs_to_affine,
)
from .mesh import SHARD_AXIS, pallas_guard


class MeshMsmContext:
    """Device-mesh-resident base set: every device holds its contiguous
    1/D range of the SRS (the v1 init semantics the rebuild standardizes
    on, /root/reference/src/dispatcher.rs:572-578)."""

    # per-call lane-add budget PER DEVICE (all devices run concurrently);
    # same knob semantics as MsmContext's chunking
    _CALL_ADDS = int(os.environ.get("DPT_MSM_CALL_ADDS", "8000000"))

    def __init__(self, mesh, bases):
        self.mesh = mesh
        self.d = d = mesh.devices.size
        n = len(bases)
        self.n = n
        # pad so the local slice is even-sized and groupable; identity
        # padding columns never change the sum
        self.padded_n = n + (-n) % (16 * d)
        self.local_n = self.padded_n // d
        # window choice from the PER-DEVICE slice (what each device's
        # bucket pipeline actually sees): signed radix-256 once the slice
        # is big enough, like MsmContext.c_batch
        self.c = 8 if self.local_n >= 256 else window_bits(self.local_n)
        self.signed = self.c == 8
        self.windows = SCALAR_BITS // self.c

        pad = self.padded_n - n
        if isinstance(bases, DeviceCommitKey):
            # device-built SRS (Jacobian, arbitrary Z): normalize once via
            # batched inversion on whatever device it lives on, then
            # reshard onto the mesh
            point = bases.point
            if pad:
                point = tuple(jnp.pad(p, ((0, 0), (0, pad))) for p in point)
            ax, ay, ainf = CJ.batch_to_affine(point)
        else:
            ax, ay, ainf = points_to_device(bases, pad)  # host numpy

        pt_sh = NamedSharding(mesh, P(None, SHARD_AXIS, None))
        inf_sh = NamedSharding(mesh, P(SHARD_AXIS, None))
        resh = (np.reshape if isinstance(ax, np.ndarray) else jnp.reshape)
        self.point = (
            jax.device_put(resh(ax, (FQ_LIMBS, d, self.local_n)), pt_sh),
            jax.device_put(resh(ay, (FQ_LIMBS, d, self.local_n)), pt_sh),
            jax.device_put(resh(ainf, (d, self.local_n)), inf_sh),
        )

        self._digits_sh = NamedSharding(mesh, P(None, None, SHARD_AXIS, None))
        self._digits_fns = {}
        self._chunk_fns = {}
        self._finish_fns = {}

        # pallas_disabled at TRACE time: this jit runs on mesh-replicated
        # operands under the GSPMD partitioner, where a pallas_call (no
        # SPMD partitioning rule) would fail to partition or silently
        # gather — same invariant as MeshBackend's round math. The
        # explicit shard_map chunk bodies keep the kernel (per-device
        # local shapes).
        def _merge(a, b):
            with FJ.pallas_disabled():
                return CJ.proj_add(tuple(a), tuple(b))

        self._merge_fn = jax.jit(_merge)

    # --- digit extraction ----------------------------------------------------

    def _digits_np(self, scalars):
        """Host ints -> (W, D, local) numpy digits."""
        if self.signed:
            dg = signed_digits_of_scalars(scalars, self.padded_n)
        else:
            dg = digits_of_scalars(scalars, self.padded_n, self.c)
        return dg.reshape(self.windows, self.d, self.local_n)

    def _digits_of_handles(self, hs):
        """B Montgomery (16, L) handles -> (B, W, D, local) device digits,
        extracted on device (no host round-trip before a commitment)."""
        key = tuple(h.shape[1] for h in hs)
        fn = self._digits_fns.get(key)
        if fn is None:
            W, d, loc = self.windows, self.d, self.local_n

            def build(handles):
                # pallas_disabled: handles arrive mesh-sharded and this
                # jit is GSPMD-partitioned (not shard_map'd) — a traced
                # pallas mont_mul here would break on a real TPU mesh
                with FJ.pallas_disabled():
                    outs = []
                    for h in handles:
                        if self.signed:
                            dg = signed_digits_from_mont(h, self.padded_n)
                        else:
                            dg = digits_from_mont(h, self.c, self.padded_n)
                        outs.append(dg.reshape(W, d, loc))
                    return jnp.stack(outs)

            fn = jax.jit(build, out_shardings=self._digits_sh)
            self._digits_fns[key] = fn
        return fn(list(hs))

    # --- sharded bucket accumulation ----------------------------------------

    def _chunk_fn(self, jc, group, B):
        """shard_map'd program: per-device bucket planes on a jc-wide local
        chunk, then cross-device all_gather + fold -> replicated planes.
        Key carries the autotune plan revision (the traced scan resolves
        the kernel branch per call): a mid-process plan reload must not
        serve a program traced under the previous plan."""
        from ..backend import autotune
        key = autotune.cache_key(jc, group, B)
        if key not in self._chunk_fns:
            scan = (bucket_planes_batch_signed if self.signed
                    else bucket_planes_batch)

            def body(ax, ay, ainf, digits):
                # pallas only if the mesh devices are TPUs (mesh.pallas_guard)
                with pallas_guard(self.mesh):
                    # local block: ax/ay (24, 1, jc), ainf (1, jc),
                    # digits (B, W, 1, jc)
                    acc = scan(ax[:, 0], ay[:, 0], ainf[0],
                               digits[:, :, 0], group=group)
                    # fold bucket planes across the mesh on device (the
                    # reference folds partial totals on the dispatcher host,
                    # dispatcher2.rs:888-890); the fold body is identical to
                    # the group fold's -> compiled once
                    gathered = tuple(lax.all_gather(b, SHARD_AXIS) for b in acc)
                    return fold_planes(*gathered)

            # check_vma=False: the all_gather+fold makes the outputs
            # replicated in value, which the varying-axes checker cannot
            # infer statically
            self._chunk_fns[key] = jax.jit(_shard_map(
                body, mesh=self.mesh,
                in_specs=(P(None, SHARD_AXIS, None), P(None, SHARD_AXIS, None),
                          P(SHARD_AXIS, None), P(None, None, SHARD_AXIS, None)),
                out_specs=(P(None, None, None),) * 3, check_vma=False))
        return self._chunk_fns[key]

    def _finish_fn(self, batch):
        if batch not in self._finish_fns:
            def _finish(ax, ay, az):
                with pallas_guard(self.mesh):
                    return finish_batch(ax, ay, az, batch=batch,
                                        signed=self.signed)
            self._finish_fns[batch] = jax.jit(_finish)
        return self._finish_fns[batch]

    def _exec(self, digits):
        """digits (B, W, D, local) -> B affine points (host ints/None)."""
        B = digits.shape[0]
        W = self.windows
        ax, ay, ainf = self.point
        chunk = max(16, (self._CALL_ADDS // (B * W)) & ~15)
        acc = None
        j0 = 0
        while j0 < self.local_n:
            jc = min(chunk, self.local_n - j0)
            g = _group_size_batch(jc, B, self.c, signed=self.signed)
            fn = self._chunk_fn(jc, g, B)
            part = fn(ax[:, :, j0:j0 + jc], ay[:, :, j0:j0 + jc],
                      ainf[:, j0:j0 + jc], digits[:, :, :, j0:j0 + jc])
            if acc is None:
                acc = part
            else:
                acc = tuple(self._merge_fn(acc, part))
            j0 += jc
        # commit the replicated fold result to ONE device before the
        # O(W * buckets) finish tail: otherwise the finish jit inherits the
        # D-way replicated sharding and every device redundantly executes
        # the whole tail. Under multi-controller the global array is not
        # fully addressable, so each process pulls its LOCAL replica
        # (identical by construction).
        dev = next((dv for dv in self.mesh.devices.ravel()
                    if dv.process_index == jax.process_index()),
                   self.mesh.devices.ravel()[0])
        acc = tuple(jax.device_put(a.addressable_data(0), dev) for a in acc)
        tx, ty, tz = self._finish_fn(B)(*acc)
        tx, ty, tz = np.asarray(tx), np.asarray(ty), np.asarray(tz)
        return [_proj_limbs_to_affine(tx[:, j], ty[:, j], tz[:, j])
                for j in range(B)]

    # --- public surface (mirrors MsmContext) --------------------------------

    def msm(self, scalars):
        """Σ scalars_i * bases_i -> affine point (host ints) or None."""
        return self.msm_many([scalars])[0]

    def msm_many(self, scalar_lists):
        """B MSMs over host int scalar lists in one batched mesh launch."""
        for s in scalar_lists:
            assert len(s) <= self.n
        digits = np.stack([self._digits_np(s) for s in scalar_lists])
        return self._exec(jax.device_put(digits, self._digits_sh))

    def msm_mont_limbs(self, h):
        """Commit a (16, L <= padded_n) Montgomery coefficient handle."""
        return self.msm_mont_limbs_many([h])[0]

    # like MsmContext: fixed chunk width keeps the compiled batch-shape set
    # small across prover rounds (8, then the 5/2-size residuals)
    _BATCH_CHUNK = int(os.environ.get("DPT_MSM_BATCH", "8"))

    def msm_mont_limbs_many(self, hs):
        """Commit B Montgomery coefficient handles; digit extraction and
        bucket accumulation run sharded on the mesh, only the resulting
        group elements return to the host (for the transcript)."""
        for h in hs:
            assert h.shape[1] <= self.padded_n, (h.shape, self.padded_n)
        out = []
        for i in range(0, len(hs), self._BATCH_CHUNK):
            digits = self._digits_of_handles(hs[i:i + self._BATCH_CHUNK])
            out.extend(self._exec(digits))
        return out
