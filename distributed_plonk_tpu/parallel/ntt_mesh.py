"""Sharded 4-step NTT over a device mesh: one `all_to_all`, no host hops.

TPU-native replacement for the reference's distributed FFT protocol
(driver /root/reference/src/dispatcher2.rs:731-787; worker stage kernels
src/worker.rs:66-115; peer all-to-all src/worker.rs:293-344,412-438).
Where the reference pays 4 network phases per FFT through the dispatcher,
here the whole decomposition is ONE compiled program: the row/column FFT
stages run sharded under shard_map and the inter-stage transpose is a
single `jax.lax.all_to_all` over the mesh axis (ICI on real hardware).

Math (Bailey/4-step; the reference's spec is src/playground.rs:21-80,
derived here from first principles): for N = r*c, w = w_N,

  X[k1 + r*k2] = sum_{j2<c} w^{j2 k1} w_c^{j2 k2}
                   [ sum_{j1<r} x[j2 + c*j1] w_r^{j1 k1} ]

  1. A[j2, j1] = x[j2 + c*j1]; r-point NTT per row j2   (sharded over j2)
  2. A[j2, k1] *= w^{j2*k1}                             (elementwise)
  3. transpose -> B[k1, j2]                             (all_to_all)
  4. c-point NTT per row k1                             (sharded over k1)
  output: X[k1 + r*k2] = B_hat[k1, k2].

Coset and inverse variants fold their scalings into the same program:
forward-coset pre-scales the input by g^j, inverse post-scales the output
by 1/N (plain) or g^-j/N (coset), matching poly.py bit-for-bit.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# version-compat shard_map wrapper (check_vma/check_rep) — needed to
# disable the replication checker when the body traces a pallas_call,
# which has no replication rule (same workaround as the mesh MSM's
# pallas scans; the shim owns the jax-version fallback too)
from .msm_mesh import _shard_map as _shard_map_compat

from ..constants import R_MOD, FR_GENERATOR, FR_LIMBS
from ..fields import fr_inv, fr_root_of_unity
from ..backend import autotune
from ..backend import field_jax as FJ
from ..backend.field_jax import FR
from ..backend import ntt_jax
from ..backend.limbs import ints_to_limbs, limbs_to_ints
from .mesh import SHARD_AXIS, pallas_guard


def _split_rc(n):
    """n = r*c with r = 2^floor(log2(n)/2) (the reference's split,
    /root/reference/src/worker.rs:142-155)."""
    log_n = n.bit_length() - 1
    r = 1 << (log_n // 2)
    return r, n // r


class MeshNttPlan:
    """Tables + cached compiled programs for one (mesh, N) pair."""

    def __init__(self, mesh, n):
        assert n & (n - 1) == 0
        self.mesh = mesh
        self.n = n
        self.r, self.c = _split_rc(n)
        d = mesh.devices.size
        assert self.r % d == 0 and self.c % d == 0, (
            f"mesh size {d} must divide both r={self.r} and c={self.c}")
        self.plan_r = ntt_jax.get_plan(self.r)
        self.plan_c = ntt_jax.get_plan(self.c)
        self._fns = {}

        w = fr_root_of_unity(n)
        w_inv = fr_inv(w) if n > 1 else 1
        g = FR_GENERATOR
        g_inv = fr_inv(g)
        n_inv = fr_inv(n % R_MOD)
        r, c = self.r, self.c

        # mid twiddles: T[j2, k1] = w^{±j2*k1}, built incrementally per row
        def mid_table(base):
            rows = []
            row_base = 1
            for j2 in range(c):
                rows.extend(ntt_jax._powers(row_base, r))
                row_base = row_base * base % R_MOD
            return ntt_jax._mont_table(rows)  # (16, c*r) row-major [j2, k1]

        self.mid_fwd = mid_table(w).reshape(FR_LIMBS, c, r)
        self.mid_inv = mid_table(w_inv).reshape(FR_LIMBS, c, r)

        # forward-coset pre-scale at A[j2, j1]: g^{j2 + c*j1}
        pre = []
        for j2 in range(c):
            pre.extend(ntt_jax._powers(pow(g, c, R_MOD), r, start=pow(g, j2, R_MOD)))
        self.pre_coset = ntt_jax._mont_table(pre).reshape(FR_LIMBS, c, r)

        # inverse post-scale at out[k1, k2]: n_inv * g^-(k1 + r*k2)
        post = []
        for k1 in range(r):
            post.extend(ntt_jax._powers(pow(g_inv, r, R_MOD), c,
                                        start=n_inv * pow(g_inv, k1, R_MOD)))
        self.post_coset = ntt_jax._mont_table(post).reshape(FR_LIMBS, r, c)
        self.post_plain = ntt_jax._mont_table([n_inv])  # (16, 1)

    def kernel(self, inverse=False, coset=False, boundary="mont"):
        """Compiled (16, n) -> (16, n) mesh program for one mode (at the
        active DPT_NTT_RADIX and DPT_NTT_KERNEL — part of the cache key,
        like the single-device kernels; under the pallas kernel the
        per-shard run_stages calls pick up the fused multi-stage kernel
        unchanged, and pallas_guard falls them back to the XLA tables on
        a non-TPU mesh at trace time)."""
        key = autotune.cache_key(
            inverse, coset, boundary, ntt_jax._active_radix(n=self.n),
            ntt_jax._active_kernel(n=self.n))
        # will the TRACED body actually run pallas? Resolve under the
        # same guard the trace runs under (pallas_guard disables it for
        # a non-TPU mesh), so check_vma below is only relaxed for
        # programs that genuinely contain a pallas_call
        with pallas_guard(self.mesh):
            pallas_active = ntt_jax._active_kernel() == "pallas"
        if key in self._fns:
            fn, consts = self._fns[key]
            return lambda v: fn(v, consts)

        n, r, c = self.n, self.r, self.c
        d = self.mesh.devices.size
        plain = boundary == "plain"

        # host numpy constants: jit moves them onto the mesh's devices (which
        # may not be the process default backend, e.g. cpu mesh + tpu default)
        # — the row/column stage tables come from the SAME shared stage core
        # the single-device kernels run (ntt_jax.run_stages), so the active
        # radix (DPT_NTT_RADIX) covers the sharded path too
        consts = {
            "core_r": self.plan_r.core_consts(inverse),
            "core_c": self.plan_c.core_consts(inverse),
            "mid": self.mid_inv if inverse else self.mid_fwd,
        }
        if coset and not inverse:
            consts["pre"] = self.pre_coset
        if inverse:
            consts["post"] = (self.post_coset if coset else self.post_plain)

        row_spec = P(None, SHARD_AXIS, None)
        # every stage-core table is replicated (O(n) twiddles/exponents,
        # no per-shard content), whatever the radix's table set is
        const_specs = {
            "core_r": {k: P(*([None] * np.ndim(a)))
                       for k, a in consts["core_r"].items()},
            "core_c": {k: P(*([None] * np.ndim(a)))
                       for k, a in consts["core_c"].items()},
            "mid": row_spec,
        }
        if "pre" in consts:
            const_specs["pre"] = row_spec
        if "post" in consts:
            const_specs["post"] = (row_spec if consts["post"].ndim == 3
                                   else P(None, None))

        def sharded_body(a, cs):
            # a: (16, c/d, r) local rows of A
            if "pre" in cs:
                a = FJ.mont_mul(FR, a, cs["pre"])
            v = ntt_jax.run_stages(a, cs["core_r"])
            v = FJ.mont_mul(FR, v, cs["mid"])
            # the ONE inter-stage transpose: (16, c/d, r) -> (16, c, r/d)
            v = lax.all_to_all(v, SHARD_AXIS, split_axis=2, concat_axis=1,
                               tiled=True)
            v = v.swapaxes(1, 2)  # local transpose -> (16, r/d, c)
            v = ntt_jax.run_stages(v, cs["core_c"])
            if "post" in cs:
                post = cs["post"]
                if post.ndim == 2:  # plain 1/n scalar, broadcast symbolically
                    post = jnp.broadcast_to(post[:, :, None], v.shape)
                v = FJ.mont_mul(FR, v, post)
            return v

        # a pallas_call has no shard_map replication rule: disable the
        # checker ONLY when the traced body will contain one — every
        # XLA-core program (including pallas-requested-but-guarded-off
        # on a non-TPU mesh) keeps the full replication check
        smapped = _shard_map_compat(
            sharded_body, mesh=self.mesh,
            in_specs=(row_spec, const_specs), out_specs=row_spec,
            **({"check_vma": False} if pallas_active else {}))

        lane_sh = jax.sharding.NamedSharding(self.mesh, P(None, SHARD_AXIS))

        @jax.jit
        def fn(x, cs):
            # pallas only if the MESH devices are TPUs (a cpu mesh can be
            # traced in a tpu-default process — mesh.pallas_guard); the
            # plain-boundary conversions run OUTSIDE shard_map at the
            # GSPMD level, where a pallas_call must never appear even on
            # a real TPU mesh (same invariant as MeshBackend round math)
            with pallas_guard(self.mesh):
                # x: (16, n) global
                if plain:
                    with FJ.pallas_disabled():
                        x = FJ.to_mont(FR, x)
                a = x.reshape(FR_LIMBS, r, c).swapaxes(1, 2)  # A[j2, j1]
                out = smapped(a, cs)                       # (16, r, c) = X[k1, k2]
                x = out.swapaxes(1, 2).reshape(FR_LIMBS, n)  # X[k1 + r*k2]
                # PIN the output to the lane-sharded layout: the swapaxes+
                # reshape leaves the sharding unconstrained and GSPMD was
                # observed to REPLICATE the result across the mesh (the
                # mesh_prove_2p15 residency check measured 25 replicated
                # coset planes, 463 MiB/device vs the 109 MiB plan). The
                # constraint costs one relayout collective; round math
                # downstream then stays O(m/D) per device.
                if not plain:
                    x = jax.lax.with_sharding_constraint(x, lane_sh)
                if plain:
                    with FJ.pallas_disabled():
                        x = FJ.from_mont(FR, x)
                return x

        self._fns[key] = (fn, consts)
        return lambda v: fn(v, consts)

    def run_ints(self, values, inverse=False, coset=False):
        assert len(values) <= self.n
        padded = list(values) + [0] * (self.n - len(values))
        v = ints_to_limbs(padded, FR_LIMBS)  # host numpy; jit places on mesh
        out = self.kernel(inverse, coset, boundary="plain")(v)
        if jax.process_count() > 1:
            # multi-controller: the result is sharded across hosts; gather
            # it to a replicated layout (DCN all-gather) so every process
            # can read the full vector
            rep = jax.sharding.NamedSharding(self.mesh, P(None, None))
            out = jax.jit(lambda x: x, out_shardings=rep)(out)
        return limbs_to_ints(np.asarray(out))
