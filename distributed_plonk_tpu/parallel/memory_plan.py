"""Per-device memory plan for mesh NTT/MSM at reference scale.

The reference's v2 workload pushes the quotient domain to 2^21
(/root/reference/src/dispatcher2.rs:246: m = 6(n+1)+1 rounded up for the
2^18 main domain) and shards it over 2 workers whose footprint is O(N/P)
rows + O(N/P) columns (src/worker.rs:223-227). This module computes the
same budget for the TPU mesh layout so configurations are validated
BEFORE a 9-figure-element allocation hits a chip (tests assert the v5e
numbers; scripts consult it when picking chunk sizes).

Layout recap (ntt_mesh.MeshNttPlan): N = r*c, rows sharded over the mesh
axis; every element is 16 u32 limbs (64 B). Constant tables (mid twiddles,
coset pre/post scales) are row-sharded alongside the data.
"""

FR_BYTES_DEVICE = 16 * 4  # (16,) uint32 limbs per element

# peak transient multiplier for one f32-path mont_mul over a batch: the
# dominant intermediate is the (2L, 2L, batch) f32 byte-product tensor
# (32*32*4 B/element for Fr) when XLA materializes it un-fused — the
# worst-case bound chunk planners must respect
FR_MONT_MUL_TRANSIENT = 32 * 32 * 4


def _split_rc(n):
    log_n = n.bit_length() - 1
    r = 1 << (log_n // 2)
    return r, n // r


def ntt_mesh_plan(n, n_devices, batch=1):
    """Byte budget for a batch-B mesh NTT of size n over n_devices.

    Returns a dict of per-device byte counts:
      data: the sharded (16, B, c/d, r) working array (stage 1 view)
      tables: mid twiddles + coset pre/post scales (row-sharded, x3)
      transient_full: worst-case un-fused mont_mul byte-product tensor
      transient_stage: same, if the kernel chunks the batch to one row block
      total_fused / total_worst: planning envelopes
    """
    r, c = _split_rc(n)
    local = n // n_devices
    data = FR_BYTES_DEVICE * batch * local
    tables = 3 * FR_BYTES_DEVICE * local  # mid + pre + post, row-sharded
    transient_full = FR_MONT_MUL_TRANSIENT * batch * local
    # double-buffer: input + output of each fused stage
    total_fused = 2 * data + tables
    total_worst = 2 * data + tables + transient_full
    return {
        "r": r, "c": c, "local_elems": local,
        "data": data, "tables": tables,
        "transient_full": transient_full,
        "total_fused": total_fused, "total_worst": total_worst,
    }


def round3_mesh_plan(n, m, n_devices):
    """Per-device RESIDENT byte budget at the mesh quotient evaluation
    (the round-3 peak): the 25 coset planes (13 selectors + 5 sigmas +
    5 wires + z + pi), their stacked copies inside the one-shot quotient
    kernel (jnp.stack makes (16, k, m) copies of sel/sig/wires), and the
    3 domain tables — all lane-sharded m/D wide. This is the figure
    scripts/mesh_prove_scale.py checks against live per-device buffer
    stats, validating the 2^21+ plan by execution (reference analog of
    the O(N/P) worker footprint, /root/reference/src/worker.rs:223-227)."""
    local = m // n_devices
    planes = 25 * FR_BYTES_DEVICE * local
    stacks = 23 * FR_BYTES_DEVICE * local  # sel(13)+sig(5)+wires(5) stacked
    tables = 3 * FR_BYTES_DEVICE * local   # ep, zh_inv, shifted_inv
    # n-scale state (pk polys, wire polys) is m/8-scale — small but real
    base = 28 * FR_BYTES_DEVICE * (n // n_devices)
    return {
        "local_elems": local, "planes": planes, "stacks": stacks,
        "tables": tables, "base": base,
        "resident": planes + stacks + tables + base,
    }


def msm_mesh_plan(n, n_devices, batch=1, c_bits=8, signed=True,
                  group=512):
    """Byte budget for a batch-B mesh MSM of n points over n_devices."""
    fq = 24 * 4
    local = -(-n // n_devices)
    windows = 256 // c_bits
    buckets = 1 << (c_bits - 1) if signed else 1 << c_bits
    coords = 2 if signed else 3  # affine bases vs jacobian
    bases = coords * fq * local
    digits = 4 * batch * windows * local
    planes = 3 * fq * group * batch * windows * buckets
    return {
        "local_points": local, "bases": bases, "digits": digits,
        "planes": planes, "total": bases + digits + 2 * planes,
    }
