"""Device mesh construction.

Replaces the reference's network config (`config/network.json` +
/root/reference/src/config.rs:5-9): where the reference enumerates worker
socket addresses, the TPU build enumerates devices on one axis of a
jax.sharding.Mesh. Multi-host extension happens by initializing
jax.distributed and letting jax.devices() span hosts (DCN), with the same
mesh axis semantics.
"""

import numpy as np
import jax

SHARD_AXIS = "shards"


def make_mesh(n_devices=None, platform=None):
    """1-D mesh over the first n_devices (default: all) devices.

    platform: None = jax's default backend. On hosts where a TPU plugin
    outranks JAX_PLATFORMS (e.g. the axon tunnel exposes 1 real chip),
    pass platform="cpu" to build the N-device virtual host mesh
    (--xla_force_host_platform_device_count).
    """
    devs = jax.devices(platform) if platform else jax.devices()
    if n_devices is not None:
        assert len(devs) >= n_devices, (
            f"need {n_devices} {platform or 'default'} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.array(devs), (SHARD_AXIS,))
