"""Device mesh construction.

Replaces the reference's network config (`config/network.json` +
/root/reference/src/config.rs:5-9): where the reference enumerates worker
socket addresses, the TPU build enumerates devices on one axis of a
jax.sharding.Mesh. Multi-host extension happens by initializing
jax.distributed and letting jax.devices() span hosts (DCN), with the same
mesh axis semantics.
"""

import contextlib

import numpy as np
import jax

SHARD_AXIS = "shards"


def pallas_guard(mesh):
    """Context manager for TRACING mesh programs: disables the Pallas
    mont_mul dispatch unless the mesh's own devices are TPUs.

    field_jax._use_pallas keys off jax.default_backend(), which is the
    PROCESS default — on a host where a TPU plugin outranks JAX_PLATFORMS
    (the axon tunnel), a virtual CPU mesh traced in a TPU-default process
    would otherwise emit Mosaic pallas_calls that cannot lower for CPU
    execution (observed: cpu_aot_loader KeyError crash in the bucket
    scan). On a real TPU mesh this is a no-op and the kernels stay."""
    from ..backend import field_jax as FJ

    if mesh.devices.ravel()[0].platform == "tpu":
        return contextlib.nullcontext()
    return FJ.pallas_disabled()


def init_multihost(coordinator, num_processes, process_id,
                   local_device_ids=None):
    """Join a multi-host (DCN) mesh group: after this, jax.devices() spans
    every host and make_mesh() builds cross-host meshes whose collectives
    ride ICI within a pod and DCN across pods.

    This is the multi-controller replacement for the reference's
    dispatcher->worker star + worker<->worker peer mesh
    (/root/reference/config/network.json, src/worker.rs:441-536): instead
    of one coordinator driving RPC fan-outs, every host runs the same
    program and XLA inserts the cross-host collectives.

    coordinator: "host:port" of process 0 (the network.json analog).
    """
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return jax.process_count(), jax.device_count()


def make_submesh(devices):
    """1-D mesh over an EXPLICIT device list — the placement scheduler's
    construction hook (service/placement.py): it partitions jax.devices()
    into disjoint leased submeshes, and each lease's big sharded prove
    runs on a Mesh built from exactly its devices, so concurrent
    submeshes never contend for a chip. The device list should be
    ICI-contiguous (the leaser hands out contiguous runs of the
    enumeration order) for collective locality."""
    devs = list(devices)
    assert devs, "submesh needs at least one device"
    return jax.sharding.Mesh(np.array(devs), (SHARD_AXIS,))


def make_mesh(n_devices=None, platform=None):
    """1-D mesh over the first n_devices (default: all) devices.

    platform: None = jax's default backend. On hosts where a TPU plugin
    outranks JAX_PLATFORMS (e.g. the axon tunnel exposes 1 real chip),
    pass platform="cpu" to build the N-device virtual host mesh
    (--xla_force_host_platform_device_count).
    """
    devs = jax.devices(platform) if platform else jax.devices()
    if n_devices is not None:
        assert len(devs) >= n_devices, (
            f"need {n_devices} {platform or 'default'} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.array(devs), (SHARD_AXIS,))
