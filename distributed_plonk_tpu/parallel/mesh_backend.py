"""MeshBackend: the full 5-round prover over a device mesh.

The mesh analog of the reference's fully-distributed v2 prover
(/root/reference/src/dispatcher2.rs:192-713): where the reference's
dispatcher drives per-FFT and per-MSM RPC fan-outs to workers and
reassembles results on the host between every phase
(dispatcher2.rs:731-787, 834-893), here the whole prover state lives
SHARDED on a jax.sharding.Mesh for all 5 rounds:

  - poly handles are (16, L) Montgomery limb arrays laid out
    P(None, "shards") over the mesh axis — each device owns a contiguous
    coefficient range, the moral equivalent of the reference's
    FftWorkload row/col ranges (src/utils.rs:3-19) but resident across
    rounds instead of re-scattered per call;
  - NTTs run as the one-program 4-step mesh NTT (ntt_mesh.MeshNttPlan:
    sharded butterfly stages + a single lax.all_to_all transpose over
    ICI), replacing the reference's 4 network phases per FFT;
  - commitments run as the range-sharded signed Pippenger
    (msm_mesh.MeshMsmContext): on-device digit extraction per shard,
    bucket planes folded across the mesh with all_gather + projective
    adds, replacing the reference's host-side partial-sum fold;
  - the remaining round math (permutation product, quotient evaluation,
    blinding, evaluation, linear combination, synthetic division)
    reuses the single-device jitted kernels on sharded inputs — XLA's
    SPMD partitioner inserts the cross-shard collectives (the log-depth
    prefix-product scans become collective-permute ladders), which is
    the TPU-native replacement for writing per-phase RPCs.

Domains too small to 2D-shard across the mesh (r or c not divisible by
the device count) fall back to the replicated single-device kernels on
the same mesh devices — correctness is placement-independent, and the
tiny-domain case is exactly where sharding has nothing to win.

prove(rng, ckt, pk, MeshBackend(mesh)) produces byte-identical proofs to
the host oracle and the single-device backend (asserted in
tests/test_mesh_backend_prove.py), matching the reference's invariant
that the distributed result equals the single-node one (SURVEY.md §4).
"""

import functools
import os

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..constants import FR_GENERATOR, FR_LIMBS
from ..backend import field_jax as FJ
from ..backend import prover_jax as PJ
from ..backend.jax_backend import JaxBackend
from .mesh import SHARD_AXIS
from .ntt_mesh import MeshNttPlan, _split_rc
from .msm_mesh import MeshMsmContext

import jax.numpy as jnp


class MeshBackend(JaxBackend):
    """Backend whose poly handles are mesh-sharded device arrays."""

    name = "mesh"
    # memory strategy here is sharding, not streaming+packing: slicing a
    # GSPMD-sharded lane axis per quotient chunk would reshard every slice
    quotient_streamed = None
    quotient_poly_streamed = None
    # MeshMsmContext has no stacked-chunk commit path; prove_many's
    # getattr falls back to commit_many_h (and mesh placements are
    # single-job groups anyway — big proves shard, they don't batch)
    commit_batch = None

    # minimum per-device coefficient count for sharding a handle: below
    # this, elementwise/scan round math runs REPLICATED on the mesh
    # (sharding 32 coefficients over 8 devices buys nothing and costs an
    # SPMD-partitioned compile of every scan kernel — measured ~45 s per
    # kernel per shape on the 8-device CPU mesh). The explicit collective
    # paths (4-step mesh NTT, range-sharded mesh MSM) are always sharded;
    # this knob only gates GSPMD propagation through the round math.
    _MIN_LOCAL = int(os.environ.get("DPT_MESH_MIN_LOCAL", "1024"))

    def __init__(self, mesh):
        super().__init__()
        self.mesh = mesh
        self.d = mesh.devices.size
        self._mesh_plans = {}

    # --- placement hooks ----------------------------------------------------

    def _sharding1(self, L):
        """Sharding for a (16, L) handle: coefficient-sharded when the
        length divides evenly and the local slice is worth it, replicated
        otherwise."""
        sharded = L % self.d == 0 and L // self.d >= self._MIN_LOCAL
        spec = P(None, SHARD_AXIS) if sharded else P(None)
        return NamedSharding(self.mesh, spec)

    def _lift_arr(self, arr):
        return jax.device_put(arr, self._sharding1(arr.shape[1]))

    def _lift_tab(self, arr, w, n):
        sharded = n % self.d == 0 and n // self.d >= self._MIN_LOCAL
        spec = P(None, None, SHARD_AXIS) if sharded else P(None)
        return jax.device_put(arr.reshape(FR_LIMBS, w, n),
                              NamedSharding(self.mesh, spec))

    # --- NTT: 4-step mesh kernel with small-domain fallback -----------------

    def _plan(self, n):
        if n not in self._mesh_plans:
            r, c = _split_rc(n)
            self._mesh_plans[n] = (MeshNttPlan(self.mesh, n)
                                   if r % self.d == 0 and c % self.d == 0
                                   else None)
        return self._mesh_plans[n]

    def _kernel(self, domain, h, inverse, coset):
        plan = self._plan(domain.size)
        if plan is None:
            return super()._kernel(domain, h, inverse, coset)
        if h.shape[1] < domain.size:
            h = jnp.pad(h, ((0, 0), (0, domain.size - h.shape[1])))
        assert h.shape[1] == domain.size
        return plan.kernel(inverse=inverse, coset=coset, boundary="mont")(h)

    def _kernel_many(self, domain, hs, inverse, coset):
        plan = self._plan(domain.size)
        if plan is None:
            return super()._kernel_many(domain, hs, inverse, coset)
        # one 4-step mesh program per poly: at mesh-worthy sizes the
        # single-poly program already fills the devices, and a fixed
        # shape set (one per mode) keeps compiles bounded
        fn = plan.kernel(inverse=inverse, coset=coset, boundary="mont")
        out = []
        for h in hs:
            if h.shape[1] < domain.size:
                h = jnp.pad(h, ((0, 0), (0, domain.size - h.shape[1])))
            out.append(fn(h))
        return out

    # --- MSM: range-sharded signed Pippenger --------------------------------

    def _make_msm_ctx(self, bases):
        return MeshMsmContext(self.mesh, bases)

    # --- quotient tables pinned to the mesh ---------------------------------

    def _domain_tables(self, m, n, group_gen):
        # the parent's domain_tables_jit has no array inputs, so it would
        # compute on the process-default device — possibly a different
        # platform than the mesh. Pin computation + placement to the mesh
        # via out_shardings.
        key = (m, n)
        with self._cache_lock:
            hit = self._domain_tabs.get(key)
        if hit is None:
            sh = self._sharding1(m)
            fn = jax.jit(PJ.domain_tables, static_argnums=(0, 1, 2, 3),
                         out_shardings={"ep": sh, "zh_inv": sh,
                                        "shifted_inv": sh})
            hit = fn(m, n, FR_GENERATOR, group_gen)
            with self._cache_lock:
                self._domain_tabs[key] = hit
        return hit


def _no_pallas(name):
    """Wrap an inherited round-math method in field_jax.pallas_disabled():
    these run as GSPMD-auto-sharded jit programs on the mesh, where a
    pallas_call (no SPMD partitioning rule) must not appear. The explicit
    shard_map paths — mesh NTT and mesh MSM, the hot 95% — keep the Pallas
    multiplier (per-device local)."""
    parent = getattr(JaxBackend, name)

    @functools.wraps(parent)
    def wrapped(self, *args, **kwargs):
        with FJ.pallas_disabled():
            return parent(self, *args, **kwargs)

    return wrapped


for _name in ("blind", "eval_h", "eval_many_h", "lin_comb_h", "synth_div_h",
              "perm_product", "quotient", "degree_is", "split",
              "dump_h", "load_h"):
    setattr(MeshBackend, _name, _no_pallas(_name))
del _name
