"""Range-check circuits: prove `count` public values lie in [0, 2^bits).

The classic bit-decomposition gadget on the TurboPlonk gate set: each
value is decomposed into `bits` private bit witnesses, every bit is
constrained boolean (enforce_bool, one q_mul gate each), and the bits
are recomposed back to the public value through a chain of 4-input
linear-combination gates (3 bits + the running accumulator per gate, so
ceil(bits/3) lc gates per value). Cost: count * (bits + ceil(bits/3) + 2)
gates plus the IO rows — a deliberately lc/mul-heavy selector profile,
the opposite end of the spectrum from the q_hash-dominated Rescue
families, so shape buckets of equal domain size but different kind carry
genuinely different selector polynomials (what the kind-in-shape_key
satellite of ISSUE 17 protects).
"""

import random

from ..circuit import PlonkCircuit

MAX_BITS = 64
MAX_COUNT = 512


def validate(obj):
    bits = obj.get("bits")
    if not isinstance(bits, int) or not 1 <= bits <= MAX_BITS:
        raise ValueError(f"range spec needs 1 <= bits <= {MAX_BITS}")
    count = obj.get("count", 1)
    if not isinstance(count, int) or not 1 <= count <= MAX_COUNT:
        raise ValueError(f"range spec needs 1 <= count <= {MAX_COUNT}")
    return {"bits": bits, "count": count}


def build(params, seed):
    bits, count = params["bits"], params["count"]
    rng = random.Random(seed)
    cs = PlonkCircuit()
    for _ in range(count):
        value = rng.randrange(1 << bits)
        value_var = cs.create_public_variable(value)
        bit_vars = []
        for i in range(bits):
            b = cs.create_variable((value >> i) & 1)
            cs.enforce_bool(b)
            bit_vars.append(b)
        # recompose little-endian, 3 bits + accumulator per lc gate:
        # acc' = acc + 2^i b_i + 2^(i+1) b_(i+1) + 2^(i+2) b_(i+2)
        acc = cs.zero_var
        for i in range(0, bits, 3):
            chunk = bit_vars[i:i + 3]
            coeffs = [1] + [1 << (i + j) for j in range(len(chunk))]
            while len(chunk) < 3:
                chunk.append(cs.zero_var)
                coeffs.append(0)
            acc = cs.lc([acc] + chunk, coeffs)
        cs.enforce_equal(acc, value_var)
    ok, bad = cs.check_satisfiability()
    assert ok, f"range circuit unsatisfied at gate {bad}"
    return cs.finalize()
