"""Rescue-hash preimage circuits: knowledge of (x, y, z) with
H(x, y, z) = digest, digest public.

One rescue.hash3_gadget per statement — a single width-4 Rescue-Prime
permutation, ~148 q_hash-dominated gates — with the computed digest
exposed as a public input. The preimage triple stays private (plain
witness variables, never IO rows). This is the pure-hash end of the zoo's
selector spectrum: essentially every gate row carries q_hash weight,
which stresses the selector-commitment path the lc-heavy `range` family
barely touches.
"""

import random

from ..circuit import PlonkCircuit
from ..constants import R_MOD
from .. import rescue

MAX_COUNT = 256


def validate(obj):
    count = obj.get("count", 1)
    if not isinstance(count, int) or not 1 <= count <= MAX_COUNT:
        raise ValueError(f"preimage spec needs 1 <= count <= {MAX_COUNT}")
    return {"count": count}


def build(params, seed):
    rng = random.Random(seed)
    cs = PlonkCircuit()
    for _ in range(params["count"]):
        x, y, z = (rng.randrange(R_MOD) for _ in range(3))
        xv, yv, zv = (cs.create_variable(v) for v in (x, y, z))
        digest_var = rescue.hash3_gadget(cs, xv, yv, zv)
        assert cs.witness[digest_var] == rescue.hash3(x, y, z)
        cs.set_public(digest_var)
    ok, bad = cs.check_satisfiability()
    assert ok, f"preimage circuit unsatisfied at gate {bad}"
    return cs.finalize()
