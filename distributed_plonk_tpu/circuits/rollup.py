"""Rollup-style state-transition batch: the zoo's flagship shape.

M account-balance updates applied in sequence under one 3-ary Rescue
Merkle root, proven in ONE circuit: the pre-batch root and the
post-batch root are the only public inputs, and every intermediate
transition is enforced in-circuit — for each update, membership of the
old balance under the current root AND correctness of the new root after
writing `old + delta` back into the same leaf slot. The host-side
MerkleTree is purely a witness oracle (paths, siblings, expected roots);
nothing it produces is trusted by the circuit beyond the two public
roots.

The update gadget is the cost win over two independent membership proofs:
the position bits, their boolean/one-hot constraints, and the sibling
witnesses are SHARED between the old-root and new-root recomputations
(only the two Rescue chains differ), ~2x148 gates per level instead of
2x159. Per update: 2(H+1) Rescue permutations + selection ≈ 310(H+1)
gates, so even the small test shapes land in the multi-thousand-gate
domains the schedulers' flagship SLO class is meant to carry.
"""

import random

from ..circuit import PlonkCircuit
from ..constants import R_MOD
from .. import merkle, rescue

MAX_HEIGHT = 16
MAX_UPDATES = 64


def validate(obj):
    height = obj.get("height")
    if not isinstance(height, int) or not 1 <= height <= MAX_HEIGHT:
        raise ValueError(f"rollup spec needs 1 <= height <= {MAX_HEIGHT}")
    updates = obj.get("updates", 1)
    if not isinstance(updates, int) or not 1 <= updates <= MAX_UPDATES:
        raise ValueError(f"rollup spec needs 1 <= updates <= {MAX_UPDATES}")
    cap = merkle.BRANCH ** height
    num_accounts = obj.get("num_accounts")
    if num_accounts is None:
        num_accounts = min(cap, max(updates, 2))
    if not isinstance(num_accounts, int) or not 1 <= num_accounts <= cap:
        raise ValueError(
            f"rollup spec needs 1 <= num_accounts <= 3^height ({cap})")
    return {"height": height, "updates": updates,
            "num_accounts": num_accounts}


def _update_gadget(cs, index, old_payload_var, new_payload_var, path):
    """Recompute the root twice from one leaf slot — once with the old
    payload, once with the new — sharing the position bits (boolean +
    one-hot constrained) and sibling witnesses between the two chains.
    `path` holds the PRE-update siblings; returns (old_root, new_root)
    variables."""
    idx_var = cs.create_variable(index)
    cs.add_constant_gate(idx_var, index)
    old_cur = rescue.hash3_gadget(cs, idx_var, old_payload_var, cs.one_var)
    new_cur = rescue.hash3_gadget(cs, idx_var, new_payload_var, cs.one_var)
    for pos, sibs in path:
        b = [cs.create_variable(1 if pos == j else 0)
             for j in range(merkle.BRANCH)]
        for bj in b:
            cs.enforce_bool(bj)
        cs.enforce_equal(
            cs.lc([b[0], b[1], b[2], cs.zero_var], [1, 1, 1, 0]), cs.one_var)
        sib_vars = [cs.create_variable(s) for s in sibs]
        old_cur = rescue.hash3_gadget(
            cs, *merkle._select3(cs, old_cur, sib_vars, b))
        new_cur = rescue.hash3_gadget(
            cs, *merkle._select3(cs, new_cur, sib_vars, b))
    return old_cur, new_cur


def build(params, seed):
    height = params["height"]
    updates = params["updates"]
    num_accounts = params["num_accounts"]
    rng = random.Random(seed)

    balances = [rng.randrange(R_MOD) for _ in range(num_accounts)]
    tree = merkle.MerkleTree(balances, height=height)

    cs = PlonkCircuit()
    cur_root_var = cs.create_public_variable(tree.root)
    for m in range(updates):
        # account choice is structural (m % num_accounts, like the merkle
        # workload's leaf indices): same params -> same paths -> same wiring
        account = m % num_accounts
        proof = tree.open(account)
        delta = rng.randrange(R_MOD)
        old_var = cs.create_variable(proof.payload)
        delta_var = cs.create_variable(delta)
        new_var = cs.add(old_var, delta_var)
        old_root, new_root = _update_gadget(
            cs, account, old_var, new_var, proof.path)
        cs.enforce_equal(old_root, cur_root_var)
        cur_root_var = new_root
        # advance the witness oracle and cross-check the in-circuit root
        balances[account] = (balances[account] + delta) % R_MOD
        tree = merkle.MerkleTree(balances, height=height)
        assert cs.witness[new_root] == tree.root
    cs.set_public(cur_root_var)

    ok, bad = cs.check_satisfiability()
    assert ok, f"rollup circuit unsatisfied at gate {bad}"
    return cs.finalize()
