"""The circuit zoo: real workloads behind `circuit_kind` (ISSUE 17).

Everything the service proved before this package existed was the
synthetic `_toy_circuit` chain in service/jobs.py plus the Merkle
workload generator — fine for exercising the prover, useless for
exercising the SCHEDULER, whose whole job (shape bucketing, cross-job
batching, placement, SLO classes) only becomes interesting under
heterogeneous traffic. The zoo is a registry of circuit families built
on the existing 5-wire/13-selector builder (circuit.PlonkCircuit), each
obeying the service's one structural contract (service/jobs.py):

    two specs with the same params but different seeds produce circuits
    with IDENTICAL structure (gates, wiring, selectors) — only witness
    values and public inputs differ.

That contract is what lets a bucket's SRS + proving key be shared across
every job in the bucket, so every builder here derives gate COUNT and
WIRING purely from params, and draws only witness VALUES from the seed.

Kinds (each module exposes validate(obj) -> params and
build(params, seed) -> finalized, satisfiability-checked circuit):

  range     bit-decomposition range checks: `count` public values each
            proven to lie in [0, 2^bits) via enforce_bool chains
  preimage  Rescue-hash preimage knowledge: public digests, private
            (x, y, z) preimages through hash3_gadget
  rollup    the flagship shape — a rollup-style state-transition batch:
            `updates` account-balance updates under one 3-ary Rescue
            Merkle root, old root and final root public, every
            intermediate transition proven in-circuit

The pre-existing `toy` and `merkle` kinds stay where they were
(service/jobs.py, workload.py); the registry here covers only the new
families, and service/jobs.py routes `circuit_kind` through REGISTRY so
adding a kind is: write a module, add it to REGISTRY, done — loadgen's
--circuit-mix and the bucket cache pick it up by name.
"""

from . import preimage, range_check, rollup

# kind name -> module with validate(obj)->params, build(params, seed)->ckt
REGISTRY = {
    "range": range_check,
    "preimage": preimage,
    "rollup": rollup,
}

KINDS = tuple(sorted(REGISTRY))


def validate_params(kind, obj):
    """Untrusted wire dict -> canonical params dict for `kind`.
    Raises ValueError with a client-presentable reason."""
    mod = REGISTRY.get(kind)
    if mod is None:
        raise ValueError(f"unknown circuit kind {kind!r}")
    return mod.validate(obj)


def build(kind, params, seed):
    """(kind, params, seed) -> finalized circuit; every builder runs
    check_satisfiability() before finalize, so a buggy witness generator
    fails loudly at build time, never as an unverifiable proof."""
    mod = REGISTRY.get(kind)
    if mod is None:
        raise ValueError(f"unknown circuit kind {kind!r}")
    return mod.build(params, seed)
