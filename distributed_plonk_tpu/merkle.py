"""3-ary Rescue Merkle tree + membership proofs, native and in-circuit.

Re-provides the `jf-primitives` MerkleTree surface the reference's workload
generator consumes (/root/reference/src/dispatcher.rs:1076-1096 builds a
height-32 tree and pulls per-element membership proofs;
/root/reference/src/dispatcher.rs:1097-1108 verifies them in-circuit via
MerkleTreeGadget). Same shape: branching factor 3 (the Rescue rate), sparse
tree addressed by u64 leaf index, leaf digest = H(index, payload, tag).

The in-circuit path verifier costs ~159 gates per level (148 for the
permutation + 11 for position selection: 3 enforce_bool + one-hot lc +
enforce_equal + 6 in _select3, the same count workload.py's cost model
uses), matching the order of the reference's stated cost model
`num_proofs * (157*height + 149)`
(/root/reference/src/dispatcher.rs:1068-1070).
"""

from .constants import R_MOD
from . import rescue

BRANCH = 3
LEAF_TAG = 1  # domain separator: leaf digests vs internal nodes


def leaf_digest(index, payload):
    return rescue.hash3(index, payload, LEAF_TAG)


def node_digest(children):
    assert len(children) == BRANCH
    return rescue.hash3(*children)


class MerkleTree:
    """Dense bottom-up 3-ary tree over a list of payloads.

    Supports the reference workload's access pattern: build once from a
    vector of leaves, read the root, open membership proofs by index.
    """

    def __init__(self, payloads, height=None):
        self.payloads = [p % R_MOD for p in payloads]
        n = max(1, len(self.payloads))
        h = 1
        while BRANCH ** h < n:
            h += 1
        if height is not None:
            assert BRANCH ** height >= n, "height too small for leaf count"
            h = height
        self.height = h
        level = [leaf_digest(i, p) for i, p in enumerate(self.payloads)]
        # levels[0] = leaf digests, levels[-1] = [root]
        self.levels = [level]
        empty = 0  # digest standing in for absent children
        for _ in range(h):
            level = level + [empty] * ((-len(level)) % BRANCH)
            nxt = [node_digest(level[i:i + BRANCH])
                   for i in range(0, len(level), BRANCH)]
            self.levels.append(nxt)
            level = nxt
        assert len(self.levels[-1]) == 1

    @property
    def root(self):
        return self.levels[-1][0]

    def open(self, index):
        """Membership proof: per level bottom-up, (position in {0,1,2},
        the two sibling digests left-to-right)."""
        assert 0 <= index < len(self.payloads)
        path = []
        idx = index
        for lvl in range(self.height):
            pos = idx % BRANCH
            base = idx - pos
            row = self.levels[lvl]
            sibs = [row[base + j] if base + j < len(row) else 0
                    for j in range(BRANCH) if j != pos]
            path.append((pos, sibs))
            idx //= BRANCH
        return MerkleProof(index, self.payloads[index], path)


class MerkleProof:
    def __init__(self, index, payload, path):
        self.index = index
        self.payload = payload
        self.path = path  # [(pos, [sib0, sib1])] bottom-up

    def verify(self, root):
        cur = leaf_digest(self.index, self.payload)
        for pos, sibs in self.path:
            children = list(sibs)
            children.insert(pos, cur)
            cur = node_digest(children)
        return cur == root


# --- in-circuit membership gadget --------------------------------------------

def _select3(cs, cur, sibs, b):
    """Arrange (cur, sibs[0], sibs[1]) into 3 child slots according to the
    one-hot position bits b = (b0, b1, b2): pos 0 -> (cur, s0, s1),
    pos 1 -> (s0, cur, s1), pos 2 -> (s0, s1, cur). 6 gates."""
    s0, s1 = sibs
    # slot0 = b0*(cur - s0) + s0
    d0 = cs.sub(cur, s0)
    slot0 = cs.mul_add(b[0], d0, s0, cs.one_var)
    # slot1 = b1*cur + b0*s0 + b2*s1
    t = cs.mul_add(b[1], cur, b[0], s0)
    slot1 = cs.mul_add(b[2], s1, t, cs.one_var)
    # slot2 = b2*(cur - s1) + s1
    d1 = cs.sub(cur, s1)
    slot2 = cs.mul_add(b[2], d1, s1, cs.one_var)
    return slot0, slot1, slot2


def membership_gadget(cs, index, payload_var, proof):
    """Verify a MerkleProof in-circuit; returns the computed root variable.

    Position bits are private witnesses, constrained boolean and one-hot per
    level (the index itself never needs range decomposition beyond that).
    """
    idx_var = cs.create_variable(index)
    cs.add_constant_gate(idx_var, index)  # bind the claimed leaf index
    cur = rescue.hash3_gadget(cs, idx_var, payload_var, cs.one_var)
    for pos, sibs in proof.path:
        b = [cs.create_variable(1 if pos == j else 0) for j in range(BRANCH)]
        for bj in b:
            cs.enforce_bool(bj)
        # one-hot: b0 + b1 + b2 == 1
        cs.enforce_equal(
            cs.lc([b[0], b[1], b[2], cs.zero_var], [1, 1, 1, 0]), cs.one_var)
        sib_vars = [cs.create_variable(s) for s in sibs]
        slots = _select3(cs, cur, sib_vars, b)
        cur = rescue.hash3_gadget(cs, *slots)
    return cur
