#!/usr/bin/env python3
"""Live fleet console: one terminal pane over the observability plane.

    python scripts/console.py --obs 127.0.0.1:9560            # live, 2s
    python scripts/console.py --obs 127.0.0.1:9560 --once     # snapshot
    python scripts/console.py --obs 127.0.0.1:9560 --logs 10  # w/ log tail

Renders the /fleet + /healthz JSON of a serve.py --obs-port daemon (or
any ObsServer): service readiness (queue depth, busy workers, draining),
the membership summary (epoch, width, suspects, open breakers), and one
row per fleet member — reachability, breaker/suspect state, served
request counters, live kernel gflops/MFU gauges, injected-SDC count —
plus the round-pipeline fill pane (pipelined attempts/jobs, achieved
depth, stage waits, per-round device-idle — parsed from /metrics; one
quiet '(off)' line when DPT_PIPELINE=0 or nothing pipelined yet), the
/autoscale controller pane (targets, per-class queue depth, last 5
decisions; one quiet '(off)' line when DPT_AUTOSCALE=0) and an
optional tail of the structured log ring (/logs). Plain ANSI,
no curses: works over any ssh session, and --once makes it scriptable
(the loadgen soak and tests use it as the "can an operator actually see
the fleet" check)."""

import argparse
import json
import sys
import time
import urllib.request


def _get(base, path, timeout=5):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _get_text(base, path, timeout=5):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read().decode()


def _pipeline_pane(base):
    """Round-pipeline fill pane, parsed off the Prometheus exposition
    (/metrics is the only surface that carries the dpt_pipeline* family).
    A daemon that never ran a pipelined attempt — or DPT_PIPELINE=0 —
    renders as one quiet '(off)' line."""
    try:
        text = _get_text(base, "/metrics")
    except Exception:
        return ["pipeline (off)"]
    vals = {}
    for line in text.splitlines():
        if not line.startswith(("dpt_pipeline", "dpt_pipelined")):
            continue
        name, _, raw = line.partition(" ")
        try:
            vals[name] = float(raw)
        except ValueError:
            pass
    if not vals.get("dpt_pipelined_proves_total"):
        return ["pipeline (off)"]
    idle = ", ".join(
        "r%s=%.3gs" % (k.rsplit("round", 1)[-1], v)
        for k, v in sorted(vals.items())
        if k.startswith("dpt_pipeline_device_idle_s_round"))
    return [
        "pipeline proves=%d jobs=%d depth=%g "
        "depth_p50=%g stage_wait_p95=%.3gs" % (
            vals.get("dpt_pipelined_proves_total", 0),
            vals.get("dpt_pipelined_jobs_total", 0),
            vals.get("dpt_pipeline_depth", 0),
            vals.get('dpt_pipeline_depth_achieved_seconds'
                     '{quantile="0.5"}', 0),
            vals.get('dpt_pipeline_stage_wait_s_seconds'
                     '{quantile="0.95"}', 0)),
        "  device_idle(%s)" % (idle or "-")]


def _fmt_member(m):
    state = "LEFT" if m.get("left") else \
        "SUSPECT" if m.get("suspect") else \
        "OPEN" if not m.get("usable") else \
        "up" if m.get("reachable") else "down"
    snap = m.get("snapshot") or {}
    ctr = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    served = sum(v for k, v in ctr.items() if k.startswith("served_"))
    kernels = ", ".join(
        f"{k[len('kernel_'):-len('_gflops')]}={v:g}"
        for k, v in sorted(gauges.items())
        if k.startswith("kernel_") and k.endswith("_gflops"))
    return (f"  [{m['index']:>2}] {m.get('addr', '?'):<21} {state:<7} "
            f"served={served:<6} sdc={snap.get('sdc_injected', 0):<3} "
            f"epoch={snap.get('epoch', '?'):<3} "
            f"gflops({kernels or '-'})")


def _autoscale_pane(base):
    """Controller pane: targets, per-class queue depth, the last 5
    decisions. A 404 (DPT_AUTOSCALE=0 / unattached) renders as one
    quiet '(off)' line so the console works against any daemon."""
    try:
        a = _get(base, "/autoscale")
    except Exception:
        return ["autoscale (off)"]
    b, t, cd = a.get("bounds") or {}, a.get("targets") or {}, \
        a.get("cooldowns") or {}
    st = a.get("streaks") or {}
    lines = [
        f"autoscale mode={a.get('mode')} workers={a.get('workers')} "
        f"bounds={b.get('min_workers')}..{b.get('max_workers')} "
        f"up@{t.get('up_queue_per_worker')}/worker "
        f"p95_slo={t.get('slo_p95_standard_s')} "
        f"streak(up={st.get('up')},down={st.get('down')}) "
        f"cooldown(up={cd.get('up_remaining_s')}s,"
        f"down={cd.get('down_remaining_s')}s)"]
    q = a.get("queue") or {}
    by = q.get("by_class") or {}
    lines.append("  queue  depth=%s  %s" % (
        q.get("depth"),
        " ".join(f"{c}={by.get(c, 0)}"
                 for c in ("flagship", "standard", "batch"))))
    for d in (a.get("last_decisions") or [])[-5:]:
        ts = time.strftime("%H:%M:%S", time.localtime(d.get("ts", 0)))
        lines.append(f"  {ts} [{d.get('action')}] "
                     f"applied={d.get('applied')} {d.get('reason', '')}")
    return lines


def render(base, log_tail=0):
    lines = []
    h = _get(base, "/healthz")
    flt = h.get("fleet")
    lines.append(f"service  ok={h.get('ok')} uptime={h.get('uptime_s')}s "
                 f"queue={h.get('queue_depth')} "
                 f"busy={h.get('busy_workers')} "
                 f"draining={h.get('draining')}")
    by_kind = h.get("jobs_by_kind") or {}
    if by_kind:
        # circuit-zoo pane: per-kind job table + built-aggregate count
        kinds = " ".join(
            "%s(%s)" % (k, ",".join(f"{s}={n}"
                                    for s, n in sorted(v.items())))
            for k, v in sorted(by_kind.items()))
        lines.append(f"circuits {kinds} "
                     f"aggregates={h.get('aggregates', 0)}")
    if flt:
        lines.append(f"fleet    epoch={flt['epoch']} width={flt['width']} "
                     f"usable={flt['usable']} suspects={flt['suspects']} "
                     f"breakers_open={flt['breakers_open']}")
        try:
            fl = _get(base, "/fleet")
            for m in fl.get("members", []):
                lines.append(_fmt_member(m))
        except Exception as e:  # /fleet needs attach_fleet; say so once
            lines.append(f"  (no /fleet snapshot: {e})")
    else:
        lines.append("fleet    (none attached)")
    lines.extend(_pipeline_pane(base))
    lines.extend(_autoscale_pane(base))
    if log_tail:
        try:
            lg = _get(base, f"/logs?limit={log_tail}")
            lines.append(f"logs     (last {log_tail} of seq "
                         f"{lg.get('seq')})")
            for e in lg.get("events", []):
                ts = time.strftime("%H:%M:%S",
                                   time.localtime(e.get("ts", 0)))
                extra = {k: v for k, v in e.items()
                         if k not in ("ts", "seq", "level", "subsystem",
                                      "event", "proc", "pid")}
                lines.append(f"  {ts} {e.get('level', '?'):<5} "
                             f"{e.get('subsystem', '?')}/"
                             f"{e.get('event', '?')} {extra}")
        except Exception as e:
            lines.append(f"logs     (unavailable: {e})")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--obs", required=True,
                    help="host:port of the ObsServer (serve.py banner's "
                         "'obs' field)")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (scriptable)")
    ap.add_argument("--logs", type=int, default=0, metavar="N",
                    help="also tail the last N structured log events")
    args = ap.parse_args()
    base = f"http://{args.obs}"
    if args.once:
        print(render(base, log_tail=args.logs))
        return 0
    try:
        while True:
            frame = render(base, log_tail=args.logs)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
