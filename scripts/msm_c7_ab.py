#!/usr/bin/env python3
"""A/B c=8 (32x128) vs c=7 (37x64) signed MSM windows on the chip.

DPT_MSM_C is an import-time class default, so each config runs in a
fresh subprocess: warm 2^20 MSM wall-clock (reference micro-test scale,
/root/reference/src/dispatcher.rs:188-196: 2^11 distinct bases tiled up)
plus a 2^12 host-oracle correctness check. The two configs must also
agree on the 2^20 result point.

Usage: python scripts/msm_c7_ab.py [--log-n 20] [--out FILE]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

INNER = r"""
import json, random, sys, time
sys.path.insert(0, %(repo)r)
from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.backend.msm_jax import MsmContext

LOG_N = %(log_n)d
N = 1 << LOG_N
rng = random.Random(3)
distinct = [C.g1_mul(C.G1_GEN, rng.randrange(1, R_MOD)) for _ in range(1 << 11)]
bases = (distinct * (N // len(distinct) + 1))[:N]
scalars = [rng.randrange(R_MOD) for _ in range(N)]

small = MsmContext(bases[:1 << 12])
got = small.msm(scalars[:1 << 12])
assert got == C.g1_msm(bases[:1 << 12], scalars[:1 << 12]), "oracle mismatch"

ctx = MsmContext(bases)
ctx.msm(scalars)  # compile + warm + adaptive calibration
t0 = time.perf_counter()
pt = ctx.msm(scalars)
dt = time.perf_counter() - t0
print("RESULT " + json.dumps({
    "c": MsmContext._C_BATCH, "msm_s": round(dt, 3),
    "points_per_s": round(N / dt),
    "adds_per_s": {str(k): round(v) for k, v in
                   MsmContext._measured_adds_per_s.items()},
    "oracle_2p12_ok": True,
    "point_x_mod": pt[0] %% 0xFFFFFFFF if pt else None}))
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--log-n", type=int, default=20)
    ap.add_argument("--out", default=None)
    ap.add_argument("--timeout", type=int, default=2400)
    args = ap.parse_args()

    results = []
    for c in ("8", "7"):
        env = dict(os.environ, DPT_MSM_C=c)
        print(f"[ab] c={c} ...", file=sys.stderr)
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 INNER % {"repo": REPO, "log_n": args.log_n}],
                env=env, capture_output=True, text=True,
                timeout=args.timeout)
        except subprocess.TimeoutExpired:
            results.append({"c": int(c), "error": "timeout"})
            continue
        line = next((l for l in proc.stdout.splitlines()
                     if l.startswith("RESULT ")), None)
        if line:
            results.append(json.loads(line[len("RESULT "):]))
            print(f"[ab]   -> {line[len('RESULT '):]}", file=sys.stderr)
        else:
            results.append({"c": int(c),
                            "error": (proc.stderr or "")[-500:]})
            print(f"[ab]   FAILED rc={proc.returncode}", file=sys.stderr)
    ok = [r for r in results if r.get("point_x_mod") is not None]
    agree = len(ok) == 2 and ok[0]["point_x_mod"] == ok[1]["point_x_mod"]
    blob = json.dumps({"log_n": args.log_n, "configs": results,
                       "c7_c8_agree": agree})
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")
    print(blob)


if __name__ == "__main__":
    main()
