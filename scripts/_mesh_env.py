"""Shared virtual-CPU-mesh environment forcing for the mesh scale scripts.

Must be imported (and `force_cpu_mesh()` called) BEFORE jax: the axon
sitecustomize imports jax at interpreter startup, so the env alone is
not enough — the in-process config must be pinned too (same recipe as
tests/conftest.py). DPT_MESH_PLATFORM=real skips the forcing for an
actual multi-chip pod.
"""

import os
import sys


def force_cpu_mesh(argv=None):
    if os.environ.get("DPT_MESH_PLATFORM", "cpu") != "cpu":
        return
    argv = sys.argv if argv is None else argv
    for k in list(os.environ):
        if k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            os.environ.pop(k)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # honor --devices / --devices=N (argparse has not run yet)
        n = "8"
        for i, a in enumerate(argv):
            if a == "--devices" and i + 1 < len(argv):
                n = argv[i + 1]
            elif a.startswith("--devices="):
                n = a.split("=", 1)[1]
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
