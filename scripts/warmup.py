#!/usr/bin/env python3
"""Pre-warm proof-service shape buckets (keys + compiled stages).

Two modes:

  # against a running server (WARMUP wire tag; --aot also precompiles):
  python scripts/warmup.py --host 127.0.0.1 --port 9555 \
      --spec '{"kind":"toy","gates":16}' --spec '{"kind":"toy","gates":60}'

  # offline store provisioning, no server (build keys straight into the
  # artifact store a later `serve.py --store-dir` will read):
  python scripts/warmup.py --store-dir /var/dpt/store \
      --spec '{"kind":"merkle","height":32,"num_proofs":1}'

With no --spec, warms the default loadgen mix (toy gates 16/60/150/300).
Prints one JSON line: per-shape source (memory|disk|built) + timings.
Exit 0 iff every shape warmed.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DEFAULT_MIX = [{"kind": "toy", "gates": g} for g in (16, 60, 150, 300)]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default=None,
                    help="warm a running server over the wire")
    ap.add_argument("--port", type=int, default=9555)
    ap.add_argument("--store-dir", default=None,
                    help="offline mode: provision this artifact store "
                         "directly, no server involved")
    ap.add_argument("--spec", action="append", default=[],
                    help="job spec JSON (repeatable); default: loadgen mix")
    ap.add_argument("--aot", action="store_true",
                    help="also precompile prover stages (wire mode: on the "
                         "server's backend; offline: on a local JaxBackend)")
    args = ap.parse_args()
    if (args.host is None) == (args.store_dir is None):
        ap.error("exactly one of --host or --store-dir is required")

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    specs = [json.loads(s) for s in args.spec] or list(_DEFAULT_MIX)
    shapes, ok = [], True
    t0 = time.time()

    if args.host is not None:
        from distributed_plonk_tpu.service import ServiceClient
        with ServiceClient(args.host, args.port) as c:
            for spec in specs:
                try:
                    shapes.append(c.warmup(spec, aot=args.aot))
                except Exception as e:  # noqa: BLE001 - report per shape
                    ok = False
                    shapes.append({"spec": spec, "error": repr(e)})
    else:
        from distributed_plonk_tpu.store import (ArtifactStore,
                                                 configure_jax_cache,
                                                 warm_spec)
        store = ArtifactStore(args.store_dir)
        aot_backend = None
        if args.aot:
            configure_jax_cache(args.store_dir)
            from distributed_plonk_tpu.backend.jax_backend import JaxBackend
            aot_backend = JaxBackend()
        for spec in specs:
            try:
                shapes.append(warm_spec(store, spec,
                                        aot_backend=aot_backend))
            except Exception as e:  # noqa: BLE001 - report per shape
                ok = False
                shapes.append({"spec": spec, "error": repr(e)})

    print(json.dumps({"ok": ok, "wall_s": round(time.time() - t0, 3),
                      "shapes": shapes}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
