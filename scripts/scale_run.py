#!/usr/bin/env python3
"""Reference-scale end-to-end prove runner.

Reproduces the reference's two built-in workloads on the device backend:

  v1 analog (--proofs 1):  height-32 Merkle membership, 1 proof
     => ~5.2k constraints, 2^13 domain   (/root/reference/src/dispatcher.rs:1064-1070)
  v2 analog (--proofs 50): 50 proofs => ~259k constraints, 2^18 domain,
     2^21 quotient domain                (/root/reference/src/dispatcher2.rs:1219-1221,246)

Pipeline: circuit generation -> device SRS (fixed-base batch kernel) ->
device preprocess -> 5-round prove on the JaxBackend (all polynomials
device-resident) -> stock verify. Emits one JSON object with phase and
per-round wall-clock.

Usage: python scripts/scale_run.py [--height 32] [--proofs 1] [--out FILE]
"""

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--height", type=int, default=32)
    ap.add_argument("--proofs", type=int, default=1)
    ap.add_argument("--out", default=None)
    ap.add_argument("--skip-verify", action="store_true")
    ap.add_argument("--single-prove", action="store_true",
                    help="one prove only (cold==warm; at 2^18 scale a second"
                         " prove doubles a long run for little signal)")
    args = ap.parse_args()

    from distributed_plonk_tpu import kzg
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.verifier import verify
    from distributed_plonk_tpu.workload import generate_circuit
    from distributed_plonk_tpu.backend.jax_backend import JaxBackend
    from distributed_plonk_tpu.trace import Tracer

    res = {"height": args.height, "num_proofs": args.proofs}
    t0 = time.perf_counter()
    ckt, _tree = generate_circuit(rng=random.Random(11), height=args.height,
                                  num_proofs=args.proofs)
    res["n"] = ckt.n
    res["log2_n"] = ckt.n.bit_length() - 1
    res["num_gates"] = ckt.num_gates
    res["circuit_gen_s"] = round(time.perf_counter() - t0, 3)
    print(f"[scale] circuit: {ckt.num_gates} gates -> n = 2^{res['log2_n']}"
          f" ({res['circuit_gen_s']}s)", file=sys.stderr)

    backend = JaxBackend()

    t0 = time.perf_counter()
    srs = kzg.universal_setup_device(ckt.n + 2, rng=random.Random(12))
    res["setup_s"] = round(time.perf_counter() - t0, 3)
    print(f"[scale] device SRS: {srs.count} powers ({res['setup_s']}s)",
          file=sys.stderr)

    t0 = time.perf_counter()
    pk, vk = kzg.preprocess(srs, ckt, backend=backend)
    res["preprocess_s"] = round(time.perf_counter() - t0, 3)
    print(f"[scale] preprocess ({res['preprocess_s']}s)", file=sys.stderr)

    # warm-up prove to separate XLA compile time from steady-state wall-clock
    # (the reference's Rust binaries have no compile phase; steady-state is
    # the honest comparison, cold includes jit)
    if not args.single_prove:
        t0 = time.perf_counter()
        prove(random.Random(13), ckt, pk, backend)
        res["prove_cold_s"] = round(time.perf_counter() - t0, 3)
        print(f"[scale] prove (cold, incl. compile): {res['prove_cold_s']}s",
              file=sys.stderr)

    tracer = Tracer()
    t0 = time.perf_counter()
    proof = prove(random.Random(13), ckt, pk, backend, tracer=tracer)
    res["prove_s"] = round(time.perf_counter() - t0, 3)
    res["rounds"] = {k: round(v, 3) for k, v in tracer.totals(depth=1).items()}
    res["trace"] = tracer.events
    print(f"[scale] prove (warm): {res['prove_s']}s  rounds={res['rounds']}",
          file=sys.stderr)

    if not args.skip_verify:
        t0 = time.perf_counter()
        ok = verify(vk, ckt.public_input(), proof, rng=random.Random(14))
        res["verify_s"] = round(time.perf_counter() - t0, 3)
        res["verified"] = bool(ok)
        assert ok, "proof did not verify"
        print(f"[scale] verified ({res['verify_s']}s)", file=sys.stderr)

    out = json.dumps(res)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    print(out)


if __name__ == "__main__":
    main()
