#!/usr/bin/env python3
"""Regenerate the golden proof fixtures (tests/fixtures/*.hex).

The recipes live in tests/test_proof_golden.py (RECIPES + _prove_bytes)
and are IMPORTED here — generator and replaying tests share one source,
so they cannot drift. Regeneration is only legitimate when the proof
system's output intentionally changes (it should never change silently —
that is the point of the fixtures).
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
# pure-host generation: never touch a tunneled device
for _k in list(os.environ):
    if _k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
        os.environ.pop(_k)
os.environ["JAX_PLATFORMS"] = "cpu"

FIXDIR = os.path.join(REPO, "tests", "fixtures")


def main():
    from test_proof_golden import RECIPES, _prove_bytes

    os.makedirs(FIXDIR, exist_ok=True)
    for name, build in RECIPES.items():
        ckt = build()
        blob, _ = _prove_bytes(ckt)
        path = os.path.join(FIXDIR, name + ".hex")
        with open(path, "w") as f:
            f.write(blob.hex() + "\n")
        print(f"wrote {path} ({len(blob)} bytes, "
              f"n=2^{ckt.n.bit_length() - 1})")


if __name__ == "__main__":
    main()
