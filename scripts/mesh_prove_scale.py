#!/usr/bin/env python3
"""Prove the reference's v1 workload on the MESH backend.

The virtual-mesh analog of the reference's `test2` at its v1 scale
(height-32 Merkle membership, 1 proof, 2^13 domain): preprocess and the
full 5-round prove run through `MeshBackend` — sharded handles, 4-step
all_to_all NTTs, range-sharded signed mesh MSM — and the proof is
asserted BIT-IDENTICAL to the host-oracle proof before verifying.
Until round 4 the mesh prove had only run at test size (2^8).

Usage:
  python scripts/mesh_prove_scale.py [--height 32] [--proofs 1]
      [--devices 8] [--skip-oracle] [--out FILE]
"""

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from _mesh_env import force_cpu_mesh

force_cpu_mesh()

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--height", type=int, default=32)
    ap.add_argument("--proofs", type=int, default=1)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--skip-oracle", action="store_true",
                    help="skip the pure-Python oracle prove + bit-compare"
                         " (it costs ~80 s at 2^13)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from distributed_plonk_tpu import kzg
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.verifier import verify
    from distributed_plonk_tpu.workload import generate_circuit
    from distributed_plonk_tpu.parallel.mesh import make_mesh
    from distributed_plonk_tpu.parallel.mesh_backend import MeshBackend
    from distributed_plonk_tpu.trace import Tracer

    res = {"height": args.height, "num_proofs": args.proofs,
           "devices": args.devices}
    ckt, _ = generate_circuit(rng=random.Random(11), height=args.height,
                              num_proofs=args.proofs)
    res["n"] = ckt.n
    res["log2_n"] = ckt.n.bit_length() - 1
    print(f"[mesh_prove] circuit n = 2^{res['log2_n']}", file=sys.stderr)

    t0 = time.perf_counter()
    srs = kzg.universal_setup(ckt.n + 3, rng=random.Random(12))
    res["setup_host_s"] = round(time.perf_counter() - t0, 2)

    mesh = make_mesh(args.devices, platform="cpu")
    be = MeshBackend(mesh)
    t0 = time.perf_counter()
    pk, vk = kzg.preprocess(srs, ckt, backend=be)
    res["preprocess_mesh_s"] = round(time.perf_counter() - t0, 2)
    print(f"[mesh_prove] mesh preprocess {res['preprocess_mesh_s']}s",
          file=sys.stderr)

    # residency check: snapshot live per-device bytes at quotient entry
    # (round 3's resident peak) and compare against the analytical plan —
    # memory_plan validated by EXECUTION, not arithmetic (VERDICT r4 #6)
    import jax
    from distributed_plonk_tpu.parallel import memory_plan
    from distributed_plonk_tpu.poly import Domain

    def device_live_bytes():
        per = {}
        for arr in jax.live_arrays():
            try:
                for sh in arr.addressable_shards:
                    did = sh.device.id
                    per[did] = per.get(did, 0) + sh.data.nbytes
            except Exception:
                pass
        return per

    snap = {}
    orig_quotient = be.quotient

    def spy_quotient(*a, **k):
        snap["per_device"] = device_live_bytes()
        return orig_quotient(*a, **k)

    be.quotient = spy_quotient

    tr = Tracer()
    t0 = time.perf_counter()
    proof = prove(random.Random(13), ckt, pk, be, tracer=tr)
    res["prove_mesh_s"] = round(time.perf_counter() - t0, 2)
    res["rounds"] = {k: round(v, 2) for k, v in tr.totals(1).items()}
    print(f"[mesh_prove] mesh prove {res['prove_mesh_s']}s "
          f"rounds={res['rounds']}", file=sys.stderr)

    if snap:
        from distributed_plonk_tpu.circuit import NUM_WIRE_TYPES
        m = Domain((NUM_WIRE_TYPES + 1) * (ckt.n + 1) + 1).size  # prover.py:53
        plan = memory_plan.round3_mesh_plan(ckt.n, m, args.devices)
        actual = snap["per_device"]
        worst = max(actual.values()) if actual else 0
        res["residency"] = {
            "plan_resident_per_device": plan["resident"],
            "plan_parts": {k: plan[k] for k in
                           ("planes", "stacks", "tables", "base")},
            "actual_per_device": {str(k): v for k, v in sorted(actual.items())},
            "actual_max_per_device": worst,
            # the snapshot runs BEFORE the quotient kernel stacks its
            # copies, so the plan's planes+tables+base should bound it;
            # the full 'resident' (incl. stacks) bounds the kernel peak
            "actual_within_plan": bool(
                worst <= plan["resident"] * 1.5 + (1 << 26)),
        }
        print(f"[mesh_prove] residency: actual max/device "
              f"{worst / 2**20:.1f} MiB vs plan "
              f"{plan['resident'] / 2**20:.1f} MiB "
              f"(within={res['residency']['actual_within_plan']})",
              file=sys.stderr)

    ok = verify(vk, ckt.public_input(), proof, rng=random.Random(14))
    res["verified"] = bool(ok)
    assert ok, "mesh proof did not verify"

    if not args.skip_oracle:
        from distributed_plonk_tpu.backend.python_backend import PythonBackend
        t0 = time.perf_counter()
        proof_host = prove(random.Random(13), ckt, pk, PythonBackend())
        res["prove_oracle_s"] = round(time.perf_counter() - t0, 2)
        for f in ("wires_poly_comms", "prod_perm_poly_comm",
                  "split_quot_poly_comms", "opening_proof",
                  "shifted_opening_proof", "wires_evals",
                  "wire_sigma_evals", "perm_next_eval"):
            assert getattr(proof, f) == getattr(proof_host, f), (
                f"mesh proof diverges from the host oracle at {f}")
        res["oracle_bit_identical"] = True

    line = json.dumps(res)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)

    # fail LOUDLY on a residency-plan violation — but only after the
    # measurements (multi-minute on the virtual mesh) are safely written
    if "residency" in res and not res["residency"]["actual_within_plan"]:
        raise SystemExit(
            f"per-device residency {res['residency']['actual_max_per_device']}"
            f" exceeds the round-3 plan "
            f"{res['residency']['plan_resident_per_device']} (x1.5 + 64 MiB "
            f"slack) — update memory_plan.round3_mesh_plan to the real "
            f"working set")


if __name__ == "__main__":
    main()
