#!/usr/bin/env python3
"""A/B the Pallas multiplier on the chip: lane-tile width x kernel variant.

The knobs are import-time constants (DPT_PALLAS_LANE_TILE, plus
DPT_MUL_LAZY / DPT_MUL_MXU selecting the strict, lazy or mxu kernel),
so each configuration runs in a fresh subprocess; each result row is
{"tile", "variant", ...}. Measures wide Fr/Fq mont_mul ns/lane (the rate
every NTT stage and MSM add inherits) and checks 1024 lanes against the
host oracle in every configuration.

Usage: python scripts/mul_tile_ab.py [--out FILE] [--variants lazy,mxu]
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

INNER = r"""
import json, os, random, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
import jax
import jax.numpy as jnp
from distributed_plonk_tpu.constants import R_MOD, Q_MOD, FR_MONT_R, FQ_MONT_R
from distributed_plonk_tpu.backend import field_jax as FJ
from distributed_plonk_tpu.backend.limbs import ints_to_limbs, limbs_to_ints

def sync(x):
    np.asarray(x[:, :1])

from distributed_plonk_tpu.backend import field_pallas as FP
out = {"tile": int(os.environ["DPT_PALLAS_LANE_TILE"]),
       "variant": FP._VARIANT}
rng_np = np.random.default_rng(7)
rng = random.Random(9)
for spec, lanes, mod, mont_r, name in (
        (FJ.FR, 1 << 21, R_MOD, FR_MONT_R, "fr"),
        (FJ.FQ, 1 << 20, Q_MOD, FQ_MONT_R, "fq")):
    L = spec.n_limbs
    a = jnp.asarray(rng_np.integers(0, 1 << 16, (L, lanes), dtype=np.uint32))
    mul = jax.jit(lambda u, v, s=spec: FJ.mont_mul(s, u, v))
    sync(mul(a, a))
    reps = 4
    t0 = time.perf_counter()
    for _ in range(reps):
        o = mul(a, a)
    sync(o)
    dt = (time.perf_counter() - t0) / reps
    out[f"{name}_ns_per_mul"] = round(dt / lanes * 1e9, 2)
    # oracle check on 1024 lanes through the same dispatch
    xs = [rng.randrange(mod) for _ in range(1024)]
    ys = [rng.randrange(mod) for _ in range(1024)]
    got = limbs_to_ints(np.asarray(
        mul(jnp.asarray(ints_to_limbs(xs, L)),
            jnp.asarray(ints_to_limbs(ys, L)))))
    r_inv = pow(mont_r, mod - 2, mod)
    assert got == [x * y %% mod * r_inv %% mod for x, y in zip(xs, ys)], \
        "ORACLE MISMATCH"
    out[f"{name}_oracle_ok"] = True
print("RESULT " + json.dumps(out))
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--tiles", default="512,1024,2048")
    ap.add_argument("--variants", default="strict,lazy,mxu")
    ap.add_argument("--timeout", type=int, default=1200)
    args = ap.parse_args()

    results = []
    for variant in args.variants.split(","):
        for tile in args.tiles.split(","):
            env = dict(os.environ,
                       DPT_PALLAS_LANE_TILE=tile,
                       DPT_MUL_LAZY="1" if variant == "lazy" else "0",
                       DPT_MUL_MXU="1" if variant == "mxu" else "0",
                       DPT_FIELD_MUL="pallas")
            print(f"[ab] tile={tile} variant={variant} ...", file=sys.stderr)
            try:
                proc = subprocess.run(
                    [sys.executable, "-c", INNER % {"repo": REPO}],
                    env=env, capture_output=True, text=True,
                    timeout=args.timeout)
            except subprocess.TimeoutExpired:
                results.append({"tile": int(tile), "variant": variant,
                                "error": "timeout"})
                continue
            line = next((l for l in proc.stdout.splitlines()
                         if l.startswith("RESULT ")), None)
            if line:
                results.append(json.loads(line[len("RESULT "):]))
                print(f"[ab]   -> {line[len('RESULT '):]}", file=sys.stderr)
            else:
                results.append({"tile": int(tile), "variant": variant,
                                "error": (proc.stderr or "")[-500:]})
                print(f"[ab]   FAILED rc={proc.returncode}", file=sys.stderr)
    blob = json.dumps({"configs": results})
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")
    print(blob)


if __name__ == "__main__":
    main()
