#!/usr/bin/env python3
"""A/B microbenchmark: bucket-plane update strategies for the MSM scan.

Round-4 finding (add_bench.py): the complete add itself runs at ~2M
lane-adds/s on chip, but the full bucket scan only ~0.5M — the
take_along_axis gather + put_along_axis scatter on the (24, G, M, B)
planes costs ~5x the add. Candidates:

  put      — current: take_along_axis / put_along_axis on axis 3
  onehot   — gather = masked reduction over the bucket axis; update =
             broadcast compare + where over the whole plane (pure
             streaming HBM traffic, no scatter lowering at all)

Usage: python scripts/scatter_ab.py [--g 256] [--m 32] [--steps 64]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--g", type=int, default=256)
    ap.add_argument("--m", type=int, default=32)
    ap.add_argument("--buckets", type=int, default=128)
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from distributed_plonk_tpu.constants import FQ_LIMBS
    from distributed_plonk_tpu.backend import curve_jax as CJ

    G, M, B, S = args.g, args.m, args.buckets, args.steps
    rng = np.random.default_rng(11)

    def rand_fq(shape):
        v = rng.integers(0, 1 << 16, size=(FQ_LIMBS,) + shape,
                         dtype=np.uint32)
        v[-1] &= 0x1FFF
        return jnp.asarray(v)

    planes = tuple(rand_fq((G, M, B)) for _ in range(3))
    sx = jnp.moveaxis(rand_fq((S, G)), 1, 0)       # (S, 24, G)
    sy = jnp.moveaxis(rand_fq((S, G)), 1, 0)
    dg = jnp.asarray(rng.integers(0, B, size=(S, G, M), dtype=np.uint32))
    skip = jnp.zeros((S, G, M), bool)

    def step_put(carry, x):
        bx, by, bz = carry
        sx, sy, sk, d = x
        d4 = d[None, :, :, None]
        d4b = jnp.broadcast_to(d4, (FQ_LIMBS,) + d4.shape[1:])
        cur = tuple(jnp.take_along_axis(b, d4b, axis=3)[..., 0]
                    for b in (bx, by, bz))
        sxb = jnp.broadcast_to(sx[:, :, None], cur[0].shape)
        syb = jnp.broadcast_to(sy[:, :, None], cur[0].shape)
        nv = CJ.proj_add_mixed(cur, (sxb, syb), sk)
        new = tuple(jnp.put_along_axis(b, d4b, v[..., None], axis=3,
                                       inplace=False)
                    for b, v in zip((bx, by, bz), nv))
        return new, None

    bidx = lax.broadcasted_iota(jnp.uint32, (1, G, M, B), 3)

    def _onehot_step(carry, x, add):
        """Shared gather/update body: `add` produces the new values, so
        the add and no-add variants stay identical by construction and
        their delta isolates the add cost."""
        bx, by, bz = carry
        sx, sy, sk, d = x
        hit = d[None, :, :, None] == bidx           # (1, G, M, B)
        cur = tuple(
            jnp.sum(jnp.where(hit, b, 0), axis=3, dtype=jnp.uint32)
            for b in (bx, by, bz))
        nv = add(cur, sx, sy, sk)
        new = tuple(jnp.where(hit, v[..., None], b)
                    for b, v in zip((bx, by, bz), nv))
        return new, None

    def step_onehot(carry, x):
        def add(cur, sx, sy, sk):
            sxb = jnp.broadcast_to(sx[:, :, None], cur[0].shape)
            syb = jnp.broadcast_to(sy[:, :, None], cur[0].shape)
            return CJ.proj_add_mixed(cur, (sxb, syb), sk)
        return _onehot_step(carry, x, add)

    def step_onehot_noadd(carry, x):
        """Gather + update only — isolates plane traffic from the add."""
        def add(cur, sx, sy, sk):
            return tuple(c + sx[:, :, None] for c in cur)  # stand-in
        return _onehot_step(carry, x, add)

    results = {"g": G, "m": M, "buckets": B, "steps": S,
               "backend": jax.default_backend()}
    for name, step in (("put", step_put), ("onehot", step_onehot),
                       ("onehot_noadd", step_onehot_noadd)):
        @jax.jit
        def scan(planes, xs, step=step):
            return lax.scan(step, planes, xs)[0]

        xs = (sx, sy, skip, dg)
        t0 = time.perf_counter()
        out = scan(planes, xs)
        np.asarray(out[0][:1, :1, :1, :1])
        results[f"{name}_compile_s"] = round(time.perf_counter() - t0, 1)
        t0 = time.perf_counter()
        for _ in range(args.reps):
            out = scan(planes, xs)
        np.asarray(out[0][:1, :1, :1, :1])
        dt = (time.perf_counter() - t0) / args.reps
        results[f"{name}_s"] = round(dt, 4)
        results[f"{name}_ms_per_step"] = round(dt / S * 1e3, 2)
        results[f"{name}_adds_per_s"] = int(G * M * S / dt)
        print(f"[scatter_ab] {name}: {dt/S*1e3:.1f} ms/step "
              f"({results[f'{name}_adds_per_s']/1e3:.0f}k adds/s)",
              file=sys.stderr)

    line = json.dumps(results)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)


if __name__ == "__main__":
    main()
