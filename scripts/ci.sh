#!/usr/bin/env bash
# Tier-1 verify: THE gate every PR must keep green (ROADMAP.md).
# This wrapper is the single CI entry point — it runs the ROADMAP's
# tier-1 command verbatim, so local runs, CI, and the driver all measure
# the identical surface.
#
# Usage:
#   scripts/ci.sh          full tier-1 (the ROADMAP command, wall-clock budgeted)
#   scripts/ci.sh fast     kernel-parity subset: AST hazard lints (sub-second)
#                          then NTT + MSM oracle/radix tests — the quick
#                          pre-commit check for kernel work (~6 min of
#                          XLA-CPU compiles, no prover/mesh/service)
#   scripts/ci.sh analyze  static verifier, strict: jaxpr interval bounds +
#                          exact value contracts over the FULL kernel
#                          registry + carry contracts + repo lints (python
#                          -m distributed_plonk_tpu.analysis, ~2-3 min of
#                          tracing + exact host evaluation, nothing runs on
#                          a device; `analyze --changed-only` skips
#                          unchanged kernel families)
#   scripts/ci.sh autotune kernel-autotuner smoke tier (ISSUE 14): plan
#                          store round-trip, fingerprint-mismatch rebuild,
#                          parity gate vs a lying candidate, env-override
#                          precedence, DPT_AUTOTUNE=off parity, service +
#                          fleet-worker plan pickup — tiny shapes,
#                          interpret-safe budget (XLA:CPU only)
#   scripts/ci.sh benchcheck  perf-regression smoke (ISSUE 15): gate the
#                          COMMITTED bench trajectory (BENCH_r*.json +
#                          bench_artifacts/trajectory.jsonl) through
#                          scripts/bench_compare.py — basis-aware,
#                          tolerance-table scoped, runs NO measurement
#                          (non-flaky by construction); a watched key
#                          regressing beyond tolerance exits 1 loudly
#   scripts/ci.sh chaos    fault-domain + observability suite, PLUS the
#                          result-integrity suite (ISSUE 13): injected
#                          silent data corruption (wrong MSM partial /
#                          FFT panel / round-4 eval) detected at the
#                          phase boundary, attributed to the injected
#                          worker, quarantined (LEAVE -> supervisor
#                          respawn -> challenge-gated rejoin), proofs
#                          byte-identical, and DPT_SELF_VERIFY blocking
#                          corrupt proofs from journal/clients: dead-worker
#                          sweep over every protocol phase (byte-identical
#                          proofs), breaker open/re-admission, cross-host
#                          store-fetch resume, injection layer (~1-2 min,
#                          jax-free: python backend worker subprocesses over
#                          real TCP), PLUS the self-healing-fleet suite
#                          (dynamic membership: join-mid-life FFT replan-up
#                          byte-identity, stale-epoch rejection, supervisor
#                          respawn + flap cap, warm rejoin w/ compile-cache
#                          sync, bucket-peer auto-discovery, and the
#                          kill->respawn->heal-to-full-width canary),
#                          the durable-service-plane suite
#                          (service killed at every journal transition ->
#                          restart recovers byte-identically, dedup across
#                          restart, torn journal, TTL shed, SIGTERM drain),
#                          PLUS the distributed-tracing suite: serve.py
#                          subprocess obs endpoints, 3-process fleet prove
#                          -> one merged trace artifact, wire back-compat,
#                          PLUS the placement suite: batched-vs-sequential
#                          byte-identity, submesh lease/release, batch
#                          member kill-resume, mesh-retry re-placement,
#                          DPT_BATCH_PROVE=0 parity, PLUS the closed-loop
#                          autoscaling suite (ISSUE 16): control-law
#                          hysteresis/cooldown/bounds units, SLO-class
#                          queue ordering + per-class TTLs, dry-run
#                          zero-actuator-calls pin, DPT_AUTOSCALE=0
#                          parity, graceful retire (drain-then-LEAVE),
#                          and the live supervised-fleet scale-up/
#                          retire canary (every proof byte-verified),
#                          PLUS the circuit-zoo + aggregation suite
#                          (ISSUE 17): per-kind satisfiability +
#                          structure-from-params + prove/verify byte
#                          determinism, batch-KZG aggregate accepts iff
#                          every member verifies (single 2-pair pairing
#                          check pinned by counter), corrupted-member +
#                          tampered-artifact rejection, and the service
#                          AGGREGATE round trip surviving restart
#                          (journal AGG recovery)
cd "$(dirname "$0")/.."
if [ "$1" = "analyze" ]; then
  # extra args pass through: `scripts/ci.sh analyze --changed-only` skips
  # registry families whose kernel modules are unchanged since the last
  # fully clean run (lints always run)
  shift
  exec env JAX_PLATFORMS=cpu python -m distributed_plonk_tpu.analysis --strict -q "$@"
fi
if [ "$1" = "benchcheck" ]; then
  exec env JAX_PLATFORMS=cpu python scripts/bench_compare.py
fi
if [ "$1" = "chaos" ]; then
  # the fleet-observability suite rides with the fault-domain tiers (it
  # is jax-free and exercises the same real-TCP worker topology), and
  # the benchcheck smoke runs first — it is instant and read-only
  bash scripts/ci.sh benchcheck || exit 1
  exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_runtime_faults.py tests/test_membership.py \
    tests/test_integrity.py \
    tests/test_service_journal.py \
    tests/test_trace.py tests/test_obs.py tests/test_fleet_obs.py \
    tests/test_placement.py tests/test_pipeline.py \
    tests/test_autoscale.py \
    tests/test_circuits.py tests/test_aggregate.py \
    -q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
fi
if [ "$1" = "autotune" ]; then
  exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_autotune.py \
    -q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
fi
if [ "$1" = "fast" ]; then
  # the AST lints cost <1 s and catch the jit-cache/promotion/lock bug
  # classes before any compile starts; bounds stay in `analyze` (tracing
  # the full registry is ~90 s)
  env JAX_PLATFORMS=cpu python -m distributed_plonk_tpu.analysis \
    --only lint --strict -q || exit 1
  # the chaos subset rides along: it is jax-free (no compiles) and pins
  # the fault-domain acceptance surface before kernel-parity compiles start
  bash scripts/ci.sh chaos || exit 1
  # the autotune smoke tier rides along too: tiny shapes on XLA:CPU, and
  # it pins the "off/plan-less = byte-identical dispatch" invariant the
  # kernel-parity tests below now implicitly rely on
  bash scripts/ci.sh autotune || exit 1
  exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_ntt_jax.py tests/test_ntt_pallas.py \
    tests/test_curve_msm_jax.py \
    tests/test_msm_update_paths.py tests/test_msm_pallas.py \
    tests/test_poly.py \
    -q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
fi
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
