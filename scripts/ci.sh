#!/usr/bin/env bash
# Tier-1 verify: THE gate every PR must keep green (ROADMAP.md).
# This wrapper is the single CI entry point — it runs the ROADMAP's
# tier-1 command verbatim, so local runs, CI, and the driver all measure
# the identical surface. Usage: scripts/ci.sh
cd "$(dirname "$0")/.."
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
