#!/usr/bin/env python3
"""A/B microbenchmark: current per-window vmapped bucket scan vs a
combined-window single-scatter variant. Run on the chip to find where the
~48 ms/step goes (one-off diagnostic; findings land in BASELINE.md)."""

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from distributed_plonk_tpu.constants import FQ_LIMBS
from distributed_plonk_tpu.backend import curve_jax as CJ
from distributed_plonk_tpu.backend import field_jax as FJ
from distributed_plonk_tpu.backend import msm_jax as M


def sync(x):
    np.asarray(x[0][:1, :1] if isinstance(x, tuple) else x[:1, :1])


def bench(fn, args, reps=2, tag=""):
    t0 = time.perf_counter()
    out = fn(*args)
    sync(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    sync(out)
    dt = (time.perf_counter() - t0) / reps
    return {"tag": tag, "compile_s": round(compile_s, 1), "s": round(dt, 3)}


def scan_multi(ax, ay, ainf, packed, group):
    """Combined-window signed bucket scan: ONE gather + ONE scatter per
    step covering all M = B*W digit lanes; points broadcast across M."""
    M, n = packed.shape
    steps = n // group
    G = group

    def to_scan(a):  # (24, n) -> (steps, 24, G)
        return a.reshape(FQ_LIMBS, G, steps).transpose(2, 0, 1)

    def to_scan_m(a):  # (M, n) -> (steps, G, M)
        return a.reshape(M, G, steps).transpose(2, 1, 0)

    off = packed.astype(jnp.int32) - 128
    neg = off < 0
    mag = jnp.abs(off)
    skip = (mag == 0) | ainf[None, :]
    idx = jnp.maximum(mag, 1).astype(jnp.uint32) - 1  # 0..127

    xs = (to_scan(ax), to_scan(ay), to_scan_m(skip), to_scan_m(neg),
          to_scan_m(idx))

    vz = ax.ravel()[0] & 0
    bx, by, bz = (b + vz for b in CJ.proj_inf((G, M, 128)))

    def step(carry, x):
        bx, by, bz = carry            # (24, G, M, 128)
        sx, sy, sk, ng, dg = x        # sx (24, G); sk/ng/dg (G, M)
        dg4 = dg[None, :, :, None]    # (1, G, M, 1)
        cur = tuple(jnp.take_along_axis(b, dg4, axis=3)[..., 0]
                    for b in (bx, by, bz))  # (24, G, M)
        nsy = FJ.neg(CJ.FQ, sy)
        qy = jnp.where(ng[None], nsy[:, :, None], sy[:, :, None])
        sxb = jnp.broadcast_to(sx[:, :, None], qy.shape)
        nx, ny, nz = CJ.proj_add_mixed(cur, (sxb, qy), sk)
        dg4b = jnp.broadcast_to(dg4, (FQ_LIMBS,) + dg4.shape[1:])
        new = (jnp.put_along_axis(b, dg4b, v[..., None], axis=3,
                                  inplace=False)
               for b, v in zip((bx, by, bz), (nx, ny, nz)))
        return tuple(new), None

    (bx, by, bz), _ = lax.scan(step, (bx, by, bz), xs)
    return bx, by, bz


def main():
    rng = np.random.default_rng(0)
    n = 1 << 17
    B, W = 1, 32
    group = 256

    ax = jnp.asarray(rng.integers(0, 1 << 16, (FQ_LIMBS, n), dtype=np.uint32))
    ay = jnp.asarray(rng.integers(0, 1 << 16, (FQ_LIMBS, n), dtype=np.uint32))
    ainf = jnp.zeros((n,), bool)
    packed = jnp.asarray(rng.integers(0, 256, (B * W, n), dtype=np.uint32))

    out = {"n_log2": 17, "B": B, "W": W, "group": group,
           "platform": jax.devices()[0].platform}

    # baseline: current vmapped per-window pipeline
    cur = jax.jit(partial(M.bucket_planes_batch_signed, group=group))
    out["current"] = bench(cur, (ax, ay, ainf,
                                 packed.reshape(B, W, n)), tag="vmap_per_window")

    # combined-window single-scatter scan (planes only, no fold — fold is
    # cheap; comparable because current includes fold over G which we add)
    def multi(ax, ay, ainf, packed):
        bx, by, bz = scan_multi(ax, ay, ainf, packed, group)
        planes = tuple(x.transpose(1, 0, 2, 3) for x in (bx, by, bz))
        return M.fold_planes(*planes)

    mj = jax.jit(multi)
    out["multi"] = bench(mj, (ax, ay, ainf, packed), tag="combined_window")

    # add-only ceiling: same lane count, no gather/scatter at all
    def add_only(ax, ay, ainf):
        sx = ax[:, :group * W].reshape(FQ_LIMBS, group, W)
        sy = ay[:, :group * W].reshape(FQ_LIMBS, group, W)
        sk = ainf[:group * W].reshape(group, W)
        vz = ax.ravel()[0] & 0
        acc = tuple(b + vz for b in CJ.proj_inf((group, W)))

        def step(carry, _):
            return CJ.proj_add_mixed(carry, (sx, sy), sk), None

        steps = n // group
        acc, _ = lax.scan(step, acc, None, length=steps)
        return acc

    aj = jax.jit(add_only)
    out["add_only"] = bench(aj, (ax, ay, ainf), tag="add_only_ceiling")

    steps = n // group
    for k in ("current", "multi", "add_only"):
        out[k]["ms_per_step"] = round(out[k]["s"] / steps * 1e3, 3)
        out[k]["adds_per_s"] = round(B * W * n / out[k]["s"])
    print(json.dumps(out))


if __name__ == "__main__":
    main()
