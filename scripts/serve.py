#!/usr/bin/env python3
"""Run the proof service daemon.

    JAX_PLATFORMS=cpu python scripts/serve.py --port 9555 --workers 2 \
        [--queue-depth 64] [--max-batch 8] [--retries 2] [--timeout 300] \
        [--chaos] [--verify]

--chaos enables the KILL_WORKER fault-injection tag (scripts/loadgen.py
--kill uses it); never enable it on a service you care about. --verify
makes workers verify each proof server-side before marking it done.
Prints one JSON line with the bound address once listening; SHUTDOWN tag
or Ctrl-C stops it.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_peers(arg):
    """'host:port,host:port' -> [(host, port)], failing fast with a
    message that names the flag (a forgotten port otherwise surfaces as
    a bare int() traceback)."""
    peers = []
    for entry in arg.split(","):
        host, sep, port = entry.strip().rpartition(":")
        if not sep or not host or not port.isdigit():
            raise SystemExit(
                f"--store-peers: {entry.strip()!r} is not host:port")
        peers.append((host, int(port)))
    return peers


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9555)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--queue-depth", type=int, default=64)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--retries", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=None,
                    help="per-job wall-clock budget, seconds")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--store-dir", default=None,
                    help="artifact store root: persists SRS/keys across "
                         "restarts and parks the JAX compile cache; warm "
                         "it ahead of time with scripts/warmup.py")
    ap.add_argument("--store-budget", type=int, default=None,
                    help="store byte budget (LRU eviction past it)")
    ap.add_argument("--bucket-cap", type=int, default=64,
                    help="max shape buckets resident in memory (LRU)")
    ap.add_argument("--store-peers", default=None,
                    help="comma-separated host:port peers speaking "
                         "STORE_FETCH: on a bucket miss, pull the key "
                         "blob from a warm peer (digest-verified) before "
                         "paying for a full build — a scaled-out replica "
                         "serves warm after one network copy")
    ap.add_argument("--chaos", action="store_true")
    ap.add_argument("--verify", action="store_true")
    ap.add_argument("--allow-remote-shutdown", action="store_true",
                    help="let any client's SHUTDOWN frame stop the daemon")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.store_dir is not None:
        # park the persistent compile cache under the store root BEFORE
        # any jax backend import, so compiled prover stages warm-start
        # alongside the keys they serve
        from distributed_plonk_tpu.store import set_jax_cache_env
        set_jax_cache_env(args.store_dir)
    from distributed_plonk_tpu.service import ProofService

    svc = ProofService(
        host=args.host, port=args.port, prover_workers=args.workers,
        queue_depth=args.queue_depth, max_batch=args.max_batch,
        max_retries=args.retries, job_timeout_s=args.timeout,
        ckpt_dir=args.ckpt_dir, chaos=args.chaos,
        verify_on_complete=args.verify,
        allow_remote_shutdown=args.allow_remote_shutdown,
        store_dir=args.store_dir, store_byte_budget=args.store_budget,
        bucket_cap=args.bucket_cap,
        store_peers=parse_peers(args.store_peers)
        if args.store_peers else None).start()
    print(json.dumps({"listening": f"{svc.host}:{svc.port}",
                      "workers": args.workers, "chaos": args.chaos,
                      "store": args.store_dir}),
          flush=True)
    try:
        svc.serve_forever()
    except KeyboardInterrupt:
        svc.shutdown()


if __name__ == "__main__":
    main()
