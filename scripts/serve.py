#!/usr/bin/env python3
"""Run the proof service daemon.

    JAX_PLATFORMS=cpu python scripts/serve.py --port 9555 --workers 2 \
        [--queue-depth 64] [--max-batch 8] [--retries 2] [--timeout 300] \
        [--journal-dir /var/dpt/journal] [--chaos] [--verify]

--journal-dir enables the crash-safe job journal: every submitted job
survives a crash or deploy restart (in-flight ones resume from their
checkpoints, finished ones serve from proof artifacts). SIGTERM/SIGINT
triggers a graceful drain — admission stops, in-flight jobs get up to
DPT_DRAIN_TIMEOUT_S (default 30) to finish, stragglers checkpoint and
park, the journal flushes, and the process exits 0; a later start on the
same --journal-dir picks every deferred job back up.

--chaos enables the KILL_WORKER fault-injection tag (scripts/loadgen.py
--kill uses it) and arms DPT_FAULTS-spec'd rules — including
journal-plane service kills (`DPT_FAULTS="kill:at=journal:tag=ROUND2"`
makes THIS PROCESS os._exit at exactly that journal occurrence; the
restart-recovery tests and loadgen --kill-service drive it). Never
enable it on a service you care about. --verify makes workers verify
each proof server-side before marking it done.
Prints one JSON line with the bound address once listening; SHUTDOWN tag
stops it.
"""

import argparse
import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DRAIN_TIMEOUT_S = float(os.environ.get("DPT_DRAIN_TIMEOUT_S", "30"))


def parse_peers(arg):
    """'host:port,host:port' -> [(host, port)], failing fast with a
    message that names the flag (a forgotten port otherwise surfaces as
    a bare int() traceback)."""
    peers = []
    for entry in arg.split(","):
        host, sep, port = entry.strip().rpartition(":")
        if not sep or not host or not port.isdigit():
            raise SystemExit(
                f"--store-peers: {entry.strip()!r} is not host:port")
        peers.append((host, int(port)))
    return peers


def validate_journal_dir(arg):
    """Fail fast, at flag-parse time, with a message that names the flag:
    a journal dir that can't actually take fsync'd appends must stop the
    daemon BEFORE it accepts jobs it cannot make durable (discovering it
    on the first SUBMIT would lose that job's durability silently)."""
    path = os.path.abspath(os.path.expanduser(arg))
    if os.path.exists(path) and not os.path.isdir(path):
        raise SystemExit(f"--journal-dir: {path!r} exists and is not a "
                         "directory")
    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, ".probe.%d" % os.getpid())
        with open(probe, "wb") as f:
            f.write(b"x")
            os.fsync(f.fileno())
        os.remove(probe)
    except OSError as e:
        raise SystemExit(f"--journal-dir: {path!r} is not writable "
                         f"({e.strerror or e})")
    return path


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9555)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--queue-depth", type=int, default=64)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--retries", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=None,
                    help="per-job wall-clock budget, seconds")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--journal-dir", default=None,
                    help="crash-safe job journal: jobs survive service "
                         "restarts (resume from checkpoints, finished "
                         "proofs served from artifacts); also the "
                         "SIGTERM graceful-drain surface")
    ap.add_argument("--store-dir", default=None,
                    help="artifact store root: persists SRS/keys across "
                         "restarts and parks the JAX compile cache; warm "
                         "it ahead of time with scripts/warmup.py")
    ap.add_argument("--store-budget", type=int, default=None,
                    help="store byte budget (LRU eviction past it)")
    ap.add_argument("--bucket-cap", type=int, default=64,
                    help="max shape buckets resident in memory (LRU)")
    ap.add_argument("--store-peers", default=None,
                    help="comma-separated host:port peers speaking "
                         "STORE_FETCH: on a bucket miss, pull the key "
                         "blob from a warm peer (digest-verified) before "
                         "paying for a full build — a scaled-out replica "
                         "serves warm after one network copy")
    ap.add_argument("--log-dir", default=None,
                    help="structured-log JSONL sink (obs/log.py): every "
                         "shed/retry/quarantine verdict appends one "
                         "trace-correlated JSON line to "
                         "<dir>/serve-<pid>.jsonl; the env DPT_LOG_DIR "
                         "does the same for worker subprocesses")
    ap.add_argument("--obs-port", type=int, default=None,
                    help="observability HTTP port (0 = ephemeral): serves "
                         "/metrics (Prometheus text exposition incl. "
                         "per-round latency + MFU gauges), /healthz, and "
                         "/trace/<job_id> (the job's merged distributed "
                         "timeline as chrome://tracing JSON)")
    ap.add_argument("--chaos", action="store_true")
    ap.add_argument("--verify", action="store_true")
    ap.add_argument("--allow-remote-shutdown", action="store_true",
                    help="let any client's SHUTDOWN frame stop the daemon")
    args = ap.parse_args()

    journal_dir = None
    if args.journal_dir is not None:
        journal_dir = validate_journal_dir(args.journal_dir)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.store_dir is not None:
        # park the persistent compile cache under the store root BEFORE
        # any jax backend import, so compiled prover stages warm-start
        # alongside the keys they serve
        from distributed_plonk_tpu.store import set_jax_cache_env
        set_jax_cache_env(args.store_dir)
    from distributed_plonk_tpu.obs import log as olog
    from distributed_plonk_tpu.runtime.faults import FaultInjector
    from distributed_plonk_tpu.service import ProofService
    from distributed_plonk_tpu.service.server import ObsServer

    log_path = None
    if args.log_dir is not None:
        log_path = olog.configure(log_dir=args.log_dir, proc="serve")
        if log_path is None:
            raise SystemExit(f"--log-dir: {args.log_dir!r} is not writable")

    faults = None
    if args.chaos:
        # journal-plane kills die for real: os._exit skips every atexit/
        # finally (the whole point — a crash leaves no goodbye), so the
        # restarted process sees exactly what a power cut would leave
        faults = FaultInjector.from_env(
            kill_cb=lambda _label: os._exit(1))

    svc = ProofService(
        host=args.host, port=args.port, prover_workers=args.workers,
        queue_depth=args.queue_depth, max_batch=args.max_batch,
        max_retries=args.retries, job_timeout_s=args.timeout,
        ckpt_dir=args.ckpt_dir, chaos=args.chaos,
        verify_on_complete=args.verify,
        allow_remote_shutdown=args.allow_remote_shutdown,
        store_dir=args.store_dir, store_byte_budget=args.store_budget,
        bucket_cap=args.bucket_cap, journal_dir=journal_dir,
        faults=faults,
        store_peers=parse_peers(args.store_peers)
        if args.store_peers else None).start()

    obs = None
    if args.obs_port is not None:
        obs = ObsServer(svc, host=args.host, port=args.obs_port).start()

    # closed-loop autoscaler per DPT_AUTOSCALE (0=off/bit-parity,
    # dry=recommend-only, 1=actuating). The standalone daemon has no
    # WorkerSupervisor, so worker scaling records as not-applied; lease
    # resizes and pressure sheds still actuate in mode 1.
    autoscaler = svc.attach_autoscaler()

    drain_state = {}

    def _drain_handler(signum, _frame):
        # signal handlers run on the main thread while serve_forever
        # blocks in Event.wait — drain() releases that wait when done
        if drain_state:
            return  # second signal during a drain: already on our way out
        drain_state["signal"] = signal.Signals(signum).name
        drain_state["clean"] = svc.drain(timeout_s=DRAIN_TIMEOUT_S)

    signal.signal(signal.SIGTERM, _drain_handler)
    signal.signal(signal.SIGINT, _drain_handler)

    print(json.dumps({"listening": f"{svc.host}:{svc.port}",
                      "obs": f"{obs.host}:{obs.port}" if obs else None,
                      "workers": args.workers, "chaos": args.chaos,
                      "store": args.store_dir, "journal": journal_dir,
                      "log_file": log_path,
                      "autotune": svc.autotune,
                      "autoscale": autoscaler.mode if autoscaler else "0"}),
          flush=True)
    svc.serve_forever()
    if obs is not None:
        obs.close()
    if drain_state:
        ctr = svc.metrics.snapshot()["counters"]
        print(json.dumps({"drained": drain_state.get("signal"),
                          "clean": drain_state.get("clean"),
                          "jobs_drain_parked":
                              ctr.get("jobs_drain_parked", 0)}),
              flush=True)


if __name__ == "__main__":
    main()
