#!/usr/bin/env python3
"""Execute the v2 workload's quotient-domain-sized NTT on a device mesh.

The reference's v2 run needs a 2^21-point FFT (50 proofs -> 2^18 domain,
8n quotient domain, /root/reference/src/dispatcher2.rs:1219-1221,246).
Until round 4 that size existed here only as an analytical memory plan
(parallel/memory_plan.py); this script actually runs it: forward coset
FFT then inverse on an N-device mesh (virtual CPU mesh by default, the
same code path a v5e pod would compile), asserting the round trip is
bit-exact and the forward output matches the host oracle FFT on a
random polynomial.

Usage:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/mesh_ntt_scale.py [--log2n 21] [--devices 8] \
      [--skip-oracle] [--out FILE]
"""

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from _mesh_env import force_cpu_mesh

force_cpu_mesh()

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--log2n", type=int, default=21)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--skip-oracle", action="store_true",
                    help="round-trip + linearity only (the pure-Python"
                         " oracle FFT takes ~minutes at 2^21)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from distributed_plonk_tpu.constants import R_MOD
    from distributed_plonk_tpu.parallel.ntt_mesh import MeshNttPlan, SHARD_AXIS
    from distributed_plonk_tpu.backend import prover_jax as PJ

    n = 1 << args.log2n
    devs = jax.devices()[:args.devices]
    assert len(devs) == args.devices, (
        f"need {args.devices} devices, have {len(devs)} — set "
        "XLA_FLAGS=--xla_force_host_platform_device_count")
    mesh = Mesh(np.array(devs), (SHARD_AXIS,))
    res = {"log2n": args.log2n, "devices": args.devices,
           "platform": devs[0].platform}

    rng = random.Random(0x2221)
    coeffs = [rng.randrange(R_MOD) for _ in range(n)]
    t0 = time.perf_counter()
    h = jnp.asarray(PJ.lift(coeffs))
    res["lift_s"] = round(time.perf_counter() - t0, 2)

    plan = MeshNttPlan(mesh, n)
    fwd = plan.kernel(inverse=False, coset=True, boundary="mont")
    inv = plan.kernel(inverse=True, coset=True, boundary="mont")

    t0 = time.perf_counter()
    ev = fwd(h)
    ev.block_until_ready()
    res["fwd_cold_s"] = round(time.perf_counter() - t0, 2)
    t0 = time.perf_counter()
    back = inv(ev)
    back.block_until_ready()
    res["inv_cold_s"] = round(time.perf_counter() - t0, 2)
    assert np.array_equal(np.asarray(back), np.asarray(h)), (
        "coset fft/ifft round trip not bit-exact")
    res["roundtrip_exact"] = True

    t0 = time.perf_counter()
    ev2 = fwd(h)
    ev2.block_until_ready()
    dt = time.perf_counter() - t0
    res["fwd_warm_s"] = round(dt, 4)
    res["elements_per_s"] = round(n / dt)

    if not args.skip_oracle:
        from distributed_plonk_tpu import poly
        t0 = time.perf_counter()
        exp = poly.coset_fft(poly.Domain(n), coeffs)
        res["oracle_s"] = round(time.perf_counter() - t0, 2)
        assert PJ.lower(ev) == exp, "mesh coset FFT diverges from host oracle"
        res["oracle_match"] = True

    line = json.dumps(res)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)


if __name__ == "__main__":
    main()
