"""Normalized bench trajectory records + the regression tolerance table.

The perf history used to be shape-inconsistent: `BENCH_r*.json` wraps the
bench line under `parsed` with driver fields around it, `bench_artifacts/`
holds per-tool one-off files, and nothing downstream could diff runs
without knowing every historical format. This module is the fix (ISSUE 15
satellite): ONE schema-versioned record per run,

    {"schema": 1, "source": "bench", "run": 5, "ts": ..., "basis": "chip",
     "keys": {"proofs_per_s": 1.38, "fleet_heal_s": 2.3, ...}}

appended as one JSONL line to `bench_artifacts/trajectory.jsonl` by
bench.py and scripts/add_bench.py at the end of every run, and read back
by scripts/bench_compare.py (which also knows how to normalize the legacy
BENCH_r*.json files, so the committed history stays comparable).

Basis awareness is part of the schema: "chip" lines (the device probe
passed) are only ever compared against chip lines, "degraded" (host-CPU
fallback) against degraded — a relay outage must never read as a 10x
kernel regression.

The WATCH table is the per-key regression contract: direction + relative
tolerance for every key the gate cares about. Tolerances are deliberately
loose on wall-clock keys (host-basis timings on a loaded 1-core box swing
hard) and tight on booleans (a canary flipping false is always loud).
"""

import fnmatch
import json
import os
import time

SCHEMA = 1
TRAJECTORY = os.path.join("bench_artifacts", "trajectory.jsonl")

# keys that never carry perf information (driver bookkeeping, error text)
_SKIP_KEYS = {"metric", "unit", "degraded", "schema", "n", "cmd", "rc"}


def _flatten(obj, prefix="", out=None):
    """Nested dicts -> {"a/b": v} with only numeric/bool leaves kept."""
    if out is None:
        out = {}
    for k, v in obj.items():
        if k in _SKIP_KEYS and not prefix:
            continue
        name = f"{prefix}{k}"
        if isinstance(v, dict):
            _flatten(v, prefix=name + "/", out=out)
        elif isinstance(v, bool):
            out[name] = v
        elif isinstance(v, (int, float)) and v is not None:
            out[name] = v
    return out


def basis_of(data):
    """"chip" | "degraded" for one bench-line dict (the device probe
    verdict is the `degraded` flag bench.py stamps); tool lines
    (add_bench) carry an explicit jax backend name instead."""
    if data.get("degraded"):
        return "degraded"
    backend = data.get("backend")
    if isinstance(backend, str) and backend not in ("tpu", "axon"):
        return "degraded"
    return "chip"


def normalize(source, data, run=None, ts=None):
    """One bench-line dict (bench.py's printed JSON, add_bench's results,
    a legacy BENCH_r*.json `parsed` payload) -> the schema-1 record."""
    keys = _flatten(data)
    # the headline metric/value pair becomes a stable key so the gate
    # can watch it across runs without knowing each run's metric name
    metric, value = data.get("metric"), data.get("value")
    if metric and isinstance(value, (int, float)) \
            and not isinstance(value, bool):
        keys[f"headline/{metric}"] = value
    return {"schema": SCHEMA, "source": source, "run": run,
            "ts": round(ts if ts is not None else time.time(), 3),
            "basis": basis_of(data), "keys": keys}


def append(record, repo=None, path=None):
    """Append one record to the trajectory (one JSON line); best-effort —
    a read-only checkout must not fail the bench."""
    path = path or os.path.join(repo or os.getcwd(), TRAJECTORY)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(record, separators=(",", ":"),
                               sort_keys=True) + "\n")
        return path
    except OSError:
        return None


def load_trajectory(repo):
    """All history, oldest first: legacy BENCH_r*.json (normalized) then
    trajectory.jsonl records. Unparseable entries are skipped — the
    compare gate must never crash on a foreign line."""
    records = []
    names = sorted(n for n in os.listdir(repo)
                   if n.startswith("BENCH_") and n.endswith(".json"))
    for name in names:
        try:
            with open(os.path.join(repo, name)) as f:
                wrap = json.load(f)
            parsed = wrap.get("parsed")
            if isinstance(parsed, dict):
                records.append(normalize("bench", parsed,
                                         run=wrap.get("n"), ts=0))
        except (OSError, ValueError):
            continue
    path = os.path.join(repo, TRAJECTORY)
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("schema") == SCHEMA \
                        and isinstance(rec.get("keys"), dict):
                    records.append(rec)
    return records


# -- the per-key regression contract ------------------------------------------
# (direction, relative tolerance): "higher" keys may not DROP by more
# than tol (fraction of the previous value), "lower" keys may not GROW
# by more than tol, "true" keys must stay truthy. First match wins, so
# the specific per-key entries come before the pattern families (note
# "*_per_s" must be matched before the "*_s" family catches it).

WATCH = [
    # canary booleans: flipping false is a regression at ANY tolerance
    ("analysis_clean", ("true", 0)),
    ("service_verified", ("true", 0)),
    ("service_warm_done", ("true", 0)),
    ("service_restart_recovery_ok", ("true", 0)),
    ("fleet_chaos_proof_ok", ("true", 0)),
    ("fleet_healed_ok", ("true", 0)),
    ("sdc_detected_ok", ("true", 0)),
    ("batch_prove_byte_identical", ("true", 0)),
    ("self_verify_bytes_identical", ("true", 0)),
    ("trace_ctx_adopted", ("true", 0)),
    ("autoscale_canary_ok", ("true", 0)),
    ("aggregate_ok", ("true", 0)),
    ("pipeline_byte_identical", ("true", 0)),
    # serving throughput + kernel A/Bs (ratios are basis-stable)
    ("pipeline_speedup_vs_lockstep", ("higher", 0.4)),
    ("pipelined_proofs_per_s", ("higher", 0.5)),
    ("proofs_per_s", ("higher", 0.5)),
    ("batch_prove_speedup_vs_sequential", ("higher", 0.4)),
    ("aggregate_verify_speedup_vs_sequential", ("higher", 0.5)),
    ("autotune_speedup_vs_defaults", ("higher", 0.5)),
    ("ntt_radix4_speedup_vs_radix2", ("higher", 0.5)),
    ("*_vs_host_oracle", ("higher", 0.5)),
    ("vs_baseline", ("higher", 0.5)),
    ("*_per_s", ("higher", 0.5)),
    ("mfu_*", ("higher", 0.5)),
    ("f32_fma_tflops_measured", ("higher", 0.5)),
    # robustness canaries: heal/recovery latencies (host-noisy: loose)
    ("fleet_heal_s", ("lower", 1.5)),
    ("sdc_heal_s", ("lower", 1.5)),
    ("fleet_chaos_s", ("lower", 1.5)),
    ("self_verify_overhead_pct", ("lower", 1.0)),
    ("service_roundtrip_warm_s", ("lower", 1.5)),
    ("slo_p95_standard_s", ("lower", 1.5)),
    ("headline/prove_2p13_wall_clock", ("lower", 0.5)),
    ("headline/*_throughput", ("higher", 0.5)),
]


def watch_rule(key):
    for pat, rule in WATCH:
        if fnmatch.fnmatchcase(key, pat):
            return rule
    return None


def compare(prev, cur, scale=1.0):
    """Regressions of `cur` vs `prev` (two schema-1 records of the SAME
    basis): [{key, prev, cur, change, tol, direction}]. Keys absent from
    either side, or outside the WATCH table, are skipped — the gate only
    speaks where the contract does."""
    out = []
    pk, ck = prev.get("keys") or {}, cur.get("keys") or {}
    for key, cv in sorted(ck.items()):
        rule = watch_rule(key)
        if rule is None or key not in pk:
            continue
        direction, tol = rule
        pv = pk[key]
        tol = tol * scale
        if direction == "true":
            if bool(pv) and not bool(cv):
                out.append({"key": key, "prev": pv, "cur": cv,
                            "change": "flipped false", "tol": 0,
                            "direction": direction})
            continue
        if isinstance(pv, bool) or isinstance(cv, bool) \
                or not isinstance(pv, (int, float)) \
                or not isinstance(cv, (int, float)) or pv == 0:
            continue
        rel = (cv - pv) / abs(pv)
        if direction == "higher" and rel < -tol:
            out.append({"key": key, "prev": pv, "cur": cv,
                        "change": round(rel, 4), "tol": tol,
                        "direction": direction})
        elif direction == "lower" and rel > tol:
            out.append({"key": key, "prev": pv, "cur": cv,
                        "change": round(rel, 4), "tol": tol,
                        "direction": direction})
    return out


def latest_of_basis(records, basis, before=None, source=None):
    """Most recent record of `basis` (optionally excluding the tail
    element `before` compares against). With `source`, only records of
    that source pair — a loadgen soak line and a bench line share no
    watched keys, so letting one shadow the other's predecessor would
    make the gate vacuous."""
    pool = records if before is None else records[:before]
    for rec in reversed(pool):
        if rec.get("basis") == basis and \
                (source is None or rec.get("source") == source):
            return rec
    return None
