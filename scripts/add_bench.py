#!/usr/bin/env python3
"""Microbenchmark: bare chain of complete projective mixed adds, fused
Pallas kernel vs the XLA staged-lane path — the MSM scan-step inner op
with gather/scatter removed.

Round-4 chip verdict from this tool (BASELINE.md): the bare add chain
runs at ~2.0M lane-adds/s on BOTH paths (the staged path's muls already
ride the fused Pallas multiplier, and at these widths XLA per-op
overhead amortizes; the fused whole-formula kernel ties it exactly while
costing ~194 s of Mosaic compile per shape). Since the full bucket scan
ran at only ~0.52M, the MSM bottleneck was the take/put_along_axis
scatter lowering, NOT the add — see scripts/scatter_ab.py for the 4.4x
one-hot fix.

Usage: python scripts/add_bench.py [--lanes 8192] [--steps 32] [--out FILE]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--lanes", type=int, default=8192)
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from distributed_plonk_tpu.constants import FQ_LIMBS
    from distributed_plonk_tpu.backend import curve_jax as CJ

    rng = np.random.default_rng(7)

    def rand_fq(shape):
        # arbitrary sub-p limb patterns: the add is straight-line, so
        # timing is data-independent (correctness is oracle-tested in
        # tests/test_curve_pallas.py)
        v = rng.integers(0, 1 << 16, size=(FQ_LIMBS,) + shape, dtype=np.uint32)
        v[-1] &= 0x1FFF
        return jnp.asarray(v)

    L = args.lanes
    acc = (rand_fq((L,)), rand_fq((L,)), rand_fq((L,)))
    qx = jnp.moveaxis(rand_fq((args.steps, L)), 1, 0)  # (steps, 24, L)
    qy = jnp.moveaxis(rand_fq((args.steps, L)), 1, 0)
    q_inf = jnp.zeros((args.steps, L), bool)

    def chain(acc, qx, qy, q_inf):
        def step(a, x):
            return CJ.proj_add_mixed(a, (x[0], x[1]), x[2]), None
        out, _ = lax.scan(step, acc, (qx, qy, q_inf))
        return out

    results = {"lanes": L, "steps": args.steps,
               "backend": jax.default_backend()}
    for mode, name in ((None, "fused"), ("xla", "xla")):
        CJ._ADD_MODE = mode or "auto"
        fn = jax.jit(chain)
        t0 = time.perf_counter()
        out = fn(acc, qx, qy, q_inf)
        np.asarray(out[0][:1, :1])
        results[f"{name}_compile_s"] = round(time.perf_counter() - t0, 1)
        t0 = time.perf_counter()
        for _ in range(args.reps):
            out = fn(acc, qx, qy, q_inf)
        np.asarray(out[0][:1, :1])
        dt = (time.perf_counter() - t0) / args.reps
        results[f"{name}_s"] = round(dt, 4)
        results[f"{name}_adds_per_s"] = int(L * args.steps / dt)
        print(f"[add_bench] {name}: {dt*1e3:.1f} ms for {args.steps} steps"
              f" x {L} lanes = {results[f'{name}_adds_per_s']/1e3:.0f}k adds/s",
              file=sys.stderr)

    line = json.dumps(results)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    # normalized trajectory record (scripts/bench_record.py): one
    # schema-versioned JSONL line per run so bench_compare.py and future
    # sessions can diff this tool's history without knowing its shape
    try:
        import bench_record as BR
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        BR.append(BR.normalize("add_bench", results), repo=repo)
    except Exception:
        pass
    print(line)


if __name__ == "__main__":
    main()
