#!/usr/bin/env python3
"""Opportunistic chip measurement for a FLAPPING relay.

The axon relay has been observed to die for hours and recover for
minutes. This script probes, then runs an ESCALATING series of
measurements — smallest/most-valuable first — printing one JSON line per
completed step immediately (flushed), so however short the alive window
is, whatever finished is captured. Every step is independently
try/except'd; a mid-step hang is bounded by the caller's timeout.

Usage: python scripts/chip_window.py   (ambient axon env)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def emit(obj):
    print(json.dumps(obj), flush=True)


def sync(x):
    np.asarray(x[:1, :1] if getattr(x, "ndim", 1) >= 2 else x[:1])


def step_probe():
    import jax.numpy as jnp
    t0 = time.perf_counter()
    v = int(jnp.arange(8).sum())
    assert v == 28
    return {"probe_s": round(time.perf_counter() - t0, 2)}


def step_mont_mul(log_n=18, chain=2, reps=3):
    import jax
    from distributed_plonk_tpu.backend import field_jax as FJ

    n = 1 << log_n

    @jax.jit
    def f(a, b):
        acc = a
        for _ in range(chain):
            acc = FJ.mont_mul(FJ.FR, acc, b)
        return acc

    rng = np.random.default_rng(0)
    a = rng.integers(0, 1 << 16, size=(16, n), dtype=np.uint32)
    b = rng.integers(0, 1 << 16, size=(16, n), dtype=np.uint32)
    t0 = time.perf_counter()
    sync(f(a, b))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(a, b)
    sync(out)
    dt = (time.perf_counter() - t0) / reps
    per_s = n * chain / dt
    return {"kernel": "mont_mul_fr", "n": n, "chain": chain,
            "compile_s": round(compile_s, 1), "s_per_call": round(dt, 4),
            "mul_per_s": round(per_s), "ns_per_mul": round(1e9 / per_s, 2)}


def step_ntt(log_n, reps=3):
    from distributed_plonk_tpu.backend import ntt_jax

    n = 1 << log_n
    plan = ntt_jax.get_plan(n)
    kernel = plan.kernel()
    rng = np.random.default_rng(2)
    v = rng.integers(0, 1 << 16, size=(16, n), dtype=np.uint32)
    t0 = time.perf_counter()
    sync(kernel(v))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = kernel(v)
    sync(out)
    dt = (time.perf_counter() - t0) / reps
    return {"kernel": f"ntt_2p{log_n}", "compile_s": round(compile_s, 1),
            "s": round(dt, 4), "elements_per_s": round(n / dt)}


def step_msm(log_n, reps=1):
    import random
    from distributed_plonk_tpu import curve as C
    from distributed_plonk_tpu.constants import R_MOD
    from distributed_plonk_tpu.backend.msm_jax import MsmContext

    n = 1 << log_n
    rng = random.Random(3)
    distinct = [C.g1_mul(C.G1_GEN, rng.randrange(1, R_MOD))
                for _ in range(1 << 10)]
    bases = (distinct * (n // len(distinct) + 1))[:n]
    ctx = MsmContext(bases)
    scalars = [rng.randrange(R_MOD) for _ in range(n)]
    t0 = time.perf_counter()
    ctx.msm(scalars)  # compile + warm + calibration
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        ctx.msm(scalars)
    dt = (time.perf_counter() - t0) / reps
    return {"kernel": f"msm_2p{log_n}", "compile_plus_first_s": round(compile_s, 1),
            "s": round(dt, 3), "points_per_s": round(n / dt),
            "adds_per_s_calibrated": {
                str(k): v for k, v in MsmContext._measured_adds_per_s.items()}}


STEPS = [
    ("probe", step_probe),
    ("mont_mul_fr_2p18", step_mont_mul),
    ("ntt_2p12", lambda: step_ntt(12)),
    ("ntt_2p20", lambda: step_ntt(20)),
    ("msm_2p14", lambda: step_msm(14, reps=2)),
    ("msm_2p20", lambda: step_msm(20)),
]


def main():
    for name, fn in STEPS:
        t0 = time.perf_counter()
        try:
            res = fn()
            res["step"] = name
            res["total_s"] = round(time.perf_counter() - t0, 1)
            emit(res)
        except Exception as e:
            emit({"step": name, "error": repr(e)[:300],
                  "total_s": round(time.perf_counter() - t0, 1)})
            break  # a dead relay fails everything downstream


if __name__ == "__main__":
    main()
