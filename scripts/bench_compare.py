#!/usr/bin/env python3
"""Perf-regression gate over the bench trajectory (ISSUE 15 pillar 4).

    python scripts/bench_compare.py                 # check the committed
                                                    # trajectory (ci.sh
                                                    # benchcheck)
    python scripts/bench_compare.py --line '<json>' # gate one fresh
                                                    # bench line against
                                                    # the latest committed
                                                    # record of its basis
    python scripts/bench_compare.py --report        # full history diff,
                                                    # informational

Reads BOTH formats of the perf history: the legacy driver-wrapped
BENCH_r*.json files and the normalized bench_artifacts/trajectory.jsonl
records that bench.py / scripts/add_bench.py now append (schema 1, see
scripts/bench_record.py). Comparison is BASIS-AWARE — chip lines compare
only against chip lines, degraded (host-CPU fallback) only against
degraded — and key-scoped by the WATCH tolerance table, so a relay
outage or a brand-new metric can never read as a regression.

Exit code: 0 = no watched key regressed beyond its tolerance on the
gated comparison (the LATEST record vs its same-basis predecessor, or
--line vs the latest committed record); 1 = at least one did, printed
loudly. Deliberately non-flaky: the default mode runs NO measurement —
it only reads committed numbers.
"""

import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, HERE)

import bench_record as BR  # noqa: E402


def _print_regressions(tag, regs):
    for r in regs:
        print(f"[bench_compare] REGRESSION {tag}: {r['key']} "
              f"{r['prev']} -> {r['cur']} "
              f"(change {r['change']}, tolerance {r['tol']}, "
              f"want {r['direction']})", file=sys.stderr)


def check_committed(repo, scale, verbose=False):
    """Gate: the latest trajectory record vs its same-basis predecessor.
    Returns (regressions, detail dict)."""
    records = BR.load_trajectory(repo)
    if not records:
        return [], {"records": 0, "note": "no trajectory to check"}
    cur = records[-1]
    prev = BR.latest_of_basis(records, cur.get("basis"),
                              before=len(records) - 1,
                              source=cur.get("source"))
    detail = {"records": len(records), "basis": cur.get("basis"),
              "cur_source": cur.get("source"), "cur_run": cur.get("run")}
    if prev is None:
        detail["note"] = "first record of its basis: nothing to gate"
        return [], detail
    regs = BR.compare(prev, cur, scale=scale)
    detail["prev_run"] = prev.get("run")
    detail["compared_keys"] = sum(
        1 for k in (cur.get("keys") or {})
        if BR.watch_rule(k) and k in (prev.get("keys") or {}))
    if verbose:
        # informational sweep over the whole history (never gates)
        for i in range(1, len(records)):
            p = BR.latest_of_basis(records, records[i].get("basis"),
                                   before=i,
                                   source=records[i].get("source"))
            if p is None:
                continue
            for r in BR.compare(p, records[i], scale=scale):
                print(f"[bench_compare] note run {records[i].get('run')}: "
                      f"{r['key']} {r['prev']} -> {r['cur']}",
                      file=sys.stderr)
    return regs, detail


def check_line(repo, line, scale):
    """Gate one fresh bench line (a JSON dict string) against the latest
    committed record of the same basis."""
    data = json.loads(line)
    cur = BR.normalize("bench", data)
    records = BR.load_trajectory(repo)
    prev = BR.latest_of_basis(records, cur["basis"],
                              source=cur["source"])
    if prev is None:
        return [], {"note": f"no committed {cur['basis']} record",
                    "basis": cur["basis"]}
    regs = BR.compare(prev, cur, scale=scale)
    return regs, {"basis": cur["basis"], "prev_run": prev.get("run"),
                  "compared_keys": sum(
                      1 for k in cur["keys"]
                      if BR.watch_rule(k) and k in (prev.get("keys") or {}))}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--repo", default=REPO)
    ap.add_argument("--line", default=None,
                    help="one bench-line JSON dict to gate against the "
                         "committed trajectory")
    ap.add_argument("--file", default=None,
                    help="like --line but read the JSON from a file")
    ap.add_argument("--report", action="store_true",
                    help="also print the informational full-history diff")
    ap.add_argument("--tolerance-scale", type=float, default=1.0,
                    help="multiply every WATCH tolerance (a loaded CI box "
                         "can widen the gate without editing the table)")
    args = ap.parse_args()

    if args.file:
        with open(args.file) as f:
            args.line = f.read().strip().splitlines()[-1]
    if args.line:
        regs, detail = check_line(args.repo, args.line,
                                  args.tolerance_scale)
        tag = "line-vs-committed"
    else:
        regs, detail = check_committed(args.repo, args.tolerance_scale,
                                       verbose=args.report)
        tag = "trajectory"
    _print_regressions(tag, regs)
    print(json.dumps({"ok": not regs, "mode": tag,
                      "regressions": regs, **detail}))
    return 1 if regs else 0


if __name__ == "__main__":
    sys.exit(main())
