#!/usr/bin/env python3
"""Offline kernel calibration of an artifact store (scripts/warmup.py's
sibling): measure the MSM/NTT/field-mul candidate spaces at the given
shapes on THIS machine, persist the winning plan (+ the winners'
AOT-compiled executables in the store-owned persistent compile cache),
and print one JSON report line. A store calibrated here serves with
zero knob setup: `serve.py --store-dir` (and fleet workers pointed at
the store) load the plan at startup and reach first proof with zero
measurement runs and zero kernel compiles at the calibrated shapes.

  python scripts/autotune.py --store-dir /var/dpt/store \
      --shapes 2^10,2^14,2^18 --budget-s 300 --report

With no --shapes, calibrates at DPT_AUTOTUNE_SHAPES, else the domain
sizes of the store's provisioned shape buckets (run scripts/warmup.py
first so the plan covers the real serving mix), else 2^10. --force
remeasures even when the store already holds a plan for this machine
fingerprint (knob sweeps, post-driver-update refreshes); the default is
load-or-run, so re-invoking on a calibrated store is free.

Exit 0 iff a plan is active when we're done (loaded or fresh).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--store-dir", required=True,
                    help="artifact store to calibrate (created if missing)")
    ap.add_argument("--shapes", default=None,
                    help="comma-separated domain sizes, 2^k accepted "
                         "(default: store shape buckets, else 2^10)")
    ap.add_argument("--budget-s", type=float, default=None,
                    help="wall-clock budget for the whole measure pass "
                         "(default DPT_AUTOTUNE_BUDGET_S, 120)")
    ap.add_argument("--force", action="store_true",
                    help="remeasure even if the store holds a plan for "
                         "this machine fingerprint")
    ap.add_argument("--no-aot", action="store_true",
                    help="skip pre-compiling the winners' executables")
    ap.add_argument("--report", action="store_true",
                    help="include the full per-cell plan in the output")
    args = ap.parse_args()

    from distributed_plonk_tpu.store import (ArtifactStore,
                                             configure_jax_cache)
    from distributed_plonk_tpu.store import calibration
    from distributed_plonk_tpu.backend import autotune

    t0 = time.time()
    store = ArtifactStore(args.store_dir)
    # winners' AOT executables land in the store-owned compile cache so
    # they warm-sync to workers alongside the plan itself
    configure_jax_cache(args.store_dir)
    shapes = calibration.parse_shapes(args.shapes) if args.shapes else None

    if args.force:
        tuner = autotune.Autotuner(
            shapes or calibration._default_shapes(store),
            budget_s=args.budget_s)
        with calibration.calibration_lock(store):
            plan = tuner.run(aot=not args.no_aot)
            calibration.store_plan(store, plan)
        autotune.set_active_plan(plan)
        out = {"source": "fresh", "fingerprint": plan.fingerprint,
               "cells": len(plan.cells)}
    else:
        out = calibration.load_or_run(store, mode="run", shapes=shapes,
                                      budget_s=args.budget_s,
                                      aot=not args.no_aot)

    plan = autotune.active_plan()
    ok = plan is not None
    out["ok"] = ok
    out["wall_s"] = round(time.time() - t0, 3)
    if args.report and plan is not None:
        out["plan"] = {f"{k}:{n}": cell
                       for (k, n), cell in sorted(plan.cells.items())}
        out["meta"] = plan.meta
    print(json.dumps(out), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
