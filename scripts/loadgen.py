#!/usr/bin/env python3
"""Concurrent load generator + fault injector for the proof service.

    JAX_PLATFORMS=cpu python scripts/loadgen.py            # self-hosted run
    python scripts/loadgen.py --host 127.0.0.1 --port 9555 # external server
    python scripts/loadgen.py --jobs 12 --no-kill
    python scripts/loadgen.py --kill-rate 0.5 --corrupt-rate 0.3 \
        --delay-ms 5 --store-dir /tmp/s                    # chaos soak
    DPT_AUTOSCALE=1 python scripts/loadgen.py --traffic diurnal \
        --slo-mix flagship=0.1,standard=0.6,batch=0.3      # autoscaling
        # soak: seeded diurnal arrival curve against a supervised fleet,
        # the closed-loop controller must ramp workers up into the peak
        # and retire them (drain-then-LEAVE) after it — every proof
        # byte-verified, zero flagship sheds
    python scripts/loadgen.py \
        --circuit-mix range=0.3,merkle=0.3,rollup=0.2,toy=0.2
        # circuit-zoo soak: every job's kind drawn from the weights,
        # every proof byte-verified, then the whole batch folded into
        # ONE batch-KZG aggregate verified client-side with a single
        # 2-pair pairing check (--aggregate-only accepts on that alone)
    python scripts/loadgen.py --kill-service ROUND2        # restart soak:
        # spawns scripts/serve.py as a real subprocess (journal + store),
        # submits the job mix with idempotency keys, SIGKILLs the SERVICE
        # at the given journal occurrence mid-prove, restarts it on the
        # same dirs, and requires every job to finish with proof bytes
        # BYTE-IDENTICAL to a local uninterrupted prove

Default run: spins up an in-process ProofService (chaos mode, host oracle
backend), then N submitter threads (default 8, mixed toy domain sizes
2^5..2^9) each submit over real TCP, wait, fetch, and verify their proof
client-side (keys rebuilt locally from the spec — same deterministic test
SRS). Unless --no-kill, one extra large job is the kill target: as soon as
its STATUS says running, KILL_WORKER is sent for it; the worker dies at
the next round boundary, the pool respawns a replacement, and the job
must finish DONE with retries >= 1 (checkpoint resume, not restart).

Prints one JSON summary line; exit code 0 iff every proof verified and
the injected kill (if any) produced a visible retry.
"""

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# mixed shapes: domains 32 / 128 / 256 (toy gate chains)
_MIX = [{"kind": "toy", "gates": g} for g in (16, 60, 150)]
# burst profile (--mix burst): ONE small shape for every job, submitted
# concurrently — the traffic pattern the placement layer's data-parallel
# batching exists for (same-shape jobs pop as one batch and prove
# together; the summary reports the jobs-per-launch actually achieved)
_BURST_MIX = [{"kind": "toy", "gates": 16}]
_KILL_SPEC = {"kind": "toy", "gates": 300}  # n=512: wide kill window


def _job_mix(args):
    return _BURST_MIX if args.mix == "burst" else _MIX


def _pipeline_summary(m):
    """Round-pipeline section for a soak summary: how full the pipeline
    actually ran (achieved-depth histogram), where members stalled
    (per-round stage-wait breakdown), and the per-round device-idle
    estimate. `{"enabled": False}` when nothing pipelined (DPT_PIPELINE=0
    or all traffic went down the single/batch/mesh paths)."""
    sc = m.get("counters") or {}
    if not sc.get("pipelined_proves"):
        return {"enabled": False}
    hg = m.get("histograms") or {}
    gg = m.get("gauges") or {}
    depth = hg.get("pipeline_depth_achieved") or {}
    return {
        "enabled": True,
        "proves": sc.get("pipelined_proves", 0),
        "jobs": sc.get("pipelined_jobs", 0),
        "depth": {k: depth.get(k) for k in
                  ("count", "mean_s", "p50_s", "p95_s", "max_s")
                  if k in depth},
        "stage_stalls": {
            name.rsplit("/", 1)[-1]: {
                "count": h.get("count", 0), "p50_s": h.get("p50_s"),
                "p95_s": h.get("p95_s"), "max_s": h.get("max_s")}
            for name, h in sorted(hg.items())
            if name.startswith("pipeline_stage_wait_s/")
            and h.get("count")},
        "device_idle_s": {
            name.rsplit("/", 1)[-1]: v
            for name, v in sorted(gg.items())
            if name.startswith("pipeline_device_idle_s/")},
    }


def _verify_result(header, blob, key_cache, lock):
    from distributed_plonk_tpu.proof_io import deserialize_proof
    from distributed_plonk_tpu.service.jobs import (JobSpec,
                                                    build_bucket_keys,
                                                    shape_key)
    from distributed_plonk_tpu.verifier import verify

    spec = JobSpec.from_wire(header["spec"])
    with lock:
        key = shape_key(spec)
        if key not in key_cache:
            key_cache[key] = build_bucket_keys(spec)[2]
        vk = key_cache[key]
    pub = [int(x, 16) for x in header["public_input"]]
    return verify(vk, pub, deserialize_proof(blob), rng=random.Random(1))


def _proof_reference(spec, _pk_cache={}):
    """Uninterrupted local prove of `spec` — the byte-identity oracle the
    restart soak compares recovered service results against. Proving keys
    are cached per SHAPE (the expensive part; the soak's job mix rotates
    a handful of shapes over many seeds)."""
    import random as _random
    from distributed_plonk_tpu.backend.python_backend import PythonBackend
    from distributed_plonk_tpu.proof_io import serialize_proof
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.service.jobs import (JobSpec, build_circuit,
                                                    build_bucket_keys,
                                                    shape_key)
    s = JobSpec.from_wire(spec)
    key = shape_key(s)
    if key not in _pk_cache:
        _pk_cache[key] = build_bucket_keys(s)[1]
    return serialize_proof(prove(_random.Random(s.seed), build_circuit(s),
                                 _pk_cache[key], PythonBackend()))


def _parse_slo_mix(arg):
    """'flagship=0.1,standard=0.6,batch=0.3' -> {class: weight}, failing
    fast with a message that names the flag. Weights need not sum to 1
    (they are normalized at draw time); unknown classes are an error."""
    from distributed_plonk_tpu.service.jobs import SLO_CLASSES
    mix = {}
    for entry in arg.split(","):
        name, sep, w = entry.strip().partition("=")
        if not sep or name not in SLO_CLASSES:
            raise SystemExit(f"--slo-mix: {entry.strip()!r} is not "
                             f"<class>=<weight> with class in "
                             f"{SLO_CLASSES}")
        try:
            mix[name] = float(w)
        except ValueError:
            raise SystemExit(f"--slo-mix: {w!r} is not a number")
    if not mix or sum(mix.values()) <= 0:
        raise SystemExit("--slo-mix: needs at least one positive weight")
    return mix


# circuit-zoo shapes per kind (--circuit-mix): the smallest spec of each
# family that still runs its real gadgets — range decomposition n=32,
# one 3-ary Merkle membership / one Rescue preimage n=256, one rollup
# account update under a height-1 tree n=1024 (the expensive one)
_ZOO_SPECS = {
    "toy": {"kind": "toy", "gates": 16},
    "range": {"kind": "range", "bits": 8, "count": 2},
    "merkle": {"kind": "merkle", "height": 1, "num_proofs": 1},
    "preimage": {"kind": "preimage", "count": 1},
    "rollup": {"kind": "rollup", "height": 1, "updates": 1,
               "num_accounts": 2},
}


def _parse_circuit_mix(arg):
    """'range=0.3,merkle=0.3,rollup=0.2,toy=0.2' -> {kind: weight}, same
    contract as _parse_slo_mix (normalized at draw time, unknown kinds
    fail fast naming the flag)."""
    mix = {}
    for entry in arg.split(","):
        name, sep, w = entry.strip().partition("=")
        if not sep or name not in _ZOO_SPECS:
            raise SystemExit(f"--circuit-mix: {entry.strip()!r} is not "
                             f"<kind>=<weight> with kind in "
                             f"{tuple(sorted(_ZOO_SPECS))}")
        try:
            mix[name] = float(w)
        except ValueError:
            raise SystemExit(f"--circuit-mix: {w!r} is not a number")
    if not mix or sum(mix.values()) <= 0:
        raise SystemExit("--circuit-mix: needs at least one positive "
                         "weight")
    return mix


def run_circuit_mix_soak(args):
    """--circuit-mix: the circuit-zoo + proof-aggregation soak (ISSUE 17).
    Each job's kind is drawn from the seeded weights, proved through the
    full service path, and byte-verified against a local uninterrupted
    prove. Then ONE AGGREGATE call folds every DONE job into a single
    batch-KZG artifact, which is fetched back and verified CLIENT-SIDE —
    one 2-pair pairing check for the whole batch, pinned in the summary
    by the curve-level pairing counters. --aggregate-only drops the
    per-proof verification: the batch is accepted on the aggregate alone
    (the 'N proofs in, one pairing check out' client mode). The summary
    reports per-kind submitted/done/verified/p50/p95; --record appends
    it to bench_artifacts/trajectory.jsonl."""
    from distributed_plonk_tpu import aggregate as AGG
    from distributed_plonk_tpu import curve
    from distributed_plonk_tpu.service import ProofService, ServiceClient

    t0 = time.time()
    mix = _parse_circuit_mix(args.circuit_mix)
    kinds_sorted = sorted(mix)
    wsum = sum(mix[k] for k in kinds_sorted)
    rng = random.Random(args.chaos_seed)
    draws = []
    for _ in range(args.jobs):
        r = rng.random() * wsum
        acc, kind = 0.0, kinds_sorted[-1]
        for k in kinds_sorted:
            acc += mix[k]
            if r < acc:
                kind = k
                break
        draws.append(kind)

    svc = ProofService(port=0, prover_workers=args.workers, chaos=True,
                       allow_remote_shutdown=True,
                       store_dir=args.store_dir).start()
    results = []
    results_lock = threading.Lock()

    def submitter(i, kind):
        spec = dict(_ZOO_SPECS[kind], seed=7000 + i)
        out = {"index": i, "kind": kind, "spec": spec}
        t_sub = time.monotonic()
        try:
            with ServiceClient("127.0.0.1", svc.port) as c:
                out["job_id"] = c.submit(spec)["job_id"]
                st = c.wait(out["job_id"], timeout_s=args.timeout)
                out["state"] = st["state"]
                out["roundtrip_s"] = round(time.monotonic() - t_sub, 4)
                if st["state"] == "done":
                    _hdr, blob = c.result(out["job_id"])
                    if not args.aggregate_only:
                        out["verified"] = blob == _proof_reference(spec)
                else:
                    out["error"] = st.get("error")
        except Exception as e:  # noqa: BLE001 - report, don't crash
            out["error"] = repr(e)
        with results_lock:
            results.append(out)

    threads = [threading.Thread(target=submitter, args=(i, k), daemon=True)
               for i, k in enumerate(draws)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=args.timeout)

    # the aggregation leg: every DONE job folds into ONE artifact; the
    # client re-derives the vks from the same deterministic test SRS and
    # accepts the whole batch on a single pairing check
    agg_report = {}
    metrics = {"counters": {}}
    try:
        done_ids = [r["job_id"] for r in
                    sorted(results, key=lambda r: r["index"])
                    if r.get("state") == "done"]
        with ServiceClient("127.0.0.1", svc.port) as c:
            if done_ids:
                rep = c.aggregate(done_ids)
                agg = c.fetch_aggregate(rep["agg_id"])
                curve.reset_pairing_counters()
                t_v = time.monotonic()
                agg_ok = AGG.verify(agg)
                agg_report = {
                    "agg_id": rep["agg_id"],
                    "members": len(rep["members"]),
                    "kinds": rep["kinds"],
                    "verified": bool(agg_ok),
                    "verify_s": round(time.monotonic() - t_v, 4),
                    "pairing_checks": dict(curve.PAIRING_COUNTERS),
                }
            metrics = c.metrics()
            c.shutdown_server()
    finally:
        svc.shutdown()

    sc = metrics["counters"]
    per_kind = {}
    for k in kinds_sorted:
        rs = [r for r in results if r["kind"] == k]
        rts = sorted(r["roundtrip_s"] for r in rs
                     if r.get("state") == "done"
                     and r.get("roundtrip_s") is not None)

        def pct(p, rts=rts):
            if not rts:
                return None
            return round(rts[min(len(rts) - 1, int(p * len(rts)))], 4)

        per_kind[k] = {
            "submitted": len(rs),
            "done": sum(1 for r in rs if r.get("state") == "done"),
            "verified": sum(1 for r in rs if r.get("verified")),
            "served_counter": sc.get("circuit_kind_%s" % k, 0),
            "p50_s": pct(0.50),
            "p95_s": pct(0.95),
        }
    done = sum(1 for r in results if r.get("state") == "done")
    shed = sum(1 for r in results if r.get("state") == "shed")
    verified = sum(1 for r in results if r.get("verified"))
    # the contract: every job served (zero sheds), the aggregate's one
    # pairing check accepted the whole batch, and (unless aggregate-only)
    # every proof byte-identical to a local prove
    ok = (done == args.jobs and shed == 0
          and agg_report.get("verified") is True
          and (args.aggregate_only or verified == done))
    summary = {
        "mode": "circuit-mix", "ok": ok,
        "wall_s": round(time.time() - t0, 3),
        "jobs": args.jobs, "circuit_mix": mix,
        "verify": ("aggregate-only" if args.aggregate_only
                   else "per-proof-bytes"),
        "verified": verified, "shed": shed,
        "failed": [r for r in results if r.get("state") != "done"],
        "kinds": per_kind,
        "aggregate": agg_report,
        "aggregates_built": sc.get("aggregates_built", 0),
        "pipeline": _pipeline_summary(metrics),
    }
    if args.record:
        here = os.path.dirname(os.path.abspath(__file__))
        if here not in sys.path:
            sys.path.insert(0, here)
        import bench_record
        repo = os.path.dirname(here)
        rec = bench_record.normalize(
            "loadgen", dict(summary, backend="python"))
        summary["recorded"] = bench_record.append(rec, repo=repo)
    print(json.dumps(summary), flush=True)
    return 0 if ok else 1


def _traffic_schedule(model, jobs, duration_s, seed, slo_mix):
    """[(arrival_offset_s, slo_class)] for `jobs` arrivals over
    `duration_s` seconds under a DETERMINISTIC rate curve — inverse-CDF
    sampling of evenly spaced quantiles over a 512-point grid, so the
    same (model, jobs, duration, seed) always produces the same
    schedule (the soak is replayable). Curves (t in [0,1)):

        flat     1.0
        diurnal  0.15 + 0.85*sin(pi*t)^2   — one day compressed: quiet
                 shoulders, one mid-window peak (the autoscaler must
                 ramp up into it and back down after)
        burst    0.12 off-peak, 1.0 inside [0.40, 0.60] — a step spike

    SLO classes are drawn per arrival from the seeded rng against the
    normalized `slo_mix` weights."""
    import bisect
    import math
    rng = random.Random(seed)
    grid = 512

    def rate(t):
        if model == "diurnal":
            return 0.15 + 0.85 * math.sin(math.pi * t) ** 2
        if model == "burst":
            return 1.0 if 0.40 <= t <= 0.60 else 0.12
        return 1.0

    cum = [0.0]
    for g in range(grid):
        cum.append(cum[-1] + rate((g + 0.5) / grid))
    total = cum[-1]
    classes = sorted(slo_mix)
    wsum = sum(slo_mix[c] for c in classes)
    out = []
    for i in range(jobs):
        target = (i + 0.5) / jobs * total
        g = bisect.bisect_left(cum, target)
        g = min(max(g, 1), grid)
        frac = (g - 1 + (target - cum[g - 1]) / (cum[g] - cum[g - 1])) \
            / grid
        r = rng.random() * wsum
        acc, cls = 0.0, classes[-1]
        for c in classes:
            acc += slo_mix[c]
            if r < acc:
                cls = c
                break
        out.append((round(frac * duration_s, 4), cls))
    return out


# per-class job shapes for the traffic soak: interactive classes are
# small (flagship n=32 proves in well under a tick), batch is the big
# one (n=512) — the mix that actually moves the per-class queue depths
# the lease-resize rule watches
_SLO_GATES = {"flagship": 16, "standard": 60, "batch": 150}


def run_traffic_soak(args):
    """--traffic: the closed-loop autoscaling acceptance soak (ISSUE 16).
    A supervised fleet starts at ONE worker behind a fleet-backed proof
    service with the autoscaler attached per DPT_AUTOSCALE (or
    --autoscale); a seeded arrival-rate curve (diurnal/burst/flat) with
    an SLO-class mix is replayed against it in real time. The controller
    must scale UP into the ramp (supervisor.add_slot — warm membership
    join), back DOWN after the peak (retire_slot: drain, LEAVE, SIGTERM
    — never a mid-prove kill), and EVERY served proof must be
    byte-identical to a local uninterrupted prove. The summary carries
    per-class latency percentiles + shed counts (`slo`) and the
    controller's decision trail (`autoscale`); --record appends it to
    bench_artifacts/trajectory.jsonl via scripts/bench_record.py."""
    from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                          RemoteBackend)
    from distributed_plonk_tpu.runtime.health import LivenessTracker
    from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
    from distributed_plonk_tpu.runtime.supervisor import WorkerSupervisor
    from distributed_plonk_tpu.service import ProofService, ServiceClient
    from distributed_plonk_tpu.service import autoscale as AS

    # control-loop knobs scaled to a CI-sized soak (a set env wins)
    for k, v in (("DPT_AUTOSCALE_TICK_S", "0.5"),
                 ("DPT_AS_MIN_WORKERS", "1"),
                 ("DPT_AS_MAX_WORKERS", "3"),
                 ("DPT_AS_UP_QUEUE", "2"),
                 ("DPT_AS_UP_TICKS", "2"),
                 ("DPT_AS_DOWN_TICKS", "4"),
                 ("DPT_AS_UP_COOLDOWN_S", "3"),
                 ("DPT_AS_DOWN_COOLDOWN_S", "5"),
                 ("DPT_SUP_RETIRE_TIMEOUT_S", "10")):
        os.environ.setdefault(k, v)
    if args.autoscale is not None:
        os.environ["DPT_AUTOSCALE"] = args.autoscale
    mode = AS.mode_from_env()

    from distributed_plonk_tpu.service.metrics import Metrics
    t0 = time.time()
    slo_mix = _parse_slo_mix(args.slo_mix)
    schedule = _traffic_schedule(args.traffic, args.jobs, args.duration,
                                 args.chaos_seed, slo_mix)

    fm = Metrics()  # fleet-side registry: supervisor/membership counters
    d = Dispatcher(NetworkConfig([]), metrics=fm)
    d.tracker = LivenessTracker(0, breaker_k=2, probe_base_s=0.05,
                                probe_max_s=0.5, metrics=fm)
    mserver = d.enable_membership()

    def spawn_cmd(i, slot):
        return [sys.executable, "-m",
                "distributed_plonk_tpu.runtime.worker",
                "--join", f"127.0.0.1:{mserver.port}",
                "--listen", f"127.0.0.1:{slot.port}",
                "--backend", "python"]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sup = WorkerSupervisor("127.0.0.1", mserver.port, n=1, metrics=fm,
                           cwd=repo, spawn_cmd=spawn_cmd).start()
    sup.attach_registry(d.membership)
    svc = None
    results = []
    results_lock = threading.Lock()
    asc_state = None
    svc_metrics = {"counters": {}, "gauges": {}, "histograms": {}}
    try:
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            if d.workers and d.tracker.usable_set():
                break
            time.sleep(0.1)
        # fleet-backed service: one pool worker drives the one dispatcher
        # (queue depth is the up-signal; the fleet widens the FFT shards)
        svc = ProofService(
            port=0, prover_workers=1, chaos=True, max_retries=4,
            allow_remote_shutdown=True, self_verify="1",
            backend_factory=lambda: RemoteBackend(d, dist_fft_min=64),
        ).start()
        svc.attach_autoscaler(supervisor=sup)

        start = time.monotonic()

        def submitter(i, at_s, cls):
            out = {"index": i, "slo": cls}
            delay = start + at_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            spec = {"kind": "toy", "gates": _SLO_GATES[cls],
                    "seed": 5000 + i, "slo": cls}
            out["spec"] = spec
            t_sub = time.monotonic()
            try:
                with ServiceClient("127.0.0.1", svc.port) as c:
                    out["job_id"] = c.submit(spec)["job_id"]
                    st = c.wait(out["job_id"], timeout_s=args.timeout)
                    out["state"] = st["state"]
                    out["roundtrip_s"] = round(time.monotonic() - t_sub, 4)
                    if st["state"] == "done":
                        _hdr, blob = c.result(out["job_id"])
                        out["verified"] = blob == _proof_reference(spec)
                    elif st["state"] != "shed":
                        out["error"] = st.get("error")
            except Exception as e:  # noqa: BLE001 - report, don't crash
                out["error"] = repr(e)
            with results_lock:
                results.append(out)

        threads = [threading.Thread(target=submitter, args=(i, at, cls),
                                    daemon=True)
                   for i, (at, cls) in enumerate(schedule)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=args.timeout + args.duration)
        # post-peak idle window: hold the (now-idle) service open long
        # enough for the down streak + cooldown to elapse, so the soak
        # demonstrates BOTH transitions — not just the ramp-up
        if mode == "1":
            idle_deadline = time.monotonic() + 30
            while time.monotonic() < idle_deadline:
                sc = svc.metrics.snapshot()["counters"]
                if sc.get("autoscale_scale_downs", 0) >= 1:
                    break
                time.sleep(0.25)
        if svc.autoscaler is not None:
            asc_state = svc.autoscaler.state()
        with ServiceClient("127.0.0.1", svc.port) as c:
            svc_metrics = c.metrics()
            c.shutdown_server()
    finally:
        sup.stop()
        try:
            d.shutdown()
        finally:
            d.pool.shutdown(wait=False)
        if svc is not None:
            svc.shutdown()

    sc = svc_metrics["counters"]
    fc = fm.snapshot()["counters"]
    per_class = {}
    for cls in ("flagship", "standard", "batch"):
        rs = [r for r in results if r["slo"] == cls]
        rts = sorted(r["roundtrip_s"] for r in rs
                     if r.get("state") == "done"
                     and r.get("roundtrip_s") is not None)

        def pct(p, rts=rts):
            if not rts:
                return None
            return round(rts[min(len(rts) - 1, int(p * len(rts)))], 4)

        per_class[cls] = {
            "submitted": len(rs),
            "done": sum(1 for r in rs if r.get("state") == "done"),
            "shed": sc.get(f"slo_sheds_{cls}", 0),
            "verified": sum(1 for r in rs if r.get("verified")),
            "p50_s": pct(0.50),
            "p95_s": pct(0.95),
        }
    done = sum(1 for r in results if r.get("state") == "done")
    verified = sum(1 for r in results if r.get("verified"))
    shed = sum(1 for r in results if r.get("state") == "shed")
    # the contract: every proof SERVED verified byte-identical, every
    # job accounted for (done or shed, nothing stuck), and load shedding
    # never touched the flagship class
    ok = (verified == done and done + shed == args.jobs
          and per_class["flagship"]["shed"] == 0)
    scale_ups = sc.get("autoscale_scale_ups", 0)
    scale_downs = sc.get("autoscale_scale_downs", 0)
    if mode == "1":
        # actuating acceptance: the controller visibly rode the curve
        ok = ok and scale_ups >= 1 and scale_downs >= 1
    summary = {
        "mode": "traffic", "ok": ok,
        "traffic": args.traffic, "autoscale_mode": mode,
        "wall_s": round(time.time() - t0, 3),
        "jobs": args.jobs, "duration_s": args.duration,
        "slo_mix": slo_mix,
        "verified": verified,
        "unverified_served": done - verified,
        "failed": [r for r in results
                   if not r.get("verified") and r.get("state") != "shed"],
        "slo": per_class,
        "autoscale": {
            "mode": mode,
            "ticks": sc.get("autoscale_ticks", 0),
            "decisions": sc.get("autoscale_decisions", 0),
            "scale_ups": scale_ups,
            "scale_downs": scale_downs,
            "lease_resizes": sc.get("autoscale_lease_resizes", 0),
            "sheds": sc.get("autoscale_sheds", 0),
            "actuator_errors": sc.get("autoscale_actuator_errors", 0),
            "worker_retires": fc.get("worker_retires", 0),
            # zero mid-prove kills: a retire is not a flap/respawn
            "worker_respawns": fc.get("worker_respawns", 0),
            "worker_flap_capped": fc.get("worker_flap_capped", 0),
            "final_state": asc_state,
        },
        "pipeline": _pipeline_summary(svc_metrics),
    }
    if args.record:
        here = os.path.dirname(os.path.abspath(__file__))
        if here not in sys.path:
            sys.path.insert(0, here)
        import bench_record
        rec = bench_record.normalize(
            "loadgen", dict(summary, backend="python"))
        summary["recorded"] = bench_record.append(rec, repo=repo)
    print(json.dumps(summary), flush=True)
    return 0 if ok else 1


def run_kill_service_soak(args):
    """--kill-service: the durable-service-plane acceptance soak. The
    frontend is a REAL serve.py process killed with os._exit at an exact
    journal occurrence (DPT_FAULTS journal plane), restarted on the same
    journal/store dirs, and every job — queued, mid-prove, or finished at
    kill time — must complete byte-identically with no proving repeated
    past the last checkpoint."""
    import subprocess
    import tempfile
    from distributed_plonk_tpu.service import ServiceClient

    here = os.path.dirname(os.path.abspath(__file__))
    jdir = args.journal_dir or tempfile.mkdtemp(prefix="dpt-lg-journal-")
    sdir = args.store_dir or tempfile.mkdtemp(prefix="dpt-lg-store-")
    port = args.port

    def spawn(faults=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("DPT_FAULTS", None)
        if faults:
            env["DPT_FAULTS"] = faults
        p = subprocess.Popen(
            [sys.executable, os.path.join(here, "serve.py"),
             "--port", str(port), "--workers", str(args.workers),
             "--journal-dir", jdir, "--store-dir", sdir, "--chaos",
             "--allow-remote-shutdown"],
            stdout=subprocess.PIPE, env=env, text=True)
        p.stdout.readline()  # the {"listening": ...} banner
        return p

    t0 = time.time()
    summary = {"mode": "kill-service", "kill_at": args.kill_service,
               "jobs": args.jobs, "journal_dir": jdir, "store_dir": sdir}
    # arm the service kill at the Nth matching journal occurrence; the
    # job mix below guarantees ROUND records exist before it fires
    proc = spawn(faults=f"kill:at=journal:tag={args.kill_service}")
    mix = _job_mix(args)
    specs = []
    for i in range(args.jobs):
        spec = dict(mix[i % len(mix)])
        spec.update(seed=1000 + i, priority=i % 3,
                    job_key=f"soak-{args.chaos_seed}-{i}")
        specs.append(spec)
    job_ids = {}
    try:
        with ServiceClient("127.0.0.1", port) as c:
            for i, spec in enumerate(specs):
                job_ids[i] = c.submit(spec)["job_id"]
    except Exception as e:
        # the kill can land while we are still submitting (e.g. SUBMIT-
        # tag rules): whatever was journaled must still recover below
        summary["submit_interrupted"] = repr(e)
    rc = proc.wait(timeout=args.timeout)
    summary["service_killed_rc"] = rc

    proc2 = spawn()
    recovered = verified = 0
    failures = []
    try:
        with ServiceClient("127.0.0.1", port) as c:
            for i, spec in enumerate(specs):
                # duplicate submit: dedups onto the recovered job (and
                # re-registers any job whose SUBMIT the kill swallowed)
                r = c.submit(spec)
                if r.get("dedup"):
                    recovered += 1
                st = c.wait(r["job_id"], timeout_s=args.timeout)
                if st["state"] != "done":
                    failures.append({"index": i, "state": st["state"],
                                     "error": st.get("error")})
                    continue
                _hdr, blob = c.result(r["job_id"])
                if blob == _proof_reference(spec):
                    verified += 1
                else:
                    failures.append({"index": i,
                                     "error": "proof bytes diverged"})
            metrics = c.metrics()
            c.shutdown_server()
        proc2.wait(timeout=30)
    finally:
        for p in (proc, proc2):
            if p.poll() is None:
                p.kill()
    ctr = metrics["counters"]
    ok = rc != 0 and verified == args.jobs and not failures
    summary.update({
        "ok": ok,
        "wall_s": round(time.time() - t0, 3),
        "verified_byte_identical": verified,
        "dedup_recovered": recovered,
        "failed": failures,
        "recovery": {k: ctr.get(k, 0) for k in
                     ("journal_replays", "jobs_recovered",
                      "jobs_recovered_finished", "checkpoint_resumes",
                      "dedup_hits", "jobs_shed")},
    })
    print(json.dumps(summary), flush=True)
    return 0 if ok else 1


def run_sdc_soak(args):
    """--sdc-rate: the result-integrity acceptance soak (ISSUE 13). A
    supervised 3-worker FLEET serves a mixed job stream through a
    fleet-backed proof service, with EVERY worker's data plane armed to
    silently corrupt computed results (`corrupt:at=data:rate=R` in each
    worker subprocess's DPT_FAULTS — random phases: MSM partials, FFT
    panels, NTT replies, round-4 eval chunks; random workers). The
    integrity plane must detect each corruption at its phase boundary,
    attribute + quarantine the lying worker (supervisor respawn +
    challenge-gated rejoin), DPT_SELF_VERIFY=1 must block anything that
    slips through, and EVERY served proof must verify client-side —
    zero unverified proofs served is the exit-code contract."""
    from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                          RemoteBackend)
    from distributed_plonk_tpu.runtime.health import LivenessTracker
    from distributed_plonk_tpu.runtime.integrity import FleetIntegrity
    from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
    from distributed_plonk_tpu.runtime.supervisor import WorkerSupervisor
    from distributed_plonk_tpu.service import ProofService, ServiceClient
    from distributed_plonk_tpu.service.metrics import Metrics

    t0 = time.time()
    fm = Metrics()  # fleet-side registry: integrity/quarantine counters
    d = Dispatcher(NetworkConfig([]), metrics=fm,
                   integrity=FleetIntegrity(
                       metrics=fm, msm_dup_rate=1.0,
                       rng=random.Random(args.chaos_seed)))
    d.tracker = LivenessTracker(0, breaker_k=2, probe_base_s=0.05,
                                probe_max_s=0.5, metrics=fm)
    mserver = d.enable_membership()
    fleet_n = 3

    def spawn_cmd(i, slot):
        # workers 1..n-1 are corrupt-armed in EVERY incarnation (repeat
        # offenders cycle quarantine -> respawn -> challenge, into the
        # flap cap if they keep lying); worker 0 stays clean — the soak
        # models a fleet with SOME bad chips, not a fleet where every
        # referee is also lying (all-corrupt is indistinguishable from
        # no ground truth and correctly ends in FAILED verdicts, which
        # the backstop test of this soak is not about)
        cmd = [sys.executable, "-m",
               "distributed_plonk_tpu.runtime.worker",
               "--join", f"127.0.0.1:{mserver.port}",
               "--listen", f"127.0.0.1:{slot.port}",
               "--backend", "python"]
        if i > 0:
            cmd = ["env",
                   f"DPT_FAULTS=corrupt:at=data:rate={args.sdc_rate}"] \
                + cmd
        return cmd

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sup = WorkerSupervisor("127.0.0.1", mserver.port, n=fleet_n,
                           metrics=fm, cwd=repo,
                           spawn_cmd=spawn_cmd).start()
    sup.attach_registry(d.membership)
    svc = None
    results = []
    obs_report = {}
    try:
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            if len(d.workers) == fleet_n \
                    and len(d.tracker.usable_set()) == fleet_n:
                break
            time.sleep(0.1)
        # fleet-backed service: one pool worker drives the one dispatcher
        # (verify-before-serve ON — the backstop under the phase checks)
        svc = ProofService(
            port=0, prover_workers=1, chaos=True, max_retries=4,
            allow_remote_shutdown=True, self_verify="1",
            backend_factory=lambda: RemoteBackend(d, dist_fft_min=64),
        ).start()
        key_cache, key_lock = {}, threading.Lock()
        mix = _job_mix(args)
        with ServiceClient("127.0.0.1", svc.port) as c:
            for i in range(args.jobs):
                spec = dict(mix[i % len(mix)])
                spec.update(seed=4000 + i)
                out = {"index": i, "spec": spec}
                try:
                    out["job_id"] = c.submit(spec)["job_id"]
                    st = c.wait(out["job_id"], timeout_s=args.timeout)
                    out["state"] = st["state"]
                    out["retries"] = st["retries"]
                    if st["state"] == "done":
                        header, blob = c.result(out["job_id"])
                        out["verified"] = _verify_result(
                            header, blob, key_cache, key_lock)
                    else:
                        out["error"] = st.get("error")
                except Exception as e:  # noqa: BLE001
                    out["error"] = repr(e)
                results.append(out)
            svc_metrics = c.metrics()
            c.shutdown_server()
        # best-effort: each CURRENT incarnation's own injected-SDC count
        # (corrupt incarnations that were already replaced undercount)
        sdc_injected = sum((h or {}).get("sdc_injected", 0)
                           for h in d.health())
        # fleet observability round trip (ISSUE 15): the soak exercises
        # the whole new plane end to end — METRICS_FETCH scrape rendered
        # to labelled series, LOG_FETCH event counts, and one PROFILE
        # capture — so a soak that passes proves an operator could have
        # WATCHED it pass
        obs_report = {}
        try:
            from distributed_plonk_tpu.obs import fleet as OF
            entries = d.fleet_metrics()
            obs_report["fleet_scraped"] = sum(
                1 for e in entries if e.get("snapshot"))
            obs_report["fleet_series"] = sum(
                1 for line in OF.render_prom(entries).splitlines()
                if line and not line.startswith("#"))
            obs_report["log_events_fetched"] = sum(
                len(l["events"]) for l in d.fetch_logs())
            # profile a worker that is actually schedulable (a corrupt
            # member may be mid-quarantine right now — that's the soak)
            usable = d.tracker.usable_set()
            meta, blob = d.profile_worker(usable[0] if usable else 0,
                                          duration_ms=100)
            obs_report["profile_ok"] = bool(blob)
            obs_report["profile_format"] = meta.get("format")
        except Exception as e:  # noqa: BLE001 - report, never fail a soak
            obs_report["error"] = repr(e)
    finally:
        sup.stop()
        try:
            d.shutdown()
        finally:
            d.pool.shutdown(wait=False)
        if svc is not None:
            svc.shutdown()
    fc = fm.snapshot()["counters"]
    sc = svc_metrics["counters"]
    verified = sum(1 for r in results if r.get("verified"))
    done = sum(1 for r in results if r.get("state") == "done")
    # THE contract: everything served verified — and nothing was served
    # without the self-verify gate having passed it
    ok = (verified == args.jobs and done == args.jobs)
    summary = {
        "mode": "sdc", "ok": ok,
        "wall_s": round(time.time() - t0, 3),
        "jobs": args.jobs, "sdc_rate": args.sdc_rate,
        "verified": verified,
        "unverified_served": done - verified,
        "failed": [r for r in results if not r.get("verified")],
        "detections": {
            "integrity_checks": fc.get("integrity_checks", 0),
            "integrity_failures": fc.get("integrity_failures", 0),
            "msm_dups": fc.get("integrity_msm_dups", 0),
            "eval_dups": fc.get("integrity_eval_dups", 0),
            "self_verify_checks": sc.get("self_verify_checks", 0),
            "self_verify_failures": sc.get("self_verify_failures", 0),
            "proofs_blocked": sc.get("proofs_blocked", 0),
            "sdc_injected_live": sdc_injected,
        },
        "quarantines": {
            "workers_quarantined": fc.get("workers_quarantined", 0),
            "membership_leaves": fc.get("membership_leaves", 0),
            "worker_respawns": fc.get("worker_respawns", 0),
            "challenges": fc.get("integrity_challenges", 0),
            "challenges_failed": fc.get("integrity_challenges_failed", 0),
            "flap_capped": fc.get("worker_flap_capped", 0),
        },
        "reproves": {
            "job_retries": sc.get("job_retries", 0),
            "fft_replans": fc.get("fleet_fft_replans", 0),
            "range_adoptions": fc.get("fleet_range_adoptions", 0),
        },
        "obs": obs_report,
    }
    print(json.dumps(summary), flush=True)
    return 0 if ok else 1


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default=None,
                    help="external server (default: self-hosted in-process)")
    ap.add_argument("--port", type=int, default=9555)
    ap.add_argument("--jobs", type=int, default=8)
    ap.add_argument("--mix", choices=("mixed", "burst"), default="mixed",
                    help="job-shape profile: 'mixed' rotates 3 toy "
                         "domains (2^5..2^9); 'burst' submits ONE small "
                         "shape for every job — same-shape traffic that "
                         "actually exercises the placement layer's "
                         "cross-job batched proving (see the summary's "
                         "batch.jobs_per_launch)")
    ap.add_argument("--workers", type=int, default=2,
                    help="pool size for the self-hosted server")
    ap.add_argument("--store-dir", default=None,
                    help="artifact store for the self-hosted server: run "
                         "twice with the same dir and the second run's "
                         "key_builds is 0 (warm start)")
    ap.add_argument("--no-kill", action="store_true")
    ap.add_argument("--kill-attempts", type=int, default=3,
                    help="re-tries if the kill races a finishing prove")
    ap.add_argument("--kill-rate", type=float, default=0.0,
                    help="chaos: probability per regular job of killing "
                         "its worker mid-prove (KILL_WORKER) — every "
                         "proof must STILL verify")
    ap.add_argument("--corrupt-rate", type=float, default=0.0,
                    help="chaos (self-hosted only): probability per round "
                         "boundary of flipping a byte in the just-saved "
                         "checkpoint artifact; the store's SHA-256 must "
                         "catch it and the retry restart cleanly")
    ap.add_argument("--delay-ms", type=float, default=0.0,
                    help="chaos (self-hosted only): slow-prover delay "
                         "injected at every round boundary")
    ap.add_argument("--chaos-seed", type=int, default=0xC4A05,
                    help="seed for rate-based chaos decisions")
    ap.add_argument("--kill-service", default=None, metavar="LABEL",
                    help="restart soak: spawn serve.py as a subprocess, "
                         "SIGKILL it at this journal occurrence (SUBMIT, "
                         "START, ROUND, ROUND2, DONE, ...), restart it on "
                         "the same journal/store, and require every job "
                         "byte-identical")
    ap.add_argument("--journal-dir", default=None,
                    help="journal dir for --kill-service (default: tmp)")
    ap.add_argument("--sdc-rate", type=float, default=None, metavar="R",
                    help="result-integrity soak (ISSUE 13): run the job "
                         "mix through a supervised 3-worker FLEET whose "
                         "workers silently corrupt computed results "
                         "(corrupt:at=data) at this rate — random phases "
                         "(MSM/FFT/NTT/eval) and workers; the summary "
                         "reports detections/quarantines/re-proves and "
                         "the exit code asserts zero unverified proofs "
                         "served")
    ap.add_argument("--traffic", default=None,
                    choices=("flat", "diurnal", "burst"),
                    help="autoscaling soak (ISSUE 16): replay a seeded "
                         "deterministic arrival-rate curve against a "
                         "supervised fleet with the closed-loop "
                         "autoscaler attached per DPT_AUTOSCALE — "
                         "'diurnal' is one compressed day (quiet "
                         "shoulders, one peak), 'burst' a step spike, "
                         "'flat' constant rate; the summary reports "
                         "per-class latency percentiles + sheds and the "
                         "controller's decision trail")
    ap.add_argument("--circuit-mix", default=None, metavar="KIND=W,...",
                    help="circuit-zoo + aggregation soak (ISSUE 17): "
                         "draw each job's kind from these weights "
                         "(kinds: toy, range, merkle, preimage, rollup), "
                         "byte-verify every served proof against a local "
                         "prove, then AGGREGATE the whole batch and "
                         "verify the ONE batched opening client-side "
                         "(a single 2-pair pairing check); e.g. "
                         "range=0.3,merkle=0.3,rollup=0.2,toy=0.2")
    ap.add_argument("--aggregate-only", action="store_true",
                    help="--circuit-mix: skip per-proof verification — "
                         "accept the batch on the aggregate's single "
                         "pairing check alone ('N proofs in, one "
                         "pairing check out')")
    ap.add_argument("--slo-mix", default="standard=1.0",
                    metavar="CLS=W,...",
                    help="SLO-class weights for --traffic arrivals, "
                         "e.g. flagship=0.1,standard=0.6,batch=0.3 "
                         "(normalized; drawn per arrival from "
                         "--chaos-seed)")
    ap.add_argument("--duration", type=float, default=20.0,
                    help="--traffic: seconds the arrival curve spans")
    ap.add_argument("--autoscale", default=None, choices=("0", "dry", "1"),
                    help="--traffic: override DPT_AUTOSCALE for the soak "
                         "(default: the environment decides)")
    ap.add_argument("--record", action="store_true",
                    help="--traffic/--circuit-mix: append the summary "
                         "(basis: host-oracle) to bench_artifacts/"
                         "trajectory.jsonl via scripts/bench_record.py")
    ap.add_argument("--timeout", type=float, default=600)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.circuit_mix is not None:
        return run_circuit_mix_soak(args)
    if args.traffic is not None:
        return run_traffic_soak(args)
    if args.kill_service is not None:
        return run_kill_service_soak(args)
    if args.sdc_rate is not None:
        return run_sdc_soak(args)
    from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
    from distributed_plonk_tpu.service import ProofService, ServiceClient

    chaos_rng = random.Random(args.chaos_seed)
    svc = None
    host = args.host
    port = args.port
    if host is None:
        # round-boundary chaos rides the new injection layer
        # (runtime/faults.py); wire-level kills keep using KILL_WORKER
        rules = []
        if args.corrupt_rate > 0:
            rules.append(Rule("corrupt_ckpt", rate=args.corrupt_rate))
        if args.delay_ms > 0:
            rules.append(Rule("delay", rate=1.0, ms=args.delay_ms,
                              plane="round"))
        faults = FaultInjector(rules, rng=chaos_rng) if rules else None
        svc = ProofService(port=0, prover_workers=args.workers, chaos=True,
                           allow_remote_shutdown=True,
                           store_dir=args.store_dir, faults=faults).start()
        host, port = "127.0.0.1", svc.port
    elif args.corrupt_rate or args.delay_ms:
        print(json.dumps({"ok": False,
                          "error": "--corrupt-rate/--delay-ms need the "
                                   "self-hosted server (they inject at "
                                   "the pool's round boundaries)"}))
        return 2

    key_cache, key_lock = {}, threading.Lock()
    results = []
    results_lock = threading.Lock()
    # chaos kill decisions drawn up front (one shared seeded rng would
    # race across submitter threads): deterministic per --chaos-seed
    kill_marks = [chaos_rng.random() < args.kill_rate
                  for _ in range(args.jobs)]

    def chaos_kill(c, job_id, out):
        """Poll until the job runs, then KILL_WORKER it — the prove must
        still finish (checkpoint resume) and verify."""
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            st = c.status(job_id)
            if st["state"] in ("done", "failed"):
                return
            if st["state"] == "running":
                try:
                    c.kill_worker(job_id=job_id)
                    out["chaos_killed"] = True
                except Exception:
                    pass  # prove outran the kill: a no-op injection
                return
            time.sleep(0.01)

    mix = _job_mix(args)

    def submitter(i):
        from distributed_plonk_tpu.trace import Tracer
        spec = dict(mix[i % len(mix)])
        spec.update(seed=1000 + i, priority=i % 3)
        out = {"index": i, "spec": spec}
        # each job is one end-to-end trace: the client's span is the
        # root, the server adopts the id (SUBMIT trace_ctx), and STATUS
        # reports how many spans the merged timeline collected — the
        # soak checks propagation worked on every single job
        tracer = Tracer(proc=f"loadgen/{i}")
        try:
            with ServiceClient(host, port) as c:
                with tracer.span("loadgen/submit_wait_verify") as root:
                    r = c.submit(spec,
                                 trace_ctx={"trace_id": tracer.trace_id,
                                            "parent_id": root})
                    out["job_id"] = r["job_id"]
                    out["trace_adopted"] = \
                        r.get("trace_id") == tracer.trace_id
                    if kill_marks[i]:
                        chaos_kill(c, out["job_id"], out)
                    st = c.wait(out["job_id"], timeout_s=args.timeout)
                out["state"] = st["state"]
                out["retries"] = st["retries"]
                out["wait_s"] = st["wait_s"]
                out["run_s"] = st["run_s"]
                out["trace_spans"] = st.get("trace_spans")
                if st["state"] == "done":
                    header, blob = c.result(out["job_id"])
                    out["verified"] = _verify_result(header, blob,
                                                     key_cache, key_lock)
                else:
                    out["error"] = st["error"]
        except Exception as e:  # noqa: BLE001 - report, don't crash the run
            out["error"] = repr(e)
        with results_lock:
            results.append(out)

    def run_kill_job(attempt):
        """Submit the kill target, kill its worker once running, wait."""
        spec = dict(_KILL_SPEC)
        spec.update(seed=31337 + attempt, priority=9)  # run soon and alone
        with ServiceClient(host, port) as c:
            job_id = c.submit(spec)["job_id"]
            deadline = time.monotonic() + args.timeout
            victim = None
            while time.monotonic() < deadline:
                st = c.status(job_id)
                if st["state"] in ("done", "failed"):
                    break
                if st["state"] == "running" and victim is None:
                    try:
                        victim = c.kill_worker(job_id=job_id)
                    except Exception:
                        # the prove outran us (finished between the STATUS
                        # poll and the kill frame); the retry loop below
                        # sees retries == 0 and tries a fresh target
                        break
                time.sleep(0.02)
            st = c.wait(job_id, timeout_s=args.timeout)
            out = {"job_id": job_id, "victim": victim,
                   "state": st["state"], "retries": st["retries"],
                   "attempts": st["attempts"]}
            if st["state"] == "done":
                header, blob = c.result(job_id)
                out["verified"] = _verify_result(header, blob,
                                                 key_cache, key_lock)
            return out

    t0 = time.time()
    threads = [threading.Thread(target=submitter, args=(i,), daemon=True)
               for i in range(args.jobs)]
    for t in threads:
        t.start()

    kill_report = None
    if not args.no_kill:
        for attempt in range(args.kill_attempts):
            kill_report = run_kill_job(attempt)
            if kill_report.get("retries", 0) >= 1 or \
                    kill_report["state"] != "done":
                break  # injected kill landed (or something real broke)
            # prove outran the kill; try again with a fresh target
    for t in threads:
        t.join(timeout=args.timeout)

    with ServiceClient(host, port) as c:
        metrics = c.metrics()
        if svc is not None:
            c.shutdown_server()

    verified = sum(1 for r in results if r.get("verified"))
    ok = verified == args.jobs
    if kill_report is not None:
        ok = ok and kill_report["state"] == "done" \
            and kill_report.get("verified") \
            and kill_report["retries"] >= 1
    ctr = metrics["counters"]
    recoveries = {
        "job_retries": ctr.get("job_retries", 0),
        "checkpoint_saves": ctr.get("checkpoint_saves", 0),
        "checkpoint_resumes": ctr.get("checkpoint_resumes", 0),
        "ckpt_corruptions_detected": ctr.get("faults_ckpt_corrupted", 0),
        "faults_injected": {k[len("faults_injected_"):]: v
                            for k, v in ctr.items()
                            if k.startswith("faults_injected_")},
    }
    batch_proves = ctr.get("batch_proves", 0)
    batch_jobs = ctr.get("batch_jobs", 0)
    summary = {
        "ok": ok,
        "wall_s": round(time.time() - t0, 3),
        "jobs": args.jobs,
        "mix": args.mix,
        "verified": verified,
        "failed": [r for r in results if not r.get("verified")],
        "kill": kill_report,
        # placement + cross-job batching achieved by this run's traffic:
        # jobs_per_launch is the amortization the burst profile exists
        # to demonstrate (1.0 means nothing ever batched)
        "batch": {
            "proves": batch_proves,
            "jobs": batch_jobs,
            "jobs_per_launch": (round(batch_jobs / batch_proves, 2)
                                if batch_proves else None),
            "member_kills": ctr.get("batch_member_kills", 0),
            "placement": {k: v for k, v in sorted(ctr.items())
                          if k.startswith("placement_")},
        },
        # round-pipeline fill achieved by this run's traffic (achieved
        # depth, per-round stage stalls + device-idle estimates)
        "pipeline": _pipeline_summary(metrics),
        # chaos soak report: what was injected, what the service survived
        # (every proof above still had to verify for ok=true)
        "chaos": {
            "kill_rate": args.kill_rate,
            "corrupt_rate": args.corrupt_rate,
            "delay_ms": args.delay_ms,
            "kills_marked": sum(kill_marks),
            "kills_landed": sum(1 for r in results if r.get("chaos_killed")),
            "recoveries": recoveries,
        },
        # tracing: every job's timeline must have collected spans under
        # the client-supplied trace id (propagation is part of the soak)
        "trace": {
            "adopted": sum(1 for r in results if r.get("trace_adopted")),
            "spans_total": sum(r.get("trace_spans") or 0 for r in results),
            "spans_recorded":
                ctr.get("trace_spans_recorded", 0),
        },
        # observability plane exercised by this run (structured logs are
        # recorded service-side for every shed/retry/verdict; the full
        # fleet scrape/profile round trip lives in the --sdc-rate soak)
        "obs": {
            "log_events_recorded": ctr.get("log_events", 0),
        },
        # key_builds == bucket_misses: 0 on a warm-store rerun of the same
        # shape mix (the ISSUE-2 acceptance check; see --store-dir)
        "key_builds": metrics["counters"].get("bucket_misses", 0),
        "key_disk_hits": metrics["counters"].get("bucket_disk_hits", 0),
        "metrics": {
            "counters": metrics["counters"],
            "gauges": metrics["gauges"],
            "queue_wait": metrics["histograms"].get("job_wait"),
            "rounds": {k: v for k, v in metrics["histograms"].items()
                       if k.startswith("prove_round/")},
            "throughput_jobs_per_s": metrics["throughput_jobs_per_s"],
        },
    }
    print(json.dumps(summary), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
