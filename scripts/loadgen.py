#!/usr/bin/env python3
"""Concurrent load generator + fault injector for the proof service.

    JAX_PLATFORMS=cpu python scripts/loadgen.py            # self-hosted run
    python scripts/loadgen.py --host 127.0.0.1 --port 9555 # external server
    python scripts/loadgen.py --jobs 12 --no-kill
    python scripts/loadgen.py --kill-rate 0.5 --corrupt-rate 0.3 \
        --delay-ms 5 --store-dir /tmp/s                    # chaos soak

Default run: spins up an in-process ProofService (chaos mode, host oracle
backend), then N submitter threads (default 8, mixed toy domain sizes
2^5..2^9) each submit over real TCP, wait, fetch, and verify their proof
client-side (keys rebuilt locally from the spec — same deterministic test
SRS). Unless --no-kill, one extra large job is the kill target: as soon as
its STATUS says running, KILL_WORKER is sent for it; the worker dies at
the next round boundary, the pool respawns a replacement, and the job
must finish DONE with retries >= 1 (checkpoint resume, not restart).

Prints one JSON summary line; exit code 0 iff every proof verified and
the injected kill (if any) produced a visible retry.
"""

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# mixed shapes: domains 32 / 128 / 256 (toy gate chains)
_MIX = [{"kind": "toy", "gates": g} for g in (16, 60, 150)]
_KILL_SPEC = {"kind": "toy", "gates": 300}  # n=512: wide kill window


def _verify_result(header, blob, key_cache, lock):
    from distributed_plonk_tpu.proof_io import deserialize_proof
    from distributed_plonk_tpu.service.jobs import (JobSpec,
                                                    build_bucket_keys,
                                                    shape_key)
    from distributed_plonk_tpu.verifier import verify

    spec = JobSpec.from_wire(header["spec"])
    with lock:
        key = shape_key(spec)
        if key not in key_cache:
            key_cache[key] = build_bucket_keys(spec)[2]
        vk = key_cache[key]
    pub = [int(x, 16) for x in header["public_input"]]
    return verify(vk, pub, deserialize_proof(blob), rng=random.Random(1))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default=None,
                    help="external server (default: self-hosted in-process)")
    ap.add_argument("--port", type=int, default=9555)
    ap.add_argument("--jobs", type=int, default=8)
    ap.add_argument("--workers", type=int, default=2,
                    help="pool size for the self-hosted server")
    ap.add_argument("--store-dir", default=None,
                    help="artifact store for the self-hosted server: run "
                         "twice with the same dir and the second run's "
                         "key_builds is 0 (warm start)")
    ap.add_argument("--no-kill", action="store_true")
    ap.add_argument("--kill-attempts", type=int, default=3,
                    help="re-tries if the kill races a finishing prove")
    ap.add_argument("--kill-rate", type=float, default=0.0,
                    help="chaos: probability per regular job of killing "
                         "its worker mid-prove (KILL_WORKER) — every "
                         "proof must STILL verify")
    ap.add_argument("--corrupt-rate", type=float, default=0.0,
                    help="chaos (self-hosted only): probability per round "
                         "boundary of flipping a byte in the just-saved "
                         "checkpoint artifact; the store's SHA-256 must "
                         "catch it and the retry restart cleanly")
    ap.add_argument("--delay-ms", type=float, default=0.0,
                    help="chaos (self-hosted only): slow-prover delay "
                         "injected at every round boundary")
    ap.add_argument("--chaos-seed", type=int, default=0xC4A05,
                    help="seed for rate-based chaos decisions")
    ap.add_argument("--timeout", type=float, default=600)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
    from distributed_plonk_tpu.service import ProofService, ServiceClient

    chaos_rng = random.Random(args.chaos_seed)
    svc = None
    host = args.host
    port = args.port
    if host is None:
        # round-boundary chaos rides the new injection layer
        # (runtime/faults.py); wire-level kills keep using KILL_WORKER
        rules = []
        if args.corrupt_rate > 0:
            rules.append(Rule("corrupt_ckpt", rate=args.corrupt_rate))
        if args.delay_ms > 0:
            rules.append(Rule("delay", rate=1.0, ms=args.delay_ms,
                              plane="round"))
        faults = FaultInjector(rules, rng=chaos_rng) if rules else None
        svc = ProofService(port=0, prover_workers=args.workers, chaos=True,
                           allow_remote_shutdown=True,
                           store_dir=args.store_dir, faults=faults).start()
        host, port = "127.0.0.1", svc.port
    elif args.corrupt_rate or args.delay_ms:
        print(json.dumps({"ok": False,
                          "error": "--corrupt-rate/--delay-ms need the "
                                   "self-hosted server (they inject at "
                                   "the pool's round boundaries)"}))
        return 2

    key_cache, key_lock = {}, threading.Lock()
    results = []
    results_lock = threading.Lock()
    # chaos kill decisions drawn up front (one shared seeded rng would
    # race across submitter threads): deterministic per --chaos-seed
    kill_marks = [chaos_rng.random() < args.kill_rate
                  for _ in range(args.jobs)]

    def chaos_kill(c, job_id, out):
        """Poll until the job runs, then KILL_WORKER it — the prove must
        still finish (checkpoint resume) and verify."""
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            st = c.status(job_id)
            if st["state"] in ("done", "failed"):
                return
            if st["state"] == "running":
                try:
                    c.kill_worker(job_id=job_id)
                    out["chaos_killed"] = True
                except Exception:
                    pass  # prove outran the kill: a no-op injection
                return
            time.sleep(0.01)

    def submitter(i):
        spec = dict(_MIX[i % len(_MIX)])
        spec.update(seed=1000 + i, priority=i % 3)
        out = {"index": i, "spec": spec}
        try:
            with ServiceClient(host, port) as c:
                out["job_id"] = c.submit(spec)["job_id"]
                if kill_marks[i]:
                    chaos_kill(c, out["job_id"], out)
                st = c.wait(out["job_id"], timeout_s=args.timeout)
                out["state"] = st["state"]
                out["retries"] = st["retries"]
                out["wait_s"] = st["wait_s"]
                out["run_s"] = st["run_s"]
                if st["state"] == "done":
                    header, blob = c.result(out["job_id"])
                    out["verified"] = _verify_result(header, blob,
                                                     key_cache, key_lock)
                else:
                    out["error"] = st["error"]
        except Exception as e:  # noqa: BLE001 - report, don't crash the run
            out["error"] = repr(e)
        with results_lock:
            results.append(out)

    def run_kill_job(attempt):
        """Submit the kill target, kill its worker once running, wait."""
        spec = dict(_KILL_SPEC)
        spec.update(seed=31337 + attempt, priority=9)  # run soon and alone
        with ServiceClient(host, port) as c:
            job_id = c.submit(spec)["job_id"]
            deadline = time.monotonic() + args.timeout
            victim = None
            while time.monotonic() < deadline:
                st = c.status(job_id)
                if st["state"] in ("done", "failed"):
                    break
                if st["state"] == "running" and victim is None:
                    try:
                        victim = c.kill_worker(job_id=job_id)
                    except Exception:
                        # the prove outran us (finished between the STATUS
                        # poll and the kill frame); the retry loop below
                        # sees retries == 0 and tries a fresh target
                        break
                time.sleep(0.02)
            st = c.wait(job_id, timeout_s=args.timeout)
            out = {"job_id": job_id, "victim": victim,
                   "state": st["state"], "retries": st["retries"],
                   "attempts": st["attempts"]}
            if st["state"] == "done":
                header, blob = c.result(job_id)
                out["verified"] = _verify_result(header, blob,
                                                 key_cache, key_lock)
            return out

    t0 = time.time()
    threads = [threading.Thread(target=submitter, args=(i,), daemon=True)
               for i in range(args.jobs)]
    for t in threads:
        t.start()

    kill_report = None
    if not args.no_kill:
        for attempt in range(args.kill_attempts):
            kill_report = run_kill_job(attempt)
            if kill_report.get("retries", 0) >= 1 or \
                    kill_report["state"] != "done":
                break  # injected kill landed (or something real broke)
            # prove outran the kill; try again with a fresh target
    for t in threads:
        t.join(timeout=args.timeout)

    with ServiceClient(host, port) as c:
        metrics = c.metrics()
        if svc is not None:
            c.shutdown_server()

    verified = sum(1 for r in results if r.get("verified"))
    ok = verified == args.jobs
    if kill_report is not None:
        ok = ok and kill_report["state"] == "done" \
            and kill_report.get("verified") \
            and kill_report["retries"] >= 1
    ctr = metrics["counters"]
    recoveries = {
        "job_retries": ctr.get("job_retries", 0),
        "checkpoint_saves": ctr.get("checkpoint_saves", 0),
        "checkpoint_resumes": ctr.get("checkpoint_resumes", 0),
        "ckpt_corruptions_detected": ctr.get("faults_ckpt_corrupted", 0),
        "faults_injected": {k[len("faults_injected_"):]: v
                            for k, v in ctr.items()
                            if k.startswith("faults_injected_")},
    }
    summary = {
        "ok": ok,
        "wall_s": round(time.time() - t0, 3),
        "jobs": args.jobs,
        "verified": verified,
        "failed": [r for r in results if not r.get("verified")],
        "kill": kill_report,
        # chaos soak report: what was injected, what the service survived
        # (every proof above still had to verify for ok=true)
        "chaos": {
            "kill_rate": args.kill_rate,
            "corrupt_rate": args.corrupt_rate,
            "delay_ms": args.delay_ms,
            "kills_marked": sum(kill_marks),
            "kills_landed": sum(1 for r in results if r.get("chaos_killed")),
            "recoveries": recoveries,
        },
        # key_builds == bucket_misses: 0 on a warm-store rerun of the same
        # shape mix (the ISSUE-2 acceptance check; see --store-dir)
        "key_builds": metrics["counters"].get("bucket_misses", 0),
        "key_disk_hits": metrics["counters"].get("bucket_disk_hits", 0),
        "metrics": {
            "counters": metrics["counters"],
            "gauges": metrics["gauges"],
            "queue_wait": metrics["histograms"].get("job_wait"),
            "rounds": {k: v for k, v in metrics["histograms"].items()
                       if k.startswith("prove_round/")},
            "throughput_jobs_per_s": metrics["throughput_jobs_per_s"],
        },
    }
    print(json.dumps(summary), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
