#!/bin/bash
# Stall watchdog for long chip runs: the relay's compile endpoint
# occasionally never replies (client blocks forever in tcp_recvmsg with
# no timeout available at our layer). Restart the run when its CPU time
# freezes; the persistent XLA compile cache makes every attempt ratchet
# past the shapes already compiled.
#
# Usage: run_watchdog.sh <stall_seconds> <max_attempts> <logfile> -- cmd...
set -u
if [[ $# -lt 5 || ${4:-} != "--" ]]; then
  echo "usage: run_watchdog.sh <stall_s> <max_attempts> <logfile> -- cmd..." >&2
  exit 2
fi
STALL=$1; MAX=$2; LOG=$3; shift 4

for attempt in $(seq 1 "$MAX"); do
  echo "[watchdog] attempt $attempt: $*" >> "$LOG"
  setsid "$@" >> "$LOG" 2>&1 &
  PID=$!
  last_cpu=""
  last_change=$(date +%s)
  while kill -0 "$PID" 2>/dev/null; do
    sleep 30
    cpu=$(awk '{print $14+$15}' "/proc/$PID/stat" 2>/dev/null || echo "")
    now=$(date +%s)
    if [[ -n "$cpu" && "$cpu" != "$last_cpu" ]]; then
      last_cpu=$cpu
      last_change=$now
    elif (( now - last_change > STALL )); then
      echo "[watchdog] stall: no CPU progress for ${STALL}s, killing $PID" >> "$LOG"
      kill -9 -- "-$PID" 2>/dev/null || kill -9 "$PID" 2>/dev/null
      wait "$PID" 2>/dev/null
      break
    fi
  done
  if wait "$PID" 2>/dev/null; then
    echo "[watchdog] attempt $attempt succeeded" >> "$LOG"
    exit 0
  fi
  echo "[watchdog] attempt $attempt ended (rc != 0 or killed)" >> "$LOG"
done
echo "[watchdog] giving up after $MAX attempts" >> "$LOG"
exit 1
