#!/bin/bash
# Stall watchdog for long chip runs: the relay's compile endpoint
# occasionally never replies (client blocks forever in tcp_recvmsg with
# no timeout available at our layer). Restart the run when its CPU time
# freezes; the persistent XLA compile cache makes every attempt ratchet
# past the shapes already compiled.
#
# Usage: run_watchdog.sh <stall_seconds> <max_attempts> <logfile> -- cmd...
set -u
if [[ $# -lt 5 || ${4:-} != "--" ]]; then
  echo "usage: run_watchdog.sh <stall_s> <max_attempts> <logfile> -- cmd..." >&2
  exit 2
fi
STALL=$1; MAX=$2; LOG=$3; shift 4

for attempt in $(seq 1 "$MAX"); do
  echo "[watchdog] attempt $attempt: $*" >> "$LOG"
  setsid "$@" >> "$LOG" 2>&1 &
  PID=$!
  last_cpu=""
  last_change=$(date +%s)
  stalled=""
  while kill -0 "$PID" 2>/dev/null; do
    sleep 30
    # sum utime+stime over the whole process GROUP (setsid above made
    # $PID its own pgrp): a parent blocked in wait/recv while children
    # do the work must not read as stalled. Empty sum (group already
    # gone) -> loop top's kill -0 exits next round.
    cpu=$(awk -v pg="$PID" '$5 == pg {s += $14 + $15} END {print s+0}' \
          /proc/[0-9]*/stat 2>/dev/null || echo "")
    kill -0 "$PID" 2>/dev/null || break
    now=$(date +%s)
    if [[ -n "$cpu" && "$cpu" != "$last_cpu" ]]; then
      last_cpu=$cpu
      last_change=$now
    elif (( now - last_change > STALL )); then
      echo "[watchdog] stall: no CPU progress for ${STALL}s, killing $PID" >> "$LOG"
      stalled=1
      kill -9 -- "-$PID" 2>/dev/null || kill -9 "$PID" 2>/dev/null
      wait "$PID" 2>/dev/null
      break
    fi
  done
  if [[ -z "$stalled" ]] && wait "$PID" 2>/dev/null; then
    echo "[watchdog] attempt $attempt succeeded" >> "$LOG"
    exit 0
  fi
  echo "[watchdog] attempt $attempt ended (rc != 0 or killed)" >> "$LOG"
done
echo "[watchdog] giving up after $MAX attempts" >> "$LOG"
exit 1
