#!/usr/bin/env python3
"""Kernel microbenchmarks: mont_mul / NTT throughput on the current platform.

Usage: python scripts/kernel_bench.py [fr|fq|ntt|all]
Honors DPT_FIELD_MUL (f32 default / u32 fallback) — run twice to compare the
MXU-era multiplier against the round-2 integer path. Timing syncs via a
small device->host transfer (block_until_ready is a no-op through the axon
tunnel; device execution is in-order, so fetching the last output fences
the loop).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def _sync(x):
    np.asarray(x[:1, :1] if x.ndim >= 2 else x[:1])


def bench_mont_mul(spec_name, n, chain=8, reps=3):
    import jax
    import jax.numpy as jnp
    from distributed_plonk_tpu.backend import field_jax as FJ

    spec = FJ.FR if spec_name == "fr" else FJ.FQ

    @jax.jit
    def f(a, b):
        # dependent chain: defeats dead-code elimination and amortizes
        # dispatch over `chain` multiplies
        acc = a
        for _ in range(chain):
            acc = FJ.mont_mul(spec, acc, b)
        return acc

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 1 << 16, size=(spec.n_limbs, n),
                                 dtype=np.uint32))
    b = jnp.asarray(rng.integers(0, 1 << 16, size=(spec.n_limbs, n),
                                 dtype=np.uint32))
    _sync(f(a, b))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(a, b)
    _sync(out)
    dt = (time.perf_counter() - t0) / reps
    per_s = n * chain / dt
    return {"kernel": f"mont_mul_{spec_name}", "n": n, "chain": chain,
            "s_per_call": round(dt, 5), "mul_per_s": round(per_s),
            "ns_per_mul": round(1e9 / per_s, 2)}


def bench_msm(log_n, reps=2):
    """Warm MSM at 2^log_n points (distinct-base tiling like the
    reference's micro-test, src/dispatcher.rs:188-196)."""
    import random
    from distributed_plonk_tpu import curve as C
    from distributed_plonk_tpu.constants import R_MOD
    from distributed_plonk_tpu.backend.msm_jax import MsmContext

    n = 1 << log_n
    rng = random.Random(3)
    distinct = [C.g1_mul(C.G1_GEN, rng.randrange(1, R_MOD))
                for _ in range(1 << 11)]
    bases = (distinct * (n // len(distinct) + 1))[:n]
    ctx = MsmContext(bases)
    scalars = [rng.randrange(R_MOD) for _ in range(n)]
    ctx.msm(scalars)  # compile + warm + adaptive-chunk calibration
    t0 = time.perf_counter()
    for _ in range(reps):
        ctx.msm(scalars)
    dt = (time.perf_counter() - t0) / reps
    return {"kernel": f"msm_2p{log_n}", "s": round(dt, 3),
            "points_per_s": round(n / dt),
            "adds_per_s_calibrated": {
                str(k): v for k, v in MsmContext._measured_adds_per_s.items()}}


def bench_ntt(log_n, reps=3):
    from distributed_plonk_tpu.backend import ntt_jax

    n = 1 << log_n
    plan = ntt_jax.get_plan(n)
    kernel = plan.kernel()
    rng = np.random.default_rng(2)
    v = rng.integers(0, 1 << 16, size=(16, n), dtype=np.uint32)
    _sync(kernel(v))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = kernel(v)
    _sync(out)
    dt = (time.perf_counter() - t0) / reps
    return {"kernel": f"ntt_2p{log_n}", "s": round(dt, 5),
            "elements_per_s": round(n / dt)}


def main():
    what = sys.argv[1] if len(sys.argv) > 1 else "all"
    from distributed_plonk_tpu.backend import field_jax as FJ
    out = {"mul_path": FJ._MUL_MODE}  # the resolved mode, not a guess
    import jax
    out["platform"] = jax.devices()[0].platform
    if what in ("fr", "all"):
        out["fr"] = bench_mont_mul("fr", 1 << 20)
    if what in ("fq", "all"):
        out["fq"] = bench_mont_mul("fq", 1 << 18)
    if what in ("ntt", "all"):
        out["ntt"] = bench_ntt(20)
    if what in ("msm", "all"):
        out["msm_2p16"] = bench_msm(16)
        out["msm_2p20"] = bench_msm(20, reps=1)
    if what == "msm24":
        # BASELINE config #5 (2^24 streaming MSM): the chunked pipeline
        # streams ~4.6 GB of bases through per-call-budget device launches
        out["msm_2p24"] = bench_msm(24, reps=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
