#!/usr/bin/env python
"""Run a self-healing local worker fleet: membership + supervision.

Starts a Dispatcher that OWNS a membership registry (served over
JOIN/LEAVE/ROSTER on --member-port), then a WorkerSupervisor that spawns
N worker subprocesses with `--join` — each announces itself, receives
its fleet index + epoch-numbered roster, and is schedulable from that
moment. Kill a worker (or pass --kill-after for a scripted SIGKILL):
the supervisor respawns it with jittered backoff, it re-joins IN PLACE,
warm-rejoins from store-serving peers, and the fleet heals back to full
width — the operational face of ISSUE 12's self-healing fleet.

Examples:
    python scripts/fleet.py --workers 3                      # idle fleet
    python scripts/fleet.py --workers 3 --prove              # heal demo:
        ... --kill 1 --kill-after 0.2                        # SIGKILL w1
        mid-prove, supervisor respawns, proof byte-checked vs host oracle
    python scripts/fleet.py --workers 3 --store-root /tmp/s  # with
        per-worker stores (STORE_FETCH peers; warm rejoin on respawn)

DPT_FAULTS works here too, including the proc plane:
    DPT_FAULTS="kill:at=proc:tag=FFT1:worker=1" python scripts/fleet.py \
        --workers 3 --prove
"""

import argparse
import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,  # noqa: E402
                                                      RemoteBackend)
from distributed_plonk_tpu.runtime.faults import FaultInjector  # noqa: E402
from distributed_plonk_tpu.runtime.netconfig import NetworkConfig  # noqa: E402
from distributed_plonk_tpu.runtime.supervisor import WorkerSupervisor  # noqa: E402
from distributed_plonk_tpu.service.metrics import Metrics  # noqa: E402

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))


def wait_width(dispatcher, n, timeout_s=60):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if len(dispatcher.workers) >= n and \
                len(dispatcher.tracker.usable_set()) >= n:
            return True
        time.sleep(0.1)
    return False


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--backend", default="python",
                    choices=("python", "jax"))
    ap.add_argument("--member-host", default="127.0.0.1")
    ap.add_argument("--member-port", type=int, default=0)
    ap.add_argument("--store-root", default=None,
                    help="per-worker store dirs under this root "
                         "(workers serve STORE_FETCH + warm-rejoin)")
    ap.add_argument("--prove", action="store_true",
                    help="run one distributed toy prove and byte-check "
                         "it against the host oracle")
    ap.add_argument("--kill", type=int, default=None, metavar="SLOT",
                    help="SIGKILL this supervised slot after --kill-after")
    ap.add_argument("--kill-after", type=float, default=0.5)
    ap.add_argument("--watch-s", type=float, default=None,
                    help="idle-serve this long (default: forever without "
                         "--prove)")
    ap.add_argument("--obs-dump", action="store_true",
                    help="before exiting, print one fleet observability "
                         "scrape (METRICS_FETCH per member: served "
                         "counters, kernel gauges, log-ring depth) — the "
                         "dispatcher-side pane of ISSUE 15")
    args = ap.parse_args()

    metrics = Metrics()
    faults = FaultInjector.from_env(metrics=metrics)
    d = Dispatcher(NetworkConfig([]), metrics=metrics, faults=faults)
    mserver = d.enable_membership(args.member_host, args.member_port)
    store_dirs = None
    if args.store_root:
        store_dirs = [os.path.join(args.store_root, f"worker{i}")
                      for i in range(args.workers)]
    sup = WorkerSupervisor(args.member_host, mserver.port, n=args.workers,
                           backend=args.backend, store_dirs=store_dirs,
                           metrics=metrics, cwd=REPO).start()
    # integrity quarantine -> kill the lying (but alive) process so the
    # respawn re-enters through the challenge-gated JOIN
    sup.attach_registry(d.membership)
    if faults is not None:
        faults.proc_kill_cb = sup.proc_killer(d)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        if not wait_width(d, args.workers):
            print(json.dumps({"error": "fleet did not reach width",
                              "roster": d.membership.roster()}))
            return 1
        print(json.dumps({"fleet_up": True, "member_port": mserver.port,
                          "roster": d.membership.roster()}))

        if args.kill is not None:
            threading.Timer(args.kill_after,
                            lambda: sup.kill(args.kill)).start()

        if args.prove:
            import random
            from distributed_plonk_tpu.backend.python_backend import \
                PythonBackend
            from distributed_plonk_tpu.prover import prove
            from distributed_plonk_tpu.service.jobs import (JobSpec,
                                                            build_circuit,
                                                            build_bucket_keys)
            spec = JobSpec.from_wire({"kind": "toy", "gates": 16, "seed": 7})
            ckt = build_circuit(spec)
            _srs, pk, _vk = build_bucket_keys(spec)
            want = prove(random.Random(1), ckt, pk, PythonBackend())
            t0 = time.perf_counter()
            got = prove(random.Random(1), ckt, pk,
                        RemoteBackend(d, dist_fft_min=ckt.n))
            healed = wait_width(d, args.workers, timeout_s=30)
            print(json.dumps({
                "prove_ok": got.opening_proof == want.opening_proof,
                "prove_s": round(time.perf_counter() - t0, 3),
                "healed_to_full_width": healed,
                "epoch": d.epoch,
                "counters": {k: v for k, v in sorted(
                    metrics.snapshot()["counters"].items())},
            }))
        else:
            stop.wait(args.watch_s)
        if args.obs_dump:
            entries = d.fleet_metrics()
            print(json.dumps({"fleet_obs": [
                {"index": e["index"], "addr": e["addr"],
                 "usable": e["usable"], "suspect": e["suspect"],
                 "served": sum(
                     v for k, v in ((e["snapshot"] or {})
                                    .get("counters") or {}).items()
                     if k.startswith("served_")),
                 "log_seq": (e["snapshot"] or {}).get("log_seq", 0)}
                for e in entries]}))
        return 0
    finally:
        sup.stop()
        d.shutdown()


if __name__ == "__main__":
    sys.exit(main())
