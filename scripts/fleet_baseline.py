#!/usr/bin/env python3
"""Config #2 baseline: the v1 workload proved over a local CPU worker
fleet (BASELINE.json config "2^20 circuit, 4 CPU workers over capnp" —
scaled to the workload size given on the CLI; the reference's analog is
test2 over its 2-host LAN, /root/reference/src/dispatcher2.rs:1273-1295).

Spawns N worker daemons (JAX CPU backend) on localhost, preprocesses
locally, prove()s through RemoteBackend so every NTT/MSM rides the fleet
protocol, verifies, and emits one JSON line.

Usage: python scripts/fleet_baseline.py [--workers 4] [--height 32]
           [--proofs 1] [--worker-timeout S] [--out FILE]
"""

import argparse
import json
import os
import random
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def scrubbed_cpu_env():
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--height", type=int, default=32)
    ap.add_argument("--proofs", type=int, default=1)
    ap.add_argument("--worker-timeout", type=float, default=600,
                    help="seconds to wait for the fleet to come up (4 jax"
                         " imports on one contended core take minutes)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    # the dispatcher side must also be CPU-pinned: RemoteBackend runs the
    # round math locally between fleet calls (capture the scrubbed copy
    # BEFORE clearing — scrubbed_cpu_env reads os.environ)
    scrubbed = scrubbed_cpu_env()
    os.environ.clear()
    os.environ.update(scrubbed)

    from distributed_plonk_tpu import kzg
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.verifier import verify
    from distributed_plonk_tpu.workload import generate_circuit
    from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
    from distributed_plonk_tpu.runtime.dispatcher import Dispatcher, RemoteBackend
    from distributed_plonk_tpu.trace import Tracer

    res = {"workers": args.workers, "height": args.height,
           "num_proofs": args.proofs}
    t0 = time.perf_counter()
    ckt, _ = generate_circuit(rng=random.Random(11), height=args.height,
                              num_proofs=args.proofs)
    res["n"] = ckt.n
    res["log2_n"] = ckt.n.bit_length() - 1
    res["circuit_gen_s"] = round(time.perf_counter() - t0, 3)
    print(f"[fleet] circuit n = 2^{res['log2_n']}", file=sys.stderr)

    t0 = time.perf_counter()
    srs = kzg.universal_setup(ckt.n + 3, rng=random.Random(12))
    pk, vk = kzg.preprocess(srs, ckt)
    res["setup_preprocess_host_s"] = round(time.perf_counter() - t0, 3)
    print(f"[fleet] host setup+preprocess {res['setup_preprocess_host_s']}s",
          file=sys.stderr)

    def free_port():
        # bind-0-and-read-back (same trick as tests/test_multihost.py):
        # beats a pid-derived fixed scheme, which fails only after the
        # full worker-timeout when a computed port is already bound
        import socket
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    cfg_path = os.path.join(REPO, f".fleet_baseline_{os.getpid()}.json")
    cfg = NetworkConfig(
        [f"127.0.0.1:{free_port()}" for _ in range(args.workers)])
    cfg.save(cfg_path)
    logs = []
    procs = []
    try:
        for i in range(args.workers):
            log = open(os.path.join(REPO, f".fleet_worker_{i}.log"), "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "distributed_plonk_tpu.runtime.worker",
                 str(i), cfg_path, "--backend", "jax"],
                cwd=REPO, env=scrubbed_cpu_env(), stdout=log, stderr=log))
        d = None
        deadline = time.time() + args.worker_timeout
        while time.time() < deadline:
            try:
                d = Dispatcher(cfg)
                d.ping()
                break
            except (ConnectionError, OSError):
                time.sleep(0.5)
                d = None
        assert d is not None, "workers did not come up"
        print("[fleet] workers up", file=sys.stderr)

        be = RemoteBackend(d)
        t0 = time.perf_counter()
        prove(random.Random(13), ckt, pk, be)
        res["prove_cold_s"] = round(time.perf_counter() - t0, 3)
        tr = Tracer()
        t0 = time.perf_counter()
        proof = prove(random.Random(13), ckt, pk, be, tracer=tr)
        res["prove_s"] = round(time.perf_counter() - t0, 3)
        res["rounds"] = {k: round(v, 3) for k, v in tr.totals(1).items()}
        t0 = time.perf_counter()
        ok = verify(vk, ckt.public_input(), proof, rng=random.Random(14))
        res["verify_s"] = round(time.perf_counter() - t0, 3)
        res["verified"] = bool(ok)
        assert ok
        d.shutdown()
        for p in procs:
            p.wait(timeout=15)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
        try:
            os.remove(cfg_path)
        except OSError:
            pass

    out = json.dumps(res)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    print(out)


if __name__ == "__main__":
    main()
