#!/usr/bin/env python3
"""Benchmark harness: prints ONE JSON line for the driver — always.

Headline metric: end-to-end prover wall-clock on the reference's v1
workload (height-32 Merkle membership, 1 proof => 2^13 domain,
/root/reference/src/dispatcher.rs:1064-1070), device backend, warm (the
steady-state number — the reference's Rust binaries have no jit phase, so
cold-compile time is excluded from the comparison and reported separately).

vs_baseline: measured speedup over this repo's own host CPU oracle (the
pure-Python v1-prover analog) on the SAME machine and workload. See
BASELINE.md for the arkworks-class CPU context.

Resilience contract (round-2 failure: BENCH_r02.json was rc=1 with a raw
axon-UNAVAILABLE traceback because one jnp call died): the outer process
NEVER imports jax. It probes the TPU with a short subprocess (one retry),
runs the measurement in a subprocess under a wall-clock budget, and if
anything fails — dead relay, mid-run crash, timeout — it still emits one
valid JSON line with "degraded": true, whatever partial measurements the
inner run recorded, and rc=0.

Env knobs:
  DPT_BENCH_FAST=1       skip the prove (NTT metric becomes the headline)
  DPT_BENCH_LOG_N        NTT/MSM size (default 20)
  DPT_BENCH_PROVE_HOST=1 (re)measure the host-oracle prove baseline too
  DPT_BENCH_TIMEOUT      inner measurement budget, seconds (default 3000)
  DPT_BENCH_PROBE_TIMEOUT  per-probe budget, seconds (default 150)
  DPT_BENCH_PIPELINE_TIMEOUT  pipeline A/B budget, seconds (default 1500;
                           a cold XLA compile-cache fill is ~450 s)
"""

import json
import os
import random
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

LOG_N = int(os.environ.get("DPT_BENCH_LOG_N", "20"))
N = 1 << LOG_N
_BASELINE_CACHE = os.path.join(REPO, ".bench_host_baseline.json")
_PARTIAL = os.path.join(REPO, ".bench_partial.json")
# measured once on the build host (1-core VM driving the TPU tunnel) and
# recorded here so a fresh bench host need not redo a ~30-minute pure-Python
# prove; a live measurement (DPT_BENCH_PROVE_HOST=1) overrides it
_RECORDED_HOST = {
    "ntt_2p20_host_s": 33.03,       # pure-Python radix-2 FFT, 2^20
    "prove_2p13_host_s": 76.9,      # pure-Python 5-round prove, same workload
}
# round-4 chip measurements (BASELINE.md, scale_2p13_r04.json) — the
# degraded-mode fallback values when the TPU is unreachable at capture time
_RECORDED_DEVICE = {
    "prove_2p13_wall_clock_s": 17.128,
    "prove_2p13_vs_host_oracle": 4.49,
}


def _cache():
    if os.path.exists(_BASELINE_CACHE):
        with open(_BASELINE_CACHE) as f:
            return json.load(f)
    return {}

def _cache_put(key, value):
    c = _cache()
    c[key] = value
    with open(_BASELINE_CACHE, "w") as f:
        json.dump(c, f)


def _partial_put(extra):
    """Inner run checkpoints each completed stage so a mid-run crash still
    leaves measured numbers for the outer process to report."""
    try:
        with open(_PARTIAL, "w") as f:
            json.dump(extra, f)
    except OSError:
        pass


def host_ntt_seconds():
    key = f"ntt_2p{LOG_N}_host_s"
    c = _cache()
    if key in c:
        return c[key]
    if LOG_N == 20 and _RECORDED_HOST["ntt_2p20_host_s"]:
        return _RECORDED_HOST["ntt_2p20_host_s"]
    from distributed_plonk_tpu import poly as P
    from distributed_plonk_tpu.constants import R_MOD

    rng = random.Random(1)
    values = [rng.randrange(R_MOD) for _ in range(N)]
    t0 = time.perf_counter()
    P.fft(P.Domain(N), values)
    host_s = time.perf_counter() - t0
    _cache_put(key, host_s)
    return host_s


def _ntt_stage_breakdown(plan, radix, reps=5):
    """Per-stage wall-clock of the NTT core's component bodies at
    (16, 1, n): lets a future MFU regression be pinned on a specific
    stage (radix-4 scan body / radix-2 stage or fixup / output
    bit-reversal gather) instead of just the end-to-end number."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from distributed_plonk_tpu.backend import ntt_jax as NJ

    rng = np.random.default_rng(4)
    v = jnp.asarray(rng.integers(0, 1 << 16, size=(16, 1, plan.n),
                                 dtype=np.uint32))
    pow_tab = jnp.asarray(plan.pow_fwd)

    def timed(fn, *args):
        out = fn(*args)
        np.asarray(out[:, :, :1])  # compile + warm, then fence the loop
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        np.asarray(out[:, :, :1])
        return round((time.perf_counter() - t0) / reps, 6)

    out = {}
    if radix == 4 and plan.exps4 is not None:
        e = jnp.asarray(plan.exps4[plan.exps4.shape[0] // 2])
        out["radix4_stage_s"] = timed(jax.jit(NJ._stage4), v, e, pow_tab)
        out["radix4_stages"] = int(plan.exps4.shape[0])
        if plan.fix_exps is not None:
            out["fixup_stage_s"] = timed(
                jax.jit(NJ._stage2), v, jnp.asarray(plan.fix_exps), pow_tab)
    else:
        e = jnp.asarray(plan.exps[plan.log_n // 2])
        out["radix2_stage_s"] = timed(jax.jit(NJ._stage2), v, e, pow_tab)
        out["radix2_stages"] = plan.log_n
    out["output_perm_s"] = timed(
        jax.jit(lambda a, p: a[:, :, p]), v, jnp.asarray(plan.perm))
    # fused-stage variant (ntt_pallas): the whole multi-group pipeline —
    # every butterfly stage, pre-permutation — as its pallas_call
    # sequence. On TPU this runs at the plan's full width; off-TPU the
    # interpret-mode kernel is timed at a reduced width and the entry
    # says so (the PR 5 degraded-basis convention).
    try:
        from distributed_plonk_tpu.backend import ntt_pallas as NP

        if jax.default_backend() == "tpu":
            fplan, fv = plan, v
            out["fused_basis"] = "tpu-full-size"
        else:
            nn = min(plan.n, 1 << 10)
            fplan = NJ.get_plan(nn)
            fv = v[:, :, :nn]
            out["fused_basis"] = f"degraded: interpret mode at n={nn}"
        sched = NP.plan_schedule(fplan.log_n)
        consts = {kk: jnp.asarray(a) for kk, a in
                  fplan.core_consts(False, kernel="pallas").items()}
        out["fused_groups"] = [1 << r for _, r in sched]
        out["fused_groups_s"] = timed(
            jax.jit(lambda a, c: NP.run_groups(a, c)), fv, consts)
    except Exception as e:  # diagnostic only
        out["fused_stage_error"] = repr(e)
    return out


def device_ntt_seconds():
    """(single-poly seconds, per-poly seconds in a batch-8 launch, batch
    width, radix/kernel-variant + per-stage metadata dict)."""
    import numpy as np
    from distributed_plonk_tpu.backend import ntt_jax

    def sync(x):
        # a 16-element slice transfer: block_until_ready is a no-op on the
        # tunneled platform, and pulling the full array would measure the
        # tunnel's bandwidth instead of the kernel; device execution is
        # in-order, so syncing the last output fences the whole loop
        np.asarray(x[:, :1])

    radix = ntt_jax._active_radix()
    plan = ntt_jax.get_plan(N)
    kernel = plan.kernel()  # Montgomery boundary: the device-resident hot path
    rng = np.random.default_rng(2)
    v = rng.integers(0, 1 << 16, size=(16, N), dtype=np.uint32)
    sync(kernel(v))  # compile + warm
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        out = kernel(v)
    sync(out)
    single = (time.perf_counter() - t0) / reps

    b = max(1, min(8, (1 << 21) // N))  # same memory cap as the backend
    kb = plan.kernel_batch()
    vb = rng.integers(0, 1 << 16, size=(16, b, N), dtype=np.uint32)
    sync(kb(vb)[:, 0])
    t0 = time.perf_counter()
    for _ in range(reps):
        out = kb(vb)
    sync(out[:, 0])
    batch = (time.perf_counter() - t0) / reps / b

    meta = {
        "ntt_radix": radix,
        "ntt_kernel_variant": ("radix4-fused-twiddle"
                               if radix == 4 and plan.exps4 is not None
                               else "radix2-pease"),
    }
    # diagnostics scale their rep count to the measured kernel time so a
    # slow platform (CPU fallback) doesn't burn the inner budget on them
    diag_reps = reps if single < 2.0 else 1
    try:
        # in-run A/B against the other radix (same chip, same arrays):
        # makes the radix speedup attributable without a second bench run
        other = 2 if radix == 4 else 4
        ko = plan.kernel(radix=other)
        sync(ko(v))
        t0 = time.perf_counter()
        for _ in range(diag_reps):
            out = ko(v)
        sync(out)
        other_s = (time.perf_counter() - t0) / diag_reps
        meta[f"ntt_2p{LOG_N}_radix{other}_device_s"] = round(other_s, 5)
        r4, r2 = (single, other_s) if radix == 4 else (other_s, single)
        meta["ntt_radix4_speedup_vs_radix2"] = round(r2 / r4, 2)
    except Exception as e:  # diagnostic only; never fail the bench line
        meta["ntt_ab_error"] = repr(e)
    try:
        # in-run A/B of the fused multi-stage Pallas kernel
        # (DPT_NTT_KERNEL=pallas, VMEM-resident stage groups) vs the
        # radix-4 XLA core, same arrays — mirrors
        # msm_pallas_speedup_vs_onehot. TPU: full size; CPU: the
        # interpret-mode kernel at a reduced width, recorded as a
        # degraded basis (CPU is mul-bound, the HBM win cannot show —
        # the >=1.5x target is a chip-validation ROADMAP item).
        import jax

        meta["ntt_kernel"] = ntt_jax._active_kernel()
        if jax.default_backend() == "tpu":
            ab_plan, ab_v = plan, v
            meta["ntt_ab_basis"] = "tpu-full-size"
        else:
            nn = min(N, 1 << 10)
            ab_plan = ntt_jax.get_plan(nn)
            ab_v = v[:, :nn]
            meta["ntt_ab_basis"] = ("degraded: no TPU — interpret-mode "
                                    f"kernel at n={nn}, not a chip "
                                    "measurement")
        times = {}
        for mode in ("xla", "pallas"):
            km = ab_plan.kernel(kernel=mode)
            sync(km(ab_v))  # compile + warm
            t0 = time.perf_counter()
            for _ in range(diag_reps):
                out = km(ab_v)
            sync(out)
            times[mode] = (time.perf_counter() - t0) / diag_reps
        meta["ntt_ab_xla_radix4_s"] = round(times["xla"], 5)
        meta["ntt_ab_pallas_s"] = round(times["pallas"], 5)
        meta["ntt_pallas_speedup_vs_radix4"] = round(
            times["xla"] / times["pallas"], 2)
    except Exception as e:
        meta["ntt_pallas_ab_error"] = repr(e)
    try:
        meta["ntt_stage_breakdown"] = _ntt_stage_breakdown(
            plan, radix, reps=diag_reps)
    except Exception as e:
        meta["ntt_stage_breakdown_error"] = repr(e)
    return single, batch, b, meta


def _msm_stage_breakdown(ctx, reps=3):
    """Per-stage wall-clock of the MSM pipeline at the context's real
    chunk shape (mirrors _ntt_stage_breakdown): on-device digit
    extraction / bucket-accumulation chunk (scan + group fold) /
    cross-chunk plane merge / finish tail — so an MFU regression can be
    pinned on a stage instead of just the end-to-end number."""
    import numpy as np
    import jax.numpy as jnp
    from distributed_plonk_tpu.backend import msm_jax as MJ

    B = 1
    W = -(-MJ.SCALAR_BITS // ctx.c_batch)
    nc = min(ctx._chunk_lanes(B, W), ctx.padded_n)
    g = MJ._group_size_batch(nc, B, ctx.c_batch, signed=ctx.signed)
    ax, ay, ainf = ctx.point
    rng = np.random.default_rng(6)
    h = jnp.asarray(rng.integers(0, 1 << 16, size=(16, ctx.padded_n),
                                 dtype=np.uint32))

    def timed(fn, *args, sync):
        out = fn(*args)
        sync(out)  # compile + warm, then fence the loop
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        sync(out)
        return round((time.perf_counter() - t0) / reps, 6)

    sync_rows = lambda o: np.asarray(o[:1, :1])
    sync_planes = lambda o: np.asarray(o[0][:1, :1, :1])
    out = {"chunk": int(nc), "group": int(g),
           "kernel": MJ._kernel_mode()}
    out["digits_s"] = timed(ctx._digits_batch_fn, h, sync=sync_rows)
    digits = ctx._digits_batch_fn(h)[None]  # (1, W, padded_n)
    fn = ctx._chunk_fn(nc, g)
    chunk_args = (ax[:, :nc], ay[:, :nc], ainf[:nc], digits[:, :, :nc])
    out["bucket_scan_s"] = timed(fn, *chunk_args, sync=sync_planes)
    planes = fn(*chunk_args)
    out["fold_merge_s"] = timed(ctx._merge_fn, planes, planes,
                                sync=sync_planes)
    out["finish_s"] = timed(ctx._finish_fn(B), *planes,
                            sync=lambda o: np.asarray(o[0][:1, :1]))
    return out


def _msm_kernel_ab(bases, scalars, ctx):
    """In-run A/B of the fused Pallas bucket kernel (DPT_MSM_KERNEL=
    pallas, VMEM-resident planes) vs the XLA onehot scan, same chip and
    arrays — makes `msm_pallas_speedup_vs_onehot` attributable without
    a second bench run. On TPU both modes run the full-size MSM on the
    SAME context (chunk executables are keyed by kernel mode); CPU-only
    runs time the interpret-mode kernel at a reduced size and record
    the basis as degraded rather than blocking."""
    import jax
    from distributed_plonk_tpu.backend import msm_jax as MJ

    if jax.default_backend() == "tpu":
        ctx_ab, ab_scalars = ctx, scalars
        basis = "tpu-full-size"
    else:
        nn = min(len(bases), 1 << 9)
        ctx_ab = MJ.MsmContext(bases[:nn])
        ab_scalars = scalars[:nn]
        basis = ("degraded: no TPU — interpret-mode kernel at "
                 f"n={nn}, not a chip measurement")
    times = {}
    prev = MJ._MSM_KERNEL
    try:
        for mode in ("xla", "pallas"):
            MJ._MSM_KERNEL = mode
            ctx_ab.msm(ab_scalars)  # compile + warm
            t0 = time.perf_counter()
            ctx_ab.msm(ab_scalars)
            times[mode] = time.perf_counter() - t0
    finally:
        MJ._MSM_KERNEL = prev
    return {
        "msm_ab_basis": basis,
        "msm_ab_xla_onehot_s": round(times["xla"], 4),
        "msm_ab_pallas_s": round(times["pallas"], 4),
        "msm_pallas_speedup_vs_onehot":
            round(times["xla"] / times["pallas"], 2),
    }


def device_msm_seconds():
    """2^LOG_N-point MSM (the reference's MSM micro-test scale,
    src/dispatcher.rs:188-196: 2^11 distinct bases tiled up to 2^20).
    Returns (seconds, meta) with the per-stage breakdown + the
    pallas-vs-onehot A/B."""
    from distributed_plonk_tpu import curve as C
    from distributed_plonk_tpu.constants import R_MOD
    from distributed_plonk_tpu.backend import msm_jax as MJ
    from distributed_plonk_tpu.backend.msm_jax import MsmContext

    rng = random.Random(3)
    distinct = [C.g1_mul(C.G1_GEN, rng.randrange(1, R_MOD))
                for _ in range(1 << 11)]
    bases = (distinct * (N // len(distinct) + 1))[:N]
    ctx = MsmContext(bases)
    scalars = [rng.randrange(R_MOD) for _ in range(N)]
    ctx.msm(scalars)  # compile + warm
    t0 = time.perf_counter()
    ctx.msm(scalars)
    msm_s = time.perf_counter() - t0

    meta = {"msm_kernel": MJ._kernel_mode(), "msm_c": ctx.c_batch}
    # diagnostics scale their rep count to the measured time, like the
    # NTT breakdown, so a slow platform doesn't burn the inner budget
    diag_reps = 3 if msm_s < 2.0 else 1
    try:
        meta["msm_stage_breakdown"] = _msm_stage_breakdown(
            ctx, reps=diag_reps)
    except Exception as e:  # diagnostic only; never fail the bench line
        meta["msm_stage_breakdown_error"] = repr(e)
    try:
        meta.update(_msm_kernel_ab(bases, scalars, ctx))
    except Exception as e:
        meta["msm_ab_error"] = repr(e)
    return msm_s, meta


def device_mfu():
    """Analytic MFU for the hot kernels: useful band-FMA flops (the
    irreducible byte-product work of the Montgomery SOS multiply, 3
    products x (2L)^2 MACs x 2 flops) divided by a MEASURED f32 FMA rate
    on the same chip — both numerator rates and the denominator peak are
    measured this run, so the percentages rank kernels for optimization
    (VERDICT r4 #9) without depending on xplane parsing. Returns a dict
    of mfu_* keys (percent) + the raw rates."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import lax
    from distributed_plonk_tpu.backend import field_jax as FJ

    def sync(x):
        np.asarray(x[:1, :1])

    # measured f32 FMA ceiling. The body chains INNER dependent FMAs per
    # array pass so the measurement is compute-bound, not HBM-bound (a
    # 1-FMA-per-pass chain reads ~12 B/flop and measures bandwidth — the
    # first bench build reported mul MFU > 100% against it).
    K, INNER, shape = 32, 128, (2048, 4096)
    y = jnp.full(shape, 1.000001, jnp.float32)
    z = jnp.full(shape, 1e-7, jnp.float32)

    @jax.jit
    def chain(x):
        def body(i, v):
            for _ in range(INNER):
                v = v * y + z
            return v
        return lax.fori_loop(0, K, body, x)

    x = jnp.ones(shape, jnp.float32)
    sync(chain(x))  # compile
    t0 = time.perf_counter()
    sync(chain(x))
    peak = K * INNER * shape[0] * shape[1] * 2 / (time.perf_counter() - t0)

    out = {"f32_fma_tflops_measured": round(peak / 1e12, 3)}

    # wide mont_mul rates (the Pallas path at TPU dispatch widths)
    rng = np.random.default_rng(5)
    for spec, lanes, name in ((FJ.FR, 1 << 21, "fr"), (FJ.FQ, 1 << 20, "fq")):
        L = spec.n_limbs
        a = jnp.asarray(rng.integers(0, 1 << 16, (L, lanes), dtype=np.uint32))
        mul = jax.jit(lambda u, v, s=spec: FJ.mont_mul(s, u, v))
        sync(mul(a, a))  # compile + warm
        reps = 4
        t0 = time.perf_counter()
        for _ in range(reps):
            o = mul(a, a)
        sync(o)
        rate = lanes * reps / (time.perf_counter() - t0)
        band_flops = 3 * (2 * L) ** 2 * 2  # 3 byte-product bands per SOS mul
        out[f"{name}_mul_ns"] = round(1e9 / rate, 1)
        out[f"mfu_{name}_mul_pct"] = round(100 * rate * band_flops / peak, 2)
    return out


def device_prove():
    """Warm prove of the 2^13 reference workload; returns (warm_s, cold_s,
    per-round totals)."""
    from distributed_plonk_tpu import kzg
    from distributed_plonk_tpu.workload import generate_circuit
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.verifier import verify
    from distributed_plonk_tpu.backend.jax_backend import JaxBackend
    from distributed_plonk_tpu.trace import Tracer

    ckt, _ = generate_circuit(rng=random.Random(11), height=32, num_proofs=1)
    backend = JaxBackend()
    srs = kzg.universal_setup_device(ckt.n + 2, rng=random.Random(12))
    pk, vk = kzg.preprocess(srs, ckt, backend=backend)
    t0 = time.perf_counter()
    prove(random.Random(13), ckt, pk, backend)
    cold_s = time.perf_counter() - t0
    tr = Tracer()
    t0 = time.perf_counter()
    proof = prove(random.Random(13), ckt, pk, backend, tracer=tr)
    warm_s = time.perf_counter() - t0
    assert verify(vk, ckt.public_input(), proof, rng=random.Random(14))
    return warm_s, cold_s, {k: round(v, 3) for k, v in tr.totals(1).items()}


def host_prove_seconds():
    if os.environ.get("DPT_BENCH_PROVE_HOST"):  # live measurement wins
        from distributed_plonk_tpu import kzg
        from distributed_plonk_tpu.workload import generate_circuit
        from distributed_plonk_tpu.prover import prove
        from distributed_plonk_tpu.backend.python_backend import PythonBackend

        ckt, _ = generate_circuit(rng=random.Random(11), height=32, num_proofs=1)
        srs = kzg.universal_setup(ckt.n + 2, rng=random.Random(12))
        pk, _vk = kzg.preprocess(srs, ckt)
        t0 = time.perf_counter()
        prove(random.Random(13), ckt, pk, PythonBackend())
        host_s = time.perf_counter() - t0
        _cache_put("prove_2p13_host_s", host_s)
        return host_s, "host oracle, measured on this machine this run"
    c = _cache()
    if "prove_2p13_host_s" in c:
        return (c["prove_2p13_host_s"],
                "host oracle, recorded measurement (re-measure with "
                "DPT_BENCH_PROVE_HOST=1; see BASELINE.md)")
    if _RECORDED_HOST["prove_2p13_host_s"]:
        return (_RECORDED_HOST["prove_2p13_host_s"],
                "host oracle, recorded on the build host (see BASELINE.md)")
    return None, "no host baseline available"


def _measure_autotune():
    """Kernel-calibration pickup + in-run A/B (ISSUE 14): every bench
    line records where the kernel plan came from (fresh|store|off|none),
    what the pickup cost, and what the plan is worth vs the knob-free
    defaults at the bench shape (mont-boundary NTT kernel A/B, both
    sides measured this run). The plan persists in a bench-local store
    (bench_artifacts/autotune_store), so the first line on a platform
    records source=fresh and every later line source=store — the
    trajectory shows both the calibration cost and its amortization.
    DPT_AUTOTUNE=off skips everything (pre-autotune dispatch exactly)."""
    mode = os.environ.get("DPT_AUTOTUNE", "run").strip().lower()
    out = {"autotune_plan_source": "off", "autotune_s": 0.0}
    if mode == "off":
        return out
    t0 = time.perf_counter()
    try:
        from distributed_plonk_tpu.backend import autotune as AT
        from distributed_plonk_tpu.store import ArtifactStore, calibration
        store = ArtifactStore(os.environ.get(
            "DPT_AUTOTUNE_STORE",
            os.path.join(REPO, "bench_artifacts", "autotune_store")))
        budget = float(os.environ.get("DPT_AUTOTUNE_BUDGET_S", "180"))
        rep = calibration.load_or_run(store, mode=mode, shapes=[N],
                                      budget_s=budget, aot=False)
        out["autotune_plan_source"] = rep.get("source", "none")
        plan = AT.active_plan()
        if plan is not None:
            tuner = AT.Autotuner([N], budget_s=budget)
            _, dt_plan, _ = tuner._run_ntt(N)
            AT.set_active_plan(None)
            try:
                _, dt_def, _ = tuner._run_ntt(N)
            finally:
                AT.set_active_plan(plan)
            if dt_plan > 0:
                out["autotune_speedup_vs_defaults"] = round(
                    dt_def / dt_plan, 3)
                out["autotune_ab_basis"] = (
                    "mont-boundary NTT kernel at the bench shape "
                    f"2^{LOG_N}: calibrated plan vs knob-free defaults, "
                    "both measured this run")
    except Exception as e:  # noqa: BLE001 - calibration is diagnostic;
        # never fail the bench line
        out["autotune_error"] = repr(e)
    out["autotune_s"] = round(time.perf_counter() - t0, 3)
    return out


def inner_main():
    """The actual measurement (runs in a budgeted subprocess)."""
    extra = {}
    extra.update(_measure_autotune())
    _partial_put(extra)
    ntt_dev, ntt_batch, nb, ntt_meta = device_ntt_seconds()
    extra.update(ntt_meta)
    extra[f"ntt_2p{LOG_N}_elements_per_s"] = round(N / ntt_dev)
    extra[f"ntt_2p{LOG_N}_device_s"] = round(ntt_dev, 5)
    extra[f"ntt_2p{LOG_N}_batch{nb}_per_poly_s"] = round(ntt_batch, 5)
    extra[f"ntt_2p{LOG_N}_vs_host_oracle"] = round(host_ntt_seconds() / ntt_dev, 2)
    _partial_put(extra)

    msm_dev, msm_meta = device_msm_seconds()
    extra.update(msm_meta)
    extra[f"msm_2p{LOG_N}_points_per_s"] = round(N / msm_dev)
    extra[f"msm_2p{LOG_N}_device_s"] = round(msm_dev, 3)
    _partial_put(extra)

    try:
        mfu = device_mfu()
        extra.update(mfu)
        # derived per-pipeline MFU from the measured wall-clocks above:
        # useful flops = band FMAs of the muls each pipeline performs
        peak = mfu["f32_fma_tflops_measured"] * 1e12
        fr_band = 3 * 32 * 32 * 2
        fq_band = 3 * 48 * 48 * 2
        ntt_muls = (N // 2) * LOG_N
        extra["mfu_ntt_pct"] = round(100 * ntt_muls * fr_band / (peak * ntt_dev), 2)
        # signed radix-256: 32 windows/point, ~11 Fq muls per mixed add
        msm_muls = N * 32 * 11
        extra["mfu_msm_pct"] = round(100 * msm_muls * fq_band / (peak * msm_dev), 2)
        extra["mfu_basis"] = ("band-FMA flops / f32 FMA rate, both measured "
                              "this run on this chip")
        _partial_put(extra)
    except Exception as e:  # MFU is diagnostic; never fail the bench line
        extra["mfu_error"] = repr(e)

    if not os.environ.get("DPT_BENCH_FAST"):
        warm_s, cold_s, rounds = device_prove()
        host_s, basis = host_prove_seconds()
        extra["prove_2p13_cold_s"] = round(cold_s, 2)
        extra["prove_2p13_rounds"] = rounds
        extra["baseline_basis"] = basis
        out = {
            "metric": "prove_2p13_wall_clock",
            "value": round(warm_s, 3),
            "unit": "s",
            "vs_baseline": round(host_s / warm_s, 2) if host_s else None,
        }
    else:
        out = {
            "metric": f"ntt_2p{LOG_N}_throughput",
            "value": round(N / ntt_dev),
            "unit": "field_elements_per_s",
            "vs_baseline": extra[f"ntt_2p{LOG_N}_vs_host_oracle"],
        }
    out.update(extra)
    _partial_put(out)
    print(json.dumps(out))


def service_roundtrip_main():
    """submit -> prove -> verify through the proof service (host oracle
    backend, tiny toy domain): the serving-path regression canary. Runs
    TWICE over real TCP against the same artifact store — a cold process
    (empty store: full trusted setup + preprocess) and a warm restart
    (keys served from disk, key-build count must be 0) — so every bench
    line carries the warm-start speedup. Prints one JSON line. Entirely
    jax-free (service + python backend are pure host code)."""
    import random as _random
    import shutil
    import tempfile
    from distributed_plonk_tpu.service import ProofService, ServiceClient
    from distributed_plonk_tpu.service.jobs import JobSpec, build_bucket_keys
    from distributed_plonk_tpu.proof_io import deserialize_proof
    from distributed_plonk_tpu.verifier import verify

    store_dir = tempfile.mkdtemp(prefix="dpt-bench-store-")

    def one_run(seed):
        """(roundtrip_s, status, header, blob, metrics, trace_info) for
        one fresh service process-equivalent (new ProofService, same
        store). The job is submitted under a bench-owned trace id, so
        trace_info pins the whole propagation + artifact path: spans
        collected under OUR id, and the content digest of the stored
        trace:<job_id> artifact."""
        from distributed_plonk_tpu.store import keycache as KC
        from distributed_plonk_tpu.trace import Tracer
        t0 = time.perf_counter()
        svc = ProofService(port=0, prover_workers=1, store_dir=store_dir)
        svc.start()
        tracer = Tracer(proc="bench")
        trace_info = {"spans": 0, "digest": None, "adopted": False}
        try:
            with ServiceClient("127.0.0.1", svc.port) as c:
                with tracer.span("bench/service_roundtrip") as root:
                    r = c.submit({"kind": "toy", "gates": 16, "seed": seed},
                                 trace_ctx={"trace_id": tracer.trace_id,
                                            "parent_id": root})
                    jid = r["job_id"]
                    st = c.wait(jid, timeout_s=240)
                header, blob = c.result(jid)
                m = c.metrics()
            trace_info["adopted"] = r.get("trace_id") == tracer.trace_id
            trace_info["spans"] = st.get("trace_spans") or 0
            entry = svc.store.get_entry(KC.trace_store_key(jid))
            if entry is not None:
                trace_info["digest"] = entry[1]
            return (time.perf_counter() - t0, st, header, blob, m,
                    trace_info)
        finally:
            svc.shutdown()

    def restart_recovery_run():
        """The durable-service-plane canary (PR 7): crash the service at
        the journal's ROUND2 occurrence mid-prove (in-process SIGKILL
        analog), restart it on the same journal+store, and check the
        recovered job resumes from its checkpoint (no round-1 re-prove)
        to BYTE-IDENTICAL proof bytes. Returns (ok, resumes)."""
        import time as _time
        from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
        from distributed_plonk_tpu.service.jobs import build_circuit
        from distributed_plonk_tpu.prover import prove
        from distributed_plonk_tpu.proof_io import serialize_proof
        from distributed_plonk_tpu.backend.python_backend import PythonBackend

        journal_dir = tempfile.mkdtemp(prefix="dpt-bench-journal-")
        spec_obj = {"kind": "toy", "gates": 60, "seed": 44,
                    "job_key": "bench-recovery"}
        box = {}
        faults = FaultInjector([Rule("kill", tag="ROUND2", plane="journal")],
                               kill_cb=lambda _label: box["svc"].crash())
        svc = ProofService(port=0, prover_workers=1, store_dir=store_dir,
                           journal_dir=journal_dir, chaos=True,
                           faults=faults)
        box["svc"] = svc
        svc.start()
        try:
            svc.submit_local(spec_obj)
            deadline = _time.monotonic() + 120
            while not svc._stopped.is_set() and _time.monotonic() < deadline:
                _time.sleep(0.02)
            if not svc._stopped.is_set():
                return False, 0
            svc2 = ProofService(port=0, prover_workers=1,
                                store_dir=store_dir,
                                journal_dir=journal_dir).start()
            try:
                job, deduped = svc2.submit_ex(spec_obj)
                if not (deduped and job.done_event.wait(timeout=120)
                        and job.state == "done"):
                    return False, 0
                m2 = svc2.metrics.snapshot()
                resumes = m2["counters"].get("checkpoint_resumes", 0)
                s = JobSpec.from_wire(spec_obj)
                want = serialize_proof(prove(
                    _random.Random(s.seed), build_circuit(s),
                    build_bucket_keys(s)[1], PythonBackend()))
                ok = (job.proof_bytes == want and resumes >= 1
                      and "prove_round/round1" not in m2["histograms"])
                return ok, resumes
            finally:
                svc2.shutdown()
        finally:
            shutil.rmtree(journal_dir, ignore_errors=True)

    def batch_prove_ab(n_jobs=4, gates=60):
        """In-process cross-job batching A/B (the placement layer's
        data-parallel path): N small same-shape jobs proved BATCHED
        (prover.prove_many — one commit/eval launch set across jobs) vs
        the same N proved sequentially, same process, same backend.
        Returns the speedup + throughput + the byte-identity verdict
        (batched bytes must equal sequential bytes, the placement
        contract). Host-oracle basis here; on TPU the batched launches
        amortize per-dispatch latency, which is where the speedup
        target lives (ROADMAP chip sweep)."""
        import random as _r
        from distributed_plonk_tpu.backend.python_backend import \
            PythonBackend
        from distributed_plonk_tpu.prover import prove, prove_many
        from distributed_plonk_tpu.proof_io import serialize_proof
        from distributed_plonk_tpu.service.jobs import build_circuit

        specs = [JobSpec.from_wire({"kind": "toy", "gates": gates,
                                    "seed": 7000 + i})
                 for i in range(n_jobs)]
        pk = build_bucket_keys(specs[0])[1]
        be = PythonBackend()
        ckts = [build_circuit(s) for s in specs]
        t0 = time.perf_counter()
        seq = [serialize_proof(prove(_r.Random(s.seed), c, pk, be))
               for s, c in zip(specs, ckts)]
        seq_s = time.perf_counter() - t0
        ckts2 = [build_circuit(s) for s in specs]
        t0 = time.perf_counter()
        proofs, errors = prove_many([_r.Random(s.seed) for s in specs],
                                    ckts2, pk, PythonBackend())
        bat_s = time.perf_counter() - t0
        identical = (errors == [None] * n_jobs
                     and [serialize_proof(p) for p in proofs] == seq)
        return {
            "proofs_per_s": round(n_jobs / bat_s, 3) if bat_s else None,
            "batch_prove_speedup_vs_sequential":
                round(seq_s / bat_s, 3) if bat_s else None,
            "batch_ab_jobs": n_jobs,
            "batch_ab_sequential_s": round(seq_s, 3),
            "batch_ab_batched_s": round(bat_s, 3),
            "batch_prove_byte_identical": bool(identical),
            "batch_ab_basis": ("host-oracle backend, same process; the "
                               "dispatch-amortization win is a chip "
                               "number (ROADMAP sweep)"),
        }

    def aggregate_ab(n_jobs=8):
        """Batch-KZG aggregation A/B (ISSUE 17): N mixed-kind proofs
        (toy + range-check shapes) verified one by one — N independent
        pairing checks — vs folded into ONE aggregate accepted by a
        single 2-pair pairing check. aggregate_ok pins the whole
        contract: the fold verifies, the pairing counters read exactly
        {checks: 1, pairs: 2} regardless of N, and a one-bit proof
        corruption REBUILT into a consistent aggregate is rejected (the
        soundness leg, not just artifact tamper-evidence)."""
        import random as _r
        from distributed_plonk_tpu import aggregate as AGG
        from distributed_plonk_tpu import curve
        from distributed_plonk_tpu.backend.python_backend import \
            PythonBackend
        from distributed_plonk_tpu.prover import prove
        from distributed_plonk_tpu.proof_io import serialize_proof
        from distributed_plonk_tpu.service.jobs import (build_circuit,
                                                        shape_key)

        shapes = [{"kind": "toy", "gates": 16},
                  {"kind": "range", "bits": 8, "count": 2}]
        keys, vk_cache, members = {}, {}, []
        be = PythonBackend()
        for i in range(n_jobs):
            wire = dict(shapes[i % len(shapes)], seed=8100 + i)
            s = JobSpec.from_wire(wire)
            k = shape_key(s)
            if k not in keys:
                keys[k] = build_bucket_keys(s)
            vk_cache[k] = keys[k][2]
            ckt = build_circuit(s)
            proof = prove(_r.Random(s.seed), ckt, keys[k][1], be)
            members.append({"job_id": f"bench-{i}", "spec": s.to_wire(),
                            "pub": ckt.public_input(),
                            "proof": serialize_proof(proof)})
        t0 = time.perf_counter()
        seq_ok = all(
            verify(vk_cache[shape_key(JobSpec.from_wire(m["spec"]))],
                   m["pub"], deserialize_proof(m["proof"]),
                   rng=_r.Random(1))
            for m in members)
        seq_s = time.perf_counter() - t0
        agg = AGG.build(members)
        curve.reset_pairing_counters()
        t0 = time.perf_counter()
        agg_ok = AGG.verify(agg, vk_cache)
        agg_s = time.perf_counter() - t0
        pinned = dict(curve.PAIRING_COUNTERS)
        bad_members = [dict(m) for m in members]
        pb = bytearray(bad_members[0]["proof"])
        pb[len(pb) // 2] ^= 1
        bad_members[0]["proof"] = bytes(pb)
        rejected = not AGG.verify(AGG.build(bad_members), vk_cache)
        ok = (seq_ok and agg_ok and rejected
              and pinned == {"checks": 1, "pairs": 2})
        return {
            "aggregate_ok": bool(ok),
            "aggregate_verify_speedup_vs_sequential":
                round(seq_s / agg_s, 3) if agg_s else None,
            "aggregate_ab_members": n_jobs,
            "aggregate_ab_sequential_s": round(seq_s, 3),
            "aggregate_ab_aggregate_s": round(agg_s, 3),
            "aggregate_pairing_checks": pinned,
        }

    def self_verify_ab(gates=60):
        """In-run verify-before-serve A/B (ISSUE 13): the same toy job
        proved with DPT_SELF_VERIFY=1 (host pairing verifier gating the
        DONE record) vs =0, same process — the overhead number operators
        use to decide whether always-verify is affordable for their
        shapes. Bytes must be identical either way."""
        def run(self_verify, seed):
            svc = ProofService(port=0, prover_workers=1,
                               self_verify=self_verify)
            svc.start()
            try:
                t0 = time.perf_counter()
                job = svc.submit_local({"kind": "toy", "gates": gates,
                                        "seed": seed})
                ok = job.done_event.wait(timeout=240) \
                    and job.state == "done"
                dt = time.perf_counter() - t0
                snap = svc.metrics.snapshot()
                return ok, dt, job.proof_bytes, snap
            finally:
                svc.shutdown()
        ok_off, t_off, bytes_off, _ = run("0", 71)
        ok_on, t_on, bytes_on, m_on = run("1", 71)
        hist = m_on["histograms"].get("self_verify_s", {})
        return {
            "self_verify_overhead_pct":
                round(100.0 * (t_on - t_off) / t_off, 2) if t_off else None,
            "self_verify_s": hist.get("mean_s"),
            "self_verify_bytes_identical":
                bool(ok_off and ok_on and bytes_off == bytes_on),
            "self_verify_checks":
                m_on["counters"].get("self_verify_checks", 0),
        }

    def autoscale_canary():
        """The closed-loop control-law canary (ISSUE 16): drive the
        Autoscaler's tick() directly against fake sensors/actuators —
        no threads, no sockets, an injected clock — through a ramp
        (queue breach -> scale_up), an idle tail (-> scale_down), a dry
        arm that must make ZERO actuator calls, and the off arm where
        attach() must return None (bit-parity). Returns the verdict +
        the dry arm's call count (pinned at 0 by the gate)."""
        from distributed_plonk_tpu.service import autoscale as AS

        def arm(mode):
            calls = {"n": 0, "workers": 2}

            class Act:
                def worker_count(self):
                    return calls["workers"]

                def add_worker(self):
                    calls["n"] += 1
                    calls["workers"] += 1
                    return calls["workers"] - 1

                def retire_worker(self):
                    calls["n"] += 1
                    calls["workers"] -= 1
                    return calls["workers"]

                def lease_capacity(self, frac):
                    calls["n"] += 1
                    return 4

                def shed_lowest(self, below_rank):
                    calls["n"] += 1
                    return "batch"

            box = {"depth": 8, "t": 0.0}
            asc = AS.Autoscaler(
                mode=mode, tick_s=0.01, min_workers=1, max_workers=4,
                up_queue_per_worker=2, up_ticks=2, down_ticks=2,
                up_cooldown_s=0, down_cooldown_s=0,
                sensors=lambda: {"queue_depth": box["depth"],
                                 "queue_by_class":
                                     {"standard": box["depth"]},
                                 "max_depth": 64, "busy_workers":
                                     1 if box["depth"] else 0},
                actuators=Act(), clock=lambda: box["t"])
            acts = []
            for _ in range(3):          # ramp: breach streak -> up
                box["t"] += 1
                acts += [d["action"] for d in asc.tick()]
            box["depth"] = 0
            for _ in range(3):          # idle tail -> down
                box["t"] += 1
                acts += [d["action"] for d in asc.tick()]
            return acts, calls["n"]

        live_acts, live_calls = arm("1")
        dry_acts, dry_calls = arm("dry")
        off_is_none = AS.attach(None, mode="0") is None
        ok = ("scale_up" in live_acts and "scale_down" in live_acts
              and live_calls >= 2 and "scale_up" in dry_acts
              and dry_calls == 0 and off_is_none)
        return {"autoscale_canary_ok": bool(ok),
                "autoscale_dry_actuator_calls": dry_calls}

    try:
        cold_s, st, header, blob, m_cold, trace_info = one_run(seed=42)
        warm_s, st_w, _hw, _bw, m_warm, _tw = one_run(seed=43)
        recovery_ok, recovery_resumes = restart_recovery_run()
        try:
            batch_ab = batch_prove_ab()
        except Exception as e:  # diagnostic; never fail the canary
            batch_ab = {"batch_ab_error": repr(e),
                        "batch_prove_byte_identical": False}
        try:
            sv_ab = self_verify_ab()
        except Exception as e:  # diagnostic; never fail the canary
            sv_ab = {"self_verify_ab_error": repr(e),
                     "self_verify_overhead_pct": None}
        try:
            as_canary = autoscale_canary()
        except Exception as e:  # diagnostic; never fail the canary
            as_canary = {"autoscale_canary_error": repr(e),
                         "autoscale_canary_ok": False}
        try:
            agg_ab = aggregate_ab()
        except Exception as e:  # diagnostic; never fail the canary
            agg_ab = {"aggregate_ab_error": repr(e),
                      "aggregate_ok": False,
                      "aggregate_verify_speedup_vs_sequential": None}
        spec = JobSpec.from_wire(header["spec"])
        vk = build_bucket_keys(spec)[2]
        pub = [int(x, 16) for x in header["public_input"]]
        ok = st["state"] == "done" and verify(
            vk, pub, deserialize_proof(blob), rng=_random.Random(1))
        print(json.dumps({
            "service_roundtrip_s": round(cold_s, 3),
            "service_roundtrip_warm_s": round(warm_s, 3),
            "service_warm_speedup": round(cold_s / warm_s, 2) if warm_s else None,
            "service_verified": bool(ok),
            "service_warm_done": st_w["state"] == "done",
            # contract: a warm restart rebuilds NOTHING for a seen shape
            "service_warm_key_builds":
                m_warm["counters"].get("bucket_misses", 0),
            "service_warm_disk_hits":
                m_warm["counters"].get("bucket_disk_hits", 0),
            # contract: a service crashed mid-prove recovers from journal
            # + checkpoint to byte-identical proof bytes, no re-prove of
            # completed rounds (the PR 7 durability canary)
            "service_restart_recovery_ok": bool(recovery_ok),
            "service_restart_resumes": recovery_resumes,
            # contract: the job proved under the BENCH's trace id end to
            # end, and its merged timeline is a content-addressed store
            # artifact (trace:<job_id>) — the PR 9 observability canary
            "trace_spans_total": trace_info["spans"],
            "trace_ctx_adopted": bool(trace_info["adopted"]),
            "trace_artifact_digest": trace_info["digest"],
            # placement + cross-job batching (the PR 11 canary): how the
            # scheduler routed this run's jobs, and the in-process
            # batched-vs-sequential A/B (byte-identity is part of it)
            "placement_decisions": {
                k: v for k, v in sorted(m_cold["counters"].items())
                if k.startswith(("placement_", "batch_", "submesh_"))},
            **batch_ab,
            # verify-before-serve overhead (the ISSUE 13 in-run A/B)
            **sv_ab,
            # batch-KZG aggregation (the ISSUE 17 canary): N proofs in,
            # one 2-pair pairing check out, corrupted member rejected
            **agg_ab,
            # closed-loop control law (the ISSUE 16 canary): ramp ->
            # scale_up, idle -> scale_down, dry arm pinned at ZERO
            # actuator calls, off arm attaches nothing
            **as_canary,
            # standard-class serving latency under SLO accounting (the
            # cold run's jobs are classless -> standard by default)
            "slo_p95_standard_s":
                (m_cold["histograms"].get("slo_roundtrip/standard")
                 or {}).get("p95_s"),
            "service_wait_s": st["wait_s"],
            "service_run_s": st["run_s"],
            "service_jobs_completed":
                m_cold["counters"].get("jobs_completed", 0),
        }))
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)


def pipeline_ab_main():
    """Round-pipelined proving A/B (PR 18): the SAME N jobs proved
    through prover.prove_pipelined at depth=1 (lockstep: launch, force,
    finalize, one member at a time) vs depth=4 (members staggered so one
    member's async commit/eval dispatches overlap the others' host
    transcript + challenge work). Byte-identity vs the python-oracle
    sequential proves is asserted for BOTH arms — the speedup must come
    from overlap alone, never from a schedule change the bytes could
    observe.

    Basis: the jax backend on whatever platform this process sees
    (XLA:CPU in CI — its async dispatch is what the pipeline hides host
    work behind; the chip-basis depth sweep is ROADMAP item (g)), with
    the persistent compile cache under bench_artifacts/jax_cache so
    repeat runs skip XLA compiles. Falls back to the host oracle
    (GIL-bound: expect ~1.0x) if jax is unusable. Prints one JSON
    line."""
    import random as _random
    from distributed_plonk_tpu import prover
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.backend.python_backend import PythonBackend
    from distributed_plonk_tpu.proof_io import serialize_proof
    from distributed_plonk_tpu.service.jobs import (JobSpec, build_bucket_keys,
                                                    build_circuit)

    n_jobs, gates = 4, 16
    specs = [JobSpec.from_wire({"kind": "toy", "gates": gates,
                                "seed": 7100 + i}) for i in range(n_jobs)]
    pk = build_bucket_keys(specs[0])[1]
    oracle = [serialize_proof(prove(_random.Random(s.seed),
                                    build_circuit(s), pk, PythonBackend()))
              for s in specs]
    basis = "jax backend (async dispatch), XLA compile cache warm"
    try:
        from distributed_plonk_tpu.backend import field_jax
        from distributed_plonk_tpu.backend.jax_backend import JaxBackend
        field_jax.configure_compile_cache(
            os.path.join(REPO, "bench_artifacts", "jax_cache"),
            min_compile_secs=0.5)
        be = JaxBackend()
        warm = serialize_proof(prove(_random.Random(specs[0].seed),
                                     build_circuit(specs[0]), pk, be))
        if warm != oracle[0]:
            raise RuntimeError("jax sequential bytes != host oracle")
    except Exception as e:  # no usable jax: host-oracle fallback
        be = PythonBackend()
        basis = f"host oracle (jax unusable: {e!r}; GIL-bound)"

    def arm(depth):
        ckts = [build_circuit(s) for s in specs]
        t0 = time.perf_counter()
        proofs, errors = prover.prove_pipelined(
            [_random.Random(s.seed) for s in specs], ckts, pk, be,
            depth=depth)
        dt = time.perf_counter() - t0
        ok = (errors == [None] * n_jobs
              and [serialize_proof(p) for p in proofs] == oracle)
        return dt, ok

    t1, ok1 = arm(1)
    t4, ok4 = arm(4)
    print(json.dumps({
        "pipelined_proofs_per_s": round(n_jobs / t4, 3) if t4 else None,
        "pipeline_speedup_vs_lockstep":
            round(t1 / t4, 3) if t4 else None,
        "pipeline_byte_identical": bool(ok1 and ok4),
        "pipeline_ab_jobs": n_jobs,
        "pipeline_ab_depth1_s": round(t1, 3),
        "pipeline_ab_depth4_s": round(t4, 3),
        "pipeline_ab_basis": basis,
    }))


def fleet_chaos_main():
    """The fault-domain regression canary: run one fully distributed prove
    (3 python-backend worker processes over real TCP, sharded 4-step FFTs
    + range-sharded MSM) with a worker KILLED mid-FFT1 by the chaos
    injector, and check the recovered proof is byte-identical to the host
    oracle's. Prints one JSON line ({fleet_chaos_proof_ok,
    fleet_recoveries, ...}); entirely jax-free."""
    import random as _random
    import shutil
    import tempfile
    from distributed_plonk_tpu.backend.python_backend import PythonBackend
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.runtime import protocol
    from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                          RemoteBackend,
                                                          WorkerHandle)
    from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
    from distributed_plonk_tpu.runtime.health import LivenessTracker
    from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
    from distributed_plonk_tpu.service.jobs import JobSpec, build_circuit, \
        build_bucket_keys
    from distributed_plonk_tpu.service.metrics import Metrics

    spec = JobSpec.from_wire({"kind": "toy", "gates": 16, "seed": 7})
    ckt = build_circuit(spec)
    _srs, pk, _vk = build_bucket_keys(spec)
    proof_host = prove(_random.Random(1), ckt, pk, PythonBackend())

    n_workers = 3
    base = 28500 + (os.getpid() % 450) * (n_workers + 1)
    cfg = NetworkConfig([f"127.0.0.1:{base + i}" for i in range(n_workers)])
    tmp = tempfile.mkdtemp(prefix="dpt-bench-fleet-")
    cfg_path = os.path.join(tmp, "network.json")
    cfg.save(cfg_path)
    procs = [subprocess.Popen(
        [sys.executable, "-m", "distributed_plonk_tpu.runtime.worker",
         str(i), cfg_path, "--backend", "python"], cwd=REPO)
        for i in range(n_workers)]
    t0 = time.perf_counter()
    d = None
    try:
        # readiness via tracker-free probes (tests' Fleet.wait_up idiom):
        # waiting through the breaker-armed dispatcher would record the
        # slow-startup dials as failures, open breakers (k=2), and then
        # fast-fail ping() until the deadline burns the whole 30 s
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(WorkerHandle(h, p).probe(timeout_ms=2000) is not None
                   for h, p in cfg.workers):
                break
            time.sleep(0.2)
        metrics = Metrics()
        faults = FaultInjector(
            [Rule("kill", tag=protocol.FFT1, worker=1, nth=1)],
            kill_cb=lambda i: (procs[i].kill(), procs[i].wait(timeout=10)),
            metrics=metrics)
        d = Dispatcher(cfg, metrics=metrics, faults=faults)
        # fast failure knobs: the canary must not burn minutes in backoff
        d.tracker = LivenessTracker(n_workers, breaker_k=2,
                                    probe_base_s=0.05, probe_max_s=0.5,
                                    metrics=metrics)
        for w in d.workers:
            w.tracker = d.tracker
            w.RECONNECT_TRIES = 2
            w.BACKOFF_BASE_S = 0.01
            w.BACKOFF_MAX_S = 0.05
        proof = prove(_random.Random(1), ckt, pk,
                      RemoteBackend(d, dist_fft_min=ckt.n))
        ctr = metrics.snapshot()["counters"]
        ok = (proof.opening_proof == proof_host.opening_proof
              and proof.shifted_opening_proof
              == proof_host.shifted_opening_proof
              and proof.wires_poly_comms == proof_host.wires_poly_comms
              and ctr.get("faults_injected_kill", 0) == 1)
        recoveries = sum(ctr.get(k, 0) for k in (
            "fleet_range_adoptions", "fleet_fft_replans",
            "fleet_fft_degraded", "fleet_reconnects",
            "fleet_readmissions"))
        print(json.dumps({
            "fleet_chaos_proof_ok": bool(ok),
            "fleet_recoveries": recoveries,
            "fleet_chaos_s": round(time.perf_counter() - t0, 3),
            "fleet_chaos_phase": "kill@FFT1",
            "fleet_chaos_counters": {k: v for k, v in sorted(ctr.items())
                                     if k.startswith(("fleet_", "faults_"))},
        }))
    finally:
        if d is not None:
            for w in d.workers:
                w.close()
            d.pool.shutdown(wait=False)
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait(timeout=10)
        shutil.rmtree(tmp, ignore_errors=True)


def fleet_heal_main():
    """The self-healing regression canary (ISSUE 12): 3 SUPERVISED
    worker processes under dynamic membership, one SIGKILLed mid-FFT1 by
    the `kill:at=proc` chaos plane. Measures the heal: time from the
    SIGKILL to the fleet restored at FULL width (supervisor respawn ->
    JOIN re-admission -> all members probing healthy), with the
    recovered proof byte-identical to the host oracle's. Prints one JSON
    line ({fleet_healed_ok, fleet_heal_s, ...}); entirely jax-free."""
    import random as _random
    from distributed_plonk_tpu.backend.python_backend import PythonBackend
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.runtime import protocol
    from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                          RemoteBackend)
    from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
    from distributed_plonk_tpu.runtime.health import LivenessTracker
    from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
    from distributed_plonk_tpu.runtime.supervisor import WorkerSupervisor
    from distributed_plonk_tpu.service.jobs import JobSpec, build_circuit, \
        build_bucket_keys
    from distributed_plonk_tpu.service.metrics import Metrics

    spec = JobSpec.from_wire({"kind": "toy", "gates": 16, "seed": 7})
    ckt = build_circuit(spec)
    _srs, pk, _vk = build_bucket_keys(spec)
    proof_host = prove(_random.Random(1), ckt, pk, PythonBackend())

    n_workers = 3
    metrics = Metrics()
    kill_at = []
    faults = FaultInjector(
        [Rule("kill", tag=protocol.FFT1, worker=1, nth=1, plane="proc")],
        metrics=metrics)
    d = Dispatcher(NetworkConfig([]), metrics=metrics, faults=faults)
    d.tracker = LivenessTracker(0, breaker_k=2, probe_base_s=0.05,
                                probe_max_s=0.5, metrics=metrics)
    mserver = d.enable_membership()
    sup = WorkerSupervisor("127.0.0.1", mserver.port, n=n_workers,
                           backend="python", metrics=metrics,
                           cwd=REPO).start()
    proc_kill = sup.proc_killer(d)

    def stamped_kill(i):
        kill_at.append(time.perf_counter())
        proc_kill(i)
    faults.proc_kill_cb = stamped_kill
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            if len(d.workers) == n_workers \
                    and len(d.tracker.usable_set()) == n_workers:
                break
            time.sleep(0.1)
        for w in d.workers:
            w.RECONNECT_TRIES = 2
            w.BACKOFF_BASE_S = 0.01
            w.BACKOFF_MAX_S = 0.05
        proof = prove(_random.Random(1), ckt, pk,
                      RemoteBackend(d, dist_fft_min=ckt.n))
        proof_ok = (proof.opening_proof == proof_host.opening_proof
                    and proof.shifted_opening_proof
                    == proof_host.shifted_opening_proof
                    and proof.wires_poly_comms == proof_host.wires_poly_comms)

        healed = False
        deadline = time.time() + 60
        while time.time() < deadline:
            if len(d.tracker.usable_set()) == n_workers and all(
                    w.probe(timeout_ms=2000) is not None
                    for w in d.workers):
                healed = True
                break
            time.sleep(0.1)
        heal_s = (time.perf_counter() - kill_at[0]) if kill_at else None
        ctr = metrics.snapshot()["counters"]
        print(json.dumps({
            "fleet_healed_ok": bool(
                proof_ok and healed and kill_at
                and ctr.get("worker_respawns", 0) >= 1
                and ctr.get("membership_rejoins", 0) >= 1),
            "fleet_heal_s": round(heal_s, 3) if heal_s is not None else None,
            "fleet_heal_phase": "proc-kill@FFT1",
            "fleet_heal_epoch": d.epoch,
            "fleet_heal_counters": {
                k: v for k, v in sorted(ctr.items())
                if k.startswith(("membership_", "worker_", "warm_",
                                 "fleet_", "faults_"))},
        }))
    finally:
        sup.stop()
        d.shutdown()
        d.pool.shutdown(wait=False)


def sdc_heal_main():
    """The result-integrity regression canary (ISSUE 13): 3 SUPERVISED
    workers, one silently corrupting its MSM partials (data-plane SDC —
    well-formed wrong answers). Mid-prove the integrity plane must
    detect it (duplicate execution), attribute + quarantine the liar
    (LEAVE reason=integrity), the supervisor replaces the process, and
    the respawn re-enters through the known-answer challenge — with the
    proof byte-identical to the host oracle throughout. Measures
    sdc_heal_s: first quarantine verdict -> fleet back at full
    SCHEDULABLE width. Prints one JSON line; entirely jax-free."""
    import random as _random
    import threading as _threading
    from distributed_plonk_tpu.backend.python_backend import PythonBackend
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                          RemoteBackend)
    from distributed_plonk_tpu.runtime.health import LivenessTracker
    from distributed_plonk_tpu.runtime.integrity import FleetIntegrity
    from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
    from distributed_plonk_tpu.runtime.supervisor import WorkerSupervisor
    from distributed_plonk_tpu.service.jobs import JobSpec, build_circuit, \
        build_bucket_keys
    from distributed_plonk_tpu.service.metrics import Metrics

    spec = JobSpec.from_wire({"kind": "toy", "gates": 16, "seed": 7})
    ckt = build_circuit(spec)
    _srs, pk, _vk = build_bucket_keys(spec)
    proof_host = prove(_random.Random(1), ckt, pk, PythonBackend())

    metrics = Metrics()
    d = Dispatcher(NetworkConfig([]), metrics=metrics,
                   integrity=FleetIntegrity(metrics=metrics,
                                            msm_dup_rate=1.0,
                                            rng=_random.Random(0xB)))
    d.tracker = LivenessTracker(0, breaker_k=2, probe_base_s=0.05,
                                probe_max_s=0.5, metrics=metrics)
    mserver = d.enable_membership()
    corrupt_spawns = []

    def spawn_cmd(i, slot):
        cmd = [sys.executable, "-m",
               "distributed_plonk_tpu.runtime.worker",
               "--join", f"127.0.0.1:{mserver.port}",
               "--listen", f"127.0.0.1:{slot.port}",
               "--backend", "python"]
        if i == 1 and not corrupt_spawns:
            corrupt_spawns.append(time.monotonic())
            cmd = ["env", "DPT_FAULTS=corrupt:at=data:tag=MSM:rate=1"] \
                + cmd
        return cmd

    sup = WorkerSupervisor("127.0.0.1", mserver.port, n=3,
                           metrics=metrics, cwd=REPO,
                           spawn_cmd=spawn_cmd).start()
    sup.attach_registry(d.membership)

    stamps = {}

    def watch_detect():
        # stamp the first quarantine verdict (the heal clock's zero)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and "detect" not in stamps:
            if metrics.snapshot()["counters"].get(
                    "workers_quarantined", 0) >= 1:
                stamps["detect"] = time.perf_counter()
                return
            time.sleep(0.01)
    watcher = _threading.Thread(target=watch_detect, daemon=True)
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            if len(d.workers) == 3 \
                    and len(d.tracker.usable_set()) == 3:
                break
            time.sleep(0.1)
        for w in d.workers:
            w.RECONNECT_TRIES = 2
            w.BACKOFF_BASE_S = 0.01
            w.BACKOFF_MAX_S = 0.05
        watcher.start()
        proof = prove(_random.Random(1), ckt, pk,
                      RemoteBackend(d, dist_fft_min=ckt.n))
        proof_ok = (proof.opening_proof == proof_host.opening_proof
                    and proof.shifted_opening_proof
                    == proof_host.shifted_opening_proof
                    and proof.wires_poly_comms == proof_host.wires_poly_comms)
        healed = False
        deadline = time.time() + 90
        while time.time() < deadline:
            if len(d.tracker.usable_set()) == 3:
                healed = True
                stamps.setdefault("healed", time.perf_counter())
                break
            time.sleep(0.05)
        ctr = metrics.snapshot()["counters"]
        heal_s = (stamps["healed"] - stamps["detect"]
                  if healed and "detect" in stamps else None)
        print(json.dumps({
            "sdc_detected_ok": bool(
                proof_ok and healed
                and ctr.get("workers_quarantined", 0) >= 1
                and ctr.get("integrity_failures", 0) >= 1
                and ctr.get("integrity_challenges", 0) >= 1
                and ctr.get("worker_respawns", 0) >= 1),
            "sdc_heal_s": round(heal_s, 3) if heal_s is not None else None,
            "sdc_phase": "corrupt@MSM (data plane, rate=1)",
            "sdc_counters": {
                k: v for k, v in sorted(ctr.items())
                if k.startswith(("integrity_", "workers_quarantined",
                                 "membership_", "worker_", "fleet_"))},
        }))
    finally:
        sup.stop()
        d.shutdown()
        d.pool.shutdown(wait=False)


# --- outer harness (no jax imports past this line) ---------------------------

def _emit_trajectory(out):
    """Append the normalized schema-1 record for this run's ONE line to
    bench_artifacts/trajectory.jsonl (scripts/bench_record.py) — the
    machine-readable history scripts/bench_compare.py gates on. Best
    effort: trajectory bookkeeping must never fail a bench line."""
    try:
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import bench_record as BR
        BR.append(BR.normalize("bench", out), repo=REPO)
    except Exception:
        pass

def _probe_device(timeout_s):
    """True iff a fresh interpreter can run one tiny jnp op end to end."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax.numpy as jnp; print(int(jnp.arange(8).sum()))"],
            cwd=REPO, capture_output=True, text=True, timeout=timeout_s)
        return proc.returncode == 0 and proc.stdout.strip().endswith("28")
    except subprocess.TimeoutExpired:
        return False


def _run_inner(env, timeout_s):
    """Run inner_main in a subprocess; returns parsed JSON dict or None."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--inner"],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None, "inner measurement exceeded budget"
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                break
    return None, f"inner rc={proc.returncode}: {proc.stderr[-800:]}"


def _scrubbed_cpu_env():
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("PALLAS_AXON", "AXON_", "TPU_")):
            env.pop(k)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _degraded(reason, extra=None):
    """Emit the best JSON we can without a reachable TPU: the recorded chip
    numbers under their own clearly-recorded keys (NEVER as this run's
    value — a consumer ignoring the `degraded` flag must not mistake a
    prior measurement for a fresh one) + whatever partial measurements
    exist + a small live CPU NTT so the line always carries a fresh
    measurement."""
    out = {
        "metric": "prove_2p13_wall_clock",
        "value": None,
        "unit": "s",
        "vs_baseline": None,
        "degraded": True,
        "degraded_reason": reason,
        # every line carries the autotune keys; a partial inner run's
        # real values (restored below) override these placeholders
        "autotune_plan_source": "off",
        "autotune_s": 0.0,
        "recorded_prove_2p13_s": _RECORDED_DEVICE["prove_2p13_wall_clock_s"],
        "recorded_prove_2p13_vs_host_oracle":
            _RECORDED_DEVICE["prove_2p13_vs_host_oracle"],
        "baseline_basis": ("TPU unreachable at capture time; value is null, "
                           "recorded_* keys are prior chip measurements "
                           "(BASELINE.md); cpu_* keys are live"),
    }
    if os.path.exists(_PARTIAL):
        try:
            with open(_PARTIAL) as f:
                partial = json.load(f)
            out.update({k: v for k, v in partial.items()
                        if k not in ("metric", "value", "unit", "vs_baseline")})
            out["partial_device_measurements"] = True
        except (OSError, json.JSONDecodeError):
            pass
    env = _scrubbed_cpu_env()
    env["DPT_BENCH_FAST"] = "1"
    env["DPT_BENCH_LOG_N"] = "14"
    env["DPT_BENCH_INNER_NO_PARTIAL"] = "1"
    cpu, _err = _run_inner(env, timeout_s=900)
    if cpu:
        out["cpu_ntt_2p14_device_s"] = cpu.get("ntt_2p14_device_s")
        out["cpu_ntt_2p14_elements_per_s"] = cpu.get("ntt_2p14_elements_per_s")
        for k in ("ntt_radix", "ntt_kernel_variant", "ntt_kernel",
                  "ntt_radix4_speedup_vs_radix2", "ntt_stage_breakdown",
                  "ntt_ab_basis", "ntt_ab_xla_radix4_s", "ntt_ab_pallas_s",
                  "ntt_pallas_speedup_vs_radix4", "ntt_pallas_ab_error",
                  "msm_kernel", "msm_stage_breakdown", "msm_ab_basis",
                  "msm_ab_xla_onehot_s", "msm_ab_pallas_s",
                  "msm_pallas_speedup_vs_onehot", "msm_ab_error",
                  "msm_stage_breakdown_error"):
            if k in cpu and k not in out:
                out[k] = cpu[k]
    if extra:
        out.update(extra)
    _emit_trajectory(out)
    print(json.dumps(out))


def _measure_analysis_clean():
    """Run the static verifier (`ci.sh analyze` surface) in a scrubbed
    CPU subprocess; returns {analysis_clean: bool} (+ detail on failure)
    so every trajectory line records whether this tree still PROVES its
    kernel bounds/lints — a perf number from an unverified tree is
    flagged by construction. Never fails the bench."""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "distributed_plonk_tpu.analysis",
             "--strict", "-q"],
            cwd=REPO, env=_scrubbed_cpu_env(), capture_output=True,
            text=True,
            timeout=int(os.environ.get("DPT_BENCH_ANALYSIS_TIMEOUT", "600")))
        out = {"analysis_clean": proc.returncode == 0}
        if proc.returncode != 0:
            tail = (proc.stdout or proc.stderr or "").strip().splitlines()
            out["analysis_detail"] = "; ".join(tail[-3:])[-400:]
        return out
    except Exception as e:
        return {"analysis_clean": False, "analysis_detail": repr(e)}


def _measure_fleet_chaos():
    """Run fleet_chaos_main in a scrubbed-CPU subprocess; returns its keys
    or {fleet_chaos_proof_ok: False, fleet_chaos_error} — every bench line
    records whether a distributed prove still survives a mid-FFT worker
    kill with byte-identical proof bytes. Never fails the bench."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--fleet-chaos"],
            cwd=REPO, env=_scrubbed_cpu_env(), capture_output=True, text=True,
            timeout=int(os.environ.get("DPT_BENCH_FLEET_TIMEOUT", "300")))
        for line in reversed(proc.stdout.strip().splitlines() or [""]):
            if line.strip().startswith("{"):
                return json.loads(line)
        return {"fleet_chaos_proof_ok": False, "fleet_recoveries": 0,
                "fleet_chaos_error":
                    f"rc={proc.returncode}: {proc.stderr[-300:]}"}
    except Exception as e:
        return {"fleet_chaos_proof_ok": False, "fleet_recoveries": 0,
                "fleet_chaos_error": repr(e)}


def _measure_fleet_heal():
    """Run fleet_heal_main in a scrubbed-CPU subprocess; returns its keys
    or {fleet_healed_ok: False, fleet_heal_error} — every bench line
    records whether a SIGKILLed supervised worker is respawned, rejoins,
    and the fleet heals to full width with byte-identical proof bytes.
    Never fails the bench."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--fleet-heal"],
            cwd=REPO, env=_scrubbed_cpu_env(), capture_output=True, text=True,
            timeout=int(os.environ.get("DPT_BENCH_FLEET_TIMEOUT", "300")))
        for line in reversed(proc.stdout.strip().splitlines() or [""]):
            if line.strip().startswith("{"):
                return json.loads(line)
        return {"fleet_healed_ok": False, "fleet_heal_s": None,
                "fleet_heal_error":
                    f"rc={proc.returncode}: {proc.stderr[-300:]}"}
    except Exception as e:
        return {"fleet_healed_ok": False, "fleet_heal_s": None,
                "fleet_heal_error": repr(e)}


def _measure_sdc_heal():
    """Run sdc_heal_main in a scrubbed-CPU subprocess; returns its keys
    or {sdc_detected_ok: False, sdc_error} — every bench line records
    whether injected silent data corruption is detected, attributed,
    quarantined, and healed with byte-identical proof bytes. Never
    fails the bench."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--sdc-heal"],
            cwd=REPO, env=_scrubbed_cpu_env(), capture_output=True, text=True,
            timeout=int(os.environ.get("DPT_BENCH_FLEET_TIMEOUT", "300")))
        for line in reversed(proc.stdout.strip().splitlines() or [""]):
            if line.strip().startswith("{"):
                return json.loads(line)
        return {"sdc_detected_ok": False, "sdc_heal_s": None,
                "sdc_error": f"rc={proc.returncode}: {proc.stderr[-300:]}"}
    except Exception as e:
        return {"sdc_detected_ok": False, "sdc_heal_s": None,
                "sdc_error": repr(e)}


def _measure_pipeline_ab():
    """Run pipeline_ab_main in a scrubbed-CPU subprocess; returns its keys
    or {pipeline_byte_identical: False, pipeline_ab_error} — every bench
    line records whether round-pipelined proving (depth=4) beats lockstep
    (depth=1) on the same jobs with byte-identical proofs. Own timeout
    knob: a cold XLA compile of the jax prover is ~450 s before the two
    timed arms even start (warm cache: ~6 min total). Never fails the
    bench."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--pipeline-ab"],
            cwd=REPO, env=_scrubbed_cpu_env(), capture_output=True, text=True,
            timeout=int(os.environ.get("DPT_BENCH_PIPELINE_TIMEOUT", "1500")))
        for line in reversed(proc.stdout.strip().splitlines() or [""]):
            if line.strip().startswith("{"):
                return json.loads(line)
        return {"pipeline_byte_identical": False,
                "pipeline_speedup_vs_lockstep": None,
                "pipelined_proofs_per_s": None,
                "pipeline_ab_error":
                    f"rc={proc.returncode}: {proc.stderr[-300:]}"}
    except Exception as e:
        return {"pipeline_byte_identical": False,
                "pipeline_speedup_vs_lockstep": None,
                "pipelined_proofs_per_s": None,
                "pipeline_ab_error": repr(e)}


def _measure_service_roundtrip():
    """Run service_roundtrip_main in a scrubbed-CPU subprocess; returns its
    keys, or {service_error} — the bench line never fails on it."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--service-roundtrip"],
            cwd=REPO, env=_scrubbed_cpu_env(), capture_output=True, text=True,
            timeout=int(os.environ.get("DPT_BENCH_SERVICE_TIMEOUT", "300")))
        for line in reversed(proc.stdout.strip().splitlines() or [""]):
            if line.strip().startswith("{"):
                return json.loads(line)
        return {"service_error": f"rc={proc.returncode}: {proc.stderr[-300:]}"}
    except Exception as e:
        return {"service_error": repr(e)}


def main():
    if "--inner" in sys.argv:
        if os.environ.get("DPT_BENCH_INNER_NO_PARTIAL"):
            global _partial_put
            _partial_put = lambda extra: None
        inner_main()
        return
    if "--service-roundtrip" in sys.argv:
        service_roundtrip_main()
        return
    if "--fleet-chaos" in sys.argv:
        fleet_chaos_main()
        return
    if "--fleet-heal" in sys.argv:
        fleet_heal_main()
        return
    if "--sdc-heal" in sys.argv:
        sdc_heal_main()
        return
    if "--pipeline-ab" in sys.argv:
        pipeline_ab_main()
        return
    try:
        os.remove(_PARTIAL)
    except OSError:
        pass
    # the CPU service round-trip is independent of the TPU path: overlap
    # it with the probe + device measurement instead of serializing ~10 s
    # (or its whole timeout when the service breaks) onto every run
    import threading
    svc_box = {}

    def _side_measurements():
        # SEQUENTIAL within the side thread: the analysis subprocess is
        # ~70 s of CPU-bound tracing and must not contend with the TIMED
        # service cold/warm round-trips; both still overlap the device
        # measurement
        svc_box.update(_measure_service_roundtrip())
        svc_box.update(_measure_fleet_chaos())
        svc_box.update(_measure_fleet_heal())
        svc_box.update(_measure_sdc_heal())
        svc_box.update(_measure_pipeline_ab())
        svc_box.update(_measure_analysis_clean())

    svc_thread = threading.Thread(target=_side_measurements, daemon=True)
    svc_thread.start()

    def svc():
        svc_thread.join(
            timeout=int(os.environ.get("DPT_BENCH_SERVICE_TIMEOUT", "300"))
            + 3 * int(os.environ.get("DPT_BENCH_FLEET_TIMEOUT", "300"))
            + int(os.environ.get("DPT_BENCH_PIPELINE_TIMEOUT", "1500"))
            + int(os.environ.get("DPT_BENCH_ANALYSIS_TIMEOUT", "600")) + 30)
        out = dict(svc_box)
        if not any(k.startswith("service") for k in out):
            out["service_error"] = "service roundtrip did not finish"
        if "fleet_chaos_proof_ok" not in out:
            out["fleet_chaos_proof_ok"] = False
            out["fleet_recoveries"] = 0
            out["fleet_chaos_error"] = "did not finish"
        if "fleet_healed_ok" not in out:
            out["fleet_healed_ok"] = False
            out["fleet_heal_s"] = None
            out["fleet_heal_error"] = "did not finish"
        if "sdc_detected_ok" not in out:
            out["sdc_detected_ok"] = False
            out["sdc_heal_s"] = None
            out["sdc_error"] = "did not finish"
        if "pipeline_byte_identical" not in out:
            out["pipeline_byte_identical"] = False
            out["pipeline_speedup_vs_lockstep"] = None
            out["pipelined_proofs_per_s"] = None
            out["pipeline_ab_error"] = "did not finish"
        if "analysis_clean" not in out:
            out["analysis_clean"] = False
            out["analysis_detail"] = "did not finish"
        return out

    probe_t = int(os.environ.get("DPT_BENCH_PROBE_TIMEOUT", "150"))
    budget = int(os.environ.get("DPT_BENCH_TIMEOUT", "3000"))
    if not (_probe_device(probe_t) or _probe_device(probe_t)):  # one retry
        _degraded("device probe failed twice (relay down or platform init hang)",
                  extra=svc())
        return
    result, err = _run_inner(dict(os.environ), budget)
    if result is not None:
        result.update(svc())
        _emit_trajectory(result)
        print(json.dumps(result))
    else:
        _degraded(err or "inner measurement failed", extra=svc())


if __name__ == "__main__":
    main()
