#!/usr/bin/env python3
"""Benchmark harness: prints ONE JSON line for the driver.

Headline metric: end-to-end prover wall-clock on the reference's v1
workload (height-32 Merkle membership, 1 proof => 2^13 domain,
/root/reference/src/dispatcher.rs:1064-1070), device backend, warm (the
steady-state number — the reference's Rust binaries have no jit phase, so
cold-compile time is excluded from the comparison and reported separately).

vs_baseline: measured speedup over this repo's own host CPU oracle (the
pure-Python v1-prover analog) on the SAME machine and workload. That
baseline is honest but weak — pure Python is far slower than the arkworks
CPU stack the reference runs on; see BASELINE.md for the ark-class
context (a modern CPU core does a 2^20 NTT in tens of ms, i.e. within ~2x
of one TPU v5e chip on this kernel — the win here is the prover
architecture, the MSM batching, and the mesh scale-out, not a 100x kernel
claim). Extra keys carry the kernel throughputs the driver's metric asks
for (2^20 NTT / 2^20 MSM).

Env knobs:
  DPT_BENCH_FAST=1       skip the prove (NTT metric becomes the headline)
  DPT_BENCH_LOG_N        NTT/MSM size (default 20)
  DPT_BENCH_PROVE_HOST=1 (re)measure the host-oracle prove baseline too
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

LOG_N = int(os.environ.get("DPT_BENCH_LOG_N", "20"))
N = 1 << LOG_N
_BASELINE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               ".bench_host_baseline.json")
# measured once on the build host (1-core VM driving the TPU tunnel) and
# recorded here so a fresh bench host need not redo a ~30-minute pure-Python
# prove; a live measurement (DPT_BENCH_PROVE_HOST=1) overrides it
_RECORDED_HOST = {
    "ntt_2p20_host_s": 33.03,       # pure-Python radix-2 FFT, 2^20
    "prove_2p13_host_s": 76.9,      # pure-Python 5-round prove, same workload
}


def _cache():
    if os.path.exists(_BASELINE_CACHE):
        with open(_BASELINE_CACHE) as f:
            return json.load(f)
    return {}


def _cache_put(key, value):
    c = _cache()
    c[key] = value
    with open(_BASELINE_CACHE, "w") as f:
        json.dump(c, f)


def host_ntt_seconds():
    key = f"ntt_2p{LOG_N}_host_s"
    c = _cache()
    if key in c:
        return c[key]
    if LOG_N == 20 and _RECORDED_HOST["ntt_2p20_host_s"]:
        return _RECORDED_HOST["ntt_2p20_host_s"]
    from distributed_plonk_tpu import poly as P
    from distributed_plonk_tpu.constants import R_MOD

    rng = random.Random(1)
    values = [rng.randrange(R_MOD) for _ in range(N)]
    t0 = time.perf_counter()
    P.fft(P.Domain(N), values)
    host_s = time.perf_counter() - t0
    _cache_put(key, host_s)
    return host_s


def device_ntt_seconds():
    """(single-poly seconds, per-poly seconds in a batch-8 launch)."""
    import numpy as np
    from distributed_plonk_tpu.backend import ntt_jax

    def sync(x):
        # a 16-element slice transfer: block_until_ready is a no-op on the
        # tunneled platform, and pulling the full array would measure the
        # tunnel's bandwidth instead of the kernel; device execution is
        # in-order, so syncing the last output fences the whole loop
        np.asarray(x[:, :1])

    plan = ntt_jax.get_plan(N)
    kernel = plan.kernel()  # Montgomery boundary: the device-resident hot path
    rng = np.random.default_rng(2)
    v = rng.integers(0, 1 << 16, size=(16, N), dtype=np.uint32)
    sync(kernel(v))  # compile + warm
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        out = kernel(v)
    sync(out)
    single = (time.perf_counter() - t0) / reps

    b = max(1, min(8, (1 << 21) // N))  # same memory cap as the backend
    kb = plan.kernel_batch()
    vb = rng.integers(0, 1 << 16, size=(16, b, N), dtype=np.uint32)
    sync(kb(vb)[:, 0])
    t0 = time.perf_counter()
    for _ in range(reps):
        out = kb(vb)
    sync(out[:, 0])
    batch = (time.perf_counter() - t0) / reps / b
    return single, batch, b


def device_msm_seconds():
    """2^LOG_N-point MSM (the reference's MSM micro-test scale,
    src/dispatcher.rs:188-196: 2^11 distinct bases tiled up to 2^20)."""
    from distributed_plonk_tpu import curve as C
    from distributed_plonk_tpu.constants import R_MOD
    from distributed_plonk_tpu.backend.msm_jax import MsmContext

    rng = random.Random(3)
    distinct = [C.g1_mul(C.G1_GEN, rng.randrange(1, R_MOD))
                for _ in range(1 << 11)]
    bases = (distinct * (N // len(distinct) + 1))[:N]
    ctx = MsmContext(bases)
    scalars = [rng.randrange(R_MOD) for _ in range(N)]
    ctx.msm(scalars)  # compile + warm
    t0 = time.perf_counter()
    ctx.msm(scalars)
    return time.perf_counter() - t0


def device_prove():
    """Warm prove of the 2^13 reference workload; returns (warm_s, cold_s,
    per-round totals)."""
    from distributed_plonk_tpu import kzg
    from distributed_plonk_tpu.workload import generate_circuit
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.verifier import verify
    from distributed_plonk_tpu.backend.jax_backend import JaxBackend
    from distributed_plonk_tpu.trace import Tracer

    ckt, _ = generate_circuit(rng=random.Random(11), height=32, num_proofs=1)
    backend = JaxBackend()
    srs = kzg.universal_setup_device(ckt.n + 2, rng=random.Random(12))
    pk, vk = kzg.preprocess(srs, ckt, backend=backend)
    t0 = time.perf_counter()
    prove(random.Random(13), ckt, pk, backend)
    cold_s = time.perf_counter() - t0
    tr = Tracer()
    t0 = time.perf_counter()
    proof = prove(random.Random(13), ckt, pk, backend, tracer=tr)
    warm_s = time.perf_counter() - t0
    assert verify(vk, ckt.public_input(), proof, rng=random.Random(14))
    return warm_s, cold_s, {k: round(v, 3) for k, v in tr.totals(1).items()}


def host_prove_seconds():
    if os.environ.get("DPT_BENCH_PROVE_HOST"):  # live measurement wins
        from distributed_plonk_tpu import kzg
        from distributed_plonk_tpu.workload import generate_circuit
        from distributed_plonk_tpu.prover import prove
        from distributed_plonk_tpu.backend.python_backend import PythonBackend

        ckt, _ = generate_circuit(rng=random.Random(11), height=32, num_proofs=1)
        srs = kzg.universal_setup(ckt.n + 2, rng=random.Random(12))
        pk, _vk = kzg.preprocess(srs, ckt)
        t0 = time.perf_counter()
        prove(random.Random(13), ckt, pk, PythonBackend())
        host_s = time.perf_counter() - t0
        _cache_put("prove_2p13_host_s", host_s)
        return host_s, "host oracle, measured on this machine this run"
    c = _cache()
    if "prove_2p13_host_s" in c:
        return (c["prove_2p13_host_s"],
                "host oracle, recorded measurement (re-measure with "
                "DPT_BENCH_PROVE_HOST=1; see BASELINE.md)")
    if _RECORDED_HOST["prove_2p13_host_s"]:
        return (_RECORDED_HOST["prove_2p13_host_s"],
                "host oracle, recorded on the build host (see BASELINE.md)")
    return None, "no host baseline available"


def main():
    extra = {}
    ntt_dev, ntt_batch, nb = device_ntt_seconds()
    extra[f"ntt_2p{LOG_N}_elements_per_s"] = round(N / ntt_dev)
    extra[f"ntt_2p{LOG_N}_device_s"] = round(ntt_dev, 5)
    extra[f"ntt_2p{LOG_N}_batch{nb}_per_poly_s"] = round(ntt_batch, 5)
    extra[f"ntt_2p{LOG_N}_vs_host_oracle"] = round(host_ntt_seconds() / ntt_dev, 2)

    msm_dev = device_msm_seconds()
    extra[f"msm_2p{LOG_N}_points_per_s"] = round(N / msm_dev)
    extra[f"msm_2p{LOG_N}_device_s"] = round(msm_dev, 3)

    if not os.environ.get("DPT_BENCH_FAST"):
        warm_s, cold_s, rounds = device_prove()
        host_s, basis = host_prove_seconds()
        extra["prove_2p13_cold_s"] = round(cold_s, 2)
        extra["prove_2p13_rounds"] = rounds
        extra["baseline_basis"] = basis
        out = {
            "metric": "prove_2p13_wall_clock",
            "value": round(warm_s, 3),
            "unit": "s",
            "vs_baseline": round(host_s / warm_s, 2) if host_s else None,
        }
    else:
        out = {
            "metric": f"ntt_2p{LOG_N}_throughput",
            "value": round(N / ntt_dev),
            "unit": "field_elements_per_s",
            "vs_baseline": extra[f"ntt_2p{LOG_N}_vs_host_oracle"],
        }
    out.update(extra)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
