#!/usr/bin/env python3
"""Benchmark harness: prints ONE JSON line for the driver.

Primary metric: single-device NTT throughput (the prover's dominant kernel,
reference hot loop /root/reference/src/worker.rs:66-115) on a 2^20 domain —
the scale of the reference's MSM micro-test (src/dispatcher.rs:188-196).

vs_baseline: speedup over the pure-Python host oracle (the stand-in for the
reference's CPU path; the reference itself publishes no numbers — see
BASELINE.md). The oracle's 2^20 wall-clock is measured once and cached in
.bench_host_baseline.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

LOG_N = int(os.environ.get("DPT_BENCH_LOG_N", "20"))
N = 1 << LOG_N
_BASELINE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               ".bench_host_baseline.json")


def host_oracle_seconds():
    key = f"ntt_2p{LOG_N}_host_s"
    if os.path.exists(_BASELINE_CACHE):
        with open(_BASELINE_CACHE) as f:
            cached = json.load(f)
        if key in cached:
            return cached[key]
    else:
        cached = {}
    import random
    from distributed_plonk_tpu import poly as P
    from distributed_plonk_tpu.constants import R_MOD

    rng = random.Random(1)
    domain = P.Domain(N)
    values = [rng.randrange(R_MOD) for _ in range(N)]
    t0 = time.perf_counter()
    P.fft(domain, values)
    host_s = time.perf_counter() - t0
    cached[key] = host_s
    with open(_BASELINE_CACHE, "w") as f:
        json.dump(cached, f)
    return host_s


def device_seconds():
    import numpy as np
    from distributed_plonk_tpu.backend import ntt_jax

    plan = ntt_jax.get_plan(N)
    kernel = plan.kernel()  # Montgomery boundary: the device-resident hot path
    rng = np.random.default_rng(2)
    v = rng.integers(0, 1 << 16, size=(16, N), dtype=np.uint32)
    out = kernel(v)
    out.block_until_ready()  # compile + warm
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        out = kernel(v)
    out.block_until_ready()
    return (time.perf_counter() - t0) / reps


def main():
    host_s = host_oracle_seconds()
    dev_s = device_seconds()
    print(json.dumps({
        "metric": f"ntt_2p{LOG_N}_throughput",
        "value": round(N / dev_s),
        "unit": "field_elements_per_s",
        "vs_baseline": round(host_s / dev_s, 2),
    }))


if __name__ == "__main__":
    main()
