"""End-to-end prove -> verify tests (host oracle backend).

The analog of the reference's end-to-end tests `test_plonk`
(/root/reference/src/dispatcher.rs:1118-1134) and `test2`
(/root/reference/src/dispatcher2.rs:1273-1295): build a satisfiable
circuit, prove, check the stock verifier accepts — plus negative cases
the reference lacks.
"""

import random

from distributed_plonk_tpu.circuit import PlonkCircuit
from distributed_plonk_tpu.prover import prove
from distributed_plonk_tpu.verifier import verify
from distributed_plonk_tpu.backend.python_backend import PythonBackend
from distributed_plonk_tpu.constants import R_MOD

# the shared `proven` fixture (circuit + keys + host proof) lives in conftest.py


def test_proof_verifies(proven):
    ckt, pk, vk, proof = proven
    assert verify(vk, ckt.public_input(), proof, rng=random.Random(2))


def test_proof_is_randomized_but_stable_given_rng(proven):
    ckt, pk, vk, _ = proven
    p1 = prove(random.Random(9), ckt, pk, PythonBackend())
    p2 = prove(random.Random(9), ckt, pk, PythonBackend())
    p3 = prove(random.Random(10), ckt, pk, PythonBackend())
    assert p1.wires_poly_comms == p2.wires_poly_comms
    assert p1.wires_poly_comms != p3.wires_poly_comms  # blinding differs
    assert verify(vk, ckt.public_input(), p3, rng=random.Random(2))


def test_wrong_public_input_rejected(proven):
    ckt, pk, vk, proof = proven
    assert not verify(vk, [5, 12], proof, rng=random.Random(3))


def test_corrupted_proof_rejected(proven):
    ckt, pk, vk, proof = proven
    import copy

    bad = copy.deepcopy(proof)
    bad.wires_evals[0] = (bad.wires_evals[0] + 1) % R_MOD
    assert not verify(vk, ckt.public_input(), bad, rng=random.Random(4))

    bad = copy.deepcopy(proof)
    bad.perm_next_eval = (bad.perm_next_eval + 1) % R_MOD
    assert not verify(vk, ckt.public_input(), bad, rng=random.Random(5))

    bad = copy.deepcopy(proof)
    bad.opening_proof = bad.shifted_opening_proof
    assert not verify(vk, ckt.public_input(), bad, rng=random.Random(6))


def test_unsatisfied_circuit_detected():
    ckt = PlonkCircuit()
    x = ckt.create_public_variable(3)
    y = ckt.create_public_variable(4)
    out = ckt.mul(x, y)
    # tamper the witness so the mul gate is violated
    ckt.witness[out] = 13
    ok, row = ckt.check_satisfiability()
    assert not ok
