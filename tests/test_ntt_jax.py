"""Device NTT kernels vs the poly.py oracle — all 8 flag combos.

Mirrors the reference's FFT integration matrix ({main,quot} x {fwd,inv} x
{coset,plain}, /root/reference/src/dispatcher.rs:273-345) on two domain
sizes, with the oracle being the pure-Python radix-2 NTT.
"""

import random

import pytest

from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.backend.ntt_jax import get_plan

RNG = random.Random(0x7717)


def _oracle(domain, values, inverse, coset):
    if inverse and coset:
        return P.coset_ifft(domain, values)
    if inverse:
        return P.ifft(domain, values)
    if coset:
        return P.coset_fft(domain, values)
    return P.fft(domain, values)


@pytest.mark.parametrize("n", [32, 128])
@pytest.mark.parametrize("inverse", [False, True])
@pytest.mark.parametrize("coset", [False, True])
def test_ntt_matches_oracle(n, inverse, coset):
    domain = P.Domain(n)
    plan = get_plan(n)
    values = [RNG.randrange(R_MOD) for _ in range(n)]
    got = plan.run_ints(values, inverse=inverse, coset=coset)
    assert got == _oracle(domain, values, inverse, coset)


def test_ntt_short_input_padding():
    n = 64
    domain = P.Domain(n)
    plan = get_plan(n)
    values = [RNG.randrange(R_MOD) for _ in range(20)]
    assert plan.run_ints(values) == P.fft(domain, values)


def test_fft_ifft_roundtrip_device():
    n = 64
    plan = get_plan(n)
    values = [RNG.randrange(R_MOD) for _ in range(n)]
    assert plan.run_ints(plan.run_ints(values), inverse=True) == values
    assert plan.run_ints(plan.run_ints(values, coset=True),
                         inverse=True, coset=True) == values
