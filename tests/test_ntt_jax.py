"""Device NTT kernels vs the poly.py oracle — all 8 flag combos, both radices.

Mirrors the reference's FFT integration matrix ({main,quot} x {fwd,inv} x
{coset,plain}, /root/reference/src/dispatcher.rs:273-345) with the oracle
being the pure-Python radix-2 NTT, on an even-log2 domain (64: pure radix-4
stages, peeled-last path) and an odd-log2 domain (128: radix-2 fixup-stage
path). The radix-4 fused-twiddle core must be BIT-identical to both the
oracle and the radix-2 parity core (`DPT_NTT_RADIX`), at single, batch,
and shared-stage-core granularity — that kernel-level identity is what
makes proofs byte-identical across radices.
"""

import random

import numpy as np
import pytest

from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.backend import ntt_jax
from distributed_plonk_tpu.backend.ntt_jax import get_plan

RNG = random.Random(0x7717)


def _oracle(domain, values, inverse, coset):
    if inverse and coset:
        return P.coset_ifft(domain, values)
    if inverse:
        return P.ifft(domain, values)
    if coset:
        return P.coset_fft(domain, values)
    return P.fft(domain, values)


@pytest.mark.parametrize("n", [64, 128])  # even and odd log2(n)
@pytest.mark.parametrize("inverse", [False, True])
@pytest.mark.parametrize("coset", [False, True])
def test_ntt_matches_oracle(n, inverse, coset):
    domain = P.Domain(n)
    plan = get_plan(n)
    values = [RNG.randrange(R_MOD) for _ in range(n)]
    got = plan.run_ints(values, inverse=inverse, coset=coset)
    assert got == _oracle(domain, values, inverse, coset)


@pytest.mark.parametrize("inverse", [False, True])
@pytest.mark.parametrize("coset", [False, True])
def test_radix2_matches_radix4(inverse, coset):
    """The radix-2 parity core and the radix-4 fused-twiddle core are
    bit-identical in every mode (n=64 reuses the radix-4 kernels compiled
    above; only the radix-2 variants compile here)."""
    n = 64
    plan = get_plan(n)
    values = [RNG.randrange(R_MOD) for _ in range(n)]
    r4 = plan.run_ints(values, inverse=inverse, coset=coset, radix=4)
    r2 = plan.run_ints(values, inverse=inverse, coset=coset, radix=2)
    assert r4 == r2


def test_radix_env_knob(monkeypatch):
    """DPT_NTT_RADIX routes kernel construction (the msm_jax
    DPT_BUCKET_UPDATE pattern): resolved per call, no plan rebuild.
    Memo keys go through autotune.cache_key (resolved mode + plan
    revision)."""
    from distributed_plonk_tpu.backend import autotune

    plan = get_plan(64)
    monkeypatch.setenv("DPT_NTT_RADIX", "2")
    plan.kernel(boundary="plain")
    assert autotune.cache_key(False, False, "plain", 2, "xla") in plan._fns
    monkeypatch.setenv("DPT_NTT_RADIX", "4")
    plan.kernel(boundary="plain")
    assert autotune.cache_key(False, False, "plain", 4, "xla") in plan._fns
    monkeypatch.setenv("DPT_NTT_RADIX", "3")
    with pytest.raises(ValueError):
        plan.kernel(boundary="plain")
    # tiny domains have no radix-4 stage: radix 4 falls back to the
    # radix-2 body and still matches the oracle
    monkeypatch.delenv("DPT_NTT_RADIX")
    tiny = get_plan(2)
    vals = [RNG.randrange(R_MOD) for _ in range(2)]
    assert tiny._effective_radix() == 2
    assert tiny.run_ints(vals, radix=4) == P.fft(P.Domain(2), vals)


def test_batch_kernel_matches_single():
    """(16, B, n) Montgomery batch kernel == B single launches, radix-4
    coset modes (the round-1/round-3 prover batches)."""
    import jax.numpy as jnp

    n, b = 64, 3
    plan = get_plan(n)
    v = np.random.default_rng(5).integers(
        0, 1 << 16, size=(16, b, n), dtype=np.uint32)
    for inverse, coset in ((False, True), (True, True)):
        got = np.asarray(plan.kernel_batch(inverse, coset, radix=4)(
            jnp.asarray(v)))
        want = np.stack(
            [np.asarray(plan.kernel(inverse, coset, radix=4)(
                jnp.asarray(v[:, j]))) for j in range(b)], axis=1)
        assert (got == want).all(), (inverse, coset)


def test_shared_stage_core_radix_parity():
    """run_stages (the core the mesh NTT and fleet panels call) is
    bit-identical across the radix-2 and radix-4 table sets, forward and
    inverse (eager dispatch: no XLA compile). Inputs must be CANONICAL
    limb vectors (< p): that is the contract every real pipeline meets,
    and the trivial-twiddle first-stage peel (which skips multiplies by
    the Montgomery ONE) is only a bitwise no-op on that domain."""
    import jax.numpy as jnp
    from distributed_plonk_tpu.backend.limbs import ints_to_limbs

    n, b = 64, 2
    plan = get_plan(n)
    vals = [RNG.randrange(R_MOD) for _ in range(b * n)]
    v = jnp.asarray(ints_to_limbs(vals, 16).reshape(16, b, n))
    for inverse in (False, True):
        c2 = {k: jnp.asarray(a)
              for k, a in plan.core_consts(inverse, radix=2).items()}
        c4 = {k: jnp.asarray(a)
              for k, a in plan.core_consts(inverse, radix=4).items()}
        assert "exps4" in c4 and "exps" in c2
        r2 = np.asarray(ntt_jax.run_stages(v, c2))
        r4 = np.asarray(ntt_jax.run_stages(v, c4))
        assert (r2 == r4).all(), inverse


def test_ntt_short_input_padding():
    n = 64
    domain = P.Domain(n)
    plan = get_plan(n)
    values = [RNG.randrange(R_MOD) for _ in range(20)]
    assert plan.run_ints(values) == P.fft(domain, values)


def test_fft_ifft_roundtrip_device():
    n = 64
    plan = get_plan(n)
    values = [RNG.randrange(R_MOD) for _ in range(n)]
    assert plan.run_ints(plan.run_ints(values), inverse=True) == values
    assert plan.run_ints(plan.run_ints(values, coset=True),
                         inverse=True, coset=True) == values
