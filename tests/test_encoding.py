"""zcash/IETF BLS12-381 encoding vs PUBLISHED golden vectors.

The generator encodings below are spec constants (IETF
draft-irtf-cfrg-pairing-friendly-curves appendix C / zcash / eth2's
BLS "genesis" pubkey material) — external ground truth this repo did not
produce, anchoring curve constants and sign conventions (VERDICT round 3
"external byte-compat evidence" item). The arkworks-LE transcript layout
(transcript.py) has no published vectors; its external anchor is the
merlin KAT in test_transcript.py.
"""

import random

import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu import encoding as E
from distributed_plonk_tpu.constants import R_MOD

# --- published golden vectors ------------------------------------------------

G1_GEN_COMPRESSED = bytes.fromhex(
    "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
    "6c55e83ff97a1aeffb3af00adb22c6bb")
G1_GEN_UNCOMPRESSED = bytes.fromhex(
    "17f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
    "6c55e83ff97a1aeffb3af00adb22c6bb"
    "08b3f481e3aaa0f1a09e30ed741d8ae4fcf5e095d5d00af600db18cb2c04b3ed"
    "d03cc744a2888ae40caa232946c5e7e1")
G2_GEN_COMPRESSED = bytes.fromhex(
    "93e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f5049"
    "334cf11213945d57e5ac7d055d042b7e"
    "024aa2b2f08f0a91260805272dc51051c6e47ad4fa403b02b4510b647ae3d177"
    "0bac0326a805bbefd48056c8c121bdb8")


def test_g1_generator_golden():
    assert E.g1_to_zcash(C.G1_GEN) == G1_GEN_COMPRESSED
    assert E.g1_to_zcash(C.G1_GEN, compressed=False) == G1_GEN_UNCOMPRESSED
    assert E.g1_from_zcash(G1_GEN_COMPRESSED) == C.G1_GEN
    assert E.g1_from_zcash(G1_GEN_UNCOMPRESSED) == C.G1_GEN


def test_g2_generator_golden():
    assert E.g2_to_zcash(C.G2_GEN) == G2_GEN_COMPRESSED
    assert E.g2_from_zcash(G2_GEN_COMPRESSED) == C.G2_GEN
    # uncompressed: x must prefix-match the compressed vector's payload
    # (flags cleared) and roundtrip; no published uncompressed G2 vector
    # is checked in (the compressed one pins the layout + sign convention)
    unc = E.g2_to_zcash(C.G2_GEN, compressed=False)
    assert unc[0] == G2_GEN_COMPRESSED[0] & 0x1F
    assert unc[1:96] == G2_GEN_COMPRESSED[1:]
    assert E.g2_from_zcash(unc) == C.G2_GEN


def test_infinity_encodings():
    # spec: compressed infinity = 0xc0 || zeros, uncompressed = 0x40 || zeros
    assert E.g1_to_zcash(None) == bytes([0xC0] + [0] * 47)
    assert E.g1_to_zcash(None, compressed=False) == bytes([0x40] + [0] * 95)
    assert E.g2_to_zcash(None) == bytes([0xC0] + [0] * 95)
    assert E.g1_from_zcash(bytes([0xC0] + [0] * 47)) is None
    assert E.g2_from_zcash(bytes([0xC0] + [0] * 95)) is None


def test_g1_roundtrip_random():
    rng = random.Random(42)
    for _ in range(8):
        p = C.g1_mul(C.G1_GEN, rng.randrange(1, R_MOD))
        for comp in (True, False):
            assert E.g1_from_zcash(E.g1_to_zcash(p, compressed=comp)) == p
        # negated point flips only the sign bit in compressed form
        np_ = C.g1_neg(p)
        a, b = E.g1_to_zcash(p), E.g1_to_zcash(np_)
        assert a[1:] == b[1:] and (a[0] ^ b[0]) == 0x20


def test_g2_roundtrip_random():
    rng = random.Random(43)
    for _ in range(4):
        p = C.g2_mul(C.G2_GEN, rng.randrange(1, R_MOD))
        for comp in (True, False):
            assert E.g2_from_zcash(E.g2_to_zcash(p, compressed=comp)) == p


def test_malformed_rejected():
    with pytest.raises(ValueError):
        E.g1_from_zcash(b"\x00" * 48)  # compressed length, flag unset
    with pytest.raises(ValueError):
        E.g1_from_zcash(bytes([0xE0]) + b"\x00" * 47)  # inf + sign
    with pytest.raises(ValueError):
        E.g1_from_zcash(bytes([0x9F]) + b"\xff" * 47)  # x >= q
    # an x with no curve point: search deterministically from the
    # generator's x for a non-residue x^3+4
    from distributed_plonk_tpu.constants import Q_MOD
    x = C.G1_GEN[0]
    while pow((pow(x, 3, Q_MOD) + 4) % Q_MOD, (Q_MOD - 1) // 2, Q_MOD) == 1:
        x += 1
    bad = bytearray(x.to_bytes(48, "big"))
    bad[0] |= 0x80
    with pytest.raises(ValueError):
        E.g1_from_zcash(bytes(bad))


def test_g1_non_subgroup_rejected():
    """ADVICE r4: decoders must reject on-curve points OUTSIDE the
    r-order subgroup (G1 cofactor ≈2^125). Search a small on-curve x
    deterministically; such a point is in the subgroup only with
    probability ~2^-125."""
    from distributed_plonk_tpu.constants import Q_MOD
    x = 0
    while True:
        y2 = (pow(x, 3, Q_MOD) + 4) % Q_MOD
        y = pow(y2, (Q_MOD + 1) // 4, Q_MOD)
        if y * y % Q_MOD == y2:
            p = (x, y)
            if not E._g1_in_subgroup(p):
                break
        x += 1
    for comp in (True, False):
        with pytest.raises(ValueError, match="subgroup"):
            E.g1_from_zcash(E.g1_to_zcash(p, compressed=comp))
    # sanity: subgroup members still decode
    assert E.g1_from_zcash(G1_GEN_COMPRESSED) == C.G1_GEN


def test_g2_non_subgroup_rejected():
    """Same for G2, whose cofactor is ≈2^378 — almost every on-curve
    point fails the subgroup check."""
    x0 = 0
    while True:
        y = E._fq2_sqrt(E._fq2_add(E._fq2_mul_xx_x((x0, 0)), (4, 4)))
        if y is not None:
            p = ((x0, 0), y)
            if not E._g2_in_subgroup(p):
                break
        x0 += 1
    for comp in (True, False):
        with pytest.raises(ValueError, match="subgroup"):
            E.g2_from_zcash(E.g2_to_zcash(p, compressed=comp))
    assert E.g2_from_zcash(G2_GEN_COMPRESSED) == C.G2_GEN
