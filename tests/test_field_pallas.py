"""Pallas fused mont_mul vs the host field oracle (interpret mode on CPU;
the same kernel runs compiled on TPU via DPT_FIELD_MUL=pallas)."""

import random

import numpy as np
import pytest

from distributed_plonk_tpu.constants import R_MOD, Q_MOD, FR_MONT_R, FQ_MONT_R
from distributed_plonk_tpu.backend import field_pallas as FP
from distributed_plonk_tpu.backend.field_jax import FR, FQ
from distributed_plonk_tpu.backend.limbs import ints_to_limbs, limbs_to_ints

RNG = random.Random(0xA110)


def _check(spec, mod, mont_r, n):
    xs = [RNG.randrange(mod) for _ in range(n)]
    ys = [RNG.randrange(mod) for _ in range(n)]
    # include edge values
    xs[:3] = [0, 1, mod - 1]
    ys[:3] = [mod - 1, 0, mod - 1]
    a = ints_to_limbs(xs, spec.n_limbs)
    b = ints_to_limbs(ys, spec.n_limbs)
    out = np.asarray(FP.mont_mul(spec, a, b))
    got = limbs_to_ints(out)
    r_inv = pow(mont_r, mod - 2, mod)
    exp = [x * y % mod * r_inv % mod for x, y in zip(xs, ys)]
    assert got == exp


def test_mont_mul_fr_matches_oracle():
    _check(FR, R_MOD, FR_MONT_R, 64)


def test_mont_mul_fq_matches_oracle():
    _check(FQ, Q_MOD, FQ_MONT_R, 64)


@pytest.mark.parametrize("variant", ["lazy", "mxu"])
@pytest.mark.parametrize("spec_key,mod,mont_r", [
    ("fr", R_MOD, FR_MONT_R), ("fq", Q_MOD, FQ_MONT_R)])
def test_mont_mul_variants_bit_identical(spec_key, mod, mont_r, variant):
    """Every kernel variant must be BIT-identical to the strict kernel
    and the host oracle: the lazy kernel (semi-normalized digit columns,
    3 exact sweeps instead of 5) and the mxu kernel (constant Toeplitz
    bands as bf16 matmuls) use different mid-kernel m' representatives,
    but the final conditional subtract lands on the canonical value."""
    spec = FR if spec_key == "fr" else FQ
    n = FP.LANE_TILE  # exactly one grid step
    xs = [RNG.randrange(mod) for _ in range(n)]
    ys = [RNG.randrange(mod) for _ in range(n)]
    xs[:4] = [0, 1, mod - 1, mod - 2]
    ys[:4] = [mod - 1, 0, mod - 1, mod - 2]
    a = ints_to_limbs(xs, spec.n_limbs)
    b = ints_to_limbs(ys, spec.n_limbs)
    strict = np.asarray(FP._mont_mul_flat(spec_key, True, "strict", n,
                                          a, b))
    got = np.asarray(FP._mont_mul_flat(spec_key, True, variant, n, a, b))
    assert np.array_equal(strict, got)
    r_inv = pow(mont_r, mod - 2, mod)
    assert limbs_to_ints(got) == [
        x * y % mod * r_inv % mod for x, y in zip(xs, ys)]


def test_broadcast_and_batch_shapes():
    n = 8
    xs = [RNG.randrange(R_MOD) for _ in range(n)]
    y = RNG.randrange(R_MOD)
    a = ints_to_limbs(xs, FR.n_limbs).reshape(16, 2, 4)
    b = ints_to_limbs([y], FR.n_limbs).reshape(16, 1, 1)
    out = np.asarray(FP.mont_mul(FR, a, b)).reshape(16, n)
    r_inv = pow(FR_MONT_R, R_MOD - 2, R_MOD)
    assert limbs_to_ints(out) == [x * y % R_MOD * r_inv % R_MOD for x in xs]
