"""Fused Pallas MSM bucket kernel (msm_pallas) vs the XLA scan paths.

The VMEM-resident bucket-accumulation kernel must be BIT-IDENTICAL to
msm_jax's lax.scan cores at the same group width — planes, not just
points — for every registered digit width (signed c=7/c=8, unsigned
c=4), both plane packings, batched lanes, and the prover's blinded
n+2/n+3 handle widths; and the DPT_MSM_KERNEL dispatch must leave the
end-to-end MSM (and proof bytes, test_jax_backend_prove) unchanged.
Interpret mode on CPU; the same kernels compile with Mosaic on TPU.

Interpret-mode Mosaic emulation compiles ~30 s per distinct kernel
shape, so the tier-1 set keeps shapes tiny and few; the full-prove
byte-identity run rides the slow tier.
"""

import random

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu.constants import FR_MONT_R, R_MOD
from distributed_plonk_tpu.backend import field_jax as FJ
from distributed_plonk_tpu.backend import msm_jax as M
from distributed_plonk_tpu.backend import msm_pallas as MP
from distributed_plonk_tpu.backend.limbs import ints_to_limbs

RNG = random.Random(0xB0C8)


@pytest.fixture(scope="module")
def pts16():
    n = 16
    pts = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
           for _ in range(n - 2)] + [None, None]
    ax, ay, ainf = M.points_to_device(pts, 0)
    return pts, jnp.asarray(ax), jnp.asarray(ay), jnp.asarray(ainf)


def _assert_planes_equal(got, ref, what):
    for g, r in zip(got, ref):
        assert np.array_equal(np.asarray(g), np.asarray(r)), what


def _c7_batch_digits():
    scal = [[RNG.randrange(R_MOD) for _ in range(16)] for _ in range(2)]
    return jnp.asarray(np.stack(
        [M.signed_digits7_of_scalars(s, 16) for s in scal]).reshape(74, 16))


def test_signed_c7_batch_bit_identity(pts16, monkeypatch):
    """Signed c=7 (the default batched pipeline), 2-poly batch, G=2:
    the fused kernel's planes are limb-identical to the XLA onehot
    scan. (Each distinct kernel shape costs ~30 s of interpret-mode
    Mosaic emulation compile, so the unpacked/put cross-checks ride the
    slow tier below.)"""
    _, ax, ay, ainf = pts16
    flat = _c7_batch_digits()
    monkeypatch.setattr(M, "_MSM_KERNEL", "xla")
    ref = M._bucket_scan_signed(ax, ay, ainf, flat, 2, n_buckets=64)
    got = MP.bucket_scan_signed(ax, ay, ainf, flat, 2, n_buckets=64)
    _assert_planes_equal(got, ref, "pallas packed c7")


@pytest.mark.slow
def test_signed_c7_unpacked_and_put_identity(pts16, monkeypatch):
    """The unpacked-plane kernel variant and the XLA put-strategy scan
    agree with the onehot reference limb for limb."""
    _, ax, ay, ainf = pts16
    flat = _c7_batch_digits()
    monkeypatch.setattr(M, "_MSM_KERNEL", "xla")
    ref = M._bucket_scan_signed(ax, ay, ainf, flat, 2, n_buckets=64)
    monkeypatch.setattr(M, "_BUCKET_UPDATE", "put")
    monkeypatch.setattr(M, "_PLANE_PACK", False)
    _assert_planes_equal(
        M._bucket_scan_signed(ax, ay, ainf, flat, 2, n_buckets=64), ref,
        "xla put vs onehot")
    got = MP.bucket_scan_signed(ax, ay, ainf, flat, 2, n_buckets=64,
                                packed=False)
    _assert_planes_equal(got, ref, "pallas unpacked c7")


def test_signed_c8_bit_identity(pts16, monkeypatch):
    _, ax, ay, ainf = pts16
    scal = [RNG.randrange(R_MOD) for _ in range(16)]
    flat = jnp.asarray(M.signed_digits_of_scalars(scal, 16))  # (32, 16)
    monkeypatch.setattr(M, "_MSM_KERNEL", "xla")
    ref = M._bucket_scan_signed(ax, ay, ainf, flat, 1, n_buckets=128)
    got = MP.bucket_scan_signed(ax, ay, ainf, flat, 1, n_buckets=128)
    _assert_planes_equal(got, ref, "pallas signed c8")


def test_unsigned_c4_bit_identity(pts16, monkeypatch):
    """Unsigned small-window scan (tiny keys): bucket 0 rows included,
    only infinity columns skipped — exactly like the XLA core."""
    _, ax, ay, ainf = pts16
    scal = [RNG.randrange(R_MOD) for _ in range(16)]
    flat = jnp.asarray(M.digits_of_scalars(scal, 16, 4))  # (64, 16)
    monkeypatch.setattr(M, "_MSM_KERNEL", "xla")
    ref = M._bucket_scan(ax, ay, ainf, flat, 2, 16)
    got = MP.bucket_scan(ax, ay, ainf, flat, 2, 16)
    _assert_planes_equal(got, ref, "pallas unsigned c4")


@pytest.mark.slow
def test_msm_forced_pallas_matches_oracle_and_xla(monkeypatch):
    """End-to-end MsmContext dispatch: DPT_MSM_KERNEL=pallas must give
    the same point as the XLA path and the host oracle (the fold /
    finish tails are shared, so plane identity implies point identity —
    this locks the dispatch plumbing and the pallas group-size cap)."""
    n = 64
    pts = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
           for _ in range(16)] * (n // 16)
    ks = [RNG.randrange(R_MOD) for _ in range(n)]
    monkeypatch.setattr(M, "_MSM_KERNEL", "xla")
    want = M.msm(pts, ks)
    assert want == C.g1_msm(pts, ks)
    monkeypatch.setattr(M, "_MSM_KERNEL", "pallas")
    assert M.msm(pts, ks) == want


@pytest.mark.slow
def test_blinded_handle_widths(monkeypatch):
    """Montgomery coefficient handles at the prover's blinded n+2/n+3
    widths (narrower than the key) commit to the same points under both
    kernels — the digit-extraction width is part of the jit key, so the
    widths must be exercised, not assumed."""
    dom = 32
    pts = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
           for _ in range(dom + 8)]
    handles = []
    for L in (dom + 2, dom + 3):
        vals = [RNG.randrange(R_MOD) for _ in range(L)]
        handles.append(jnp.asarray(
            ints_to_limbs([v * FR_MONT_R % R_MOD for v in vals], 16)))
    monkeypatch.setattr(M, "_MSM_KERNEL", "xla")
    want = M.MsmContext(pts).msm_mont_limbs_many(handles)
    monkeypatch.setattr(M, "_MSM_KERNEL", "pallas")
    got = M.MsmContext(pts).msm_mont_limbs_many(handles)
    assert got == want


def test_aot_compile_pallas_kernel_and_mul_path(monkeypatch):
    """MsmContext.aot_compile under DPT_MSM_KERNEL=pallas lowers the
    fused bucket kernel (the Mosaic compile is the cold-start cost the
    warmup exists to hide) and, with the fused multiplier gate on,
    pre-lowers field_pallas at the XLA scan's group-product widths —
    the PR 3 'Pallas mul path has no AOT hook' remainder. The context
    must still commit correctly afterwards."""
    monkeypatch.setattr(M, "_MSM_KERNEL", "pallas")
    monkeypatch.setattr(FJ, "_MUL_MODE", "pallas")
    monkeypatch.setattr(FJ, "_PALLAS_MIN_LANES", 1)
    n = 64
    pts = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
           for _ in range(16)] * (n // 16)
    ctx = M.MsmContext(pts)
    rep = ctx.aot_compile(batch_sizes=(1,),
                          digit_widths=(n + 2, n + 3))
    assert rep["failed"] == 0, rep
    assert rep["kernel"] == "pallas"
    assert rep["shapes"][0]["kernel"] == "pallas"
    assert rep["mul_path_widths"], rep
    monkeypatch.setattr(FJ, "_MUL_MODE", "auto")
    ks = [RNG.randrange(R_MOD) for _ in range(n)]
    assert ctx.msm(ks) == C.g1_msm(pts, ks)
