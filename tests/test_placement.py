"""Placement-aware scheduler + cross-job batched proving tests.

The hard contract pinned here: a BATCHED prove (N same-shape jobs in one
prover.prove_many lockstep, commit MSMs / evaluations launched across
jobs) produces proof bytes BYTE-IDENTICAL to N sequential proves — with
mixed per-job blinding RNGs, through the whole service path, with the
DPT_BATCH_PROVE=0 parity escape, and when one batch member is killed
mid-prove (it resumes ALONE from its snapshot; the others finish in the
original batch). Plus the submesh leasing model: a big "mesh"-classified
job and a small batch divide one injected device pool disjointly and
every lease is released.

Everything runs the host oracle backend at tiny domains (jax-free), so
the module lives in the fast/chaos tier.
"""

import random
import threading
import time

import pytest

from distributed_plonk_tpu.backend.python_backend import PythonBackend
from distributed_plonk_tpu.proof_io import serialize_proof
from distributed_plonk_tpu.prover import prove, prove_many
from distributed_plonk_tpu.service import ProofService
from distributed_plonk_tpu.service import placement as PL
from distributed_plonk_tpu.service.jobs import (JobSpec, build_bucket_keys,
                                                build_circuit)
from distributed_plonk_tpu.service.placement import (SubmeshLeaser, classify)

TOY = {"kind": "toy", "gates": 16}


def _sequential_proof(spec_obj, _pk_cache={}):
    """Uninterrupted single prove of a spec — the byte oracle."""
    s = JobSpec.from_wire(spec_obj)
    key = (s.kind, tuple(sorted(s.params.items())))
    if key not in _pk_cache:
        _pk_cache[key] = build_bucket_keys(s)[1]
    return serialize_proof(prove(random.Random(s.seed), build_circuit(s),
                                 _pk_cache[key], PythonBackend()))


# --- classification + leasing units ------------------------------------------

def test_classify_thresholds(monkeypatch):
    monkeypatch.setattr(PL, "SMALL_MAX", 1 << 14)
    monkeypatch.setattr(PL, "LARGE_MIN", 1 << 18)
    assert classify(1 << 10) == "batch"
    assert classify(1 << 14) == "batch"
    assert classify((1 << 14) + 1) == "pool"
    assert classify((1 << 18) - 1) == "pool"
    assert classify(1 << 18) == "mesh"
    assert classify(1 << 20) == "mesh"


def test_leaser_disjoint_contiguous_release():
    leaser = SubmeshLeaser([10, 11, 12, 13])
    a = leaser.lease(2)
    b = leaser.lease(1)
    # disjoint, and the 2-wide lease is a contiguous run
    assert set(a.devices).isdisjoint(b.devices)
    assert list(a.devices) == [10, 11]
    assert leaser.free_count() == 1
    # opportunistic probe: only 1 device free, a 2-wide ask says no NOW
    assert leaser.lease(2, timeout_s=0) is None
    c = leaser.lease(1, timeout_s=0)
    assert c is not None and leaser.free_count() == 0
    # nothing free: probe fails, blocking ask with a timeout times out
    assert leaser.lease(1, timeout_s=0) is None
    assert leaser.lease(1, timeout_s=0.05) is None
    for lease in (a, b, c):
        leaser.release(lease)
    assert leaser.free_count() == 4
    # double release is a no-op, not a free-list corruption
    leaser.release(a)
    assert leaser.free_count() == 4
    # oversized asks clamp to the pool
    big = leaser.lease(99)
    assert len(big) == 4


def test_leaser_blocking_handoff():
    leaser = SubmeshLeaser([0, 1])
    a = leaser.lease(2)
    got = {}

    def taker():
        got["lease"] = leaser.lease(1)  # blocks until the release

    t = threading.Thread(target=taker, daemon=True)
    t.start()
    time.sleep(0.05)
    assert "lease" not in got
    leaser.release(a)
    t.join(timeout=5)
    assert len(got["lease"]) == 1


# --- batched-vs-sequential byte-identity -------------------------------------

@pytest.mark.parametrize("n_jobs", [2, 4])
def test_prove_many_byte_identity_mixed_rngs(n_jobs):
    """prove_many == N sequential proves, bit for bit, with a DIFFERENT
    blinding rng per member (the per-member rng/transcript isolation the
    placement batch depends on)."""
    specs = [JobSpec.from_wire(dict(TOY, seed=50 + 7 * i))
             for i in range(n_jobs)]
    pk = build_bucket_keys(specs[0])[1]
    be = PythonBackend()
    want = [serialize_proof(prove(random.Random(s.seed), build_circuit(s),
                                  pk, be)) for s in specs]
    proofs, errors = prove_many(
        [random.Random(s.seed) for s in specs],
        [build_circuit(s) for s in specs], pk, PythonBackend())
    assert errors == [None] * n_jobs
    assert [serialize_proof(p) for p in proofs] == want


def _batched_service_run(specs, **svc_kwargs):
    """Submit specs BEFORE the scheduler starts (so pop_batch sees them
    as one shape batch), wait for all, return (service, jobs)."""
    svc = ProofService(port=0, prover_workers=1, **svc_kwargs)
    jobs = [svc.submit_local(s) for s in specs]
    svc.start()
    for j in jobs:
        assert j.done_event.wait(timeout=180), j.status()
    return svc, jobs


def test_service_batch_byte_identity():
    """The whole service path: 4 same-shape jobs pop as ONE placement
    batch, prove data-parallel, and every proof is byte-identical to an
    uninterrupted sequential prove of its spec."""
    specs = [dict(TOY, seed=900 + i) for i in range(4)]
    svc, jobs = _batched_service_run(specs)
    try:
        ctr = svc.metrics.snapshot()["counters"]
        assert ctr.get("placement_batch") == 1
        assert ctr.get("batch_proves") == 1
        assert ctr.get("batch_jobs") == 4
        for spec, job in zip(specs, jobs):
            assert job.state == "done"
            assert job.placement == "batch"
            assert job.status()["placement"] == "batch"
            assert job.proof_bytes == _sequential_proof(spec)
    finally:
        svc.shutdown()


def test_batch_prove_knob_off_parity(monkeypatch):
    """DPT_BATCH_PROVE=0: same traffic takes the sequential per-job pool
    path — zero batched attempts — and lands on the identical bytes."""
    monkeypatch.setattr(PL, "BATCH_PROVE", False)
    specs = [dict(TOY, seed=930 + i) for i in range(3)]
    svc, jobs = _batched_service_run(specs)
    try:
        ctr = svc.metrics.snapshot()["counters"]
        assert "batch_proves" not in ctr
        assert ctr.get("placement_pool") == 1
        for spec, job in zip(specs, jobs):
            assert job.placement == "pool"
            assert job.proof_bytes == _sequential_proof(spec)
    finally:
        svc.shutdown()


# --- batch member kill: resumes alone, others unaffected ---------------------

def test_batch_member_kill_resumes_alone():
    """A kill armed at round 2 fires on exactly ONE batch member (the
    first to reach that boundary). The member's snapshot is durable, so
    its solo retry RESUMES (no round-1 re-prove) to byte-identical
    bytes; the other members finish inside the original batch; the
    worker thread survives (no respawn)."""
    specs = [dict(TOY, seed=950 + i) for i in range(3)]
    svc = ProofService(port=0, prover_workers=1)
    jobs = [svc.submit_local(s) for s in specs]
    victim_name = svc.pool.kill_worker(at_round=2)  # pre-armed on w0g1
    svc.start()
    try:
        for j in jobs:
            assert j.done_event.wait(timeout=180), j.status()
            assert j.state == "done"
        ctr = svc.metrics.snapshot()["counters"]
        assert ctr.get("batch_member_kills") == 1
        assert ctr.get("checkpoint_resumes", 0) >= 1
        # the batch's worker thread was NOT killed/respawned
        assert ctr.get("workers_spawned") == 1
        assert "workers_killed" not in ctr
        killed = [j for j in jobs
                  if any(a["outcome"] == "killed" for a in j.attempts)]
        assert len(killed) == 1
        assert [a["outcome"] for a in killed[0].attempts] == ["killed", "ok"]
        assert killed[0].worker == victim_name  # same slot retried it
        for j in jobs:
            if j is not killed[0]:
                assert [a["outcome"] for a in j.attempts] == ["ok"]
        for spec, job in zip(specs, jobs):
            assert job.proof_bytes == _sequential_proof(spec)
    finally:
        svc.shutdown()


def test_batch_member_kill_by_job_id():
    """A JOB-targeted kill inside a running batch takes down only that
    member. Uses a bigger shape so the kill lands mid-prove."""
    specs = [{"kind": "toy", "gates": 120, "seed": 970 + i}
             for i in range(3)]
    svc = ProofService(port=0, prover_workers=1)
    jobs = [svc.submit_local(s) for s in specs]
    target = jobs[2]
    svc.start()
    try:
        deadline = time.monotonic() + 60
        killed_armed = False
        while time.monotonic() < deadline and not killed_armed:
            if target.state == "running":
                try:
                    svc.pool.kill_worker(job_id=target.id, at_round=None)
                    killed_armed = True
                except LookupError:
                    pass
            if target.done_event.is_set():
                break
            time.sleep(0.005)
        for j in jobs:
            assert j.done_event.wait(timeout=180), j.status()
            assert j.state == "done"
        for spec, job in zip(specs, jobs):
            assert job.proof_bytes == _sequential_proof(spec)
        if killed_armed and any(a["outcome"] == "killed"
                                for a in target.attempts):
            # the kill landed: it must have hit ONLY the target
            for j in jobs:
                if j is not target:
                    assert all(a["outcome"] != "killed"
                               for a in j.attempts)
    finally:
        svc.shutdown()


# --- submesh leasing: big sharded job + small batch coexist ------------------

class _RecordingMeshFactory:
    """Stub mesh-backend factory: records each lease's devices and
    proves on the host oracle (placement logic is what is under test,
    not mesh kernels)."""

    def __init__(self, hold_s=0.0):
        self.calls = []
        self.hold_s = hold_s

    def __call__(self, devices):
        self.calls.append(tuple(devices))
        hold = self.hold_s

        class _SlowBackend(PythonBackend):
            def pk_polys(self, pk):  # first backend touch of a prove
                if hold:
                    time.sleep(hold)
                return super().pk_polys(pk)

        return _SlowBackend()


def test_submesh_lease_interleaved(monkeypatch):
    """A big 'mesh'-classified job leases a disjoint submesh of the
    injected 4-device pool while a small batch still gets served (and
    takes its own 1-device lease); every lease is released at the end."""
    monkeypatch.setattr(PL, "LARGE_MIN", 256)  # n=512 toy -> "mesh"
    factory = _RecordingMeshFactory(hold_s=0.3)
    devices = ["d0", "d1", "d2", "d3"]
    svc = ProofService(port=0, prover_workers=2, devices=devices,
                       mesh_backend_factory=factory)
    big_spec = {"kind": "toy", "gates": 300, "seed": 777}   # n=512
    small_specs = [dict(TOY, seed=980 + i) for i in range(2)]
    big = svc.submit_local(big_spec)
    smalls = [svc.submit_local(s) for s in small_specs]
    svc.start()
    try:
        # while the big job holds its submesh, the small batch completes
        for j in smalls:
            assert j.done_event.wait(timeout=180), j.status()
        assert big.done_event.wait(timeout=180), big.status()
        assert big.state == "done" and big.placement == "mesh"
        assert all(j.placement == "batch" for j in smalls)
        ctr = svc.metrics.snapshot()["counters"]
        assert ctr.get("placement_mesh") == 1
        assert ctr.get("placement_batch") == 1
        # big job leased half the pool (auto policy: 4 devices -> 2),
        # contiguous; the batch's opportunistic lease was disjoint
        assert ctr.get("submesh_leases", 0) >= 2
        assert len(factory.calls) == 1
        leased = list(factory.calls[0])
        assert len(leased) == 2 and set(leased) <= set(devices)
        idx = sorted(devices.index(d) for d in leased)
        assert idx[1] - idx[0] == 1  # contiguous run (ICI locality)
        # all leases released: the pool is whole again, and the gauge
        # tracked the release edge (not just the grant low-water mark)
        assert svc.scheduler.leaser().free_count() == 4
        gauges = svc.metrics.snapshot()["gauges"]
        assert gauges.get("submesh_devices_free") == 4
        # byte-identity holds on the mesh-placed job too
        assert big.proof_bytes == _sequential_proof(big_spec)
        for spec, j in zip(small_specs, smalls):
            assert j.proof_bytes == _sequential_proof(spec)
    finally:
        svc.shutdown()


def test_mesh_retry_replaces_on_submesh(monkeypatch):
    """A mesh-placed job whose attempt is killed mid-prove goes BACK
    through the scheduler for re-placement: the retry runs on a fresh
    submesh lease (not silently on the worker's shared single-device
    backend), resumes from its snapshot, and lands on identical bytes."""
    monkeypatch.setattr(PL, "LARGE_MIN", 256)
    factory = _RecordingMeshFactory()
    svc = ProofService(port=0, prover_workers=1,
                       devices=["m0", "m1", "m2", "m3"],
                       mesh_backend_factory=factory)
    spec = {"kind": "toy", "gates": 300, "seed": 444}
    job = svc.submit_local(spec)
    svc.pool.kill_worker(at_round=2)  # fires on the mesh prove's worker
    svc.start()
    try:
        assert job.done_event.wait(timeout=180), job.status()
        assert job.state == "done"
        assert job.retries >= 1
        assert [a["outcome"] for a in job.attempts] == ["killed", "ok"]
        # re-placed: still "mesh", a SECOND lease was granted, and both
        # attempts ran on factory-built (leased-submesh) backends
        assert job.placement == "mesh"
        ctr = svc.metrics.snapshot()["counters"]
        assert ctr.get("placement_mesh") == 2
        assert ctr.get("submesh_leases", 0) >= 2
        assert ctr.get("checkpoint_resumes", 0) >= 1
        assert svc.scheduler.leaser().free_count() == 4
        assert job.proof_bytes == _sequential_proof(spec)
    finally:
        svc.shutdown()


def test_mesh_lease_released_on_failure(monkeypatch):
    """A mesh prove that dies still returns its devices to the pool."""
    monkeypatch.setattr(PL, "LARGE_MIN", 256)

    class _Boom(PythonBackend):
        def pk_polys(self, pk):
            raise RuntimeError("mesh backend exploded")

    svc = ProofService(port=0, prover_workers=1, max_retries=0,
                       devices=["a", "b"],
                       mesh_backend_factory=lambda devs: _Boom())
    job = svc.submit_local({"kind": "toy", "gates": 300, "seed": 5})
    svc.start()
    try:
        assert job.done_event.wait(timeout=120), job.status()
        assert job.state == "failed"
        assert svc.scheduler.leaser().free_count() == 2
    finally:
        svc.shutdown()
