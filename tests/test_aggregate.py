"""Batch-KZG proof aggregation tests (ISSUE 17): N proofs in, ONE 2-pair
pairing check out — pinned by the curve-level pairing counters — accepting
iff every constituent verifies, rejecting bit-flipped members and tampered
artifacts, and surviving a service restart via journal AGG recovery.
"""

import json
import random

import pytest

from distributed_plonk_tpu import aggregate as AGG
from distributed_plonk_tpu import curve
from distributed_plonk_tpu.backend.python_backend import PythonBackend
from distributed_plonk_tpu.proof_io import serialize_proof
from distributed_plonk_tpu.prover import prove
from distributed_plonk_tpu.service.jobs import (JobSpec, build_bucket_keys,
                                                build_circuit, shape_key)

# mixed-kind member pool: both shapes finalize at n=32, so the whole
# 8-member batch proves in seconds while still exercising cross-kind folds
_SHAPES = [{"kind": "toy", "gates": 16},
           {"kind": "range", "bits": 8, "count": 2}]
_keys = {}  # shape_key -> bucket keys, shared across every test here


def _member(i):
    wire = dict(_SHAPES[i % len(_SHAPES)], seed=9000 + i)
    spec = JobSpec.from_wire(wire)
    k = shape_key(spec)
    if k not in _keys:
        _keys[k] = build_bucket_keys(spec)
    ckt = build_circuit(spec)
    proof = prove(random.Random(spec.seed), ckt, _keys[k][1],
                  PythonBackend())
    return {"job_id": f"job-{i}", "spec": spec.to_wire(),
            "pub": ckt.public_input(), "proof": serialize_proof(proof)}


def _vks():
    return {k: v[2] for k, v in _keys.items()}


@pytest.fixture(scope="module")
def members8():
    return [_member(i) for i in range(8)]


def test_n8_mixed_kind_single_pairing_check(members8):
    """THE amortization claim: verifying an 8-member mixed-kind batch
    costs exactly one pairing check with two pairs."""
    agg = AGG.build(members8)
    assert len({m["spec"]["kind"] for m in agg["members"]}) == 2
    curve.reset_pairing_counters()
    assert AGG.verify(agg, _vks())
    assert curve.PAIRING_COUNTERS == {"checks": 1, "pairs": 2}


def test_content_addressed_and_byte_roundtrip(members8):
    agg = AGG.build(members8)
    assert AGG.build(members8) == agg  # deterministic
    blob = AGG.to_bytes(agg)
    assert AGG.from_bytes(blob) == agg
    assert AGG.to_bytes(AGG.from_bytes(blob)) == blob
    # member order is part of the content address
    assert AGG.build(list(reversed(members8)))["agg_id"] != agg["agg_id"]


def test_transcript_binds_every_member_bit(members8):
    norm = AGG.build(members8)["members"]
    base = AGG.derive_challenges(norm)
    assert len({c for pair in base for c in pair}) == 16  # all distinct
    tam = [dict(m) for m in norm]
    pb = bytearray(bytes.fromhex(tam[-1]["proof"]))
    pb[0] ^= 1
    tam[-1]["proof"] = bytes(pb).hex()
    shifted = AGG.derive_challenges(tam)
    # absorb-everything-THEN-draw: flipping the LAST member's first bit
    # moves even the FIRST member's challenges
    assert shifted[0] != base[0]


def test_rejects_one_bit_flipped_member(members8):
    bad = [dict(m) for m in members8]
    pb = bytearray(bad[3]["proof"])
    pb[len(pb) // 2] ^= 0x01
    bad[3]["proof"] = bytes(pb)
    # a CONSISTENT artifact around a corrupt constituent: the content
    # address matches, so rejection comes from the fold itself
    assert not AGG.verify(AGG.build(bad), _vks())
    # the other 7 still aggregate fine
    assert AGG.verify(AGG.build(bad[:3] + bad[4:]), _vks())


def test_rejects_tampered_artifact(members8):
    agg = AGG.build(members8)
    tam = json.loads(AGG.to_bytes(agg).decode())
    tam["members"][0]["job_id"] = "evil"
    assert not AGG.verify(tam, _vks())  # content address mismatch
    tam2 = json.loads(AGG.to_bytes(agg).decode())
    tam2["agg_id"] = "agg-" + "0" * 16
    assert not AGG.verify(tam2, _vks())


def test_accepts_iff_every_member_verifies(members8):
    vks = _vks()
    assert AGG.verify(AGG.build(members8[:1]), vks)
    assert AGG.verify(AGG.build(members8[:5]), vks)
    bad = dict(members8[0], job_id="forged")
    pb = bytearray(bad["proof"])
    pb[100] ^= 0xFF
    bad["proof"] = bytes(pb)
    assert not AGG.verify(AGG.build(members8[:5] + [bad]), vks)


def test_empty_and_malformed_artifacts():
    with pytest.raises(ValueError):
        AGG.build([])
    for blob in (b"junk", b"{}", b'{"schema": 1, "members": []}'):
        with pytest.raises(ValueError):
            AGG.from_bytes(blob)
    assert not AGG.verify(b"junk")


def test_aggregate_all_or_nothing_on_pending_or_unknown_member():
    from distributed_plonk_tpu.service import ProofService
    svc = ProofService(port=0, prover_workers=1).start()
    try:
        done = svc.submit_local({"kind": "toy", "gates": 16, "seed": 41})
        assert done.done_event.wait(180) and done.state == "done"
        pending = svc.submit_local({"kind": "toy", "gates": 300,
                                    "seed": 42})
        if pending.state != "done":  # n=512 proves for seconds; no race
            with pytest.raises(ValueError):
                svc.aggregate_jobs([done.id, pending.id])
        with pytest.raises(LookupError):
            svc.aggregate_jobs([done.id, "job-unknown"])
        with pytest.raises(ValueError):
            svc.aggregate_jobs([])
        assert svc.metrics.snapshot()["counters"].get(
            "aggregates_built", 0) == 0
    finally:
        svc.shutdown()


def test_service_aggregate_round_trip_survives_restart(tmp_path):
    """End to end over the wire: submit a mixed-kind batch, AGGREGATE,
    fetch + client-verify the artifact, restart the service on the same
    journal/store, and fetch + verify the SAME artifact again."""
    from distributed_plonk_tpu.service import ProofService, ServiceClient
    from distributed_plonk_tpu.service.client import ServiceError
    jdir, sdir = str(tmp_path / "j"), str(tmp_path / "s")
    specs = [{"kind": "toy", "gates": 16, "seed": 21},
             {"kind": "range", "bits": 8, "count": 2, "seed": 22},
             {"kind": "toy", "gates": 16, "seed": 23}]
    svc = ProofService(port=0, prover_workers=1, journal_dir=jdir,
                       store_dir=sdir).start()
    try:
        jobs = [svc.submit_local(s) for s in specs]
        for j in jobs:
            assert j.done_event.wait(180) and j.state == "done"
        with ServiceClient("127.0.0.1", svc.port) as c:
            rep = c.aggregate([j.id for j in jobs])
            agg = c.fetch_aggregate(rep["agg_id"])
            with pytest.raises(ServiceError):
                c.aggregate([jobs[0].id, "job-nope"])
            with pytest.raises(ServiceError):
                c.fetch_aggregate("agg-missing")
        assert rep["kinds"] == ["range", "toy"]
        assert AGG.verify(agg, _vks())
        ctr = svc.metrics.snapshot()["counters"]
        assert ctr["aggregates_built"] == 1
        assert ctr["aggregate_members"] == 3
        assert ctr["circuit_kind_toy"] == 2
        assert ctr["circuit_kind_range"] == 1
    finally:
        svc.shutdown()

    svc2 = ProofService(port=0, prover_workers=1, journal_dir=jdir,
                        store_dir=sdir).start()
    try:
        assert svc2.metrics.snapshot()["counters"].get(
            "aggregates_recovered", 0) == 1
        with ServiceClient("127.0.0.1", svc2.port) as c:
            agg2 = c.fetch_aggregate(rep["agg_id"])
        assert agg2 == agg and AGG.verify(agg2, _vks())
    finally:
        svc2.shutdown()


def test_storeless_aggregate_recovers_from_journal_hex(tmp_path):
    """No artifact store: the AGG record carries the blob inline
    (agg_hex) and a crashed service still serves it after recovery."""
    from distributed_plonk_tpu.service import ProofService
    jdir = str(tmp_path / "j")
    svc = ProofService(port=0, prover_workers=1, journal_dir=jdir)
    svc.start()
    agg_id = None
    try:
        job = svc.submit_local({"kind": "toy", "gates": 16, "seed": 31})
        assert job.done_event.wait(180) and job.state == "done"
        agg_id = svc.aggregate_jobs([job.id])["agg_id"]
        assert svc.load_aggregate_blob(agg_id) is not None
    finally:
        svc.crash()
    svc2 = ProofService(port=0, prover_workers=1, journal_dir=jdir)
    svc2.start()
    try:
        blob = svc2.load_aggregate_blob(agg_id)
        assert blob is not None
        assert AGG.verify(AGG.from_bytes(blob), _vks())
    finally:
        svc2.shutdown()
