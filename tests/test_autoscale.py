"""Closed-loop autoscaler suite (ISSUE 16).

Three layers, cheapest first:

  1. SLO-class queue units (jax-free, in-process): class-priority pop
     order, all-standard parity with the pre-class sort, per-class
     default TTLs (DPT_TTL_<CLASS>_S) vs the per-job ttl_s override,
     steal_lowest victim selection, and the full-queue flagship-preempts-
     batch admission path.
  2. Control-law units against FAKE sensors/actuators with an injected
     clock — hysteresis streaks, cooldown windows, min/max bounds, the
     lease-resize rule, pressure sheds, dry-run's ZERO-actuator-calls
     pin, and DPT_AUTOSCALE=0 attaching nothing (bit-parity).
  3. The live supervised-fleet canary: a real 1-worker fleet behind a
     fleet-backed ProofService with the actuating controller attached —
     a job ramp must scale UP (supervisor.add_slot, warm membership
     join), every proof must verify byte-identical to a local
     uninterrupted prove, and the idle tail must scale DOWN through
     retire_slot (drain -> LEAVE -> SIGTERM: zero respawns, zero flaps,
     zero mid-prove kills).
"""

import random
import time

import pytest

from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                      RemoteBackend,
                                                      WorkerHandle)
from distributed_plonk_tpu.runtime.health import LivenessTracker
from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
from distributed_plonk_tpu.runtime.supervisor import WorkerSupervisor
from distributed_plonk_tpu.service import ProofService, ServiceClient
from distributed_plonk_tpu.service import autoscale as AS
from distributed_plonk_tpu.service.jobs import (Job, JobSpec, SLO_RANK,
                                                build_bucket_keys,
                                                build_circuit,
                                                class_default_ttl,
                                                shape_key)
from distributed_plonk_tpu.service.metrics import Metrics
from distributed_plonk_tpu.service.queue import JobQueue, Rejected

import os

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
_LOAD_BUDGET_S = float(os.environ.get("DPT_TEST_WAIT_S", "120"))


@pytest.fixture(autouse=True)
def _fast_failure_knobs(monkeypatch):
    monkeypatch.setattr(WorkerHandle, "RECONNECT_TRIES", 2)
    monkeypatch.setattr(WorkerHandle, "BACKOFF_BASE_S", 0.01)
    monkeypatch.setattr(WorkerHandle, "BACKOFF_MAX_S", 0.05)
    monkeypatch.setattr(WorkerHandle, "TIMEOUT_MS", 120000)


def _wait_for(cond, timeout_s=None, interval=0.05, msg=""):
    deadline = time.monotonic() + (timeout_s or _LOAD_BUDGET_S)
    while True:
        got = cond()
        if got:
            return got
        if time.monotonic() >= deadline:
            raise AssertionError(f"timed out waiting for {msg or cond}")
        time.sleep(interval)


def _job(slo=None, priority=0, seed=1, ttl_s=None):
    wire = {"kind": "toy", "gates": 16, "seed": seed, "priority": priority}
    if slo is not None:
        wire["slo"] = slo
    if ttl_s is not None:
        wire["ttl_s"] = ttl_s
    return Job(JobSpec.from_wire(wire))


# --- SLO-class queue ----------------------------------------------------------

def test_class_priority_pop_order():
    q = JobQueue(max_depth=8)
    batch = _job(slo="batch", priority=9, seed=1)
    standard = _job(slo="standard", priority=0, seed=2)
    flagship = _job(slo="flagship", priority=0, seed=3)
    for j in (batch, standard, flagship):
        q.submit(j)
    # class outranks priority: flagship(prio 0) before batch(prio 9)
    order = [q.pop_batch(max_batch=1)[0] for _ in range(3)]
    assert [j.slo for j in order] == ["flagship", "standard", "batch"]
    assert order == [flagship, standard, batch]


def test_all_standard_stream_keeps_classless_order():
    """A stream with no slo fields sorts exactly as the pre-class queue:
    priority desc, then FIFO — the parity contract."""
    q = JobQueue(max_depth=8)
    js = [_job(priority=p, seed=i) for i, p in enumerate((0, 2, 1, 2))]
    for j in js:
        q.submit(j)
    got = [q.pop_batch(max_batch=1)[0] for _ in range(4)]
    assert got == [js[1], js[3], js[2], js[0]]
    assert all(j.slo == "standard" for j in got)


def test_depth_by_class():
    q = JobQueue(max_depth=8)
    for slo in ("batch", "batch", "flagship", None):
        q.submit(_job(slo=slo))
    assert q.depth_by_class() == {"batch": 2, "flagship": 1, "standard": 1}


def test_steal_lowest_evicts_worst_lower_class():
    q = JobQueue(max_depth=8)
    b1 = _job(slo="batch", seed=1)
    b2 = _job(slo="batch", seed=2)       # same rank/prio, later seq: worst
    s1 = _job(slo="standard", seed=3)
    for j in (b1, b2, s1):
        q.submit(j)
    assert q.steal_lowest(SLO_RANK["flagship"]) is b2
    assert q.steal_lowest(SLO_RANK["standard"]) is b1
    # only the standard job left: nothing below standard remains
    assert q.steal_lowest(SLO_RANK["standard"]) is None
    assert q.steal_lowest(SLO_RANK["batch"]) is None
    assert q.depth() == 1


def test_per_class_default_ttl_env(monkeypatch):
    monkeypatch.setenv("DPT_TTL_BATCH_S", "7.5")
    monkeypatch.delenv("DPT_TTL_STANDARD_S", raising=False)
    assert class_default_ttl("batch") == 7.5
    assert class_default_ttl("standard") is None
    t0 = time.time()
    j = _job(slo="batch")
    assert j.deadline_ts is not None and j.deadline_ts >= t0 + 7.0
    # classless/standard: no default deadline (parity with pre-class)
    assert _job().deadline_ts is None
    # the per-job ttl_s override beats the class default
    j2 = _job(slo="batch", ttl_s=1.0)
    assert j2.deadline_ts is not None and j2.deadline_ts < t0 + 5.0
    # unparseable / non-positive envs fail safe to no deadline
    monkeypatch.setenv("DPT_TTL_BATCH_S", "nope")
    assert class_default_ttl("batch") is None
    monkeypatch.setenv("DPT_TTL_BATCH_S", "0")
    assert class_default_ttl("batch") is None


def test_flagship_preempts_batch_on_full_queue():
    """Admission shed-lowest-class-first: a full queue refusing a
    flagship SUBMIT evicts the worst queued batch job (journaled SHED)
    and admits the flagship in its place; an all-standard stream keeps
    the historical plain rejection."""
    svc = ProofService(port=0, prover_workers=1, queue_depth=2)
    # never started: submissions just land in the queue
    b1, _ = svc.submit_ex({"kind": "toy", "gates": 16, "seed": 1,
                           "slo": "batch"})
    b2, _ = svc.submit_ex({"kind": "toy", "gates": 16, "seed": 2,
                           "slo": "batch"})
    f, _ = svc.submit_ex({"kind": "toy", "gates": 16, "seed": 3,
                          "slo": "flagship"})
    assert b2.state == "shed" and b1.state == "queued"
    assert f.state == "queued"
    ctr = svc.metrics.snapshot()["counters"]
    assert ctr.get("slo_preempt_sheds", 0) == 1
    assert ctr.get("slo_sheds_batch", 0) == 1
    # standard outranks batch too: the remaining batch job gets evicted
    s, _ = svc.submit_ex({"kind": "toy", "gates": 16, "seed": 4})
    assert b1.state == "shed" and s.state == "queued"
    # but with no lower class left, standard-vs-standard keeps the
    # historical plain rejection (an all-standard stream never preempts)
    with pytest.raises(Rejected):
        svc.submit_ex({"kind": "toy", "gates": 16, "seed": 5})
    assert f.state == "queued" and s.state == "queued"


# --- control-law units (fake sensors/actuators, injected clock) ---------------

class _FakeActuators:
    def __init__(self, workers=1):
        self.workers = workers
        self.calls = []

    def worker_count(self):
        return self.workers

    def add_worker(self):
        self.calls.append("add")
        self.workers += 1
        return self.workers - 1

    def retire_worker(self):
        self.calls.append("retire")
        self.workers -= 1
        return self.workers

    def lease_capacity(self, frac):
        self.calls.append(("lease", frac))
        return max(1, int(8 * frac))

    def shed_lowest(self, below_rank):
        self.calls.append(("shed", below_rank))
        return "batch"


def _controller(mode="1", workers=1, **kw):
    box = {"t": 0.0,
           "sensors": {"queue_depth": 0, "queue_by_class": {},
                       "max_depth": 64, "busy_workers": 0}}
    act = _FakeActuators(workers=workers)
    defaults = dict(mode=mode, tick_s=0.01, min_workers=1, max_workers=3,
                    up_queue_per_worker=2, up_ticks=2, down_ticks=3,
                    up_cooldown_s=10, down_cooldown_s=10,
                    shed_watermark=0.9)
    defaults.update(kw)
    asc = AS.Autoscaler(sensors=lambda: dict(box["sensors"]),
                        actuators=act, metrics=Metrics(),
                        clock=lambda: box["t"], **defaults)
    return asc, act, box


def _tick(asc, box, dt=1.0):
    box["t"] += dt
    return asc.tick()


def test_scale_up_needs_hysteresis_streak():
    asc, act, box = _controller()
    box["sensors"].update(queue_depth=8, busy_workers=1)
    assert _tick(asc, box) == []          # streak 1 of 2: no decision
    ds = _tick(asc, box)                  # streak 2: scale up
    assert [d["action"] for d in ds] == ["scale_up"] and ds[0]["applied"]
    assert act.calls == ["add"] and act.workers == 2


def test_scale_up_cooldown_and_ceiling():
    asc, act, box = _controller(up_cooldown_s=10, max_workers=2)
    box["sensors"].update(queue_depth=8, busy_workers=1)
    _tick(asc, box)
    assert [d["action"] for d in _tick(asc, box)] == ["scale_up"]
    # breach persists: cooldown (10s) blocks the next up...
    assert _tick(asc, box, dt=1.0) == []
    assert _tick(asc, box, dt=1.0) == []
    # ...and once it elapses, the ceiling (max_workers=2) does
    assert _tick(asc, box, dt=20.0) == []
    assert act.calls == ["add"] and act.workers == 2


def test_scale_down_idle_streak_and_floor():
    asc, act, box = _controller(workers=2, down_ticks=3, down_cooldown_s=0)
    for _ in range(2):
        assert _tick(asc, box) == []      # idle streaks 1, 2
    ds = _tick(asc, box)                  # streak 3: retire
    assert [d["action"] for d in ds] == ["scale_down"] and ds[0]["applied"]
    assert act.calls == ["retire"] and act.workers == 1
    # at the floor (min_workers=1) the idle streak never retires again
    for _ in range(5):
        assert _tick(asc, box) == []
    assert act.workers == 1


def test_lease_resize_tracks_batch_dominance():
    asc, act, box = _controller()
    box["sensors"].update(queue_depth=4, busy_workers=1,
                          queue_by_class={"batch": 4})
    ds = _tick(asc, box)
    assert ("lease", 0.5) in act.calls
    assert any(d["action"] == "lease_resize" for d in ds)
    # a queued flagship restores full capacity on the next tick
    box["sensors"].update(queue_by_class={"batch": 3, "flagship": 1})
    _tick(asc, box)
    assert ("lease", 1.0) in act.calls


def test_pressure_shed_at_watermark():
    asc, act, box = _controller(shed_watermark=0.9)
    box["sensors"].update(queue_depth=60, busy_workers=1, max_depth=64)
    ds = _tick(asc, box)
    assert any(d["action"] == "shed" and d["applied"] for d in ds)
    assert ("shed", SLO_RANK["flagship"]) in act.calls


def test_dry_mode_records_decisions_with_zero_actuator_calls():
    asc, act, box = _controller(mode="dry")
    box["sensors"].update(queue_depth=60, busy_workers=1, max_depth=64)
    all_ds = []
    for _ in range(4):
        all_ds += _tick(asc, box)
    acts = {d["action"] for d in all_ds}
    assert "scale_up" in acts and "shed" in acts
    assert all(d["applied"] is False for d in all_ds)
    assert act.calls == []                # THE dry contract: zero calls
    st = asc.state()
    assert st["mode"] == "dry" and st["last_decisions"]


def test_off_mode_attaches_nothing(monkeypatch):
    class _Svc:
        autoscaler = None
    svc = _Svc()
    monkeypatch.delenv("DPT_AUTOSCALE", raising=False)
    assert AS.attach(svc) is None                 # env default: off
    assert AS.attach(svc, mode="0") is None       # explicit off
    assert svc.autoscaler is None
    # unknown values fail SAFE (off), never actuating
    monkeypatch.setenv("DPT_AUTOSCALE", "bananas")
    assert AS.mode_from_env() == "0"
    monkeypatch.setenv("DPT_AUTOSCALE", "dry")
    assert AS.mode_from_env() == "dry"
    monkeypatch.setenv("DPT_AUTOSCALE", "1")
    assert AS.mode_from_env() == "1"


def test_state_shape_for_obs_endpoint():
    asc, _act, box = _controller()
    box["sensors"].update(queue_depth=2, busy_workers=1,
                          queue_by_class={"standard": 2})
    _tick(asc, box)
    st = asc.state()
    assert st["bounds"] == {"min_workers": 1, "max_workers": 3}
    assert st["queue"]["depth"] == 2
    assert st["queue"]["by_class"] == {"standard": 2}
    assert st["workers"] == 1
    assert {"up", "down"} <= set(st["streaks"])
    assert {"up_remaining_s", "down_remaining_s"} <= set(st["cooldowns"])


# --- live fleet: retire + the closed-loop canary ------------------------------

def _member_dispatcher(metrics):
    d = Dispatcher(NetworkConfig([]), metrics=metrics)
    d.tracker = LivenessTracker(0, breaker_k=2, probe_base_s=0.05,
                                probe_max_s=0.5, metrics=metrics)
    return d, d.enable_membership()


def _supervised(n, metrics):
    d, mserver = _member_dispatcher(metrics)
    sup = WorkerSupervisor("127.0.0.1", mserver.port, n=n,
                           backend="python", metrics=metrics,
                           cwd=REPO).start()
    sup.attach_registry(d.membership)
    _wait_for(lambda: len(d.workers) >= n
              and len(d.tracker.usable_set()) >= n,
              msg=f"fleet width {n}")
    return d, sup


def _shutdown(d, sup):
    sup.stop()
    try:
        d.shutdown()
    finally:
        d.pool.shutdown(wait=False)


def _reference(spec_wire, _pk_cache={}):
    """Local uninterrupted prove: the byte-identity oracle."""
    from distributed_plonk_tpu.backend.python_backend import PythonBackend
    from distributed_plonk_tpu.proof_io import serialize_proof
    from distributed_plonk_tpu.prover import prove
    s = JobSpec.from_wire(spec_wire)
    key = shape_key(s)
    if key not in _pk_cache:
        _pk_cache[key] = build_bucket_keys(s)[1]
    return serialize_proof(prove(random.Random(s.seed), build_circuit(s),
                                 _pk_cache[key], PythonBackend()))


def test_retire_slot_graceful_drain_then_leave():
    """retire_slot is not a flap: the process exits via drain+LEAVE+
    SIGTERM, the watch loop never respawns it, the membership width
    shrinks, and worker_retires (not worker_respawns) counts it."""
    fm = Metrics()
    d, sup = _supervised(2, fm)
    try:
        assert sup.retire_slot(1) is True
        assert sup.retire_slot(1) is False       # idempotent
        assert sup.active_count() == 1
        snap = sup.snapshot()[1]
        assert snap["retired"] and not snap["failed"]
        _wait_for(lambda: not sup.snapshot()[1]["alive"],
                  msg="retired worker exit")
        _wait_for(lambda: len(d.tracker.usable_set()) == 1,
                  msg="membership width 1")
        # no respawn ever follows a retire (watch a couple of periods)
        time.sleep(1.0)
        ctr = fm.snapshot()["counters"]
        assert ctr.get("worker_retires", 0) == 1
        assert ctr.get("worker_respawns", 0) == 0
        assert ctr.get("worker_flap_capped", 0) == 0
    finally:
        _shutdown(d, sup)


def test_closed_loop_canary_scales_up_and_retires():
    """The live acceptance canary: ramp -> add_slot (warm join) -> every
    proof byte-verified -> idle -> drain-then-LEAVE retire back to the
    floor. Zero respawns and zero flaps: the scale actions are never
    mid-prove kills."""
    fm = Metrics()
    d, sup = _supervised(1, fm)
    svc = None
    try:
        svc = ProofService(
            port=0, prover_workers=1, chaos=True, max_retries=4,
            allow_remote_shutdown=True, self_verify="1",
            backend_factory=lambda: RemoteBackend(d, dist_fft_min=64),
        ).start()
        asc = svc.attach_autoscaler(
            supervisor=sup, mode="1", tick_s=0.1, min_workers=1,
            max_workers=2, up_queue_per_worker=2, up_ticks=2,
            down_ticks=3, up_cooldown_s=0.2, down_cooldown_s=0.2)
        assert asc is svc.autoscaler and asc.actuating
        with ServiceClient("127.0.0.1", svc.port) as c:
            specs = [{"kind": "toy", "gates": 60, "seed": 9000 + i,
                      "slo": ("flagship" if i == 0 else "standard")}
                     for i in range(6)]
            ids = [c.submit(s)["job_id"] for s in specs]
            # the ramp breaches queue/worker >= 2 for >= 2 ticks: the
            # controller must add a slot (the warm JOIN path)
            _wait_for(lambda: sup.active_count() == 2, msg="scale up")
            for spec, jid in zip(specs, ids):
                st = c.wait(jid, timeout_s=_LOAD_BUDGET_S)
                assert st["state"] == "done", st
                assert st["slo"] == spec.get("slo", "standard")
                _hdr, blob = c.result(jid)
                assert blob == _reference(spec)
            # idle tail: retire back to the floor (drain-then-LEAVE)
            _wait_for(lambda: sup.active_count() == 1, msg="scale down")
        # the retire completes asynchronously on its own thread (drain
        # -> LEAVE -> SIGTERM): wait for the counter, not just the flag
        _wait_for(lambda: fm.snapshot()["counters"]
                  .get("worker_retires", 0) >= 1, msg="retire complete")
        sc = svc.metrics.snapshot()["counters"]
        assert sc.get("autoscale_scale_ups", 0) >= 1
        assert sc.get("autoscale_scale_downs", 0) >= 1
        assert sc.get("slo_sheds_flagship", 0) == 0
        # the standard-class roundtrip histogram fed the p95 sensor
        hist = svc.metrics.snapshot()["histograms"]
        assert hist.get("slo_roundtrip/standard", {}).get("count", 0) >= 5
        ctr = fm.snapshot()["counters"]
        assert ctr.get("worker_retires", 0) >= 1
        assert ctr.get("worker_respawns", 0) == 0
        assert ctr.get("worker_flap_capped", 0) == 0
    finally:
        if svc is not None:
            svc.shutdown()
        _shutdown(d, sup)
