"""Durable service plane tests: crash-safe job journal, restart recovery,
idempotent submission, TTL shedding, graceful drain (ISSUE 7 acceptance).

The centerpiece is the restart sweep: a REAL scripts/serve.py process is
killed with os._exit at each journal transition (SUBMIT / START / each
ROUND / DONE) via the fault injector's journal plane, restarted on the
same journal+store dirs, and must finish every job with proof bytes
byte-identical to an uninterrupted local prove — resuming from the last
checkpoint (no completed round is ever proved twice). Everything runs on
the python host-oracle backend (jax-free) at tiny toy domains; this
module is part of `ci.sh chaos` and the fast tier.
"""

import os
import random
import signal
import subprocess
import sys
import threading
import time

import pytest

from distributed_plonk_tpu.backend.python_backend import PythonBackend
from distributed_plonk_tpu.proof_io import serialize_proof
from distributed_plonk_tpu.prover import prove
from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
from distributed_plonk_tpu.service import (BucketCache, Metrics,
                                           ProofService, Rejected,
                                           ServiceClient)
from distributed_plonk_tpu.service.jobs import (JobSpec, build_bucket_keys,
                                                build_circuit)
from distributed_plonk_tpu.service.journal import (DONE, ROUND, SHED, START,
                                                   SUBMIT, JobJournal)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE = os.path.join(REPO, "scripts", "serve.py")


def reference_proof(spec_obj):
    """Uninterrupted local prove: the byte-identity oracle."""
    spec = JobSpec.from_wire(spec_obj)
    _, pk, _vk = build_bucket_keys(spec)
    return serialize_proof(prove(random.Random(spec.seed),
                                 build_circuit(spec), pk, PythonBackend()))


# --- journal unit tests ------------------------------------------------------

def _mk_journal(tmp_path, **kw):
    return JobJournal(str(tmp_path / "j"), metrics=Metrics(), **kw)


def test_journal_roundtrip_and_replay(tmp_path):
    j = _mk_journal(tmp_path)
    j.append(SUBMIT, "job-1", spec={"kind": "toy", "gates": 8, "seed": 1},
             key="k1", deadline=None, ts=123.0)
    j.append(START, "job-1", worker="w0g1")
    j.append(ROUND, "job-1", round=1)
    j.append(ROUND, "job-1", round=2)
    j.append(SUBMIT, "job-2", spec={"kind": "toy", "gates": 8, "seed": 2},
             key=None, deadline=9e9, ts=124.0)
    j.append(SHED, "job-2", reason="ttl expired in queue")
    j.close()

    j2 = _mk_journal(tmp_path)
    assert list(j2.state) == ["job-1", "job-2"]
    st1, st2 = j2.state["job-1"], j2.state["job-2"]
    assert st1["phase"] == "round" and st1["round"] == 2
    assert st1["key"] == "k1"
    assert st2["phase"] == "shed" and "ttl expired" in st2["reason"]
    j2.close()


def test_journal_compaction_bounds_the_log(tmp_path):
    j = _mk_journal(tmp_path, compact_every=10**9, retain_terminal=2)
    for i in range(8):
        jid = f"job-{i}"
        j.append(SUBMIT, jid, spec={"kind": "toy", "gates": 8, "seed": i},
                 key=None, deadline=None, ts=float(i))
        j.append(DONE, jid, proof_hex="ab", pub=["0x1"], retries=0)
    j.append(SUBMIT, "job-live", spec={"kind": "toy", "gates": 8, "seed": 9},
             key=None, deadline=None, ts=9.0)
    j.append(ROUND, "job-live", round=3)
    j.compact()
    # terminal jobs beyond retain_terminal dropped, live job never dropped
    assert "job-live" in j.state and j.state["job-live"]["round"] == 3
    terminal = [jid for jid in j.state if jid != "job-live"]
    assert terminal == ["job-6", "job-7"]
    j.close()
    # the compacted file replays to the same state
    j2 = _mk_journal(tmp_path)
    assert set(j2.state) == {"job-6", "job-7", "job-live"}
    j2.close()


@pytest.mark.parametrize("damage", ["torn", "bitflip", "garbage_tail"])
def test_journal_damaged_tail_truncate_and_continue(tmp_path, damage):
    j = _mk_journal(tmp_path)
    j.append(SUBMIT, "job-1", spec={"kind": "toy", "gates": 8, "seed": 1},
             key=None, deadline=None, ts=1.0)
    j.append(ROUND, "job-1", round=1)
    j.append(ROUND, "job-1", round=2)
    j.close()
    path = j.path
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.split(b"\n")
    if damage == "torn":          # power cut mid-append: half a record
        raw = raw[:len(raw) - len(lines[-2]) // 2 - 1]
    elif damage == "bitflip":     # bit rot inside the last record
        idx = len(raw) - len(lines[-2]) // 2
        raw = raw[:idx] + bytes([raw[idx] ^ 0xFF]) + raw[idx + 1:]
    else:                         # appended garbage, no newline
        raw += b"\x00\xffnot a record"
    with open(path, "wb") as f:
        f.write(raw)

    j2 = _mk_journal(tmp_path)   # replay must truncate, never crash
    st = j2.state["job-1"]
    assert st["round"] in (1, 2)  # damaged suffix dropped, prefix kept
    snap = j2.metrics.snapshot()["counters"]
    assert snap["journal_torn_records"] == 1
    # the journal keeps working after surgery: append + clean replay
    j2.append(ROUND, "job-1", round=3)
    j2.close()
    j3 = _mk_journal(tmp_path)
    assert j3.state["job-1"]["round"] == 3
    assert "journal_torn_records" not in j3.metrics.snapshot()["counters"]
    j3.close()


def test_journal_sealed_writes_nothing(tmp_path):
    j = _mk_journal(tmp_path)
    j.append(SUBMIT, "job-1", spec={"kind": "toy", "gates": 8, "seed": 1},
             key=None, deadline=None, ts=1.0)
    j.seal()
    assert j.append(ROUND, "job-1", round=1) is False
    j2 = _mk_journal(tmp_path)
    assert j2.state["job-1"]["phase"] == "submit"
    j2.close()


# --- restart sweep: service killed at every journal transition ---------------

def _spawn_serve(port, journal_dir, store_dir, faults=None, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DPT_FAULTS", None)
    if faults:
        env["DPT_FAULTS"] = faults
    env.update(env_extra or {})
    p = subprocess.Popen(
        [sys.executable, SERVE, "--port", str(port), "--workers", "1",
         "--journal-dir", journal_dir, "--store-dir", store_dir, "--chaos"],
        stdout=subprocess.PIPE, env=env, text=True, cwd=REPO)
    assert "listening" in p.stdout.readline()
    return p


def _port(offset):
    return 24100 + (os.getpid() % 400) * 12 + offset


SWEEP_SPEC = {"kind": "toy", "gates": 60, "seed": 5}  # n=128: 4 rounds saved
SWEEP_PHASES = ["SUBMIT", "START", "ROUND1", "ROUND2", "ROUND3", "ROUND4",
                "DONE"]


@pytest.mark.parametrize("phase", SWEEP_PHASES)
def test_service_killed_at_each_journal_transition(tmp_path, phase):
    """The ISSUE-7 acceptance sweep: os._exit at one exact journal
    occurrence, restart on the same dirs, byte-identical completion with
    no proving repeated past the last checkpointed round."""
    port = _port(SWEEP_PHASES.index(phase))
    jdir, sdir = str(tmp_path / "journal"), str(tmp_path / "store")
    os.makedirs(sdir, exist_ok=True)
    spec = dict(SWEEP_SPEC, job_key=f"sweep-{phase}")

    p = _spawn_serve(port, jdir, sdir, faults=f"kill:at=journal:tag={phase}")
    try:
        with ServiceClient("127.0.0.1", port) as c:
            c.submit(spec)
    except (ConnectionError, OSError):
        pass  # SUBMIT-phase kill dies before the reply frame
    assert p.wait(timeout=120) == 1  # died via os._exit(1), not cleanly

    p2 = _spawn_serve(port, jdir, sdir)
    try:
        with ServiceClient("127.0.0.1", port) as c:
            # duplicate submit dedups onto the recovered job — also how a
            # client whose SUBMIT reply was lost in the crash finds its id
            r = c.submit(spec)
            assert r["dedup"] is True, r
            st = c.wait(r["job_id"], timeout_s=180)
            assert st["state"] == "done", st
            _hdr, blob = c.result(r["job_id"])
            m = c.metrics()
    finally:
        p2.terminate()
        p2.wait(timeout=30)

    assert blob == reference_proof(spec), \
        f"recovered proof bytes diverged (killed at {phase})"
    ctr, hists = m["counters"], m["histograms"]
    if phase == "DONE":
        # finished before the kill: served from the proof artifact,
        # nothing proved in the restarted service
        assert ctr.get("jobs_completed", 0) == 0
        assert ctr.get("jobs_recovered_finished", 0) == 1
    else:
        assert ctr.get("jobs_recovered", 0) == 1
        if phase.startswith("ROUND"):
            # resumed past the checkpoint: the completed rounds are NOT
            # proved again (round1 histogram would exist if they were)
            assert ctr.get("checkpoint_resumes", 0) >= 1
            assert "prove_round/round1" not in hists, \
                f"round 1 re-proved after {phase} kill"


def test_sigterm_graceful_drain_then_resume(tmp_path):
    """SIGTERM: admission stops, the drain deadline forces a mid-prove
    checkpoint park, exit code 0; restart resumes byte-identically."""
    port = _port(8)
    jdir, sdir = str(tmp_path / "journal"), str(tmp_path / "store")
    os.makedirs(sdir, exist_ok=True)
    spec = {"kind": "toy", "gates": 300, "seed": 8, "job_key": "drain-1"}

    p = _spawn_serve(port, jdir, sdir,
                     env_extra={"DPT_DRAIN_TIMEOUT_S": "0.05"})
    with ServiceClient("127.0.0.1", port) as c:
        jid = c.submit(spec)["job_id"]
        # wait until it is actually proving so the drain has work to park
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if c.status(jid)["state"] == "running":
                break
            time.sleep(0.02)
    p.send_signal(signal.SIGTERM)
    assert p.wait(timeout=60) == 0  # graceful drain exits 0
    out = p.stdout.read()
    assert '"drained": "SIGTERM"' in out

    p2 = _spawn_serve(port, jdir, sdir)
    try:
        with ServiceClient("127.0.0.1", port) as c:
            r = c.submit(spec)
            assert r["dedup"] is True
            assert c.wait(r["job_id"], timeout_s=240)["state"] == "done"
            _hdr, blob = c.result(r["job_id"])
    finally:
        p2.terminate()
        p2.wait(timeout=30)
    assert blob == reference_proof(spec)


def test_serve_rejects_bad_journal_dir(tmp_path):
    """--journal-dir fail-fast: a path that cannot take the journal must
    stop the daemon before it accepts jobs it cannot make durable."""
    not_a_dir = tmp_path / "file"
    not_a_dir.write_text("x")
    p = subprocess.run(
        [sys.executable, SERVE, "--journal-dir", str(not_a_dir)],
        capture_output=True, text=True, cwd=REPO, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert p.returncode != 0
    assert "--journal-dir" in p.stderr


# --- in-process recovery paths ----------------------------------------------

TOY = {"kind": "toy", "gates": 8}


def test_dedup_across_restart_serves_artifact_without_reprove(tmp_path):
    jdir, sdir = str(tmp_path / "j"), str(tmp_path / "s")
    spec = dict(TOY, seed=3, job_key="dd-1")
    svc = ProofService(port=0, prover_workers=1, journal_dir=jdir,
                       store_dir=sdir).start()
    try:
        job = svc.submit_local(spec)
        assert job.done_event.wait(120) and job.state == "done"
        want = job.proof_bytes
        # in-flight dedup too
        j2, dd = svc.submit_ex(spec)
        assert dd and j2.id == job.id
    finally:
        svc.shutdown()

    svc2 = ProofService(port=0, prover_workers=1, journal_dir=jdir,
                        store_dir=sdir).start()
    try:
        j3, dd3 = svc2.submit_ex(spec)
        assert dd3 and j3.id == job.id and j3.state == "done"
        assert j3.proof_bytes == want == reference_proof(spec)
        ctr = svc2.metrics.snapshot()["counters"]
        assert ctr.get("jobs_completed", 0) == 0      # no re-prove
        assert ctr["jobs_recovered_finished"] == 1
        assert ctr["dedup_hits"] == 1
        # the finished proof is a normal store artifact: STORE_FETCHable
        from distributed_plonk_tpu.store import load_proof
        blob, pub, _meta = load_proof(svc2.store, job.id)
        assert blob == want
    finally:
        svc2.shutdown()


def test_crash_midprove_recovers_without_reproving_rounds(tmp_path):
    """In-process twin of the subprocess sweep (and of bench.py's
    service_restart_recovery_ok canary): crash() at journal ROUND2."""
    jdir, sdir = str(tmp_path / "j"), str(tmp_path / "s")
    spec = {"kind": "toy", "gates": 60, "seed": 5, "job_key": "crash-1"}
    box = {}
    faults = FaultInjector([Rule("kill", tag="ROUND2", plane="journal")],
                           kill_cb=lambda _label: box["svc"].crash())
    svc = ProofService(port=0, prover_workers=1, journal_dir=jdir,
                       store_dir=sdir, chaos=True, faults=faults)
    box["svc"] = svc
    svc.start()
    job = svc.submit_local(spec)
    deadline = time.monotonic() + 120
    while not svc._stopped.is_set():
        assert time.monotonic() < deadline, "service never crashed"
        time.sleep(0.02)
    assert job.state != "done"

    svc2 = ProofService(port=0, prover_workers=1, journal_dir=jdir,
                        store_dir=sdir).start()
    try:
        j2, dd = svc2.submit_ex(spec)
        assert dd and j2.done_event.wait(180) and j2.state == "done"
        m = svc2.metrics.snapshot()
        assert m["counters"]["checkpoint_resumes"] >= 1
        assert "prove_round/round1" not in m["histograms"]
        assert j2.proof_bytes == reference_proof(spec)
    finally:
        svc2.shutdown()


def test_ttl_shed_verdict_journaled_and_queryable(tmp_path):
    jdir = str(tmp_path / "j")
    svc = ProofService(port=0, prover_workers=1, journal_dir=jdir).start()
    try:
        big = svc.submit_local(dict(TOY, gates=300, seed=1))
        tiny = svc.submit_local(dict(TOY, seed=2, ttl_s=0.05,
                                     job_key="shed-1"))
        assert tiny.done_event.wait(240)
        assert tiny.state == "shed" and "ttl expired" in tiny.error
        assert big.done_event.wait(240) and big.state == "done"
        assert svc.metrics.snapshot()["counters"]["jobs_shed"] == 1
        # the wire view of a shed verdict
        assert tiny.status()["state"] == "shed"
    finally:
        svc.shutdown()
    # verdict survives a restart (journaled SHED record)
    svc2 = ProofService(port=0, prover_workers=1, journal_dir=jdir).start()
    try:
        j2 = svc2.get_job(tiny.id)
        assert j2.state == "shed" and "ttl expired" in j2.error
        # dedup maps the key to the shed verdict, not a fresh prove
        j3, dd = svc2.submit_ex(dict(TOY, seed=2, ttl_s=0.05,
                                     job_key="shed-1"))
        assert dd and j3.state == "shed"
    finally:
        svc2.shutdown()


def test_ttl_expired_during_outage_is_shed_at_recovery(tmp_path):
    """The deadline is the ORIGINAL submission's: a job whose TTL lapsed
    while the service was down is shed at recovery, not resumed — and a
    restart must never silently extend a TTL."""
    jdir = str(tmp_path / "j")
    svc = ProofService(port=0, prover_workers=1, journal_dir=jdir)
    # no start(): the job sits queued, then the 'process' dies
    job = svc.submit_local(dict(TOY, seed=4, ttl_s=0.1, job_key="out-1"))
    svc.crash()
    time.sleep(0.2)  # the outage outlives the TTL

    svc2 = ProofService(port=0, prover_workers=1, journal_dir=jdir)
    svc2._recover()
    j2 = svc2.get_job(job.id)
    assert j2.state == "shed" and "during restart" in j2.error
    assert svc2.metrics.snapshot()["counters"]["jobs_shed"] == 1
    assert svc2.queue.depth() == 0
    svc2.crash()


def test_rejected_submit_never_resurrects(tmp_path):
    """A queue_full rejection is journaled terminally: replay must not
    re-enqueue a job whose client was told 'no'."""
    jdir = str(tmp_path / "j")
    svc = ProofService(port=0, prover_workers=1, queue_depth=1,
                       journal_dir=jdir)
    # no start(): the scheduler must not drain the queue mid-test
    svc.submit_local(dict(TOY, seed=1))
    with pytest.raises(Rejected):
        svc.submit_local(dict(TOY, seed=2, job_key="rej-1"))
    svc.crash()

    svc2 = ProofService(port=0, prover_workers=1, journal_dir=jdir)
    svc2._recover()   # start() would also kick the scheduler off
    rejected = [j for j in svc2.jobs.values() if j.job_key == "rej-1"]
    assert rejected and rejected[0].state == "shed"
    assert "rejected" in rejected[0].error
    assert svc2.queue.depth() == 1  # only the admitted job came back
    # the refused job_key is FREE after restart, exactly as on the live
    # path: a retry is a fresh admission, not a dedup onto the verdict
    j_retry, dd = svc2.submit_ex(dict(TOY, seed=2, job_key="rej-1"))
    assert not dd and j_retry.state == "queued"
    svc2.crash()


def test_recovery_force_enqueues_past_depth_cap(tmp_path):
    """Recovery re-admits what the previous process admitted, even past
    this process's queue depth — a restart must never shed valid work."""
    jdir = str(tmp_path / "j")
    svc = ProofService(port=0, prover_workers=1, queue_depth=8,
                       journal_dir=jdir)
    for i in range(6):
        svc.submit_local(dict(TOY, seed=10 + i))
    svc.crash()
    svc2 = ProofService(port=0, prover_workers=1, queue_depth=2,
                        journal_dir=jdir)
    svc2._recover()
    assert svc2.queue.depth() == 6
    assert svc2.metrics.snapshot()["counters"]["jobs_recovered"] == 6
    svc2.crash()


# --- bucket-cache per-key latch (ROADMAP remainder) --------------------------

def test_bucket_latch_cold_miss_does_not_stall_other_shapes():
    """The PR-6 remainder this PR closes: one shape's slow cold load
    (unreachable peer, long build) must not block other shapes' lookups.
    Timing-bound: B resolves while A is still stuck in its load."""
    cache = BucketCache(Metrics())
    spec_a = JobSpec.from_wire(dict(TOY, gates=8, seed=0))
    spec_b = JobSpec.from_wire(dict(TOY, gates=12, seed=0))
    stall = threading.Event()
    entered = threading.Event()
    real = cache._load_or_build

    def slow_load(spec, key):
        if spec.params["gates"] == 8:
            entered.set()
            assert stall.wait(30)
        return real(spec, key)

    cache._load_or_build = slow_load
    t = threading.Thread(target=cache.get, args=(spec_a,), daemon=True)
    t.start()
    assert entered.wait(10)
    t0 = time.monotonic()
    cache.get(spec_b)               # must not wait for A's latch
    elapsed = time.monotonic() - t0
    stall.set()
    t.join(timeout=60)
    assert elapsed < 5, \
        f"shape B stalled {elapsed:.1f}s behind shape A's cold load"


def test_bucket_latch_concurrent_same_shape_builds_once():
    cache = BucketCache(Metrics())
    spec = JobSpec.from_wire(dict(TOY, gates=8, seed=0))
    builds = []
    real = cache._load_or_build

    def counting_load(s, key):
        builds.append(key)
        time.sleep(0.1)             # widen the race window
        return real(s, key)

    cache._load_or_build = counting_load
    results = []
    threads = [threading.Thread(
        target=lambda: results.append(cache.get(spec)), daemon=True)
        for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(builds) == 1, f"duplicated key setup: {builds}"
    assert len(results) == 4 and all(r is results[0] for r in results)
    ctr = cache.metrics.snapshot()["counters"]
    assert ctr.get("bucket_latch_waits", 0) == 3
