"""Transcript stack tests: keccak vs hashlib SHA3, STROBE/merlin behavior."""

import hashlib

from distributed_plonk_tpu import transcript as T


def _sha3_256(data):
    """SHA3-256 built on our keccak_f1600 (rate 136, pad 0x06 / 0x80)."""
    rate = 136
    state = bytearray(200)
    padded = bytearray(data)
    pad_len = rate - (len(data) % rate)
    padded += bytes(pad_len)
    padded[len(data)] ^= 0x06
    padded[-1] ^= 0x80
    for off in range(0, len(padded), rate):
        for i in range(rate):
            state[i] ^= padded[off + i]
        state = T.keccak_f1600_bytes(state)
    return bytes(state[:32])


def test_keccak_matches_hashlib():
    for msg in [b"", b"abc", b"x" * 135, b"y" * 136, b"z" * 1000]:
        assert _sha3_256(msg) == hashlib.sha3_256(msg).digest(), msg[:8]


def test_merlin_deterministic_and_order_sensitive():
    t1 = T.MerlinTranscript(b"test")
    t1.append_message(b"a", b"hello")
    c1 = t1.challenge_bytes(b"c", 32)

    t2 = T.MerlinTranscript(b"test")
    t2.append_message(b"a", b"hello")
    c2 = t2.challenge_bytes(b"c", 32)
    assert c1 == c2

    t3 = T.MerlinTranscript(b"test")
    t3.append_message(b"a", b"hellp")
    assert t3.challenge_bytes(b"c", 32) != c1

    t4 = T.MerlinTranscript(b"test2")
    t4.append_message(b"a", b"hello")
    assert t4.challenge_bytes(b"c", 32) != c1


def test_challenge_changes_after_append():
    t = T.MerlinTranscript(b"test")
    a = t.challenge_bytes(b"c", 64)
    t.append_message(b"m", b"data")
    b = t.challenge_bytes(b"c", 64)
    assert a != b


def test_long_absorb_crosses_rate_boundary():
    t = T.MerlinTranscript(b"test")
    t.append_message(b"big", b"q" * 1000)
    assert len(t.challenge_bytes(b"c", 200)) == 200


def test_g1_compression_flags():
    from distributed_plonk_tpu import curve as C
    from distributed_plonk_tpu.constants import Q_MOD

    b = T.g1_to_bytes_compressed(None)
    assert b[47] & (1 << 6)
    p = C.G1_GEN
    b = T.g1_to_bytes_compressed(p)
    assert int.from_bytes(b[:47] + bytes([b[47] & 0x3F]), "little") == p[0]
    neg = C.g1_neg(p)
    bn = T.g1_to_bytes_compressed(neg)
    assert (b[47] ^ bn[47]) & (1 << 7)  # exactly one of y/-y has the flag


def test_fr_serialization_roundtrip():
    x = 0x1234567890ABCDEF
    assert T.fr_from_le_bytes_mod_order(T.fr_to_bytes(x)) == x
