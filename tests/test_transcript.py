"""Transcript stack tests: keccak vs hashlib SHA3, STROBE/merlin behavior."""

import hashlib

from distributed_plonk_tpu import transcript as T


def _sha3_256(data):
    """SHA3-256 built on our keccak_f1600 (rate 136, pad 0x06 / 0x80)."""
    rate = 136
    state = bytearray(200)
    padded = bytearray(data)
    pad_len = rate - (len(data) % rate)
    padded += bytes(pad_len)
    padded[len(data)] ^= 0x06
    padded[-1] ^= 0x80
    for off in range(0, len(padded), rate):
        for i in range(rate):
            state[i] ^= padded[off + i]
        state = T.keccak_f1600_bytes(state)
    return bytes(state[:32])


def test_keccak_matches_hashlib():
    for msg in [b"", b"abc", b"x" * 135, b"y" * 136, b"z" * 1000]:
        assert _sha3_256(msg) == hashlib.sha3_256(msg).digest(), msg[:8]


def test_merlin_deterministic_and_order_sensitive():
    t1 = T.MerlinTranscript(b"test")
    t1.append_message(b"a", b"hello")
    c1 = t1.challenge_bytes(b"c", 32)

    t2 = T.MerlinTranscript(b"test")
    t2.append_message(b"a", b"hello")
    c2 = t2.challenge_bytes(b"c", 32)
    assert c1 == c2

    t3 = T.MerlinTranscript(b"test")
    t3.append_message(b"a", b"hellp")
    assert t3.challenge_bytes(b"c", 32) != c1

    t4 = T.MerlinTranscript(b"test2")
    t4.append_message(b"a", b"hello")
    assert t4.challenge_bytes(b"c", 32) != c1


def test_challenge_changes_after_append():
    t = T.MerlinTranscript(b"test")
    a = t.challenge_bytes(b"c", 64)
    t.append_message(b"m", b"data")
    b = t.challenge_bytes(b"c", 64)
    assert a != b


def test_long_absorb_crosses_rate_boundary():
    t = T.MerlinTranscript(b"test")
    t.append_message(b"big", b"q" * 1000)
    assert len(t.challenge_bytes(b"c", 200)) == 200


def test_g1_compression_flags():
    from distributed_plonk_tpu import curve as C
    from distributed_plonk_tpu.constants import Q_MOD

    b = T.g1_to_bytes_compressed(None)
    assert b[47] & (1 << 6)
    p = C.G1_GEN
    b = T.g1_to_bytes_compressed(p)
    assert int.from_bytes(b[:47] + bytes([b[47] & 0x3F]), "little") == p[0]
    neg = C.g1_neg(p)
    bn = T.g1_to_bytes_compressed(neg)
    assert (b[47] ^ bn[47]) & (1 << 7)  # exactly one of y/-y has the flag


def test_fr_serialization_roundtrip():
    x = 0x1234567890ABCDEF
    assert T.fr_from_le_bytes_mod_order(T.fr_to_bytes(x)) == x


def test_merlin_known_answer_vs_rust_crate():
    """Known-answer test against the merlin 3.0 Rust crate itself.

    This is the `equivalence_simple` sequence from merlin's own test suite
    (dalek-cryptography/merlin, src/transcript.rs); the expected hex is the
    crate's recorded STROBE output, also pinned by independent ports
    (merlin.go, noble JS). Passing it proves the whole
    keccak-f1600/STROBE-128/merlin framing stack here is byte-compatible
    with the library the reference's FakeStandardTranscript wraps
    (/root/reference/src/dispatcher2.rs:44-154)."""
    t = T.MerlinTranscript(b"test protocol")
    t.append_message(b"some label", b"some data")
    assert t.challenge_bytes(b"challenge", 32).hex() == (
        "d5a21972d0d5fe320c0d263fac7fffb8145aa640af6e9bca177c03c7efcf0615")


def test_plonk_schedule_recorded_vectors():
    """Regression pin of the jf-plonk-style challenge schedule bytes.

    Recorded once from this implementation (which passes the merlin crate
    KAT above): any refactor of the transcript stack that changes these
    bytes would silently break byte-compatibility of proofs."""
    t = T.MerlinTranscript(b"PlonkProof")
    t.append_message(b"field size in bits", (255).to_bytes(8, "little"))
    t.append_message(b"domain size", (1 << 13).to_bytes(8, "little"))
    expected = {
        b"beta": "c91644208bf979da8bd5ddbad67773147c28f04c18a008075e1d4833"
                 "6aa840244347e5107cb7d0fba3b2f5b4187df95b62a817a46a97f68f"
                 "487d75fb3331a974",
        b"gamma": "1f247ab0bdd12a3aca00b5e9a2b405390759afb7a1c4a935cec198e1"
                  "abda4b30bbb7fa8234096a6da6eff416248312915d0445c671d429df"
                  "faf8467a9cf1f435",
        b"alpha": "53573610031251ab8dc50b6cd3af3dd591d824bc7e080ccddadbc25a"
                  "13a52207deba64272c943b4387a2675cc0000ce07f0a17038130efb1"
                  "fbf6176594986989",
    }
    for label, want in expected.items():
        buf = t.challenge_bytes(label, 64)
        assert buf.hex() == want, label
        t.append_message(label, buf[:32])
