"""Native data plane (C++ limb codec/transpose) + framed transport +
distributed worker/dispatcher runtime.

The runtime analog of the reference's distributed tests (test_msm
/root/reference/src/dispatcher.rs:177-244, test_fft :246-350, test2
dispatcher2.rs:1273-1295) — but against an in-process localhost fleet
(SURVEY.md §4's "missing piece"), not a hand-provisioned LAN.
"""

import os
import random
import subprocess
import sys
import time

import numpy as np
import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.backend.limbs import ints_to_limbs
from distributed_plonk_tpu.runtime import native, protocol
from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
from distributed_plonk_tpu.runtime.dispatcher import Dispatcher, RemoteBackend

RNG = random.Random(0xD15)
REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))


# --- data plane --------------------------------------------------------------

def test_native_limb_codec_matches_python():
    vals = [RNG.randrange(R_MOD) for _ in range(100)]
    raw = b"".join(v.to_bytes(32, "little") for v in vals)
    got = native.bytes_to_limbs(raw, 100, 32)
    assert np.array_equal(got, ints_to_limbs(vals, 16))
    assert native.limbs_to_bytes(got) == raw


def test_native_limb_codec_rejects_unreduced():
    bad = np.full((16, 4), 0x10000, dtype=np.uint32)
    with pytest.raises(ValueError):
        native.limbs_to_bytes(bad)


def test_native_transpose():
    a = np.arange(96 * 130, dtype=np.uint32).reshape(96, 130)
    assert np.array_equal(native.transpose(a), a.T)


# --- transport + fleet -------------------------------------------------------

def _spawn_fleet(tmp_path_factory, backend, port_base, startup_s):
    """Start a 2-worker fleet; yields a connected Dispatcher and always
    reaps the worker processes (including when startup fails)."""
    cfg_path = str(tmp_path_factory.mktemp(f"rt-{backend}") / "network.json")
    base = port_base + (os.getpid() % 500) * 2
    cfg = NetworkConfig([f"127.0.0.1:{base}", f"127.0.0.1:{base + 1}"])
    cfg.save(cfg_path)
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "distributed_plonk_tpu.runtime.worker",
             str(i), cfg_path, "--backend", backend],
            cwd=REPO)
        for i in range(2)
    ]
    try:
        d = None
        deadline = time.time() + startup_s
        while time.time() < deadline:
            try:
                d = Dispatcher(cfg)
                d.ping()
                break
            except (ConnectionError, OSError):
                time.sleep(0.3)
                d = None
        assert d is not None, f"{backend} workers did not come up"
        d.worker_procs = procs  # exposed for failure-injection tests
        yield d
        d.shutdown()
        for p in procs:
            p.wait(timeout=10)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    yield from _spawn_fleet(tmp_path_factory, "python", 19000, 30)


def test_distributed_msm(fleet):
    n = 64
    bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD)) for _ in range(n - 1)]
    bases.append(None)
    scalars = [RNG.randrange(R_MOD) for _ in range(n - 1)] + [0]
    fleet.init_bases(bases)
    assert fleet.msm(scalars) == C.g1_msm(bases, scalars)


def test_distributed_ntt_all_modes(fleet):
    n = 64
    domain = P.Domain(n)
    values = [RNG.randrange(R_MOD) for _ in range(n)]
    assert fleet.ntt(values) == P.fft(domain, values)
    assert fleet.ntt(values, inverse=True) == P.ifft(domain, values)
    assert fleet.ntt(values, coset=True) == P.coset_fft(domain, values)
    assert fleet.ntt(values, inverse=True, coset=True) == P.coset_ifft(domain, values)
    jobs = [(values, False, False), (values, True, False), (values, False, True)]
    got = fleet.ntt_many(jobs)
    assert got == [P.fft(domain, values), P.ifft(domain, values),
                   P.coset_fft(domain, values)]


@pytest.mark.parametrize("coset", [False, True])
@pytest.mark.parametrize("inverse", [False, True])
def test_distributed_sharded_fft(fleet, inverse, coset):
    """Cross-worker 4-step FFT == oracle for all mode combos, both square
    (r == c) and uneven (r != c) splits — the fleet analog of the
    reference's test_fft 8-combo sweep (src/dispatcher.rs:246-350)."""
    for n in (64, 128):
        domain = P.Domain(n)
        values = [RNG.randrange(R_MOD) for _ in range(n)]
        if inverse and coset:
            want = P.coset_ifft(domain, values)
        elif inverse:
            want = P.ifft(domain, values)
        elif coset:
            want = P.coset_fft(domain, values)
        else:
            want = P.fft(domain, values)
        assert fleet.fft_dist(values, inverse=inverse, coset=coset) == want


def test_remote_prove_matches_oracle(fleet, proven):
    """Fully-distributed prove through the worker fleet == host proof
    (the reference's test2 invariant), with the per-poly NTT batches
    actually spread across >1 worker (join_all, dispatcher2.rs:294-321)."""
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.verifier import verify

    ckt, pk, vk, proof_host = proven
    before = fleet.stats()
    proof = prove(random.Random(1), ckt, pk, RemoteBackend(fleet))
    assert verify(vk, ckt.public_input(), proof, rng=random.Random(2))
    assert proof.opening_proof == proof_host.opening_proof
    assert proof.wires_poly_comms == proof_host.wires_poly_comms
    assert proof.split_quot_poly_comms == proof_host.split_quot_poly_comms

    # every worker served both NTTs and MSM shards during the prove
    after = fleet.stats()
    for b, a in zip(before, after):
        assert a.get(str(protocol.NTT), 0) > b.get(str(protocol.NTT), 0)
        assert a.get(str(protocol.MSM), 0) > b.get(str(protocol.MSM), 0)


def test_remote_prove_with_sharded_fft(fleet, proven):
    """Prove with every main-domain+ NTT run as the cross-worker sharded
    4-step FFT (the reference's v2 hot path, dispatcher2.rs:731-787):
    proof still byte-identical."""
    from distributed_plonk_tpu.prover import prove

    ckt, pk, vk, proof_host = proven
    before = fleet.stats()
    proof = prove(random.Random(1), ckt, pk,
                  RemoteBackend(fleet, dist_fft_min=ckt.n))
    assert proof.opening_proof == proof_host.opening_proof
    assert proof.split_quot_poly_comms == proof_host.split_quot_poly_comms
    after = fleet.stats()
    for b, a in zip(before, after):
        assert a.get(str(protocol.FFT2), 0) > b.get(str(protocol.FFT2), 0)
        assert a.get(str(protocol.FFT_EXCHANGE), 0) > b.get(str(protocol.FFT_EXCHANGE), 0)


@pytest.mark.slow
def test_sharded_fft_2p16_within_budget(fleet):
    """The fleet 4-step FFT at 2^16 under a wall-clock budget — the data
    plane is bulk limb codecs + numpy restrides end to end (VERDICT round-2
    weakness #8: the per-int Python plane was the 2^18 bottleneck); oracle
    checked via round-trip (forward then inverse) plus a spot-check against
    the host FFT on a random subset is too weak — full ifft oracle compare
    stays exact and is itself fast."""
    n = 1 << 16
    values = [RNG.randrange(R_MOD) for _ in range(n)]
    t0 = time.time()
    out = fleet.fft_dist(values, inverse=True)
    elapsed = time.time() - t0
    domain = P.Domain(n)
    assert out == P.ifft(domain, values)
    # generous for a 1-core CI host driving 2 python-backend workers; the
    # round-2 per-int plane was far beyond this at 2^16
    assert elapsed < 420, f"fleet 2^16 iFFT took {elapsed:.0f}s"


@pytest.fixture(scope="module")
def jax_fleet(tmp_path_factory):
    """Two workers on the JAX backend: FFT1/FFT2 run as single batched
    device launches over limb panels (runtime/jax_stages.py)."""
    yield from _spawn_fleet(tmp_path_factory, "jax", 21000, 60)


@pytest.mark.parametrize("coset", [False, True])
@pytest.mark.parametrize("inverse", [False, True])
def test_jax_fleet_sharded_fft(jax_fleet, inverse, coset):
    """Cross-worker 4-step FFT on jax workers (batched stage kernels) ==
    oracle, all mode combos, square and uneven splits."""
    for n in (64, 128):
        domain = P.Domain(n)
        values = [RNG.randrange(R_MOD) for _ in range(n)]
        if inverse and coset:
            want = P.coset_ifft(domain, values)
        elif inverse:
            want = P.ifft(domain, values)
        elif coset:
            want = P.coset_fft(domain, values)
        else:
            want = P.fft(domain, values)
        got = jax_fleet.fft_dist(values, inverse=inverse, coset=coset)
        assert got == want, (n, inverse, coset)


def test_msm_elastic_recovery(tmp_path_factory):
    """Kill one worker mid-prove: its MSM range is re-provisioned onto a
    healthy worker and the result is unchanged — the failure the reference
    cannot survive (every RPC is .unwrap(), SURVEY.md §5: 'a worker crash
    hangs or panics the prove')."""
    gen = _spawn_fleet(tmp_path_factory, "python", 23000, 30)
    d = next(gen)
    try:
        n = 64
        bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                 for _ in range(n)]
        scalars = [RNG.randrange(R_MOD) for _ in range(n)]
        want = C.g1_msm(bases, scalars)
        d.init_bases(bases)
        assert d.msm(scalars) == want

        d.worker_procs[1].kill()
        d.worker_procs[1].wait(timeout=10)
        assert d.msm(scalars) == want  # range 1 adopted by worker 0
    finally:
        gen.close()


def test_msm_recovery_memoized_and_repeated(tmp_path_factory):
    """After a death, later MSMs route straight to the adopting worker
    (no re-dial / re-upload), and a fresh init_bases resets adoptions."""
    gen = _spawn_fleet(tmp_path_factory, "python", 25000, 30)
    d = gen.__next__()
    try:
        n = 32
        bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                 for _ in range(n)]
        scalars = [RNG.randrange(R_MOD) for _ in range(n)]
        want = C.g1_msm(bases, scalars)
        d.init_bases(bases)
        d.worker_procs[1].kill()
        d.worker_procs[1].wait(timeout=10)
        assert d.msm(scalars) == want
        assert d._adopted == {1: 0}
        # memoized: repeated msm works and keeps the adoption
        assert d.msm(scalars) == want
        assert d._adopted == {1: 0}
        # re-provisioning with one worker dead still succeeds lazily
        bases2 = bases[::-1]
        d.init_bases(bases2)
        assert d._adopted == {}
        assert d.msm(scalars) == C.g1_msm(bases2, scalars)
        assert d._adopted == {1: 0}
    finally:
        gen.close()


def test_ntt_routes_around_dead_worker(tmp_path_factory):
    """Whole-poly NTT offload is stateless, so a dead worker is skipped."""
    gen = _spawn_fleet(tmp_path_factory, "python", 27000, 30)
    d = gen.__next__()
    try:
        n = 64
        domain = P.Domain(n)
        values = [RNG.randrange(R_MOD) for _ in range(n)]
        d.worker_procs[0].kill()
        d.worker_procs[0].wait(timeout=10)
        # worker index 0 is the preferred target; must fall through to 1
        assert d.ntt(values, worker=0) == P.fft(domain, values)
        assert d.ntt_many([(values, True, False)]) == \
            [P.ifft(domain, values)]
    finally:
        gen.close()
