"""Tracer unit tests + per-round prove instrumentation + merge/export."""

import json
import math

from distributed_plonk_tpu.trace import (NULL_TRACER, Tracer, merge_traces,
                                         msm_flops, ntt_flops,
                                         to_chrome_trace)


def test_tracer_spans_nest_and_total():
    tr = Tracer()
    with tr.span("round1"):
        with tr.span("ifft", polys=5):
            pass
    with tr.span("round2"):
        pass
    spans = [e["span"] for e in tr.events]
    assert spans == ["round1/ifft", "round1", "round2"]
    assert tr.events[0]["polys"] == 5
    tot = tr.totals(depth=1)
    assert set(tot) == {"round1", "round2"}
    data = json.loads(tr.to_json())
    assert len(data["events"]) == 3


def test_spans_carry_ids_timestamps_and_parents():
    """The PR 9 satellite fix: spans without start times could not be
    ordered or reconstructed — every event now carries ts/sid/parent."""
    tr = Tracer(proc="p")
    with tr.span("outer") as outer_sid:
        with tr.span("inner"):
            pass
    inner, outer = tr.events
    assert len(tr.trace_id) == 32 and len(inner["sid"]) == 16
    assert inner["parent"] == outer_sid == outer["sid"]
    assert "parent" not in outer          # root span
    # start order is reconstructable: outer started first, and the
    # inner span lies within the outer's [ts, ts+dur] window
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur_s"] <= outer["ts"] + outer["dur_s"] + 1e-3
    d = tr.dump()
    assert d["proc"] == "p" and d["pid"] and d["host"]


def test_overlapping_spans_reconstruct():
    """Concurrent spans (the PR 6 overlapped canaries, pool concurrency)
    are distinguishable by their timestamps, not just durations."""
    import threading
    tr = Tracer()
    gate = threading.Barrier(2)

    def one(name):
        with tr.span(name):
            gate.wait(timeout=5)

    ts = [threading.Thread(target=one, args=(f"job{i}",)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    a, b = sorted(tr.events, key=lambda e: e["ts"])
    # both ran simultaneously: the second started before the first ended
    assert b["ts"] < a["ts"] + a["dur_s"]
    assert a["tid"] != b["tid"]


def test_context_inject_extract_links_processes():
    parent = Tracer(proc="client")
    with parent.span("request") as sid:
        ctx = parent.context()
    assert ctx == {"trace_id": parent.trace_id, "parent_id": sid}
    child = Tracer.from_context(ctx, proc="server")
    assert child.trace_id == parent.trace_id
    with child.span("serve"):
        pass
    assert child.events[0]["parent"] == sid
    # explicit parent override (the per-frame linkage receivers use)
    with child.span("serve2", parent="ab" * 8):
        pass
    assert child.events[1]["parent"] == "ab" * 8
    # synthetic spans inherit the remote parent too (the queue-wait
    # event must not fall out of the client's tree)
    child.add_event("queued", ts=1.0, dur_s=0.1)
    assert child.events[2]["parent"] == sid
    # garbage context degrades to a fresh root trace, never an error
    fresh = Tracer.from_context(None)
    assert len(fresh.trace_id) == 32


def test_merge_applies_offsets_and_sorts():
    a = Tracer(proc="dispatcher")
    with a.span("fleet"):
        pass
    b = Tracer.from_context(a.context(), proc="worker")
    with b.span("kernel"):
        pass
    # pretend worker's clock runs 100s ahead: offset correction must
    # pull its spans back onto the dispatcher's timeline
    b_dump = b.dump()
    for ev in b_dump["events"]:
        ev["ts"] += 100.0
    merged = merge_traces([a.dump(), b_dump], offsets=[0.0, 100.0])
    assert merged["trace_id"] == a.trace_id
    assert [p["proc"] for p in merged["processes"]] == ["dispatcher",
                                                       "worker"]
    ts = [e["ts"] for e in merged["events"]]
    assert ts == sorted(ts)
    assert max(ts) - min(ts) < 10  # the 100s skew was corrected away
    assert {e["proc"] for e in merged["events"]} == {"dispatcher", "worker"}


def test_chrome_trace_schema():
    tr = Tracer(proc="x")
    with tr.span("a", polys=3):
        with tr.span("b"):
            pass
    ct = to_chrome_trace(merge_traces([tr.dump()]))
    meta = [e for e in ct["traceEvents"] if e["ph"] == "M"]
    assert meta and meta[0]["name"] == "process_name"
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 2
    for e in xs:
        for key in ("name", "cat", "ts", "dur", "pid", "tid", "args"):
            assert key in e, (key, e)
        assert e["ts"] >= 0 and e["dur"] >= 0
    assert any(e["args"].get("polys") == 3 for e in xs)
    assert ct["otherData"]["trace_id"] == tr.trace_id
    json.dumps(ct)  # the export must be pure JSON


def test_synthetic_events_and_flops_models():
    tr = Tracer()
    sid = tr.add_event("service/queued", ts=123.0, dur_s=0.5, job_id="j1")
    assert tr.events[0]["ts"] == 123.0 and tr.events[0]["sid"] == sid
    assert ntt_flops(1) == 0
    assert ntt_flops(8) == 4 * 3 * (3 * 32 * 32 * 2)
    assert ntt_flops(8, 2) == 2 * ntt_flops(8)
    assert msm_flops(10) == 10 * 32 * 11 * (3 * 48 * 48 * 2)


def test_null_tracer_noop():
    with NULL_TRACER.span("x") as sid:
        assert sid is None
    assert NULL_TRACER.totals() == {}
    assert NULL_TRACER.context() is None
    assert NULL_TRACER.dump() == {}


def test_prove_emits_round_spans(proven):
    import random
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.backend.python_backend import PythonBackend

    ckt, pk, vk, proof = proven
    tr = Tracer()
    proof2 = prove(random.Random(1), ckt, pk, PythonBackend(), tracer=tr)
    # same rng seed => identical proof; tracing must not perturb the prover
    assert proof2.wires_poly_comms == proof.wires_poly_comms
    tot = tr.totals(depth=1)
    assert set(tot) == {"round1", "round2", "round3", "round4", "round5"}
    assert all(v >= 0 for v in tot.values())
    sub = [e["span"] for e in tr.events]
    assert "round3/quotient_evals" in sub and "round1/commit_wires" in sub
    # kernel spans carry the flops/bytes attribution the MFU gauges read
    commits = [e for e in tr.events if e["span"] == "round1/commit_wires"]
    assert commits[0]["flops"] > 0 and commits[0]["data_bytes"] > 0
    # one timeline: every span under the one trace id, ts-ordered spans
    # reconstruct the round sequence
    rounds = [e for e in tr.events if e["span"].startswith("round")
              and "/" not in e["span"]]
    assert [e["span"] for e in sorted(rounds, key=lambda e: e["ts"])] == \
        ["round1", "round2", "round3", "round4", "round5"]


# --- metrics export (service/metrics.py satellites) --------------------------

def test_histogram_snapshot_reports_samples_and_clamps():
    from distributed_plonk_tpu.service.metrics import Histogram
    h = Histogram()
    h.record(1.0)
    h.record(2.0)
    snap = h.snapshot()
    # the old int(p*len) indexed the max for ANY p >= 0.5 at 2 samples;
    # nearest-rank gives the median
    assert snap["p50_s"] == 1.0
    assert snap["p99_s"] == 2.0
    assert snap["samples"] == 2 and snap["count"] == 2
    one = Histogram()
    one.record(3.0)
    s1 = one.snapshot()
    assert s1["p50_s"] == s1["p99_s"] == 3.0 and s1["samples"] == 1
    # past the reservoir cap, samples < count (percentiles are estimates)
    big = Histogram()
    for i in range(3000):
        big.record(float(i))
    sb = big.snapshot()
    assert sb["count"] == 3000 and sb["samples"] == 2048
    assert math.isclose(sb["p50_s"], 1500.0, rel_tol=0.2)


def test_prometheus_exposition():
    from distributed_plonk_tpu.service.metrics import Metrics
    m = Metrics()
    m.inc("jobs_completed", 3)
    m.gauge("queue_depth", 7)
    m.observe("job_run", 0.5)
    m.observe("prove_round/round1", 0.25)
    text = m.to_prometheus(extra_gauges={"queue_high_water": 9})
    assert "# TYPE dpt_jobs_completed_total counter" in text
    assert "dpt_jobs_completed_total 3" in text
    assert "dpt_queue_depth 7" in text
    assert "dpt_queue_high_water 9" in text
    assert 'dpt_job_run_seconds{quantile="0.5"} 0.5' in text
    assert "dpt_prove_round_round1_seconds_count 1" in text
    assert "dpt_uptime_s" in text
    # exposition-format discipline: every line is `name value` or a
    # comment; names are [a-zA-Z0-9_:] only
    import re
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name = line.split(None, 1)[0]
        assert re.fullmatch(r"[a-zA-Z_][a-zA-Z0-9_]*(\{[^}]*\})?", name), line


def test_observe_kernels_mfu_gauges():
    from distributed_plonk_tpu.service.metrics import Metrics
    m = Metrics()
    m.observe_kernels(
        [{"span": "round1/commit_wires", "dur_s": 2.0, "flops": 4e9},
         {"span": "round1", "dur_s": 1.0}],        # no flops: skipped
        peak_tflops=0.004)
    g = m.snapshot()["gauges"]
    assert g["kernel_commit_wires_gflops"] == 2.0
    assert g["mfu_commit_wires_pct"] == 50.0
    assert not any(k.endswith("round1_gflops") for k in g)


def test_obs_lint_catches_undocumented_metric():
    from distributed_plonk_tpu.analysis.lint import lint_source
    doc = ("Glossary:\n"
           "    jobs_completed   terminal outcomes\n"
           "    faults_injected_*  chaos family\n"
           "    store_hits       scoped store metric\n")
    src = ("class A:\n"
           "    def f(self):\n"
           "        self.metrics.inc('jobs_completed')\n"
           "        self.metrics.inc('faults_injected_kill')\n"
           "        self.metrics.inc('hits')\n"            # store_hits
           "        self.metrics.observe('ghost_seconds', 1)\n")
    found = lint_source(src, kinds=("obs",), glossary_doc=doc)
    assert len(found) == 1 and found[0].code == "OBS01"
    assert "ghost_seconds" in found[0].message
    # prose in the DESCRIPTION column must not document a metric: only
    # the name column (before the >=2-space gap) counts
    prose = ("class B:\n"
             "    def f(self):\n"
             "        self.metrics.inc('outcomes')\n"
             "        self.metrics.inc('terminal')\n")
    doc2 = "Glossary:\n    jobs_completed   terminal outcomes\n"
    assert len(lint_source(prose, kinds=("obs",), glossary_doc=doc2)) == 2
    # pragma suppression works like every other lint
    src_ok = src.replace("self.metrics.observe('ghost_seconds', 1)",
                         "self.metrics.observe('ghost_seconds', 1)"
                         "  # analysis: ok(test-only)")
    assert lint_source(src_ok, kinds=("obs",), glossary_doc=doc) == []
