"""Tracer unit tests + per-round prove instrumentation."""

import json

from distributed_plonk_tpu.trace import Tracer, NULL_TRACER


def test_tracer_spans_nest_and_total():
    tr = Tracer()
    with tr.span("round1"):
        with tr.span("ifft", polys=5):
            pass
    with tr.span("round2"):
        pass
    spans = [e["span"] for e in tr.events]
    assert spans == ["round1/ifft", "round1", "round2"]
    assert tr.events[0]["polys"] == 5
    tot = tr.totals(depth=1)
    assert set(tot) == {"round1", "round2"}
    data = json.loads(tr.to_json())
    assert len(data["events"]) == 3


def test_null_tracer_noop():
    with NULL_TRACER.span("x"):
        pass
    assert NULL_TRACER.totals() == {}


def test_prove_emits_round_spans(proven):
    import random
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.backend.python_backend import PythonBackend

    ckt, pk, vk, proof = proven
    tr = Tracer()
    proof2 = prove(random.Random(1), ckt, pk, PythonBackend(), tracer=tr)
    # same rng seed => identical proof; tracing must not perturb the prover
    assert proof2.wires_poly_comms == proof.wires_poly_comms
    tot = tr.totals(depth=1)
    assert set(tot) == {"round1", "round2", "round3", "round4", "round5"}
    assert all(v >= 0 for v in tot.values())
    sub = [e["span"] for e in tr.events]
    assert "round3/quotient_evals" in sub and "round1/commit_wires" in sub
