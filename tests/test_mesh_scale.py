"""Mesh NTT/MSM beyond toy sizes + the 2^21 quotient-domain memory plan.

Round-2 gap (VERDICT weak #7): mesh paths were tested only to n=512/64,
while the reference exercises 2^20 MSM / 2^13 FFT over live workers
(/root/reference/src/dispatcher.rs:188-196,253-254) and its v2 workload
needs a 2^21 quotient-domain NTT (src/dispatcher2.rs:246). These run on
the 8-device virtual CPU mesh within an explicit wall-clock budget.
"""

import random
import time

import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.parallel.mesh import make_mesh
from distributed_plonk_tpu.parallel.memory_plan import (
    ntt_mesh_plan, msm_mesh_plan)

RNG = random.Random(0x5CA1E)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8, platform="cpu")


@pytest.mark.slow
def test_mesh_ntt_2p14(mesh8):
    from distributed_plonk_tpu.parallel.ntt_mesh import MeshNttPlan

    n = 1 << 14
    values = [RNG.randrange(R_MOD) for _ in range(n)]
    domain = P.Domain(n)
    plan = MeshNttPlan(mesh8, n)
    t0 = time.time()
    coeffs = plan.run_ints(values, inverse=True)
    elapsed = time.time() - t0
    assert coeffs == P.ifft(domain, values)
    evals = plan.run_ints(coeffs, coset=True)
    assert evals == P.coset_fft(domain, coeffs)
    assert elapsed < 600, f"mesh 2^14 iNTT took {elapsed:.0f}s"


@pytest.mark.slow
def test_mesh_msm_2p12(mesh8):
    from distributed_plonk_tpu.parallel.msm_mesh import MeshMsmContext

    n = 1 << 12
    distinct = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                for _ in range(64)]
    bases = (distinct * (n // 64))[:n]
    scalars = [RNG.randrange(R_MOD) for _ in range(n)]
    ctx = MeshMsmContext(mesh8, bases)
    assert ctx.signed  # local slice 512 >= 256: the c=8 signed hot path
    t0 = time.time()
    got = ctx.msm(scalars)
    elapsed = time.time() - t0
    assert got == C.g1_msm(bases, scalars)
    assert elapsed < 900, f"mesh 2^12 MSM took {elapsed:.0f}s"


@pytest.mark.slow
def test_mesh_msm_2p16_signed_handles(mesh8):
    """2^16-point mesh MSM through the PROVER surface: Montgomery poly
    handles in, signed batched pipeline per shard, on-device digit
    extraction + plane fold (the round-3 ceiling was 2^12 host-int
    scalars through the unsigned scan; reference micro-test scale is
    2^20 over live workers, src/dispatcher.rs:188-196)."""
    import jax.numpy as jnp
    from distributed_plonk_tpu.parallel.msm_mesh import MeshMsmContext
    from distributed_plonk_tpu.backend import prover_jax as PJ

    n = 1 << 16
    distinct = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                for _ in range(256)]
    bases = (distinct * (n // 256))[:n]
    ctx = MeshMsmContext(mesh8, bases)
    assert ctx.signed and ctx.c == 8
    coeff_lists = [[RNG.randrange(R_MOD) for _ in range(n)]
                   for _ in range(2)]
    handles = [jnp.asarray(PJ.lift(cs)) for cs in coeff_lists]
    t0 = time.time()
    got = ctx.msm_mont_limbs_many(handles)
    elapsed = time.time() - t0
    for g, cs in zip(got, coeff_lists):
        assert g == C.g1_msm(bases, cs)
    assert elapsed < 1800, f"mesh 2^16 batched MSM took {elapsed:.0f}s"


def test_quotient_domain_2p21_memory_plan():
    """The v2 workload's 2^21 quotient NTT must fit a v5e-8 mesh with
    margin: the sharded working set is small; even the worst-case un-fused
    mont_mul transient stays under half of one chip's 16 GB HBM."""
    HBM = 16 << 30
    plan = ntt_mesh_plan(1 << 21, 8, batch=1)
    assert plan["r"] * plan["c"] == 1 << 21
    assert plan["total_fused"] < HBM // 100, plan   # ~50 MB/device fused
    assert plan["total_worst"] < HBM // 2, plan     # <8 GB even un-fused
    # single-chip fallback (the current bench hardware) also fits fused
    single = ntt_mesh_plan(1 << 21, 1, batch=1)
    assert single["total_fused"] + single["transient_full"] // 8 < HBM, single

    # the 2^18-key signed MSM planes at the default chunking fit comfortably
    msm = msm_mesh_plan(1 << 18, 8, batch=8, group=64)
    assert msm["total"] < HBM // 4, msm
