"""Static verifier: seeded mutants are caught, the production surface is
clean, and the CLI exit code tracks violations.

The mutants mirror the bug classes the verifier exists for:
- DROPPED CARRY SWEEP: uncarried columns flow into the next product ->
  u32 product overflow the interval pass must flag;
- WIDENED SHIFT: a byte-column recombine shifted past its headroom;
- PYTHON FLOAT in a traced kernel: silent f32 promotion;
- REMOVED LOCK: shared-state write outside the lock scope (AST lint);
- STALE JIT CACHE KEY: a cached trace depending on a non-key parameter.

analysis/mutants.py carries the VALUE-class corpus on top (dropped
carry lane, off-by-one limb shift, wrong modulus constant, swapped
twiddle table — each bounds-clean and rejected only by the value pass —
plus the lock-order-cycle and undocumented-knob lint sources); the
harness tests below assert every one of those is still rejected for
the right reason.

Each must produce >= 1 violation / finding; the real kernels and the
real repo must produce none (the `--strict` contract ci.sh analyze
enforces over the FULL registry — here a representative subset keeps
tier-1 cheap).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_plonk_tpu.analysis import bounds as B
from distributed_plonk_tpu.analysis import lint as L
from distributed_plonk_tpu.analysis import registry as R
from distributed_plonk_tpu.analysis.__main__ import main as cli_main
from distributed_plonk_tpu.backend import field_jax as FJ

U16 = (1 << 16) - 1


# --- seeded kernel mutants (each must be caught) ------------------------------

def test_mutant_dropped_carry_sweep_is_caught():
    spec = FJ.FR
    l = spec.n_limbs

    def mont_mul_dropped_sweep(a, b):
        t_cols = FJ._mul_columns_u32(a, b, 2 * l)
        t_lo = t_cols[:l]  # MUTANT: carry sweep dropped
        ninv = FJ._bcast_const(spec.ninv_limbs, a.ndim)
        m, _ = FJ._carry_sweep(FJ._mul_columns_u32(t_lo, ninv, l))
        p = FJ._bcast_const(spec.mod_limbs, a.ndim)
        mp_cols = FJ._mul_columns_u32(m, p, 2 * l)
        _, c_lo = FJ._carry_sweep(mp_cols[:l] + t_lo)
        hi = (mp_cols[l:] + t_cols[l:]).at[0].add(c_lo)
        return FJ._cond_sub_mod(spec, hi)

    v = B.check_fn("mutant", mont_mul_dropped_sweep,
                   (B.limb_rows(l, 4), B.limb_rows(l, 4)))
    assert v and any("range exceeded" in x.message for x in v)


def test_mutant_widened_shift_is_caught():
    def combine_widened(col8):
        c = col8.astype(jnp.uint32)
        return c[0::2] + (c[1::2] << 16)  # MUTANT: << 8 widened to << 16

    v = B.check_fn("mutant", combine_widened,
                   (B.Bound((32, 4), jnp.float32, 0, 96 * 255 ** 2),))
    assert v and any("shift_left" == x.prim for x in v)


def test_mutant_python_float_is_caught():
    v = B.check_fn("mutant", lambda a: (a * 1.5).astype(jnp.uint32),
                   (B.limb_rows(16, 4),))
    assert v and any("integer-valued" in x.message for x in v)


def test_floor_remainder_chain_is_bounded_and_mutants_caught():
    """The pow2-rescale/floor provenance rules (the lazy-carry local
    rounds): the exact x - floor(x*2^-8)*256 remainder proves < 256,
    while (a) a mismatched restore base and (b) a non-pow2 scale are
    NOT granted the remainder bound / exactness."""
    import numpy as np_

    def local_round(cols):
        hi = jnp.floor(cols * np_.float32(1.0 / 256.0))
        return cols - hi * np_.float32(256.0)

    f32_in = (B.Bound((8, 4), jnp.float32, 0, 1 << 22),)
    assert B.check_fn("ok", local_round, f32_in,
                      out_bounds=[(0, 255)]) == []

    def wrong_base(cols):  # MUTANT: restores with 512, not 256
        hi = jnp.floor(cols * np_.float32(1.0 / 256.0))
        return cols - hi * np_.float32(512.0)

    v = B.check_fn("mutant", wrong_base, f32_in, out_bounds=[(0, 255)])
    assert v and any(x.prim == "output" for x in v)

    def not_pow2(cols):  # MUTANT: 1/320 scaling is NOT exact in f32
        return jnp.floor(cols * np_.float32(1.0 / 320.0))

    v = B.check_fn("mutant", not_pow2, f32_in)
    assert v and any("integer-valued" in x.message for x in v)


def test_mutant_unbounded_scan_carry_is_caught():
    from jax import lax

    def grows(v):
        def body(c, _):
            return c + v, None
        out, _ = lax.scan(body, v, None, length=8)
        return out

    v = B.check_fn("mutant", grows,
                   (B.Bound((4,), jnp.uint32, 0, 1 << 30),))
    assert v and any("stabilize" in x.message or "range exceeded"
                     in x.message for x in v)


def test_declared_output_bound_is_enforced():
    # a kernel that leaks 17-bit values violates the limb postcondition
    v = B.check_fn("mutant", lambda a: a + a,
                   (B.limb_rows(16, 4),), out_bounds=[(0, U16)])
    assert v and any(x.prim == "output" for x in v)


def test_mutant_pallas_stale_scratch_is_caught(monkeypatch):
    """Inside the fused bucket kernel's pallas_call jaxpr: dropping the
    group-product scratch zeroing (stale f32 columns accumulate across
    the ~12 products of an add AND across grid steps) must be flagged —
    the interpreter enters the kernel jaxpr, models the VMEM refs as
    interval cells, and runs the grid to a fixpoint."""
    import jax.numpy as jnp_
    from distributed_plonk_tpu.backend import curve_pallas as CP

    def band_no_zero(t_ref, a_bytes, b_bytes, w):  # MUTANT: no reset
        nb = a_bytes.shape[0]
        for i in range(nb):
            t_ref[i:i + nb, :w] += a_bytes[i][None, :] * b_bytes
        return t_ref[:, :w]

    monkeypatch.setattr(CP, "_band_mul_w", band_no_zero)
    entry = next(e for e in R.build_registry()
                 if e.name == "msm/bucket_pallas_signed_c7_packed")
    # the kernel wrapper is a module-level jit: drop its cached traces so
    # the mutant actually traces here and the clean suite re-traces after
    import jax
    jax.clear_caches()
    try:
        v = entry.check(strict=True)
    finally:
        jax.clear_caches()
    assert v and any("exactness" in x.message or "stabilize" in x.message
                     or "range exceeded" in x.message for x in v)


# --- AST lint mutants ---------------------------------------------------------

_LOCK_MUTANT = '''
import threading
class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self.entries = {}
    def put(self, k, v):
        with self._lock:
            self.entries[k] = v
    def evict_all(self):   # MUTANT: lock removed
        self.entries = {}
'''

_LOCK_CLEAN = _LOCK_MUTANT.replace(
    "    def evict_all(self):   # MUTANT: lock removed\n"
    "        self.entries = {}",
    "    def evict_all(self):\n"
    "        with self._lock:\n"
    "            self.entries = {}")

_JIT_MUTANT = '''
import jax
from functools import partial
class Kernels:
    def fn(self, n, width):
        if n not in self._fns:
            self._fns[n] = jax.jit(partial(extract, width=width))
        return self._fns[n]
'''

_PROM_MUTANT = "def k(x):\n    return x * 2.0\n"


def test_mutant_removed_lock_is_caught():
    f = L.lint_source(_LOCK_MUTANT)
    assert any(x.code == "LOCK01" for x in f)
    assert not L.lint_source(_LOCK_CLEAN)


def test_lock02_unlocked_write_vs_locked_read():
    src = '''
import threading
class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self.stopping = False
    def gate(self):
        with self._lock:
            return self.stopping
    def stop(self):
        self.stopping = True
'''
    f = L.lint_source(src)
    assert any(x.code == "LOCK02" for x in f)


def test_mutant_stale_jit_cache_key_is_caught():
    f = L.lint_source(_JIT_MUTANT)
    assert any(x.code == "JIT01" and "width" in x.message for x in f)
    # keying on width fixes it
    fixed = _JIT_MUTANT.replace("self._fns[n]",
                                "self._fns[(n, width)]")
    assert not L.lint_source(fixed)


def test_mutant_float_literal_lint_and_pragma():
    assert any(x.code == "PROM01" for x in L.lint_source(_PROM_MUTANT))
    suppressed = _PROM_MUTANT.replace(
        "x * 2.0", "x * 2.0  # analysis: ok(host-only scale)")
    assert not L.lint_source(suppressed)


def test_lock_held_helper_methods_do_not_false_positive():
    src = '''
import threading
class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self.seq = 0
    def bump(self):
        self.seq += 1          # only ever called under the lock
    def put(self):
        with self._lock:
            self.bump()
'''
    assert not L.lint_source(src)


# --- seeded mutant harness (analysis/mutants.py) ------------------------------

from distributed_plonk_tpu.analysis import mutants as M


def test_mutant_harness_every_bug_class_rejected():
    """The ISSUE-19 acceptance gate: >= 5 distinct seeded kernel bug
    classes, each rejected under --strict by the pass that owns it —
    and each value-class mutant PROVEN bounds-clean, demonstrating the
    interval pass's blind spot is real (check_mutants errors on both
    kinds of drift)."""
    seen = []
    errors = M.check_mutants(progress=lambda m, bv, vv: seen.append(m))
    assert errors == []
    assert len(seen) >= 5
    assert len({m.bug for m in seen}) >= 5
    assert any(m.bug == "dropped-carry-lane" for m in seen)


def test_mutant_lock_order_cycle_is_caught():
    f = L.lint_source(M.LOCK03_MUTANT)
    assert any(x.code == "LOCK03" and "lock-order cycle" in x.message
               for x in f)
    # the same classes with the back edge hoisted out of the lock: the
    # cycle is broken and LOCK03 must stay silent
    fixed = L.lint_source(M.LOCK03_FIXED)
    assert not any(x.code == "LOCK03" for x in fixed)


def test_mutant_self_deadlock_is_caught():
    f = L.lint_source(M.LOCK03_SELF_MUTANT)
    assert any(x.code == "LOCK03" and "re-acquired" in x.message
               for x in f)
    # an RLock is re-entrant: the identical call shape is fine
    relock = M.LOCK03_SELF_MUTANT.replace("threading.Lock()",
                                          "threading.RLock()")
    assert not any(x.code == "LOCK03" for x in L.lint_source(relock))


def test_mutant_undocumented_knob_is_caught():
    f = L.lint_source(M.ENV01_MUTANT, kinds=("env",))
    assert any(x.code == "ENV01" and "DPT_MUTANT_UNDOCUMENTED_KNOB"
               in x.message for x in f)
    # documenting the knob in the glossary clears it
    assert not L.lint_source(M.ENV01_MUTANT, kinds=("env",),
                             knob_glossary_doc=M.ENV01_GLOSSARY)


def test_wildcard_knob_glossary_entries():
    doc = "Knobs:\n\n    DPT_TTL_*  per-class TTL overrides.\n"
    src = 'import os\nv = os.environ.get("DPT_TTL_GOLD_S")\n'
    assert not L.lint_source(src, kinds=("env",), knob_glossary_doc=doc)
    other = 'import os\nv = os.environ.get("DPT_OTHER")\n'
    assert any(x.code == "ENV01" for x in
               L.lint_source(other, kinds=("env",),
                             knob_glossary_doc=doc))


# --- carry contracts ----------------------------------------------------------

def test_carry_contracts_hold_for_both_fields():
    assert B.check_contracts() == []


def test_carry_contract_catches_bad_field_layout():
    # a modulus too large for its limb count breaks the 2p <= R claim
    class BadSpec:
        name = "Bad"
        mod = (1 << 255) + 1   # 2p > 2^256 = R at 16 limbs
        n_limbs = 16

    v = B.check_contracts(specs=(BadSpec,))
    assert v and any("cond_sub_fits" in x.kernel for x in v)


# --- the production surface is clean ------------------------------------------

def test_repo_lints_clean():
    assert [str(f) for f in L.run_lints()] == []


@pytest.mark.parametrize("subset", [
    ("field/fr_mont_mul", "field/carry_sweep", "field/fr_add"),
    ("ntt/n32_radix4_inv0_coset1_mont", "ntt/n32_radix2"),
    ("msm/digits_signed_c7_L66", "msm/bucket_scan_signed_onehot_packed"),
    ("msm/bucket_pallas_signed_c7_packed",),
    ("ntt/n32_pallas", "field/fr_mont_mul_pallas_lazy"),
    ("curve/proj_add",),
])
def test_registry_subset_clean(subset):
    # the FULL registry is ci.sh analyze's job (~80 s); tier-1 proves a
    # representative slice of every kernel family stays clean
    seen = []
    violations, checked = R.run_bounds(
        strict=True, names=list(subset),
        progress=lambda name, v: seen.append(name))
    assert checked >= len(subset), (subset, seen)
    assert [str(v) for v in violations] == []


# --- CLI exit codes -----------------------------------------------------------

def test_cli_exit_zero_on_clean_lint_pass():
    assert cli_main(["--only", "lint", "-q"]) == 0


def test_cli_exit_nonzero_on_mutant_registry(monkeypatch):
    mutant = R.Entry("mutant/overflow", lambda a: a * a,
                     (B.Bound((4,), jnp.uint32, 0, 1 << 20),))
    monkeypatch.setattr(R, "build_registry", lambda: [mutant])
    assert cli_main(["--only", "bounds", "--strict", "-q"]) == 1
