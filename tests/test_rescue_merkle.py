"""Rescue hash, Merkle tree, and workload circuit tests.

The application layer the reference pulls from jf-primitives
(/root/reference/src/dispatcher.rs:25-26,1076-1108): hash + tree natively,
the membership gadget in-circuit, and the end-to-end analog of `test_plonk`
(/root/reference/src/dispatcher.rs:1118-1134) on the Merkle workload.
"""

import random

from distributed_plonk_tpu import merkle, rescue
from distributed_plonk_tpu.circuit import PlonkCircuit
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.workload import generate_circuit


def test_permutation_invertible_shape():
    rng = random.Random(0)
    st = [rng.randrange(R_MOD) for _ in range(rescue.STATE_WIDTH)]
    out = rescue.permutation(st)
    assert len(out) == rescue.STATE_WIDTH
    assert out != st
    # deterministic
    assert rescue.permutation(st) == out


def test_sbox_roundtrip():
    rng = random.Random(1)
    for _ in range(8):
        x = rng.randrange(R_MOD)
        y = pow(x, rescue.ALPHA_INV, R_MOD)
        assert pow(y, rescue.ALPHA, R_MOD) == x


def test_mds_is_invertible():
    # row-reduce MDS mod r; full rank required (necessary MDS condition)
    m = [row[:] for row in rescue.MDS]
    n = rescue.STATE_WIDTH
    rank = 0
    for col in range(n):
        piv = next((r for r in range(rank, n) if m[r][col] % R_MOD), None)
        if piv is None:
            continue
        m[rank], m[piv] = m[piv], m[rank]
        inv = pow(m[rank][col], -1, R_MOD)
        m[rank] = [v * inv % R_MOD for v in m[rank]]
        for r in range(n):
            if r != rank and m[r][col]:
                f = m[r][col]
                m[r] = [(a - f * b) % R_MOD for a, b in zip(m[r], m[rank])]
        rank += 1
    assert rank == n


def test_permutation_gadget_matches_native():
    rng = random.Random(2)
    st = [rng.randrange(R_MOD) for _ in range(4)]
    cs = PlonkCircuit()
    vs = [cs.create_variable(x) for x in st]
    outs = rescue.permutation_gadget(cs, vs)
    assert [cs.witness[o] for o in outs] == rescue.permutation(st)
    ok, bad = cs.check_satisfiability()
    assert ok, f"gate {bad} violated"


def test_sponge_variable_length():
    assert rescue.sponge([1, 2, 3]) != rescue.sponge([1, 2, 3, 0])
    assert rescue.sponge([1, 2]) != rescue.sponge([1, 2, 0])
    assert rescue.sponge([5]) == rescue.sponge([5])


def test_merkle_tree_and_proofs():
    rng = random.Random(3)
    payloads = [rng.randrange(R_MOD) for _ in range(20)]
    t = merkle.MerkleTree(payloads, height=3)
    for i in (0, 1, 8, 19):
        p = t.open(i)
        assert p.verify(t.root)
        assert not merkle.MerkleProof(i, (p.payload + 1) % R_MOD, p.path).verify(t.root)
        # wrong position bits
        pos, sibs = p.path[0]
        badpath = [((pos + 1) % 3, sibs)] + p.path[1:]
        assert not merkle.MerkleProof(i, p.payload, badpath).verify(t.root)


def test_merkle_rejects_cross_leaf():
    rng = random.Random(4)
    payloads = [rng.randrange(R_MOD) for _ in range(9)]
    t = merkle.MerkleTree(payloads, height=2)
    p0, p1 = t.open(0), t.open(1)
    # proof for index 0 cannot authenticate payload of index 1
    assert not merkle.MerkleProof(0, p1.payload, p0.path).verify(t.root)


def test_membership_gadget_matches_native():
    rng = random.Random(5)
    payloads = [rng.randrange(R_MOD) for _ in range(9)]
    t = merkle.MerkleTree(payloads, height=2)
    cs = PlonkCircuit()
    proof = t.open(4)
    pv = cs.create_variable(proof.payload)
    root_var = merkle.membership_gadget(cs, 4, pv, proof)
    assert cs.witness[root_var] == t.root
    ok, bad = cs.check_satisfiability()
    assert ok, f"gate {bad} violated"


def test_workload_generator_scale():
    ckt, tree = generate_circuit(rng=random.Random(6), height=3,
                                 num_proofs=2, num_leaves=9)
    assert ckt.num_inputs == 1
    assert ckt.public_input() == [tree.root]
    assert ckt.n >= 1024
    ok, bad = ckt.check_satisfiability()
    assert ok, f"gate {bad} violated"


def test_workload_prove_verify_end_to_end():
    """The test_plonk analog: prove Merkle membership, stock verifier accepts."""
    from distributed_plonk_tpu import kzg
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.verifier import verify
    from distributed_plonk_tpu.backend.python_backend import PythonBackend

    ckt, tree = generate_circuit(rng=random.Random(7), height=2,
                                 num_proofs=1, num_leaves=9)
    srs = kzg.universal_setup(ckt.n + 3, tau=0xFEEDFACE)
    pk, vk = kzg.preprocess(srs, ckt)
    proof = prove(random.Random(8), ckt, pk, PythonBackend())
    assert verify(vk, ckt.public_input(), proof, rng=random.Random(9))
    assert not verify(vk, [(tree.root + 1) % R_MOD], proof, rng=random.Random(10))
