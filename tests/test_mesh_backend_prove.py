"""End-to-end prove on the MESH backend (8-device virtual CPU mesh).

The mesh analog of the reference's `test2` (fully-distributed prove,
/root/reference/src/dispatcher2.rs:1273-1295): every NTT rides the
sharded 4-step kernel (single all_to_all), every commitment the
range-sharded signed Pippenger with on-device plane fold, and the round
math runs SPMD-partitioned on sharded handles — and the proof must be
bit-identical to the host-oracle proof (same rng) and verify, the
reference's distributed == single-node invariant (SURVEY.md §4).
"""

import random

import pytest

from distributed_plonk_tpu.prover import prove
from distributed_plonk_tpu.verifier import verify
from distributed_plonk_tpu.parallel.mesh import make_mesh
from distributed_plonk_tpu.parallel.mesh_backend import MeshBackend

# multi-minute under the current jax: the full mesh prove/preprocess
# compile ~every sharded kernel variant on the 8-device CPU emulation
# (>9 min wall measured), which is exactly pytest.ini's definition of the
# slow tier. Mesh MSM/NTT correctness stays in the smoke tier via
# test_mesh_parallel.py; this end-to-end bit-identity check runs with the
# full suite.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8, platform="cpu")


def test_mesh_prove_verifies_and_matches_oracle(proven, mesh8):
    ckt, pk, vk, proof_host = proven
    be = MeshBackend(mesh8)
    proof_mesh = prove(random.Random(1), ckt, pk, be)
    assert verify(vk, ckt.public_input(), proof_mesh, rng=random.Random(2))

    # same device-residency budget as the single-device backend: pk +
    # circuit tables + public input up, one batched round-4 eval down
    assert be.lifts == 3, be.lifts
    assert be.lowers == 1, be.lowers

    assert proof_mesh.wires_poly_comms == proof_host.wires_poly_comms
    assert proof_mesh.prod_perm_poly_comm == proof_host.prod_perm_poly_comm
    assert proof_mesh.split_quot_poly_comms == proof_host.split_quot_poly_comms
    assert proof_mesh.opening_proof == proof_host.opening_proof
    assert proof_mesh.shifted_opening_proof == proof_host.shifted_opening_proof
    assert proof_mesh.wires_evals == proof_host.wires_evals
    assert proof_mesh.wire_sigma_evals == proof_host.wire_sigma_evals
    assert proof_mesh.perm_next_eval == proof_host.perm_next_eval


def test_mesh_preprocess_matches_oracle(proven, mesh8):
    """Device preprocess through the mesh backend: selector/sigma
    commitments (the vk) must equal the host preprocess byte-for-byte
    (mirrors PlonkKzgSnark::preprocess, reference dispatcher2.rs:1280)."""
    from distributed_plonk_tpu import kzg

    ckt, pk_host, vk_host, _ = proven
    be = MeshBackend(mesh8)
    srs = kzg.universal_setup(ckt.n + 3, tau=0xDEADBEEF)
    pk, vk = kzg.preprocess(srs, ckt, backend=be)
    assert vk.selector_comms == vk_host.selector_comms
    assert vk.sigma_comms == vk_host.sigma_comms

    # and a prove with the mesh-preprocessed pk (device-registered pk
    # handles) still matches the oracle proof
    proof = prove(random.Random(1), ckt, pk, be)
    assert proof.opening_proof == (prove(random.Random(1), ckt, pk_host,
                                         be).opening_proof)
