"""End-to-end observability tests (ISSUE 9 acceptance surface).

Three planes, all over real processes:
- a REAL serve.py subprocess with --obs-port: Prometheus /metrics (round
  histograms + MFU gauges), /healthz, and /trace/<job_id> — the merged
  chrome trace carries spans from >= 2 processes (client + service)
  under ONE trace id with monotonic timestamps;
- a 3-process worker fleet (the chaos-harness topology): a distributed
  prove under a dispatcher tracer yields one trace:<job_id> store
  artifact whose chrome export holds dispatcher AND worker spans under a
  single trace id, offset-corrected;
- wire-level back-compat: frames WITHOUT the TRACED flag parse exactly
  as before (an old client keeps working against a new worker).
"""

import json
import os
import random
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from distributed_plonk_tpu.runtime import protocol
from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                      RemoteBackend,
                                                      WorkerHandle)
from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
from distributed_plonk_tpu.trace import (Tracer, merge_traces,
                                         to_chrome_trace)

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
RNG = random.Random(0x0B5)


def _assert_chrome_schema(ct):
    """Schema-validate a chrome trace-event export (the satellite's
    explicit check): metadata rows name processes, every span row is a
    complete event with the required keys and sane values."""
    assert set(ct) >= {"traceEvents", "displayTimeUnit", "otherData"}
    meta = [e for e in ct["traceEvents"] if e.get("ph") == "M"]
    xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
    assert meta and xs
    for e in meta:
        assert e["name"] == "process_name" and "name" in e["args"]
    for e in xs:
        for key in ("name", "cat", "ts", "dur", "pid", "tid", "args"):
            assert key in e, (key, e)
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert isinstance(e["pid"], int)
    json.dumps(ct)  # must be pure JSON
    return xs


def _spawn_workers(tmp_path, n, port_base, trace_cap=None):
    base = port_base + (os.getpid() % 400) * (n + 1)
    cfg = NetworkConfig([f"127.0.0.1:{base + i}" for i in range(n)])
    cfg_path = str(tmp_path / "network.json")
    cfg.save(cfg_path)
    env = dict(os.environ)
    if trace_cap is not None:
        env["DPT_WORKER_TRACE_CAP"] = str(trace_cap)
    procs = [subprocess.Popen(
        [sys.executable, "-m", "distributed_plonk_tpu.runtime.worker",
         str(i), cfg_path, "--backend", "python"], cwd=REPO, env=env)
        for i in range(n)]
    deadline = time.time() + 30
    pending = set(range(n))
    while pending and time.time() < deadline:
        for i in sorted(pending):
            h, p = cfg.workers[i]
            if WorkerHandle(h, p).probe(timeout_ms=2000) is not None:
                pending.discard(i)
        if pending:
            time.sleep(0.2)
    assert not pending, f"workers {sorted(pending)} did not come up"
    return cfg, procs


def _kill_all(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        p.wait(timeout=10)


# --- fleet plane: the acceptance criterion -----------------------------------

def test_fleet_prove_produces_merged_trace_artifact(tmp_path, proven):
    """3-process chaos-harness topology: a fully distributed prove under
    a dispatcher tracer -> ONE trace:<job_id> store artifact whose
    chrome export contains dispatcher AND worker spans under a single
    trace id, with monotonic offset-corrected timestamps."""
    from distributed_plonk_tpu.prover import prove
    from distributed_plonk_tpu.store import ArtifactStore
    from distributed_plonk_tpu.store import keycache as KC

    ckt, pk, vk, proof_host = proven
    cfg, procs = _spawn_workers(tmp_path, 3, 30500)
    d = None
    try:
        tracer = Tracer(proc="dispatcher")
        d = Dispatcher(cfg, tracer=tracer)
        proof = prove(random.Random(1), ckt, pk,
                      RemoteBackend(d, dist_fft_min=ckt.n))
        assert proof.opening_proof == proof_host.opening_proof

        merged = d.collect_trace()
        assert merged["trace_id"] == tracer.trace_id
        procs_by_name = {p["proc"]: p for p in merged["processes"]}
        assert "dispatcher" in procs_by_name
        worker_procs = [p for p in merged["processes"]
                        if p["proc"].startswith("worker/")]
        assert len(worker_procs) >= 2, merged["processes"]
        assert len({p["pid"] for p in merged["processes"]}) >= 3

        spans = [e["span"] for e in merged["events"]]
        assert any(s.startswith("fleet/") for s in spans)        # dispatcher
        assert any(s.startswith("serve/") for s in spans)        # workers
        # fan-out rpc spans run on executor threads (path has no fleet/
        # prefix — the stack is thread-local) but still chain to their
        # fleet span via the explicit parent — the TREE survives the hop
        by_sid = {e["sid"]: e for e in merged["events"]}
        rpcs = [e for e in merged["events"]
                if e["span"] in ("rpc/msm", "rpc/fft_init", "rpc/fft1",
                                 "rpc/fft2_prepare", "rpc/fft2")]
        assert rpcs
        for e in rpcs:
            parent = by_sid.get(e.get("parent"))
            assert parent is not None and \
                parent["span"].startswith("fleet/"), e
        assert any(s.endswith("/msm") and "flops" in e
                   for s, e in zip(spans, merged["events"]))
        # peer exchange legs landed in the SAME trace (worker->worker
        # context propagation through FFT2_PREPARE)
        assert any(s == "serve/fft_exchange" for s in spans), \
            sorted(set(spans))

        # monotonic, offset-corrected: merged order is by corrected ts,
        # and every worker span lies inside the dispatcher's prove window
        ts = [e["ts"] for e in merged["events"]]
        assert ts == sorted(ts)
        disp = [e for e in merged["events"] if e["proc"] == "dispatcher"]
        lo = min(e["ts"] for e in disp) - 5.0
        hi = max(e["ts"] + e["dur_s"] for e in disp) + 5.0
        assert all(lo <= e["ts"] <= hi for e in merged["events"])

        # one content-addressed artifact per job, like proofs
        store = ArtifactStore(str(tmp_path / "store"))
        digest = KC.store_trace(store, "job-fleet-1", merged)
        assert digest
        reloaded = KC.load_trace(store, "job-fleet-1")
        assert reloaded["trace_id"] == tracer.trace_id
        xs = _assert_chrome_schema(to_chrome_trace(reloaded))
        assert len({e["pid"] for e in xs}) >= 3

        # TRACE_DUMP is fetch-and-forget: a second collect holds only
        # the dispatcher's own spans
        again = d.collect_trace()
        assert [p["proc"] for p in again["processes"]] == ["dispatcher"]
    finally:
        if d is not None:
            for w in d.workers:
                w.close()
            d.pool.shutdown(wait=False)
        _kill_all(procs)


# --- wire plane: back-compat -------------------------------------------------

def test_wire_backcompat_and_trace_dump(tmp_path):
    ctx = {"trace_id": "ab" * 16, "parent_id": "cd" * 8}
    tag, payload = protocol.wrap_traced(protocol.NTT, b"body", ctx)
    assert tag == protocol.NTT | protocol.TRACED
    assert protocol.strip_context(tag, payload) == (protocol.NTT, ctx,
                                                   b"body")
    # a no-context frame passes through strip_context untouched
    assert protocol.strip_context(protocol.NTT, b"body") == \
        (protocol.NTT, None, b"body")
    assert protocol.wrap_traced(protocol.NTT, b"body", None) == \
        (protocol.NTT, b"body")
    assert protocol.tag_name(protocol.MSM | protocol.TRACED) == "MSM"

    from distributed_plonk_tpu import poly as P
    from distributed_plonk_tpu.constants import R_MOD
    cfg, procs = _spawn_workers(tmp_path, 1, 31200)
    try:
        n = 16
        values = [RNG.randrange(R_MOD) for _ in range(n)]
        want = P.fft(P.Domain(n), values)

        # old client: tracer-less dispatcher sends flag-less frames
        plain = Dispatcher(cfg)
        assert plain.ntt(values) == want
        snap = plain.workers[0].probe()
        assert snap["traces"] == 0        # nothing buffered for it
        for w in plain.workers:
            w.close()
        plain.pool.shutdown(wait=False)

        # new client: same worker, traced frames, dump comes back
        d = Dispatcher(cfg, tracer=Tracer(proc="d2"))
        assert d.ntt(values) == want
        assert d.workers[0].probe()["traces"] == 1
        merged = d.collect_trace()
        assert {e["proc"] for e in merged["events"]} == {"d2", "worker/0"}
        # unknown trace id answers {} (worker restarted / LRU-dropped)
        raw = d.workers[0].call(
            protocol.TRACE_DUMP,
            protocol.encode_json({"trace_id": "ff" * 16}), traced=False)
        assert protocol.decode_json(raw) == {}
        for w in d.workers:
            w.close()
        d.pool.shutdown(wait=False)
    finally:
        _kill_all(procs)


# --- durability: the trace identity is part of the journal contract ----------

def test_trace_id_survives_service_restart(tmp_path):
    """The SUBMIT reply told the client a trace id; a crash + recovery
    must keep answering to it (the journal SUBMIT record carries it), or
    the client's spans orphan from the recovered job's timeline."""
    from distributed_plonk_tpu.service import ProofService

    ctx = {"trace_id": "5a" * 16, "parent_id": "6b" * 8}
    spec = {"kind": "toy", "gates": 16, "seed": 21, "job_key": "tr-k",
            "trace_ctx": ctx}
    svc = ProofService(port=0, prover_workers=1,
                       journal_dir=str(tmp_path / "j"),
                       store_dir=str(tmp_path / "s"))
    # crash BEFORE starting the scheduler: the job is journaled but
    # never proved — recovery must resume it under the adopted identity
    job, _ = svc.submit_ex(spec)
    assert job.trace_id == ctx["trace_id"]
    svc.crash()

    svc2 = ProofService(port=0, prover_workers=1,
                        journal_dir=str(tmp_path / "j"),
                        store_dir=str(tmp_path / "s")).start()
    try:
        job2, deduped = svc2.submit_ex(spec)
        assert deduped and job2.id == job.id
        assert job2.trace_id == ctx["trace_id"]
        assert job2.trace_parent == ctx["parent_id"]
        assert job2.done_event.wait(timeout=120) and job2.state == "done"
        # the stored artifact answers to the same id
        from distributed_plonk_tpu.store import keycache as KC
        merged = KC.load_trace(svc2.store, job2.id)
        assert merged["trace_id"] == ctx["trace_id"]
        # ...and the prover spans chain up to the client's parent span
        roots = [e for e in merged["events"]
                 if e.get("parent") == ctx["parent_id"]]
        assert roots, merged["events"][:3]
    finally:
        svc2.shutdown()


# --- service plane: serve.py subprocess + obs HTTP ---------------------------

@pytest.fixture()
def serve_proc(tmp_path):
    """A REAL serve.py subprocess with --obs-port; yields (addr, obs,
    proc)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DPT_FAULTS", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "serve.py"),
         "--port", "0", "--obs-port", "0", "--workers", "1",
         "--store-dir", str(tmp_path / "store"),
         "--allow-remote-shutdown"],
        stdout=subprocess.PIPE, env=env, text=True, cwd=REPO)
    banner = json.loads(proc.stdout.readline())
    try:
        yield banner["listening"], banner["obs"], proc
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def test_serve_subprocess_obs_endpoints_and_merged_trace(serve_proc):
    from distributed_plonk_tpu.service import ServiceClient

    addr, obs, proc = serve_proc
    host, port = addr.rsplit(":", 1)
    base = f"http://{obs}"

    client_tr = Tracer(proc="test-client")
    with ServiceClient(host, int(port)) as c:
        with client_tr.span("client/prove_request") as root:
            r = c.submit({"kind": "toy", "gates": 16, "seed": 11},
                         trace_ctx={"trace_id": client_tr.trace_id,
                                    "parent_id": root})
            assert r["trace_id"] == client_tr.trace_id  # adopted, not stamped
            st = c.wait(r["job_id"], timeout_s=180)
        assert st["state"] == "done"
        assert st["trace_spans"] >= 6
        job_id = r["job_id"]

        # /healthz: the readiness-probe shape
        h = json.loads(_get(base + "/healthz"))
        assert h["ok"] is True and h["queue_depth"] == 0

        # /metrics: Prometheus text exposition with round latency
        # histograms AND MFU gauges (the acceptance criterion's curl)
        text = _get(base + "/metrics").decode()
        assert "# TYPE dpt_jobs_completed_total counter" in text
        assert "dpt_jobs_completed_total 1" in text
        assert 'dpt_prove_round_round1_seconds{quantile="0.5"}' in text
        assert "dpt_mfu_commit_wires_pct" in text
        assert "dpt_kernel_commit_wires_gflops" in text
        assert "dpt_queue_depth 0" in text

        # /trace/<job_id>: chrome trace of the server-side timeline
        ct = json.loads(_get(base + f"/trace/{job_id}"))
        xs = _assert_chrome_schema(ct)
        assert ct["otherData"]["trace_id"] == client_tr.trace_id
        names = [e["name"] for e in xs]
        assert "service/queued" in names and "round1" in names

        # the raw merged dump + the client's own spans = one timeline
        # from >= 2 PROCESSES under one trace id (context propagation
        # across the wire is what makes them correlate)
        raw = json.loads(_get(base + f"/trace/{job_id}?raw=1"))
        combined = merge_traces([client_tr.dump(), raw])
        assert combined["trace_id"] == client_tr.trace_id
        pids = {e["pid"] for e in combined["events"]}
        assert len(pids) >= 2, combined["processes"]
        ts = [e["ts"] for e in combined["events"]]
        assert ts == sorted(ts)
        # parent linkage survives the hop: the prover-side spans chain up
        # to the client's root span id
        roots = [e for e in combined["events"]
                 if e.get("parent") == client_tr.events[0]["sid"]]
        assert roots, "no server span parented to the client's root"

        # unknown paths/jobs answer 404, never crash the service
        for bad in ("/trace/nope", "/bogus"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base + bad)
            assert ei.value.code == 404
        c.shutdown_server()
    proc.wait(timeout=30)
