"""Checkpoint/resume tests (host oracle backend).

The contract claimed by checkpoint.py's docstring, now actually enforced:
a prove interrupted after ANY saved round (1-4) and resumed from the
snapshot produces a proof BYTE-IDENTICAL (proof_io fixed layout) to an
uninterrupted run, and a completed prove leaves no snapshot behind.

Also the tier-1 prove() smoke test that would have caught the round-5
`_enc_point` NameError: a plain checkpoint-free prove on the host backend.
"""

import random

import pytest

from distributed_plonk_tpu.backend.python_backend import PythonBackend
from distributed_plonk_tpu.checkpoint import ProverCheckpoint
from distributed_plonk_tpu.proof_io import serialize_proof
from distributed_plonk_tpu.prover import prove
from distributed_plonk_tpu.verifier import verify

SEED = 7


class _Interrupted(Exception):
    pass


class _KillAfterRound(ProverCheckpoint):
    """Persist the snapshot like the real thing, then die — simulating a
    worker crash at the round-N boundary (the snapshot is already durable,
    the process is not)."""

    def __init__(self, path, kill_round):
        super().__init__(path)
        self.kill_round = kill_round

    def save(self, round_no, *args, **kwargs):
        super().save(round_no, *args, **kwargs)
        if round_no == self.kill_round:
            raise _Interrupted(f"killed after round {round_no}")


@pytest.fixture(scope="module")
def baseline(proven):
    """Uninterrupted, checkpoint-free proof bytes at a fixed blind seed."""
    ckt, pk, vk, _ = proven
    proof = prove(random.Random(SEED), ckt, pk, PythonBackend())
    return ckt, pk, vk, serialize_proof(proof)


def test_prove_smoke(proven):
    # checkpoint-free prove must not touch (or crash in) any checkpoint code
    ckt, pk, vk, _ = proven
    proof = prove(random.Random(3), ckt, pk, PythonBackend())
    assert verify(vk, ckt.public_input(), proof, rng=random.Random(4))


@pytest.mark.parametrize("kill_round", [1, 2, 3, 4])
def test_resume_is_byte_identical(tmp_path, baseline, kill_round):
    ckt, pk, vk, want = baseline
    path = str(tmp_path / f"kill{kill_round}.ckpt.npz")
    backend = PythonBackend()

    with pytest.raises(_Interrupted):
        prove(random.Random(SEED), ckt, pk, backend,
              checkpoint=_KillAfterRound(path, kill_round))
    assert (tmp_path / f"kill{kill_round}.ckpt.npz").exists()

    # fresh process analog: new RNG object, new backend, plain checkpoint
    proof = prove(random.Random(SEED), ckt, pk, PythonBackend(),
                  checkpoint=ProverCheckpoint(path))
    assert serialize_proof(proof) == want
    # clear-on-success: nothing left to resume from
    assert not (tmp_path / f"kill{kill_round}.ckpt.npz").exists()


def test_uninterrupted_checkpointed_prove_matches_and_clears(tmp_path, baseline):
    ckt, pk, vk, want = baseline
    path = str(tmp_path / "clean.ckpt.npz")
    proof = prove(random.Random(SEED), ckt, pk, PythonBackend(),
                  checkpoint=ProverCheckpoint(path))
    assert serialize_proof(proof) == want
    assert not (tmp_path / "clean.ckpt.npz").exists()


def test_fingerprint_mismatch_rejected(tmp_path, baseline):
    from tests.conftest import build_test_circuit
    from distributed_plonk_tpu import kzg

    ckt, pk, vk, _ = baseline
    path = str(tmp_path / "fp.ckpt.npz")
    with pytest.raises(_Interrupted):
        prove(random.Random(SEED), ckt, pk, PythonBackend(),
              checkpoint=_KillAfterRound(path, 1))

    # resuming against different keys must raise, not emit a bad proof
    other = build_test_circuit()
    other.finalize()
    srs = kzg.universal_setup(other.n + 3, tau=0xFEEDFACE)
    pk2, _ = kzg.preprocess(srs, other)
    with pytest.raises(ValueError, match="different circuit"):
        prove(random.Random(SEED), other, pk2, PythonBackend(),
              checkpoint=ProverCheckpoint(path))
