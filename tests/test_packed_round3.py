"""Packed round 3: limb-packed coset planes + sliced quotient evaluation.

The single-device memory strategy for the reference's quotient pipeline
(/root/reference/src/dispatcher2.rs:382-507): coset evals live packed
(two 16-bit limbs per u32) and the quotient evaluation runs in lane
slices. These tests pin the invariant that the packed+sliced path is
VALUE-IDENTICAL to the one-shot unpacked path (which the host oracle and
mesh backend keep using).
"""

import random

import numpy as np
import jax.numpy as jnp

from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.poly import Domain
from distributed_plonk_tpu.backend import field_jax as FJ
from distributed_plonk_tpu.backend import prover_jax as PJ
from distributed_plonk_tpu.backend.jax_backend import JaxBackend

RNG = random.Random(0x9A4D)


def _rand_h(length):
    return jnp.asarray(PJ.lift([RNG.randrange(R_MOD) for _ in range(length)]))


def test_pack_unpack_roundtrip():
    v = _rand_h(320)
    p = PJ.pack_jit(v)
    assert p.shape == (8, 320)
    assert np.array_equal(np.asarray(FJ.unpack_limb_pairs(p)), np.asarray(v))


def test_quotient_streamed_matches_unpacked_multislice():
    """The streaming round 3 (accumulating gate/acc2 plane by plane,
    sliced final combine) must be VALUE-IDENTICAL to the one-shot
    unpacked path from the same coefficient handles."""
    n, m = 64, 512
    qd = Domain(m)
    be = JaxBackend()
    be._QUOT_SLICE = 128  # force 4 combine slices through one program

    sel = [_rand_h(n) for _ in range(13)]
    sig = [_rand_h(n) for _ in range(5)]
    wir = [_rand_h(n + 2) for _ in range(5)]  # blinded wire lengths
    zpoly = _rand_h(n + 3)
    pi = _rand_h(n)
    k = [RNG.randrange(R_MOD) for _ in range(5)]
    beta, gamma, alpha, asdn = (RNG.randrange(R_MOD) for _ in range(4))

    batch = be.coset_fft_many(qd, sel + sig + wir + [zpoly, pi])
    ref = be.quotient(n, m, qd, k, beta, gamma, alpha, asdn,
                      batch[:13], batch[13:18], batch[18:23],
                      batch[23], batch[24])
    got = be.quotient_streamed(n, m, qd, k, beta, gamma, alpha, asdn,
                               sel, sig, wir, zpoly, pi)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


def test_coset_fft_many_packed_matches():
    m = 256
    qd = Domain(m)
    be = JaxBackend()
    hs = [_rand_h(m), _rand_h(m // 2), _rand_h(m)]  # short handle pads
    plain = be.coset_fft_many(qd, hs)
    packed = be.coset_fft_many_packed(qd, hs)
    for a, b in zip(plain, packed):
        assert b.shape == (8, m)
        assert np.array_equal(np.asarray(a),
                              np.asarray(FJ.unpack_limb_pairs(b)))
