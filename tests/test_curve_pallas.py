"""Fused Pallas complete projective add vs the XLA path and the host
curve oracle (interpret mode on CPU; the same kernels run compiled on
TPU behind curve_jax.proj_add/_mixed's wide-shape gate)."""

import random

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu.constants import FQ_LIMBS, FQ_MONT_R, Q_MOD, R_MOD
from distributed_plonk_tpu.backend import curve_jax as CJ
from distributed_plonk_tpu.backend import curve_pallas as CP
from distributed_plonk_tpu.backend.limbs import ints_to_limbs, limbs_to_ints

RNG = random.Random(0xADD)
_R_INV = pow(FQ_MONT_R, Q_MOD - 2, Q_MOD)


def _proj_device(points):
    """list of (affine point | None) -> homogeneous projective Montgomery
    coords (identity = (0 : 1 : 0))."""
    xs = [p[0] * FQ_MONT_R % Q_MOD if p else 0 for p in points]
    ys = [p[1] * FQ_MONT_R % Q_MOD if p else FQ_MONT_R for p in points]
    zs = [FQ_MONT_R if p else 0 for p in points]
    return tuple(jnp.asarray(ints_to_limbs(v, FQ_LIMBS)) for v in (xs, ys, zs))


def _proj_to_affine(coords):
    """(X, Y, Z) limb arrays -> list of (affine point | None)."""
    X, Y, Z = (limbs_to_ints(np.asarray(c)) for c in coords)
    out = []
    for x, y, z in zip(X, Y, Z):
        x, y, z = (v * _R_INV % Q_MOD for v in (x, y, z))
        if z == 0:
            out.append(None)
            continue
        zi = pow(z, Q_MOD - 2, Q_MOD)
        out.append((x * zi % Q_MOD, y * zi % Q_MOD))
    return out


def _rand_pts(n):
    return [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD)) for _ in range(n)]


def _edge_pairs():
    """P==Q, P==-Q, P=identity, Q=identity, both identity — the cases the
    complete formula must flow through with no masking."""
    p = C.g1_mul(C.G1_GEN, 7)
    q = C.g1_mul(C.G1_GEN, 11)
    pneg = (p[0], Q_MOD - p[1])
    return [(p, p), (p, pneg), (None, q), (p, None), (None, None)]


@pytest.mark.slow
def test_proj_add_matches_oracle_and_xla():
    pairs = _edge_pairs() + list(zip(_rand_pts(11), _rand_pts(11)))
    ps = _proj_device([a for a, _ in pairs])
    qs = _proj_device([b for _, b in pairs])
    got = CP.proj_add(ps, qs)
    # bit-identical to the XLA staged-lane path, not merely equal mod p
    ref = CJ.proj_add(ps, qs)
    for g, r in zip(got, ref):
        assert np.array_equal(np.asarray(g), np.asarray(r))
    exp = [C.g1_add_affine(a, b) for a, b in pairs]
    assert _proj_to_affine(got) == exp


@pytest.mark.slow
def test_proj_add_mixed_matches_oracle_and_xla():
    # accumulator with arbitrary Z (built by a prior add), affine addend
    base = _rand_pts(13)
    addend = _rand_pts(13)
    acc = CJ.proj_add(_proj_device(base), _proj_device(base))  # 2*base, Z != R
    pairs = list(zip([C.g1_add_affine(b, b) for b in base], addend))
    # edge rows: acc identity; P == Q; P == -Q
    acc = tuple(jnp.concatenate([a, b], axis=1) for a, b in zip(
        acc, _proj_device([None, addend[0], C.g1_neg(addend[1])])))
    pairs += [(None, addend[0]), (addend[0], addend[0]),
              (C.g1_neg(addend[1]), addend[1])]
    q = _proj_device([b for _, b in pairs])
    got = CP.proj_add_mixed(acc, (q[0], q[1]))
    exp = [C.g1_add_affine(a, b) for a, b in pairs]
    assert _proj_to_affine(got) == exp


def test_dispatch_gate_respects_mask_and_bitmatch():
    """curve_jax.proj_add_mixed with the fused path forced must equal the
    XLA path limb-for-limb, including the q_inf select."""
    n = 9
    pts = _rand_pts(n)
    acc = _proj_device(pts)
    q = _proj_device(_rand_pts(n))
    q_inf = jnp.asarray([i % 3 == 0 for i in range(n)])
    ref = CJ.proj_add_mixed(acc, (q[0], q[1]), q_inf)
    res = CP.proj_add_mixed(acc, (q[0], q[1]))
    got = CJ.pt_select(q_inf, acc, res)
    for g, r in zip(got, ref):
        assert np.array_equal(np.asarray(g), np.asarray(r))
