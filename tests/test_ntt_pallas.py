"""Fused multi-stage Pallas NTT (ntt_pallas) vs the XLA stage cores.

The VMEM-resident kernel must be BIT-IDENTICAL to the radix-4 XLA core
and the host oracle for every (inverse, coset, boundary) mode, edge
widths down to n=1 (where the dispatch falls back exactly like
radix-4's n<=2 fallback), batch kernels, forced multi-group schedules,
and the shared run_stages core the mesh/fleet paths consume; and the
round-3 pointwise fusion (gate/sigma epilogues + combine prologue,
DPT_R3_FUSE) must be value-identical to the unfused product path.
Interpret mode on CPU; the same kernels compile with Mosaic on TPU.

Interpret-mode emulation costs ~15-25 s of compile per distinct kernel
program, so the tier-1 set keeps programs tiny and few; the full
8-mode x odd/even sweep and the mesh-parity run ride the slow tier
(proof-byte identity rides test_jax_backend_prove, also slow).
"""

import random

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.backend import field_jax as FJ
from distributed_plonk_tpu.backend import ntt_jax as NTT
from distributed_plonk_tpu.backend import ntt_pallas as NP
from distributed_plonk_tpu.backend.limbs import ints_to_limbs

RNG = random.Random(0xF057)


def _vals(n):
    return [RNG.randrange(R_MOD) for _ in range(n)]


def _mont_rows(n, b=None):
    """CANONICAL Montgomery-form field elements (bit-identity across
    different stage decompositions only holds for reduced inputs — the
    kernels' documented boundary contract)."""
    from distributed_plonk_tpu.constants import FR_MONT_R

    def one(_):
        return ints_to_limbs([RNG.randrange(R_MOD) * FR_MONT_R % R_MOD
                              for _ in range(n)], 16)

    if b is None:
        return jnp.asarray(one(0))
    return jnp.asarray(np.stack([one(i) for i in range(b)], axis=1))


def _oracle(n, vals, inverse, coset):
    d = P.Domain(n)
    fn = {(False, False): P.fft, (False, True): P.coset_fft,
          (True, False): P.ifft, (True, True): P.coset_ifft}[(inverse, coset)]
    return fn(d, vals)


def test_pallas_matches_xla_and_oracle_n64(monkeypatch):
    """n=64 (even log2, single fused group at the default rows cap):
    the pallas kernel is limb-identical to the radix-4 XLA kernel at
    the Montgomery boundary in the plain and fused-coset-pre-scale
    modes, and matches the host oracle through the plain boundary in
    the fused-inverse-post-scale mode. (Each distinct pallas program
    costs ~20 s of interpret-mode compile, and tier-1 has a wall-clock
    budget: the full 8-mode x odd/even matrix rides the slow tier.)"""
    n = 64
    plan = NTT.get_plan(n)
    v = _mont_rows(n)
    got = np.asarray(plan.kernel(False, True, kernel="pallas")(v))
    ref = np.asarray(plan.kernel(False, True, kernel="xla")(v))
    assert np.array_equal(got, ref)
    vals = _vals(n)
    assert (plan.run_ints(vals, inverse=True, coset=True, kernel="pallas")
            == _oracle(n, vals, True, True))


@pytest.mark.slow
def test_pallas_all_modes_odd_even_sweep():
    """The full 8-mode sweep at odd AND even log2(n) — every
    (inverse, coset, boundary) combination bit-identical to the host
    oracle (plain boundary) / radix-4 core (Montgomery boundary)."""
    for n in (32, 64):
        plan = NTT.get_plan(n)
        vals = _vals(n)
        v = _mont_rows(n)
        for inverse in (False, True):
            for coset in (False, True):
                got = plan.run_ints(vals, inverse=inverse, coset=coset,
                                    kernel="pallas")
                assert got == _oracle(n, vals, inverse, coset), \
                    (n, inverse, coset, "plain")
                gm = np.asarray(plan.kernel(inverse, coset,
                                            kernel="pallas")(v))
                rm = np.asarray(plan.kernel(inverse, coset,
                                            kernel="xla")(v))
                assert np.array_equal(gm, rm), (n, inverse, coset, "mont")


def test_edge_widths_and_fallback():
    """n=1/2 have no fused schedule: kernel='pallas' falls back to the
    XLA body (like radix-4's n<=2 fallback) and still matches the
    oracle. n=4 is the smallest real fused program (single group,
    rows=4, one-lane tiles)."""
    for n in (1, 2):
        plan = NTT.get_plan(n)
        vals = _vals(n)
        assert plan._effective_kernel("pallas") == "xla"
        assert plan.run_ints(vals, kernel="pallas") == _oracle(
            n, vals, False, False)
    plan = NTT.get_plan(4)
    vals = _vals(4)
    assert plan.run_ints(vals, coset=True, kernel="pallas") == _oracle(
        4, vals, False, True)


@pytest.mark.slow
def test_edge_width_sweep():
    """n=8..128: one fused mode per width (they alternate so both the
    forward-coset pre-scale and the inverse post-scale paths see every
    schedule shape, including the odd-log2 unbalanced group splits)."""
    for i, n in enumerate((8, 16, 32, 128)):
        plan = NTT.get_plan(n)
        vals = _vals(n)
        inverse = bool(i % 2)
        assert plan.run_ints(vals, inverse=inverse, coset=True,
                             kernel="pallas") == _oracle(
            n, vals, inverse, True), n


@pytest.mark.slow
def test_batch_kernel_matches_single(monkeypatch):
    """(16, B, n) pallas batch kernel == the XLA batch kernel, B=3
    (the prover's round-1/round-3 launch shape, (B, tiles) grid)."""
    n = 32
    plan = NTT.get_plan(n)
    vb = _mont_rows(n, b=3)
    got = np.asarray(plan.kernel_batch(False, True, kernel="pallas")(vb))
    ref = np.asarray(plan.kernel_batch(False, True, kernel="xla")(vb))
    assert np.array_equal(got, ref)


def test_multi_group_and_vmem_knobs(monkeypatch):
    """A narrow group cap forces MULTIPLE sequential fused groups and a
    small VMEM budget forces narrow lane tiles — both must stay
    bit-identical (fresh NttPlan instances so the forced schedules do
    not poison the shared plan cache)."""
    n = 64
    vals = _vals(n)
    monkeypatch.setattr(NP, "_ROWS_CAP", 8)   # groups of R=3,3 at n=64
    monkeypatch.setattr(NP, "_VMEM_MB", 1)
    plan = NTT.NttPlan(n)
    sched = NP.plan_schedule(plan.log_n)
    assert len(sched) == 2 and all(r == 3 for _, r in sched)
    assert plan.run_ints(vals, inverse=True, coset=True,
                         kernel="pallas") == _oracle(n, vals, True, True)


def test_run_stages_shared_core(monkeypatch):
    """The shared stage core dispatches to the fused kernel from the
    SAME consts dict the mesh/fleet paths build (core_consts), and is
    bit-identical to the XLA tables — covering the mesh 4-step and
    fleet panel integration seam without a mesh."""
    n = 16
    plan = NTT.get_plan(n)
    v = _mont_rows(n, b=2)
    monkeypatch.setattr(NTT, "_NTT_KERNEL", "pallas")
    consts_p = {k: jnp.asarray(a)
                for k, a in plan.core_consts(False).items()}
    assert any(k.startswith("pg") for k in consts_p)
    got = np.asarray(NTT.run_stages(v, consts_p))
    monkeypatch.setattr(NTT, "_NTT_KERNEL", "xla")
    consts_x = {k: jnp.asarray(a)
                for k, a in plan.core_consts(False).items()}
    assert not any(k.startswith("pg") for k in consts_x)
    ref = np.asarray(NTT.run_stages(v, consts_x))
    assert np.array_equal(got, ref)


def test_dispatch_knob(monkeypatch):
    """DPT_NTT_KERNEL resolution: auto is xla off-TPU, pallas/xla force,
    bad values raise, pallas_disabled overrides even a forced pallas
    (the GSPMD invariant), and the mesh guard path falls back at trace
    time (same seam msm_jax pins)."""
    monkeypatch.setattr(NTT, "_NTT_KERNEL", "auto")
    assert NTT._active_kernel() == "xla"  # no TPU in this container
    monkeypatch.setattr(NTT, "_NTT_KERNEL", "pallas")
    assert NTT._active_kernel() == "pallas"
    monkeypatch.setattr(NTT, "_NTT_KERNEL", "xla")
    assert NTT._active_kernel() == "xla"
    assert NTT._active_kernel("pallas") == "pallas"
    with pytest.raises(ValueError):
        NTT._active_kernel("mosaic")
    monkeypatch.setattr(NTT, "_NTT_KERNEL", "turbo")
    with pytest.raises(ValueError):
        NTT._active_kernel()
    monkeypatch.setattr(NTT, "_NTT_KERNEL", "pallas")
    with FJ.pallas_disabled():
        assert NTT._active_kernel() == "xla"
        assert NTT._active_kernel("pallas") == "xla"


def test_schedule_consistency():
    """plan_schedule covers every stage exactly once for all widths and
    caps, and schedule_from_consts round-trips it (the trace-time
    re-derivation used inside run_groups)."""
    import itertools
    for log_n, cap in itertools.product(range(2, 21), (4, 8, 16, 64)):
        saved = NP._ROWS_CAP
        NP._ROWS_CAP = cap
        try:
            sched = NP.plan_schedule(log_n)
        finally:
            NP._ROWS_CAP = saved
        assert sum(r for _, r in sched) == log_n
        assert [s0 for s0, _ in sched] == [
            sum(r for _, r in sched[:i]) for i in range(len(sched))]
        assert all(1 <= r <= max(2, cap.bit_length() - 1) for _, r in sched)
        # group 0 always has a stage-1 table, later groups a stage-0 one
        # (schedule_from_consts depends on at least one table per group)
        assert sched[0][1] >= 2 or len(sched) == 1


@pytest.mark.slow
def test_aot_compile_pallas_mode(monkeypatch):
    """NttPlan.aot_compile under the pallas kernel lowers the fused
    programs (mode-aware, like MsmContext.aot_compile) — this is the
    warm_stages / warmup.py --aot path. Montgomery boundary only keeps
    the interpret-mode compile budget small; the kernel stays correct
    after the AOT pass."""
    n = 16
    monkeypatch.setattr(NTT, "_NTT_KERNEL", "pallas")
    plan = NTT.NttPlan(n)
    rep = plan.aot_compile(boundaries=("mont",))
    assert rep["kernel"] == "pallas"
    assert rep["compiled"] == 4 and rep["failed"] == 0
    vals = _vals(n)
    assert plan.run_ints(vals, coset=True) == _oracle(n, vals, False, True)


@pytest.mark.slow
def test_mesh_kernel_parity(monkeypatch):
    """The mesh 4-step NTT under DPT_NTT_KERNEL=pallas: per-shard
    run_stages calls pick the fused kernel inside shard_map (the guard
    is forced open the way test_mesh_parallel does for the MSM) and the
    result matches the host oracle bit for bit."""
    import contextlib
    from distributed_plonk_tpu.parallel import ntt_mesh
    from distributed_plonk_tpu.parallel.mesh import make_mesh

    monkeypatch.setattr(NTT, "_NTT_KERNEL", "pallas")
    monkeypatch.setattr(ntt_mesh, "pallas_guard",
                        lambda mesh: contextlib.nullcontext())
    mesh = make_mesh(2, platform="cpu")
    n = 64
    plan = ntt_mesh.MeshNttPlan(mesh, n)
    vals = _vals(n)
    assert plan.run_ints(vals, inverse=True, coset=True) == _oracle(
        n, vals, True, True)
    # and with the REAL guard (cpu mesh): trace-time fallback to the
    # XLA tables, still correct
    monkeypatch.undo()
    monkeypatch.setattr(NTT, "_NTT_KERNEL", "pallas")
    plan2 = ntt_mesh.MeshNttPlan(mesh, n)
    assert plan2.run_ints(vals, coset=True) == _oracle(n, vals, False, True)


def test_round3_fusion_matches_unfused():
    """DPT_R3_FUSE: the fused round 3 (gate/sigma folds as coset-FFT
    epilogues + the combine as the coset-iNTT prologue, via
    NttPlan.kernel_fused) produces the SAME quotient polynomial as the
    unfused standalone-step path, bit for bit."""
    from distributed_plonk_tpu.poly import Domain
    from distributed_plonk_tpu.backend import prover_jax as PJ
    from distributed_plonk_tpu.backend import jax_backend as JB

    n, m = 64, 256
    qd = Domain(m)

    def rand_h(length):
        return jnp.asarray(PJ.lift([RNG.randrange(R_MOD)
                                    for _ in range(length)]))

    sel = [rand_h(n) for _ in range(13)]
    sig = [rand_h(n) for _ in range(5)]
    wir = [rand_h(n + 2) for _ in range(5)]
    zpoly = rand_h(n + 3)
    pi = rand_h(n)
    k = [RNG.randrange(R_MOD) for _ in range(5)]
    beta, gamma, alpha, asdn = (RNG.randrange(R_MOD) for _ in range(4))
    args = (n, m, qd, k, beta, gamma, alpha, asdn, sel, sig, wir, zpoly, pi)

    saved = JB._R3_FUSE
    saved_br = JB._R3_BITREV
    try:
        JB._R3_FUSE = True
        fused = np.asarray(JB.JaxBackend().quotient_poly_streamed(*args))
        JB._R3_FUSE = False
        unfused = np.asarray(JB.JaxBackend().quotient_poly_streamed(*args))
        # DPT_R3_BITREV: the deferred-bit-reversal pipeline (producer
        # launches emit constant-geometry order, tables re-indexed, one
        # input gather at the consuming iNTT) must be bit-identical to
        # BOTH the per-launch-permuted fused path and the unfused path
        JB._R3_FUSE = True
        JB._R3_BITREV = not saved_br
        flipped = np.asarray(JB.JaxBackend().quotient_poly_streamed(*args))
    finally:
        JB._R3_FUSE = saved
        JB._R3_BITREV = saved_br
    assert np.array_equal(fused, unfused)
    assert np.array_equal(fused, flipped)
