"""Wire-tag conformance + control-plane degradation (TAG01's test half).

Every tag in protocol.TAG_NAMES needs a back-compat story: a peer that
does not implement a tag answers ERR on a connection that keeps serving
(the fleet's rolling-upgrade invariant, test_fleet_obs carries the
worker-plane half). This module is the service-control-plane half —
STATS / STATUS / METRICS / KILL_WORKER / AGG_FETCH raw frames against a
live ProofService, error paths included — and the parity check that the
analyzer's AST replica of the tag table (analysis.lint TAG01) never
drifts from the real protocol.TAG_NAMES.
"""

import json

from distributed_plonk_tpu.runtime import native, protocol
from distributed_plonk_tpu.service import ProofService


def test_tag_table_parity_with_lint_replica():
    # the TAG01 lint reads protocol.py by AST (it must not import the
    # native codec); a new tag that lands in one table but not the other
    # means the lint silently stops covering it
    from distributed_plonk_tpu.analysis import lint
    assert set(lint._protocol_tags()) == set(protocol.TAG_NAMES.values())


def test_control_plane_tags_degrade_to_err_and_keep_serving():
    svc = ProofService(port=0, prover_workers=1).start()
    conn = native.connect("127.0.0.1", svc.port)
    try:
        def ask(tag, payload=b""):
            conn.send(tag, payload)
            rtag, body = conn.recv()
            return rtag, body

        # STATS is a worker-plane tag the service does not implement: it
        # must degrade to ERR "unknown tag", never kill the connection
        rtag, body = ask(protocol.STATS)
        assert rtag == protocol.ERR
        assert protocol.decode_json(body)["reason"] == "unknown tag"

        # STATUS of a job that does not exist: loud, structured ERR
        rtag, body = ask(protocol.STATUS,
                         protocol.encode_json({"job_id": "job-404"}))
        assert rtag == protocol.ERR
        assert "unknown job" in protocol.decode_json(body)["reason"]

        # METRICS answers on the same connection the failures rode
        rtag, body = ask(protocol.METRICS)
        assert rtag == protocol.OK
        snap = json.loads(body.decode())
        assert "queue_depth" in snap["gauges"]

        # KILL_WORKER without --chaos: refused with the arming hint, not
        # silently ignored (fault injection must never be ambient)
        rtag, body = ask(protocol.KILL_WORKER,
                         protocol.encode_json({"worker": 0}))
        assert rtag == protocol.ERR
        assert "fault injection disabled" in \
            protocol.decode_json(body)["reason"]

        # AGG_FETCH of an aggregate that was never built
        rtag, body = ask(protocol.AGG_FETCH,
                         protocol.encode_json({"agg_id": "agg-404"}))
        assert rtag == protocol.ERR
        assert "no aggregate" in protocol.decode_json(body)["reason"]

        # ...and the connection still serves after five ERR/OK rounds
        rtag, _ = ask(protocol.PING)
        assert rtag == protocol.OK
    finally:
        conn.close()
        svc.shutdown()
