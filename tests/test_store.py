"""Artifact store + warm start: the contracts the serving path leans on.

- bucket keys round-trip the store ELEMENT-IDENTICAL (proofs made with a
  disk-loaded proving key are byte-equal to fresh-key proofs, so golden
  fixtures and checkpoint fingerprints survive a server restart);
- the store detects corrupted/truncated artifacts, deletes them, and the
  cache falls through to a fresh build instead of crashing;
- LRU byte-budget eviction removes least-recently-USED entries first;
- a second BucketCache over the same store root (the restarted-server
  case) serves previously seen shapes from disk without ever calling
  build_bucket_keys;
- the in-memory tier is bounded (entry cap + eviction counter).

Pure host (tiny toy domains, no XLA) — runs in the fast host tier.
"""

import random

import pytest

from distributed_plonk_tpu.proof_io import deserialize_proof, serialize_proof
from distributed_plonk_tpu.prover import prove
from distributed_plonk_tpu.service.jobs import (JobSpec, build_bucket_keys,
                                                build_circuit, shape_key)
from distributed_plonk_tpu.service.metrics import Metrics
from distributed_plonk_tpu.service.scheduler import BucketCache
from distributed_plonk_tpu.service import scheduler as scheduler_mod
from distributed_plonk_tpu.store import (ArtifactStore, bucket_store_key,
                                         deserialize_bucket, load_bucket,
                                         serialize_bucket, store_bucket)
from distributed_plonk_tpu.backend.python_backend import PythonBackend
from distributed_plonk_tpu.verifier import verify

TOY = {"kind": "toy", "gates": 8}


def _spec(seed=0, **over):
    d = dict(TOY, seed=seed)
    d.update(over)
    return JobSpec.from_wire(d)


@pytest.fixture(scope="module")
def built():
    """One shared key build for the module (the expensive part)."""
    return build_bucket_keys(_spec())


# --- serialization round trip ------------------------------------------------

def test_bucket_roundtrip_element_identical(built):
    srs, pk, vk = built
    srs2, pk2, vk2 = deserialize_bucket(serialize_bucket(srs, pk, vk))
    assert srs2.powers_of_g1 == srs.powers_of_g1
    assert (srs2.g2, srs2.tau_g2) == (srs.g2, srs.tau_g2)
    assert pk2.ck == pk.ck
    assert pk2.selectors == pk.selectors and pk2.sigmas == pk.sigmas
    assert pk2.domain.size == pk.domain.size
    assert vk2.selector_comms == vk.selector_comms
    assert vk2.sigma_comms == vk.sigma_comms
    assert (vk2.domain_size, vk2.num_inputs, vk2.k) == \
        (vk.domain_size, vk.num_inputs, vk.k)


def test_proof_bytes_identical_with_loaded_keys(built, tmp_path):
    srs, pk, vk = built
    store = ArtifactStore(str(tmp_path))
    key = shape_key(_spec())
    store_bucket(store, key, srs, pk, vk, build_s=0.5)
    _srs2, pk2, vk2, meta = load_bucket(store, key)
    assert meta["build_s"] == 0.5

    spec = _spec(seed=7)
    want = serialize_proof(
        prove(random.Random(7), build_circuit(spec), pk, PythonBackend()))
    ckt = build_circuit(spec)
    got = serialize_proof(
        prove(random.Random(7), ckt, pk2, PythonBackend()))
    assert got == want
    assert verify(vk2, ckt.public_input(), deserialize_proof(got),
                  rng=random.Random(1))


# --- integrity: corruption detect-and-rebuild --------------------------------

def _corrupt_object(store, key, mutate):
    ent = store._manifest["entries"][bucket_store_key(key)]
    path = store._obj_path(ent["digest"])
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(mutate(blob))


@pytest.mark.parametrize("mutate", [
    lambda b: b[: len(b) // 2],                     # truncation
    lambda b: b[:100] + bytes([b[100] ^ 0xFF]) + b[101:],  # bit damage
], ids=["truncated", "flipped"])
def test_corrupt_artifact_rebuilds(built, tmp_path, mutate):
    srs, pk, vk = built
    metrics = Metrics()
    store = ArtifactStore(str(tmp_path), metrics=metrics.scoped("store"))
    key = shape_key(_spec())
    store_bucket(store, key, srs, pk, vk)
    _corrupt_object(store, key, mutate)

    # the store detects, logs, deletes — and reports a miss
    assert load_bucket(store, key) is None
    snap = metrics.snapshot()
    assert snap["counters"]["store_corrupt"] == 1
    assert bucket_store_key(key) not in store.keys()

    # ... so the cache's build tier repopulates instead of crashing
    cache = BucketCache(metrics, store=store)
    res = cache.get(_spec())
    assert res.vk.selector_comms == vk.selector_comms
    snap = metrics.snapshot()
    assert snap["counters"]["bucket_misses"] == 1
    assert load_bucket(store, key) is not None  # healed on disk


def test_undeserializable_blob_is_dropped(tmp_path):
    store = ArtifactStore(str(tmp_path))
    key = shape_key(_spec())
    store.put(bucket_store_key(key), b"not a bucket blob at all")
    assert load_bucket(store, key) is None  # parse fails -> treated as miss
    assert store.keys() == []               # and the stale entry is gone


# --- LRU byte-budget eviction ------------------------------------------------

def test_eviction_least_recently_used_first(tmp_path):
    metrics = Metrics()
    store = ArtifactStore(str(tmp_path), byte_budget=250,
                          metrics=metrics.scoped("store"))
    for name in ("a", "b", "c"):
        store.put(name, bytes(80), meta={"n": name})
    assert store.keys() == ["a", "b", "c"]
    assert store.get("a") is not None   # touch: a is now most recent
    store.put("d", bytes(80))           # 320 > 250: evict LRU until under
    assert store.keys() == ["a", "c", "d"]  # b (oldest-used) went first
    snap = metrics.snapshot()
    assert snap["counters"]["store_evictions"] == 1
    assert snap["gauges"]["store_bytes"] == 240
    store.put("e", bytes(200))          # forces out everything else but e
    assert "e" in store.keys()
    assert store.stats()["bytes"] <= 250


def test_orphaned_blobs_swept_on_open(tmp_path):
    import os
    store = ArtifactStore(str(tmp_path))
    store.put("k", b"payload")
    path = store._obj_path(store._manifest["entries"]["k"]["digest"])
    # simulate a manifest reset / lost writer race: entry gone, blob left
    os.remove(store._manifest_path)
    old = os.path.getmtime(path) - 3600
    os.utime(path, (old, old))  # past the sweep's age floor
    store2 = ArtifactStore(str(tmp_path))
    assert store2.keys() == []
    assert not os.path.exists(path)  # orphan reclaimed, budget stays honest


def test_just_written_entry_survives_tiny_budget(tmp_path):
    store = ArtifactStore(str(tmp_path), byte_budget=10)
    store.put("big", bytes(100))
    assert store.get("big") is not None  # never evict the entry just put


# --- jax compile-cache GC (shared byte budget) -------------------------------

def _fake_jax_cache(root, sizes):
    """Files under <root>/jax_cache/<fp>/ with staged mtimes (oldest
    first), mirroring the per-machine-fingerprint layout."""
    import os
    import time as _time
    d = os.path.join(str(root), "jax_cache", "fp0")
    os.makedirs(d, exist_ok=True)
    now = _time.time()
    paths = []
    for i, size in enumerate(sizes):
        p = os.path.join(d, f"exe{i}.bin")
        with open(p, "wb") as f:
            f.write(bytes(size))
        os.utime(p, (now - 1000 + i, now - 1000 + i))
        paths.append(p)
    return paths


def test_jax_cache_counts_against_budget_oldest_first(tmp_path):
    import os
    metrics = Metrics()
    store = ArtifactStore(str(tmp_path), byte_budget=300,
                          metrics=metrics.scoped("store"))
    store.put("key", bytes(100))
    paths = _fake_jax_cache(tmp_path, [100, 100, 100])  # 100 + 300 > 300
    # stats() reports the last-gauged total (no walk on the poll path);
    # the explicit accessor walks and refreshes it
    assert store.jax_cache_bytes() == 300
    assert store.stats()["jax_cache_bytes"] == 300
    removed = store.sweep_jax_cache()
    # artifact bytes (100) leave 200 for the cache: the OLDEST file goes
    assert removed == 1
    assert not os.path.exists(paths[0])
    assert os.path.exists(paths[1]) and os.path.exists(paths[2])
    # the manifest entry is untouched — executables yield before keys
    assert store.get("key") is not None
    assert metrics.snapshot()["counters"]["store_jax_cache_evictions"] == 1


def test_jax_cache_swept_on_open_and_put(tmp_path):
    import os
    _fake_jax_cache(tmp_path, [200, 200])
    store = ArtifactStore(str(tmp_path), byte_budget=250)
    # open-time sweep already bounded the cache
    assert store.stats()["jax_cache_bytes"] <= 250
    # a put() past the throttle window re-sweeps: shrink the budget's
    # free share by writing artifacts, with the throttle disabled
    store._jax_sweep_interval = 0.0
    store.put("a", bytes(200))
    assert store.stats()["jax_cache_bytes"] <= 50
    assert store.get("a") is not None


def test_jax_cache_untouched_without_budget(tmp_path):
    import os
    paths = _fake_jax_cache(tmp_path, [1 << 20])
    store = ArtifactStore(str(tmp_path))  # no budget: GC disabled
    assert store.sweep_jax_cache() == 0
    assert os.path.exists(paths[0])


# --- warm start across processes ---------------------------------------------

def test_second_cache_instance_hits_disk_skips_build(tmp_path, monkeypatch):
    m1 = Metrics()
    cache1 = BucketCache(m1, store=ArtifactStore(str(tmp_path)))
    res1 = cache1.get(_spec(seed=1))
    assert m1.snapshot()["counters"]["bucket_misses"] == 1

    # "restarted server": fresh store handle + fresh cache over the same
    # root; a rebuild here would defeat the whole subsystem, so make any
    # build attempt an error
    def boom(spec, backend=None):
        raise AssertionError("warm path called build_bucket_keys")

    monkeypatch.setattr(scheduler_mod.J, "build_bucket_keys", boom)
    m2 = Metrics()
    cache2 = BucketCache(m2, store=ArtifactStore(str(tmp_path)))
    res2 = cache2.get(_spec(seed=2))
    snap = m2.snapshot()
    assert snap["counters"]["bucket_disk_hits"] == 1
    assert "bucket_misses" not in snap["counters"]
    assert res2.vk.selector_comms == res1.vk.selector_comms
    assert res2.pk.ck == res1.pk.ck

    # memory tier on the second touch
    cache2.get(_spec(seed=3))
    assert m2.snapshot()["counters"]["bucket_hits"] == 1


# --- bounded in-memory tier --------------------------------------------------

def test_memory_tier_entry_cap_and_eviction_counter():
    metrics = Metrics()
    cache = BucketCache(metrics, max_entries=1)  # no store: build tier only
    a, b = _spec(), _spec(gates=12)
    cache.get(a)
    cache.get(b)          # evicts a
    cache.get(b)          # memory hit
    cache.get(a)          # rebuilt (a was evicted)
    snap = metrics.snapshot()
    assert snap["counters"]["bucket_misses"] == 3
    assert snap["counters"]["bucket_mem_evictions"] == 2
    assert snap["counters"]["bucket_hits"] == 1
    assert snap["gauges"]["buckets_resident"] == 1


def test_concurrent_writers_merge_not_clobber(tmp_path):
    """Two writer PROCESSES' worth of store objects on one root: each
    holds a stale in-memory manifest while the other writes; the
    file-locked merge-on-load must preserve BOTH writers' entries
    (pre-PR-4 behavior: last manifest save wins and drops the other's)."""
    root = str(tmp_path / "s")
    a = ArtifactStore(root)
    b = ArtifactStore(root)  # loaded an empty manifest: stale vs a's puts
    a.put("ka", b"alpha")
    b.put("kb", b"beta")     # without merge-on-load this would drop "ka"
    a.put("ka2", b"alpha2")  # and this would drop "kb"
    fresh = ArtifactStore(root)
    assert set(fresh.keys()) >= {"ka", "kb", "ka2"}
    assert fresh.get("ka") == b"alpha"
    assert fresh.get("kb") == b"beta"
    # deletes are honored across writers too: disk is authoritative
    assert b.delete("ka")
    a.put("ka3", b"alpha3")
    assert "ka" not in ArtifactStore(root).keys()
    assert ArtifactStore(root).get("ka3") == b"alpha3"


def test_concurrent_writer_threads_stress(tmp_path):
    """Interleaved writers on separate store objects over one root: all
    entries written by either survive, under real thread interleaving."""
    import threading as _t
    root = str(tmp_path / "s2")
    stores = [ArtifactStore(root) for _ in range(2)]
    errs = []

    def writer(i):
        try:
            for k in range(12):
                stores[i].put(f"w{i}-{k}", b"x%d-%d" % (i, k))
        except Exception as e:  # pragma: no cover - failure reporting
            errs.append(e)

    ts = [_t.Thread(target=writer, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    final = ArtifactStore(root)
    assert set(final.keys()) == {f"w{i}-{k}"
                                 for i in range(2) for k in range(12)}
