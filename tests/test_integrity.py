"""Result-integrity plane tests (ISSUE 13): silent wrong answers are
detected at the phase boundary, attributed to the lying worker,
quarantined, and healed — with proofs byte-identical to the host oracle
and no corrupted proof ever served.

Acceptance surface: `corrupt:at=data` injected at each of {MSM partial,
FFT panel, round-4 eval} on a 3-worker fleet is detected, attributed to
the injected worker index, and quarantined; the quarantine flows through
LEAVE -> supervisor respawn -> challenge-gated rejoin back to a
full-width fleet; DPT_SELF_VERIFY blocks a corrupted proof from the
journal DONE record and the client; and with the plane OFF everything is
bit-for-bit the pre-integrity behavior with zero new counters.

Wait discipline: event-driven waits against generous deadlines, never
fixed sleeps (this module runs inside ci.sh chaos and tier-1 under
load).
"""

import os
import random
import subprocess
import sys
import time

import pytest

from distributed_plonk_tpu import curve as C
from distributed_plonk_tpu import poly as P
from distributed_plonk_tpu.constants import R_MOD
from distributed_plonk_tpu.runtime import integrity as I
from distributed_plonk_tpu.runtime import protocol
from distributed_plonk_tpu.runtime.dispatcher import (Dispatcher,
                                                      RemoteBackend,
                                                      WorkerHandle)
from distributed_plonk_tpu.runtime.faults import FaultInjector, Rule
from distributed_plonk_tpu.runtime.health import LivenessTracker
from distributed_plonk_tpu.runtime.integrity import FleetIntegrity
from distributed_plonk_tpu.runtime.netconfig import NetworkConfig
from distributed_plonk_tpu.runtime.supervisor import WorkerSupervisor
from distributed_plonk_tpu.service.metrics import Metrics

RNG = random.Random(0x5DC)
REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
_LOAD_BUDGET_S = float(os.environ.get("DPT_TEST_WAIT_S", "120"))


@pytest.fixture(autouse=True)
def _fast_failure_knobs(monkeypatch):
    monkeypatch.setattr(WorkerHandle, "RECONNECT_TRIES", 2)
    monkeypatch.setattr(WorkerHandle, "BACKOFF_BASE_S", 0.01)
    monkeypatch.setattr(WorkerHandle, "BACKOFF_MAX_S", 0.05)
    monkeypatch.setattr(WorkerHandle, "TIMEOUT_MS", 120000)


def _wait_for(cond, timeout_s=None, interval=0.05, msg=""):
    deadline = time.monotonic() + (timeout_s or _LOAD_BUDGET_S)
    while True:
        got = cond()
        if got:
            return got
        if time.monotonic() >= deadline:
            raise AssertionError(f"timed out waiting for {msg or cond}")
        time.sleep(interval)


# --- unit layer: the check math against the poly oracle ----------------------

def test_transform_identity_all_modes():
    """The closed-form expected output evaluation matches the oracle
    transform's actual power sum for every (inverse, coset) mode, and a
    single flipped element is caught and attributed to its panel."""
    rng = random.Random(11)
    n = 64
    dom = P.Domain(n)
    x = [rng.randrange(R_MOD) for _ in range(n)]
    t = rng.randrange(2, R_MOD)
    r_dim = 1 << ((n.bit_length() - 1) // 2)
    c_dim = n // r_dim
    transforms = {
        (False, False): P.fft, (False, True): P.coset_fft,
        (True, False): P.ifft, (True, True): P.coset_ifft,
    }
    for (inverse, coset), fn in transforms.items():
        y = fn(dom, x)
        assert I.power_sum(y, t) == I.expected_output_eval(
            x, t, inverse, coset), (inverse, coset)
        # per-panel expectation partitions the total
        bounds = [0, r_dim // 3, r_dim]
        parts = [I.expected_panel_eval(x, t, a, b, r_dim, c_dim,
                                       inverse, coset)
                 for a, b in zip(bounds[:-1], bounds[1:])]
        assert sum(parts) % R_MOD == I.power_sum(y, t)
        # flip one element inside panel 0: only panel 0's sum moves
        bad = list(y)
        bad[0] = (bad[0] + 1) % R_MOD  # flat index 0 -> k1=0 (panel 0)
        assert I.cols_power_sum(bad, t, 0, r_dim // 3, r_dim) != parts[0]
        assert I.cols_power_sum(bad, t, r_dim // 3, r_dim, r_dim) \
            == parts[1]
    # rows partition the input power sum (the input-side partial)
    rb = [0, c_dim // 2, c_dim]
    s = sum(I.rows_power_sum(x, t, a, b, c_dim)
            for a, b in zip(rb[:-1], rb[1:])) % R_MOD
    assert s == I.power_sum(x, t)


def test_g1_sanity_checks():
    p = C.g1_mul(C.G1_GEN, 12345)
    assert I.g1_on_curve(p) and I.g1_in_subgroup(p)
    assert I.g1_in_subgroup(None)  # infinity is a fine partial
    off = (p[0], (p[1] + 1) % C.Q_MOD)  # one flipped coordinate
    assert not I.g1_on_curve(off)
    assert not I.g1_in_subgroup(off)


def test_tracker_suspect_is_sticky():
    t = LivenessTracker(2, breaker_k=3, probe_base_s=0.01,
                        probe_max_s=0.05)
    assert t.mark_suspect(0)
    assert not t.mark_suspect(0)       # idempotent
    assert not t.usable(0)
    assert not t.record_ok(0)          # a probe answer does NOT re-admit
    assert not t.usable(0)
    time.sleep(0.06)
    assert not t.probe_due(0)          # no half-open probes for suspects
    assert t.snapshot()[0]["suspect"]
    t.clear_suspect(0)                 # only the challenge gate absolves
    assert t.usable(0)
    assert t.usable(1)                 # neighbor untouched throughout


def test_faults_data_and_proof_planes_parse():
    f = FaultInjector([Rule.parse("corrupt:at=data:tag=MSM:worker=1"),
                       Rule.parse("corrupt:at=proof:rate=1")])
    assert not f.on_data(0, protocol.MSM)    # wrong worker
    assert not f.on_data(1, protocol.NTT)    # wrong tag
    assert f.on_data(1, protocol.MSM)        # fires exactly once
    assert not f.on_data(1, protocol.MSM)
    assert f.on_proof("job")                 # rate=1: every proof
    assert f.on_proof("job")
    # data/proof rules never leak onto the wire plane
    assert f.on_send(1, protocol.MSM, b"") == protocol.MSM


# --- live fleet: detection + attribution per phase ---------------------------

class EnvFleet:
    """N worker subprocesses with PER-WORKER environment — how the
    data-plane chaos (`corrupt:at=data`, parsed by each worker from its
    own DPT_FAULTS) is armed on exactly one fleet member."""

    def __init__(self, tmp_path, n, port_base, envs=None):
        self.n = n
        base = port_base + (os.getpid() % 400) * (n + 1)
        self.cfg = NetworkConfig(
            [f"127.0.0.1:{base + i}" for i in range(n)])
        self.cfg_path = str(tmp_path / "network.json")
        self.cfg.save(self.cfg_path)
        self.procs = [None] * n
        self.envs = envs or {}
        for i in range(n):
            self.start(i)

    def start(self, i, faults=None):
        env = dict(os.environ)
        env.pop("DPT_FAULTS", None)
        spec = faults if faults is not None else self.envs.get(i)
        if spec:
            env["DPT_FAULTS"] = spec
        self.procs[i] = subprocess.Popen(
            [sys.executable, "-m", "distributed_plonk_tpu.runtime.worker",
             str(i), self.cfg_path], cwd=REPO, env=env)

    def kill(self, i):
        p = self.procs[i]
        if p is not None and p.poll() is None:
            p.kill()
            p.wait(timeout=10)

    def restart(self, i, faults=None):
        self.kill(i)
        self.start(i, faults=faults)

    def wait_up(self, timeout_s=None):
        deadline = time.monotonic() + (timeout_s or _LOAD_BUDGET_S)
        pending = set(range(self.n))
        while pending and time.monotonic() < deadline:
            for i in sorted(pending):
                h, p = self.cfg.workers[i]
                if WorkerHandle(h, p).probe(timeout_ms=5000) is not None:
                    pending.discard(i)
            if pending:
                time.sleep(0.2)
        assert not pending, f"workers {sorted(pending)} did not come up"

    def close(self):
        for i in range(self.n):
            if self.procs[i] is not None and self.procs[i].poll() is None:
                self.procs[i].kill()
        for p in self.procs:
            if p is not None:
                p.wait(timeout=10)


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    f = EnvFleet(tmp_path_factory.mktemp("sdc"), 3, 34000)
    try:
        f.wait_up()
        yield f
    finally:
        f.close()


def _dispatcher(fleet, metrics=None, dup_rate=1.0, integrity=True):
    metrics = metrics or Metrics()
    integ = FleetIntegrity(metrics=metrics, msm_dup_rate=dup_rate,
                           rng=random.Random(0xD0)) if integrity else False
    d = Dispatcher(fleet.cfg, metrics=metrics, integrity=integ)
    d.tracker = LivenessTracker(fleet.n, breaker_k=2, probe_base_s=0.05,
                                probe_max_s=0.5, metrics=metrics)
    for w in d.workers:
        w.tracker = d.tracker
    return d, metrics


def _close(d):
    for w in d.workers:
        w.close()
    d.pool.shutdown(wait=False)


def test_wrong_msm_partial_detected_and_attributed(fleet):
    """Worker 1 silently serves a wrong (on-curve, in-subgroup) MSM
    partial: duplicate execution catches it, the third worker's vote
    attributes it, worker 1 is quarantined, and the fold is EXACT."""
    fleet.restart(1, faults="corrupt:at=data:tag=MSM")
    fleet.wait_up()
    d, metrics = _dispatcher(fleet)
    try:
        n = 48
        bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                 for _ in range(n)]
        scalars = [RNG.randrange(R_MOD) for _ in range(n)]
        d.init_bases(bases)
        assert d.msm(scalars) == C.g1_msm(bases, scalars)
        assert d.tracker.is_suspect(1)
        assert not d.tracker.usable(1)
        snap = metrics.snapshot()["counters"]
        assert snap.get("integrity_failures", 0) >= 1
        assert snap.get("integrity_msm_dups", 0) >= 1
        assert snap.get("workers_quarantined", 0) == 1
        # the quarantined fleet keeps serving exact results (survivors)
        assert d.msm(scalars) == C.g1_msm(bases, scalars)
        # HEALTH surfaces both sides: the dispatcher verdict and the
        # worker's own injected-SDC count
        health = d.health()
        assert health[1]["suspect"] is True
        assert health[1]["sdc_injected"] >= 1
        assert health[0]["suspect"] is False
    finally:
        _close(d)
    fleet.restart(1)
    fleet.wait_up()


def test_adopted_range_goes_through_integrity_check(fleet):
    """The recovery path is checked like the primary path (the PR 12
    stale-base class must be caught there too): worker 1 dies, its range
    is adopted by worker 2 — which serves WRONG partials — and the
    duplicate-execution sampler catches the adopted range, quarantines
    worker 2, and recomputes on the one remaining healthy worker."""
    fleet.restart(2, faults="corrupt:at=data:tag=MSM:rate=1")
    fleet.wait_up()
    d, metrics = _dispatcher(fleet)
    try:
        n = 30
        bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                 for _ in range(n)]
        scalars = [RNG.randrange(R_MOD) for _ in range(n)]
        want = C.g1_msm(bases, scalars)
        d.init_bases(bases)
        fleet.kill(1)  # range 1's adoption rotation starts at worker 2
        assert d.msm(scalars) == want
        assert d.tracker.is_suspect(2)
        snap = metrics.snapshot()["counters"]
        assert snap.get("fleet_range_adoptions", 0) >= 1
        assert snap.get("integrity_failures", 0) >= 1
        assert snap.get("workers_quarantined", 0) == 1
        # still exact with one worker dead and one quarantined
        assert d.msm(scalars) == want
    finally:
        _close(d)
    fleet.restart(1)
    fleet.restart(2)
    fleet.wait_up()


def test_wrong_fft_panel_detected_and_attributed(fleet):
    """Worker 1's FFT2 result panel suffers SDC: the gathered output
    fails the Schwartz-Zippel identity, per-panel bisection names worker
    1, it is quarantined, and the replan on survivors returns EXACT
    bytes."""
    fleet.restart(1, faults="corrupt:at=data:tag=FFT2")
    fleet.wait_up()
    d, metrics = _dispatcher(fleet)
    try:
        n = 256
        values = [RNG.randrange(R_MOD) for _ in range(n)]
        assert d.fft_dist(values, inverse=True, coset=True) \
            == P.coset_ifft(P.Domain(n), values)
        assert d.tracker.is_suspect(1)
        snap = metrics.snapshot()["counters"]
        assert snap.get("integrity_failures", 0) >= 1
        assert snap.get("workers_quarantined", 0) == 1
        assert snap.get("fleet_fft_replans", 0) >= 1
    finally:
        _close(d)
    fleet.restart(1)
    fleet.wait_up()


def test_wrong_round4_eval_detected_and_attributed(fleet):
    """Worker 1 serves a wrong partial Horner sum: duplicate execution
    disagrees, the host referee attributes it, and the served value is
    the exact one."""
    fleet.restart(1, faults="corrupt:at=data:tag=EVAL")
    fleet.wait_up()
    d, metrics = _dispatcher(fleet)
    try:
        coeffs = [RNG.randrange(R_MOD) for _ in range(200)]
        z = RNG.randrange(R_MOD)
        assert d.eval_poly(coeffs, z) == P.poly_eval(coeffs, z)
        assert d.tracker.is_suspect(1)
        snap = metrics.snapshot()["counters"]
        assert snap.get("integrity_eval_dups", 0) >= 1
        assert snap.get("integrity_failures", 0) >= 1
        assert snap.get("workers_quarantined", 0) == 1
        # eval_many keeps serving exact values on the survivors
        got = d.eval_many([(coeffs, z), (coeffs[: 60], z)])
        assert got == [P.poly_eval(coeffs, z), P.poly_eval(coeffs[:60], z)]
    finally:
        _close(d)
    fleet.restart(1)
    fleet.wait_up()


def test_ntt_offload_checked_and_rerouted(fleet):
    """The whole-poly NTT offload (round-robin / quorum-degraded path)
    is checked too: a worker serving a wrong NTT is quarantined and the
    rotation serves the exact result from the next worker."""
    fleet.restart(0, faults="corrupt:at=data:tag=NTT")
    fleet.wait_up()
    d, metrics = _dispatcher(fleet)
    try:
        n = 64
        values = [RNG.randrange(R_MOD) for _ in range(n)]
        assert d.ntt(values, worker=0) == P.fft(P.Domain(n), values)
        assert d.tracker.is_suspect(0)
        assert metrics.snapshot()["counters"].get(
            "workers_quarantined", 0) == 1
    finally:
        _close(d)
    fleet.restart(0)
    fleet.wait_up()


def test_challenge_rejects_still_corrupt_worker(fleet):
    """The known-answer challenge gate: a worker that still serves
    wrong NTTs fails it (stays quarantined); a clean worker passes."""
    fleet.restart(2, faults="corrupt:at=data:tag=NTT:rate=1")
    fleet.wait_up()
    d, metrics = _dispatcher(fleet)
    try:
        h2, p2 = fleet.cfg.workers[2]
        assert d.run_challenge(h2, p2) is False
        h0, p0 = fleet.cfg.workers[0]
        assert d.run_challenge(h0, p0) is True
        snap = metrics.snapshot()["counters"]
        assert snap.get("integrity_challenges", 0) == 2
        assert snap.get("integrity_challenges_failed", 0) == 1
    finally:
        _close(d)
    fleet.restart(2)
    fleet.wait_up()


def test_integrity_off_parity(fleet):
    """DPT_INTEGRITY off: legacy wire behavior (no FFT2 piggyback
    requested), exact results, and ZERO integrity counters — the plane
    costs nothing when disabled."""
    fleet.wait_up()
    d, metrics = _dispatcher(fleet, integrity=False)
    try:
        assert d.integrity is None
        n = 64
        values = [RNG.randrange(R_MOD) for _ in range(n)]
        assert d.fft_dist(values, inverse=True) \
            == P.ifft(P.Domain(n), values)
        bases = [C.g1_mul(C.G1_GEN, RNG.randrange(1, R_MOD))
                 for _ in range(16)]
        scalars = [RNG.randrange(R_MOD) for _ in range(16)]
        d.init_bases(bases)
        assert d.msm(scalars) == C.g1_msm(bases, scalars)
        ctr = metrics.snapshot()["counters"]
        assert not any(k.startswith(("integrity", "workers_quarantined"))
                       for k in ctr), ctr
    finally:
        _close(d)


# --- quarantine lifecycle end to end -----------------------------------------

def test_quarantine_leave_respawn_challenge_rejoin(proven, tmp_path):
    """THE lifecycle canary: a supervised 3-worker fleet with one member
    silently corrupting MSM partials. Mid-prove the integrity plane
    detects + attributes it, quarantines it (LEAVE, reason=integrity),
    the supervisor SIGKILLs the alive-but-lying process, the respawn
    re-JOINs through the known-answer challenge, and the fleet heals to
    full width — with BOTH proves byte-identical to the host oracle."""
    from distributed_plonk_tpu.prover import prove

    ckt, pk, vk, proof_host = proven
    metrics = Metrics()
    d = Dispatcher(NetworkConfig([]), metrics=metrics,
                   integrity=FleetIntegrity(metrics=metrics,
                                            msm_dup_rate=1.0,
                                            rng=random.Random(0xE7)))
    d.tracker = LivenessTracker(0, breaker_k=2, probe_base_s=0.05,
                                probe_max_s=0.5, metrics=metrics)
    mserver = d.enable_membership()
    corrupt_spawns = []

    def spawn_cmd(i, slot):
        cmd = [sys.executable, "-m",
               "distributed_plonk_tpu.runtime.worker",
               "--join", f"127.0.0.1:{mserver.port}",
               "--listen", f"127.0.0.1:{slot.port}",
               "--backend", "python"]
        if i == 1 and not corrupt_spawns:
            # only the FIRST incarnation lies; the respawn is clean and
            # must pass the challenge gate
            corrupt_spawns.append(time.monotonic())
            cmd = ["env", "DPT_FAULTS=corrupt:at=data:tag=MSM:rate=1"] \
                + cmd
        return cmd

    sup = WorkerSupervisor("127.0.0.1", mserver.port, n=3,
                           metrics=metrics, cwd=REPO,
                           spawn_cmd=spawn_cmd).start()
    sup.attach_registry(d.membership)
    try:
        _wait_for(lambda: len(d.workers) == 3
                  and len(d.tracker.usable_set()) == 3, msg="fleet up")
        corrupt_idx = d.membership._find("127.0.0.1", sup.slots[1].port)
        assert corrupt_idx is not None

        proof = prove(random.Random(1), ckt, pk,
                      RemoteBackend(d, dist_fft_min=ckt.n))
        assert proof.opening_proof == proof_host.opening_proof
        assert proof.shifted_opening_proof \
            == proof_host.shifted_opening_proof
        assert proof.wires_poly_comms == proof_host.wires_poly_comms
        assert proof.split_quot_poly_comms \
            == proof_host.split_quot_poly_comms

        snap = metrics.snapshot()["counters"]
        assert snap.get("workers_quarantined", 0) >= 1
        assert snap.get("integrity_failures", 0) >= 1
        assert snap.get("membership_leaves", 0) >= 1

        # heal: supervisor kills the liar, respawn rejoins via the
        # challenge, fleet returns to full width SCHEDULABLE
        _wait_for(lambda: len(d.tracker.usable_set()) == 3,
                  msg="challenge-gated heal to full width")
        snap = metrics.snapshot()["counters"]
        assert snap.get("worker_respawns", 0) >= 1
        assert snap.get("membership_rejoins", 0) >= 1
        assert snap.get("integrity_challenges", 0) >= 1
        assert not d.tracker.is_suspect(corrupt_idx)
        assert (("127.0.0.1", sup.slots[1].port)
                not in d.membership.quarantined)

        # the healed, full-width fleet still proves byte-identically
        proof2 = prove(random.Random(1), ckt, pk,
                       RemoteBackend(d, dist_fft_min=ckt.n))
        assert proof2.opening_proof == proof_host.opening_proof
    finally:
        sup.stop()
        try:
            d.shutdown()
        finally:
            d.pool.shutdown(wait=False)


# --- verify-before-serve ------------------------------------------------------

def test_self_verify_blocks_corrupt_proof(tmp_path, monkeypatch):
    """A proof corrupted between prove and serve (at=proof chaos) is
    BLOCKED by verify-before-serve — never journaled DONE, never handed
    to the client — and the re-prove serves a verifying proof."""
    import json
    from distributed_plonk_tpu.service import ProofService, ServiceClient
    from distributed_plonk_tpu.service.jobs import (JobSpec,
                                                    build_bucket_keys)
    from distributed_plonk_tpu.proof_io import deserialize_proof
    from distributed_plonk_tpu.verifier import verify

    faults = FaultInjector([Rule.parse("corrupt:at=proof:nth=1")])
    svc = ProofService(port=0, prover_workers=1, chaos=True,
                       faults=faults, self_verify="1",
                       journal_dir=str(tmp_path / "j"),
                       store_dir=str(tmp_path / "s")).start()
    try:
        with ServiceClient("127.0.0.1", svc.port) as c:
            jid = c.submit({"kind": "toy", "gates": 16, "seed": 5})["job_id"]
            st = c.wait(jid, timeout_s=_LOAD_BUDGET_S)
            assert st["state"] == "done", json.dumps(st)
            assert st["retries"] == 1  # the blocked attempt re-proved
            header, blob = c.result(jid)
            m = c.metrics()
        ctr = m["counters"]
        assert ctr.get("proofs_blocked", 0) == 1
        assert ctr.get("self_verify_failures", 0) == 1
        assert ctr.get("self_verify_checks", 0) >= 2
        assert "self_verify_s" in m["histograms"]
        # what WAS served verifies
        spec = JobSpec.from_wire(header["spec"])
        vk = build_bucket_keys(spec)[2]
        pub = [int(x, 16) for x in header["public_input"]]
        assert verify(vk, pub, deserialize_proof(blob),
                      rng=random.Random(1))
        # the journal's DONE record is the GOOD proof: a restart serves
        # verifying bytes without re-proving
        svc.shutdown()
        svc2 = ProofService(port=0, prover_workers=1,
                            journal_dir=str(tmp_path / "j"),
                            store_dir=str(tmp_path / "s")).start()
        try:
            job = svc2.get_job(jid)
            assert job is not None and job.state == "done"
            assert job.proof_bytes == blob
        finally:
            svc2.shutdown()
    finally:
        svc.shutdown()


def test_self_verify_off_and_auto_parity(tmp_path):
    """DPT_SELF_VERIFY=0 (and the default auto mode on pool-placed
    local proves) adds ZERO checks and zero counters; proof bytes are
    the exact bytes an always-verify service serves."""
    from distributed_plonk_tpu.service import ProofService

    spec = {"kind": "toy", "gates": 16, "seed": 9}

    def run(self_verify):
        svc = ProofService(port=0, prover_workers=1,
                           self_verify=self_verify).start()
        try:
            job = svc.submit_local(dict(spec))
            assert job.done_event.wait(timeout=_LOAD_BUDGET_S)
            assert job.state == "done"
            return job.proof_bytes, svc.metrics.snapshot()
        finally:
            svc.shutdown()

    bytes_off, m_off = run("0")
    bytes_auto, m_auto = run("auto")
    bytes_on, m_on = run("1")
    assert bytes_off == bytes_on == bytes_auto
    for m in (m_off, m_auto):
        assert not any(k.startswith(("self_verify", "proofs_blocked"))
                       for k in m["counters"]), m["counters"]
        assert "self_verify_s" not in m["histograms"]
    assert m_on["counters"].get("self_verify_checks", 0) == 1
