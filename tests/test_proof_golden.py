"""Golden proof bytes: the full serialized proof pinned in-tree.

The byte-identity regression floor VERDICT r4 asked for: fixed
(seed, tau) recipes must reproduce the checked-in proof bytes EXACTLY —
any silent change to the transcript schedule, commitment math, blinding
order, or serialization breaks these tests. (The reference's analogous
invariant is that its distributed prover byte-matches jf-plonk's,
/root/reference/src/dispatcher2.rs:44-154 + SURVEY.md §4; with no Rust
toolchain here, this repo's own pinned bytes are the regression anchor,
layered on the EXTERNAL anchors: the merlin KAT in test_transcript.py
and the zcash generator vectors in test_encoding.py.)

Regenerate (only for intentional proof-system changes):
    python scripts/gen_proof_fixtures.py
"""

import os
import random

import pytest

from distributed_plonk_tpu import kzg, proof_io
from distributed_plonk_tpu.prover import prove
from distributed_plonk_tpu.verifier import verify
from distributed_plonk_tpu.backend.python_backend import PythonBackend

from conftest import build_test_circuit

FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def _fixture(name):
    with open(os.path.join(FIXDIR, name + ".hex")) as f:
        return bytes.fromhex(f.read().strip())


def _prove_bytes(ckt):
    """THE golden recipe (tau, prove seed, verify seed, host oracle) —
    scripts/gen_proof_fixtures.py imports this same function, so the
    generator and the replaying tests can never drift apart."""
    if not ckt._finalized:
        ckt.finalize()
    srs = kzg.universal_setup(ckt.n + 3, tau=0xDEADBEEF)
    pk, vk = kzg.preprocess(srs, ckt)
    proof = prove(random.Random(1), ckt, pk, PythonBackend())
    assert verify(vk, ckt.public_input(), proof, rng=random.Random(2))
    return proof_io.serialize_proof(proof), proof


def _build_merkle_2p13():
    """v1 workload scale: height-32 Merkle, 1 proof, n=2^13
    (/root/reference/src/dispatcher.rs:1064-1070)."""
    from distributed_plonk_tpu.workload import generate_circuit

    ckt, _ = generate_circuit(rng=random.Random(11), height=32, num_proofs=1)
    return ckt


# fixture name -> circuit builder; the generator iterates this dict
RECIPES = {
    "proof_small": build_test_circuit,
    "proof_merkle_h32_p1": _build_merkle_2p13,
}


def test_proof_roundtrip_and_golden_small():
    blob, proof = _prove_bytes(build_test_circuit())
    assert len(blob) == proof_io.PROOF_BYTES
    back = proof_io.deserialize_proof(blob)
    assert proof_io.serialize_proof(back) == blob
    assert back.wires_poly_comms == proof.wires_poly_comms
    assert back.perm_next_eval == proof.perm_next_eval
    assert blob == _fixture("proof_small")


@pytest.mark.slow
def test_proof_golden_merkle_2p13():
    blob, _ = _prove_bytes(_build_merkle_2p13())
    assert blob == _fixture("proof_merkle_h32_p1")


def test_deserialize_rejects_malformed():
    blob, _ = _prove_bytes(build_test_circuit())
    with pytest.raises(ValueError):
        proof_io.deserialize_proof(blob[:-1])
    # corrupt a commitment byte -> point validation fails
    bad = bytearray(blob)
    bad[1] ^= 0xFF
    with pytest.raises(ValueError):
        proof_io.deserialize_proof(bytes(bad))
    # push a scalar out of canonical range
    bad = bytearray(blob)
    bad[proof_io.PROOF_BYTES - 1] = 0xFF
    with pytest.raises(ValueError):
        proof_io.deserialize_proof(bytes(bad))
