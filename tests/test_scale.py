"""Reference-scale end-to-end workloads (opt-in: slow, device-bound).

The reference's two built-in workloads (SURVEY.md §6): height-32 Merkle
membership at 1 proof -> 2^13 domain (v1, dispatcher.rs:1064-1070) and at
50 proofs -> 2^18 domain / 2^21 quotient (v2, dispatcher2.rs:1219-1221).
Run with DPT_SCALE_TEST=1 (and ideally on the real chip: the default test
env pins JAX_PLATFORMS=cpu); scripts/scale_run.py is the standalone
driver for the same flow with timing output.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))

pytestmark = pytest.mark.skipif(
    not os.environ.get("DPT_SCALE_TEST"),
    reason="reference-scale run is opt-in (DPT_SCALE_TEST=1); "
           "it cold-compiles large-domain kernels")


def test_height32_one_proof_2p13():
    env = dict(os.environ)
    # let the script inherit the real-device platform if available
    env.pop("JAX_PLATFORMS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "scale_run.py"),
         "--height", "32", "--proofs", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=7200)
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["log2_n"] == 13, res
    assert res["verified"] is True
